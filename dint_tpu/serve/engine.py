"""ServeEngine: the always-on dintserve serving loop.

Turns the batch certification engines into a long-lived service: an
open-loop arrival stream (arrivals.py) fills variable-occupancy cohorts,
a depth-k double-buffered pump keeps the device busy while the host
accumulates the next block and drains the previous one, and an SLO
controller (controller.py) adapts the cohort width among a menu of
pre-compiled widths and sheds admissions the SLO can no longer cover.

Three structural commitments, each pinned by a test:

* **Bit-identity.** Transaction content comes from the cohort PRNG key
  (fold_in(base_key, block_idx) — the closed-loop convention), and the
  occupancy mask erases lanes >= occ AFTER full-width generation. At
  occ == width the serve path is therefore value-identical to the
  closed-loop runner on the same keys: serving is a masking of batch
  certification, not a fork of it.
* **Zero steady-state allocation.** Every serve block runs through the
  same jitted callable with donate_argnums=0: after warmup the carry
  (db tables, contexts, counters) ping-pongs through donated buffers
  and `jax.live_arrays()` stays constant block over block.
* **Graceful degradation.** Past saturation the controller sits at the
  knee width and SHEDS (newest-first) instead of stalling; every shed
  lane is tallied host-side and mirrored into the device counter ledger
  (serve_shed_lanes), so the artifact can prove the service never
  silently dropped work.

Clocking: a RealClock serves wall time (hardware runs); a VirtualClock
plus the controller's ServiceModel makes the whole loop — ingestion,
width choices, shedding — a deterministic function of (schedule, seed),
which is how the CPU tests pin controller behaviour.
"""
from __future__ import annotations

import collections
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as mon
from ..stats import LatencyHistogram
from .arrivals import ArrivalStream
from .controller import (ControllerCfg, ServiceModel, WidthController,
                         recommend_hot_frac)


class RealClock:
    """Wall time (monotonic) — hardware serving."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, s: float) -> None:
        if s > 0:
            time.sleep(s)


class VirtualClock:
    """Deterministic time: advances only when told. Under it the serve
    loop never calls time.*, so two runs with the same schedule + seed
    are bit-identical — including every controller decision."""

    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        if s > 0:
            self.t += s


# process-wide (run, init, drain) cache: two ServeEngines over the same
# (engine, geometry, width, flags) share one jitted serve step — the
# callables are stateless, so sharing is safe, and a restarted engine
# (or a CPU test rerunning a config) never pays the compile twice
_RUNNER_CACHE: dict = {}


def cached_runner(engine: str, size: int, *, val_words: int = 4, **kw):
    """Build (run, init, drain) for a dense engine, at most once per
    process per distinct (engine, size, val_words, kw) — the serve
    plane's compile cache, also usable for closed-loop comparison
    builds in tests. Unhashable kw values fall back to an uncached
    build rather than failing."""
    try:
        key = (engine, size, val_words, tuple(sorted(kw.items())))
    except TypeError:
        key = None
    if key is not None and key in _RUNNER_CACHE:
        return _RUNNER_CACHE[key]
    if engine == "tatp_dense":
        from ..engines import tatp_dense as td
        out = td.build_pipelined_runner(size, val_words=val_words, **kw)
    elif engine == "store":
        # round-20 dintscan: the KV store as a first-class serve family
        # (YCSB-E-shaped on-device cohorts, optional ordered-run scans)
        from ..engines import store as st
        out = st.build_serve_runner(size, val_words=val_words, **kw)
    elif engine == "multihost_sb":
        # the mesh serving plane (serve/mesh.py): kw carries the 2-D
        # mesh; the builder is itself memoized, this cache just keeps
        # the lookup uniform across engine families
        from ..parallel import multihost_sb as mhs
        mkw = dict(kw)
        out = mhs.build_multihost_sb_runner(mkw.pop("mesh"), size, **mkw)
    else:
        from ..engines import smallbank_dense as sd
        out = sd.build_pipelined_runner(size, **kw)
    if key is not None:
        _RUNNER_CACHE[key] = out
    return out


class ServeEngine:
    """Long-lived serving plane over one dense engine family.

    Parameters
    ----------
    engine : 'tatp_dense' | 'smallbank_dense'
    size : table size (n_sub / n_accounts)
    cfg / model : controller config + service-time prior
    cohorts_per_block : steps per dispatched block (pipeline depth rides
        the existing engines; this is the scan length per dispatch)
    depth : host->device pump depth — the host runs at most ``depth``
        blocks ahead of the oldest unretired block (2 = the classic
        double buffer; shim/pump.py got the same knob this round)
    clock : RealClock (default) or VirtualClock (deterministic tests)
    monitor : thread the dintmon counter plane (needed for the serve
        counter reconciliation identity and hot_frac auto-sizing)
    runner_kw : forwarded to build_pipelined_runner (use_pallas, mix,
        use_hotset, hot_frac, ...) — always wins over the plan
    plan : "auto" (default) reads the pinned PLAN.json (analysis/plan):
        the width menu + SLO come from the plan's serve priors when
        ``cfg`` is None, build knobs the plan pins for this engine's
        serve workload replace the env-flag default path, and the
        hot_frac prior seeds the rebuild loop. A plan dict is accepted
        directly (tests); None disables plan consumption. Without a
        readable plan everything falls back to today's defaults and
        the snapshot records ``"plan": None`` — never a silent default.
    adapt_hot_frac : rebuild the width menu at the plan-recommended
        hot_frac at width-switch drain boundaries (the only points the
        pipeline is empty and the tables are host-side, so a re-shape
        is safe). None = auto: on iff a hot_frac prior exists and the
        counter plane that feeds the recommendation is threaded.
    """

    # engine families this class can drive; subclasses (serve/mesh.py's
    # MeshServeEngine) narrow it to their own runner-builder path
    ENGINES: tuple[str, ...] = ("tatp_dense", "smallbank_dense", "store")

    def __init__(self, engine: str, size: int, *,
                 cfg: ControllerCfg | None = None,
                 model: ServiceModel | None = None,
                 cohorts_per_block: int = 2, depth: int = 2,
                 val_words: int = 4, clock=None, monitor: bool = True,
                 seed: int = 0, idle_poll_us: float = 50_000.0,
                 runner_kw: dict | None = None, plan="auto",
                 adapt_hot_frac: bool | None = None):
        assert engine in self.ENGINES, engine
        assert depth >= 1
        self.engine = engine
        self.size = size
        self.cpb = cohorts_per_block
        self.depth = depth
        self.val_words = val_words
        self.clock = clock or RealClock()
        self.monitor = monitor
        self.idle_poll_us = idle_poll_us
        self.runner_kw = dict(runner_kw or {})

        plan_knobs, priors, self.plan_meta = self._resolve_plan(plan)
        if cfg is None and priors:
            cfg = ControllerCfg(
                widths=tuple(sorted(int(w) for w in priors["widths"])),
                slo_us=float(priors["slo_us"]))
        self.cfg = cfg or ControllerCfg()
        if model is None and priors:
            model = ServiceModel(base_us=priors["model"]["base_us"],
                                 per_lane_ns=priors["model"]["per_lane_ns"])
        self.model = model or ServiceModel()
        self._apply_plan_knobs(plan_knobs)

        # hot_frac rebuild loop: prior from runner_kw if pinned by the
        # caller, else the plan's serve prior; None = engine family has
        # no hot tier and the loop stays off
        self._hot_frac = self.runner_kw.get("hot_frac")
        if self._hot_frac is None and priors:
            self._hot_frac = priors.get("hot_frac")
        if adapt_hot_frac is None:
            adapt_hot_frac = self._hot_frac is not None and self.monitor
        self.adapt_hot_frac = bool(adapt_hot_frac)
        self.hot_frac_rebuilds = 0

        self.base_key = jax.random.PRNGKey(seed)
        self.ctl = WidthController(self.cfg, self.model)

        # one pre-compiled (run, init, drain) per registered width —
        # built eagerly so no width switch ever pays a compile online
        self._runners = {w: self._build(w) for w in self.cfg.widths}

        self._db = self._fresh_db(seed)
        self._cur_w: int | None = None
        self._carry = None

        # host-side ledgers
        self.queue_hist = LatencyHistogram()     # per admitted lane (µs)
        self.service_hist = LatencyHistogram()   # per retired block (µs)
        self.stats_total = None                  # summed engine stats
        self.counters_total: dict[str, int] = {}
        self.shed_total = 0
        self._shed_pending = 0                   # awaiting device mirror
        self.admitted_total = 0
        self.offered_total = 0
        self.blocks = 0
        self.steps_by_width: dict[int, int] = {w: 0 for w in self.cfg.widths}
        self._backlog: collections.deque[float] = collections.deque()
        self._pending: collections.deque = collections.deque()
        self._block_idx = 0
        self._t0 = None
        self._elapsed = 0.0

    # -- construction ---------------------------------------------------

    def _resolve_plan(self, plan):
        """-> (knobs, serve_priors | None, meta | None) for this
        engine's serve workload. Missing / unreadable plan degrades to
        (today's env-default behaviour, no priors, meta None)."""
        if plan is None:
            return {}, None, None
        from ..analysis import plan as P
        doc = plan if isinstance(plan, dict) else None
        if doc is None:
            try:
                doc = P.load_plan()
            except (OSError, ValueError):
                return {}, None, None
        wname = P.SERVE_WORKLOADS.get(self.engine)
        if wname is None or wname not in doc.get("workloads", {}):
            return {}, None, None
        knobs, meta = P.resolve_for(wname, plan=doc)
        return knobs, doc["workloads"][wname].get("serve"), meta

    def _apply_plan_knobs(self, knobs: dict) -> None:
        """Plan-resolved build knobs replace the env-flag default path:
        a knob the caller left out of runner_kw builds at the plan's
        pinned value instead of whatever the ambient DINT_* flags say
        (under DINT_PLAN_OVERRIDE=1 resolve_for already folded the env
        value back in). Explicit runner_kw always wins."""
        for k, v in knobs.items():
            self.runner_kw.setdefault(k, v)

    def _fresh_db(self, seed: int):
        if self.engine == "tatp_dense":
            from ..engines import tatp_dense as td
            return td.populate(np.random.default_rng(seed), self.size,
                               val_words=self.val_words)
        if self.engine == "store":
            from ..clients import micro
            return micro.make_store_table(self.size,
                                          val_words=self.val_words)
        from ..engines import smallbank_dense as sd
        return sd.create(self.size)

    def _build(self, w: int):
        return cached_runner(
            self.engine, self.size, val_words=self.val_words,
            w=w, cohorts_per_block=self.cpb, monitor=self.monitor,
            trace=False, serve=True, **self.runner_kw)

    def warmup(self) -> None:
        """Compile every registered width's serve step + drain before
        serving starts: compilation is minutes-scale on TPU and must
        never be charged to a client's queueing delay. Runs each width
        once on a THROWAWAY copy of the tables (run/drain donate their
        carry, so the live db is never touched); the jit cache keyed on
        the carry shapes then serves every later dispatch. VirtualClock
        tests skip this — virtual time never observes compile time."""
        zeros = np.zeros(self.cpb, np.int32)
        key = jax.random.PRNGKey(0)
        for w in self.cfg.widths:
            run, init, drain = self._runners[w]
            db = jax.tree_util.tree_map(jnp.array, self._db)
            carry = init(db)
            carry, _ = run(carry, key, zeros, zeros)
            drain(carry)

    # -- width lifecycle ------------------------------------------------

    def _attach(self, w: int) -> None:
        """init at width w (first block or after a width-switch drain)."""
        _, init, _ = self._runners[w]
        self._carry = init(self._db)
        self._db = None          # ownership moved into the carry
        self._cur_w = w

    def _detach(self) -> None:
        """Drain the live pipeline: flush in-flight cohorts, absorb the
        tail stats and the device counter ledger, recover the db."""
        self._retire_all()
        _, _, drain = self._runners[self._cur_w]
        out = drain(self._carry)
        self._carry = None
        db, tail = out[0], out[1]
        self._absorb_stats(np.asarray(tail, np.int64))
        if self.monitor:
            snap = mon.snapshot(out[-1])
            for k, v in snap.items():
                self.counters_total[k] = self.counters_total.get(k, 0) + v
        self._db = db
        self._cur_w = None

    def _absorb_stats(self, stats: np.ndarray) -> None:
        row = stats.astype(np.int64).sum(axis=0)
        self.stats_total = (row if self.stats_total is None
                            else self.stats_total + row)

    def _maybe_rebuild_hot_frac(self) -> None:
        """At a width-switch drain boundary (pipeline empty, tables
        host-side — the only safe re-shape points) fold the observed
        hot-tier counters into a new hot_frac and rebuild the width
        menu when the recommendation moved. With no hot-tier traffic
        (hot counters zero) the recommendation is the status quo and
        this is a no-op, so plans without a hot tier never rebuild."""
        if not self.adapt_hot_frac or self._hot_frac is None:
            return
        rec = self.hot_frac_recommendation(self._hot_frac)
        self.ctl.journal_hot_frac(
            self._hot_frac, self.counters_total.get("hot_hits", 0),
            self.counters_total.get("hot_cold_rows", 0), rec)
        if rec == self._hot_frac:
            return
        self._hot_frac = rec
        self.runner_kw["hot_frac"] = rec
        self.hot_frac_rebuilds += 1
        self._runners = {w: self._build(w) for w in self.cfg.widths}

    # -- the pump -------------------------------------------------------

    def _dispatch(self, occ: np.ndarray, shed0: int) -> None:
        run, _, _ = self._runners[self._cur_w]
        key = jax.random.fold_in(self.base_key, self._block_idx)
        shed = np.zeros(self.cpb, np.int32)
        shed[0] = shed0
        t_disp = self.clock.now()
        self._carry, stats = run(self._carry, key,
                                 occ.astype(np.int32), shed)
        self._pending.append((stats, t_disp, self._cur_w))
        self._block_idx += 1
        self.blocks += 1
        self.steps_by_width[self._cur_w] += self.cpb
        if isinstance(self.clock, VirtualClock):
            # the model IS the device under virtual time
            self.clock.sleep(self.cpb * self.model.service_us(self._cur_w)
                             * 1e-6)
        if len(self._pending) >= self.depth:
            self._retire_one()

    def _retire_one(self) -> None:
        stats, t_disp, w = self._pending.popleft()
        host = np.asarray(stats, np.int64)     # blocks until materialized
        if isinstance(self.clock, VirtualClock):
            service_us = self.cpb * self.model.service_us(w)
        else:
            service_us = max((self.clock.now() - t_disp) * 1e6, 1e-3)
        self._absorb_stats(host)
        self.service_hist.add(service_us)
        self.ctl.observe_service(w, service_us / self.cpb)

    def _retire_all(self) -> None:
        while self._pending:
            self._retire_one()

    # -- the serving loop -----------------------------------------------

    def _rel_now(self) -> float:
        return self.clock.now() - self._t0

    def _ingest(self, stream: ArrivalStream, dt: float) -> None:
        got = stream.take_until(self._rel_now())
        self.offered_total += len(got)
        self._backlog.extend(got.tolist())
        if dt > 0:
            self.ctl.observe_rate(len(got) / dt)

    def _admit(self) -> int:
        """Shed newest arrivals past the SLO-feasible backlog bound.
        Returns lanes shed this poll (also queued for device mirror)."""
        cap = self.ctl.max_backlog()
        backlog0 = len(self._backlog)
        shed = 0
        while len(self._backlog) > cap:
            self._backlog.pop()               # newest first
            shed += 1
        if shed:
            self.ctl.journal_shed(backlog0, shed)
        self.shed_total += shed
        self._shed_pending += shed
        return shed

    def _fill_block(self, w: int) -> np.ndarray:
        """Pop FIFO arrivals into per-cohort occupancies and charge each
        admitted lane its queueing delay (dispatch − arrival)."""
        occ = np.zeros(self.cpb, np.int32)
        t = self._rel_now()
        for i in range(self.cpb):
            n = min(len(self._backlog), w)
            occ[i] = n
            if n:
                ts = np.fromiter((self._backlog.popleft() for _ in range(n)),
                                 np.float64, count=n)
                self.queue_hist.add(np.maximum(t - ts, 0.0) * 1e6)
        self.admitted_total += int(occ.sum())
        return occ

    def run(self, schedule: np.ndarray, *, max_blocks: int | None = None
            ) -> dict:
        """Serve one arrival schedule to completion (every arrival either
        served or shed), then flush the pump, drain the pipeline, and
        return the report. Re-entrant: a second schedule continues on
        the same tables."""
        stream = ArrivalStream(schedule)
        if self._t0 is None:
            self._t0 = self.clock.now()
        last_poll = self._rel_now()

        while True:
            now = self._rel_now()
            self._ingest(stream, now - last_poll)
            last_poll = now
            self._admit()

            if not self._backlog:
                if stream.exhausted:
                    break
                nxt = stream.peek() - self._rel_now()
                # idle: park until the next arrival (bounded by the idle
                # poll so a real server still services its control plane)
                self.clock.sleep(max(min(nxt, self.idle_poll_us * 1e-6),
                                     1e-9))
                continue

            w = self.ctl.width()
            if w != self._cur_w:
                if self._cur_w is not None:
                    self._detach()
                self._maybe_rebuild_hot_frac()
                self._attach(w)

            occ = self._fill_block(w)
            shed0, self._shed_pending = self._shed_pending, 0
            self._dispatch(occ, shed0)

            if max_blocks is not None and self.blocks >= max_blocks:
                break

        self._retire_all()
        self._elapsed = self._rel_now()
        return self.snapshot()

    def close(self) -> None:
        """Flush + drain; recovers the tables into self._db."""
        if self._cur_w is not None:
            self._detach()

    # -- reporting ------------------------------------------------------

    def hot_frac_recommendation(self, cur: float) -> float:
        """Auto-size hot_frac from the observed hot-tier counters (to be
        applied at the next engine rebuild — hot_frac is a shape)."""
        return recommend_hot_frac(
            cur, self.counters_total.get("hot_hits", 0),
            self.counters_total.get("hot_cold_rows", 0))

    def snapshot(self) -> dict:
        elapsed = self._elapsed or max(self._rel_now(), 1e-9)
        qp, sp = self.queue_hist.percentiles(), self.service_hist.percentiles()
        counters = dict(self.counters_total)
        if self.monitor and self._carry is not None:
            # non-destructive peek at the live ledger (absorbed for real
            # at the next drain; snapshot() must reconcile mid-flight)
            for k, v in mon.snapshot(self._carry[-1]).items():
                counters[k] = counters.get(k, 0) + v
        committed = attempted = 0
        if self.stats_total is not None:
            # STAT_ATTEMPTED / STAT_COMMITTED are 0/1 for both families
            attempted, committed = int(self.stats_total[0]), \
                int(self.stats_total[1])
        return {
            "engine": self.engine,
            "widths": list(self.cfg.widths),
            "blocks": self.blocks,
            "steps_by_width": {str(k): v
                               for k, v in self.steps_by_width.items()},
            "offered": self.offered_total,
            "admitted": self.admitted_total,
            "shed": self.shed_total,
            "attempted": attempted,
            "committed": committed,
            "elapsed_s": elapsed,
            "offered_rate": self.offered_total / elapsed,
            "achieved_rate": committed / elapsed,
            "slo_us": self.cfg.slo_us,
            "slo_met": qp["p99"] <= self.cfg.slo_us,
            "queue": {**qp, "hist": self.queue_hist.to_dict()},
            "service": {**sp, "hist": self.service_hist.to_dict()},
            "controller": self.ctl.snapshot(),
            "counters": counters,
            "plan": self.plan_meta,
            "hot_frac": {"current": self._hot_frac,
                         "adaptive": self.adapt_hot_frac,
                         "rebuilds": self.hot_frac_rebuilds},
        }
