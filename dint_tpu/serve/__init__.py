"""dintserve: the always-on serving plane (round 17).

Batch certification becomes a service: open-loop arrival schedules
(`arrivals`) fill variable-occupancy cohorts, `ServeEngine` pumps them
through the pre-compiled dense engines depth-k deep with zero
steady-state allocation, and the SLO controller (`controller`) adapts
cohort width among a registered menu and sheds — never stalls — past
saturation. `tools/dintserve.py` is the CLI; exp.py's serve sweep emits
the latency-vs-offered-load artifact with exact queue/service
attribution. Round 18 adds `MeshServeEngine` (mesh.py): the whole 2-D
(dcn x ici) mesh as one open-loop service — per-host admission feeding
one global controller, width switches coordinated mesh-wide at drain
boundaries, and the optional double-buffered (overlap) route.
"""
from __future__ import annotations

from .arrivals import (ArrivalStream, burst_schedule,  # noqa: F401
                       constant_schedule, make_schedule, poisson_schedule)
from .controller import (ControllerCfg, ServiceModel,  # noqa: F401
                         WidthController, choose_width, max_backlog,
                         recommend_hot_frac, simulate_widths)
from .engine import (RealClock, ServeEngine, VirtualClock,  # noqa: F401
                     cached_runner)
from .mesh import MeshServeEngine                      # noqa: F401
