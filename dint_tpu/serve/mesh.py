"""MeshServeEngine: the whole (hosts x chips) mesh as ONE open-loop
transactional service (dintmesh, round 18).

DINT tops out at 3 shard servers with per-packet in-kernel handling; our
answer to "millions of users" is to serve SmallBank over the full 2-D
(dcn x ici) mesh as a single always-on plane. The two halves already
exist: `serve/engine.py` (round 17) is the single-device open-loop pump
with pre-drawn Caladan-style arrivals, and `parallel/multihost_sb.py`
(round 14) is cross-shard 2PC over the mesh but closed-loop. This module
composes them on the serve=True cohort form the runner gained this
round:

* **Per-host admission, one global controller.** Arrivals are routed to
  hosts round-robin at ingest (arrival k -> host k mod H — a stand-in
  for H independent NIC queues, deterministic under VirtualClock); each
  host sheds NEWEST-FIRST against its own backlog bound, but the width
  policy is ONE `WidthController` over the global offered rate observed
  in per-device units (``lanes_scale = H*C``) — every device always
  serves at the same width, which is what keeps one jitted step valid
  for the whole mesh.
* **Mesh-coordinated width switches at drain boundaries.** A width
  switch is a recompile point, so it is already the natural mesh-wide
  barrier: `_detach` drains the jitted pipeline across every device
  (flush steps + tail stats + counter ledger), then `_attach` inits the
  new width. No device ever runs a different width than its peers.
* **Shed mirror across the mesh.** Host h's shed tally rides the next
  dispatched block at occ/shed slot [h, 0, 0], so the device-side
  serve_shed_lanes counter reconciles with the per-host host tallies
  exactly as on the single-device plane.
* **Overlap knob.** ``overlap=True`` serves through the double-buffered
  route (cohort i+1's host-aggregated DCN all_to_all issued under
  cohort i's owner waves — bit-identical to the unoverlapped route by
  the runner's pin). Default OFF pending the pre-registered hardware
  A/B (PERF.md round 18 decision rule); the CPU tests pin that the
  serving plane's reports are identical either way.

Deterministic end-to-end under VirtualClock: the ServiceModel IS the
device (one block advances virtual time by cpb x service_us(w)), so two
runs with the same (schedule, seed, geometry) produce bit-identical
reports, width trajectories, and shed counts.
"""
from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np

from .arrivals import ArrivalStream
from .controller import ControllerCfg, ServiceModel, WidthController
from .engine import ServeEngine, VirtualClock, cached_runner


class MeshServeEngine(ServeEngine):
    """Open-loop SmallBank serving over the 2-D (dcn x ici) mesh.

    Parameters beyond ServeEngine's: ``mesh_shape`` = (n_hosts, n_ici)
    (>= 3 hosts — the replication fault-domain rule); ``hierarchical``
    picks the ici-then-dcn exchange; ``overlap`` enables the
    double-buffered route. Both default to None = resolved from the
    pinned plan's multihost_serve workload (analysis/plan; currently
    hierarchical ON / overlap OFF pending the pre-registered hardware
    A/B per PERF.md round 18) and fall back to the same values when no
    plan is readable, so behaviour without a plan is unchanged. size is
    n_accounts (global)."""

    ENGINES = ("multihost_sb",)

    def __init__(self, n_accounts: int, *,
                 mesh_shape: tuple[int, int] = (4, 2),
                 cfg: ControllerCfg | None = None,
                 model: ServiceModel | None = None,
                 cohorts_per_block: int = 2, depth: int = 2,
                 clock=None, monitor: bool = True, seed: int = 0,
                 idle_poll_us: float = 50_000.0,
                 hierarchical: bool | None = None,
                 overlap: bool | None = None,
                 runner_kw: dict | None = None, plan="auto",
                 adapt_hot_frac: bool | None = None):
        from ..parallel import multihost_sb as mhs
        self.n_hosts, self.n_ici = int(mesh_shape[0]), int(mesh_shape[1])
        self.mesh = mhs.make_mesh_2d(self.n_hosts, self.n_ici)
        self.n_devices = self.n_hosts * self.n_ici
        self.hierarchical = hierarchical
        self.overlap = overlap
        super().__init__("multihost_sb", n_accounts, cfg=cfg, model=model,
                         cohorts_per_block=cohorts_per_block, depth=depth,
                         clock=clock, monitor=monitor, seed=seed,
                         idle_poll_us=idle_poll_us, runner_kw=runner_kw,
                         plan=plan, adapt_hot_frac=adapt_hot_frac)
        # ONE global controller in per-device units: D cohorts of width w
        # serve every step, so the single-device policy functions apply
        # to offered_rate / D unchanged
        self.ctl = WidthController(self.cfg, self.model,
                                   lanes_scale=self.n_devices)
        # per-host admission state (the base class _backlog is unused)
        self._host_backlog: list[collections.deque] = [
            collections.deque() for _ in range(self.n_hosts)]
        self._host_shed_pending = [0] * self.n_hosts
        self.shed_by_host = [0] * self.n_hosts
        self.admitted_by_host = [0] * self.n_hosts
        self._arrival_idx = 0

    # -- construction ---------------------------------------------------

    def _apply_plan_knobs(self, knobs: dict) -> None:
        """hierarchical/overlap are constructor attributes here, not
        runner_kw: consume them from the plan when the caller left them
        at None, then fall back to the historical defaults (ON / OFF)
        so a missing plan changes nothing. Runs inside ServeEngine's
        __init__ BEFORE the width menu is built."""
        if self.hierarchical is None:
            self.hierarchical = bool(knobs.get("hierarchical", True))
        if self.overlap is None:
            self.overlap = bool(knobs.get("overlap", False))
        rest = {k: v for k, v in knobs.items()
                if k not in ("hierarchical", "overlap")}
        super()._apply_plan_knobs(rest)

    def _fresh_db(self, seed: int):
        from ..parallel import multihost_sb as mhs
        return mhs.create_multihost_sb(self.mesh, self.size)

    def _build(self, w: int):
        return cached_runner(
            "multihost_sb", self.size, mesh=self.mesh, w=w,
            cohorts_per_block=self.cpb, monitor=self.monitor,
            hierarchical=self.hierarchical, serve=True,
            overlap=self.overlap, **self.runner_kw)

    def warmup(self) -> None:
        zeros = np.zeros((self.n_hosts, self.n_ici, self.cpb), np.int32)
        key = jax.random.PRNGKey(0)
        for w in self.cfg.widths:
            run, init, drain = self._runners[w]
            db = jax.tree_util.tree_map(jnp.array, self._db)
            carry = init(db)
            carry, _ = run(carry, key, zeros, zeros)
            drain(carry)

    # -- the pump -------------------------------------------------------

    def _dispatch(self, occ: np.ndarray, shed: np.ndarray) -> None:
        run, _, _ = self._runners[self._cur_w]
        key = jax.random.fold_in(self.base_key, self._block_idx)
        t_disp = self.clock.now()
        self._carry, stats = run(self._carry, key, occ, shed)
        self._pending.append((stats, t_disp, self._cur_w))
        self._block_idx += 1
        self.blocks += 1
        self.steps_by_width[self._cur_w] += self.cpb
        if isinstance(self.clock, VirtualClock):
            # the model IS the device: the whole mesh advances one block
            self.clock.sleep(self.cpb * self.model.service_us(self._cur_w)
                             * 1e-6)
        if len(self._pending) >= self.depth:
            self._retire_one()

    # -- per-host admission ---------------------------------------------

    def _ingest(self, stream: ArrivalStream, dt: float) -> None:
        got = stream.take_until(self._rel_now())
        self.offered_total += len(got)
        for ts in got.tolist():
            self._host_backlog[self._arrival_idx % self.n_hosts].append(ts)
            self._arrival_idx += 1
        if dt > 0:
            # global rate; the controller converts to per-device units
            self.ctl.observe_rate(len(got) / dt)

    def _admit(self) -> int:
        """Per-host newest-first shedding: each host's bound is the
        single-device backlog bound times the n_ici chips it feeds."""
        cap = self.ctl.max_backlog() * self.n_ici
        shed = 0
        for h, bl in enumerate(self._host_backlog):
            backlog0 = len(bl)
            host_shed = 0
            while len(bl) > cap:
                bl.pop()                      # newest first
                self.shed_by_host[h] += 1
                self._host_shed_pending[h] += 1
                host_shed += 1
            if host_shed:
                self.ctl.journal_shed(backlog0, host_shed,
                                      scale=self.n_ici, host=h)
            shed += host_shed
        self.shed_total += shed
        self._shed_pending += shed
        return shed

    def _fill_block(self, w: int) -> np.ndarray:
        """Per-host FIFO fill into [H, C, cpb] occupancies (cohort-major
        across the host's chips) + queue-delay charge per admitted
        lane."""
        occ = np.zeros((self.n_hosts, self.n_ici, self.cpb), np.int32)
        t = self._rel_now()
        for h, bl in enumerate(self._host_backlog):
            for i in range(self.cpb):
                for c in range(self.n_ici):
                    n = min(len(bl), w)
                    occ[h, c, i] = n
                    if n:
                        ts = np.fromiter(
                            (bl.popleft() for _ in range(n)),
                            np.float64, count=n)
                        self.queue_hist.add(np.maximum(t - ts, 0.0) * 1e6)
            self.admitted_by_host[h] += int(occ[h].sum())
        self.admitted_total += int(occ.sum())
        return occ

    def _shed_mirror(self) -> np.ndarray:
        """Move the pending per-host shed tallies onto the device ledger:
        host h's count rides slot [h, 0, 0] of the next block."""
        shed = np.zeros((self.n_hosts, self.n_ici, self.cpb), np.int32)
        for h in range(self.n_hosts):
            shed[h, 0, 0] = self._host_shed_pending[h]
            self._host_shed_pending[h] = 0
        self._shed_pending = 0
        return shed

    # -- the serving loop -----------------------------------------------

    def run(self, schedule: np.ndarray, *, max_blocks: int | None = None
            ) -> dict:
        stream = ArrivalStream(schedule)
        if self._t0 is None:
            self._t0 = self.clock.now()
        last_poll = self._rel_now()

        while True:
            now = self._rel_now()
            self._ingest(stream, now - last_poll)
            last_poll = now
            self._admit()

            if not any(self._host_backlog):
                if stream.exhausted:
                    break
                nxt = stream.peek() - self._rel_now()
                self.clock.sleep(max(min(nxt, self.idle_poll_us * 1e-6),
                                     1e-9))
                continue

            w = self.ctl.width()
            if w != self._cur_w:
                # mesh-coordinated switch: _detach's drain flushes the
                # jitted pipeline on EVERY device — the recompile point
                # is the mesh-wide barrier, no extra protocol needed
                if self._cur_w is not None:
                    self._detach()
                self._maybe_rebuild_hot_frac()
                self._attach(w)

            occ = self._fill_block(w)
            self._dispatch(occ, self._shed_mirror())

            if max_blocks is not None and self.blocks >= max_blocks:
                break

        self._retire_all()
        self._elapsed = self._rel_now()
        return self.snapshot()

    # -- reporting ------------------------------------------------------

    def snapshot(self) -> dict:
        rep = super().snapshot()
        rep["mesh"] = {"n_hosts": self.n_hosts, "n_ici": self.n_ici,
                       "hierarchical": self.hierarchical,
                       "overlap": self.overlap}
        rep["per_host"] = [
            {"host": h, "admitted": self.admitted_by_host[h],
             "shed": self.shed_by_host[h]}
            for h in range(self.n_hosts)]
        return rep
