from . import u64, hashing, segments  # noqa: F401
