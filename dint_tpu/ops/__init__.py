from . import u64, hashing, segments, pallas_gather  # noqa: F401
