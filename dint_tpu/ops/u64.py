"""64-bit key handling on TPU without x64 mode.

TPUs natively operate on 32-bit lanes; JAX's default configuration downcasts
uint64 to uint32. Rather than enable global x64 (which would also pull f64
emulation into every kernel), device code represents a 64-bit key as a pair of
uint32 arrays ``(hi, lo)``. Host code (numpy) uses plain uint64.

The reference's keys are u64 (e.g. `struct message.key`,
/root/reference/tatp/ebpf/utils.h:80-87); TATP composite keys pack
(s_id, sf_type, start_time) into one u64, so full 64-bit fidelity is kept.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

U32 = jnp.uint32


def split(x: np.ndarray):
    """Host-side: uint64 ndarray -> (hi, lo) uint32 ndarrays."""
    x = np.asarray(x, dtype=np.uint64)
    return (x >> np.uint64(32)).astype(np.uint32), (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def join(hi, lo) -> np.ndarray:
    """Host-side: (hi, lo) uint32 ndarrays -> uint64 ndarray."""
    return (np.asarray(hi, np.uint64) << np.uint64(32)) | np.asarray(lo, np.uint64)


def eq(a_hi, a_lo, b_hi, b_lo):
    """Elementwise 64-bit equality on (hi, lo) pairs."""
    return (a_hi == b_hi) & (a_lo == b_lo)


def const(value: int):
    """Python int -> (hi, lo) uint32 scalars (jnp)."""
    value &= (1 << 64) - 1
    return U32(value >> 32), U32(value & 0xFFFFFFFF)


def add32c(a, b):
    """uint32 add with carry-out: returns (sum, carry)."""
    s = a + b
    return s, (s < a).astype(U32)


def add(a_hi, a_lo, b_hi, b_lo):
    """64-bit add on pairs."""
    lo, c = add32c(a_lo, b_lo)
    return a_hi + b_hi + c, lo


def xor(a_hi, a_lo, b_hi, b_lo):
    return a_hi ^ b_hi, a_lo ^ b_lo


def shr(hi, lo, n: int):
    """Logical shift right by constant n (0 < n < 64)."""
    if n >= 32:
        return jnp.zeros_like(hi), hi >> U32(n - 32) if n > 32 else hi
    return hi >> U32(n), (lo >> U32(n)) | (hi << U32(32 - n))


def shl(hi, lo, n: int):
    if n >= 32:
        return (lo << U32(n - 32)) if n > 32 else lo, jnp.zeros_like(lo)
    return (hi << U32(n)) | (lo >> U32(32 - n)), lo << U32(n)


def mul32x32(a, b):
    """Full 32x32 -> 64-bit product as (hi, lo) using 16-bit limbs.

    Avoids uint64 entirely so it lowers to plain 32-bit VPU multiplies.
    """
    a = a.astype(U32)
    b = b.astype(U32)
    a_lo = a & U32(0xFFFF)
    a_hi = a >> U32(16)
    b_lo = b & U32(0xFFFF)
    b_hi = b >> U32(16)
    ll = a_lo * b_lo                      # <= 2^32 - 2^17 + 1, fits
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    # mid = lh + hl + (ll >> 16), may carry past 32 bits
    mid, c1 = add32c(lh, hl)
    mid, c2 = add32c(mid, ll >> U32(16))
    lo = (mid << U32(16)) | (ll & U32(0xFFFF))
    hi = hh + (mid >> U32(16)) + ((c1 + c2) << U32(16))
    return hi, lo


def mul(a_hi, a_lo, b_hi, b_lo):
    """64x64 -> low 64 bits of product, as pairs."""
    hi, lo = mul32x32(a_lo, b_lo)
    hi = hi + a_lo * b_hi + a_hi * b_lo
    return hi, lo


def lt(a_hi, a_lo, b_hi, b_lo):
    """Unsigned 64-bit less-than."""
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo < b_lo))
