"""Pallas/Mosaic DMA-ring kernels for batched random access over the
HBM-resident dense tables.

PERF.md "Where the remaining 2.5x lives": the dense engines' step cost is
pinned to a short serialized chain of random-access HBM ops (gathers /
scatter-max / gather-back) at ~0.6-0.9 ms per 16-32k random indices each —
XLA emits one device op per access with no way to overlap a chain that is
data-dependent. The reference collapses its per-request path into ONE fused
in-kernel pass at the NIC (tatp/ebpf/shard_kern.c); this module is the TPU
analogue: kernels that walk K random rows with a ring of NSLOTS outstanding
row DMAs (HBM latency hiding inside one kernel launch) instead of N chained
XLA gather ops.

Two kernel families, both production entry points behind `DINT_USE_PALLAS`
(env) / `use_pallas=` (engine kwarg):

* `gather_rows(tab, idx, vw)` — the wave-1/validate/magic reads: K rows of
  `vw` u32 words from a tight interleaved 1-D table (row r's words at
  [r*vw, (r+1)*vw), the engines/tatp_dense.DenseDB.val layout). Indices are
  prefetched to SMEM (PrefetchScalarGridSpec), the kernel keeps NSLOTS row
  DMAs in flight. Semantics == `tab[(idx[:,None]*vw + arange(vw)).ravel()]`
  bit for bit (pinned in tests/test_pallas_ops.py); indices MUST be
  in-bounds — the engines clamp masked lanes onto the sentinel row, and
  unlike XLA's clipping gather a Pallas DMA from an out-of-range offset is
  undefined.

* `lock_arbitrate(arb, rows, active, step, k_arb)` — the fused
  gather -> stamp-compare -> scatter-max lock path of engines/tatp_dense:
  ONE kernel pass replaces the 3-op chain (arb gather, masked scatter-max
  of `(step << k_arb) | (M-1-lane)`, winner gather-back). The kernel walks
  the M write-slot lanes in order doing a read-modify-write per lane:
  first ACTIVE lane on a free row wins the stamp, later lanes observe
  either the in-batch stamp (step field == step) or the previous step's
  stamp (== step-1) and reject. That sequential rule is EXACTLY the XLA
  scatter-max outcome (max of the packed stamps == smallest lane index,
  proof in tests/test_pallas_ops.py::test_lock_arbitrate_matches_xla): the
  arb array and grant vector are bit-identical to the XLA path. The arb
  input is donated (input_output_aliases), so the 0.6 GB array is updated
  in place. Hardware hazard discipline: reads run NSLOTS ahead of the
  RMW point, a write DMA is force-waited when its slot is reused (lag
  NSLOTS), and an SMEM window of the last 2*NSLOTS granted rows catches
  the only writes a prefetched read can miss — so in-batch duplicates
  arbitrate correctly even with the ring fully in flight.

Round 10 adds the HOT-SET family (dintcache): the TPU-native analogue of
DINT's kernel/user split across the MEMORY hierarchy — HBM is "userspace",
VMEM is "XDP". The engines keep a compact physical mirror of the hot index
prefix (a few MiB; engines/smallbank_dense.attach_hotset) that installs
write through to, so there is no coherence protocol, just a partition:

* `gather_rows_hot(tab, mirror, idx, midx, vw)` — bulk-DMAs the whole
  mirror into VMEM once per invocation (~10 µs sequential at a few MiB),
  then serves lanes with `midx >= 0` by VMEM-local row copies while lanes
  with `midx < 0` walk the HBM DMA ring exactly like `gather_rows`.
  Semantics: `out[i] = mirror[midx[i]] if midx[i] >= 0 else tab[idx[i]]`
  (rows of vw words) — bit-identical to the plain gather whenever the
  mirror invariant `mirror[m] == tab[row_of(m)]` holds, which the engines'
  write-through installs maintain by construction.

* `scatter_rows_hot(tab, mirror, idx, midx, mask, vals, vw)` — the fused
  install/scatter variant: one kernel writes each masked lane's row into
  the HBM table AND (for `midx >= 0` lanes) into the mirror, replacing the
  XLA double scatter of the write-through path. Masked-out lanes write
  nothing (no OOB-sentinel traffic); indices among masked lanes must be
  unique — the same one-X-writer-per-row contract the engines' XLA
  `unique_indices=True` scatters already certify.

* `lock_arbitrate(..., hot_n=H)` — the fused lock pass with the arb
  array's `[0, H)` prefix cached in VMEM for the duration of the pass:
  hot lanes' RMW DMAs are VMEM-local, the prefix is bulk-copied back at
  the end, and the ring/hazard discipline is UNCHANGED (hot and cold
  lanes use the same slot ring, only the copy endpoints differ) so the
  first-lane-wins equivalence proof carries over verbatim. hot_n=0 (the
  default) is the round-6 kernel.

Round 12 adds the MEGAKERNEL family (`DINT_USE_FUSED` env / `use_fused=`
kwarg, default off): each fuses a PAIR of adjacent engine waves into one
dispatch, shortening the step's dependent-dispatch chain from ~6 to ~4.
`lock_validate` composes the arb RMW (`_arb_rmw`, hot_n prefix included)
with the OCC validate read and the next cohort's fresh meta read;
`gather_streams`/`scatter_streams` run N independent gather/masked-
scatter rings back-to-back in one launch (the install table write, its
mirror write-through, and the replication-log append = `install_log`).
Every stream is the round-6/10 ring verbatim — only dispatch boundaries
are removed — so outputs stay bit-identical to the unfused path
(tests/test_fused_ops.py) and `resolve_use_fused()` carries the same
probe-and-degrade contract below.

Fallback contract (ISSUE 1): Mosaic rejection must DEGRADE, not crash —
round 3 already hit one such rejection class (scalar VMEM stores,
tools/profile_pallas.py). `resolve_use_pallas()` therefore compiles + runs
both kernels at the caller's real lane geometry (tiny tables — the failure
modes are construct/SMEM-budget level, not table-size level) and verifies
the gather against `jnp.take` before saying yes; any exception or mismatch
logs one warning and returns False, and every builder falls back to the
XLA path. The hot-set kernels carry the same contract through
`hot_kernels_available()`, and the hot PARTITION itself has a pure-XLA
form (`hot_gather`'s index-compare partition + small-array gather), so a
Mosaic rejection costs the VMEM residency, never the hot-set split. The
probes cache per (backend, interpret, kernel, geometry) —
`kernels_available` re-probes only the kernel whose geometry changed, so
a builder rebuild (bench.py's full-geometry fallback) no longer recompiles
probes it already ran. On CPU every kernel runs under `interpret=True`
(the Mosaic pipeline never runs), which is what makes the whole layer
tier-1-testable without hardware.
"""
from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

I32 = jnp.int32
U32 = jnp.uint32

NSLOTS = 16      # outstanding row DMAs in the gather ring
RMW_SLOTS = 8    # outstanding read DMAs in the lock RMW ring
WIN = 2 * RMW_SLOTS   # recent-grant window: covers every write a read
#                       prefetched RMW_SLOTS ahead can race (see module doc)

log = logging.getLogger("dint_tpu.pallas")


def use_interpret() -> bool:
    """interpret=True off-TPU (CPU tier-1 tests, virtual meshes); the env
    override exists so hardware debugging can force either mode."""
    env = os.environ.get("DINT_PALLAS_INTERPRET")
    if env is not None:
        return env != "0"
    return jax.default_backend() != "tpu"


def env_use_pallas() -> bool:
    return os.environ.get("DINT_USE_PALLAS", "0") not in ("", "0")


def env_use_hotset() -> bool:
    return os.environ.get("DINT_USE_HOTSET", "0") not in ("", "0")


def env_use_fused() -> bool:
    return os.environ.get("DINT_USE_FUSED", "0") not in ("", "0")


def env_use_scan() -> bool:
    return os.environ.get("DINT_USE_SCAN", "0") not in ("", "0")


def resolve_use_scan(explicit: bool | None = None) -> bool:
    """Engine-builder gate for the dintscan ordered-run scan path:
    explicit kwarg wins, else the DINT_USE_SCAN env. No kernel probe here
    — the scan path has a pure-XLA slab gather (scan_slab); whether the
    streaming DMA kernel serves it rides the engine's use_pallas
    resolution (scan_kernels_available), per the round-6/10 contract."""
    if explicit is None:
        return env_use_scan()
    return bool(explicit)


def resolve_use_hotset(explicit: bool | None = None) -> bool:
    """Engine-builder gate for the hot-set partition: explicit kwarg wins,
    else the DINT_USE_HOTSET env. No kernel probe here — the partition has
    a pure-XLA form (hot_gather); whether the VMEM kernels serve it is
    resolved separately (resolve_use_pallas + hot_kernels_available)."""
    if explicit is None:
        return env_use_hotset()
    return bool(explicit)


# ------------------------------------------------------------- row gather


def _gather_kernel(vw: int, nslots: int, idx_ref, tab_ref, out_ref, sem):
    """idx_ref: SMEM [K] i32 row ids (prefetched); tab_ref: ANY [N*vw] u32;
    out_ref: ANY [K*vw] u32; sem: DMA sems [nslots]. Ring of nslots
    outstanding one-row HBM->HBM copies (validated against XLA's gather in
    interpret mode AND at K=256/N=10k geometry by tools/profile_pallas_hbm)."""
    k = idx_ref.shape[0]

    def copy(i):
        r = idx_ref[i]
        return pltpu.make_async_copy(
            tab_ref.at[pl.ds(r * vw, vw)],
            out_ref.at[pl.ds(i * vw, vw)],
            sem.at[jax.lax.rem(i, nslots)])

    def prime(i, _):
        copy(i).start()
        return 0

    jax.lax.fori_loop(0, min(nslots, k), prime, 0)

    def body(i, _):
        copy(i).wait()               # slot free again

        def issue(_):
            copy(i + nslots).start()
            return 0

        jax.lax.cond(i + nslots < k, issue, lambda _: 0, 0)
        return 0

    jax.lax.fori_loop(0, k, body, 0)


@functools.partial(jax.jit, static_argnums=(2, 3))
def gather_rows(tab, idx, vw: int = 1, interpret: bool | None = None):
    """K random rows of `vw` u32 words from the flat table `tab`
    (row r at [r*vw, (r+1)*vw)). Returns u32 [K*vw] — bit-identical to
    `tab[(idx[:,None]*vw + arange(vw)).reshape(-1)]` for in-bounds idx.
    `vw=1` covers the meta/arb/bal/stamp single-word gathers; callers that
    need one word at an offset inside wider rows pass pre-scaled flat word
    indices with vw=1 (e.g. the magic check's `rows*VW + 1`)."""
    if interpret is None:
        interpret = use_interpret()
    k = idx.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((NSLOTS,))],
    )
    return pl.pallas_call(
        functools.partial(_gather_kernel, vw, NSLOTS),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((k * vw,), U32),
        interpret=bool(interpret),
    )(idx.astype(I32), tab)


# ------------------------------------------------- hot-set row gather


def _gather_hot_kernel(vw: int, nslots: int, idx_ref, midx_ref, tab_ref,
                       mir_ref, out_ref, mir_vmem, load_sem, sem):
    """gather_rows with a VMEM-resident mirror: one bulk HBM->VMEM copy of
    the whole mirror up front, then the usual ring of nslots outstanding
    row copies — hot lanes (midx >= 0) copy VMEM-locally from the mirror,
    cold lanes DMA from the HBM table. Hot and cold lanes share the slot
    ring (same semaphore, same row size), so the round-6 ring discipline
    is unchanged."""
    k = idx_ref.shape[0]
    load = pltpu.make_async_copy(mir_ref, mir_vmem, load_sem)
    load.start()
    load.wait()

    def cold(i):
        return pltpu.make_async_copy(
            tab_ref.at[pl.ds(idx_ref[i] * vw, vw)],
            out_ref.at[pl.ds(i * vw, vw)],
            sem.at[jax.lax.rem(i, nslots)])

    def hot(i):
        return pltpu.make_async_copy(
            mir_vmem.at[pl.ds(midx_ref[i] * vw, vw)],
            out_ref.at[pl.ds(i * vw, vw)],
            sem.at[jax.lax.rem(i, nslots)])

    def start(i):
        @pl.when(midx_ref[i] >= 0)
        def _():
            hot(i).start()

        @pl.when(midx_ref[i] < 0)
        def _():
            cold(i).start()

    def wait(i):
        @pl.when(midx_ref[i] >= 0)
        def _():
            hot(i).wait()

        @pl.when(midx_ref[i] < 0)
        def _():
            cold(i).wait()

    def prime(i, _):
        start(i)
        return 0

    jax.lax.fori_loop(0, min(nslots, k), prime, 0)

    def body(i, _):
        wait(i)

        @pl.when(i + nslots < k)
        def _():
            start(i + nslots)

        return 0

    jax.lax.fori_loop(0, k, body, 0)


@functools.partial(jax.jit, static_argnums=(4, 5))
def gather_rows_hot(tab, mirror, idx, midx, vw: int = 1,
                    interpret: bool | None = None):
    """Partitioned row gather: `out[i] = mirror[midx[i]*vw +: vw]` when
    `midx[i] >= 0`, else `tab[idx[i]*vw +: vw]`. Bit-identical to
    `gather_rows(tab, idx, vw)` whenever the mirror mirrors the table
    (the engines' write-through invariant). Cold-lane idx must be
    in-bounds (same sentinel-clamp contract as gather_rows); hot-lane
    midx must address the mirror."""
    if interpret is None:
        interpret = use_interpret()
    k = idx.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((mirror.shape[0],), U32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((NSLOTS,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_gather_hot_kernel, vw, NSLOTS),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((k * vw,), U32),
        interpret=bool(interpret),
    )(idx.astype(I32), midx.astype(I32), tab, mirror)


def _xla_hot_gather(tab, mirror, idx, midx, vw: int):
    """The XLA fallback partition: index-compare + small-array gather.
    Same semantics as the kernel; exists so a Mosaic rejection costs the
    VMEM residency, never the hot-set split."""
    flat_c = (idx[:, None] * vw + jnp.arange(vw, dtype=I32)).reshape(-1)
    mc = jnp.maximum(midx, 0)
    flat_h = (mc[:, None] * vw + jnp.arange(vw, dtype=I32)).reshape(-1)
    hot = jnp.repeat(midx >= 0, vw)
    return jnp.where(hot, mirror[flat_h], tab[flat_c])


def hot_gather(tab, mirror, idx, midx, vw: int = 1,
               use_pallas: bool = False):
    """Engine entry point for the partitioned gather: the VMEM kernel when
    the builder resolved pallas, the index-compare XLA partition
    otherwise. Returns u32 [K*vw]."""
    if use_pallas:
        return gather_rows_hot(tab, mirror, idx.astype(I32),
                               midx.astype(I32), vw)
    return _xla_hot_gather(tab, mirror, idx.astype(I32),
                           midx.astype(I32), vw)


# --------------------------------------------- dintscan sequential slabs


def _scan_kernel(vw: int, lg: int, nslots: int, off_ref, order_ref,
                 hi_ref, lo_ref, ver_ref, val_ref,
                 ohi_ref, olo_ref, over_ref, oval_ref, sem):
    """Sequential-slab gather over the ordered run: per lane, FOUR
    contiguous-row DMAs (key_hi/key_lo/ver of `lg` rows + their `lg*vw`
    val words) land the window [off, off+lg) into the lane's reply slab.
    Lanes are walked in ascending-offset order (order_ref, prefetched) so
    consecutive DMAs touch adjacent HBM — the sequential-bandwidth shape
    the sorted layout exists for — while slabs land at each lane's
    ORIGINAL index, keeping outputs order-independent and bit-identical
    to the XLA slab gather. The ring keeps nslots lanes (4 DMAs each) in
    flight; off must be in-bounds ([0, cap-lg], the engine's clamped
    locate offsets) — a Pallas DMA from an out-of-range offset is
    undefined, unlike XLA's clipping gather."""
    k = off_ref.shape[0]

    def copies(j):
        lane = order_ref[j]
        base = off_ref[lane]
        s = jax.lax.rem(j, nslots)
        return (
            pltpu.make_async_copy(hi_ref.at[pl.ds(base, lg)],
                                  ohi_ref.at[pl.ds(lane * lg, lg)],
                                  sem.at[s, 0]),
            pltpu.make_async_copy(lo_ref.at[pl.ds(base, lg)],
                                  olo_ref.at[pl.ds(lane * lg, lg)],
                                  sem.at[s, 1]),
            pltpu.make_async_copy(ver_ref.at[pl.ds(base, lg)],
                                  over_ref.at[pl.ds(lane * lg, lg)],
                                  sem.at[s, 2]),
            pltpu.make_async_copy(val_ref.at[pl.ds(base * vw, lg * vw)],
                                  oval_ref.at[pl.ds(lane * lg * vw, lg * vw)],
                                  sem.at[s, 3]),
        )

    def start(j):
        for c in copies(j):
            c.start()

    def wait(j):
        for c in copies(j):
            c.wait()

    def prime(j, _):
        start(j)
        return 0

    jax.lax.fori_loop(0, min(nslots, k), prime, 0)

    def body(j, _):
        wait(j)

        @pl.when(j + nslots < k)
        def _():
            start(j + nslots)

        return 0

    jax.lax.fori_loop(0, k, body, 0)


@functools.partial(jax.jit, static_argnums=(6, 7, 8))
def scan_rows(run_hi, run_lo, run_ver, run_val, off, order, lg: int,
              vw: int, interpret: bool | None = None):
    """K sequential windows of `lg` rows from the ordered run's flat
    arrays. `order` is the lane walk order (ascending off); returns
    (hi, lo, ver, val) slabs of shapes [K*lg] / [K*lg*vw], bit-identical
    to the XLA slab gather for in-bounds off."""
    if interpret is None:
        interpret = use_interpret()
    k = off.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 4,
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 4,
        scratch_shapes=[pltpu.SemaphoreType.DMA((NSLOTS, 4))],
    )
    return pl.pallas_call(
        functools.partial(_scan_kernel, vw, lg, NSLOTS),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((k * lg,), U32),
                   jax.ShapeDtypeStruct((k * lg,), U32),
                   jax.ShapeDtypeStruct((k * lg,), U32),
                   jax.ShapeDtypeStruct((k * lg * vw,), U32)],
        interpret=bool(interpret),
    )(off.astype(I32), order.astype(I32), run_hi, run_lo, run_ver, run_val)


def _xla_scan_slab(run_hi, run_lo, run_ver, run_val, off, lg: int, vw: int):
    """The XLA fallback partition: per-lane dynamic-slice-shaped gathers
    of the same contiguous windows. Costs random-gather issue rate where
    the kernel streams, never correctness."""
    idx = off[:, None] + jnp.arange(lg, dtype=I32)[None, :]
    widx = (idx * vw)[:, :, None] + jnp.arange(vw, dtype=I32)[None, None, :]
    return (run_hi[idx], run_lo[idx], run_ver[idx],
            run_val[widx])


def scan_slab(run_hi, run_lo, run_ver, run_val, off, lg: int, vw: int,
              use_pallas: bool = False):
    """Engine entry point for the dintscan window gather: the streaming
    DMA kernel when the builder resolved pallas, the XLA slab gather
    otherwise. Returns (hi, lo, ver [K, lg], val [K, lg, vw])."""
    off = off.astype(I32)
    k = off.shape[0]
    if use_pallas:
        order = jnp.argsort(off)
        hi, lo, ver, val = scan_rows(run_hi, run_lo, run_ver, run_val,
                                     off, order, lg, vw)
        return (hi.reshape(k, lg), lo.reshape(k, lg), ver.reshape(k, lg),
                val.reshape(k, lg, vw))
    return _xla_scan_slab(run_hi, run_lo, run_ver, run_val, off, lg, vw)


# ---------------------------------------------- hot-set fused install


def _scatter_hot_kernel(vw: int, nslots: int, idx_ref, midx_ref, msk_ref,
                        vals_ref, tab_in, mir_in, tab_out, mir_out,
                        tlane, mlane, tsem, msem):
    """Fused write-through install: per masked lane, one row DMA into the
    HBM table and (when midx >= 0) one into the mirror. Unmasked lanes
    issue nothing (no OOB-sentinel traffic). Per-slot SMEM trackers
    record WHICH lane's copy occupies a ring slot so reuse force-waits
    exactly the copies that were started. In-flight writes never collide:
    indices among masked lanes are unique (the engines' one-X-writer-
    per-row certification, the same contract their unique_indices=True
    XLA scatters declare)."""
    k = idx_ref.shape[0]

    def t_copy(i):
        return pltpu.make_async_copy(
            vals_ref.at[pl.ds(i * vw, vw)],
            tab_out.at[pl.ds(idx_ref[i] * vw, vw)],
            tsem.at[jax.lax.rem(i, nslots)])

    def m_copy(i):
        return pltpu.make_async_copy(
            vals_ref.at[pl.ds(i * vw, vw)],
            mir_out.at[pl.ds(midx_ref[i] * vw, vw)],
            msem.at[jax.lax.rem(i, nslots)])

    def init(s, _):
        tlane[s] = I32(-1)
        mlane[s] = I32(-1)
        return 0

    jax.lax.fori_loop(0, nslots, init, 0)

    def body(i, _):
        s = jax.lax.rem(i, nslots)

        @pl.when(tlane[s] >= 0)
        def _():
            t_copy(tlane[s]).wait()

        tlane[s] = I32(-1)

        @pl.when(mlane[s] >= 0)
        def _():
            m_copy(mlane[s]).wait()

        mlane[s] = I32(-1)

        @pl.when(msk_ref[i] != 0)
        def _():
            t_copy(i).start()
            tlane[s] = i

            @pl.when(midx_ref[i] >= 0)
            def _():
                m_copy(i).start()
                mlane[s] = i

        return 0

    jax.lax.fori_loop(0, k, body, 0)

    def drain(s, _):
        @pl.when(tlane[s] >= 0)
        def _():
            t_copy(tlane[s]).wait()

        @pl.when(mlane[s] >= 0)
        def _():
            m_copy(mlane[s]).wait()

        return 0

    jax.lax.fori_loop(0, nslots, drain, 0)


@functools.partial(jax.jit, static_argnums=(6, 7), donate_argnums=(0, 1))
def scatter_rows_hot(tab, mirror, idx, midx, mask, vals, vw: int = 1,
                     interpret: bool | None = None):
    """Fused install: for every lane with mask != 0, write vals row i into
    `tab[idx[i]*vw +: vw]` AND, when `midx[i] >= 0`, into
    `mirror[midx[i]*vw +: vw]`. Returns (tab', mirror'), both updated in
    place (donated). Indices among masked lanes must be unique."""
    if interpret is None:
        interpret = use_interpret()
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        scratch_shapes=[
            pltpu.SMEM((NSLOTS,), I32),     # tlane: lane holding tab slot
            pltpu.SMEM((NSLOTS,), I32),     # mlane: lane holding mir slot
            pltpu.SemaphoreType.DMA((NSLOTS,)),
            pltpu.SemaphoreType.DMA((NSLOTS,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_scatter_hot_kernel, vw, NSLOTS),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(tab.shape, U32),
                   jax.ShapeDtypeStruct(mirror.shape, U32)),
        # operands 4/5 (post scalar-prefetch: vals, tab, mirror) -> in-place
        input_output_aliases={4: 0, 5: 1},
        interpret=bool(interpret),
    )(idx.astype(I32), midx.astype(I32), mask.astype(I32), vals, tab,
      mirror)


def hot_scatter(tab, mirror, idx, midx, mask, vals, vw: int = 1,
                use_pallas: bool = False):
    """Engine entry point for the write-through install: the fused kernel
    when the builder resolved pallas, the XLA double scatter otherwise
    (both 1-D unique-index fast paths). Returns (tab', mirror')."""
    if use_pallas:
        return scatter_rows_hot(tab, mirror, idx, midx, mask, vals, vw)
    n_tab = tab.shape[0] // vw
    n_mir = mirror.shape[0] // vw
    widx = jnp.where(mask != 0, idx, n_tab)
    wflat = (widx[:, None] * vw + jnp.arange(vw, dtype=I32)).reshape(-1)
    tab = tab.at[wflat].set(vals, mode="drop", unique_indices=True)
    hmask = (mask != 0) & (midx >= 0)
    hidx = jnp.where(hmask, midx, n_mir)
    hflat = (hidx[:, None] * vw + jnp.arange(vw, dtype=I32)).reshape(-1)
    mirror = mirror.at[hflat].set(vals, mode="drop", unique_indices=True)
    return tab, mirror


# ------------------------------------------------------- fused lock pass


def _arb_rmw(k_arb: int, hot_n: int, rows_ref, act_ref, t, arb_out,
             rbuf, wbuf, gbuf, win_row, hot_vmem, rsem, wsem, hsem):
    """Sequential first-lane-wins RMW over M lock lanes — the fused form of
    gather -> stamp-compare -> scatter-max (bit-equivalence argument in the
    module docstring). Grants accumulate in the SMEM ``gbuf``; the caller
    DMAs them out (lock_arbitrate's trailing copy) or keeps composing
    (lock_validate). This is the WHOLE arbitration pass — hot-prefix
    load/store, ring init, prime, body, drain — factored so the megakernel
    reuses it verbatim and the round-6 equivalence proof carries over
    unchanged.

    ``hot_n`` > 0 additionally caches the arb prefix [0, hot_n) in VMEM
    for the whole pass: lanes on hot rows RMW against the VMEM copy
    (VMEM-local DMAs — no HBM latency on the 90% of a skewed batch), cold
    lanes against HBM, and the prefix is bulk-copied back at the end. Hot
    and cold rows are DISJOINT index sets, both lane classes run the SAME
    slot ring / force-wait / grant-window discipline (only the copy
    endpoints differ), so the round-6 hazard argument — every write older
    than the ring depth has landed, the SMEM window catches the rest —
    holds verbatim."""
    m = rows_ref.shape[0]

    if hot_n > 0:
        load = pltpu.make_async_copy(arb_out.at[pl.ds(0, hot_n)],
                                     hot_vmem, hsem)
        load.start()
        load.wait()

    def _rd(i, ref):
        return pltpu.make_async_copy(
            ref.at[pl.ds(rows_ref[i], 1)],
            rbuf.at[pl.ds(jax.lax.rem(i, RMW_SLOTS), 1)],
            rsem.at[jax.lax.rem(i, RMW_SLOTS)])

    def _wr(i, ref):
        return pltpu.make_async_copy(
            wbuf.at[pl.ds(jax.lax.rem(i, RMW_SLOTS), 1)],
            ref.at[pl.ds(rows_ref[i], 1)],
            wsem.at[jax.lax.rem(i, RMW_SLOTS)])

    def _route(i, mk, verb):
        """Issue (verb='start') or retire (verb='wait') lane i's copy
        against its row's endpoint: the VMEM prefix for hot rows, the HBM
        array for cold. Descriptors are identical in size/semaphore, so
        the ring discipline does not see the split."""
        if hot_n == 0:
            getattr(mk(i, arb_out), verb)()
            return

        @pl.when(rows_ref[i] < hot_n)
        def _():
            getattr(mk(i, hot_vmem), verb)()

        @pl.when(rows_ref[i] >= hot_n)
        def _():
            getattr(mk(i, arb_out), verb)()

    def read_start(i):
        _route(i, _rd, "start")

    def read_wait(i):
        _route(i, _rd, "wait")

    def write_start(i):
        _route(i, _wr, "start")

    def write_wait(i):
        _route(i, _wr, "wait")

    def init_win(i, _):
        win_row[i] = I32(-1)
        return 0

    jax.lax.fori_loop(0, WIN, init_win, 0)

    def init_wbuf(i, _):
        # wbuf doubles as the per-slot write-in-flight flag: packed stamps
        # are never 0 (step >= 2), so nonzero == a write DMA to force-wait
        wbuf[i] = U32(0)
        return 0

    jax.lax.fori_loop(0, RMW_SLOTS, init_wbuf, 0)

    def prime(i, _):
        read_start(i)
        return 0

    jax.lax.fori_loop(0, min(RMW_SLOTS, m), prime, 0)

    def body(i, _):
        s = jax.lax.rem(i, RMW_SLOTS)
        # a write DMA still in flight on this slot belongs to lane
        # i - RMW_SLOTS: force-wait it so (a) wbuf[s] is reusable and
        # (b) every write older than the ring depth has LANDED before the
        # reads issued this iteration (the hazard-window invariant)
        @pl.when(jnp.logical_and(i >= RMW_SLOTS,
                                 wbuf[jax.lax.rem(i, RMW_SLOTS)] != U32(0)))
        def _():
            write_wait(i - RMW_SLOTS)

        wbuf[s] = U32(0)

        read_wait(i)
        old = rbuf[s]
        r = rows_ref[i]

        # writes a ring-prefetched read can have missed are exactly the
        # last WIN lanes' grants — scan the SMEM window for this row
        def scan(j, hit):
            return jnp.logical_or(hit, win_row[j] == r)

        taken_win = jax.lax.fori_loop(0, WIN, scan, False)

        stamp = old >> k_arb
        held = stamp == t - U32(1)              # stamped by the previous step
        taken = jnp.logical_or(stamp == t, taken_win)   # in-batch winner
        grant = jnp.logical_and(act_ref[i] != 0,
                                jnp.logical_not(jnp.logical_or(held, taken)))

        gbuf[i] = jax.lax.select(grant, U32(1), U32(0))
        win_row[jax.lax.rem(i, WIN)] = jax.lax.select(grant, r, I32(-1))

        @pl.when(grant)
        def _():
            inv = U32(m - 1) - i.astype(U32)    # == XLA's inverted slot
            wbuf[s] = (t << k_arb) | inv
            write_start(i)

        @pl.when(i + RMW_SLOTS < m)
        def _():
            read_start(i + RMW_SLOTS)

        return 0

    jax.lax.fori_loop(0, m, body, 0)

    def drain(j, _):
        i = m - min(RMW_SLOTS, m) + j

        @pl.when(wbuf[jax.lax.rem(i, RMW_SLOTS)] != U32(0))
        def _():
            write_wait(i)

        return 0

    jax.lax.fori_loop(0, min(RMW_SLOTS, m), drain, 0)

    if hot_n > 0:
        # every hot write has retired (drain above), so the VMEM prefix is
        # the final state of rows [0, hot_n): one bulk copy back in place
        store = pltpu.make_async_copy(hot_vmem, arb_out.at[pl.ds(0, hot_n)],
                                      hsem)
        store.start()
        store.wait()


def _arbitrate_kernel(k_arb: int, hot_n: int, rows_ref, act_ref, t_ref,
                      arb_in, arb_out, grant_out, rbuf, wbuf, gbuf,
                      win_row, hot_vmem, rsem, wsem, gsem, hsem):
    """The standalone lock pass: the RMW core plus one trailing DMA that
    carries the SMEM grant bits out. arb_in/arb_out alias (in-place update
    of the HBM array)."""
    _arb_rmw(k_arb, hot_n, rows_ref, act_ref, t_ref[0], arb_out, rbuf,
             wbuf, gbuf, win_row, hot_vmem, rsem, wsem, hsem)
    out = pltpu.make_async_copy(gbuf, grant_out, gsem)
    out.start()
    out.wait()


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def lock_arbitrate(arb, rows, active, step, k_arb: int,
                   interpret: bool | None = None, hot_n: int = 0):
    """Fused lock pass over the step-stamped arb array (engines/tatp_dense
    layout: `step << k_arb | inverted_slot`). Returns (arb', grant u32[M])
    bit-identical to the XLA chain

        old  = arb[rows]; held = (old >> k_arb) == step - 1
        cand = active & ~held
        arb' = arb.at[where(cand, rows, oob)].max((step << k_arb)
                                                  | (M-1 - lane), "drop")
        grant = cand & (arb'[rows] == packed)

    for in-bounds rows (masked lanes must carry active=False and a valid
    sentinel row id, exactly what pipe_step already does). The arb buffer
    is donated and updated in place.

    ``hot_n`` (static) > 0 caches the arb prefix [0, hot_n) in VMEM for
    the pass (the dintcache hot tier — module docstring); outputs stay
    bit-identical, only the DMA endpoints of hot lanes change."""
    if interpret is None:
        interpret = use_interpret()
    m = rows.shape[0]
    assert 0 <= hot_n <= arb.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        scratch_shapes=[
            pltpu.SMEM((RMW_SLOTS,), U32),    # rbuf: in-flight read words
            pltpu.SMEM((RMW_SLOTS,), U32),    # wbuf: in-flight write words
            pltpu.SMEM((m,), U32),            # gbuf: per-lane grant bits
            pltpu.SMEM((WIN,), I32),          # win_row: recent granted rows
            pltpu.VMEM((max(hot_n, 1),), U32),  # hot arb prefix residency
            pltpu.SemaphoreType.DMA((RMW_SLOTS,)),
            pltpu.SemaphoreType.DMA((RMW_SLOTS,)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    arb2, grant = pl.pallas_call(
        functools.partial(_arbitrate_kernel, k_arb, hot_n),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(arb.shape, U32),
                   jax.ShapeDtypeStruct((m,), U32)),
        # operand 3 (post scalar-prefetch) -> output 0: in-place arb update
        input_output_aliases={3: 0},
        interpret=bool(interpret),
    )(rows.astype(I32), active.astype(I32),
      step.reshape(1).astype(U32), arb)
    return arb2, grant


# ------------------------------------------------- round-12 megakernels
#
# Two fusions that each swallow a PAIR of adjacent waves of the engine
# step (PERF.md round 12), shortening the dependency chain from ~6
# dispatches to ~4:
#
# * lock_validate — the lock-arbitration RMW (_arb_rmw, including its
#   hot_n VMEM prefix residency) composed with the OCC validate read and
#   the next cohort's fresh meta read in ONE dispatch. meta and arb are
#   disjoint arrays, so phase order inside the kernel cannot change any
#   output and the round-6 first-lane-wins proof carries verbatim.
#
# * gather_streams / scatter_streams — N independent row-gather /
#   masked-row-scatter rings run back-to-back inside one dispatch (the
#   install table write, its mirror write-through, and the replication-
#   log append become one kernel: install_log). Each stream is the
#   round-6/round-10 single-target ring verbatim; only the dispatch
#   boundary between them is removed. Streams must target DISJOINT
#   arrays; indices < 0 are masked lanes (no traffic); masked-in indices
#   per stream must be unique — the engines' one-writer-per-row
#   certification, identical to their unique_indices=True XLA scatters.


def _lock_validate_kernel(k_arb: int, hot_n: int, vidx_ref, vv1_ref,
                          ridx_ref, rows_ref, act_ref, t_ref, meta_in,
                          arb_in, arb_out, grant_out, vbad_out, rmeta_out,
                          rbuf, wbuf, gbuf, win_row, hot_vmem, vrbuf, vb,
                          rsem, wsem, gsem, hsem, vsem, vbsem, msem):
    """The lock+validate megakernel: (1) ring-gather each validate lane's
    packed meta word into SMEM and compare against the expected version
    (vb[i] = word != vv1[i]); (2) ring-gather the next cohort's fresh
    meta words straight to HBM (_gather_kernel verbatim); (3) run the
    arbitration RMW (_arb_rmw verbatim); (4) DMA the grant bits and
    validate verdicts out. meta_in and arb_out are disjoint arrays, so
    the phases commute with the unfused two-dispatch schedule bit for
    bit."""
    v = vidx_ref.shape[0]
    t = t_ref[0]

    def vcopy(i):
        return pltpu.make_async_copy(
            meta_in.at[pl.ds(vidx_ref[i], 1)],
            vrbuf.at[pl.ds(jax.lax.rem(i, RMW_SLOTS), 1)],
            vsem.at[jax.lax.rem(i, RMW_SLOTS)])

    def vprime(i, _):
        vcopy(i).start()
        return 0

    jax.lax.fori_loop(0, min(RMW_SLOTS, v), vprime, 0)

    def vbody(i, _):
        vcopy(i).wait()
        word = vrbuf[jax.lax.rem(i, RMW_SLOTS)]
        vb[i] = jax.lax.select(word != vv1_ref[i], U32(1), U32(0))

        # the slot's word was consumed above, so reuse is hazard-free
        @pl.when(i + RMW_SLOTS < v)
        def _():
            vcopy(i + RMW_SLOTS).start()

        return 0

    jax.lax.fori_loop(0, v, vbody, 0)

    _gather_kernel(1, NSLOTS, ridx_ref, meta_in, rmeta_out, msem)

    _arb_rmw(k_arb, hot_n, rows_ref, act_ref, t, arb_out, rbuf, wbuf,
             gbuf, win_row, hot_vmem, rsem, wsem, hsem)

    gout = pltpu.make_async_copy(gbuf, grant_out, gsem)
    gout.start()
    gout.wait()
    vout = pltpu.make_async_copy(vb, vbad_out, vbsem)
    vout.start()
    vout.wait()


@functools.partial(jax.jit, static_argnums=(8, 9, 10))
def lock_validate(arb, meta, vidx, vv1, ridx, rows, active, step,
                  k_arb: int, interpret: bool | None = None,
                  hot_n: int = 0):
    """Fused lock+validate pass. Returns (arb', grant u32[M], vbad u32[V],
    rmeta u32[R]) where (arb', grant) are bit-identical to
    `lock_arbitrate(arb, rows, active, step, k_arb, hot_n=hot_n)`,
    `vbad[i] = (meta[vidx[i]] != vv1[i])` (the OCC validate verdict; the
    engine masks it with is_read afterwards exactly as it masked the
    unfused compare), and `rmeta = meta[ridx]` (the next cohort's version
    seeds, == gather_rows(meta, ridx, 1)). All indices must be in-bounds
    (sentinel-clamped by the engines, same contract as gather_rows). The
    arb buffer is donated and updated in place."""
    if interpret is None:
        interpret = use_interpret()
    m = rows.shape[0]
    v = vidx.shape[0]
    r = ridx.shape[0]
    assert 0 <= hot_n <= arb.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        scratch_shapes=[
            pltpu.SMEM((RMW_SLOTS,), U32),    # rbuf: in-flight read words
            pltpu.SMEM((RMW_SLOTS,), U32),    # wbuf: in-flight write words
            pltpu.SMEM((m,), U32),            # gbuf: per-lane grant bits
            pltpu.SMEM((WIN,), I32),          # win_row: recent granted rows
            pltpu.VMEM((max(hot_n, 1),), U32),  # hot arb prefix residency
            pltpu.SMEM((RMW_SLOTS,), U32),    # vrbuf: in-flight meta words
            pltpu.SMEM((v,), U32),            # vb: per-lane validate bits
            pltpu.SemaphoreType.DMA((RMW_SLOTS,)),   # rsem
            pltpu.SemaphoreType.DMA((RMW_SLOTS,)),   # wsem
            pltpu.SemaphoreType.DMA(()),             # gsem
            pltpu.SemaphoreType.DMA(()),             # hsem
            pltpu.SemaphoreType.DMA((RMW_SLOTS,)),   # vsem
            pltpu.SemaphoreType.DMA(()),             # vbsem
            pltpu.SemaphoreType.DMA((NSLOTS,)),      # msem (rmeta ring)
        ],
    )
    arb2, grant, vbad, rmeta = pl.pallas_call(
        functools.partial(_lock_validate_kernel, k_arb, hot_n),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(arb.shape, U32),
                   jax.ShapeDtypeStruct((m,), U32),
                   jax.ShapeDtypeStruct((v,), U32),
                   jax.ShapeDtypeStruct((r,), U32)),
        # operand 7 (post scalar-prefetch: meta, arb) -> output 0
        input_output_aliases={7: 0},
        interpret=bool(interpret),
    )(vidx.astype(I32), vv1.astype(U32), ridx.astype(I32),
      rows.astype(I32), active.astype(I32), step.reshape(1).astype(U32),
      meta, arb)
    return arb2, grant, vbad, rmeta


def _gather_streams_kernel(vws: tuple, nslots: int, *refs):
    s_n = len(vws)
    idxs = refs[:s_n]
    tabs = refs[s_n:2 * s_n]
    outs = refs[2 * s_n:3 * s_n]
    sems = refs[3 * s_n:]
    for s in range(s_n):
        _gather_kernel(vws[s], nslots, idxs[s], tabs[s], outs[s], sems[s])


@functools.partial(jax.jit, static_argnums=(2, 3))
def gather_streams(tabs, idxs, vws: tuple, interpret: bool | None = None):
    """N independent row gathers in ONE dispatch: stream s gathers
    `idxs[s]` rows of `vws[s]` u32 words from `tabs[s]` — each stream is
    _gather_kernel verbatim, so per-stream semantics equal
    `gather_rows(tabs[s], idxs[s], vws[s])` bit for bit. Returns a tuple
    of u32 [K_s * vws[s]] arrays."""
    if interpret is None:
        interpret = use_interpret()
    tabs = tuple(tabs)
    idxs = tuple(i.astype(I32) for i in idxs)
    s_n = len(vws)
    assert len(tabs) == len(idxs) == s_n
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=s_n,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * s_n,
        out_specs=tuple(pl.BlockSpec(memory_space=pltpu.ANY)
                        for _ in range(s_n)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((NSLOTS,))
                        for _ in range(s_n)],
    )
    return pl.pallas_call(
        functools.partial(_gather_streams_kernel, tuple(vws), NSLOTS),
        grid_spec=grid_spec,
        out_shape=tuple(
            jax.ShapeDtypeStruct((idxs[s].shape[0] * vws[s],), U32)
            for s in range(s_n)),
        interpret=bool(interpret),
    )(*idxs, *tabs)


def _xla_gather_streams(tabs, idxs, vws):
    """XLA form of gather_streams (per-stream flat gathers) — the probe
    ground truth and the shape the unfused engine paths already emit."""
    outs = []
    for tab, idx, vw in zip(tabs, idxs, vws):
        idx = idx.astype(I32)
        flat = (idx[:, None] * vw + jnp.arange(vw, dtype=I32)).reshape(-1)
        outs.append(tab[flat])
    return tuple(outs)


def _scatter_one_stream(vw: int, nslots: int, idx_ref, vals_ref, out_ref,
                        trk, sem):
    """One masked row-scatter ring (idx < 0 = masked lane, no traffic):
    the scatter_rows_hot single-target discipline — a per-slot SMEM
    tracker records WHICH lane's copy occupies a ring slot so reuse
    force-waits exactly the copies that were started."""
    k = idx_ref.shape[0]

    def cp(i):
        return pltpu.make_async_copy(
            vals_ref.at[pl.ds(i * vw, vw)],
            out_ref.at[pl.ds(idx_ref[i] * vw, vw)],
            sem.at[jax.lax.rem(i, nslots)])

    def init(s, _):
        trk[s] = I32(-1)
        return 0

    jax.lax.fori_loop(0, nslots, init, 0)

    def body(i, _):
        s = jax.lax.rem(i, nslots)

        @pl.when(trk[s] >= 0)
        def _():
            cp(trk[s]).wait()

        trk[s] = I32(-1)

        @pl.when(idx_ref[i] >= 0)
        def _():
            cp(i).start()
            trk[s] = i

        return 0

    jax.lax.fori_loop(0, k, body, 0)

    def drain(s, _):
        @pl.when(trk[s] >= 0)
        def _():
            cp(trk[s]).wait()

        return 0

    jax.lax.fori_loop(0, nslots, drain, 0)


def _scatter_streams_kernel(vws: tuple, nslots: int, *refs):
    s_n = len(vws)
    idxs = refs[:s_n]
    vals = refs[s_n:2 * s_n]
    # refs[2*s_n : 3*s_n] are the aliased table INPUTS — never read; the
    # in-place targets are the aliased outputs
    outs = refs[3 * s_n:4 * s_n]
    trks = refs[4 * s_n:5 * s_n]
    sems = refs[5 * s_n:]
    for s in range(s_n):
        _scatter_one_stream(vws[s], nslots, idxs[s], vals[s], outs[s],
                            trks[s], sems[s])


@functools.partial(jax.jit, static_argnums=(3, 4), donate_argnums=(0,))
def scatter_streams(tabs, idxs, vals, vws: tuple,
                    interpret: bool | None = None):
    """N independent masked row scatters in ONE dispatch (the install_log
    megakernel): stream s writes `vals[s]` row i into
    `tabs[s][idxs[s][i]*vw +: vw]` for every lane with `idxs[s][i] >= 0`;
    lanes with idx < 0 write nothing. Streams must target DISJOINT
    arrays; masked-in indices per stream must be unique (the engines'
    one-writer-per-row certification). Every table is donated and updated
    in place; returns the updated tuple, bit-identical per stream to the
    engines' `tab.at[flat].set(vals, mode="drop", unique_indices=True)`
    with the mask folded onto an OOB row."""
    if interpret is None:
        interpret = use_interpret()
    tabs = tuple(tabs)
    idxs = tuple(i.astype(I32) for i in idxs)
    vals = tuple(vals)
    s_n = len(vws)
    assert len(tabs) == len(idxs) == len(vals) == s_n
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=s_n,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * (2 * s_n),
        out_specs=tuple(pl.BlockSpec(memory_space=pltpu.ANY)
                        for _ in range(s_n)),
        scratch_shapes=(
            [pltpu.SMEM((NSLOTS,), I32) for _ in range(s_n)]
            + [pltpu.SemaphoreType.DMA((NSLOTS,)) for _ in range(s_n)]),
    )
    return pl.pallas_call(
        functools.partial(_scatter_streams_kernel, tuple(vws), NSLOTS),
        grid_spec=grid_spec,
        out_shape=tuple(jax.ShapeDtypeStruct(t.shape, U32) for t in tabs),
        # operands 2S+s (post scalar-prefetch: vals x S, tabs x S) -> s
        input_output_aliases={2 * s_n + s: s for s in range(s_n)},
        interpret=bool(interpret),
    )(*idxs, *vals, *tabs)


def _xla_scatter_streams(tabs, idxs, vals, vws):
    """XLA form of scatter_streams: per-stream 1-D unique-index drop
    scatters with masked lanes folded onto the OOB row — exactly the
    shape the unfused engine installs already emit."""
    outs = []
    for tab, idx, val, vw in zip(tabs, idxs, vals, vws):
        idx = idx.astype(I32)
        n = tab.shape[0] // vw
        widx = jnp.where(idx >= 0, idx, n)
        wflat = (widx[:, None] * vw + jnp.arange(vw, dtype=I32)).reshape(-1)
        outs.append(tab.at[wflat].set(val.astype(U32), mode="drop",
                                      unique_indices=True))
    return tuple(outs)


# ------------------------------------------------------ fallback plumbing

# per-kernel probe results, keyed ("gather"|"lock"|"hot", backend,
# interpret, geometry...): a builder rebuild that reuses one kernel's
# geometry never re-compiles that kernel's probe just because the OTHER
# kernel's geometry (or None-ness) changed — bench.py's full-geometry
# fallback rebuild used to pay the gather probe twice for exactly that
def _probe_key(kernel: str, *geom) -> tuple:
    return (kernel, jax.default_backend(), use_interpret()) + geom


_probe_cache: dict[tuple, bool] = {}


def _probed(key, probe) -> bool:
    hit = _probe_cache.get(key)
    if hit is not None:
        return hit
    ok = True
    try:
        probe()
    except Exception as e:  # Mosaic rejection / SMEM overflow / interp bug
        log.warning("pallas kernel probe %s unavailable on %s (falling "
                    "back to the XLA path): %r", key[0],
                    jax.default_backend(), repr(e)[:300])
        ok = False
    _probe_cache[key] = ok
    return ok


def _probe_gather(n_idx: int) -> bool:
    def probe():
        n = 64
        tab = jnp.arange(n * 4, dtype=U32)
        idx = (jnp.arange(n_idx, dtype=I32) * 7) % n
        got = gather_rows(tab, idx, 4)
        want = jnp.take(tab.reshape(n, 4), idx, axis=0).reshape(-1)
        if not bool(jnp.array_equal(got, want)):
            raise RuntimeError("gather_rows output != XLA gather")

    return _probed(_probe_key("gather", n_idx), probe)


def _probe_lock(m_lock: int, k_arb: int, hot_n: int = 0) -> bool:
    def probe():
        n = 64
        arb = jnp.zeros((n + 1,), U32)
        rows = (jnp.arange(m_lock, dtype=I32) * 3) % n
        act = jnp.ones((m_lock,), bool)
        arb2, grant = lock_arbitrate(arb, rows, act, jnp.asarray(2, U32),
                                     k_arb, hot_n=hot_n)
        jax.block_until_ready((arb2, grant))

    return _probed(_probe_key("lock", m_lock, k_arb, hot_n), probe)


def _probe_hot(n_idx: int, vw: int = 1) -> bool:
    """Compile + run the hot-set gather AND fused-install kernels at the
    caller's lane geometry with a tiny mirror, checking both against
    their XLA partitions. The mirror size does not change the eqn stream
    (it only scales the one bulk DMA), so lane geometry is the probe
    axis, like the plain gather."""
    def probe():
        n, h = 64, 16
        tab = jnp.arange(n * vw, dtype=U32)
        mirror = tab[:h * vw]
        idx = (jnp.arange(n_idx, dtype=I32) * 7) % n
        midx = jnp.where(idx < h, idx, -1)
        got = gather_rows_hot(tab, mirror, idx, midx, vw)
        want = _xla_hot_gather(tab, mirror, idx, midx, vw)
        if not bool(jnp.array_equal(got, want)):
            raise RuntimeError("gather_rows_hot output != XLA partition")
        # masked writers must be unique rows: mask the first min(n, k)
        # lanes, one row each, straddling the hot boundary
        lane = jnp.arange(n_idx, dtype=I32)
        uniq = (lane < n) & ((lane % 3) == 0)
        rows = jax.lax.rem(lane, I32(n))
        vals = jnp.arange(n_idx * vw, dtype=U32)
        hmidx = jnp.where(rows < h, rows, -1)
        t_p, m_p = scatter_rows_hot(jnp.array(tab), jnp.array(mirror),
                                    rows, hmidx, uniq, vals, vw)
        t_x, m_x = hot_scatter(jnp.array(tab), jnp.array(mirror), rows,
                               hmidx, uniq, vals, vw, use_pallas=False)
        if not (bool(jnp.array_equal(t_p, t_x))
                and bool(jnp.array_equal(m_p, m_x))):
            raise RuntimeError("scatter_rows_hot output != XLA partition")

    return _probed(_probe_key("hot", n_idx, vw), probe)


def _probe_scan(n_idx: int, lg: int, vw: int) -> bool:
    """Compile + run scan_rows at the caller's lane geometry over a tiny
    run and check it bit-for-bit against the XLA slab gather. Same
    degrade contract as the other probes."""
    def probe():
        n = max(lg + 8, 64)
        hi = jnp.arange(n, dtype=U32)
        lo = hi * U32(3)
        ver = hi + U32(100)
        val = jnp.arange(n * vw, dtype=U32)
        off = ((jnp.arange(n_idx, dtype=I32) * 7) % (n - lg))
        order = jnp.argsort(off)
        got = scan_rows(hi, lo, ver, val, off, order, lg, vw)
        want = _xla_scan_slab(hi, lo, ver, val, off, lg, vw)
        k = n_idx
        got = (got[0].reshape(k, lg), got[1].reshape(k, lg),
               got[2].reshape(k, lg), got[3].reshape(k, lg, vw))
        for g, w in zip(got, want):
            if not bool(jnp.array_equal(g, w)):
                raise RuntimeError("scan_rows output != XLA slab gather")

    return _probed(_probe_key("scan", n_idx, lg, vw), probe)


def scan_kernels_available(n_idx: int = 512, lg: int = 16,
                           vw: int = 4) -> bool:
    """Availability probe for the dintscan streaming slab kernel. Same
    degrade contract as kernels_available: False routes the scan path to
    the XLA slab gather (bandwidth cost, never correctness)."""
    return _probe_scan(n_idx, lg, vw)


def kernels_available(n_idx: int = 512, m_lock: int | None = 64,
                      k_arb: int = 18) -> bool:
    """Compile AND run the requested kernels at the caller's lane geometry
    (small tables — SMEM budget scales with lane count, not table bytes),
    checking the gather against jnp.take. Any exception or mismatch =>
    False. Each kernel's probe is cached independently per (backend,
    interpret, geometry): one small compile per kernel per runner
    configuration, once per process."""
    ok = _probe_gather(n_idx)
    if ok and m_lock is not None:
        ok = _probe_lock(m_lock, k_arb)
    return ok


def hot_kernels_available(n_idx: int = 512, vw: int = 1,
                          m_lock: int | None = None, k_arb: int = 18,
                          hot_n: int = 16) -> bool:
    """Availability probe for the hot-set kernel family (gather + fused
    install, plus the hot-prefix lock pass when m_lock is given). Same
    degrade contract as kernels_available."""
    ok = _probe_hot(n_idx, vw)
    if ok and m_lock is not None:
        ok = _probe_lock(m_lock, k_arb, hot_n=min(hot_n, 16))
    return ok


def resolve_use_pallas(explicit: bool | None = None, *, n_idx: int = 512,
                       m_lock: int | None = 64, k_arb: int = 18) -> bool:
    """Engine-builder entry point: explicit kwarg wins, else the
    DINT_USE_PALLAS env; when requested, the availability probe runs at the
    builder's real lane geometry and a Mosaic failure degrades to False
    (logged warning, never an exception)."""
    if explicit is None:
        explicit = env_use_pallas()
    if not explicit:
        return False
    return kernels_available(n_idx=n_idx, m_lock=m_lock, k_arb=k_arb)


# ------------------------------------------- round-12 megakernel probes


def _probe_lockv(n_val: int, n_read: int, m_lock: int, k_arb: int,
                 hot_n: int = 0) -> bool:
    """Compile + run lock_validate at the caller's lane geometry and check
    it against the COMPOSITION it replaces: lock_arbitrate (itself proven
    against the XLA chain) + the direct meta gathers/compares. Any
    mismatch or Mosaic rejection degrades to the unfused dispatches."""
    def probe():
        n = 64
        meta = ((jnp.arange(n, dtype=U32) * U32(7)) << 1) | U32(1)
        arb = jnp.zeros((n + 1,), U32)
        vidx = (jnp.arange(n_val, dtype=I32) * 5) % n
        vv1 = jnp.where(jnp.arange(n_val) % 3 == 0,
                        meta[vidx], meta[vidx] + U32(2))
        ridx = (jnp.arange(n_read, dtype=I32) * 7) % n
        rows = (jnp.arange(m_lock, dtype=I32) * 3) % n
        act = jnp.arange(m_lock) % 2 == 0
        t = jnp.asarray(2, U32)
        arb2, grant, vbad, rmeta = lock_validate(
            arb, meta, vidx, vv1, ridx, rows, act, t, k_arb, hot_n=hot_n)
        arb_u, grant_u = lock_arbitrate(jnp.array(arb), rows, act, t,
                                        k_arb, hot_n=hot_n)
        vbad_u = (meta[vidx] != vv1).astype(U32)
        rmeta_u = meta[ridx]
        if not (bool(jnp.array_equal(arb2, arb_u))
                and bool(jnp.array_equal(grant, grant_u))
                and bool(jnp.array_equal(vbad, vbad_u))
                and bool(jnp.array_equal(rmeta, rmeta_u))):
            raise RuntimeError("lock_validate output != unfused pair")

    return _probed(_probe_key("lockv", n_val, n_read, m_lock, k_arb,
                              hot_n), probe)


def _probe_gather_streams(geoms: tuple) -> bool:
    """geoms: tuple of (k, vw) per stream — the caller's real lane
    geometry (small tables; failure modes are construct-level)."""
    def probe():
        n = 64
        tabs, idxs = [], []
        for si, (k, vw) in enumerate(geoms):
            tabs.append(jnp.arange(n * vw, dtype=U32) * U32(si + 1))
            idxs.append((jnp.arange(k, dtype=I32) * (5 + si)) % n)
        vws = tuple(vw for _, vw in geoms)
        got = gather_streams(tuple(tabs), tuple(idxs), vws)
        want = _xla_gather_streams(tabs, idxs, vws)
        for g, w_ in zip(got, want):
            if not bool(jnp.array_equal(g, w_)):
                raise RuntimeError("gather_streams != XLA gathers")

    return _probed(_probe_key("gstreams", geoms), probe)


def _probe_scatter_streams(geoms: tuple) -> bool:
    """geoms: tuple of (k, vw) per stream. Masked-in rows are unique per
    stream (the engines' contract); masked lanes carry idx = -1."""
    def probe():
        n = 64
        tabs, idxs, vals = [], [], []
        for si, (k, vw) in enumerate(geoms):
            tabs.append(jnp.arange(n * vw, dtype=U32))
            lane = jnp.arange(k, dtype=I32)
            uniq = (lane < n) & (lane % (2 + si % 2) == 0)
            idxs.append(jnp.where(uniq, lane % n, -1))
            vals.append(jnp.arange(k * vw, dtype=U32) + U32(si))
        vws = tuple(vw for _, vw in geoms)
        got = scatter_streams(tuple(jnp.array(tb) for tb in tabs),
                              tuple(idxs), tuple(vals), vws)
        want = _xla_scatter_streams(tabs, idxs, vals, vws)
        for g, w_ in zip(got, want):
            if not bool(jnp.array_equal(g, w_)):
                raise RuntimeError("scatter_streams != XLA scatters")

    return _probed(_probe_key("sstreams", geoms), probe)


def fused_kernels_available(*, lockv=None, gathers=None,
                            scatters=None) -> bool:
    """Availability probe for the round-12 megakernels. ``lockv`` is
    (n_val, n_read, m_lock, k_arb, hot_n) or None; ``gathers`` /
    ``scatters`` are tuples of per-stream (k, vw) geometry or None. Same
    degrade contract and per-(backend, interpret, geometry) cache as
    kernels_available."""
    ok = True
    if lockv is not None:
        n_val, n_read, m_lock, k_arb, hot_n = lockv
        ok = _probe_lockv(n_val, n_read, m_lock, k_arb,
                          hot_n=min(hot_n, 16))
    if ok and gathers:
        ok = _probe_gather_streams(tuple(gathers))
    if ok and scatters:
        ok = _probe_scatter_streams(tuple(scatters))
    return ok


def resolve_use_fused(explicit: bool | None = None, *, lockv=None,
                      gathers=None, scatters=None) -> bool:
    """Engine-builder gate for the fused wave pairs: explicit kwarg wins,
    else the DINT_USE_FUSED env (default off — PERF.md round-12 decision
    rule); when requested, every megakernel the engine would dispatch is
    probed at its real geometry and any failure degrades to the unfused
    two-kernel/XLA path (logged warning, never an exception)."""
    if explicit is None:
        explicit = env_use_fused()
    if not explicit:
        return False
    return fused_kernels_available(lockv=lockv, gathers=gathers,
                                   scatters=scatters)
