"""Pallas/Mosaic DMA-ring kernels for batched random access over the
HBM-resident dense tables.

PERF.md "Where the remaining 2.5x lives": the dense engines' step cost is
pinned to a short serialized chain of random-access HBM ops (gathers /
scatter-max / gather-back) at ~0.6-0.9 ms per 16-32k random indices each —
XLA emits one device op per access with no way to overlap a chain that is
data-dependent. The reference collapses its per-request path into ONE fused
in-kernel pass at the NIC (tatp/ebpf/shard_kern.c); this module is the TPU
analogue: kernels that walk K random rows with a ring of NSLOTS outstanding
row DMAs (HBM latency hiding inside one kernel launch) instead of N chained
XLA gather ops.

Two kernel families, both production entry points behind `DINT_USE_PALLAS`
(env) / `use_pallas=` (engine kwarg):

* `gather_rows(tab, idx, vw)` — the wave-1/validate/magic reads: K rows of
  `vw` u32 words from a tight interleaved 1-D table (row r's words at
  [r*vw, (r+1)*vw), the engines/tatp_dense.DenseDB.val layout). Indices are
  prefetched to SMEM (PrefetchScalarGridSpec), the kernel keeps NSLOTS row
  DMAs in flight. Semantics == `tab[(idx[:,None]*vw + arange(vw)).ravel()]`
  bit for bit (pinned in tests/test_pallas_ops.py); indices MUST be
  in-bounds — the engines clamp masked lanes onto the sentinel row, and
  unlike XLA's clipping gather a Pallas DMA from an out-of-range offset is
  undefined.

* `lock_arbitrate(arb, rows, active, step, k_arb)` — the fused
  gather -> stamp-compare -> scatter-max lock path of engines/tatp_dense:
  ONE kernel pass replaces the 3-op chain (arb gather, masked scatter-max
  of `(step << k_arb) | (M-1-lane)`, winner gather-back). The kernel walks
  the M write-slot lanes in order doing a read-modify-write per lane:
  first ACTIVE lane on a free row wins the stamp, later lanes observe
  either the in-batch stamp (step field == step) or the previous step's
  stamp (== step-1) and reject. That sequential rule is EXACTLY the XLA
  scatter-max outcome (max of the packed stamps == smallest lane index,
  proof in tests/test_pallas_ops.py::test_lock_arbitrate_matches_xla): the
  arb array and grant vector are bit-identical to the XLA path. The arb
  input is donated (input_output_aliases), so the 0.6 GB array is updated
  in place. Hardware hazard discipline: reads run NSLOTS ahead of the
  RMW point, a write DMA is force-waited when its slot is reused (lag
  NSLOTS), and an SMEM window of the last 2*NSLOTS granted rows catches
  the only writes a prefetched read can miss — so in-batch duplicates
  arbitrate correctly even with the ring fully in flight.

Fallback contract (ISSUE 1): Mosaic rejection must DEGRADE, not crash —
round 3 already hit one such rejection class (scalar VMEM stores,
tools/profile_pallas.py). `resolve_use_pallas()` therefore compiles + runs
both kernels at the caller's real lane geometry (tiny tables — the failure
modes are construct/SMEM-budget level, not table-size level) and verifies
the gather against `jnp.take` before saying yes; any exception or mismatch
logs one warning and returns False, and every builder falls back to the
XLA path. On CPU every kernel runs under `interpret=True` (the Mosaic
pipeline never runs), which is what makes the whole layer tier-1-testable
without hardware.
"""
from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

I32 = jnp.int32
U32 = jnp.uint32

NSLOTS = 16      # outstanding row DMAs in the gather ring
RMW_SLOTS = 8    # outstanding read DMAs in the lock RMW ring
WIN = 2 * RMW_SLOTS   # recent-grant window: covers every write a read
#                       prefetched RMW_SLOTS ahead can race (see module doc)

log = logging.getLogger("dint_tpu.pallas")


def use_interpret() -> bool:
    """interpret=True off-TPU (CPU tier-1 tests, virtual meshes); the env
    override exists so hardware debugging can force either mode."""
    env = os.environ.get("DINT_PALLAS_INTERPRET")
    if env is not None:
        return env != "0"
    return jax.default_backend() != "tpu"


def env_use_pallas() -> bool:
    return os.environ.get("DINT_USE_PALLAS", "0") not in ("", "0")


# ------------------------------------------------------------- row gather


def _gather_kernel(vw: int, nslots: int, idx_ref, tab_ref, out_ref, sem):
    """idx_ref: SMEM [K] i32 row ids (prefetched); tab_ref: ANY [N*vw] u32;
    out_ref: ANY [K*vw] u32; sem: DMA sems [nslots]. Ring of nslots
    outstanding one-row HBM->HBM copies (validated against XLA's gather in
    interpret mode AND at K=256/N=10k geometry by tools/profile_pallas_hbm)."""
    k = idx_ref.shape[0]

    def copy(i):
        r = idx_ref[i]
        return pltpu.make_async_copy(
            tab_ref.at[pl.ds(r * vw, vw)],
            out_ref.at[pl.ds(i * vw, vw)],
            sem.at[jax.lax.rem(i, nslots)])

    def prime(i, _):
        copy(i).start()
        return 0

    jax.lax.fori_loop(0, min(nslots, k), prime, 0)

    def body(i, _):
        copy(i).wait()               # slot free again

        def issue(_):
            copy(i + nslots).start()
            return 0

        jax.lax.cond(i + nslots < k, issue, lambda _: 0, 0)
        return 0

    jax.lax.fori_loop(0, k, body, 0)


@functools.partial(jax.jit, static_argnums=(2, 3))
def gather_rows(tab, idx, vw: int = 1, interpret: bool | None = None):
    """K random rows of `vw` u32 words from the flat table `tab`
    (row r at [r*vw, (r+1)*vw)). Returns u32 [K*vw] — bit-identical to
    `tab[(idx[:,None]*vw + arange(vw)).reshape(-1)]` for in-bounds idx.
    `vw=1` covers the meta/arb/bal/stamp single-word gathers; callers that
    need one word at an offset inside wider rows pass pre-scaled flat word
    indices with vw=1 (e.g. the magic check's `rows*VW + 1`)."""
    if interpret is None:
        interpret = use_interpret()
    k = idx.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((NSLOTS,))],
    )
    return pl.pallas_call(
        functools.partial(_gather_kernel, vw, NSLOTS),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((k * vw,), U32),
        interpret=bool(interpret),
    )(idx.astype(I32), tab)


# ------------------------------------------------------- fused lock pass


def _arbitrate_kernel(k_arb: int, rows_ref, act_ref, t_ref, arb_in,
                      arb_out, grant_out, rbuf, wbuf, gbuf, win_row,
                      rsem, wsem, gsem):
    """Sequential first-lane-wins RMW over M lock lanes — the fused form of
    gather -> scatter-max -> gather-back (bit-equivalence argument in the
    module docstring). arb_in/arb_out alias (in-place update of the HBM
    array); grants accumulate in SMEM and leave in one trailing DMA."""
    m = rows_ref.shape[0]
    t = t_ref[0]

    def read(i):
        return pltpu.make_async_copy(
            arb_out.at[pl.ds(rows_ref[i], 1)],
            rbuf.at[pl.ds(jax.lax.rem(i, RMW_SLOTS), 1)],
            rsem.at[jax.lax.rem(i, RMW_SLOTS)])

    def write(i):
        return pltpu.make_async_copy(
            wbuf.at[pl.ds(jax.lax.rem(i, RMW_SLOTS), 1)],
            arb_out.at[pl.ds(rows_ref[i], 1)],
            wsem.at[jax.lax.rem(i, RMW_SLOTS)])

    def init_win(i, _):
        win_row[i] = I32(-1)
        return 0

    jax.lax.fori_loop(0, WIN, init_win, 0)

    def init_wbuf(i, _):
        # wbuf doubles as the per-slot write-in-flight flag: packed stamps
        # are never 0 (step >= 2), so nonzero == a write DMA to force-wait
        wbuf[i] = U32(0)
        return 0

    jax.lax.fori_loop(0, RMW_SLOTS, init_wbuf, 0)

    def prime(i, _):
        read(i).start()
        return 0

    jax.lax.fori_loop(0, min(RMW_SLOTS, m), prime, 0)

    def body(i, _):
        s = jax.lax.rem(i, RMW_SLOTS)
        # a write DMA still in flight on this slot belongs to lane
        # i - RMW_SLOTS: force-wait it so (a) wbuf[s] is reusable and
        # (b) every write older than the ring depth has LANDED before the
        # reads issued this iteration (the hazard-window invariant)
        @pl.when(jnp.logical_and(i >= RMW_SLOTS,
                                 wbuf[jax.lax.rem(i, RMW_SLOTS)] != U32(0)))
        def _():
            write(i - RMW_SLOTS).wait()

        wbuf[s] = U32(0)

        read(i).wait()
        old = rbuf[s]
        r = rows_ref[i]

        # writes a ring-prefetched read can have missed are exactly the
        # last WIN lanes' grants — scan the SMEM window for this row
        def scan(j, hit):
            return jnp.logical_or(hit, win_row[j] == r)

        taken_win = jax.lax.fori_loop(0, WIN, scan, False)

        stamp = old >> k_arb
        held = stamp == t - U32(1)              # stamped by the previous step
        taken = jnp.logical_or(stamp == t, taken_win)   # in-batch winner
        grant = jnp.logical_and(act_ref[i] != 0,
                                jnp.logical_not(jnp.logical_or(held, taken)))

        gbuf[i] = jax.lax.select(grant, U32(1), U32(0))
        win_row[jax.lax.rem(i, WIN)] = jax.lax.select(grant, r, I32(-1))

        @pl.when(grant)
        def _():
            inv = U32(m - 1) - i.astype(U32)    # == XLA's inverted slot
            wbuf[s] = (t << k_arb) | inv
            write(i).start()

        @pl.when(i + RMW_SLOTS < m)
        def _():
            read(i + RMW_SLOTS).start()

        return 0

    jax.lax.fori_loop(0, m, body, 0)

    def drain(j, _):
        i = m - min(RMW_SLOTS, m) + j

        @pl.when(wbuf[jax.lax.rem(i, RMW_SLOTS)] != U32(0))
        def _():
            write(i).wait()

        return 0

    jax.lax.fori_loop(0, min(RMW_SLOTS, m), drain, 0)

    out = pltpu.make_async_copy(gbuf, grant_out, gsem)
    out.start()
    out.wait()


@functools.partial(jax.jit, static_argnums=(4, 5))
def lock_arbitrate(arb, rows, active, step, k_arb: int,
                   interpret: bool | None = None):
    """Fused lock pass over the step-stamped arb array (engines/tatp_dense
    layout: `step << k_arb | inverted_slot`). Returns (arb', grant u32[M])
    bit-identical to the XLA chain

        old  = arb[rows]; held = (old >> k_arb) == step - 1
        cand = active & ~held
        arb' = arb.at[where(cand, rows, oob)].max((step << k_arb)
                                                  | (M-1 - lane), "drop")
        grant = cand & (arb'[rows] == packed)

    for in-bounds rows (masked lanes must carry active=False and a valid
    sentinel row id, exactly what pipe_step already does). The arb buffer
    is donated and updated in place."""
    if interpret is None:
        interpret = use_interpret()
    m = rows.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        scratch_shapes=[
            pltpu.SMEM((RMW_SLOTS,), U32),    # rbuf: in-flight read words
            pltpu.SMEM((RMW_SLOTS,), U32),    # wbuf: in-flight write words
            pltpu.SMEM((m,), U32),            # gbuf: per-lane grant bits
            pltpu.SMEM((WIN,), I32),          # win_row: recent granted rows
            pltpu.SemaphoreType.DMA((RMW_SLOTS,)),
            pltpu.SemaphoreType.DMA((RMW_SLOTS,)),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    arb2, grant = pl.pallas_call(
        functools.partial(_arbitrate_kernel, k_arb),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(arb.shape, U32),
                   jax.ShapeDtypeStruct((m,), U32)),
        # operand 3 (post scalar-prefetch) -> output 0: in-place arb update
        input_output_aliases={3: 0},
        interpret=bool(interpret),
    )(rows.astype(I32), active.astype(I32),
      step.reshape(1).astype(U32), arb)
    return arb2, grant


# ------------------------------------------------------ fallback plumbing

_probe_cache: dict[tuple, bool] = {}


def kernels_available(n_idx: int = 512, m_lock: int | None = 64,
                      k_arb: int = 18) -> bool:
    """Compile AND run both kernels at the caller's lane geometry (small
    tables — SMEM budget scales with lane count, not table bytes), checking
    the gather against jnp.take. Any exception or mismatch => False. Cached
    per (backend, interpret, geometry): the probe costs one small compile
    per runner configuration, once per process."""
    key = (jax.default_backend(), use_interpret(), n_idx, m_lock, k_arb)
    hit = _probe_cache.get(key)
    if hit is not None:
        return hit
    ok = True
    try:
        n = 64
        tab = jnp.arange(n * 4, dtype=U32)
        idx = (jnp.arange(n_idx, dtype=I32) * 7) % n
        got = gather_rows(tab, idx, 4)
        want = jnp.take(tab.reshape(n, 4), idx, axis=0).reshape(-1)
        if not bool(jnp.array_equal(got, want)):
            raise RuntimeError("gather_rows output != XLA gather")
        if m_lock is not None:
            arb = jnp.zeros((n + 1,), U32)
            rows = (jnp.arange(m_lock, dtype=I32) * 3) % n
            act = jnp.ones((m_lock,), bool)
            arb2, grant = lock_arbitrate(arb, rows, act,
                                         jnp.asarray(2, U32), k_arb)
            jax.block_until_ready((arb2, grant))
    except Exception as e:  # Mosaic rejection / SMEM overflow / interp bug
        log.warning("pallas kernels unavailable on %s (falling back to the "
                    "XLA gather path): %r", jax.default_backend(),
                    repr(e)[:300])
        ok = False
    _probe_cache[key] = ok
    return ok


def resolve_use_pallas(explicit: bool | None = None, *, n_idx: int = 512,
                       m_lock: int | None = 64, k_arb: int = 18) -> bool:
    """Engine-builder entry point: explicit kwarg wins, else the
    DINT_USE_PALLAS env; when requested, the availability probe runs at the
    builder's real lane geometry and a Mosaic failure degrades to False
    (logged warning, never an exception)."""
    if explicit is None:
        explicit = env_use_pallas()
    if not explicit:
        return False
    return kernels_available(n_idx=n_idx, m_lock=m_lock, k_arb=k_arb)
