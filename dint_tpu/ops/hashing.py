"""Jittable hashing for key -> bucket / lock-slot / bloom-bit mapping.

The reference uses fasthash64 for all three roles
(/root/reference/store/ebpf/utils.h:120-168: key->bucket, key->lock unit,
top-6-bits->bloom bit). We do not need the identical hash — servers own their
tables — but we do need the same *roles*. The device hash here is the full
fasthash64 finalizer structure re-expressed on (hi, lo) uint32 pairs so host
(numpy uint64) and device (uint32 pairs) agree bit-for-bit, which lets host
shims pre-compute shard routing while device kernels recompute bucket indices
locally.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import u64
from .u64 import U32

# fasthash64's mix constant (m) and seed, see store/ebpf/utils.h:120-168.
_M = 0x880355F21E6D1965
_SEED = 0xDEADBEEF


def _mix(hi, lo):
    """fasthash64 mix step: h ^= h >> 23; h *= 0x2127599bf4325c37; h ^= h >> 47."""
    s_hi, s_lo = u64.shr(hi, lo, 23)
    hi, lo = u64.xor(hi, lo, s_hi, s_lo)
    c_hi, c_lo = u64.const(0x2127599BF4325C37)
    hi, lo = u64.mul(hi, lo, c_hi, c_lo)
    s_hi, s_lo = u64.shr(hi, lo, 47)
    return u64.xor(hi, lo, s_hi, s_lo)


def hash64(key_hi, key_lo):
    """fasthash64 of a single u64 key (len=8, fixed seed), on uint32 pairs."""
    m_hi, m_lo = u64.const(_M)
    # h = seed ^ (8 * m)
    h0 = (_SEED ^ (8 * _M)) & ((1 << 64) - 1)
    h_hi, h_lo = u64.const(h0)
    h_hi = jnp.broadcast_to(h_hi, key_hi.shape).astype(U32)
    h_lo = jnp.broadcast_to(h_lo, key_lo.shape).astype(U32)
    v_hi, v_lo = _mix(key_hi.astype(U32), key_lo.astype(U32))
    h_hi, h_lo = u64.xor(h_hi, h_lo, v_hi, v_lo)
    h_hi, h_lo = u64.mul(h_hi, h_lo, m_hi, m_lo)
    return _mix(h_hi, h_lo)


def hash64_np(key: np.ndarray) -> np.ndarray:
    """Host-side fasthash64, bit-identical to hash64 (validated in tests)."""
    mask = np.uint64(0xFFFFFFFFFFFFFFFF)
    m = np.uint64(_M)
    c = np.uint64(0x2127599BF4325C37)

    def mix(h):
        h = h ^ (h >> np.uint64(23))
        with np.errstate(over="ignore"):
            h = (h * c) & mask
        return h ^ (h >> np.uint64(47))

    key = np.asarray(key, np.uint64)
    h = np.uint64((_SEED ^ (8 * _M)) & 0xFFFFFFFFFFFFFFFF)
    with np.errstate(over="ignore"):
        h = (h ^ mix(key)) * m & mask
    return mix(h)


def bucket_pair(key_hi, key_lo, n_buckets: int):
    """key -> two independent bucket choices (power-of-two-choices hashing).

    Uses disjoint bits of one fasthash64 evaluation: low word for the first
    choice, high word for the second — zero extra hash cost. Requires
    n_buckets <= 2^26 so the second choice stays clear of the bloom bits
    (which use the hash's top 6 bits).
    """
    assert n_buckets & (n_buckets - 1) == 0 and n_buckets <= (1 << 26)
    hi, lo = hash64(key_hi, key_lo)
    return ((lo & U32(n_buckets - 1)).astype(jnp.int32),
            (hi & U32(n_buckets - 1)).astype(jnp.int32))


def bucket_pair_np(key, n_buckets: int):
    assert n_buckets & (n_buckets - 1) == 0 and n_buckets <= (1 << 26)
    h = hash64_np(key)
    return ((h & np.uint64(n_buckets - 1)).astype(np.int64),
            ((h >> np.uint64(32)) & np.uint64(n_buckets - 1)).astype(np.int64))


def bucket(key_hi, key_lo, n_buckets: int):
    """key -> bucket index in [0, n_buckets); n_buckets must be a power of 2."""
    assert n_buckets & (n_buckets - 1) == 0, "n_buckets must be a power of two"
    _, lo = hash64(key_hi, key_lo)
    return (lo & U32(n_buckets - 1)).astype(jnp.int32)


def bucket_np(key, n_buckets: int):
    assert n_buckets & (n_buckets - 1) == 0
    return (hash64_np(key) & np.uint64(n_buckets - 1)).astype(np.int64)


def bloom_bit(key_hi, key_lo):
    """key -> bit position in a 64-bit per-bucket bloom filter.

    Mirrors the reference's use of the hash's top 6 bits
    (store/ebpf/store_kern.c:88-95).
    """
    hi, _ = hash64(key_hi, key_lo)
    return (hi >> U32(26)).astype(jnp.int32)  # top 6 bits of the 64-bit hash


def bloom_bit_np(key):
    return (hash64_np(key) >> np.uint64(58)).astype(np.int64)
