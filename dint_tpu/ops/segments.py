"""Sort + segmented-reduction primitives: the batch conflict-resolution core.

The reference serializes conflicting ops with per-entry CAS spinlocks and
RETRY-to-client (store/ebpf/store_kern.c:62-67, lock_2pl/caladan/server.cc:51-57).
On TPU there is no spinning: a step takes a batch of R requests, sorts them by
64-bit key (stable in arrival order), groups equal keys into segments, and
resolves each segment with *closed-form* segmented reductions that are
serial-equivalent to processing the segment's requests one at a time in
arrival order. Table updates then have exactly one writer per key (the
segment representative), so scatters are conflict-free and deterministic.

Everything here is shape-static and jit/vmap/shard_map friendly.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

I32 = jnp.int32


class SortedBatch(NamedTuple):
    """A batch sorted by (key_hi, key_lo, arrival order).

    All fields have shape [R]. ``perm`` maps sorted position -> original
    position; replies computed in sorted order are returned to original order
    with :func:`unsort`.
    """
    key_hi: jax.Array
    key_lo: jax.Array
    perm: jax.Array       # int32: original index of each sorted element
    head: jax.Array       # bool: first element of its key segment
    last: jax.Array       # bool: last element of its key segment
    head_pos: jax.Array   # int32: sorted position of this segment's head
    seg_id: jax.Array     # int32: dense segment id (0..n_segments-1)
    rank: jax.Array       # int32: position within segment (0 = earliest arrival)


def sort_batch(key_hi, key_lo) -> SortedBatch:
    """Sort a batch of 64-bit keys; arrival order (= index) breaks ties."""
    r = key_hi.shape[0]
    order = jnp.arange(r, dtype=I32)
    s_hi, s_lo, perm = jax.lax.sort((key_hi, key_lo, order), num_keys=3)
    head = jnp.concatenate(
        [jnp.ones((1,), bool),
         (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1])])
    last = jnp.concatenate([head[1:], jnp.ones((1,), bool)])
    idx = jnp.arange(r, dtype=I32)
    head_pos = jax.lax.cummax(jnp.where(head, idx, 0))
    seg_id = jnp.cumsum(head.astype(I32)) - 1
    rank = idx - head_pos
    return SortedBatch(s_hi, s_lo, perm, head, last, head_pos, seg_id, rank)


def at_head(sb: SortedBatch, x):
    """Broadcast each segment's head value of x to every element."""
    return x[sb.head_pos]


def seg_sum(sb: SortedBatch, x):
    """Per-element inclusive-total: sum of x over the element's whole segment."""
    r = sb.key_hi.shape[0]
    totals = jax.ops.segment_sum(x, sb.seg_id, num_segments=r)
    return totals[sb.seg_id]


def seg_cumsum_excl(sb: SortedBatch, x):
    """Segmented exclusive prefix sum (sum of x over earlier-arrival same-key)."""
    cs = jnp.cumsum(x, axis=0)
    incl = cs - (cs[sb.head_pos] - x[sb.head_pos])
    return incl - x


def seg_min_where(sb: SortedBatch, pred, x, default):
    """Per-segment min of x over elements where pred, broadcast to all.

    ``default`` is returned exactly for segments with no element satisfying
    pred (masked-out elements contribute the reduction identity, not default).
    """
    r = sb.key_hi.shape[0]
    ident = jnp.iinfo(x.dtype).max
    masked = jnp.where(pred, x, ident)
    mins = jax.ops.segment_min(masked, sb.seg_id, num_segments=r)[sb.seg_id]
    return jnp.where(seg_any(sb, pred), mins, default)


def seg_max_where(sb: SortedBatch, pred, x, default):
    """Per-segment max of x over elements where pred, broadcast to all.

    ``default`` is returned exactly for segments with no element satisfying pred.
    """
    r = sb.key_hi.shape[0]
    ident = jnp.iinfo(x.dtype).min
    masked = jnp.where(pred, x, ident)
    maxs = jax.ops.segment_max(masked, sb.seg_id, num_segments=r)[sb.seg_id]
    return jnp.where(seg_any(sb, pred), maxs, default)


def seg_any(sb: SortedBatch, pred):
    return seg_sum(sb, pred.astype(I32)) > 0


def first_rank_where(sb: SortedBatch, pred):
    """Rank (within segment) of the earliest element satisfying pred, or big."""
    big = jnp.int32(1 << 30)
    return seg_min_where(sb, pred, sb.rank, big)


def unsort(sb: SortedBatch, *xs):
    """Return arrays computed in sorted order to original batch order."""
    out = []
    for x in xs:
        o = jnp.zeros_like(x)
        out.append(o.at[sb.perm].set(x))
    return out[0] if len(out) == 1 else tuple(out)


def scatter_rows(table, row_idx, values, mask):
    """table[row_idx[i]] = values[i] where mask[i]; masked lanes are dropped.

    One-writer discipline is the caller's job (pass mask = segment-last).
    Masked lanes are routed out of range and dropped, so no sentinel row is
    needed in the table.
    """
    n = table.shape[0]
    safe_idx = jnp.where(mask, row_idx, n)  # out of range -> dropped
    return table.at[safe_idx].set(values, mode="drop")
