"""Workload generators: txn mixes, skewed key sampling, trace generation.

Ports of the reference's generators:
  * SmallBank mix 15/15/15/25/15/15 with 90% of txns on a 4% hot set
    (smallbank/caladan/smallbank.h:16-18,29-50,63-69)
  * TATP mix 35/35/10/2/14/2/2 with NURand subscriber ids, A=1048575
    (tatp/caladan/tatp.h:40-43,57-63)
  * 2PL/FaSST lock traces: 20k txns x 5-10 sorted locks, read-prop 0.8
    (lock_2pl/caladan/trace_init.sh:6-25)
"""
from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------- smallbank

SB_AMALGAMATE = 0
SB_BALANCE = 1
SB_DEPOSIT = 2
SB_SEND_PAYMENT = 3
SB_TRANSACT_SAVING = 4
SB_WRITE_CHECK = 5

# mix percentages, smallbank/caladan/smallbank.h:63-69
SB_MIX = np.array([15, 15, 15, 25, 15, 15], np.float64) / 100.0
SB_MAGIC = 0x5B5B
SB_HOT_FRAC = 0.04        # 960k of 24M accounts
SB_HOT_PROB = 0.9         # 90% of txns hit the hot set


def sb_sample_accounts(rng: np.random.Generator, n: int, n_accounts: int,
                       hot_frac: float = SB_HOT_FRAC,
                       hot_prob: float = SB_HOT_PROB):
    """Skewed account sampling: hot set = first hot_frac of the keyspace."""
    hot_n = max(int(n_accounts * hot_frac), 1)
    is_hot = rng.random(n) < hot_prob
    return np.where(is_hot,
                    rng.integers(0, hot_n, size=n),
                    rng.integers(0, n_accounts, size=n)).astype(np.int64)


def sb_make_txns(rng: np.random.Generator, n: int, n_accounts: int,
                 mix=SB_MIX, **skew):
    """Generate a cohort of SmallBank txns: (type [n], a1 [n], a2 [n])."""
    ttype = rng.choice(6, size=n, p=mix).astype(np.int32)
    a1 = sb_sample_accounts(rng, n, n_accounts, **skew)
    a2 = sb_sample_accounts(rng, n, n_accounts, **skew)
    # two-account txns need distinct accounts
    clash = (a1 == a2)
    a2 = np.where(clash, (a2 + 1) % n_accounts, a2)
    return ttype, a1, a2


# ----------------------------------------------------------------- zipf

ZIPF_THETA = 0.99          # YCSB default skew; DINT's store micro is Zipfian

_zipf_cdf_cache: dict[tuple[int, float], np.ndarray] = {}


def zipf_cdf(n_keys: int, theta: float = ZIPF_THETA) -> np.ndarray:
    """CDF of the Zipfian rank distribution P(k) ∝ 1/k^theta over ranks
    [1, n_keys], cached per (n_keys, theta) — one float64 cumsum, reused
    by every wave of a client."""
    key = (int(n_keys), float(theta))
    cdf = _zipf_cdf_cache.get(key)
    if cdf is None:
        w = 1.0 / np.power(np.arange(1, n_keys + 1, dtype=np.float64),
                           theta)
        cdf = np.cumsum(w / w.sum())
        _zipf_cdf_cache[key] = cdf
    return cdf


def zipf_keys(rng: np.random.Generator, n: int, n_keys: int,
              theta: float = ZIPF_THETA) -> np.ndarray:
    """Zipfian key ids in [1, n_keys] with rank == key id (no scramble):
    the hot head IS the smallest ids, i.e. the dintcache hot-set prefix —
    the same alignment the reference's skewed store benchmark exploits
    with its in-kernel cache (DINT NSDI'24 §store)."""
    u = rng.random(n)
    k = np.searchsorted(zipf_cdf(n_keys, theta), u, side="right") + 1
    return np.clip(k, 1, n_keys).astype(np.uint64)


# ----------------------------------------------------------- dintscan / YCSB-E


def scan_lengths(rng: np.random.Generator, n: int, max_len: int,
                 min_len: int = 1) -> np.ndarray:
    """Uniform scan lengths in [min_len, max_len] — YCSB-E's default
    request-distribution for scan length (uniform over [1, max]); the
    engine additionally clips to its static scan_max slab width."""
    assert 1 <= min_len <= max_len
    return rng.integers(min_len, max_len + 1, size=n).astype(np.uint32)


def zipf_scan_starts(rng: np.random.Generator, n: int, n_keys: int,
                     theta: float = ZIPF_THETA) -> np.ndarray:
    """YCSB-E start keys: zipfian over the keyspace, same rank == key-id
    alignment as zipf_keys — scans over the hot head of the ordered run
    touch the same rows repeatedly, the scan analogue of the point
    workloads' cacheable skew."""
    return zipf_keys(rng, n, n_keys, theta)


# YCSB-E: 95% scans / 5% inserts (upserts here); YCSB-B: 95/5 read/update.
YCSB_E_SCAN_FRAC = 0.95
YCSB_E_MAX_SCAN = 100


def ycsb_e_ops(rng: np.random.Generator, n: int, n_keys: int,
               scan_frac: float = YCSB_E_SCAN_FRAC,
               max_len: int = YCSB_E_MAX_SCAN,
               theta: float = ZIPF_THETA):
    """One YCSB-E-shaped cohort for the store engine: scans with zipfian
    start keys + uniform lengths, the remainder upsert writes.

    Returns (is_scan [n] bool, keys [n] u64, scan_len [n] u32 — zero on
    write lanes). Deterministic per rng state (tests/test_workloads.py).
    """
    is_scan = rng.random(n) < scan_frac
    starts = zipf_scan_starts(rng, n, n_keys, theta)
    writes = zipf_keys(rng, n, n_keys, theta)
    keys = np.where(is_scan, starts, writes)
    lens = np.where(is_scan, scan_lengths(rng, n, max_len), 0) \
        .astype(np.uint32)
    return is_scan, keys, lens


# ---------------------------------------------------------------- tatp

TATP_GET_SUBSCRIBER = 0
TATP_GET_ACCESS = 1
TATP_GET_NEW_DEST = 2
TATP_UPDATE_SUBSCRIBER = 3
TATP_UPDATE_LOCATION = 4
TATP_INSERT_CF = 5
TATP_DELETE_CF = 6

# mix percentages, tatp/caladan/tatp.h:57-63
TATP_MIX = np.array([35, 35, 10, 2, 14, 2, 2], np.float64) / 100.0
TATP_A = 1048575  # NURand A, tatp/caladan/tatp.h:40-43


def nurand(rng: np.random.Generator, a: int, n: int, size: int):
    """TATP non-uniform subscriber id in [1, n] (tatp/caladan/tatp.h:40-43)."""
    x = rng.integers(0, a + 1, size=size)
    y = rng.integers(1, n + 1, size=size)
    return ((x | y) % n) + 1


# ---------------------------------------------------------------- lock traces


def lock_trace(rng: np.random.Generator, n_txns: int = 20_000,
               locks_per_txn=(5, 10), key_range: int = 4800,
               read_prop: float = 0.8):
    """2PL/FaSST trace: per txn, 5-10 distinct keys in sorted order with
    per-key read/write mode (lock_2pl/caladan/trace_init.sh:6-25).

    Returns list of (keys [k] int64 ascending, is_read [k] bool).
    """
    txns = []
    for _ in range(n_txns):
        k = int(rng.integers(locks_per_txn[0], locks_per_txn[1] + 1))
        keys = np.sort(rng.choice(key_range, size=k, replace=False))
        is_read = rng.random(k) < read_prop
        txns.append((keys.astype(np.int64), is_read))
    return txns


# ------------------------------------------------------------- mix sampling


def mix_thresholds(mix) -> np.ndarray:
    """Cumulative u32 thresholds for sampling a txn type from one uniform
    u32 word via `searchsorted(thresh, word, side="right")` — the
    reference's proportion-filled workgen array
    (store/caladan/client_caladan.cc:56-66) in closed form. Normalizes
    `mix` (raw weights are fine, as with jax.random.choice) and clips the
    final threshold to 0xFFFFFFFF; clamp the searchsorted result to
    len(mix)-1 for the 2^-32 word == max edge."""
    m = np.asarray(mix, np.float64)
    c = np.cumsum(m / m.sum())
    return (c * 2.0**32).astype(np.uint64).clip(0, 0xFFFFFFFF) \
        .astype(np.uint32)
