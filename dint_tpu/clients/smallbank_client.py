"""SmallBank transaction coordinator: batched 2PC over 3 replicated shards.

Host-side, vectorized equivalent of the reference's client coordinator
threads (smallbank/caladan/client_ebpf_shard.cc): a cohort of W in-flight
txns advances through the commit pipeline in lockstep waves —

  lock+read (primary, X/S fused)  ->  compute  ->  CommitLog (all 3 shards)
  ->  CommitBck (2 backups)  ->  CommitPrim (primary)  ->  Release

(pipeline at client_ebpf_shard.cc:389-560; abort path = release granted
locks, :330-370). Where the reference runs 3 coordinator threads fanning
messages per shard (:287-325), this coordinator builds one batch per shard
per wave and runs the jitted shard engine on it.

Value layout: word0 = balance (int32, two's complement), word1 = magic
(parity with sb_sav_magic/sb_chk_magic asserts, smallbank/ebpf/smallbank.h:12-14).
"""
from __future__ import annotations

import dataclasses

import jax
import numpy as np

from .. import stats
from ..engines import smallbank
from ..engines.types import Batch, Op, Reply, make_batch
from . import workloads as wl

VW = 2
N_SHARDS = 3


@dataclasses.dataclass
class Stats(stats.TxnStats):
    aborted_lock: int = 0
    aborted_logic: int = 0   # insufficient funds etc.


def init_shards(n_accounts: int, init_balance: int = 1000):
    """All 3 replicas populated identically (reference populates every record
    on all 3 servers, smallbank/ebpf/shard_user.c:74-77)."""
    vals = np.zeros((n_accounts, VW), np.uint32)
    vals[:, 0] = np.uint32(init_balance)
    vals[:, 1] = wl.SB_MAGIC
    shards = []
    for _ in range(N_SHARDS):
        s = smallbank.create(n_accounts, val_words=VW)
        # fresh buffers per field: steps donate their state, so sav/chk
        # (and each replica) must not alias one device array — same rule
        # as tatp_client.populate_shards
        s = s.replace(
            sav=s.sav.replace(val=jax.numpy.asarray(vals.reshape(-1)),
                              ver=jax.numpy.ones(n_accounts,
                                                 jax.numpy.uint32)),
            chk=s.chk.replace(val=jax.numpy.asarray(vals.reshape(-1)),
                              ver=jax.numpy.ones(n_accounts,
                                                 jax.numpy.uint32)))
        shards.append(s)
    return shards


class Coordinator:
    def __init__(self, shards, width: int = 4096):
        self.shards = list(shards)
        self.width = width
        self._step = jax.jit(smallbank.step, donate_argnums=0)
        self.stats = Stats()

    # -------------------------------------------------------------- helpers

    def _run_wave(self, ops, tbls, accts, vals=None, vers=None):
        """Route ops to primary-by-account shards and run one step on each.

        All arrays are flat [M]; routing key = acct % 3 unless `shard_of`
        lanes are pre-assigned via the `shard` argument of _run_wave_explicit.
        """
        return self._run_wave_explicit(ops, tbls, accts, accts % N_SHARDS, vals, vers)

    def _run_wave_explicit(self, ops, tbls, accts, shard_of, vals=None, vers=None):
        m = len(ops)
        rt = np.zeros(m, np.int32)
        rv = np.zeros((m, VW), np.uint32)
        rver = np.zeros(m, np.uint32)
        if vals is None:
            vals = np.zeros((m, VW), np.uint32)
        if vers is None:
            vers = np.zeros(m, np.uint32)
        for s in range(N_SHARDS):
            all_idx = np.nonzero(shard_of == s)[0]
            # skewed waves SPILL across multiple batches instead of crashing
            # (the reference client likewise spreads over extra RTTs)
            for start in range(0, max(len(all_idx), 1), self.width):
                idx = all_idx[start:start + self.width]
                if len(idx) == 0:
                    continue
                batch = make_batch(ops[idx], accts[idx].astype(np.uint64),
                                   vals[idx], vers=vers[idx], tables=tbls[idx],
                                   width=self.width, val_words=VW)
                self.shards[s], rep = self._step(self.shards[s], batch)
                rt[idx] = np.asarray(rep.rtype)[: len(idx)]
                rv[idx] = np.asarray(rep.val)[: len(idx)]
                rver[idx] = np.asarray(rep.ver)[: len(idx)]
        return rt, rv, rver

    # -------------------------------------------------------------- cohort

    def run_cohort(self, ttype, a1, a2):
        """Drive one cohort of txns through the full pipeline. Returns Stats
        delta for this cohort."""
        w = len(ttype)
        self.stats.attempted += w
        SAV, CHK = smallbank.SAVINGS, smallbank.CHECKING
        X, S = Op.ACQ_X_READ, Op.ACQ_S_READ

        # --- build lock set (up to 3 per txn): (op, table, acct) ------------
        l_op = np.zeros((w, 3), np.int32)     # 0 = unused slot
        l_tb = np.zeros((w, 3), np.int32)
        l_ac = np.zeros((w, 3), np.int64)

        def setlock(mask, slot, op, tb, ac):
            l_op[mask, slot] = op
            l_tb[mask, slot] = tb
            l_ac[mask, slot] = ac[mask]

        t = ttype
        m = t == wl.SB_AMALGAMATE
        setlock(m, 0, X, SAV, a1); setlock(m, 1, X, CHK, a1); setlock(m, 2, X, CHK, a2)
        m = t == wl.SB_BALANCE
        setlock(m, 0, S, SAV, a1); setlock(m, 1, S, CHK, a1)
        m = t == wl.SB_DEPOSIT
        setlock(m, 0, X, CHK, a1)
        m = t == wl.SB_SEND_PAYMENT
        setlock(m, 0, X, CHK, a1); setlock(m, 1, X, CHK, a2)
        m = t == wl.SB_TRANSACT_SAVING
        setlock(m, 0, X, SAV, a1)
        m = t == wl.SB_WRITE_CHECK
        setlock(m, 0, S, SAV, a1); setlock(m, 1, X, CHK, a1)

        # --- wave 1: fused lock+read at primaries ---------------------------
        used = l_op.reshape(-1) != 0
        f_op = l_op.reshape(-1)[used]
        f_tb = l_tb.reshape(-1)[used]
        f_ac = l_ac.reshape(-1)[used]
        txn_of = np.repeat(np.arange(w), 3)[used]
        rt, rv, rver = self._run_wave(f_op, f_tb, f_ac)

        granted = rt == Reply.GRANT
        # magic-byte parity check (reference asserts on every read,
        # smallbank/caladan/client_ebpf_shard.cc:375-380)
        assert (rv[granted, 1] == wl.SB_MAGIC).all(), "magic corrupted"
        txn_rejected = np.zeros(w, bool)
        np.logical_or.at(txn_rejected, txn_of, ~granted)
        self.stats.aborted_lock += int(txn_rejected.sum())

        # balances read (int32), keyed back to (txn, slot)
        bal = np.zeros((w, 3), np.int64)
        ver = np.zeros((w, 3), np.uint32)
        flat_bal = rv[:, 0].astype(np.uint32).view(np.int32).astype(np.int64)
        slot_of = np.tile(np.arange(3), w)[used]
        bal[txn_of, slot_of] = flat_bal
        ver[txn_of, slot_of] = rver

        # --- compute phase (vectorized per txn type) ------------------------
        alive = ~txn_rejected
        amt = np.full(w, 5, np.int64)  # fixed amounts keep invariants simple
        nw_val = np.zeros((w, 3), np.int64)    # new balances per lock slot
        nw_do = np.zeros((w, 3), bool)         # which slots get written
        logic_abort = np.zeros(w, bool)

        m = alive & (t == wl.SB_AMALGAMATE)
        nw_val[m, 0] = 0
        nw_val[m, 1] = 0
        nw_val[m, 2] = bal[m, 2] + bal[m, 0] + bal[m, 1]
        nw_do[m] = True
        m = alive & (t == wl.SB_DEPOSIT)
        nw_val[m, 0] = bal[m, 0] + amt[m]
        nw_do[m, 0] = True
        m = alive & (t == wl.SB_SEND_PAYMENT)
        insufficient = bal[:, 0] < amt
        logic_abort |= m & insufficient
        ok = m & ~insufficient
        nw_val[ok, 0] = bal[ok, 0] - amt[ok]
        nw_val[ok, 1] = bal[ok, 1] + amt[ok]
        nw_do[ok, 0] = True
        nw_do[ok, 1] = True
        m = alive & (t == wl.SB_TRANSACT_SAVING)
        neg = (bal[:, 0] + amt) < 0
        logic_abort |= m & neg
        ok = m & ~neg
        nw_val[ok, 0] = bal[ok, 0] + amt[ok]
        nw_do[ok, 0] = True
        m = alive & (t == wl.SB_WRITE_CHECK)
        overdraw = (bal[:, 0] + bal[:, 1]) < amt
        nw_val[m, 1] = bal[m, 1] - amt[m] - np.where(overdraw[m], 1, 0)
        nw_do[m, 1] = True

        self.stats.aborted_logic += int(logic_abort.sum())
        commit = alive & ~logic_abort & (t != wl.SB_BALANCE)

        # --- commit waves: log x3, bck x2, prim x1 --------------------------
        wmask = nw_do & commit[:, None]
        c_txn, c_slot = np.nonzero(wmask)
        c_tb = l_tb[c_txn, c_slot]
        c_ac = l_ac[c_txn, c_slot]
        c_val = np.zeros((len(c_txn), VW), np.uint32)
        c_val[:, 0] = nw_val[c_txn, c_slot].astype(np.int32).view(np.uint32)
        c_val[:, 1] = wl.SB_MAGIC
        c_ver = ver[c_txn, c_slot] + 1
        ops_log = np.full(len(c_txn), Op.COMMIT_LOG, np.int32)
        prim = (c_ac % N_SHARDS).astype(np.int64)
        # CommitLog to ALL 3 shards (client_ebpf_shard.cc:389-560)
        for s in range(N_SHARDS):
            self._run_wave_explicit(ops_log, c_tb, c_ac,
                                    np.full(len(c_txn), s), c_val, c_ver)
        ops_bck = np.full(len(c_txn), Op.COMMIT_BCK, np.int32)
        for off in (1, 2):
            self._run_wave_explicit(ops_bck, c_tb, c_ac,
                                    (prim + off) % N_SHARDS, c_val, c_ver)
        ops_prim = np.full(len(c_txn), Op.COMMIT_PRIM, np.int32)
        self._run_wave_explicit(ops_prim, c_tb, c_ac, prim, c_val, c_ver)

        # --- release all granted locks (aborts release too) -----------------
        rel_mask = granted
        r_op = np.where(f_op[rel_mask] == X, Op.REL_X, Op.REL_S).astype(np.int32)
        rt_rel, _, _ = self._run_wave(r_op, f_tb[rel_mask], f_ac[rel_mask])
        assert (rt_rel == Reply.ACK).all()

        self.stats.committed += int((commit | (alive & (t == wl.SB_BALANCE) & ~logic_abort)).sum())
        return self.stats


def total_balance(shards) -> int:
    """Sum of all balances on a replica (invariant checking)."""
    s = shards[0]
    sav = np.asarray(s.sav.val)[0::s.sav.val_words] \
        .view(np.int32).astype(np.int64).sum()
    chk = np.asarray(s.chk.val)[0::s.chk.val_words] \
        .view(np.int32).astype(np.int64).sum()
    return int(sav + chk)
