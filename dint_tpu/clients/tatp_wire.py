"""TATP coordinator over the WIRE: full transactions against 3 UDP servers.

The reference's TATP numbers are inherently over-network: a Caladan client
coordinator fans each transaction's per-shard message batches to 3 shard
servers over UDP (tatp/caladan/client_ebpf_shard.cc:636-677, servers
tatp/udp/server_shard.cc). This module is that exact topology in-process:
three `EnginePump`s (one populated TATP shard each — real separate
"servers" with their own UDP sockets, RX batching, and jitted certify
steps) and a `WireCoordinator` that reuses the host coordinator's wave
logic (clients/tatp_client.Coordinator) with `_run_wave` rerouted through
`ShimClient` datagrams in the reference's 55-byte wire format.

Every phase of every transaction — read+lock, validate, CommitLog x3,
CommitBck x2, CommitPrim, abort — crosses the wire as datagrams, so this
is the full request -> batch -> certify -> reply path for the flagship
workload (the round-3 verdict's missing demonstration), measured by
`exp.py`'s `tatp_wire_txn` point.

Wire-format constraint: the MSG55 `ord` field is u8, so ONE SOCKET
matches at most 256 in-flight datagrams per server; waves are chunked to
that bound and replies are reordered by the echoed `ord` (UDP may
reorder). To hold more than 256 in flight per shard — the reference keeps
hundreds outstanding via per-uthread resend loops
(client_ebpf_shard.cc:643-677) — each shard gets `n_socks` independent
sockets and chunks are pipelined concurrently across them, each socket
being its own u8-ord space. Unanswered lanes retry on their own socket;
after `max_tries` the lane is marked Reply.TIMEOUT and its txn is counted
in the ab_timeout taxonomy (the reference resends forever, so loss shows
up as latency; a capped budget must yield a number + timeout count, not a
voided run). Replies whose echoed ord/key/table do not match a STILL
OUTSTANDING request are late stragglers from a timed-out try and are
discarded (the reference's `assert(msg.key == key)` pattern).
Shared-with-reference hazard: a retried OCC_LOCK whose original GRANT
reply was lost re-sends against its own server-side lock and reads
REJECT — a UDP request/reply protocol cannot distinguish that from a
true conflict (the reference's NetHandshake loop has the same exposure);
on loopback, reply loss is effectively nil.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

from ..engines import tatp
from ..engines.types import Op, Reply
from ..shim import TATP, EnginePump, ShimClient
from ..shim.native import VAL_SIZE
from . import tatp_client as tc

N_SHARDS = tc.N_SHARDS
_CHUNK = 256        # u8 ord field: max matchable datagrams per exchange

# engine op -> wire request code (inverse of shim.wire.TATP.req_map)
_OP2WIRE = np.full(64, 255, np.uint8)
for _w, _op in enumerate(TATP.req_map):
    if _op != Op.NOP:
        _OP2WIRE[_op] = _w

# (wire request, wire reply) -> engine Reply code (inverse of rep_map)
_WIRE2REP = np.full((64, 256), Reply.NONE, np.int32)
for _w in range(TATP.rep_map.shape[0]):
    for _r in range(TATP.rep_map.shape[1]):
        _code = TATP.rep_map[_w, _r]
        if _code >= 0:
            _WIRE2REP[_w, _code] = _r


@contextlib.contextmanager
def serve_shards(n_subscribers: int, width: int = 1024, val_words: int = 10,
                 flush_us: int = 500, seed: int = 0, **kw):
    """Start 3 shard servers (reference topology: one process per shard,
    tatp/udp/server_shard.cc) on loopback UDP; yields their ports."""
    shards, _ = tc.populate_shards(np.random.default_rng(seed),
                                   n_subscribers, val_words=val_words, **kw)
    pumps = []
    try:
        for s in shards:
            pumps.append(EnginePump(TATP, tatp.step, s, width=width,
                                    flush_us=flush_us,
                                    val_words=val_words).start())
        yield [p.port for p in pumps]
    finally:
        for p in pumps:
            p.close()


class WireCoordinator(tc.Coordinator):
    """tc.Coordinator with every wave crossing the wire to 3 UDP servers.

    Inherits the whole transaction state machine (run_cohort: mix/NURand
    generation, wave structure, abort taxonomy, magic asserts) — only the
    transport differs, exactly like the reference's client_udp vs
    client_caladan variants share their txn logic."""

    def __init__(self, ports, n_subscribers: int, width: int = 4096,
                 val_words: int = 10, host: str = "127.0.0.1",
                 timeout_ms: int = 10_000, max_tries: int = 8,
                 n_socks: int = 4):
        # no local shards: state lives behind the sockets
        self.p = n_subscribers
        self.width = width
        self.vw = val_words
        self.attr = False
        self.stats = tc.Stats()
        self.timeout_ms = timeout_ms
        self.max_tries = max_tries
        # n_socks sockets per shard: each is an independent u8-ord space,
        # so a shard holds up to n_socks*256 requests in flight
        self.clients = [[ShimClient(host, p) for _ in range(n_socks)]
                        for p in ports]

    def close(self):
        for socks in self.clients:
            for c in socks:
                c.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def _exchange_chunk(self, client, chunk, lo, ops, tbls, keys, vals,
                        vers, rt, rv, rver, wire_req) -> int:
        """One <=256-lane chunk on one socket: send, reorder replies by
        echoed ord, retry unanswered lanes. Writes this chunk's disjoint
        slice of rt/rv/rver; returns the number of timed-out lanes."""
        pend = chunk
        for _ in range(self.max_tries):
            if len(pend) == 0:
                return 0
            wv = np.zeros((len(pend), VAL_SIZE), np.uint8)
            wv[:, : self.vw * 4] = np.ascontiguousarray(
                vals[pend, : self.vw].astype(np.uint32)
            ).view(np.uint8).reshape(len(pend), -1)
            # ords are STABLE across retries (lane's position within
            # its original chunk), so a straggler reply from an
            # earlier try always maps back to the lane that sent it —
            # per-try renumbering could mis-credit a same-key lane
            r = client.exchange(
                wire_req[pend], keys[pend].astype(np.uint64),
                tables=tbls[pend].astype(np.uint8), vals=wv,
                vers=vers[pend].astype(np.uint32),
                ords=(pend - lo).astype(np.uint8),
                timeout_ms=self.timeout_ms)
            n = r["n"]
            if n == 0:
                continue
            # ord -> lane within the chunk; sanity-check the echoed
            # key/table against what that lane sent (the reference's
            # assert(msg.key == key) pattern) and drop mismatches
            ordv = r["ord"][:n].astype(np.int64)
            ok = ordv < len(chunk)
            cand = chunk[np.where(ok, ordv, 0)]
            ok &= (r["key"][:n] == keys[cand].astype(np.uint64)) \
                & (r["table"][:n] == tbls[cand].astype(np.uint8))
            # a straggler whose lane was ALREADY answered by a later try
            # must not clobber the recorded reply (for OCC_LOCK it could
            # arbitrarily flip GRANT/REJECT attribution)
            ok &= np.isin(cand, pend)
            idx = cand[ok]
            if len(idx):
                sel_n = np.nonzero(ok)[0]
                rt[idx] = _WIRE2REP[wire_req[idx], r["type"][:n][sel_n]]
                got_v = r["val"][:n][sel_n].reshape(len(sel_n), VAL_SIZE)
                rv[idx] = np.ascontiguousarray(
                    got_v[:, : self.vw * 4]).view(np.uint32).reshape(
                        len(sel_n), -1)
                rver[idx] = r["ver"][:n][sel_n]
                pend = pend[~np.isin(pend, idx)]
        # resend budget exhausted: surface as a counted timeout, not a
        # voided run (run_cohort classifies these txns as ab_timeout)
        rt[pend] = Reply.TIMEOUT
        return len(pend)

    def _exchange_shard(self, s, ops, tbls, keys, vals, vers):
        """One shard's lanes: chunk to the u8-ord bound and pipeline the
        chunks concurrently across the shard's sockets (each socket = one
        independent ord space; exchange blocks in C with the GIL released,
        so the chunks genuinely overlap on the wire)."""
        m = len(ops)
        rt = np.full(m, Reply.NONE, np.int32)
        rv = np.zeros((m, self.vw), np.uint32)
        rver = np.zeros(m, np.uint32)
        wire_req = _OP2WIRE[ops]
        chunks = [(lo, np.arange(lo, min(lo + _CHUNK, m)))
                  for lo in range(0, m, _CHUNK)]
        socks = self.clients[s]
        timeouts = [0] * len(socks)

        def worker(wi):
            # socket wi serves chunks wi, wi+n_socks, ... serially; other
            # sockets run their share concurrently
            for ci in range(wi, len(chunks), len(socks)):
                lo, chunk = chunks[ci]
                timeouts[wi] += self._exchange_chunk(
                    socks[wi], chunk, lo, ops, tbls, keys, vals, vers,
                    rt, rv, rver, wire_req)

        if len(chunks) == 1:
            worker(0)
        else:
            ts = [threading.Thread(target=worker, args=(wi,))
                  for wi in range(min(len(socks), len(chunks)))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        return rt, rv, rver, sum(timeouts)

    def _run_wave(self, ops, tbls, keys, shard_of=None, vals=None,
                  vers=None):
        m = len(ops)
        rt = np.full(m, Reply.NONE, np.int32)
        rv = np.zeros((m, self.vw), np.uint32)
        rver = np.zeros(m, np.uint32)
        if vals is None:
            vals = np.zeros((m, self.vw), np.uint32)
        if vers is None:
            vers = np.zeros(m, np.uint32)
        if shard_of is None:
            shard_of = keys % N_SHARDS
        active = ops != Op.NOP
        # concurrent per-shard fan-out, like the reference's 3 coordinator
        # threads (client_ebpf_shard.cc:636-677): exchange blocks in C
        # (GIL released), so the 3 server round-trips overlap
        errs = []
        tmo = [0] * N_SHARDS

        def one(s, idx):
            try:
                srt, srv, srver, stmo = self._exchange_shard(
                    s, ops[idx], tbls[idx], keys[idx], vals[idx],
                    vers[idx])
                rt[idx] = srt
                rv[idx] = srv
                rver[idx] = srver
                tmo[s] = stmo
            except Exception as e:      # surfaced after join
                errs.append(e)

        threads = []
        for s in range(N_SHARDS):
            idx = np.nonzero(active & (shard_of == s))[0]
            if len(idx):
                threads.append(threading.Thread(target=one, args=(s, idx)))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        self.stats.timeout_lanes += sum(tmo)  # after join: single-threaded
        return rt, rv, rver
