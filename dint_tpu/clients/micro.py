"""Microbenchmark clients: store, lock_2pl, lock_fasst, log_server.

Host-side, wave-batched equivalents of the reference's four microbenchmark
clients (SURVEY.md §2.1 #6/#9/#12/#14):

  * StoreClient — TATP-subset GET/SET mix over a populated KV table;
    contention (50R/50W) and parallel (100R) mixes per
    /root/reference/store/caladan/client_caladan.cc:56-66, with the
    magic-byte check every read asserts (:160).
  * Lock2PLClient — trace replay of sorted-key lock txns under no-wait 2PL:
    all of a txn's locks go out in one wave (the reference's coordinator
    likewise batches per-shard, smallbank/caladan/client_ebpf_shard.cc:287-325);
    on any REJECT the txn releases what it got and restarts
    (lock_2pl/caladan/client.cc:205-219).
  * FasstClient — FaSST OCC replay: read-set READ_VER + write-set LOCK in
    one wave (lock_fasst/caladan/client.cc:246-277), validation re-read
    (:199-215), then COMMIT_VER or ABORT (:216-236).
  * LogClient — replication-log append replay
    (log_server/caladan/client.cc:147-167).

Latency accounting: a wave's wall time is attributed to every request in
it; txn latency = time from first wave of the attempt chain to commit —
same definition as the reference's microtime() around the whole txn
(tatp/caladan/client_ebpf_shard.cc:1617-1652).
"""
from __future__ import annotations

import time

import jax
import numpy as np

from ..engines import fasst, lock2pl, logsrv, store
from ..engines.types import Op, Reply, make_batch
from ..stats import Recorder
from ..tables import kv, locks, log as logring
from . import workloads as wl

STORE_MAGIC = 0x55AA


def make_store_table(n_keys: int, *, n_buckets: int | None = None,
                     val_words: int = 10) -> kv.KVTable:
    """Populated store table: keys 1..n, val word0 = key, word1 = magic
    (store/caladan/client_caladan.cc:160). Shared by the in-process store
    client and the wire-path bench so both serve identical contents."""
    if n_buckets is None:
        n_buckets = max(16, 1 << int(np.ceil(np.log2(n_keys / 2))))
    keys = np.arange(1, n_keys + 1, dtype=np.uint64)
    vals = np.zeros((n_keys, val_words), np.uint32)
    vals[:, 0] = keys.astype(np.uint32)
    vals[:, 1] = STORE_MAGIC
    return kv.populate(kv.create(n_buckets, val_words=val_words), keys, vals)


class _SteppedClient:
    """Shared plumbing: jitted donated step + timed wave runner."""

    def __init__(self, state, step_fn, width: int, val_words: int):
        self.state = state
        self.width = width
        self.vw = val_words
        self._step = jax.jit(step_fn, donate_argnums=0)
        self.rec = Recorder()

    def _wave(self, ops, keys, vals=None, vers=None, tables=None):
        """Run one batch; returns (rtype, rval, rver, wall_s)."""
        m = len(ops)
        assert m <= self.width, f"wave of {m} exceeds width {self.width}"
        batch = make_batch(ops, keys, vals, vers=vers, tables=tables,
                           width=self.width, val_words=self.vw)
        t0 = time.monotonic()
        self.state, rep = self._step(self.state, batch)
        rt = np.asarray(rep.rtype)[:m]
        dt = time.monotonic() - t0
        self.rec.device_busy_s += dt
        return rt, np.asarray(rep.val)[:m], np.asarray(rep.ver)[:m], dt


class StoreClient(_SteppedClient):
    """GET/SET mix over a pre-populated table. ``read_frac=1.0`` is the
    reference's 'parallel' benchmark, 0.5 the 'contention' one
    (store/caladan/client_caladan.cc:56-66).

    ``key_dist="zipfian"`` draws keys from the YCSB-style Zipfian whose
    hot head is the smallest key ids (workloads.zipf_keys) — DINT's
    skewed store benchmark. ``use_hotset`` (None = DINT_USE_HOTSET env)
    attaches the dintcache mirror for the first ``hot_frac`` of the
    keyspace and threads it through every step (write-through,
    bit-identical replies); DINT_USE_PALLAS additionally serves the
    partition with the VMEM hot kernels.

    ``use_scan`` (None = DINT_USE_SCAN env) attaches the dintscan ordered
    run and lets waves carry Op.SCAN lanes (``scan_frac`` of the mix,
    zipfian start keys + uniform lengths clipped to ``scan_max``).
    In-doubt/retry semantics match the GET path's populated-key asserts:
    a scan must answer VAL, except when the run's overlay went stale —
    then the engine replies RETRY, the client rebuilds the run at the
    next maintenance point and RE-SENDS exactly those lanes, and the
    retry must answer VAL (the capped-resend discipline of the TIMEOUT
    sentinel, here with the rebuild as the recovery action)."""

    def __init__(self, table: kv.KVTable, n_keys: int, width: int = 4096,
                 val_words: int = 10, read_frac: float = 0.5,
                 key_dist: str = "uniform", zipf_theta: float = wl.ZIPF_THETA,
                 hot_frac: float | None = None, use_hotset=None,
                 use_pallas=None, use_scan=None, scan_frac: float = 0.0,
                 scan_max: int = 8, max_scan_len: int | None = None,
                 delta_cap: int = 64, rebuild_every: int = 8):
        from ..ops import pallas_gather as pg
        from ..tables import run as run_mod

        assert key_dist in ("uniform", "zipfian")
        self.use_hotset = pg.resolve_use_hotset(use_hotset)
        self.use_scan = pg.resolve_use_scan(use_scan)
        self.scan_max = int(scan_max)
        self.scan_frac = float(scan_frac) if self.use_scan else 0.0
        self.max_scan_len = int(max_scan_len or scan_max)
        self.delta_cap = int(delta_cap)
        self.rebuild_every = max(int(rebuild_every), 1)
        self._waves_since_rebuild = 0
        up = pg.resolve_use_pallas(use_pallas, n_idx=width, m_lock=None)
        run0 = None
        if self.use_scan:
            run0 = run_mod.from_table(table, delta_cap=int(delta_cap))
            if up and not pg.scan_kernels_available(
                    n_idx=width, lg=self.scan_max + run0.delta_cap,
                    vw=val_words):
                up = False
        hot = None
        if self.use_hotset:
            if up and not pg.hot_kernels_available(n_idx=width):
                up = False
            frac = 0.04 if hot_frac is None else float(hot_frac)
            # mirror ids are key_lo < hot_n; keys are 1-based, so cover
            # keys 1..frac*n with hot_n = frac*n + 1
            hot_n = min(int(n_keys * frac) + 1, n_keys + 1)
            hot = store.attach_hot(table, hot_n)

        smax = self.scan_max
        if self.use_scan and self.use_hotset:
            def step_fn(state, batch, _up=up):
                t, h, rn = state
                t, rep, h, rn, srep = store.step(
                    t, batch, hot=h, use_pallas=_up, run=rn, scan_max=smax)
                return (t, h, rn), (rep, srep)

            state = (table, hot, run0)
        elif self.use_scan:
            def step_fn(state, batch, _up=up):
                t, rn = state
                t, rep, rn, srep = store.step(
                    t, batch, use_pallas=_up, run=rn, scan_max=smax)
                return (t, rn), (rep, srep)

            state = (table, run0)
        elif self.use_hotset:
            def step_fn(state, batch, _up=up):
                t, h = state
                t, rep, h = store.step(t, batch, hot=h, use_pallas=_up)
                return (t, h), rep

            state = (table, hot)
        else:
            state, step_fn = table, store.step
        super().__init__(state, step_fn, width, val_words)
        if self.use_scan:
            def _rebuild(state):
                t, rest = state[0], state[1:]
                return (t,) + rest[:-1] + (store.rebuild_run(t, rest[-1]),)

            self._rebuild = jax.jit(_rebuild, donate_argnums=0)
        self.n_keys = n_keys
        self.read_frac = read_frac
        self.key_dist = key_dist
        self.zipf_theta = zipf_theta
        self.use_pallas = up

    @classmethod
    def populated(cls, n_keys: int, *, n_buckets: int | None = None,
                  val_words: int = 10, **kw):
        table = make_store_table(n_keys, n_buckets=n_buckets,
                                 val_words=val_words)
        return cls(table, n_keys, val_words=val_words, **kw)

    def _keys(self, rng: np.random.Generator, n: int) -> np.ndarray:
        if self.key_dist == "zipfian":
            return wl.zipf_keys(rng, n, self.n_keys, self.zipf_theta)
        return rng.integers(1, self.n_keys + 1, size=n).astype(np.uint64)

    def _wave_scan(self, ops, keys, vals, vers):
        """Like _wave, for the scan-threaded step whose reply is
        (Replies, ScanReplies)."""
        m = len(ops)
        assert m <= self.width, f"wave of {m} exceeds width {self.width}"
        batch = make_batch(ops, keys, vals, vers=vers,
                           width=self.width, val_words=self.vw)
        t0 = time.monotonic()
        self.state, (rep, srep) = self._step(self.state, batch)
        rt = np.asarray(rep.rtype)[:m]
        dt = time.monotonic() - t0
        self.rec.device_busy_s += dt
        return rt, np.asarray(rep.val)[:m], np.asarray(rep.ver)[:m], srep, dt

    def run_wave(self, rng: np.random.Generator, n: int | None = None):
        n = n or self.width
        keys = self._keys(rng, n)
        is_scan = rng.random(n) < self.scan_frac
        is_read = ~is_scan & (rng.random(n) < self.read_frac)
        ops = np.where(is_scan, Op.SCAN,
                       np.where(is_read, Op.GET, Op.SET)).astype(np.int32)
        vals = np.zeros((n, self.vw), np.uint32)
        vals[:, 0] = rng.integers(0, 1 << 30, size=n).astype(np.uint32)
        vals[:, 1] = STORE_MAGIC
        srep = None
        if self.use_scan:
            vers = np.where(is_scan,
                            wl.scan_lengths(rng, n, self.max_scan_len),
                            0).astype(np.uint32)
            rt, rv, rr, srep, dt = self._wave_scan(ops, keys, vals, vers)
        else:
            assert not is_scan.any(), "scan lanes need use_scan=True"
            rt, rv, rr, dt = self._wave(ops, keys, vals)
        got = rt[is_read] == Reply.VAL
        assert got.all(), "populated key missing"
        assert (rv[is_read][:, 1] == STORE_MAGIC).all(), "magic corrupted"
        ok = int((rt == Reply.VAL).sum() + (rt == Reply.ACK).sum())
        if self.use_scan:
            sc = rt[is_scan]
            assert np.isin(sc, (Reply.VAL, Reply.RETRY)).all(), \
                "scan lane answered neither VAL nor RETRY"
            cnt = np.asarray(srep.count)[:n]
            okv = is_scan & (rt == Reply.VAL)
            assert (cnt[okv] <= np.minimum(vers[okv], self.scan_max)).all()
            assert (rr[okv] == cnt[okv]).all()
            retry = is_scan & (rt == Reply.RETRY)
            if retry.any():
                # in-doubt recovery, GET-path style: the stale overlay is
                # the known cause, so rebuild NOW and re-send exactly the
                # RETRY lanes — the retry must answer VAL
                self.state = self._rebuild(self.state)
                self._waves_since_rebuild = 0
                rt2, _, rr2, srep2, _ = self._wave_scan(
                    ops[retry], keys[retry], vals[retry], vers[retry])
                assert (rt2 == Reply.VAL).all(), "scan retry still in doubt"
                ok += int(len(rt2))
        self.rec.record(n, ok, np.full(n, dt * 1e6))
        self._waves_since_rebuild += 1
        if self.use_scan and self._waves_since_rebuild >= self.rebuild_every:
            # drain-boundary maintenance: fold the overlay into the run
            self.state = self._rebuild(self.state)
            self._waves_since_rebuild = 0
        return ok


class LogClient(_SteppedClient):
    """Append replay (log_server/caladan/client.cc:147-167)."""

    def __init__(self, ring: logring.LogRing | None = None, width: int = 4096,
                 val_words: int = 10, lanes: int = 16, capacity: int = 1 << 20):
        ring = ring or logring.create(lanes, capacity, val_words)
        super().__init__(ring, logsrv.step, width, val_words)

    def run_wave(self, rng: np.random.Generator, n: int | None = None):
        n = n or self.width
        keys = rng.integers(0, 10_000, size=n).astype(np.uint64)
        vals = rng.integers(0, 1 << 16, size=(n, self.vw)).astype(np.uint32)
        vers = rng.integers(1, 1 << 20, size=n).astype(np.uint32)
        ops = np.full(n, Op.LOG_APPEND, np.int32)
        rt, _, _, dt = self._wave(ops, keys, vals, vers)
        assert (rt == Reply.ACK).all()
        self.rec.record(n, n, np.full(n, dt * 1e6))
        return n


class _TraceCohort:
    """A rotating cohort of in-flight trace txns with retry-on-abort and
    per-txn start timestamps."""

    def __init__(self, trace, cohort: int, rng: np.random.Generator):
        self.trace = trace
        self.rng = rng
        self.next_txn = cohort
        idx = np.arange(cohort) % len(trace)
        self.cur = [trace[i] for i in idx]
        self.t_start = np.full(cohort, time.monotonic())

    def refill(self, done_mask: np.ndarray):
        """Replace completed txns with fresh ones; returns their latencies."""
        now = time.monotonic()
        lats = (now - self.t_start[done_mask]) * 1e6
        for i in np.nonzero(done_mask)[0]:
            self.cur[i] = self.trace[self.next_txn % len(self.trace)]
            self.next_txn += 1
            self.t_start[i] = now
        return lats


def _flatten(cohort_txns):
    """[(keys, is_read)] -> flat arrays + txn index per lane."""
    keys = np.concatenate([t[0] for t in cohort_txns])
    is_read = np.concatenate([t[1] for t in cohort_txns])
    txn_of = np.repeat(np.arange(len(cohort_txns)),
                       [len(t[0]) for t in cohort_txns])
    return keys.astype(np.uint64), is_read, txn_of


class Lock2PLClient(_SteppedClient):
    """No-wait 2PL trace replay (lock_2pl/caladan/client.cc:167-219)."""

    def __init__(self, trace, n_slots: int = 1 << 16, cohort: int = 512,
                 width: int = 8192, val_words: int = 1,
                 rng: np.random.Generator | None = None):
        super().__init__(locks.create_sx(n_slots), lock2pl.step, width, val_words)
        self.co = _TraceCohort(trace, cohort, rng or np.random.default_rng(1))

    def run_round(self):
        """One acquire wave + one release wave over the whole cohort."""
        keys, is_read, txn_of = _flatten(self.co.cur)
        w = len(self.co.cur)
        ops = np.where(is_read, Op.ACQ_S, Op.ACQ_X).astype(np.int32)
        rt, _, _, _ = self._wave(ops, keys)

        granted_lane = rt == Reply.GRANT
        rejected_txn = np.zeros(w, bool)
        np.logical_or.at(rejected_txn, txn_of, rt == Reply.REJECT)
        committed = ~rejected_txn

        # release everything granted (commit: txn end; abort: rollback,
        # client.cc:205-219) — one wave
        rel_mask = granted_lane
        if rel_mask.any():
            rel_ops = np.where(is_read[rel_mask], Op.REL_S, Op.REL_X).astype(np.int32)
            rrt, _, _, _ = self._wave(rel_ops, keys[rel_mask])
            assert (rrt == Reply.ACK).all()

        lats = self.co.refill(committed)  # aborted txns retry, keeping t_start
        self.rec.record(int(w), int(committed.sum()), lats)
        return int(committed.sum())


class FasstClient(_SteppedClient):
    """FaSST OCC trace replay (lock_fasst/caladan/client.cc:184-280).

    ``attribute=True`` runs the lock-attribution server variant
    (engines.fasst.step_attr == tatp/ebpf/lock_kern.c) and keeps the
    reference's conflict-attribution counters lock_cnt /
    reject_sharing_cnt / reject_same_key_cnt
    (tatp/caladan/client_lock.cc:62-64,768-771) in ``rec.extra``."""

    def __init__(self, trace, n_slots: int = 1 << 16, cohort: int = 512,
                 width: int = 8192, val_words: int = 1,
                 rng: np.random.Generator | None = None,
                 attribute: bool = False):
        state = (locks.create_occ_attr(n_slots) if attribute
                 else locks.create_occ(n_slots))
        step_fn = fasst.step_attr if attribute else fasst.step
        super().__init__(state, step_fn, width, val_words)
        self.co = _TraceCohort(trace, cohort, rng or np.random.default_rng(2))
        self.attribute = attribute
        if attribute:
            self.rec.extra.update(lock_cnt=0, reject_sharing_cnt=0,
                                  reject_same_key_cnt=0)

    def run_round(self):
        keys, is_read, txn_of = _flatten(self.co.cur)
        w = len(self.co.cur)

        # wave 1: read-set versions + write-set locks (client.cc:246-277)
        ops = np.where(is_read, Op.READ_VER, Op.LOCK).astype(np.int32)
        rt, _, rver, _ = self._wave(ops, keys)
        lock_lane = ~is_read
        got_lock = rt == Reply.GRANT
        if self.attribute:
            self.rec.extra["lock_cnt"] += int(lock_lane.sum())
            self.rec.extra["reject_sharing_cnt"] += int(
                (lock_lane & (rt == Reply.REJECT)).sum())
            self.rec.extra["reject_same_key_cnt"] += int(
                (lock_lane & (rt == Reply.REJECT_SAME_KEY)).sum())
        lock_fail = np.zeros(w, bool)
        np.logical_or.at(lock_fail, txn_of, lock_lane & ~got_lock)

        # wave 2: validate = re-read read-set; abort if the version changed OR
        # the slot is now locked by a concurrent writer (:199-215 — the
        # reference checks both; the lock bit rides reply val word 0)
        val_fail = np.zeros(w, bool)
        rd = is_read
        if rd.any():
            v_ops = np.full(int(rd.sum()), Op.READ_VER, np.int32)
            vrt, vval, vver, _ = self._wave(v_ops, keys[rd])
            assert (vrt == Reply.VAL).all()
            bad = (vver != rver[rd]) | (vval[:, 0] != 0)
            np.logical_or.at(val_fail, txn_of[rd], bad)
        aborted = lock_fail | val_fail
        committed = ~aborted

        # wave 3: COMMIT_VER for committed txns' write-sets; ABORT for
        # granted locks of aborted txns (:216-236)
        fin_lane = lock_lane & got_lock
        if fin_lane.any():
            fl_ops = np.where(aborted[txn_of[fin_lane]], Op.ABORT,
                              Op.COMMIT_VER).astype(np.int32)
            frt, _, _, _ = self._wave(fl_ops, keys[fin_lane])
            assert (frt == Reply.ACK).all()

        lats = self.co.refill(committed)
        self.rec.record(int(w), int(committed.sum()), lats)
        return int(committed.sum())
