from . import workloads  # noqa: F401
