"""TATP transaction coordinator: batched OCC 2PC over 3 replicated shards.

Host-side, vectorized equivalent of the reference's TATP client
(tatp/caladan/client_ebpf_shard.cc): a cohort of W in-flight txns advances in
waves through the FaSST-style OCC pipeline —

  wave 1: READ read-set + LOCK write-set (fused, one step)   (:608-677)
  wave 2: validate = re-READ, compare versions               (:688-768)
  wave 3: CommitLog -> all 3 shards                          (:779-810)
  wave 4: Commit/Insert/DeleteBck -> 2 backup shards         (:812-860)
  wave 5: Commit/Insert/DeletePrim -> primary (installs + releases lock) (:862-900)
  abort:  ABORT (unlock) each granted lock                   (:681-703)

Txn mix 35/35/10/2/14/2/2 with NURand subscriber ids
(tatp/caladan/tatp.h:40-43,57-63). Routing: shard = key % 3 per key
(tatp/caladan/client_ebpf_shard.cc:636-641).

Value layout: word0 = payload, word1 = magic (parity with
tatp_sub_msc_location_magic etc., tatp/caladan/tatp.h:67-72).
"""
from __future__ import annotations

import dataclasses

import jax
import numpy as np

from .. import stats
from ..engines import tatp
from ..engines.types import Op, Reply, make_batch
from ..tables import kv, locks
from . import workloads as wl

N_SHARDS = 3
MAGIC = 0x7A79


@dataclasses.dataclass
class Stats(stats.TxnStats):
    aborted_lock: int = 0      # write-set lock rejected
    aborted_validate: int = 0  # read-set version changed
    aborted_missing: int = 0   # required row absent / insert-exists
    aborted_timeout: int = 0   # wire transport exhausted resends (incl.
    timeout_lanes: int = 0     # in-doubt commits); lanes = raw datagram count
    # lock-attribution counters (live when the shards were built with
    # tatp.create(attr_locks=True); the reference's instrumented client
    # keeps the same three, tatp/caladan/client_lock.cc:62-64,768-771)
    lock_cnt: int = 0              # OCC_LOCK lanes issued
    reject_sharing_cnt: int = 0    # rejected by a DIFFERENT key (hash share)
    reject_same_key_cnt: int = 0   # rejected by the SAME key (true conflict)


def populate_shards(rng: np.random.Generator, n_subscribers: int,
                    val_words: int = 10, **kw):
    """Build 3 identical replicas (reference populate:
    tatp/caladan/client_ebpf_shard.cc:96-341). Returns (shards, cf_keys)."""
    p1 = n_subscribers + 1
    s_ids = np.arange(1, p1)

    def mkvals(n, payload):
        v = np.zeros((n, val_words), np.uint32)
        v[:, 0] = payload
        v[:, 1] = MAGIC
        return v

    # ai/sf: each subscriber has a random subset of types 1..4 (>=1)
    ai_present = rng.random((p1, 4)) < 0.625   # avg 2.5 of 4
    sf_present = rng.random((p1, 4)) < 0.625
    ai_present[0] = sf_present[0] = False
    ai_present[1:][ai_present[1:].sum(1) == 0, 0] = True
    sf_present[1:][sf_present[1:].sum(1) == 0, 0] = True

    # cf: 25% of present sf rows have each start_time
    cf_keys = []
    sfi, sft = np.nonzero(sf_present)
    for st in (0, 8, 16):
        mask = rng.random(len(sfi)) < 0.25
        cf_keys.append(tatp.cf_key(sfi[mask], sft[mask] + 1, st))
    cf_keys = np.unique(np.concatenate(cf_keys)).astype(np.uint64)

    shard0 = tatp.create(n_subscribers, val_words=val_words, **kw)
    del s_ids
    sub_vals = mkvals(p1, np.arange(p1))
    ver1 = np.ones(p1, np.uint32)
    ver1[0] = 0
    ai_vals = mkvals(4 * p1, np.arange(4 * p1))
    sf_vals = mkvals(4 * p1, np.arange(4 * p1))
    ai_ver = np.where(ai_present.reshape(-1), 1, 0).astype(np.uint32)
    sf_ver = np.where(sf_present.reshape(-1), 1, 0).astype(np.uint32)

    cf_table = kv.populate(shard0.cf, cf_keys,
                           mkvals(len(cf_keys), cf_keys.astype(np.uint32)))
    shards = []
    for _ in range(N_SHARDS):
        s = tatp.create(n_subscribers, val_words=val_words, **kw)
        s = s.replace(
            sub=s.sub.replace(val=jax.numpy.asarray(sub_vals.reshape(-1)),
                              ver=jax.numpy.asarray(ver1)),
            sec=s.sec.replace(val=jax.numpy.asarray(sub_vals.reshape(-1)),
                              ver=jax.numpy.asarray(ver1)),
            ai=s.ai.replace(val=jax.numpy.asarray(ai_vals.reshape(-1)),
                            ver=jax.numpy.asarray(ai_ver)),
            sf=s.sf.replace(val=jax.numpy.asarray(sf_vals.reshape(-1)),
                            ver=jax.numpy.asarray(sf_ver)),
            cf=cf_table,
        )
        # independent buffers per replica: steps donate their state, so
        # replicas must not share device arrays
        s = jax.tree.map(jax.numpy.array, s)
        shards.append(s)
    return shards, cf_keys


class Coordinator:
    def __init__(self, shards, n_subscribers: int, width: int = 4096,
                 val_words: int = 10):
        self.shards = list(shards)
        self.p = n_subscribers
        self.width = width
        self.vw = val_words
        # donate the shard state: steps update tables in place in HBM instead
        # of copying the full state every call
        self._step = jax.jit(tatp.step, donate_argnums=0)
        # attribution counters are only meaningful against attr shards
        # (tatp.create(attr_locks=True)): the plain server cannot
        # distinguish CF same-key conflicts from hash sharing
        self.attr = isinstance(self.shards[0].cf_lock, locks.OCCAttrTable)
        self.stats = Stats()

    def _run_wave(self, ops, tbls, keys, shard_of=None, vals=None, vers=None):
        m = len(ops)
        rt = np.zeros(m, np.int32)
        rv = np.zeros((m, self.vw), np.uint32)
        rver = np.zeros(m, np.uint32)
        if vals is None:
            vals = np.zeros((m, self.vw), np.uint32)
        if vers is None:
            vers = np.zeros(m, np.uint32)
        if shard_of is None:
            shard_of = keys % N_SHARDS
        for s in range(N_SHARDS):
            idx = np.nonzero(shard_of == s)[0]
            if len(idx) == 0:
                continue
            assert len(idx) <= self.width
            batch = make_batch(ops[idx], keys[idx].astype(np.uint64), vals[idx],
                               vers=vers[idx], tables=tbls[idx],
                               width=self.width, val_words=self.vw)
            self.shards[s], rep = self._step(self.shards[s], batch)
            rt[idx] = np.asarray(rep.rtype)[: len(idx)]
            rv[idx] = np.asarray(rep.val)[: len(idx)]
            rver[idx] = np.asarray(rep.ver)[: len(idx)]
        return rt, rv, rver

    def run_cohort(self, rng: np.random.Generator, w: int):
        st = self.stats
        st.attempted += w
        T = tatp
        ttype = rng.choice(7, size=w, p=wl.TATP_MIX).astype(np.int32)
        s_id = wl.nurand(rng, wl.TATP_A, self.p, w).astype(np.int64)
        xtype = rng.integers(1, 5, size=w)          # ai_type / sf_type
        stime = rng.choice([0, 8, 16], size=w)

        # ---- wave 1: up to 4 lanes per txn: (op, table, key) ---------------
        K = 4
        ops = np.zeros((w, K), np.int32)
        tbl = np.zeros((w, K), np.int32)
        key = np.zeros((w, K), np.int64)
        # lane roles per txn for later phases
        sf_idx = s_id * 4 + (xtype - 1)
        ai_idx = s_id * 4 + (xtype - 1)
        cfk = tatp.cf_key(s_id, xtype, stime)

        def put(mask, lane, op, tb, k):
            ops[mask, lane] = op
            tbl[mask, lane] = tb
            key[mask, lane] = k[mask]

        t = ttype
        m = t == wl.TATP_GET_SUBSCRIBER
        put(m, 0, Op.OCC_READ, T.SUBSCRIBER, s_id)
        m = t == wl.TATP_GET_ACCESS
        put(m, 0, Op.OCC_READ, T.ACCESS_INFO, ai_idx)
        m = t == wl.TATP_GET_NEW_DEST
        put(m, 0, Op.OCC_READ, T.SPECIAL_FACILITY, sf_idx)
        put(m, 1, Op.OCC_READ, T.CALL_FORWARDING, cfk)
        m = t == wl.TATP_UPDATE_SUBSCRIBER
        put(m, 0, Op.OCC_READ, T.SUBSCRIBER, s_id)
        put(m, 1, Op.OCC_READ, T.SPECIAL_FACILITY, sf_idx)
        put(m, 2, Op.OCC_LOCK, T.SUBSCRIBER, s_id)
        put(m, 3, Op.OCC_LOCK, T.SPECIAL_FACILITY, sf_idx)
        m = t == wl.TATP_UPDATE_LOCATION
        put(m, 0, Op.OCC_READ, T.SEC_SUBSCRIBER, s_id)
        put(m, 1, Op.OCC_READ, T.SUBSCRIBER, s_id)
        put(m, 2, Op.OCC_LOCK, T.SUBSCRIBER, s_id)
        m = t == wl.TATP_INSERT_CF
        put(m, 0, Op.OCC_READ, T.SPECIAL_FACILITY, sf_idx)
        put(m, 1, Op.OCC_READ, T.CALL_FORWARDING, cfk)
        put(m, 2, Op.OCC_LOCK, T.CALL_FORWARDING, cfk)
        m = t == wl.TATP_DELETE_CF
        put(m, 0, Op.OCC_READ, T.CALL_FORWARDING, cfk)
        put(m, 1, Op.OCC_LOCK, T.CALL_FORWARDING, cfk)

        used = ops.reshape(-1) != 0
        txn_of = np.repeat(np.arange(w), K)[used]
        lane_of = np.tile(np.arange(K), w)[used]
        rt, rv, rver = self._run_wave(ops.reshape(-1)[used],
                                      tbl.reshape(-1)[used],
                                      key.reshape(-1)[used])
        # magic parity check on every VAL (tatp client asserts,
        # client_ebpf_shard.cc:879-884)
        isval = rt == Reply.VAL
        assert (rv[isval, 1] == MAGIC).all(), "magic corrupted"

        r_rt = np.full((w, K), -1, np.int32)
        r_ver = np.zeros((w, K), np.uint32)
        r_rt[txn_of, lane_of] = rt
        r_ver[txn_of, lane_of] = rver

        is_lock_lane = ops == Op.OCC_LOCK
        is_rej = (r_rt == Reply.REJECT) | (r_rt == Reply.REJECT_SAME_KEY)
        lock_rejected = (is_rej & is_lock_lane).any(1)
        if self.attr:
            # attribution: dense-table row locks are EXACT, so their
            # rejects are same-key conflicts by construction; only the
            # hash-conflated CF lock table can reject on slot sharing,
            # which the attr server distinguishes via REJECT_SAME_KEY
            # (lock_kern.c:292-298)
            is_dense_lane = tbl < T.CALL_FORWARDING
            st.lock_cnt += int(is_lock_lane.sum())
            st.reject_sharing_cnt += int(
                (is_lock_lane & ~is_dense_lane
                 & (r_rt == Reply.REJECT)).sum())
            st.reject_same_key_cnt += int(
                (is_lock_lane & ((r_rt == Reply.REJECT_SAME_KEY)
                                 | (is_dense_lane
                                    & (r_rt == Reply.REJECT)))).sum())

        # required-row checks
        missing = np.zeros(w, bool)
        m = t == wl.TATP_GET_ACCESS       # ai row must exist (cc:583-587)
        missing |= m & (r_rt[:, 0] != Reply.VAL)
        m = t == wl.TATP_GET_NEW_DEST     # sf AND cf must exist
        missing |= m & ((r_rt[:, 0] != Reply.VAL)
                        | (r_rt[:, 1] != Reply.VAL))
        m = t == wl.TATP_UPDATE_SUBSCRIBER
        missing |= m & ((r_rt[:, 0] != Reply.VAL) | (r_rt[:, 1] != Reply.VAL))
        m = t == wl.TATP_UPDATE_LOCATION
        missing |= m & ((r_rt[:, 0] != Reply.VAL) | (r_rt[:, 1] != Reply.VAL))
        m = t == wl.TATP_INSERT_CF        # sf must exist; cf must NOT exist
        missing |= m & ((r_rt[:, 0] != Reply.VAL) | (r_rt[:, 1] == Reply.VAL))
        m = t == wl.TATP_DELETE_CF        # cf must exist
        missing |= m & (r_rt[:, 0] != Reply.VAL)

        is_ro = (t == wl.TATP_GET_SUBSCRIBER) | (t == wl.TATP_GET_ACCESS) | \
                (t == wl.TATP_GET_NEW_DEST)
        rw = ~is_ro
        # transport timeouts (wire coordinator only; the in-process path
        # never produces Reply.TIMEOUT) classify FIRST: a lane whose reply
        # never arrived says nothing about locks or row existence
        timed = (r_rt == Reply.TIMEOUT).any(1)
        st.aborted_timeout += int(timed.sum())
        alive = rw & ~lock_rejected & ~missing & ~timed
        st.aborted_lock += int((rw & lock_rejected & ~timed).sum())
        st.aborted_missing += int(
            (missing & ~(rw & lock_rejected) & ~timed).sum())

        # ---- wave 2: validate read-set (re-read, compare versions) ---------
        # read-set lanes are the OCC_READ lanes of alive RW txns
        is_read_lane = (ops == Op.OCC_READ) & alive[:, None]
        v_used = is_read_lane.reshape(-1)
        if v_used.any():
            v_txn = np.repeat(np.arange(w), K)[v_used]
            v_lane = np.tile(np.arange(K), w)[v_used]
            vt, _, vver = self._run_wave(
                np.full(v_used.sum(), Op.OCC_READ, np.int32),
                tbl.reshape(-1)[v_used], key.reshape(-1)[v_used])
            changed = np.zeros(w, bool)
            # a row that vanished or changed version fails validation
            bad = (vver != r_ver[v_txn, v_lane]) | \
                  ((vt != Reply.VAL) & (r_rt[v_txn, v_lane] == Reply.VAL))
            # for InsertCF the cf read was NOT_EXIST; it must STILL not exist
            np.logical_or.at(changed, v_txn, bad)
            tmo2 = np.zeros(w, bool)   # lost validate reply != version change
            np.logical_or.at(tmo2, v_txn, vt == Reply.TIMEOUT)
            st.aborted_timeout += int((alive & tmo2).sum())
            st.aborted_validate += int((alive & changed & ~tmo2).sum())
            alive = alive & ~changed & ~tmo2

        # ---- commit waves --------------------------------------------------
        # write-set per txn: (table, key, newval, kind) kind: 0=commit 1=insert 2=delete
        wr_ops = {0: Op.COMMIT_PRIM, 1: Op.INSERT_PRIM, 2: Op.DELETE_PRIM}
        bk_ops = {0: Op.COMMIT_BCK, 1: Op.INSERT_BCK, 2: Op.DELETE_BCK}
        w_tb, w_key, w_kind, w_txn = [], [], [], []

        def add_writes(mask, tb, k, kind):
            idxs = np.nonzero(mask)[0]
            w_tb.append(np.full(len(idxs), tb))
            w_key.append(k[idxs])
            w_kind.append(np.full(len(idxs), kind))
            w_txn.append(idxs)

        add_writes(alive & (t == wl.TATP_UPDATE_SUBSCRIBER), T.SUBSCRIBER, s_id, 0)
        add_writes(alive & (t == wl.TATP_UPDATE_SUBSCRIBER), T.SPECIAL_FACILITY, sf_idx, 0)
        add_writes(alive & (t == wl.TATP_UPDATE_LOCATION), T.SUBSCRIBER, s_id, 0)
        add_writes(alive & (t == wl.TATP_INSERT_CF), T.CALL_FORWARDING, cfk, 1)
        add_writes(alive & (t == wl.TATP_DELETE_CF), T.CALL_FORWARDING, cfk, 2)

        if w_tb and sum(len(x) for x in w_tb):
            c_tb = np.concatenate(w_tb).astype(np.int32)
            c_key = np.concatenate(w_key).astype(np.int64)
            c_kind = np.concatenate(w_kind).astype(np.int32)
            c_txn = np.concatenate(w_txn)
            n_l = len(c_tb)
            c_val = np.zeros((n_l, self.vw), np.uint32)
            c_val[:, 0] = rng.integers(0, 1 << 16, size=n_l).astype(np.uint32)
            c_val[:, 1] = MAGIC
            prim = (c_key % N_SHARDS).astype(np.int64)

            # every commit wave's replies are captured: a TIMEOUT lane in
            # CommitLog x3 / CommitBck x2 puts its WHOLE txn in doubt
            # exactly like a lost CommitPrim reply below — previously a
            # timed-out log/backup lane silently committed with no
            # guaranteed WAL entry or replica copy. The reference treats
            # the log acks as the commit point and resends until acked
            # (client_ebpf_shard.cc:779-860); with a capped resend budget
            # the honest outcome is abort + aborted_timeout, and later
            # waves SKIP the doubted txn's lanes so an unlogged write is
            # never installed.
            lane_to = np.zeros(n_l, bool)

            def wave(opv, shard_of):
                """Run one commit wave over the lanes of still-clean txns;
                fold TIMEOUT lanes into the doubt set (txn granularity).
                Returns the replies of the lanes actually sent."""
                d = np.zeros(w, bool)
                np.logical_or.at(d, c_txn, lane_to)
                idx = np.nonzero(~d[c_txn])[0]
                rtw = np.zeros(0, np.int32)
                if len(idx):
                    rtw, _, _ = self._run_wave(opv[idx], c_tb[idx],
                                               c_key[idx], shard_of[idx],
                                               c_val[idx])
                    lane_to[idx] |= rtw == Reply.TIMEOUT
                return rtw

            log_op = np.where(c_kind == 2, Op.DELETE_LOG,
                              Op.COMMIT_LOG).astype(np.int32)
            for s in range(N_SHARDS):
                wave(log_op, np.full(n_l, s))
            bck = np.vectorize(bk_ops.get)(c_kind).astype(np.int32)
            for off in (1, 2):
                wave(bck, (prim + off) % N_SHARDS)
            pr = np.vectorize(wr_ops.get)(c_kind).astype(np.int32)
            prt = wave(pr, prim)
            assert (prt != Reply.NONE).all()

            in_doubt = np.zeros(w, bool)
            np.logical_or.at(in_doubt, c_txn, lane_to)
            st.aborted_timeout += int((alive & in_doubt).sum())
            alive = alive & ~in_doubt

        # ---- abort unlocks: granted locks of dead RW txns -------------------
        # In-doubt txns (TIMEOUT lanes above) are unlocked here too. That
        # is safe only under this client's SINGLE-COORDINATOR deployment:
        # one Coordinator owns every in-flight txn (the reference
        # benchmarks likewise run one client process per experiment,
        # client_ebpf_shard.cc), so no other coordinator can observe the
        # row between the lost reply and this ABORT, and an ABORT for a
        # lock whose GRANT reply was lost simply finds the lock already
        # held by us and releases it. With multiple coordinators an
        # in-doubt txn would need resolution (resend until acked, as the
        # reference does, or a recovery pass over the log x3) BEFORE its
        # locks could be released — releasing early would let another
        # coordinator certify against a possibly-installed write.
        dead = rw & ~alive
        ab_lane = is_lock_lane & (r_rt == Reply.GRANT) & dead[:, None]
        a_used = ab_lane.reshape(-1)
        if a_used.any():
            self._run_wave(np.full(a_used.sum(), Op.ABORT, np.int32),
                           tbl.reshape(-1)[a_used], key.reshape(-1)[a_used])

        st.committed += int((is_ro & ~missing & ~timed).sum() + alive.sum())
        return st
