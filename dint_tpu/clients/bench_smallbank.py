"""SmallBank bench window: committed txn/s on the device-fused pipeline.

Reference-scale parameters (BASELINE.md): 24M accounts x {SAVINGS, CHECKING},
90% of txns on the 4% hot set, mix 15/15/15/25/15/15, 3 replicated shards
with the log x3 / bck x2 / prim commit pipeline
(smallbank/caladan/client_ebpf_shard.cc:389-560). Called from bench.py's
child process; returns extra JSON fields for the headline line.

The balance-conservation invariant is checked over the whole window:
table-sum delta (mod 2^32) must equal the pipeline's own committed-delta
accounting. A violation raises — a corrupted window must not report a number.
"""
from __future__ import annotations

import jax
import numpy as np

from .. import stats
from ..engines import smallbank_pipeline as sp

N_ACCOUNTS = 24_000_000
WIDTH = 8192
BLOCK = 16


def run(window_s: float = 10.0, n_accounts: int = N_ACCOUNTS,
        width: int = WIDTH, block: int = BLOCK) -> dict:
    stacked = sp.create_stacked(n_accounts)
    base = int(np.asarray(sp.total_balance(stacked)))
    runner = sp.build_runner(n_accounts, w=width, cohorts_per_block=block)
    key = jax.random.PRNGKey(1)

    stacked, total, warm, dt, _, _ = stats.run_window(
        runner, stacked, key, window_s, sp.N_STATS, warmup_blocks=1)

    committed = int(total[sp.STAT_COMMITTED])
    attempted = int(total[sp.STAT_ATTEMPTED])
    if int(total[sp.STAT_MAGIC_BAD] + warm[sp.STAT_MAGIC_BAD]) != 0:
        raise RuntimeError("smallbank magic-byte integrity violated")
    # conservation covers the WHOLE run (warmup writes land in the tables too)
    accounted = int(total[sp.STAT_BAL_DELTA] + warm[sp.STAT_BAL_DELTA])
    final = int(np.asarray(sp.total_balance(stacked)))
    if (final - base) % (1 << 32) != accounted % (1 << 32):
        raise RuntimeError(
            f"balance conservation violated: table delta {final - base} != "
            f"accounted {accounted} (mod 2^32)")

    return {
        "smallbank_committed_txns_per_sec": round(committed / dt, 1),
        "smallbank_abort_rate": round(1 - committed / max(attempted, 1), 5),
        "smallbank_balance_conserved": True,
    }
