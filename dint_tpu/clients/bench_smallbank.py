"""SmallBank bench window: committed txn/s on the dense fused pipeline.

Reference-scale parameters (BASELINE.md): 24M accounts x {SAVINGS, CHECKING},
90% of txns on the 4% hot set, mix 15/15/15/25/15/15, 3 replicated shards
with the log x3 / bck x2 / prim commit pipeline
(smallbank/caladan/client_ebpf_shard.cc:389-560). Called from bench.py's
child process; returns extra JSON fields for the headline line. Runs the
sort-free dense engine (engines/smallbank_dense.py) with cross-cohort lock
concurrency; the generic engine (engines/smallbank_pipeline.py) remains the
semantics reference.

The balance-conservation invariant is checked over the whole window:
table-sum delta (mod 2^32) must equal the pipeline's own committed-delta
accounting. A violation raises — a corrupted window must not report a number.
"""
from __future__ import annotations

import jax
import numpy as np

from .. import stats
from ..engines import smallbank_dense as sd

N_ACCOUNTS = 24_000_000
WIDTH = 8192
BLOCK = 16
# both sides of the width/abort trade, quoted side by side: w=8192 commits
# fewer txn/s at low-single-digit aborts; w=16384 commits more at ~2x the
# abort rate. The HEADLINE is the abort-matched point (lowest abort rate)
# because the baseline criterion is throughput at MATCHED abort rate
# (BASELINE.md north star), not peak throughput at any abort rate.
WIDTHS = (8192, 16384)


def run(window_s: float = 10.0, n_accounts: int = N_ACCOUNTS,
        widths=WIDTHS, block: int = BLOCK, hot_frac: float | None = None,
        hot_prob: float | None = None,
        knobs: dict | None = None) -> dict:
    """Bench every width in ``widths``; headline the abort-matched point
    and quote all (width, tps, abort_rate) points.

    ``hot_frac``/``hot_prob`` override the workload's 90%/4% skew (the
    bench.py --hot-frac/--hot-prob knobs). ``knobs`` carries the
    plan-resolved builder knobs (use_pallas / use_hotset / use_fused —
    bench.py's _plan_resolve); None falls back to the builder's env
    resolution (DINT_USE_HOTSET etc.)."""
    points = [_run_one(window_s, n_accounts, w, block, hot_frac, hot_prob,
                       knobs)
              for w in widths]
    head = min(points, key=lambda p: p["abort_rate"])
    return {
        "smallbank_committed_txns_per_sec": head["committed_tps"],
        "smallbank_abort_rate": head["abort_rate"],
        "smallbank_width": head["width"],
        "smallbank_points": points,
        "smallbank_use_hotset": head["use_hotset"],
        "smallbank_hot_frac": head["hot_frac"],
        "smallbank_hot_prob": head["hot_prob"],
        "smallbank_balance_conserved": True,
    }


def _run_one(window_s: float, n_accounts: int, width: int, block: int,
             hot_frac: float | None = None,
             hot_prob: float | None = None,
             knobs: dict | None = None) -> dict:
    from ..ops import pallas_gather as pg
    from . import workloads as wl

    db = sd.create(n_accounts)
    base = int(np.asarray(sd.total_balance(db)))
    runner, init, drain = sd.build_pipelined_runner(
        n_accounts, w=width, cohorts_per_block=block, hot_frac=hot_frac,
        hot_prob=hot_prob, **(knobs or {}))
    carry = init(db)
    key = jax.random.PRNGKey(1)

    # explicit pre-run: the first call compiles for fresh-array layouts and
    # run_window's warmup block then compiles the donated-carry layout, so
    # no XLA compile lands inside the timed window (bench.py's TATP leg and
    # exp.py pipeline_open warm twice for the same reason)
    carry, s0 = runner(carry, jax.random.fold_in(key, 999_999))
    warm0 = np.asarray(s0, np.int64).sum(axis=0)

    carry, total, warm, dt, _, _ = stats.run_window(
        runner, carry, key, window_s, sd.N_STATS, warmup_blocks=1)
    warm = warm + warm0
    db, tail = drain(carry)
    tail = np.asarray(tail, np.int64).sum(axis=0)

    committed = int(total[sd.STAT_COMMITTED] + tail[sd.STAT_COMMITTED])
    attempted = int(total[sd.STAT_ATTEMPTED] + tail[sd.STAT_ATTEMPTED])
    if int(total[sd.STAT_MAGIC_BAD] + warm[sd.STAT_MAGIC_BAD]
           + tail[sd.STAT_MAGIC_BAD]) != 0:
        raise RuntimeError("smallbank magic-byte integrity violated")
    # conservation covers the WHOLE run (warmup writes land in the tables too)
    accounted = int(total[sd.STAT_BAL_DELTA] + warm[sd.STAT_BAL_DELTA]
                    + tail[sd.STAT_BAL_DELTA])
    final = int(np.asarray(sd.total_balance(db)))
    if (final - base) % (1 << 32) != accounted % (1 << 32):
        raise RuntimeError(
            f"balance conservation violated: table delta {final - base} != "
            f"accounted {accounted} (mod 2^32)")

    return {
        "width": width,
        "committed_tps": round(committed / dt, 1),
        "abort_rate": round(1 - committed / max(attempted, 1), 5),
        # skew + hot-tier provenance: A/B artifacts must be
        # distinguishable (same rule as bench.py's "use_pallas"); a
        # plan-resolved knob records the value that actually built
        "use_hotset": (knobs["use_hotset"]
                       if knobs and "use_hotset" in knobs
                       else pg.resolve_use_hotset(None)),
        "hot_frac": wl.SB_HOT_FRAC if hot_frac is None else float(hot_frac),
        "hot_prob": wl.SB_HOT_PROB if hot_prob is None else float(hot_prob),
    }
