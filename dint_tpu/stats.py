"""L5 measurement layer: the reference's client-side stat contract.

Re-expresses the reference's per-thread counters + timed stat window
(/root/reference/store/caladan/stat.h:10-20: warmup to t=5s, measure to
t=15s) and the final metric block every client prints (throughput, goodput,
average/median/99th/99.9th latency in microseconds —
tatp/caladan/client_ebpf_shard.cc:368-377). Batched TPU execution changes
*how* latencies arise (a txn's latency spans the waves of its cohort) but
not the metric definitions, which are kept identical so results are
side-by-side comparable with the reference's clients.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np


@dataclasses.dataclass
class Window:
    """Warmup/measure/exit schedule (store/caladan/stat.h:10-13)."""
    warmup_s: float = 5.0
    measure_s: float = 10.0

    @property
    def total_s(self):
        return self.warmup_s + self.measure_s


class StatClock:
    """Drives a client loop through warmup -> measure -> done phases.

    Usage: tick() each iteration; record counters only when `measuring`
    (False again once the window has ended).
    """

    def __init__(self, window: Window | None = None):
        self.window = window or Window()
        self.t0 = time.monotonic()
        self._measure_t0 = None
        self._measure_t1 = None
        self._done = False

    def tick(self) -> str:
        now = time.monotonic()
        t = now - self.t0
        # Close the interval over the wave that ran since the previous tick
        # BEFORE classifying this one, so the final measured wave's duration
        # is included when this tick crosses into "done" (counts and time
        # then cover exactly the same waves).
        if self._measure_t0 is not None and not self._done:
            self._measure_t1 = now
        if t < self.window.warmup_s:
            return "warmup"
        if t < self.window.total_s:
            if self._measure_t0 is None:
                self._measure_t0 = self._measure_t1 = now
            return "measure"
        self._done = True
        return "done"

    @property
    def measuring(self) -> bool:
        return (not self._done and self._measure_t0 is not None
                and self._measure_t1 is not None)

    @property
    def measured_s(self) -> float:
        if self._measure_t0 is None or self._measure_t1 is None:
            return 0.0
        return self._measure_t1 - self._measure_t0


class LatencyHistogram:
    """Fixed log-bucketed latency histogram (µs) — the dintscope SLO
    sensor that rides NEXT TO the reservoir (bench/exp artifacts carry it
    as the "lat_hist" block alongside the percentile block).

    Why a second structure when `LatencyReservoir` already exists: the
    reservoir is exact until `cap` and then SAMPLED — merging two
    downsampled reservoirs (cross-shard, cross-window) is approximate and
    order-dependent. Bucket counts add exactly: `merge` is associative
    and commutative, so per-shard / per-window histograms compose into
    run totals with zero loss (the same property the reference gets from
    per-CPU counter maps), which is what an always-on serving plane needs
    for SLO accounting. The price is resolution: 8 buckets per octave
    (width 2^(1/8) ≈ 9.05%), so a percentile read off the histogram is
    within ±2^(1/16)-1 ≈ 4.4% relative error of the exact nth-element
    value (buckets represent by their geometric midpoint; bounded-error
    contract pinned in tests/test_stats.py).

    Range: 2^-4 µs .. 2^28 µs (~4.5 min), 256 buckets; out-of-range
    samples clamp to the edge buckets (the bound does not cover them).
    Totality matches the round-3 reservoir contract: empty -> zeros,
    n == 1 -> every percentile is the same defined value, non-finite
    samples are excluded (counted in `dropped_nonfinite`), never NaN.
    """

    LO_EXP = -4
    HI_EXP = 28
    PER_OCTAVE = 8
    N_BUCKETS = (HI_EXP - LO_EXP) * PER_OCTAVE
    SCHEMA = 1

    def __init__(self):
        self.counts = np.zeros(self.N_BUCKETS, np.int64)
        self.n = 0
        self.sum_us = 0.0
        self.dropped_nonfinite = 0

    def add(self, lat_us: np.ndarray | float):
        arr = np.atleast_1d(np.asarray(lat_us, np.float64))
        finite = np.isfinite(arr)
        self.dropped_nonfinite += int(len(arr) - finite.sum())
        arr = arr[finite]
        if not len(arr):
            return
        # log2 of a non-positive sample is -inf -> clamps to bucket 0
        with np.errstate(divide="ignore"):
            idx = np.floor(np.log2(np.maximum(arr, 0.0))
                           * self.PER_OCTAVE) - self.LO_EXP * self.PER_OCTAVE
        idx = np.clip(np.nan_to_num(idx, neginf=0.0), 0,
                      self.N_BUCKETS - 1).astype(np.int64)
        np.add.at(self.counts, idx, 1)
        self.n += len(arr)
        self.sum_us += float(arr.sum())

    def merge(self, other: "LatencyHistogram"):
        """Exact, associative, commutative: bucket counts add. Returns
        self (accumulator style: `total.merge(shard_a).merge(shard_b)`)."""
        self.counts += other.counts
        self.n += other.n
        self.sum_us += other.sum_us
        self.dropped_nonfinite += other.dropped_nonfinite
        return self

    def _edge(self, i: int) -> float:
        return 2.0 ** (self.LO_EXP + i / self.PER_OCTAVE)

    def _rep(self, i: int) -> float:
        """Bucket representative: geometric midpoint of its edges."""
        return 2.0 ** (self.LO_EXP + (i + 0.5) / self.PER_OCTAVE)

    def quantile(self, q: float) -> float:
        """Value at quantile q in [0, 1] (0 when empty): the
        representative of the bucket holding the ceil(q*n)-th sample —
        the histogram analogue of nth_element."""
        if self.n == 0:
            return 0.0
        rank = min(max(int(np.ceil(q * self.n)), 1), self.n)
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, rank))
        return self._rep(i)

    def percentiles(self) -> dict:
        """Same keys/totality as LatencyReservoir.percentiles."""
        if self.n == 0:
            return dict(avg=0.0, p50=0.0, p99=0.0, p999=0.0)
        return dict(avg=self.sum_us / self.n, p50=self.quantile(0.50),
                    p99=self.quantile(0.99), p999=self.quantile(0.999))

    def to_dict(self) -> dict:
        """Sparse, schema-stable serialization (artifact "lat_hist"
        block): only non-zero buckets, keyed by index."""
        return {
            "schema": self.SCHEMA,
            "lo_exp": self.LO_EXP, "per_octave": self.PER_OCTAVE,
            "n": int(self.n), "sum_us": round(self.sum_us, 3),
            "dropped_nonfinite": int(self.dropped_nonfinite),
            "buckets": {str(i): int(c) for i, c in enumerate(self.counts)
                        if c},
            **{f"{k}_us": round(v, 2)
               for k, v in self.percentiles().items()},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LatencyHistogram":
        if d.get("lo_exp", cls.LO_EXP) != cls.LO_EXP or \
                d.get("per_octave", cls.PER_OCTAVE) != cls.PER_OCTAVE:
            raise ValueError("histogram bucket geometry mismatch")
        h = cls()
        for i, c in (d.get("buckets") or {}).items():
            h.counts[int(i)] = int(c)
        h.n = int(d.get("n", int(h.counts.sum())))
        h.sum_us = float(d.get("sum_us", 0.0))
        h.dropped_nonfinite = int(d.get("dropped_nonfinite", 0))
        return h


class LatencyReservoir:
    """Latency sample store (µs). The reference keeps every sample in a
    per-thread vector and nth_element's it (store/caladan/stat.h:15-20);
    we keep up to `cap` samples with reservoir downsampling past that.

    Every sample is ALSO counted into a `LatencyHistogram` (`self.hist`):
    the reservoir serves exact percentiles for one window, the histogram
    serves exact cross-shard/cross-window merges and the artifact
    "lat_hist" block — two views of the same stream."""

    def __init__(self, cap: int = 1 << 20, seed: int = 0):
        self.cap = cap
        self.samples = np.empty(cap, np.float64)
        self.n_kept = 0
        self.n_seen = 0
        self.hist = LatencyHistogram()
        self._rng = np.random.default_rng(seed)

    def add(self, lat_us: np.ndarray | float):
        arr = np.atleast_1d(np.asarray(lat_us, np.float64))
        self.hist.add(arr)
        for start in range(0, len(arr), self.cap):
            self._add_chunk(arr[start:start + self.cap])

    def _add_chunk(self, arr):
        n = len(arr)
        room = self.cap - self.n_kept
        take = min(room, n)
        if take:
            self.samples[self.n_kept:self.n_kept + take] = arr[:take]
            self.n_kept += take
        rest = arr[take:]
        if len(rest):
            # reservoir: each later sample replaces a random kept one with
            # probability cap / seen-so-far
            seen = self.n_seen + take + np.arange(1, len(rest) + 1)
            keep = self._rng.random(len(rest)) < (self.cap / seen)
            idx = self._rng.integers(0, self.cap, size=len(rest))
            self.samples[idx[keep]] = rest[keep]
        self.n_seen += n

    def percentiles(self):
        """Metric dict, DEFINED at every fill level (tests/test_stats.py):

        * empty reservoir -> all zeros (a window that measured nothing
          reports 0, never NaN — the reference prints 0 lat lines too);
        * n == 1 -> every percentile equals the sample (linear
          interpolation over one point degenerates to it);
        * non-finite samples (a NaN/inf fed by a timing glitch) are
          EXCLUDED rather than poisoning every percentile — np.percentile
          propagates NaN through the whole vector otherwise.
        """
        s = self.samples[: self.n_kept]
        if len(s):
            s = s[np.isfinite(s)]
        if len(s) == 0:
            return dict(avg=0.0, p50=0.0, p99=0.0, p999=0.0)
        p50, p99, p999 = np.percentile(s, [50, 99, 99.9])
        return dict(avg=float(s.mean()), p50=float(p50), p99=float(p99),
                    p999=float(p999))


class CpuMonitor:
    """Independent host-utilization measurement, the reference's cpu_util
    service (smallbank/cpu_util.h:37-46: user vs kernel core-seconds from
    /proc/stat over the measurement window, printed as `primary
    ucores/kcores` in every client's final stats). Machine-wide AND
    process-level (this process = the host shim + dispatch loop, the TPU
    analogue of the reference's 16 server worker cores)."""

    def __init__(self):
        self._t0 = time.monotonic()
        self._m0 = self._machine()
        self._p0 = self._process()

    @staticmethod
    def _machine():
        with open("/proc/stat") as f:
            parts = f.readline().split()
        # user, nice, system, idle, iowait, irq, softirq
        user = int(parts[1]) + int(parts[2])
        kernel = int(parts[3]) + int(parts[6]) + int(parts[7])
        return user, kernel

    @staticmethod
    def _process():
        with open("/proc/self/stat") as f:
            parts = f.read().rsplit(") ", 1)[1].split()
        return int(parts[11]), int(parts[12])   # utime, stime

    def cores(self) -> dict:
        """Core-equivalents busy since construction (jiffies / HZ / wall)."""
        hz = float(os.sysconf("SC_CLK_TCK"))
        dt = max(time.monotonic() - self._t0, 1e-9)
        m1 = self._machine()
        p1 = self._process()
        return {
            "host_ucores": round((m1[0] - self._m0[0]) / hz / dt, 3),
            "host_kcores": round((m1[1] - self._m0[1]) / hz / dt, 3),
            "proc_ucores": round((p1[0] - self._p0[0]) / hz / dt, 3),
            "proc_kcores": round((p1[1] - self._p0[1]) / hz / dt, 3),
        }


def steady_blocks(block_s):
    """Trim run_window's block-time samples to steady state: the first is
    dispatch-only (async) and the last folds in the final queue-drain fetch
    (~2x a steady block)."""
    return block_s[1:-1] if len(block_s) > 2 else block_s


def cohort_latency_percentiles(block_s, cohorts_per_block: int, depth: int):
    """Latency percentiles at cohort granularity from per-block wall times.

    A txn completes `depth` pipeline steps after its cohort's dispatch.
    Cohort j of a block spends its first (cpb - j) steps in its own block
    (per-step time = that block's wall / cpb) and any remaining steps
    spill into the NEXT block's per-step time — so samples carry real
    cross-block jitter instead of one value per block, and p99.9 is
    measured, not structurally equal to p99 (the reference samples every
    txn and nth_elements the vector, store/caladan/stat.h:15-20; this is
    the batched analogue at scan-block timestamp granularity).

    Returns the percentile dict + ``n`` = sample count."""
    bs = np.asarray(steady_blocks(block_s), np.float64)
    lat = LatencyReservoir()
    if len(bs):
        step = bs / cohorts_per_block
        j = np.arange(cohorts_per_block)
        spill = np.minimum(np.maximum(j + depth - cohorts_per_block, 0),
                           depth)
        for b in range(len(bs)):
            s_next = step[b + 1] if b + 1 < len(bs) else step[b]
            lat.add(((depth - spill) * step[b] + spill * s_next) * 1e6)
    out = lat.percentiles()
    out["n"] = lat.n_seen
    out["hist"] = lat.hist.to_dict()    # the artifact "lat_hist" block
    return out


def run_latency_window(runner, state, key, window_s: float, n_stats: int,
                       depth: int, warmup_blocks: int = 2):
    """Latency-mode window: MEASURED per-cohort latency from real
    timestamps instead of the block-time model.

    Built for runners with cohorts_per_block == 1: every call dispatches
    one pipeline step and its stats are fetched SYNCHRONOUSLY, so the
    cohort dispatched at call j completes during call j+depth-1 (its
    wave-1 step plus depth-1 further steps) and its latency is the
    wall-clock difference t_end[j+depth-1] - t_start[j] — an actual
    measurement spanning real device execution, the batched analogue of
    the reference's every-txn microtime() sampling
    (store/caladan/stat.h:15-20). The per-step sync fetch costs
    throughput relative to run_window's overlapped dispatch — that is the
    latency/throughput trade a latency-mode run exists to expose.

    Returns (state, total, dt, steps, percentiles dict with ``n`` =
    cohort sample count). Totals note: a cohort's outcome stats surface
    depth-1 steps after its dispatch, so the timed fetches (+ the
    caller's drain) also capture the warmup cohorts' outcomes —
    `total` covers warmup_blocks + steps dispatched cohorts (a
    ~warmup/steps relative overcount vs the timed window, <1% at any
    real window length)."""
    import jax

    for i in range(warmup_blocks):
        state, stats = runner(state, jax.random.fold_in(key, 10**6 + i))
        np.asarray(stats)   # fetch = sync

    total = np.zeros(n_stats, np.int64)
    t_start, t_end = [], []
    t0 = time.time()
    i = 0
    while time.time() - t0 < window_s:
        t_start.append(time.time())
        state, stats = runner(state, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)    # sync fetch
        t_end.append(time.time())
        i += 1
    dt = time.time() - t0
    lat = LatencyReservoir()
    if i > depth:
        samples = (np.asarray(t_end[depth - 1:]) -
                   np.asarray(t_start[: i - depth + 1])) * 1e6
        lat.add(samples)
    out = lat.percentiles()
    out["n"] = lat.n_seen
    out["hist"] = lat.hist.to_dict()
    return state, total, dt, i, out


def run_window(runner, state, key, window_s: float, n_stats: int,
               warmup_blocks: int = 1):
    """Timed measurement loop shared by the device-fused pipeline benches.

    Runs `warmup_blocks` dispatches (compile + cache warm), then dispatches
    until `window_s` elapses, overlapping the host-side stats reduction of
    block i-1 with device execution of block i. Syncs by VALUE FETCH
    (np.asarray), never jax.block_until_ready — the axon platform returns
    from block_until_ready while the device is still executing, so a fetch
    is the only honest window bracket.

    Returns (state, total [n_stats] i64, warm_total [n_stats] i64,
    elapsed_s, blocks, block_s): `total` covers only the timed window;
    `warm_total` covers warmup (callers with table-vs-accounting invariants
    need it — warmup writes land in the tables too). `block_s` is the wall
    time of each timed loop iteration (dispatch of block i + fetch of block
    i-1's stats) — in steady state ≈ one block of device time, the basis
    for cohort-granularity latency percentiles.
    """
    import jax

    warm_total = np.zeros(n_stats, np.int64)
    for i in range(warmup_blocks):
        state, stats = runner(state, jax.random.fold_in(key, i))
        warm_total += np.asarray(stats, np.int64).sum(axis=0)

    total = np.zeros(n_stats, np.int64)
    block_s = []
    t0 = time.time()
    i = warmup_blocks
    pending = None
    tprev = t0
    while time.time() - t0 < window_s:
        state, stats = runner(state, jax.random.fold_in(key, i))
        if pending is not None:
            total += np.asarray(pending, np.int64).sum(axis=0)
        pending = stats
        i += 1
        now = time.time()
        block_s.append(now - tprev)
        tprev = now
    if pending is not None:
        total += np.asarray(pending, np.int64).sum(axis=0)  # fetch = sync
        # the final fetch closes the last block's device time
        block_s[-1] = time.time() - tprev + block_s[-1]
    dt = time.time() - t0
    return state, total, warm_total, dt, i - warmup_blocks, block_s


@dataclasses.dataclass
class TxnStats:
    """Base attempted/committed accounting shared by all txn coordinators
    (client Stats dataclasses subclass this with their abort breakdowns)."""
    attempted: int = 0
    committed: int = 0

    @property
    def abort_rate(self):
        if self.attempted == 0:
            return 0.0
        return 1.0 - self.committed / self.attempted


@dataclasses.dataclass
class MetricBlock:
    """The fixed stat block (client_ebpf_shard.cc:368-377), plus the TPU
    device-duty-cycle analogue of `primary ucores/kcores`."""
    throughput: float        # attempted txn/s (pkt/s for microbenchmarks)
    goodput: float           # committed txn/s
    avg_us: float
    p50_us: float
    p99_us: float
    p999_us: float
    device_duty: float = 0.0   # fraction of wall time the device was stepping
    extra: dict = dataclasses.field(default_factory=dict)

    @property
    def abort_rate(self):
        if self.throughput <= 0:
            return 0.0
        return 1.0 - self.goodput / self.throughput

    def to_dict(self):
        d = dict(throughput=round(self.throughput, 1),
                 goodput=round(self.goodput, 1),
                 abort_rate=round(self.abort_rate, 6),
                 avg_us=round(self.avg_us, 2), p50_us=round(self.p50_us, 2),
                 p99_us=round(self.p99_us, 2), p999_us=round(self.p999_us, 2),
                 device_duty=round(self.device_duty, 4))
        d.update(self.extra)
        return d

    def format(self) -> str:
        """Human block in the reference's shape (client_ebpf_shard.cc:368-377)."""
        lines = [
            f"throughput: {self.throughput:.1f}",
            f"goodput: {self.goodput:.1f}",
            f"average: {self.avg_us:.2f} us",
            f"median: {self.p50_us:.2f} us",
            f"99th: {self.p99_us:.2f} us",
            f"99.9th: {self.p999_us:.2f} us",
            f"device duty: {self.device_duty:.4f}",
        ]
        for k, v in self.extra.items():
            lines.append(f"{k}: {v}")
        return "\n".join(lines)

    def json(self) -> str:
        return json.dumps(self.to_dict())


class Recorder:
    """Counter + latency accumulator a client drives during the measure
    window; emits the MetricBlock at the end.

    Call :meth:`reset` after warmup so jit compile time and cold-cache waves
    don't pollute the measured window (the reference's stat window likewise
    excludes the first 5 s, store/caladan/stat.h:10-13)."""

    def __init__(self, lat_cap: int = 1 << 20):
        self._lat_cap = lat_cap
        self.extra: dict = {}
        self.reset()

    def reset(self):
        self.attempted = 0
        self.committed = 0
        self.lat = LatencyReservoir(self._lat_cap)
        self.device_busy_s = 0.0

    def record(self, attempted: int, committed: int,
               lat_us: np.ndarray | None = None,
               device_s: float = 0.0):
        self.attempted += attempted
        self.committed += committed
        if lat_us is not None and len(np.atleast_1d(lat_us)):
            self.lat.add(lat_us)
        self.device_busy_s += device_s

    def block(self, elapsed_s: float) -> MetricBlock:
        p = self.lat.percentiles()
        el = max(elapsed_s, 1e-12)
        extra = dict(self.extra)
        # the exact-merge histogram rides every metric block next to the
        # reservoir percentiles (artifact schema hygiene, OBSERVABILITY.md)
        extra.setdefault("lat_hist", self.lat.hist.to_dict())
        return MetricBlock(
            throughput=self.attempted / el,
            goodput=self.committed / el,
            avg_us=p["avg"], p50_us=p["p50"], p99_us=p["p99"],
            p999_us=p["p999"],
            device_duty=self.device_busy_s / el,
            extra=extra,
        )
