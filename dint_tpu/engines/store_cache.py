"""Cache-mode store: device-resident cache over a host backing KVS.

TPU equivalent of the reference's defining kernel/user split (SURVEY.md §3.1,
§3.2): the XDP program owns a fixed-size 4-way single-hash cache
(`struct cache_entry`, store/ebpf/utils.h:58-66) and answers hits at the NIC;
misses travel to a userspace KVS worker (store/ebpf/store_user.c:99-168) with
the evicted dirty record piggybacked (`ext_message`), and the TC egress hook
installs the fetched record into the cache on the way back
(store/ebpf/store_kern.c:302-372).

Here the device (HBM) cache is a `tables.kv.KVTable` + dirty bitmap; the
backing store is `shim.host_kvs.HostKVS`. One `cache_step` certifies a batch
against the cache and emits a miss vector; the host resolves misses and
queues refill records; `refill` installs them next step (the TC equivalent),
returning evicted dirty records for host write-back.

Three policies, matching the reference's ablation servers:
  WB_BLOOM    write-back + per-bucket bloom negatives  (#1, store_kern.c)
  WB_NOBLOOM  write-back, miss on every absent key     (#2, store_wb_kern.c)
  WT          write-through: GET served from cache; SET invalidates the
              cached slot and passes through            (#3, store_wt_kern.c:115-151)

Batch semantics: per key segment, GETs see pre-batch cache state, writes
apply in lane order (the store.step contract). If ANY lane of a key segment
misses, the WHOLE segment is deferred to the host (reply MISS), which
resolves it sequentially — coarser than the reference's per-packet
interleaving but serial-equivalent. INSERTs always defer to the host (the
reference's write-allocate happens on the refill path here; the
write-through variant's in-kernel clean-slot fill, store_wt_kern.c:153-196,
is subsumed by refill).
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from ..ops import hashing, segments
from ..ops import pallas_gather as pg
from ..tables import kv
from .types import Batch, Op, Replies, Reply

I32 = jnp.int32
U32 = jnp.uint32

WB_BLOOM = "wb_bloom"
WB_NOBLOOM = "wb_nobloom"
WT = "wt"
POLICIES = (WB_BLOOM, WB_NOBLOOM, WT)

# reply code for "deferred to host" lanes (internal to the cache server;
# never hits the wire — the host overwrites it before replying)
MISS = 100


@flax.struct.dataclass
class CacheTable:
    """``hot_val``/``hot_ver`` (None = off) are the dintcache hot tier
    inside the cache tier — "XDP within XDP": a key-indexed write-through
    mirror of the hot key prefix (key_hi == 0, key_lo < hot_n) serving
    the probe's val/ver reads for hot lanes, maintained at the write-back
    and refill install points. Mirror entries of keys NOT currently
    cached are stale by design: every val0/ver0 consumer in cache_step is
    hit0-masked (same argument as engines/store.HotKV)."""
    kv: kv.KVTable
    dirty: jax.Array      # bool [NB*S] (flat entries, like kv.KVTable)
    clock: jax.Array      # u32 [] victim rotor (reference picks by slot scan)
    hot_val: jax.Array | None = None   # u32 [hot_n * VW]
    hot_ver: jax.Array | None = None   # u32 [hot_n]


def create(n_buckets: int, slots: int = 4, val_words: int = 10,
           hot_keys: int = 0) -> CacheTable:
    """``hot_keys`` > 0 attaches the dintcache mirror for key ids
    [0, hot_keys) (empty, coherent with the empty cache)."""
    return CacheTable(kv=kv.create(n_buckets, slots, val_words),
                      dirty=jnp.zeros((n_buckets * slots,), bool),
                      clock=U32(0),
                      hot_val=(jnp.zeros((hot_keys * val_words,), U32)
                               if hot_keys else None),
                      hot_ver=(jnp.zeros((hot_keys,), U32)
                               if hot_keys else None))


def _hot_n(cache: CacheTable) -> int:
    return cache.hot_ver.shape[0] if cache.hot_ver is not None else 0


def _probe1(t: kv.KVTable, key_hi, key_lo, bkt):
    """Single-hash probe (the reference cache is single-hash 4-way)."""
    hit, slot, eidx = _probe1_loc(t, key_hi, key_lo, bkt)
    return hit, slot, kv.entry_val(t, eidx), t.ver[eidx]


def _probe1_loc(t: kv.KVTable, key_hi, key_lo, bkt):
    """Location-only probe half: the hot tier serves hot lanes' val/ver
    from its mirror, so the value gather is the caller's choice."""
    rows = kv.bucket_rows(t, bkt)
    rows_hi = t.key_hi[rows]
    rows_lo = t.key_lo[rows]
    rows_valid = t.valid[rows]
    match = rows_valid & (rows_hi == key_hi[:, None]) & (rows_lo == key_lo[:, None])
    hit = match.any(axis=-1)
    slot = jnp.argmax(match, axis=-1).astype(I32)
    return hit, slot, bkt * t.slots + slot


def cache_step(cache: CacheTable, batch: Batch, *, policy: str = WB_BLOOM,
               use_pallas: bool = False):
    """Certify a batch against the cache.

    Returns (cache', replies, miss, flush):
      miss: bool [R] — lanes the host must resolve (whole key segments;
        replies there carry rtype == MISS).
      flush: dict {mask, key_hi, key_lo, val, ver} — dirty cached records of
        deferred segments, invalidated here; the host MUST apply these as
        write-backs *before* resolving the miss lanes, or it would serve the
        deferred segment from stale backing data (the reference's analogue:
        the evicted dirty record rides the ext_message to userspace and is
        applied before the miss is served, store/ebpf/store_user.c:99-168).
    """
    assert policy in POLICIES
    r = batch.width
    t = cache.kv
    sb = segments.sort_batch(batch.key_hi, batch.key_lo)
    op = batch.op[sb.perm]
    val_in = batch.val[sb.perm]

    bkt = hashing.bucket(sb.key_hi, sb.key_lo, t.n_buckets)
    hn = _hot_n(cache)
    if hn:
        # dintcache partition: hot keys' val/ver from the mirror, cold
        # from the cache entries (``use_pallas`` = the VMEM hot kernel)
        hit0, slot0, eidx0 = _probe1_loc(t, sb.key_hi, sb.key_lo, bkt)
        kmidx = jnp.where((sb.key_hi == U32(0)) & (sb.key_lo < U32(hn)),
                          sb.key_lo.astype(I32), -1)
        val0 = pg.hot_gather(t.val, cache.hot_val, eidx0, kmidx,
                             t.val_words,
                             use_pallas=use_pallas).reshape(r, t.val_words)
        ver0 = pg.hot_gather(t.ver, cache.hot_ver, eidx0, kmidx, 1,
                             use_pallas=use_pallas)
    else:
        hit0, slot0, val0, ver0 = _probe1(t, sb.key_hi, sb.key_lo, bkt)

    is_get = op == Op.GET
    is_set = op == Op.SET
    is_ins = op == Op.INSERT
    is_del = op == Op.DELETE
    used = op != Op.NOP

    if policy == WB_BLOOM:
        absent = ~kv.bloom_maybe(t, sb.key_hi, sb.key_lo, bkt, bkt)
    else:
        absent = jnp.zeros((r,), bool)

    # lanes that can be served from the cache alone
    local_get = is_get & (hit0 | absent)
    local_set = is_set & hit0 if policy != WT else jnp.zeros((r,), bool)
    local = local_get | local_set
    # INSERT/DELETE and anything else — including Op.SCAN (round-20
    # dintscan): range scans need the ORDERED run over the full
    # keyspace, which only the authoritative store owns; a cache holds
    # an arbitrary working-set subset, so scan lanes always defer and
    # the host resolves them against the backing KVS — defers to the host
    lane_miss = used & ~local
    # whole-segment deferral: one miss lane defers its key's every lane
    seg_miss = segments.seg_any(sb, lane_miss)
    miss = used & seg_miss

    # ---- cache-local semantics on fully-hit segments ----------------------
    n_set_before = segments.seg_cumsum_excl(sb, is_set.astype(I32))
    n_set_total = segments.seg_sum(sb, is_set.astype(I32))
    last_s = segments.seg_max_where(sb, is_set, sb.rank, I32(-1))
    pos_last = jnp.clip(sb.head_pos + last_s, 0, r - 1)

    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where(is_get & hit0, Reply.VAL, rtype)
    rtype = jnp.where(is_get & absent & ~hit0, Reply.NOT_EXIST, rtype)
    rtype = jnp.where(is_set, Reply.ACK, rtype)
    rtype = jnp.where(miss, MISS, rtype)
    rval = jnp.where((is_get & hit0 & ~miss)[:, None], val0, jnp.zeros_like(val0))
    rver = jnp.where(is_get & hit0 & ~miss, ver0, U32(0))
    rver = jnp.where(is_set & ~miss, ver0 + (n_set_before + 1).astype(U32), rver)

    # ---- cache mutations ---------------------------------------------------
    # 1. any deferred segment drops its cached copy (and flushes it if dirty)
    #    so the host resolves against fresh backing data; covers the
    #    write-through SET invalidate (store_wt_kern.c:115-151) and the
    #    delete/insert paths in one rule.
    inval = sb.last & seg_miss & hit0
    flush_mask = inval & cache.dirty[bkt * t.slots + slot0]
    flush = {
        "mask": flush_mask,
        "key_hi": sb.key_hi.astype(U32), "key_lo": sb.key_lo.astype(U32),
        "val": val0, "ver": ver0,
    }
    ne = t.n_buckets * t.slots
    e_i = jnp.where(inval, bkt * t.slots + slot0, ne)
    cache = cache.replace(
        kv=t.replace(valid=t.valid.at[e_i].set(False, mode="drop")),
        dirty=cache.dirty.at[e_i].set(False, mode="drop"))

    # 2. write-back: the segment-last lane of a fully-local segment installs
    #    the last SET's value and marks the slot dirty
    if policy != WT:
        t2 = cache.kv
        writer = sb.last & ~seg_miss & (last_s >= 0) & hit0
        new_ver = ver0 + n_set_total.astype(U32)
        e_w = jnp.where(writer, bkt * t2.slots + slot0,
                        t2.n_buckets * t2.slots)
        if hn:
            # write-back writes through to the mirror (writer = one lane
            # per key segment, distinct entries AND distinct key ids)
            w_midx = jnp.where(writer & (kmidx >= 0), kmidx, -1)
            e_raw = bkt * t2.slots + slot0
            val_new, hot_val = pg.hot_scatter(
                t2.val, cache.hot_val, e_raw, w_midx, writer,
                val_in[pos_last].reshape(-1), t2.val_words,
                use_pallas=use_pallas)
            ver_new, hot_ver = pg.hot_scatter(
                t2.ver, cache.hot_ver, e_raw, w_midx, writer, new_ver, 1,
                use_pallas=use_pallas)
            cache = cache.replace(
                kv=t2.replace(val=val_new, ver=ver_new),
                dirty=cache.dirty.at[e_w].set(True, mode="drop"),
                hot_val=hot_val, hot_ver=hot_ver,
            )
        else:
            cache = cache.replace(
                kv=t2.replace(
                    val=t2.val.at[kv.val_word_idx(t2, e_w)].set(
                        val_in[pos_last].reshape(-1), mode="drop"),
                    ver=t2.ver.at[e_w].set(new_ver, mode="drop"),
                ),
                dirty=cache.dirty.at[e_w].set(True, mode="drop"),
            )

    o_rtype, o_rver, o_miss = segments.unsort(sb, rtype, rver, miss)
    o_rval = segments.unsort(sb, rval)
    return (cache, Replies(rtype=o_rtype, val=o_rval, ver=o_rver), o_miss,
            flush)


def refill(cache: CacheTable, key_hi, key_lo, val, ver, bloom_hi, bloom_lo,
           mask):
    """Install host-fetched records (the TC-egress equivalent,
    store_kern.c:302-372) and set each touched bucket's bloom word (the
    DELETE-path bloom handoff, tatp/ebpf/shard_kern.c:1186-1192).

    mask: bool [R] — lanes carrying a record. ver == 0 means "no record;
    just install the bloom word" (pure bloom refresh after DELETE).
    Victim choice: first invalid slot, else clock rotor over slots (the
    reference scans for invalid then overwrites, store_kern.c:208-246).
    Returns (cache', evicted dict) — evicted dirty records for host
    write-back (the ext_message ver1==1 protocol, store/ebpf/store_user.c:99-168).
    """
    t = cache.kv
    r = key_hi.shape[0]
    bkt = hashing.bucket(key_hi, key_lo, t.n_buckets)
    # one install per bucket per call (host guarantees: it dedups refills);
    # serialize same-bucket installs by keeping only the first
    sb = segments.sort_batch(jnp.zeros((r,), U32), bkt.astype(U32))
    first = sb.head
    m = mask[sb.perm] & first
    keep = segments.unsort(sb, m)

    has_rec = keep & (ver != 0)
    hit, slot_h, _, _ = _probe1(t, key_hi, key_lo, bkt)
    rows_valid = t.valid[kv.bucket_rows(t, bkt)]
    free_any = (~rows_valid).any(axis=-1)
    first_free = jnp.argmax(~rows_valid, axis=-1).astype(I32)
    rotor = ((cache.clock + jnp.arange(r, dtype=U32)) % U32(t.slots)).astype(I32)
    victim = jnp.where(hit, slot_h, jnp.where(free_any, first_free, rotor))
    e_vic = bkt * t.slots + victim

    ev_valid = has_rec & ~hit & ~free_any
    ev_dirty = ev_valid & cache.dirty[e_vic]
    evicted = {
        "mask": ev_dirty,
        "key_hi": t.key_hi[e_vic], "key_lo": t.key_lo[e_vic],
        "val": kv.entry_val(t, e_vic), "ver": t.ver[e_vic],
    }

    ne = t.n_buckets * t.slots
    e_r = jnp.where(has_rec, e_vic, ne)
    hn = _hot_n(cache)
    if hn:
        # refill installs write through to the mirror (the TC-egress
        # install is the slow path, so the XLA double scatter suffices);
        # one install per bucket and host-deduped keys keep both index
        # sets unique
        midx = jnp.where(has_rec & (key_hi.astype(U32) == U32(0))
                         & (key_lo.astype(U32) < U32(hn)),
                         key_lo.astype(I32), -1)
        val_new, hot_val = pg.hot_scatter(
            t.val, cache.hot_val, e_vic, midx, has_rec, val.reshape(-1),
            t.val_words, use_pallas=False)
        ver_new, hot_ver = pg.hot_scatter(
            t.ver, cache.hot_ver, e_vic, midx, has_rec, ver, 1,
            use_pallas=False)
        cache = cache.replace(hot_val=hot_val, hot_ver=hot_ver)
    else:
        val_new = t.val.at[kv.val_word_idx(t, e_r)].set(
            val.reshape(-1), mode="drop")
        ver_new = t.ver.at[e_r].set(ver, mode="drop")
    new = t.replace(
        key_hi=t.key_hi.at[e_r].set(key_hi.astype(U32), mode="drop"),
        key_lo=t.key_lo.at[e_r].set(key_lo.astype(U32), mode="drop"),
        val=val_new,
        ver=ver_new,
        valid=t.valid.at[e_r].set(True, mode="drop"),
    )
    safe_bloom = jnp.where(keep, bkt, t.n_buckets)
    new = new.replace(
        bloom_hi=new.bloom_hi.at[safe_bloom].set(bloom_hi, mode="drop"),
        bloom_lo=new.bloom_lo.at[safe_bloom].set(bloom_lo, mode="drop"),
    )
    dirty = cache.dirty.at[e_r].set(False, mode="drop")
    return cache.replace(kv=new, dirty=dirty,
                         clock=cache.clock + U32(1)), evicted
