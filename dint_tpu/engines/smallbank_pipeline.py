"""Device-fused SmallBank transaction pipeline: whole txns in one jitted step.

Companion to engines/tatp_pipeline.py for the SmallBank workload. The
reference's client coordinator (smallbank/caladan/client_ebpf_shard.cc)
drives each txn through the wave pipeline

  fused X/S lock+read at primaries  ->  compute  ->  CommitLog x3 shards
  ->  CommitBck x2 backups  ->  CommitPrim  ->  Release granted locks

(:389-560; abort path releases granted locks, :330-370). The host
coordinator port (clients/smallbank_client.py) keeps that wave structure but
pays a host<->device RTT per wave. Here the entire cohort — on-device
workload generation (mix 15/15/15/25/15/15, 90%-hot-set skew,
smallbank/caladan/smallbank.h:16-18,29-50,63-69), shard routing, both
certification waves, replication fan-out, balance logic, and abort
accounting — runs inside one jitted function over the 3 stacked shard
replicas (vmapped smallbank.step), with a lax.scan running many cohorts per
dispatch. Host traffic per block is one RNG key in, one stats matrix out.

Wave structure per cohort (2 vmapped steps):
  wave 1  [3w lanes]  fused ACQ_{S,X}_READ at owner shards (up to 3 lock
                      slots per txn)
  wave 2  [9w lanes]  log block (COMMIT_LOG on all shards) + role block
                      (COMMIT_PRIM at owner / COMMIT_BCK at backups) +
                      release block (REL_X/REL_S of every granted lock,
                      committed or aborted, at owners)

Intra-cohort lock conflicts are real concurrency: two txns in one cohort
contending on an account resolve exactly like the reference's no-wait 2PL
(first-in-lane-order wins, rest REJECT -> txn aborts), so the abort rate
responds to skew/contention.

Stats additionally track the signed sum of balance deltas written by
committed txns (STAT_BAL_DELTA) so a bench window can check the
balance-conservation invariant without fetching the tables.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..clients import workloads as wl
from ._memo import memoize_builder
from ..monitor import counters as mon
from ..monitor import waves
from . import smallbank
from .types import Batch, Op, PAD_KEY, Reply

I32 = jnp.int32
U32 = jnp.uint32

N_SHARDS = 3
L = 3                  # lock slots per txn
AMT = 5                # fixed amount for deposit/send_payment/write_check
TS_AMT_MAX = 20        # transact_saving samples a SIGNED amount in
                       # [-TS_AMT_MAX, TS_AMT_MAX]: withdrawals can overdraw
                       # (esp. after amalgamate zeroes a hot savings row),
                       # making the negative-balance logic abort a live path
MAGIC = wl.SB_MAGIC
VW = 2                 # word0 = balance (i32 bits), word1 = magic

# stats vector layout
STAT_ATTEMPTED = 0
STAT_COMMITTED = 1
STAT_AB_LOCK = 2
STAT_AB_LOGIC = 3
STAT_MAGIC_BAD = 4
STAT_BAL_DELTA = 5     # signed; sums the window's committed balance deltas
N_STATS = 6

_PAD32 = U32(PAD_KEY & 0xFFFFFFFF)


def create_stacked(n_accounts: int, init_balance: int = 1000) -> smallbank.Shard:
    """3 identically-populated replicas as one stacked Shard pytree
    (reference populates every record on all 3 servers,
    smallbank/ebpf/shard_user.c:74-77). Built on device: no host-side
    materialization of the 24M-account tables."""
    def one():
        s = smallbank.create(n_accounts, val_words=VW)
        val = jnp.zeros((n_accounts, VW), U32)
        val = val.at[:, 0].set(U32(init_balance))
        val = val.at[:, 1].set(U32(MAGIC))
        val = val.reshape(-1)            # flat interleaved (tables.dense)
        ver = jnp.ones((n_accounts,), U32)
        return s.replace(sav=s.sav.replace(val=val, ver=ver),
                         chk=s.chk.replace(val=val, ver=ver))

    proto = one()
    return jax.tree.map(lambda x: jnp.broadcast_to(x[None],
                                                   (N_SHARDS,) + x.shape), proto)


def total_balance(stacked: smallbank.Shard, replica: int = 0):
    """Device-side balance sum over one replica, wrapping mod 2^32 (x64 is
    off, so i32 accumulate; conservation checks must compare DELTAS under
    the same wraparound — exact because two's-complement add is associative)."""
    vw = stacked.sav.val_words
    sav = stacked.sav.val[replica, 0::vw].astype(I32)   # word0 = balance
    chk = stacked.chk.val[replica, 0::vw].astype(I32)
    return sav.sum(dtype=I32) + chk.sum(dtype=I32)


def gen_cohort(key, w: int, n_accounts: int, hot_frac: float = wl.SB_HOT_FRAC,
               hot_prob: float = wl.SB_HOT_PROB, mix=None):
    """On-device workload generation: (ttype [w], a1 [w], a2 [w]).

    Hot-set skew per smallbank/caladan/smallbank.h:29-50: 90% of samples in
    the first 4% of the keyspace (skew/mix overridable for sweep ablations).

    One `random.bits` draw feeds every field via modular reduction — the
    reference's generators are likewise `rand() % n` (smallbank.h:29-50);
    threefry split chains + weighted `choice` measured ~2 ms per 8192-txn
    step on v5e."""
    bits = jax.random.bits(key, (w, 5), U32)
    thresh = jnp.asarray(wl.mix_thresholds(
        wl.SB_MIX if mix is None else mix))
    ttype = jnp.minimum(
        jnp.searchsorted(thresh, bits[:, 0], side="right"), 5).astype(I32)
    hot_n = max(int(n_accounts * hot_frac), 1)
    hot_cut = U32(min(int(hot_prob * 2.0**32), 0xFFFFFFFF))

    def sample(word, coin):
        hot = (word % U32(hot_n)).astype(I32)
        uni = (word % U32(n_accounts)).astype(I32)
        return jnp.where(coin < hot_cut, hot, uni)

    a1 = sample(bits[:, 1], bits[:, 3])
    a2 = sample(bits[:, 2], bits[:, 4])
    a2 = jnp.where(a1 == a2, (a2 + 1) % n_accounts, a2)
    return ttype, a1, a2


def _lock_slots(ttype, a1, a2):
    """Per-txn lock set [w, L]: (op, table, acct) — the reference's per-txn
    lock lists (client_ebpf_shard.cc TxnAmalgamate:255 etc.)."""
    w = ttype.shape[0]
    SAV, CHK = smallbank.SAVINGS, smallbank.CHECKING
    X, S = Op.ACQ_X_READ, Op.ACQ_S_READ
    z = jnp.zeros((w,), I32)

    t = ttype
    is_am = t == wl.SB_AMALGAMATE
    is_ba = t == wl.SB_BALANCE
    is_de = t == wl.SB_DEPOSIT
    is_sp = t == wl.SB_SEND_PAYMENT
    is_ts = t == wl.SB_TRANSACT_SAVING
    is_wc = t == wl.SB_WRITE_CHECK

    # slot 0: amalgamate/transact X SAV, balance/write_check S SAV,
    # deposit/send_payment X CHK
    op0 = jnp.select([is_am | is_de | is_sp | is_ts, is_ba | is_wc], [X, S], 0)
    tb0 = jnp.where(is_de | is_sp, CHK, SAV)
    ac0 = a1
    # slot 1
    op1 = jnp.select([is_am | is_sp | is_wc, is_ba], [X, S], 0)
    tb1 = jnp.full((w,), CHK, I32)
    ac1 = jnp.where(is_sp, a2, a1)
    # slot 2
    op2 = jnp.where(is_am, X, 0)
    tb2 = jnp.full((w,), CHK, I32)
    ac2 = a2

    ops = jnp.stack([op0, op1, op2], axis=1)
    tbl = jnp.stack([tb0, tb1, tb2], axis=1)
    acc = jnp.stack([ac0, ac1, ac2], axis=1)
    return ops, tbl, acc


def compute_phase(ttype, bal, alive, ts_amt):
    """Per-txn-type balance logic, shared by every SmallBank engine
    (client_ebpf_shard.cc TxnAmalgamate:255 / TxnSendPayment:830 /
    TxnTransactSaving:1116 / TxnWriteCheck:1241 compute steps).

    bal [w, L] are the fused-read balances of the txn's lock slots.
    Returns (nw [w, L] new balances, do [w, L] slots written,
    logic_abort [w], commit [w] writes-install, committed [w])."""
    w, _ = bal.shape
    t = ttype
    b0, b1, b2 = bal[:, 0], bal[:, 1], bal[:, 2]
    nw = jnp.zeros((w, L), I32)
    do = jnp.zeros((w, L), bool)
    logic_abort = jnp.zeros((w,), bool)

    m = alive & (t == wl.SB_AMALGAMATE)
    nw = nw.at[:, 2].set(jnp.where(m, b2 + b0 + b1, nw[:, 2]))
    do = do | (m[:, None] & jnp.ones((1, L), bool))
    m = alive & (t == wl.SB_DEPOSIT)
    nw = nw.at[:, 0].set(jnp.where(m, b0 + AMT, nw[:, 0]))
    do = do.at[:, 0].set(do[:, 0] | m)
    m = alive & (t == wl.SB_SEND_PAYMENT)
    insufficient = b0 < AMT
    logic_abort |= m & insufficient
    ok = m & ~insufficient
    nw = nw.at[:, 0].set(jnp.where(ok, b0 - AMT, nw[:, 0]))
    nw = nw.at[:, 1].set(jnp.where(ok, b1 + AMT, nw[:, 1]))
    do = do.at[:, 0].set(do[:, 0] | ok)
    do = do.at[:, 1].set(do[:, 1] | ok)
    m = alive & (t == wl.SB_TRANSACT_SAVING)
    neg = (b0 + ts_amt) < 0
    logic_abort |= m & neg
    ok = m & ~neg
    nw = nw.at[:, 0].set(jnp.where(ok, b0 + ts_amt, nw[:, 0]))
    do = do.at[:, 0].set(do[:, 0] | ok)
    m = alive & (t == wl.SB_WRITE_CHECK)
    overdraw = (b0 + b1) < AMT
    nw = nw.at[:, 1].set(jnp.where(
        m, b1 - AMT - jnp.where(overdraw, 1, 0), nw[:, 1]))
    do = do.at[:, 1].set(do[:, 1] | m)

    commit = alive & ~logic_abort & (t != wl.SB_BALANCE)
    committed = commit | (alive & (t == wl.SB_BALANCE))
    return nw, do, logic_abort, commit, committed


def _broadcast_batch(op_s, table, key_lo, val, ver):
    s = op_s.shape[0]

    def bc(x):
        return jnp.broadcast_to(x[None], (s,) + x.shape)

    return Batch(op=op_s, table=bc(table),
                 key_hi=bc(jnp.zeros_like(key_lo)), key_lo=bc(key_lo),
                 val=bc(val), ver=bc(ver))


def _merge(owner, stacked):
    r = owner.shape[0]
    return stacked[owner, jnp.arange(r)]


def cohort_step(stacked: smallbank.Shard, key, *, w: int, n_accounts: int,
                counters: mon.Counters | None = None):
    """One full cohort of w txns against the 3 stacked replicas.
    Returns (stacked', stats [N_STATS] i32), with the updated Counters
    appended when the dintmon plane is threaded (``counters``)."""
    step_v = jax.vmap(smallbank.step)
    kgen, kamt = jax.random.split(key)
    with waves.scope("smallbank_pipeline", "gen"):
        ttype, a1, a2 = gen_cohort(kgen, w, n_accounts)
        ts_amt = jax.random.randint(kamt, (w,), -TS_AMT_MAX,
                                    TS_AMT_MAX + 1, dtype=I32)
        l_op, l_tb, l_ac = _lock_slots(ttype, a1, a2)     # [w, L]
    r = w * L

    lane_op = l_op.reshape(r)
    lane_tbl = l_tb.reshape(r)
    lane_acc = l_ac.reshape(r)
    used = lane_op != 0
    lane_key = jnp.where(used, lane_acc.astype(U32), _PAD32)
    owner = (lane_acc % N_SHARDS).astype(I32)
    sid = jnp.arange(N_SHARDS, dtype=I32)

    zval = jnp.zeros((r, VW), U32)
    zver = jnp.zeros((r,), U32)

    # ---- wave 1: fused lock+read at owners ---------------------------------
    with waves.scope("smallbank_pipeline", "wave1"):
        op_s = jnp.where((owner[None] == sid[:, None]) & used[None],
                         lane_op[None], Op.NOP)
        stacked, rep1 = step_v(stacked, _broadcast_batch(
            op_s, lane_tbl, lane_key, zval, zver))
        rt1 = _merge(owner, rep1.rtype).reshape(w, L)
        rv1 = _merge(owner, rep1.val)                     # [r, VW]
        rver1 = _merge(owner, rep1.ver).reshape(w, L)

        active = l_op != 0
        granted = active & (rt1 == Reply.GRANT)
        magic_bad = jnp.sum(granted.reshape(r) & (rv1[:, 1] != MAGIC),
                            dtype=I32)
        lock_rejected = (active & (rt1 == Reply.REJECT)).any(axis=1)
        alive = ~lock_rejected

        bal = jnp.where(granted,
                        rv1[:, 0].reshape(w, L).astype(I32), 0)  # [w, L]

    with waves.scope("smallbank_pipeline", "compute"):
        nw, do, logic_abort, commit, committed = compute_phase(
            ttype, bal, alive, ts_amt)
        do_write = do & commit[:, None] & active          # [w, L]
        bal_delta = jnp.sum(jnp.where(do_write, nw - bal, 0), dtype=I32)

    # ---- wave 2: log x3 + role (prim/bck) + release ------------------------
    with waves.scope("smallbank_pipeline", "wave2"):
        c_val = jnp.zeros((r, VW), U32)
        c_val = c_val.at[:, 0].set(nw.reshape(r).astype(U32))
        c_val = c_val.at[:, 1].set(jnp.where(do_write.reshape(r),
                                             U32(MAGIC), U32(0)))
        c_ver = jnp.where(do_write, rver1 + 1, 0).reshape(r).astype(U32)
        dwf = do_write.reshape(r)
        c_key = jnp.where(dwf, lane_acc.astype(U32), _PAD32)

        log_op = jnp.where(dwf, Op.COMMIT_LOG, Op.NOP)    # all shards
        role_s = jnp.where(dwf[None],
                           jnp.where(owner[None] == sid[:, None],
                                     Op.COMMIT_PRIM, Op.COMMIT_BCK),
                           Op.NOP)                         # [S, r]

        relf = granted.reshape(r)
        rel_op = jnp.where(lane_op == Op.ACQ_X_READ, Op.REL_X, Op.REL_S)
        rel_s = jnp.where(relf[None] & (owner[None] == sid[:, None]),
                          rel_op[None], Op.NOP)            # [S, r]
        rel_key = jnp.where(relf, lane_acc.astype(U32), _PAD32)

        lane2_key = jnp.concatenate([c_key, c_key, rel_key])
        lane2_tbl = jnp.concatenate([lane_tbl, lane_tbl, lane_tbl])
        lane2_val = jnp.concatenate([c_val, c_val, jnp.zeros((r, VW), U32)])
        lane2_ver = jnp.concatenate([c_ver, c_ver, jnp.zeros((r,), U32)])
        op2_s = jnp.concatenate([
            jnp.broadcast_to(log_op[None], (N_SHARDS, r)), role_s, rel_s],
            axis=1)
        stacked, _ = step_v(stacked, _broadcast_batch(
            op2_s, lane2_tbl, lane2_key, lane2_val, lane2_ver))

    stats = jnp.stack([
        jnp.asarray(w, I32),
        committed.sum(dtype=I32),
        lock_rejected.sum(dtype=I32),
        logic_abort.sum(dtype=I32),
        magic_bad,
        bal_delta,
    ])
    if counters is not None:
        counters = mon.bump(counters, {
            mon.CTR_STEPS: 1,
            mon.CTR_TXN_ATTEMPTED: stats[STAT_ATTEMPTED],
            mon.CTR_TXN_COMMITTED: stats[STAT_COMMITTED],
            mon.CTR_AB_LOCK: stats[STAT_AB_LOCK],
            mon.CTR_AB_LOGIC: stats[STAT_AB_LOGIC],
            mon.CTR_MAGIC_BAD: magic_bad,
            mon.CTR_LOCK_REQUESTS: active.sum(dtype=I32),
            mon.CTR_LOCK_GRANTED: granted.sum(dtype=I32),
            mon.CTR_LOCK_REJECTED: (active & ~granted).sum(dtype=I32),
            mon.CTR_INSTALL_WRITES: do_write.sum(dtype=I32),
            mon.CTR_LOG_APPENDS: do_write.sum(dtype=I32),
            mon.CTR_DISPATCH_XLA: 1,
        })
        return stacked, stats, counters
    return stacked, stats


@memoize_builder
def build_runner(n_accounts: int, w: int = 4096,
                 cohorts_per_block: int = 8, monitor: bool = False):
    """jit(scan(cohort_step)): one dispatch runs `cohorts_per_block` cohorts.

    Returns run(stacked, key) -> (stacked', stats [cohorts_per_block, N_STATS]).
    State is donated — tables update in place in HBM.

    ``monitor``: thread the dintmon counter plane — the carry becomes
    (stacked, counters) and run returns it updated; off (default) =
    contract and jaxpr unchanged.
    """
    step = functools.partial(cohort_step, w=w, n_accounts=n_accounts)

    def scan_fn(carry, key):
        if monitor:
            stacked, cnt = carry
            stacked, stats, cnt = step(stacked, key, counters=cnt)
            return (stacked, cnt), stats
        stacked, stats = step(carry, key)
        return stacked, stats

    def block(carry, key):
        keys = jax.random.split(key, cohorts_per_block)
        return jax.lax.scan(scan_fn, carry, keys)

    return jax.jit(block, donate_argnums=0)
