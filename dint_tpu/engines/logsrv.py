"""log_server: batched replication-log append server.

TPU equivalent of the reference's in-XDP log append
(log_server/ebpf/ls_kern.c:40-78: parse, pick per-CPU ring, append, ACK).
Appends land in multi-lane HBM rings (tables.log); a batch's appends are a
single conflict-free scatter.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tables import log as logring
from .types import Batch, Op, Replies, Reply

I32 = jnp.int32
U32 = jnp.uint32


def step(ring: logring.LogRing, batch: Batch):
    do = batch.op == Op.LOG_APPEND
    is_del = jnp.zeros_like(batch.op)
    ring, _, _ = logring.append(ring, do, batch.table, is_del,
                                batch.key_hi, batch.key_lo, batch.ver, batch.val)
    rtype = jnp.where(do, I32(Reply.ACK), I32(Reply.NONE))
    return ring, Replies(rtype=rtype, val=jnp.zeros_like(batch.val),
                         ver=jnp.zeros_like(batch.ver))
