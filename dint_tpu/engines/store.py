"""store: batched KV server engine (GET/SET/INSERT/DELETE).

TPU equivalent of the reference's store servers — the XDP fast path
(store/ebpf/store_kern.c:32-300: parse, hash, CAS entry lock, slot scan,
reply) and the userspace KVS fallback (store/ebpf/kvs.h) — collapsed into one
batched state machine over an HBM-resident table that holds the whole
keyspace.

Batch semantics (the serialization contract, also implemented by the
sequential oracle in dint_tpu.testing.oracle):
  * Per key, a batch is processed as: all GETs first (they see pre-batch
    state), then writes in arrival (lane) order. This is a valid serial
    order; clients cannot distinguish it from the reference's
    packet-arrival interleaving.
  * SET/INSERT are upserts; each bumps the version by 1. DELETE invalidates.
  * Replies: GET -> VAL(val, ver) or NOT_EXIST; SET/INSERT -> ACK(new ver);
    DELETE -> ACK or NOT_EXIST; bucket overflow on insert -> SPILL (the host
    overflow store takes the key; the reference instead runs an
    eviction/miss protocol through userspace, store/ebpf/store_kern.c:208-246).
  * RETRY (reference entry-spinlock busy) is never emitted: there are no
    spinlocks to lose.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from ..ops import hashing, segments
from ..ops import pallas_gather as pg
from ..tables import kv
from .types import Batch, Op, Replies, Reply

I32 = jnp.int32
U32 = jnp.uint32


@flax.struct.dataclass
class HotKV:
    """dintcache hot tier for the store engine: a key-indexed write-through
    mirror of keys (0, k) with key_lo < hot_n and key_hi == 0 — the head
    of the store benchmark's Zipfian distribution, whose rank IS the key
    id (clients/workloads.zipf_keys). The mirror replaces the val/ver
    gathers of the probe for hot lanes (a VMEM-resident small array in
    the pallas kernel, a small-array gather on XLA); installs write
    through, so mirror == table for every key the probe can hit. Mirror
    entries of ABSENT keys are stale by design: every consumer of
    val0/ver0 in step() is masked by hit0."""
    val: jax.Array    # u32 [hot_n * VW]
    ver: jax.Array    # u32 [hot_n]

    @property
    def hot_n(self):
        return self.ver.shape[0]


def attach_hot(table: kv.KVTable, hot_n: int) -> HotKV:
    """Build the hot mirror for key ids [0, hot_n) from the current table
    (one batched probe; run after populate)."""
    hot_n = max(int(hot_n), 1)
    klo = jnp.arange(hot_n, dtype=U32)
    khi = jnp.zeros((hot_n,), U32)
    b1, b2 = hashing.bucket_pair(khi, klo, table.n_buckets)
    hit, _, _, val, ver, _, _ = kv.probe(table, khi, klo, b1, b2)
    return HotKV(val=jnp.where(hit[:, None], val, U32(0)).reshape(-1),
                 ver=jnp.where(hit, ver, U32(0)))


def step(table: kv.KVTable, batch: Batch, *, maintain_bloom: bool = False,
         hot: HotKV | None = None, use_pallas: bool = False):
    """One server step: certify and apply a batch. Returns (table', replies)
    — or (table', replies, hot') when the dintcache hot tier is threaded.

    ``maintain_bloom`` (static) keeps per-bucket bloom filters exact across
    inserts/deletes. The full-table fast path doesn't need them (probe() is
    exact); they exist for cache-mode parity with the reference's negative
    lookups (store/ebpf/store_kern.c:88-95) and cost a hash per slot per
    touched bucket, so they're off by default.

    ``hot`` (a HotKV, or None = off): serve hot keys' val/ver reads from
    the mirror and write installs through to it — replies and table are
    bit-identical to the default path (tests/test_hotset.py).
    ``use_pallas`` (static) routes the partitioned gathers/install
    through the ops/pallas_gather hot kernels.
    """
    r = batch.width
    sb = segments.sort_batch(batch.key_hi, batch.key_lo)
    op = batch.op[sb.perm]
    val_in = batch.val[sb.perm]

    b1, b2 = hashing.bucket_pair(sb.key_hi, sb.key_lo, table.n_buckets)
    if hot is None:
        hit0, fbkt, slot0, val0, ver0, free1, free2 = kv.probe(
            table, sb.key_hi, sb.key_lo, b1, b2)
    else:
        hot_n = hot.hot_n
        vw = table.val_words
        hit0, fbkt, slot0, free1, free2 = kv.probe_loc(
            table, sb.key_hi, sb.key_lo, b1, b2)
        eidx0 = fbkt * table.slots + slot0
        kmidx = jnp.where((sb.key_hi == U32(0))
                          & (sb.key_lo < U32(hot_n)),
                          sb.key_lo.astype(I32), -1)
        val0 = pg.hot_gather(table.val, hot.val, eidx0, kmidx, vw,
                             use_pallas=use_pallas).reshape(r, vw)
        ver0 = pg.hot_gather(table.ver, hot.ver, eidx0, kmidx, 1,
                             use_pallas=use_pallas)
    # insert destination: the emptier of the two candidate buckets
    dest = jnp.where(free2 > free1, b2, b1)
    bkt = jnp.where(hit0, fbkt, dest)
    alt = jnp.where(hit0, fbkt, b1 + b2 - dest)   # the other candidate

    is_get = op == Op.GET
    is_install = (op == Op.SET) | (op == Op.INSERT)
    is_delete = op == Op.DELETE
    is_write = is_install | is_delete

    n_inst_before = segments.seg_cumsum_excl(sb, is_install.astype(I32))
    n_inst_total = segments.seg_sum(sb, is_install.astype(I32))
    last_w_rank = segments.seg_max_where(sb, is_write, sb.rank, I32(-1))
    pos_last = jnp.clip(sb.head_pos + last_w_rank, 0, r - 1)
    last_is_del = is_delete[pos_last]
    last_val = val_in[pos_last]

    ver0_eff = jnp.where(hit0, ver0, U32(0))
    any_write = last_w_rank >= 0
    final_exists = jnp.where(any_write, ~last_is_del, hit0)
    final_ver = ver0_eff + n_inst_total.astype(U32)

    # ---- replies (sorted space) -------------------------------------------
    # exact sequential existence at each write's point: the latest write
    # before me in my segment decides, else pre-batch state
    idx = jnp.arange(r, dtype=I32)
    w_pos = jax.lax.cummax(jnp.where(is_write, idx, I32(-1)))
    prev_w_pos = jnp.concatenate([jnp.full((1,), -1, I32), w_pos[:-1]])
    in_seg = prev_w_pos >= sb.head_pos
    existed_here = jnp.where(in_seg, is_install[jnp.clip(prev_w_pos, 0, r - 1)], hit0)
    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where(is_get, jnp.where(hit0, Reply.VAL, Reply.NOT_EXIST), rtype)
    rtype = jnp.where(is_install, Reply.ACK, rtype)
    rtype = jnp.where(is_delete,
                      jnp.where(existed_here, Reply.ACK, Reply.NOT_EXIST), rtype)
    rval = jnp.where(is_get[:, None] & hit0[:, None], val0, jnp.zeros_like(val0))
    rver = jnp.where(is_get, jnp.where(hit0, ver0, U32(0)), U32(0))
    rver = jnp.where(is_install, ver0_eff + (n_inst_before + 1).astype(U32), rver)

    # ---- writer election: segment-last lane acts for its key -------------
    writer = sb.last & any_write
    w_upd = writer & final_exists & hit0
    w_alloc = writer & final_exists & ~hit0
    w_del = writer & ~final_exists & hit0

    # back to original order for phase B + scatters
    (o_upd, o_alloc, o_del, o_bkt, o_alt, o_slot0, o_ver) = segments.unsort(
        sb, w_upd, w_alloc, w_del, bkt, alt, slot0, final_ver)
    o_val = segments.unsort(sb, last_val)
    o_khi, o_klo = segments.unsort(sb, sb.key_hi, sb.key_lo)

    # ---- phase B: slot allocation for inserts, per destination bucket ----
    sb2 = segments.sort_batch(jnp.zeros((r,), U32), o_bkt.astype(U32))
    alloc2 = o_alloc[sb2.perm]
    rank_alloc = segments.seg_cumsum_excl(sb2, alloc2.astype(I32))
    bkt2 = o_bkt[sb2.perm]
    has2, slot_new2 = kv.nth_free_slot(
        table.valid[kv.bucket_rows(table, bkt2)], rank_alloc)
    ok2 = alloc2 & has2
    spill2 = alloc2 & ~has2
    ok, spill1, slot_new = segments.unsort(sb2, ok2, spill2, slot_new2)

    # ---- phase B2: overflow retries its ALTERNATE candidate bucket --------
    # (two-choice insert: only give up when both buckets are full). Ranks in
    # the alternate must skip slots phase B just handed out there.
    taken = jnp.zeros((table.n_buckets + 1,), I32).at[
        jnp.where(ok, o_bkt, table.n_buckets)].add(1, mode="drop")
    sb3 = segments.sort_batch(jnp.zeros((r,), U32), o_alt.astype(U32))
    retry3 = spill1[sb3.perm]
    rank3 = segments.seg_cumsum_excl(sb3, retry3.astype(I32)) + taken[o_alt[sb3.perm]]
    has3, slot_new3 = kv.nth_free_slot(
        table.valid[kv.bucket_rows(table, o_alt[sb3.perm])], rank3)
    ok3_s = retry3 & has3
    ok_alt, slot_alt = segments.unsort(sb3, ok3_s, slot_new3)
    spill = spill1 & ~ok_alt
    ok = ok | ok_alt
    o_bkt = jnp.where(ok_alt, o_alt, o_bkt)
    slot_new = jnp.where(ok_alt, slot_alt, slot_new)

    # spill => every install of that key failed: fix up replies for the whole
    # key segment (installs -> SPILL, deletes -> NOT_EXIST since nothing was
    # ever installed; GETs already answered from pre-state)
    seg_spill = segments.seg_any(sb, spill[sb.perm])
    rtype = jnp.where(seg_spill & is_install, I32(Reply.SPILL), rtype)
    rtype = jnp.where(seg_spill & is_delete, I32(Reply.NOT_EXIST), rtype)
    rver = jnp.where(seg_spill & is_install, U32(0), rver)

    # ---- scatters (flat 1-D unique-index: one writer per entry) ----------
    # NOTE on unique_indices=True + the OOB sentinel: every MASKED lane is
    # routed to the same out-of-bounds index (ne), so indices are only
    # unique among the lanes that actually write — duplicated OOB lanes
    # technically violate JAX's uniqueness contract (documented UB). We
    # rely on mode="drop" discarding OOB lanes before any dedup matters;
    # tests/test_ops.py::test_oob_dup_scatter_unique_indices pins this
    # lowering behavior so a jaxlib upgrade that changes it fails loudly
    # instead of corrupting tables. (Same pattern: tatp_dense.pipe_step
    # wflat / populate_device idx, smallbank_dense scatters.)
    ne = table.n_buckets * table.slots
    s = table.slots
    w_any_slot = o_upd | ok | o_del
    t_slot = jnp.where(o_upd | o_del, o_slot0, slot_new)
    e_any = jnp.where(w_any_slot, o_bkt * s + t_slot, ne)
    new_valid = table.valid.at[e_any].set(~o_del, mode="drop",
                                          unique_indices=True)
    wv = (o_upd | ok)
    sl_v = jnp.where(o_upd, o_slot0, slot_new)
    e_v = jnp.where(wv, o_bkt * s + sl_v, ne)
    if hot is None:
        val_new = table.val.at[kv.val_word_idx(table, e_v)].set(
            o_val.reshape(-1), mode="drop", unique_indices=True)
        ver_new = table.ver.at[e_v].set(o_ver, mode="drop",
                                        unique_indices=True)
    else:
        # write-through install: table entry AND key-indexed mirror (one
        # fused kernel on the pallas route). One writer per key segment,
        # so entry AND mirror indices are unique among masked lanes.
        w_midx = jnp.where(wv & (o_khi == U32(0))
                           & (o_klo < U32(hot_n)),
                           o_klo.astype(I32), -1)
        e_w = o_bkt * s + sl_v
        val_new, hot_val = pg.hot_scatter(
            table.val, hot.val, e_w, w_midx, wv, o_val.reshape(-1), vw,
            use_pallas=use_pallas)
        ver_new, hot_ver = pg.hot_scatter(
            table.ver, hot.ver, e_w, w_midx, wv, o_ver, 1,
            use_pallas=use_pallas)
        hot = hot.replace(val=hot_val, ver=hot_ver)
    table = table.replace(
        key_hi=table.key_hi.at[e_v].set(o_khi, mode="drop",
                                        unique_indices=True),
        key_lo=table.key_lo.at[e_v].set(o_klo, mode="drop",
                                        unique_indices=True),
        val=val_new,
        ver=ver_new,
        valid=new_valid,
    )
    if maintain_bloom:
        # recompute exactly for buckets whose membership changed
        table = kv.recompute_bloom(table, o_bkt, ok | o_del)

    o_rtype, o_rver = segments.unsort(sb, rtype, rver)
    o_rval = segments.unsort(sb, rval)
    replies = Replies(rtype=o_rtype, val=o_rval, ver=o_rver)
    if hot is not None:
        return table, replies, hot
    return table, replies
