"""store: batched KV server engine (GET/SET/INSERT/DELETE).

TPU equivalent of the reference's store servers — the XDP fast path
(store/ebpf/store_kern.c:32-300: parse, hash, CAS entry lock, slot scan,
reply) and the userspace KVS fallback (store/ebpf/kvs.h) — collapsed into one
batched state machine over an HBM-resident table that holds the whole
keyspace.

Batch semantics (the serialization contract, also implemented by the
sequential oracle in dint_tpu.testing.oracle):
  * Per key, a batch is processed as: all GETs first (they see pre-batch
    state), then writes in arrival (lane) order. This is a valid serial
    order; clients cannot distinguish it from the reference's
    packet-arrival interleaving.
  * SET/INSERT are upserts; each bumps the version by 1. DELETE invalidates.
  * Replies: GET -> VAL(val, ver) or NOT_EXIST; SET/INSERT -> ACK(new ver);
    DELETE -> ACK or NOT_EXIST; bucket overflow on insert -> SPILL (the host
    overflow store takes the key; the reference instead runs an
    eviction/miss protocol through userspace, store/ebpf/store_kern.c:208-246).
  * RETRY (reference entry-spinlock busy) is never emitted: there are no
    spinlocks to lose.
"""
from __future__ import annotations

import functools

import flax.struct
import jax
import jax.numpy as jnp

from ..monitor import waves
from ..ops import hashing, segments
from ..ops import pallas_gather as pg
from ..tables import kv
from ..tables import run as run_mod
from .types import Batch, Op, Replies, Reply, ScanReplies

I32 = jnp.int32
U32 = jnp.uint32


@flax.struct.dataclass
class HotKV:
    """dintcache hot tier for the store engine: a key-indexed write-through
    mirror of keys (0, k) with key_lo < hot_n and key_hi == 0 — the head
    of the store benchmark's Zipfian distribution, whose rank IS the key
    id (clients/workloads.zipf_keys). The mirror replaces the val/ver
    gathers of the probe for hot lanes (a VMEM-resident small array in
    the pallas kernel, a small-array gather on XLA); installs write
    through, so mirror == table for every key the probe can hit. Mirror
    entries of ABSENT keys are stale by design: every consumer of
    val0/ver0 in step() is masked by hit0."""
    val: jax.Array    # u32 [hot_n * VW]
    ver: jax.Array    # u32 [hot_n]

    @property
    def hot_n(self):
        return self.ver.shape[0]


def attach_hot(table: kv.KVTable, hot_n: int) -> HotKV:
    """Build the hot mirror for key ids [0, hot_n) from the current table
    (one batched probe; run after populate)."""
    hot_n = max(int(hot_n), 1)
    klo = jnp.arange(hot_n, dtype=U32)
    khi = jnp.zeros((hot_n,), U32)
    b1, b2 = hashing.bucket_pair(khi, klo, table.n_buckets)
    hit, _, _, val, ver, _, _ = kv.probe(table, khi, klo, b1, b2)
    return HotKV(val=jnp.where(hit[:, None], val, U32(0)).reshape(-1),
                 ver=jnp.where(hit, ver, U32(0)))


def step(table: kv.KVTable, batch: Batch, *, maintain_bloom: bool = False,
         hot: HotKV | None = None, use_pallas: bool = False,
         run: run_mod.OrderedRun | None = None, scan_max: int = 8):
    """One server step: certify and apply a batch. Returns (table', replies)
    — plus `hot'` when the dintcache hot tier is threaded, plus
    `(run', scan_replies)` when the dintscan ordered run is threaded
    (in that order: table, replies[, hot][, run, scan_replies]).

    ``maintain_bloom`` (static) keeps per-bucket bloom filters exact across
    inserts/deletes. The full-table fast path doesn't need them (probe() is
    exact); they exist for cache-mode parity with the reference's negative
    lookups (store/ebpf/store_kern.c:88-95) and cost a hash per slot per
    touched bucket, so they're off by default.

    ``hot`` (a HotKV, or None = off): serve hot keys' val/ver reads from
    the mirror and write installs through to it — replies and table are
    bit-identical to the default path (tests/test_hotset.py).
    ``use_pallas`` (static) routes the partitioned gathers/install
    through the ops/pallas_gather hot kernels, and the scan window
    through the streaming scan_rows kernel.

    ``run`` (a tables.run.OrderedRun, or None = off): serve Op.SCAN lanes
    from the ordered run's merged run∪delta view — scans are phase-1
    reads, so like GETs they see PRE-batch state — and write this batch's
    effective installs/deletes through to the run's delta overlay. The
    lane's Replies slot carries VAL + the row count in `ver` (RETRY when
    the run is stale); rows land in the ScanReplies slab, at most
    ``scan_max`` (static) per lane, request length in ``batch.ver``.
    """
    r = batch.width
    sb = segments.sort_batch(batch.key_hi, batch.key_lo)
    op = batch.op[sb.perm]
    val_in = batch.val[sb.perm]

    b1, b2 = hashing.bucket_pair(sb.key_hi, sb.key_lo, table.n_buckets)
    with waves.scope("store", "probe"):
        if hot is None:
            hit0, fbkt, slot0, val0, ver0, free1, free2 = kv.probe(
                table, sb.key_hi, sb.key_lo, b1, b2)
        else:
            hot_n = hot.hot_n
            vw = table.val_words
            hit0, fbkt, slot0, free1, free2 = kv.probe_loc(
                table, sb.key_hi, sb.key_lo, b1, b2)
            eidx0 = fbkt * table.slots + slot0
            kmidx = jnp.where((sb.key_hi == U32(0))
                              & (sb.key_lo < U32(hot_n)),
                              sb.key_lo.astype(I32), -1)
            val0 = pg.hot_gather(table.val, hot.val, eidx0, kmidx, vw,
                                 use_pallas=use_pallas).reshape(r, vw)
            ver0 = pg.hot_gather(table.ver, hot.ver, eidx0, kmidx, 1,
                                 use_pallas=use_pallas)
    # insert destination: the emptier of the two candidate buckets
    dest = jnp.where(free2 > free1, b2, b1)
    bkt = jnp.where(hit0, fbkt, dest)
    alt = jnp.where(hit0, fbkt, b1 + b2 - dest)   # the other candidate

    is_get = op == Op.GET
    is_install = (op == Op.SET) | (op == Op.INSERT)
    is_delete = op == Op.DELETE
    is_write = is_install | is_delete

    n_inst_before = segments.seg_cumsum_excl(sb, is_install.astype(I32))
    n_inst_total = segments.seg_sum(sb, is_install.astype(I32))
    last_w_rank = segments.seg_max_where(sb, is_write, sb.rank, I32(-1))
    pos_last = jnp.clip(sb.head_pos + last_w_rank, 0, r - 1)
    last_is_del = is_delete[pos_last]
    last_val = val_in[pos_last]

    ver0_eff = jnp.where(hit0, ver0, U32(0))
    any_write = last_w_rank >= 0
    final_exists = jnp.where(any_write, ~last_is_del, hit0)
    final_ver = ver0_eff + n_inst_total.astype(U32)

    # ---- replies (sorted space) -------------------------------------------
    # exact sequential existence at each write's point: the latest write
    # before me in my segment decides, else pre-batch state
    idx = jnp.arange(r, dtype=I32)
    w_pos = jax.lax.cummax(jnp.where(is_write, idx, I32(-1)))
    prev_w_pos = jnp.concatenate([jnp.full((1,), -1, I32), w_pos[:-1]])
    in_seg = prev_w_pos >= sb.head_pos
    existed_here = jnp.where(in_seg, is_install[jnp.clip(prev_w_pos, 0, r - 1)], hit0)
    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where(is_get, jnp.where(hit0, Reply.VAL, Reply.NOT_EXIST), rtype)
    rtype = jnp.where(is_install, Reply.ACK, rtype)
    rtype = jnp.where(is_delete,
                      jnp.where(existed_here, Reply.ACK, Reply.NOT_EXIST), rtype)
    rval = jnp.where(is_get[:, None] & hit0[:, None], val0, jnp.zeros_like(val0))
    rver = jnp.where(is_get, jnp.where(hit0, ver0, U32(0)), U32(0))
    rver = jnp.where(is_install, ver0_eff + (n_inst_before + 1).astype(U32), rver)

    # ---- writer election: segment-last lane acts for its key -------------
    writer = sb.last & any_write
    w_upd = writer & final_exists & hit0
    w_alloc = writer & final_exists & ~hit0
    w_del = writer & ~final_exists & hit0

    # back to original order for phase B + scatters
    (o_upd, o_alloc, o_del, o_bkt, o_alt, o_slot0, o_ver) = segments.unsort(
        sb, w_upd, w_alloc, w_del, bkt, alt, slot0, final_ver)
    o_val = segments.unsort(sb, last_val)
    o_khi, o_klo = segments.unsort(sb, sb.key_hi, sb.key_lo)

    # ---- phase B: slot allocation for inserts, per destination bucket ----
    sb2 = segments.sort_batch(jnp.zeros((r,), U32), o_bkt.astype(U32))
    alloc2 = o_alloc[sb2.perm]
    rank_alloc = segments.seg_cumsum_excl(sb2, alloc2.astype(I32))
    bkt2 = o_bkt[sb2.perm]
    has2, slot_new2 = kv.nth_free_slot(
        table.valid[kv.bucket_rows(table, bkt2)], rank_alloc)
    ok2 = alloc2 & has2
    spill2 = alloc2 & ~has2
    ok, spill1, slot_new = segments.unsort(sb2, ok2, spill2, slot_new2)

    # ---- phase B2: overflow retries its ALTERNATE candidate bucket --------
    # (two-choice insert: only give up when both buckets are full). Ranks in
    # the alternate must skip slots phase B just handed out there.
    taken = jnp.zeros((table.n_buckets + 1,), I32).at[
        jnp.where(ok, o_bkt, table.n_buckets)].add(1, mode="drop")
    sb3 = segments.sort_batch(jnp.zeros((r,), U32), o_alt.astype(U32))
    retry3 = spill1[sb3.perm]
    rank3 = segments.seg_cumsum_excl(sb3, retry3.astype(I32)) + taken[o_alt[sb3.perm]]
    has3, slot_new3 = kv.nth_free_slot(
        table.valid[kv.bucket_rows(table, o_alt[sb3.perm])], rank3)
    ok3_s = retry3 & has3
    ok_alt, slot_alt = segments.unsort(sb3, ok3_s, slot_new3)
    spill = spill1 & ~ok_alt
    ok = ok | ok_alt
    o_bkt = jnp.where(ok_alt, o_alt, o_bkt)
    slot_new = jnp.where(ok_alt, slot_alt, slot_new)

    # spill => every install of that key failed: fix up replies for the whole
    # key segment (installs -> SPILL, deletes -> NOT_EXIST since nothing was
    # ever installed; GETs already answered from pre-state)
    seg_spill = segments.seg_any(sb, spill[sb.perm])
    rtype = jnp.where(seg_spill & is_install, I32(Reply.SPILL), rtype)
    rtype = jnp.where(seg_spill & is_delete, I32(Reply.NOT_EXIST), rtype)
    rver = jnp.where(seg_spill & is_install, U32(0), rver)

    # ---- scatters (flat 1-D unique-index: one writer per entry) ----------
    # NOTE on unique_indices=True + the OOB sentinel: every MASKED lane is
    # routed to the same out-of-bounds index (ne), so indices are only
    # unique among the lanes that actually write — duplicated OOB lanes
    # technically violate JAX's uniqueness contract (documented UB). We
    # rely on mode="drop" discarding OOB lanes before any dedup matters;
    # tests/test_ops.py::test_oob_dup_scatter_unique_indices pins this
    # lowering behavior so a jaxlib upgrade that changes it fails loudly
    # instead of corrupting tables. (Same pattern: tatp_dense.pipe_step
    # wflat / populate_device idx, smallbank_dense scatters.)
    ne = table.n_buckets * table.slots
    s = table.slots
    w_any_slot = o_upd | ok | o_del
    t_slot = jnp.where(o_upd | o_del, o_slot0, slot_new)
    with waves.scope("store", "install"):
        e_any = jnp.where(w_any_slot, o_bkt * s + t_slot, ne)
        new_valid = table.valid.at[e_any].set(~o_del, mode="drop",
                                              unique_indices=True)
        wv = (o_upd | ok)
        sl_v = jnp.where(o_upd, o_slot0, slot_new)
        e_v = jnp.where(wv, o_bkt * s + sl_v, ne)
        if hot is None:
            val_new = table.val.at[kv.val_word_idx(table, e_v)].set(
                o_val.reshape(-1), mode="drop", unique_indices=True)
            ver_new = table.ver.at[e_v].set(o_ver, mode="drop",
                                            unique_indices=True)
        else:
            # write-through install: table entry AND key-indexed mirror (one
            # fused kernel on the pallas route). One writer per key segment,
            # so entry AND mirror indices are unique among masked lanes.
            w_midx = jnp.where(wv & (o_khi == U32(0))
                               & (o_klo < U32(hot_n)),
                               o_klo.astype(I32), -1)
            e_w = o_bkt * s + sl_v
            val_new, hot_val = pg.hot_scatter(
                table.val, hot.val, e_w, w_midx, wv, o_val.reshape(-1), vw,
                use_pallas=use_pallas)
            ver_new, hot_ver = pg.hot_scatter(
                table.ver, hot.ver, e_w, w_midx, wv, o_ver, 1,
                use_pallas=use_pallas)
            hot = hot.replace(val=hot_val, ver=hot_ver)
        table = table.replace(
            key_hi=table.key_hi.at[e_v].set(o_khi, mode="drop",
                                            unique_indices=True),
            key_lo=table.key_lo.at[e_v].set(o_klo, mode="drop",
                                            unique_indices=True),
            val=val_new,
            ver=ver_new,
            valid=new_valid,
        )
    if maintain_bloom:
        # recompute exactly for buckets whose membership changed
        table = kv.recompute_bloom(table, o_bkt, ok | o_del)

    o_rtype, o_rver = segments.unsort(sb, rtype, rver)
    o_rval = segments.unsort(sb, rval)

    # ---- dintscan: Op.SCAN lanes answered from the PRE-batch run∪delta ----
    # view (a valid serial order: scans sit in phase 1 with the GETs), then
    # this batch's effective writes — exactly the lanes the scatters above
    # installed (spilled inserts never reach table OR overlay) — write
    # through to the delta overlay, keeping run∪delta == table.
    scan_rep = None
    if run is not None:
        vw = table.val_words
        assert run.cap == ne and run.val_words == vw, \
            "run must be from_table-shaped for this table"
        lg_win = scan_max + run.delta_cap
        assert ne >= lg_win, "table too small for scan_max + delta_cap"
        is_scan = batch.op == Op.SCAN
        with waves.scope("store", "scan_locate"):
            off = run_mod.locate(run, batch.key_hi, batch.key_lo)
        # clamp so EVERY route gathers the identical in-bounds window
        # (coverage: clamping only moves the window start DOWN, and rows
        # below the lower bound are filtered by the >= start-key check)
        off_c = jnp.clip(off, 0, ne - lg_win)
        with waves.scope("store", "scan"):
            s_hi, s_lo, s_ver, s_val = pg.scan_slab(
                run.key_hi, run.key_lo, run.ver, run.val, off_c, lg_win,
                vw, use_pallas=use_pallas)
            # stale overlay => overflowed => the merged view may be missing
            # writes: answer no rows, reply RETRY (re-send after rebuild)
            slen = jnp.where(is_scan & ~run.stale,
                             jnp.clip(batch.ver.astype(I32), 0, scan_max),
                             I32(0))
            count, k_hi, k_lo, k_ver, k_val, d_hits = run_mod.merge_scan(
                run, s_hi, s_lo, s_ver, s_val, off_c,
                batch.key_hi, batch.key_lo, slen, scan_max)
        scan_rep = ScanReplies(key_hi=k_hi, key_lo=k_lo, ver=k_ver,
                               val=k_val, count=count, delta_hits=d_hits)
        o_rtype = jnp.where(is_scan,
                            jnp.where(run.stale, I32(Reply.RETRY),
                                      I32(Reply.VAL)), o_rtype)
        o_rver = jnp.where(is_scan, count.astype(U32), o_rver)
        o_rval = jnp.where(is_scan[:, None], U32(0), o_rval)
        with waves.scope("store", "delta_append"):
            run = run_mod.delta_append(
                run, o_khi, o_klo, o_ver, o_val.reshape(-1), o_del,
                o_upd | ok | o_del)

    replies = Replies(rtype=o_rtype, val=o_rval, ver=o_rver)
    out = (table, replies)
    if hot is not None:
        out = out + (hot,)
    if run is not None:
        out = out + (run, scan_rep)
    return out


def rebuild_run(table: kv.KVTable, run: run_mod.OrderedRun):
    """Drain-boundary run maintenance (serve plane): merge-compact the
    delta overlay into the run — or re-snapshot from the table when the
    overlay went stale. Scoped as the dint.store.run_rebuild wave."""
    with waves.scope("store", "run_rebuild"):
        return run_mod.refresh(table, run)


# ------------------------------------------------------------- dintserve

STORE_MAGIC = 0x55AA   # val word1 of populated rows (clients/micro.py)


def build_serve_runner(n_keys: int, w: int = 4096,
                       cohorts_per_block: int = 8, val_words: int = 10,
                       read_frac: float = 0.5, scan_frac: float = 0.0,
                       max_scan_len: int = 8, scan_max: int = 8,
                       delta_cap: int | None = None,
                       hot_frac: float | None = None,
                       hot_prob: float | None = None,
                       use_pallas=None, use_scan=None,
                       monitor: bool = False, trace=None,
                       serve: bool = False):
    """Serve-plane runner for the store engine (dintscan's host workload):
    jit(scan(step)) over carry (table[, run][, counters]). Returns
    (run, init, drain) under the ServeEngine contract:
      run(carry, key[, occ, shed]) -> (carry', stats [cohorts_per_block, 2])
      init(db)   -> carry (attaches the ordered run when use_scan)
      drain(carry) -> (db, stats [1, 2][, counters])

    Cohorts are generated ON DEVICE from the block key: YCSB-E-shaped —
    ``scan_frac`` of lanes issue Op.SCAN with uniform lengths in
    [1, max_scan_len] (engine clips to ``scan_max``); the rest split
    ``read_frac`` GET / else SET, keys drawn with the store benchmark's
    hot-prefix skew (hot head == smallest ids, the zipf_keys alignment).
    Stats rows are (attempted, committed): attempted = admitted lanes,
    committed = VAL/ACK replies (stale-scan RETRYs are NOT committed —
    the client re-sends after the rebuild).

    ``use_scan``: None = honor DINT_USE_SCAN. Threads the ordered-run
    snapshot + delta overlay through every step and merge-compacts it
    at each block's drain boundary (dint.store.run_rebuild) — the run
    stays sorted without ever stalling the step. Off: Op.SCAN is never
    generated and the carry/jaxpr are unchanged from the point engine.

    ``use_pallas``: None = honor DINT_USE_PALLAS; gates BOTH the point
    gathers and the sequential-DMA scan_rows kernel (probe-and-degrade:
    a Mosaic rejection of the scan kernel at this geometry falls back
    to the XLA slab route, bit-identical by contract).

    ``serve``: variable-occupancy mode — run takes occ/shed i32
    [cohorts_per_block]; lanes >= occ are masked to NOP/PAD before the
    step (padded lanes, the serve reconciliation identity).
    ``trace`` is accepted for contract uniformity and ignored: the
    store engine has no txn ring.
    """
    del trace
    from ..clients import workloads as wl
    from ..monitor import counters as mon
    use_scan = pg.resolve_use_scan(use_scan)
    use_pallas = pg.resolve_use_pallas(use_pallas, n_idx=w, m_lock=None)
    hfrac = wl.SB_HOT_FRAC if hot_frac is None else float(hot_frac)
    hprob = wl.SB_HOT_PROB if hot_prob is None else float(hot_prob)
    hot_n = max(1, min(int(n_keys * hfrac), n_keys))
    if not use_scan:
        scan_frac = 0.0

    def gen(key, occ):
        """One on-device cohort: (Batch, admitted, n_scan_lanes)."""
        ks = jax.random.split(key, 6)
        lane = jnp.arange(w, dtype=I32)
        admitted = lane < occ
        is_scan = (jax.random.uniform(ks[0], (w,)) < scan_frac) \
            if scan_frac > 0.0 else jnp.zeros((w,), bool)
        is_get = ~is_scan & (jax.random.uniform(ks[1], (w,)) < read_frac)
        hot = jax.random.uniform(ks[2], (w,)) < hprob
        klo = jnp.where(
            hot, jax.random.randint(ks[3], (w,), 1, hot_n + 1),
            jax.random.randint(ks[4], (w,), 1, n_keys + 1)).astype(U32)
        op = jnp.where(is_scan, I32(Op.SCAN),
                       jnp.where(is_get, I32(Op.GET), I32(Op.SET)))
        op = jnp.where(admitted, op, I32(Op.NOP))
        klo = jnp.where(admitted, klo, U32(0xFFFFFFFF))
        khi = jnp.where(admitted, U32(0), U32(0xFFFFFFFF))
        val = jnp.zeros((w, val_words), U32)
        val = val.at[:, 0].set(klo).at[:, 1].set(U32(STORE_MAGIC))
        slen = jax.random.randint(ks[5], (w,), 1, max_scan_len + 1)
        ver = jnp.where(admitted & is_scan, slen.astype(U32), U32(0))
        batch = Batch(op=op, table=jnp.zeros((w,), I32), key_hi=khi,
                      key_lo=klo, val=val, ver=ver)
        return batch, admitted, (admitted & is_scan)

    def scan_fn(carry, x):
        key, occ, shed = x if serve else (x, None, None)
        occ = jnp.asarray(w, I32) if occ is None else occ
        shed = I32(0) if shed is None else shed
        table = carry[0]
        run = carry[1] if use_scan else None
        cnt = carry[-1] if monitor else None
        batch, admitted, scan_lanes = gen(key, occ)
        if use_scan:
            table, rep, run, srep = step(table, batch, run=run,
                                         scan_max=scan_max,
                                         use_pallas=use_pallas)
        else:
            table, rep = step(table, batch, use_pallas=use_pallas)
            srep = None
        committed = (admitted
                     & ((rep.rtype == Reply.VAL)
                        | (rep.rtype == Reply.ACK))).sum(dtype=I32)
        stats = jnp.stack([occ, committed])
        cnt = mon.bump(cnt, {
            mon.CTR_STEPS: 1,
            mon.CTR_SERVE_OCC_LANES: occ,
            mon.CTR_SERVE_PAD_LANES: jnp.asarray(w, I32) - occ,
            mon.CTR_SERVE_SHED_LANES: shed,
            (mon.CTR_DISPATCH_PALLAS if use_pallas
             else mon.CTR_DISPATCH_XLA): 1,
            **({mon.CTR_SCAN_REQUESTS: scan_lanes.sum(dtype=I32),
                mon.CTR_SCAN_ROWS: srep.count.sum(dtype=I32),
                mon.CTR_SCAN_DELTA_HITS: srep.delta_hits.sum(dtype=I32)}
               if use_scan else {}),
        })
        out = (table,) + ((run,) if use_scan else ()) \
            + ((cnt,) if monitor else ())
        return out, stats

    def _post(carry):
        # block drain boundary: fold the overlay back into the run so
        # the NEXT block's scans start from a fresh (never-stale) view
        if use_scan:
            carry = ((carry[0], rebuild_run(carry[0], carry[1]))
                     + carry[2:])
        return carry

    if serve:
        def block(carry, key, occ, shed):
            keys = jax.random.split(key, cohorts_per_block)
            carry, stats = jax.lax.scan(scan_fn, carry, (keys, occ, shed))
            return _post(carry), stats
    else:
        def block(carry, key):
            keys = jax.random.split(key, cohorts_per_block)
            carry, stats = jax.lax.scan(scan_fn, carry, keys)
            return _post(carry), stats

    def init(db):
        assert db.val_words == val_words, (db.val_words, val_words)
        base = (db,)
        if use_scan:
            ne = db.n_buckets * db.slots
            # default overlay: one wave's worth of distinct writes, NOT
            # table-sized — the scan coverage window is scan_max + dcap
            # rows per lane, so an oversized overlay quadratically
            # inflates merge_scan's [w, lg, dcap] overlay compare
            dcap = min(64, max(1, ne - scan_max)) if delta_cap is None \
                else int(delta_cap)
            assert ne >= scan_max + dcap, (ne, scan_max, dcap)
            base = base + (run_mod.from_table(db, delta_cap=dcap),)
        return base + ((mon.create(),) if monitor else ())

    @functools.partial(jax.jit, donate_argnums=0)
    def drain(carry):
        # nothing is in flight (the store step is unpipelined); the run
        # is derived state — dropped here, re-snapshot at next attach
        table = carry[0]
        cnt = carry[-1] if monitor else None
        zero = jnp.zeros((1, 2), I32)
        return (table, zero) + ((cnt,) if monitor else ())

    init.trace_cfg = None
    return jax.jit(block, donate_argnums=0), init, drain
