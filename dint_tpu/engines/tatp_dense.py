"""Sort-free dense TATP engine: the TPU-first fast path.

The generic engine (engines/tatp.py) resolves intra-batch conflicts with
64-bit sorts + segmented reductions over EVERY lane x 3 vmapped shard
replicas — protocol-faithful but ~200x off the reference's throughput
(tatp/ebpf/shard_kern.c:111-197 does one hash + one CAS per packet). This
module is the redesign that removes the sort entirely, exploiting three
structural facts the reference cannot:

1. **Every TATP table is dense-indexable.** SUBSCRIBER/SEC_SUBSCRIBER/
   ACCESS_INFO/SPECIAL_FACILITY index by s_id directly (tatp/caladan/
   tatp.h:28), and even CALL_FORWARDING's composite key
   ``s_id*12 + (sf_type-1)*3 + start_time/8`` is bounded by 12*(n_sub+1),
   so the "sparse" table is a dense array plus an `exists` bit. The
   reference hashes it (tatp/ebpf/shard_kern.c:61-108) only because its
   kvs.h is generic; no bloom filter is needed when lookups are exact.
   All 5 tables live in ONE flat row-id space:
   rows [0,p1) sub | [p1,2p1) sec | [2p1,6p1) ai | [6p1,10p1) sf |
   [10p1,22p1) cf, with row N as the gather/scatter sentinel for NOP lanes.

2. **The 3 servers' lock tables partition by key.** Locks for key k are
   only ever taken at server k%3 (tatp/caladan/client_ebpf_shard.cc:
   636-641), so the union of the 3 per-server lock arrays is one exact
   per-row lock bit — no routing, no hash conflation (exact locks also
   remove the reference's false REJECT_LOCK on hash collisions, the
   ablation its lock_kern.c instrumentation exists to measure).

3. **Replicas are bit-identical by construction.** Every certified write
   applies at primary + both backups (client_ebpf_shard.cc:779-900), so
   the single-chip engine stores table content ONCE and keeps the
   replication physical where it matters for recovery: the log x3
   (tables/log.RepLog packs 3 replica entries per slot). The multi-chip
   path (parallel/sharded.py) places real per-device replicas; a
   single-chip emulation holding 3 bit-identical copies in one HBM adds
   no fidelity — it only triples memory (measured: XLA tiles [N, 3, VW]
   u32 to 2 KB/row, 4.5 GB for the bench's 2.2M rows).

Per-row metadata packs into ONE u32 word (`meta`):

    bits [31:1] = ver   (monotonic: commit/insert/delete all bump it, so
                         OCC validate is an equality compare with no
                         delete/reinsert ABA window)
    bit  0      = exists

`meta` IS the value OCC validation compares — reads never observe locks,
exactly the reference's verify stage (client_ebpf_shard.cc:765-768),
because locks live in a SEPARATE step-stamped arbitration array (`arb`):

    arb[row] = step_granted << K_ARB | (2w-1 - winning_slot)

Every lock in the 3-stage pipeline has a FIXED lifetime — granted in
wave 1 of step t, released in wave 3 of step t+2 (commit, insert,
delete, and abort all release then) — so releases need no scatter at
all: a row is held iff ``(arb >> K_ARB) == step - 1``, and stamps from
step-2 or older have simply expired (the same expiring-stamp design as
smallbank_dense's S/X tables). This removes BOTH wave-3 release lanes
and the wave-1 grant scatter from the meta dependency chain: the table
chain is install-scatter -> gather (2 random ops) and the lock chain is
gather -> scatter-max -> gather (3 random ops) on an INDEPENDENT array,
so XLA overlaps them — measured on v5e, the serialized 5-op meta chain
was the step's critical path (PERF.md round 3).

Conflict resolution per fused step (replacing ops/segments.sort_batch):
  * commits: X-certified one-writer-per-row -> direct scatter.
  * lock acquires: first-slot-wins via scatter-MAX of the packed
    (step, inverted slot) stamp — the batched equivalent of the
    reference's CAS loop (shard_kern.c:251-297). Candidates targeting a
    HELD row (stamp == step-1) are masked out of the scatter, so a
    stream of rejected attempts cannot re-stamp (livelock) a hot row.
    Arbitration runs in [w, 2] write-slot space (2 lock slots per txn),
    measured 2x cheaper than arbitrating all [w, K] lanes.
  * reads/validates: pure gathers.

Scatter discipline (TPU, all measured on v5e): every scatter is 1-D or
row-major on axis 0 with ``unique_indices=True`` and masked lanes routed
OUT OF BOUNDS under ``mode="drop"`` — duplicate-index and multi-dim-index
scatters serialize, and uniqueness is guaranteed by certification (one
X-lock holder per row). Row N is a never-written sentinel that NOP lanes
gather from; OOB gather indices clip onto it.

The 3-stage software pipeline (wave 1 of cohort t + validate of t-1 +
commit of t-2 fused into ONE device program) is inherited from
engines/tatp_pipeline.py, which remains the semantics reference; its
gen_cohort (txn mix, NURand, lane layout) is reused verbatim.

Memory: ~22*(n_sub+1) rows; val dominates at N*VW u32 words in a tight
interleaved 1-D layout (40 B/row at VW=10 — see DenseDB.val). At the
reference's full n_sub=7e6 (tatp/caladan/tatp.h:28) that is ~6.2 GB val
+ 0.6 GB meta + the log — single-chip HBM, populated on device
(populate_device). The multi-chip shard path (parallel/dense_sharded.py)
multiplies throughput, not feasibility.
"""
from __future__ import annotations

import functools

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from ._memo import memoize_builder
from ..monitor import counters as mon
from ..monitor import txnevents as txe
from ..monitor import waves
from ..ops import pallas_gather as pg
from ..tables import log as logring
from . import tatp
from .types import Op, Reply
from .tatp_pipeline import K, MAGIC, N_SHARDS, classify_wave1, gen_cohort
from .tatp_pipeline import (STAT_ATTEMPTED, STAT_COMMITTED, STAT_AB_LOCK,     # noqa: F401 (re-exported)
                            STAT_AB_MISSING, STAT_AB_VALIDATE, STAT_MAGIC_BAD,
                            N_STATS)

I32 = jnp.int32
U32 = jnp.uint32

# arb stamp layout: step << K_ARB | (2w-1 - slot). Supports w <= 2^17 and
# 2^(32-K_ARB) = 16384 steps between rebases (build_pipelined_runner
# rebases the stamps when step approaches the limit).
K_ARB = 18
REBASE_AT = (1 << (32 - K_ARB)) - 4096


def _bases(p1: int) -> np.ndarray:
    """Flat row-id base per table id (tatp.SUBSCRIBER..tatp.CALL_FORWARDING)."""
    return np.cumsum([0, p1, p1, 4 * p1, 4 * p1]).astype(np.int32)


def n_rows(n_sub: int) -> int:
    return 22 * (n_sub + 1)


@flax.struct.dataclass
class DenseDB:
    """All 5 TATP tables + locks + logs in flat dense arrays (row N is the
    sentinel every NOP/padded lane gathers from; it is never written).

    ``val`` is a tight interleaved 1-D word array (row r's words at
    [r*VW, (r+1)*VW)) — NOT [N+1, VW]: XLA tiles a trailing dim of 10 to
    128 lanes (512 B/row), which put the reference's 7M-subscriber scale
    (tatp/caladan/tatp.h:28, 154M rows) at 79 GB. The 1-D layout is the
    same one the multi-chip backups always used
    (parallel/dense_sharded.ShardState) and costs 40 B/row: ~6.2 GB at
    7M subscribers, single-chip HBM."""
    val: jax.Array      # u32 [(N+1) * VW] interleaved; word0 payload, word1 magic
    meta: jax.Array     # u32 [N+1]      ver<<1 | exists
    arb: jax.Array      # u32 [N+1]      step-stamped lock arbitration word
    step: jax.Array     # u32 scalar, monotonic (starts at 2: stamp 0 is
                        #   "never held", so step-1 must never be 0)
    log: logring.RepLog   # 3 replica entries packed per slot (log x3)
    val_words: int = flax.struct.field(pytree_node=False, default=10)
    # dintcache hot tier (round 10; OFF by default — TATP is uniform, the
    # partition is exposed for skewed-TATP experiments): the hot set is
    # the flat ROW prefix [0, hot_n), which covers the subscriber-table
    # prefix — the table every transaction touches. hot_meta/hot_val are
    # physical write-through mirrors of that prefix; the arb prefix needs
    # no mirror (lock_arbitrate caches it in VMEM for the pass).
    hot_meta: jax.Array | None = None   # u32 [hot_n]
    hot_val: jax.Array | None = None    # u32 [hot_n * VW]
    hot_n: int = flax.struct.field(pytree_node=False, default=0)

    @property
    def n_sub(self):
        return self.meta.shape[0] // 22 - 1

    @property
    def val2d(self):
        """[..., N+1, VW] view for tests / recovery / oracles (materializes
        a tiled copy on device — NOT the hot path)."""
        return self.val.reshape(self.val.shape[:-1]
                                + (-1, self.val_words))

    # convenience views (tests / recovery / oracles — not the hot path)
    @property
    def ver(self):
        return self.meta >> 1

    @property
    def exists(self):
        return (self.meta & 1) != 0

    @property
    def locked(self):
        """Rows X-held RIGHT NOW: stamped by the previous step (stamps
        from step-2 and older have expired). Works on stacked
        [..., N+1] state too."""
        return (self.arb >> K_ARB) == (self.step[..., None] - 1)


def create(n_sub: int, val_words: int = 10, log_lanes: int = 16,
           log_capacity: int = 1 << 16,
           log_replicas: int = N_SHARDS) -> DenseDB:
    """``log_replicas``: the single-chip engine packs the log x3 locally;
    the multi-chip path (parallel/dense_sharded.py) passes 1 because the
    3 copies live on 3 devices there.

    ``log_capacity`` bounds the recovery window: the ring wraps like the
    reference's (ls_kern.c:72-73) and recover_* refuses a wrapped ring —
    at bench throughput the 1M-entry default wraps within ~1 s; pass a
    larger capacity when recovery artifacts are wanted."""
    n1 = n_rows(n_sub) + 1
    # flat word indices (row * VW + j) are computed in i32 on device
    assert n1 * val_words < (1 << 31), \
        f"n_sub={n_sub} x val_words={val_words} overflows i32 row*VW indices"
    return DenseDB(
        val=jnp.zeros((n1 * val_words,), U32),
        meta=jnp.zeros((n1,), U32),
        arb=jnp.zeros((n1,), U32),
        step=jnp.asarray(2, U32),
        log=logring.create_rep(log_lanes, log_capacity, val_words,
                               replicas=log_replicas),
        val_words=val_words,
    )


def populate(rng: np.random.Generator, n_sub: int, val_words: int = 10,
             **kw) -> DenseDB:
    """Same population as clients/tatp_client.populate_shards (reference
    populate: tatp/caladan/client_ebpf_shard.cc:96-341): all subscribers
    present, ai/sf types present w.p. 0.625 (>=1 each), CF rows on 25% of
    present sf rows per start_time; val word0 = row payload, word1 = magic
    (tatp/caladan/tatp.h:67-72)."""
    p1 = n_sub + 1
    db = create(n_sub, val_words=val_words, **kw)
    n1 = n_rows(n_sub) + 1
    base = _bases(p1)

    val = np.zeros((n1, val_words), np.uint32)
    meta = np.zeros(n1, np.uint32)

    def put(rows, payload):
        val[rows, 0] = payload.astype(np.uint32)
        val[rows, 1] = MAGIC
        meta[rows] = (1 << 1) | 1             # ver 1, exists

    s_ids = np.arange(1, p1)
    put(base[tatp.SUBSCRIBER] + s_ids, s_ids)
    put(base[tatp.SEC_SUBSCRIBER] + s_ids, s_ids)

    ai_present = rng.random((p1, 4)) < 0.625
    sf_present = rng.random((p1, 4)) < 0.625
    ai_present[0] = sf_present[0] = False
    ai_present[1:][ai_present[1:].sum(1) == 0, 0] = True
    sf_present[1:][sf_present[1:].sum(1) == 0, 0] = True
    ai_idx = np.nonzero(ai_present.reshape(-1))[0]
    sf_idx = np.nonzero(sf_present.reshape(-1))[0]
    put(base[tatp.ACCESS_INFO] + ai_idx, ai_idx)
    put(base[tatp.SPECIAL_FACILITY] + sf_idx, sf_idx)

    sfi, sft = np.nonzero(sf_present)
    cf_keys = []
    for st in (0, 8, 16):
        mask = rng.random(len(sfi)) < 0.25
        cf_keys.append(np.asarray(tatp.cf_key(sfi[mask], sft[mask] + 1, st)))
    cf_keys = np.unique(np.concatenate(cf_keys)).astype(np.int64)
    put(base[tatp.CALL_FORWARDING] + cf_keys, cf_keys)

    return db.replace(val=jnp.asarray(val.reshape(-1)),
                      meta=jnp.asarray(meta))


def populate_device(key, n_sub: int, val_words: int = 10, **kw) -> DenseDB:
    """On-device populate for reference-scale tables: same population RULES
    as `populate` (all subscribers present; ai/sf types present w.p. 0.625
    with >=1 each; CF on 25% of present sf rows per start_time —
    tatp/caladan/client_ebpf_shard.cc:96-341) drawn from the device RNG, so
    the 6+ GB val array at n_sub=7e6 is generated in HBM instead of being
    built in host numpy and pushed through the tunnel. Not bit-identical to
    the numpy path (different RNG stream); distribution-identical, which is
    what the abort-taxonomy expectations depend on."""
    p1 = n_sub + 1
    db = create(n_sub, val_words=val_words, **kw)
    n1 = n_rows(n_sub) + 1
    base = jnp.asarray(_bases(p1))

    @jax.jit
    def build(key):
        # every draw/temp here is deliberately 1-D: a (p1, 4) or (p1, 4, 3)
        # draw pads its minor dim up to 128 lanes under TPU tiling — at
        # p1=7e6 the (p1,4,3) bernoulli padded 42.7x to 13.35 GB and OOMed
        # the 16 GB chip AT COMPILE TIME (measured, round 5). Flat layouts
        # pad 1.0x; per-subscriber reductions use strided slices instead
        # of a trailing axis.
        k_ai, k_sf, k_cf = jax.random.split(key, 3)
        sub_e = jnp.arange(p1, dtype=I32) >= 1                  # [p1]

        def present(k):
            pr = jax.random.bernoulli(k, 0.625, (p1 * 4,))      # idx=s*4+t
            any4 = pr[0::4] | pr[1::4] | pr[2::4] | pr[3::4]
            pr = pr.at[0::4].set(pr[0::4] | ~any4)              # >=1 each
            # s = idx//4: 1-D gather instead of a [p1,4] broadcast
            return pr & sub_e[jnp.arange(p1 * 4, dtype=I32) // 4]

        ai_p = present(k_ai)                                    # [4*p1]
        sf_p = present(k_sf)
        # cf rows flat [12*p1]: idx = s*12 + (sf_type-1)*3 + start_time/8,
        # exactly tatp.cf_key's layout; idx//3 is the covering sf element
        cf_p = sf_p[jnp.arange(p1 * 12, dtype=I32) // 3] \
            & jax.random.bernoulli(k_cf, 0.25, (p1 * 12,))
        exists = jnp.concatenate([
            sub_e, sub_e, ai_p, sf_p, cf_p,
            jnp.zeros((1,), bool)])                             # [n1]
        meta = jnp.where(exists, U32((1 << 1) | 1), U32(0))

        # payload = index within the row's table region (populate's `put`);
        # 5 scalar compares instead of searchsorted's vmapped while loop
        rows = jnp.arange(n1, dtype=I32)
        region = sum((rows >= base[i]).astype(I32) for i in range(1, 5))
        payload = (rows - base[region]).astype(U32)
        val = jnp.zeros((n1 * val_words,), U32)
        idx = jnp.where(exists, rows, n1) * val_words   # absent -> dropped
        val = val.at[idx].set(payload, mode="drop", unique_indices=True)
        val = val.at[idx + 1].set(U32(MAGIC), mode="drop",
                                  unique_indices=True)
        return val, meta

    val, meta = build(key)
    return db.replace(val=val, meta=meta)


def attach_hotset(db: DenseDB, hot_rows: int) -> DenseDB:
    """Build the hot mirror for the flat row prefix [0, hot_rows) from the
    current tables (DenseDB docstring; skewed-TATP experiments)."""
    hot_rows = int(min(max(int(hot_rows), 1), n_rows(db.n_sub)))
    return db.replace(hot_meta=db.meta[:hot_rows],
                      hot_val=db.val[:hot_rows * db.val_words],
                      hot_n=hot_rows)


# ---------------------------------------------------------------- pipeline


@flax.struct.dataclass
class DenseCtx:
    """An in-flight cohort between pipeline stages (cf. tatp_pipeline.PipeCtx
    — row ids and versions are captured once at wave 1). Bootstrap cohorts
    have attempted == 0 and all-False masks."""
    rows: jax.Array       # i32 [w, K] flat row ids (sentinel for NOP lanes)
    is_read: jax.Array    # bool [w, K] OCC_READ lanes
    vv1: jax.Array        # u32 [w, K] meta (ver<<1|exists) at wave 1
    alive: jax.Array      # bool [w]
    ro_commit: jax.Array  # bool [w]
    granted: jax.Array    # bool [w, 2]
    ws_rows: jax.Array    # i32 [w, 2] write-slot row ids (sentinel if inactive)
    ws_vv: jax.Array      # u32 [w, 2] write-slot ver:exists at wave 1
    ws_tbl: jax.Array     # i32 [w, 2]
    ws_key: jax.Array     # i32 [w, 2] (logged key)
    ws_kind: jax.Array    # i32 [w, 2] 0 commit / 1 insert / 2 delete
    ws_active: jax.Array  # bool [w, 2]
    attempted: jax.Array  # i32 scalar
    ab_lock: jax.Array    # i32 scalar
    ab_missing: jax.Array # i32 scalar
    ab_validate: jax.Array  # i32 scalar
    magic_bad: jax.Array  # i32 scalar


def empty_ctx(w: int) -> DenseCtx:
    def z(shape, dt):
        return jnp.asarray(np.zeros(shape, dt))

    return DenseCtx(
        rows=z((w, K), np.int32), is_read=z((w, K), bool),
        vv1=z((w, K), np.uint32), alive=z((w,), bool),
        ro_commit=z((w,), bool), granted=z((w, 2), bool),
        ws_rows=z((w, 2), np.int32), ws_vv=z((w, 2), np.uint32),
        ws_tbl=z((w, 2), np.int32),
        ws_key=z((w, 2), np.int32), ws_kind=z((w, 2), np.int32),
        ws_active=z((w, 2), bool),
        attempted=z((), np.int32), ab_lock=z((), np.int32),
        ab_missing=z((), np.int32), ab_validate=z((), np.int32),
        magic_bad=z((), np.int32))


def _stats_of(c: DenseCtx):
    return jnp.stack([
        c.attempted, (c.ro_commit | c.alive).sum(dtype=I32),
        c.ab_lock, c.ab_missing, c.ab_validate, c.magic_bad])


@flax.struct.dataclass
class Installs:
    """Wave-3 install record of one step: what a backup replica must apply
    (parallel/dense_sharded.py ppermutes this to the +1/+2 devices — the
    reference's CommitBck x2 + CommitLog fan-out,
    client_ebpf_shard.cc:779-900). Rows are the emitting device's local
    ids; wmask marks real writes (releases are lock-only and stay local)."""
    wmask: jax.Array     # bool [2w]
    rows: jax.Array      # i32 [2w]
    meta: jax.Array      # u32 [2w]  new ver<<1|exists
    val: jax.Array       # u32 [2w, VW]
    tbl: jax.Array       # i32 [2w]  (for the log)
    key: jax.Array       # u32 [2w]
    is_del: jax.Array    # i32 [2w]
    ver: jax.Array       # u32 [2w]


def pipe_step(db: DenseDB, c1: DenseCtx, c2: DenseCtx, key, *, w: int,
              n_sub: int, val_words: int, gen_new: bool = True, mix=None,
              emit_installs: bool = False, check_magic: bool = True,
              use_pallas: bool = False, use_hotset: bool = False,
              use_fused: bool = False,
              occupancy: jax.Array | None = None,
              shed: jax.Array | None = None,
              counters: mon.Counters | None = None,
              ring: txe.TxnRing | None = None,
              tcfg: txe.TraceCfg | None = None):
    """One fused device step: commit wave of c2, validate wave of c1, and
    read+lock wave of a NEW cohort — ordered commits -> reads -> locks per
    row exactly like the generic engine's phase order (engines/tatp.
    _dense_step), so cohort t-2's installs are visible to t-1's validation
    and this step's reads, and its unlocks free rows for this step's lock
    acquires. Returns (db', new_ctx, c1', stats-of-c2), plus the Installs
    record when ``emit_installs`` (static) is set.

    ``use_pallas`` (static) routes the step's random-access hot ops through
    the Pallas DMA-ring kernels (ops/pallas_gather): the fused meta gather
    and the magic-word gather become ring gathers, and the 3-op lock chain
    (arb gather -> masked scatter-max -> winner gather-back) collapses into
    ONE fused kernel pass — shortening the step's random-access dependency
    chain from ~5 chained XLA ops to ~3. Outputs are bit-identical to the
    XLA path (tests/test_pallas_ops.py); builders resolve the flag via
    pg.resolve_use_pallas, which degrades to False when Mosaic rejects a
    kernel.

    ``use_hotset`` (static; OFF by default — TATP is uniform) serves the
    meta/magic gathers through the dintcache row-prefix partition (db must
    carry the mirror — attach_hotset), write-through at the wave-3
    installs, and caches the arb prefix in VMEM inside the fused lock
    pass. Bit-identical to the default path (tests/test_hotset.py);
    exposed for skewed-TATP experiments.

    ``use_fused`` (static; OFF by default) swallows wave pairs into the
    round-12 megakernels: lock arbitration + OCC validate-gather run as
    ONE lock_validate dispatch, and the install scatter + replication-log
    append run as ONE install_log scatter_streams dispatch — shortening
    the chain from ~6 dispatches to ~4. Bit-identical to the unfused path
    (tests/test_fused_ops.py); independent of ``use_pallas`` (the magic
    gather still dispatches by use_pallas) and composes with
    ``use_hotset`` (arb prefix stays VMEM-resident inside lock_validate;
    installs write through the mirrors as extra streams). Builders
    resolve via pg.resolve_use_fused (probe-and-degrade).

    ``occupancy``/``shed`` (device i32 scalars, or None = off): the
    dintserve variable-occupancy plane. Lanes >= occupancy of the freshly
    generated cohort are forced to no-ops (ops -> NOP, write slots
    deactivated) BEFORE wave 1, so a partially filled serving cohort
    certifies exactly the admitted prefix and ``attempted`` counts only
    real admissions; the value is a traced scalar, so ONE compiled step
    serves every occupancy at this width. ``shed`` mirrors the host-side
    SLO-shed tally onto the device ledger (counted like trace_dropped).
    At occupancy == w the masks are all-true and outputs are
    bit-identical to the closed-loop path (pinned in
    tests/test_dintserve.py). None (the default) adds nothing.

    ``counters`` (a monitor.Counters, or None = off): the device-resident
    counter plane. When threaded, the step bumps the dintmon registry
    in-step (txn outcomes from c2's completing stats, lock arbitration
    won-vs-lost for the new cohort, validate lanes/failures for c1,
    install/log counts, ring high-water, backend dispatch) with
    unique-index scatter-adds and returns the updated Counters appended
    to the result tuple. None (the default) threads no counter state and
    leaves the jaxpr untouched.

    ``ring``/``tcfg`` (monitor.txnevents): the dinttrace flight-recorder
    plane — the new cohort's lock verdicts and wave-1 outcomes, c1's
    validate verdicts and wave-2 outcomes, and c2's landing installs for
    the deterministically sampled txn-id subset, ONE scatter-add per
    step. The updated TxnRing is appended LAST (after Counters and the
    Installs record); None (default) adds nothing to the jaxpr."""
    p1 = n_sub + 1
    n1 = n_rows(n_sub) + 1
    sent = n1 - 1     # sentinel row: gathered by NOP lanes, never written
    oob = n1          # scatter index for masked lanes under mode="drop"
    base = jnp.asarray(_bases(p1))
    kg, kv3 = jax.random.split(key)
    t = db.step

    # ---- wave 3 of c2: install + log --------------------------------------
    # the meta scatter covers ONLY real writes: lock releases are implicit —
    # c2's stamps (from step t-2) expire this step, which is exactly when
    # COMMIT/INSERT/DELETE_PRIM and ABORT release the row lock in the
    # reference (shard_kern.c:338-476). Uniqueness: one X-holder per row,
    # and a txn's two slots target different tables.
    # MACHINE-CHECKED (dintlint protocol pass, ANALYSIS.md): wmask must
    # stay data-dependent on c2.alive — the chain grant -> alive ->
    # ~changed -> wmask is what proves lock-dominates-write and
    # validate-before-install; severing it fails the tier-1 gate.
    with waves.scope("tatp_dense",
                     "install_log" if use_fused else "install"):
        do_write = c2.ws_active & c2.alive[:, None]             # [w, 2]
        wmask = do_write.reshape(-1)
        wkind = c2.ws_kind.reshape(-1)
        newex = (wkind != 2) & wmask
        vv = c2.ws_vv.reshape(-1)   # wave-1 meta (ver<<1|exists): the row
        #                             was X-held since, so still current
        meta_new = (((vv >> 1) + 1) << 1) | newex.astype(U32)
        wrows = jnp.where(wmask, c2.ws_rows.reshape(-1), oob)   # [2w]
        hn = db.hot_n
        hot_meta, hot_val = db.hot_meta, db.hot_val
        payload = jax.random.randint(kv3, (w, 2), 0, 1 << 16, dtype=I32)
        newval = jnp.zeros((w, 2, val_words), U32)
        newval = newval.at[:, :, 0].set(payload.astype(U32))
        newval = newval.at[:, :, 1].set(
            jnp.where(do_write & (c2.ws_kind != 2), U32(MAGIC), U32(0)))
        newval = newval.reshape(-1, val_words)
        newval = jnp.where((wkind == 2)[:, None], U32(0),
                           newval)                      # delete zeroes
        newver = (vv >> 1) + 1
        flags_del = (wkind == 2).astype(I32)
        log_tbl = c2.ws_tbl.reshape(-1)
        log_key = c2.ws_key.reshape(-1).astype(U32)
        zero_hi = jnp.zeros_like(log_key)
        if use_fused:
            # install_log megakernel: the val + meta installs, the
            # replicated log append, and (hotset) the mirror write-through
            # are N masked row-scatter streams of ONE dispatch. The log
            # plan (lane/rank/slot + replica-packed rows) is the exact
            # append_rep plan, so ring bytes match the unfused path
            lflat, entry3, lane_counts = logring.plan_rep(
                db.log, wmask, log_tbl, flags_del, zero_hi, log_key,
                newver, newval)
            wsr = c2.ws_rows.reshape(-1)
            widx = jnp.where(wmask, wsr, -1)
            tabs = [db.val, db.meta, db.log.entries.reshape(-1)]
            idxs = [widx, widx, lflat]
            vals = [newval.reshape(-1), meta_new, entry3.reshape(-1)]
            vws = [val_words, 1, db.log.entries.shape[1]]
            if use_hotset:
                w_midx = jnp.where(wmask & (wsr < hn), wsr, -1)
                tabs += [hot_val, hot_meta]
                idxs += [w_midx, w_midx]
                vals += [newval.reshape(-1), meta_new]
                vws += [val_words, 1]
            outs = pg.scatter_streams(tuple(tabs), tuple(idxs),
                                      tuple(vals), tuple(vws))
            val, meta = outs[0], outs[1]
            logs = db.log.replace(
                entries=outs[2].reshape(db.log.entries.shape),
                head=db.log.head + lane_counts)
            if use_hotset:
                hot_val, hot_meta = outs[3], outs[4]
        elif use_hotset:
            # partitioned write-through install: the row prefix is the hot
            # set, so mirror index == row for hot rows (fused kernel on the
            # pallas route, double 1-D unique-index scatters on XLA)
            wsr = c2.ws_rows.reshape(-1)
            w_midx = jnp.where(wmask & (wsr < hn), wsr, -1)
            meta, hot_meta = pg.hot_scatter(db.meta, hot_meta, wsr, w_midx,
                                            wmask, meta_new, 1,
                                            use_pallas=use_pallas)
            val, hot_val = pg.hot_scatter(db.val, hot_val, wsr, w_midx,
                                          wmask, newval.reshape(-1),
                                          val_words, use_pallas=use_pallas)
        else:
            meta = db.meta.at[wrows].set(meta_new, mode="drop",
                                         unique_indices=True)
            # interleaved-1-D install: row r's words live at
            # [r*VW, (r+1)*VW); the masked-lane oob row lands at
            # n1*VW >= len and drops (same discipline as
            # parallel/dense_sharded._apply_backup)
            wflat = (wrows[:, None] * val_words
                     + jnp.arange(val_words, dtype=I32)).reshape(-1)
            val = db.val.at[wflat].set(newval.reshape(-1), mode="drop",
                                       unique_indices=True)

    if not use_fused:
        with waves.scope("tatp_dense", "log_append"):
            logs = logring.append_rep(db.log, wmask, log_tbl, flags_del,
                                      zero_hi, log_key, newver, newval)

    # ---- wave 1: new cohort read + lock -----------------------------------
    if gen_new:
        with waves.scope("tatp_dense", "gen"):
            ttype, ops, tbl, kk, ws = gen_cohort(kg, w, n_sub, mix=mix)
        ws_active, ws_lane, ws_tbl, ws_key, ws_kind = ws
    else:
        ttype = jnp.zeros((w,), I32)
        ops = jnp.zeros((w, K), I32)
        tbl = jnp.zeros((w, K), I32)
        kk = jnp.zeros((w, K), I32)
        ws_active = jnp.zeros((w, 2), bool)
        ws_lane = jnp.zeros((w, 2), I32)
        ws_tbl = jnp.zeros((w, 2), I32)
        ws_key = jnp.zeros((w, 2), I32)
        ws_kind = jnp.zeros((w, 2), I32)

    if occupancy is not None:
        # serving-plane occupancy mask: the cohort is generated full-width
        # (RNG stream identical to the closed-loop path) and the lanes past
        # the admitted occupancy are erased before any wave sees them —
        # NOP lanes gather the sentinel and their write slots never enter
        # arbitration, so a padded lane is provably traffic-free
        with waves.scope("tatp_dense", "serve"):
            occ = jnp.asarray(occupancy, I32)
            lane_ok = jnp.arange(w, dtype=I32) < occ
            ops = jnp.where(lane_ok[:, None], ops, Op.NOP)
            ws_active = ws_active & lane_ok[:, None]

    used = ops != Op.NOP
    rows = jnp.where(used, base[tbl] + kk, sent)                # [w, K]
    is_read = ops == Op.OCC_READ

    if use_fused:
        # lock_validate megakernel: c1's validate re-read + verdict, the
        # new cohort's fresh meta read, and the whole lock-arbitration RMW
        # (hot_n arb-prefix residency included) in ONE dispatch. The meta
        # reads ride the same kernel as the arb write-back; outputs are
        # bit-identical to the unfused pair (tests/test_fused_ops.py).
        # The lock chain runs on the arb array, independent of meta, so
        # hoisting it into this wave cannot change any output.
        with waves.scope("tatp_dense", "lock_validate"):
            ws_rows = jnp.where(ws_active, base[ws_tbl] + ws_key,
                                sent)                           # [w, 2]
            flat_ws = ws_rows.reshape(-1)
            active = ws_active.reshape(-1)
            if counters is not None or ring is not None:
                # won-vs-lost split needs the pre-arbitration stamps, read
                # BEFORE the kernel aliases arb in place (read-before-
                # donate, same as the unfused pallas route)
                held = (db.arb[flat_ws] >> K_ARB) == (t - 1)
            arb, grant_u, vbad, rmeta_f = pg.lock_validate(
                db.arb, meta, c1.rows.reshape(-1), c1.vv1.reshape(-1),
                rows.reshape(-1), flat_ws, active, t, K_ARB,
                hot_n=hn if use_hotset else 0)
            grant = (grant_u != 0).reshape(w, 2)
            rmeta = rmeta_f.reshape(w, K)                       # [w, K]
        # in-kernel verdict == (meta[vidx] != vv1); the is_read mask is
        # applied here exactly as the unfused compare applied it
        bad = c1.is_read & (vbad.reshape(w, K) != 0)
    else:
        # ONE fused meta gather serves wave 2 (c1's validate re-read) AND
        # wave 1 (the new cohort's reads). Both gathers depend on the same
        # install scatter and on nothing else of each other, so XLA could
        # overlap their DMAs (PERF.md round-3 finding 3) — the fusion still
        # halves per-op launch/descriptor overhead on ops measured at
        # 0.6-0.9 ms per 16-32k random indices
        with waves.scope("tatp_dense", "meta_gather"):
            gidx = jnp.concatenate([c1.rows.reshape(-1), rows.reshape(-1)])
            if use_hotset:
                g_midx = jnp.where(gidx < hn, gidx, -1)
                g = pg.hot_gather(meta, hot_meta, gidx, g_midx, 1,
                                  use_pallas=use_pallas)
            else:
                g = (pg.gather_rows(meta, gidx, 1) if use_pallas
                     else meta[gidx])
            vvB = g[: w * K].reshape(w, K)                      # [w, K]
            rmeta = g[w * K:].reshape(w, K)                     # [w, K]
        bad = c1.is_read & (vvB != c1.vv1)

    # ---- wave 2 of c1: validate read-set version compare ------------------
    changed = bad.any(axis=1)
    if counters is not None or ring is not None:
        # lanes of surviving RW txns checked / failed — the same lane set
        # the generic pipeline re-reads (_validate_lanes), so the parity
        # counters are engine-independent. The flight recorder needs the
        # per-lane masks (and c1's PRE-verdict alive) for its VALIDATE
        # and wave-2 OUTCOME events, captured before the replace below.
        v_alive = c1.alive[:, None]
        v_lanes = (c1.is_read & v_alive).sum(dtype=I32)
        v_failed = (bad & v_alive).sum(dtype=I32)
        val_mask = (c1.is_read & v_alive).reshape(-1)       # [wK]
        val_bad = (bad & v_alive).reshape(-1)               # [wK]
        c1_alive_pre = c1.alive
    c1 = c1.replace(alive=c1.alive & ~changed,
                    ab_validate=(c1.alive & changed).sum(dtype=I32))

    vv1 = rmeta                     # ver<<1|exists — locks live elsewhere
    rex = (rmeta & 1) != 0
    if check_magic:
        # the magic-parity oracle costs one [w,K] single-word gather over
        # the 6.2 GB val array per step; check_magic=False is an A/B
        # measurement knob (DINT_BENCH_CHECK_MAGIC=0) quantifying it —
        # the default keeps the reference's every-read integrity check
        with waves.scope("tatp_dense", "magic_gather"):
            midx = (rows * val_words + 1).reshape(-1)
            if use_hotset:
                # the mirror is the flat word prefix [0, hn*VW): a hot
                # row's magic word sits at the same flat offset in it
                mg_midx = jnp.where((rows < hn).reshape(-1), midx, -1)
                rmagic = pg.hot_gather(val, hot_val, midx, mg_midx, 1,
                                       use_pallas=use_pallas).reshape(w, K)
            else:
                rmagic = (pg.gather_rows(val, midx, 1).reshape(w, K)
                          if use_pallas else val[midx].reshape(w, K))
            magic_bad = jnp.sum(is_read & rex & (rmagic != MAGIC),
                                dtype=I32)
    else:
        magic_bad = jnp.asarray(0, I32)

    # lock arbitration in [w, 2] write-slot space: first slot wins per row
    # (batched CAS, tatp/ebpf/shard_kern.c:251-297); losers and held rows
    # REJECT. The whole chain — stamp gather, masked scatter-max, winner
    # gather-back — runs on the arb array, INDEPENDENT of the meta/val
    # install chain. held = stamped by the previous step; c2's stamps
    # (t-2) expired this step, matching the wave-3 release timing above.
    # Candidates for held rows are masked OUT of the scatter so rejected
    # attempts cannot keep a hot row stamped (no livelock). On the fused
    # route the whole chain already ran inside lock_validate above.
    ws_vv = jnp.take_along_axis(rmeta, ws_lane, axis=1)
    if not use_fused:
        with waves.scope("tatp_dense", "lock"):
            ws_rows = jnp.where(ws_active, base[ws_tbl] + ws_key,
                                sent)                           # [w, 2]
            flat_ws = ws_rows.reshape(-1)
            active = ws_active.reshape(-1)
            if use_pallas:
                if counters is not None or ring is not None:
                    # the fused kernel only exposes winners; the
                    # won-vs-lost split needs the pre-arbitration stamps,
                    # read BEFORE the kernel aliases arb in place (a
                    # read-before-donate, which the dintlint aliasing pass
                    # permits; bit-identical to the XLA path's arb_old
                    # gather)
                    held = ((pg.gather_rows(db.arb, flat_ws, 1) >> K_ARB)
                            == (t - 1))
                # fused kernel pass: gather + stamp compare + first-lane-
                # wins scatter-max + winner read-back in ONE launch, arb
                # updated in place (bit-identical to the XLA chain below —
                # pinned in tests/test_pallas_ops.py)
                # hot_n > 0 caches the arb prefix in VMEM for the pass
                # (dintcache); outputs bit-identical either way
                arb, grant_u = pg.lock_arbitrate(
                    db.arb, flat_ws, active, t, K_ARB,
                    hot_n=hn if use_hotset else 0)
                grant = (grant_u != 0).reshape(w, 2)
            else:
                arb_old = db.arb[flat_ws]   # [2w]; sentinel never stamped
                held = (arb_old >> K_ARB) == (t - 1)
                inv_slot = U32(2 * w - 1) - jnp.arange(2 * w, dtype=U32)
                packed = (t << K_ARB) | inv_slot
                cand = active & ~held
                arb = db.arb.at[jnp.where(cand, flat_ws, oob)].max(
                    packed, mode="drop")
                grant = (cand & (arb[flat_ws] == packed)).reshape(w, 2)

    # reply types: reads from the gather; write-slot GRANT/REJECT direct
    rt = jnp.where(is_read & used,
                   jnp.where(rex, Reply.VAL, Reply.NOT_EXIST), Reply.NONE)
    ws_rt = jnp.where(grant, Reply.GRANT,
                      jnp.where(ws_active, Reply.REJECT, Reply.NONE))

    # ---- wave-1 outcome: shared per-txn-type rules ------------------------
    is_ro, rw, granted, lock_rejected, missing = classify_wave1(
        ttype, rt, ops, ws_active, ws_lane, ws_rt=ws_rt)

    new_ctx = DenseCtx(
        rows=rows, is_read=is_read & used, vv1=vv1,
        alive=rw & ~lock_rejected & ~missing,
        ro_commit=is_ro & ~missing, granted=granted,
        ws_rows=ws_rows, ws_vv=ws_vv,
        ws_tbl=ws_tbl, ws_key=ws_key, ws_kind=ws_kind,
        ws_active=ws_active,
        attempted=(occ if occupancy is not None
                   else jnp.asarray(w if gen_new else 0, I32)),
        ab_lock=(rw & lock_rejected).sum(dtype=I32),
        ab_missing=((rw & ~lock_rejected & missing)
                    | (is_ro & missing)).sum(dtype=I32),
        ab_validate=jnp.asarray(0, I32),
        magic_bad=magic_bad)

    db = db.replace(val=val, meta=meta, arb=arb, step=t + 1, log=logs,
                    hot_meta=hot_meta, hot_val=hot_val)
    if counters is not None:
        grant_l = grant.reshape(-1)
        hot_ctrs = {}
        if use_hotset:
            # partition accounting over the meta + magic gathers (the arb
            # prefix residency has no per-lane split to count). The fused
            # lock_validate reads the main meta table directly (bit-
            # identical by the mirror invariant), so its lanes are not
            # partitioned and only the magic gather counts there
            if use_fused:
                hits = jnp.asarray(0, I32)
                lanes = 0
                refresh = 0
            else:
                hits = (g_midx >= 0).sum(dtype=I32)
                lanes = 2 * w * K
                refresh = hn * 4
            if check_magic:
                hits = hits + (mg_midx >= 0).sum(dtype=I32)
                lanes += w * K
                refresh += hn * val_words * 4
            hot_ctrs = {
                mon.CTR_HOT_HITS: hits,
                mon.CTR_HOT_COLD_ROWS: lanes - hits,
                mon.CTR_HOT_REFRESH_BYTES: refresh if use_pallas else 0,
            }
        serve_ctrs = {}
        if occupancy is not None:
            serve_ctrs = {
                mon.CTR_SERVE_OCC_LANES: occ,
                mon.CTR_SERVE_PAD_LANES: jnp.asarray(w, I32) - occ,
                mon.CTR_SERVE_SHED_LANES:
                    jnp.asarray(0 if shed is None else shed, I32),
            }
        counters = mon.bump(counters, {
            **hot_ctrs,
            **serve_ctrs,
            mon.CTR_STEPS: 1,
            mon.CTR_TXN_ATTEMPTED: c2.attempted,
            mon.CTR_TXN_COMMITTED: (c2.ro_commit | c2.alive).sum(dtype=I32),
            mon.CTR_AB_LOCK: c2.ab_lock,
            mon.CTR_AB_MISSING: c2.ab_missing,
            mon.CTR_AB_VALIDATE: c2.ab_validate,
            mon.CTR_MAGIC_BAD: c2.magic_bad,
            mon.CTR_LOCK_REQUESTS: active.sum(dtype=I32),
            mon.CTR_LOCK_GRANTED: (active & grant_l).sum(dtype=I32),
            mon.CTR_LOCK_REJECTED: (active & ~grant_l).sum(dtype=I32),
            mon.CTR_LOCK_REJECT_HELD: (active & held).sum(dtype=I32),
            mon.CTR_LOCK_REJECT_ARB:
                (active & ~held & ~grant_l).sum(dtype=I32),
            mon.CTR_VALIDATE_LANES: v_lanes,
            mon.CTR_VALIDATE_FAILED: v_failed,
            mon.CTR_INSTALL_WRITES: wmask.sum(dtype=I32),
            mon.CTR_LOG_APPENDS: wmask.sum(dtype=I32),
            (mon.CTR_DISPATCH_PALLAS if use_pallas
             else mon.CTR_DISPATCH_XLA): 1,
            **({mon.CTR_FUSED_DISPATCH: 1} if use_fused else {}),
        })
        counters = mon.gauge_max(
            counters, {mon.CTR_RING_HWM: logs.head.max()})
    extra = ()
    if ring is not None:
        # dinttrace: the txn id is recomputable per cohort — gen_step*w +
        # lane (c1 generated at t-1, c2 at t-2), so the assembler joins a
        # txn's lock, validate, install, and outcome events without any
        # id traveling through the carry. The OUTCOME masks mirror the
        # counter formulas above exactly (ro commits + lock/missing
        # aborts classify at wave 1; rw commits + validate aborts at
        # wave 2), so full-rate event counts reconcile with the ledger.
        with waves.scope("tatp_dense", "trace"):
            tu = jnp.asarray(t).astype(U32)
            lane_w = jnp.arange(w, dtype=U32)
            txn_new = tu * U32(w) + lane_w
            txn_c1 = (tu - U32(1)) * U32(w) + lane_w
            txn_c2 = (tu - U32(2)) * U32(w) + lane_w
            grant_l = grant.reshape(-1)
            lock_aux = (jnp.where(grant_l, txe.LOCK_GRANTED, 0)
                        | jnp.where(held, txe.LOCK_HELD, 0))
            miss_m = (rw & ~lock_rejected & missing) | (is_ro & missing)
            out1_mask = (rw & lock_rejected) | miss_m | new_ctx.ro_commit
            out1_cause = jnp.where(
                rw & lock_rejected, txe.CAUSE_LOCK,
                jnp.where(miss_m, txe.CAUSE_MISSING, txe.CAUSE_COMMIT))
            out2_cause = jnp.where(changed, txe.CAUSE_VALIDATE,
                                   txe.CAUSE_COMMIT)
            groups = (
                txe.ev(active, jnp.repeat(txn_new, 2), txe.EV_LOCK,
                       waves.full_name("tatp_dense", "lock"),
                       aux=lock_aux, step=tu),
                txe.ev(val_mask, jnp.repeat(txn_c1, K), txe.EV_VALIDATE,
                       waves.full_name("tatp_dense", "meta_gather"),
                       aux=val_bad, step=tu),
                txe.ev(wmask, jnp.repeat(txn_c2, 2), txe.EV_INSTALL,
                       waves.full_name("tatp_dense", "install"),
                       step=tu),
                txe.ev(out1_mask, txn_new, txe.EV_OUTCOME,
                       waves.full_name("tatp_dense", "lock"),
                       aux=out1_cause, step=tu),
                txe.ev(c1_alive_pre, txn_c1, txe.EV_OUTCOME,
                       waves.full_name("tatp_dense", "meta_gather"),
                       aux=out2_cause, step=tu),
            )
            ring, counters = txe.emit(ring, tcfg, groups, counters)
        extra = (ring,)
    if emit_installs:
        inst = Installs(
            wmask=wmask, rows=c2.ws_rows.reshape(-1),
            meta=jnp.where(wmask, meta_new, U32(0)),
            val=newval, tbl=log_tbl, key=log_key,
            is_del=flags_del, ver=newver)
        if counters is not None:
            return (db, new_ctx, c1, _stats_of(c2), inst, counters) + extra
        return (db, new_ctx, c1, _stats_of(c2), inst) + extra
    if counters is not None:
        return (db, new_ctx, c1, _stats_of(c2), counters) + extra
    return (db, new_ctx, c1, _stats_of(c2)) + extra


def rebase_stamps(db: DenseDB) -> DenseDB:
    """Rebase arb stamps so the step field never overflows its u32 budget:
    live stamps (step-1 -> 2, step-2 -> 1) are kept, everything older is
    zeroed, and the step counter restarts at 3. One full elementwise pass,
    run once per ~16k steps."""
    with waves.scope("tatp_dense", "rebase"):
        t = db.step
        ts = db.arb >> K_ARB
        keep = ts + 2 >= t
        new_ts = jnp.where(keep, ts - (t - 3), 0)
        arb = jnp.where(keep, (new_ts << K_ARB)
                        | (db.arb & U32((1 << K_ARB) - 1)), U32(0))
        # t*0+3 (not a fresh constant) so the step keeps its varying-axis
        # type under shard_map's lax.cond (dense_sharded.block_local)
        return db.replace(arb=arb, step=t * U32(0) + U32(3))


@memoize_builder
def build_pipelined_runner(n_sub: int, w: int = 8192, val_words: int = 10,
                           cohorts_per_block: int = 8, mix=None,
                           check_magic: bool = True, use_pallas=None,
                           use_hotset: bool = False, hot_frac=None,
                           use_fused=None, log_replicas: int = N_SHARDS,
                           monitor: bool = False, trace=None,
                           trace_rate=None, trace_cap=None,
                           serve: bool = False):
    """jit(scan(pipe_step)) over carry (db, c1, c2); same contract as
    tatp_pipeline.build_pipelined_runner: returns (run, init, drain).

    ``serve``: the dintserve variable-occupancy mode. run's signature
    becomes ``run(carry, key, occ, shed)`` with occ/shed i32
    [cohorts_per_block] arrays scanned alongside the step keys — each
    step masks lanes >= occ[i] to no-ops and mirrors shed[i] onto the
    device ledger (pipe_step's occupancy/shed). Carry layout, init, and
    drain are unchanged, so the serving engine reuses the closed-loop
    drain verbatim.

    ``use_pallas``: None = honor DINT_USE_PALLAS env; True/False forces.
    When requested, the Pallas kernels are probed at this runner's lane
    geometry and a Mosaic failure falls back to the XLA path with a logged
    warning (ops/pallas_gather.resolve_use_pallas).

    ``use_hotset`` / ``hot_frac``: the dintcache row-prefix partition,
    OFF by default and deliberately NOT env-driven here — TATP's NURand
    workload is near-uniform, so the hot tier only pays at this engine
    unless the experiment skews it; pass use_hotset=True (hot_frac = the
    mirrored fraction of the subscriber prefix, default 4%) for
    skewed-TATP experiments. init() attaches the mirror.

    ``use_fused``: None = honor DINT_USE_FUSED env; True/False forces.
    Routes the step through the round-12 megakernels (lock_validate +
    install_log) after probing them at this runner's geometry —
    ``log_replicas`` must match the DenseDB's log (it sizes the log
    stream's row width for the probe). Probe failure degrades to the
    unfused path with a logged warning (pg.resolve_use_fused).

    ``monitor``: thread the dintmon counter plane through the carry. The
    carry grows a trailing monitor.Counters leaf (init creates it; read
    it between dispatches with monitor.snapshot(carry[-1])) and drain
    returns (db, stats, counters). Off (default) = contract and jaxpr
    unchanged, outputs bit-identical.

    ``trace`` / ``trace_rate`` / ``trace_cap``: the dinttrace flight
    recorder (None = honor DINT_TRACE / DINT_TRACE_RATE). When on, the
    carry gains a monitor.txnevents.TxnRing leaf BEFORE the counters leaf
    (so counters stay carry[-1]); each block starts from a zeroed ring and
    the caller drains it between dispatches with monitor.txnevents
    .TxnMonitor.observe. ``trace_cap`` defaults to one full block of
    candidates (w*(K+6) per step) so nothing drops at rate 1.0; the
    resolved txnevents.TraceCfg hangs off ``init.trace_cfg``. Off =
    engine outputs bit-identical, not one extra jaxpr eqn."""
    assert 2 * w <= (1 << K_ARB), f"w={w} exceeds the arb slot field"
    use_hotset = bool(use_hotset)
    use_pallas = pg.resolve_use_pallas(use_pallas, n_idx=2 * w * K,
                                       m_lock=2 * w, k_arb=K_ARB)
    hot_rows = 0
    if use_hotset:
        frac = 0.04 if hot_frac is None else float(hot_frac)
        hot_rows = max(1, min(int((n_sub + 1) * frac), n_rows(n_sub)))
        if use_pallas and not pg.hot_kernels_available(
                n_idx=2 * w * K, m_lock=2 * w, k_arb=K_ARB):
            use_pallas = False      # partition stays; XLA serves it
    ew3 = int(log_replicas) * (logring.HDR_WORDS + val_words)
    scat_geoms = ((2 * w, val_words), (2 * w, 1), (2 * w, ew3))
    if use_hotset:
        scat_geoms = scat_geoms + ((2 * w, val_words), (2 * w, 1))
    use_fused = pg.resolve_use_fused(
        use_fused,
        lockv=(w * K, w * K, 2 * w, K_ARB,
               hot_rows if use_hotset else 0),
        scatters=scat_geoms)
    kw = dict(w=w, n_sub=n_sub, val_words=val_words,
              check_magic=check_magic, use_pallas=use_pallas,
              use_hotset=use_hotset, use_fused=use_fused)
    trace_on = txe.trace_enabled(trace)
    tcfg = None
    if trace_on:
        # candidates/step: LOCK [2w] + VALIDATE [wK] + INSTALL [2w] +
        # OUTCOME x2 [2w] — default cap holds a full block at rate 1.0
        n_step = w * (K + 6)
        cap = int(trace_cap) if trace_cap else n_step * cohorts_per_block
        tcfg = txe.TraceCfg(rate=txe.trace_rate(trace_rate), cap=cap,
                            wave=waves.full_name("tatp_dense", "trace"))

    def step_mon(db, c1, c2, key, cnt, ring, **skw):
        """pipe_step with counters/ring or None, normalized to a fixed
        6-arity (db, new_ctx, c1, stats, cnt, ring)."""
        out = pipe_step(db, c1, c2, key, counters=cnt, ring=ring,
                        tcfg=tcfg, **skw)
        i = 4
        cnt = out[i] if cnt is not None else None
        i += 1 if cnt is not None else 0
        ring = out[i] if ring is not None else None
        return out[0], out[1], out[2], out[3], cnt, ring

    def scan_fn(carry, x):
        key, occ, shed = x if serve else (x, None, None)
        db, c1, c2 = carry[:3]
        ring = carry[3] if trace_on else None
        cnt = carry[-1] if monitor else None
        db, new_ctx, c1, stats, cnt, ring = step_mon(
            db, c1, c2, key, cnt, ring, mix=mix,
            occupancy=occ, shed=shed, **kw)
        out = ((db, new_ctx, c1) + ((ring,) if trace_on else ())
               + ((cnt,) if monitor else ()))
        return out, stats

    def _pre(carry):
        db = jax.lax.cond(carry[0].step >= U32(REBASE_AT), rebase_stamps,
                          lambda d: d, carry[0])
        carry = (db,) + carry[1:]
        if trace_on:     # each drained window is self-contained
            carry = carry[:3] + (txe.reset(carry[3]),) + carry[4:]
        return carry

    if serve:
        def block(carry, key, occ, shed):
            carry = _pre(carry)
            keys = jax.random.split(key, cohorts_per_block)
            return jax.lax.scan(scan_fn, carry, (keys, occ, shed))
    else:
        def block(carry, key):
            carry = _pre(carry)
            keys = jax.random.split(key, cohorts_per_block)
            return jax.lax.scan(scan_fn, carry, keys)

    def init(db):
        if use_hotset and db.hot_n == 0:
            db = attach_hotset(db, hot_rows)
        base = (db, empty_ctx(w), empty_ctx(w))
        return (base + ((txe.create_ring(tcfg.cap),) if trace_on else ())
                + ((mon.create(),) if monitor else ()))

    init.trace_cfg = tcfg

    @functools.partial(jax.jit, donate_argnums=0)
    def drain(carry):
        db, c1, c2 = carry[:3]
        ring = txe.reset(carry[3]) if trace_on else None
        cnt = carry[-1] if monitor else None
        key = jax.random.PRNGKey(0)
        db, _, c1, s1, cnt, ring = step_mon(db, c1, c2, key, cnt, ring,
                                            gen_new=False, **kw)
        db, _, _, s2, cnt, ring = step_mon(db, empty_ctx(w), c1, key, cnt,
                                           ring, gen_new=False, **kw)
        stats = jnp.stack([s1, s2])
        return ((db, stats) + ((ring,) if trace_on else ())
                + ((cnt,) if monitor else ()))

    return jax.jit(block, donate_argnums=0), init, drain
