"""Sort-free dense TATP engine: the TPU-first fast path.

The generic engine (engines/tatp.py) resolves intra-batch conflicts with
64-bit sorts + segmented reductions over EVERY lane x 3 vmapped shard
replicas — protocol-faithful but ~200x off the reference's throughput
(tatp/ebpf/shard_kern.c:111-197 does one hash + one CAS per packet). This
module is the redesign that removes the sort entirely, exploiting three
structural facts the reference cannot:

1. **Every TATP table is dense-indexable.** SUBSCRIBER/SEC_SUBSCRIBER/
   ACCESS_INFO/SPECIAL_FACILITY index by s_id directly (tatp/caladan/
   tatp.h:28), and even CALL_FORWARDING's composite key
   ``s_id*12 + (sf_type-1)*3 + start_time/8`` is bounded by 12*(n_sub+1),
   so the "sparse" table is a dense array plus an `exists` bit. The
   reference hashes it (tatp/ebpf/shard_kern.c:61-108) only because its
   kvs.h is generic; no bloom filter is needed when lookups are exact.
   All 5 tables live in ONE flat row-id space:
   rows [0,p1) sub | [p1,2p1) sec | [2p1,6p1) ai | [6p1,10p1) sf |
   [10p1,22p1) cf, with row N as the gather/scatter sentinel for NOP lanes.

2. **The 3 servers' lock tables partition by key.** Locks for key k are
   only ever taken at server k%3 (tatp/caladan/client_ebpf_shard.cc:
   636-641), so the union of the 3 per-server lock arrays is one exact
   per-row lock bit — no routing, no hash conflation (exact locks also
   remove the reference's false REJECT_LOCK on hash collisions, the
   ablation its lock_kern.c instrumentation exists to measure).

3. **Replicas are bit-identical by construction.** Every certified write
   applies at primary + both backups (client_ebpf_shard.cc:779-900), so
   the single-chip engine stores table content ONCE and keeps the
   replication physical where it matters for recovery: the log x3
   (tables/log.RepLog packs 3 replica entries per slot). The multi-chip
   path (parallel/sharded.py) places real per-device replicas; a
   single-chip emulation holding 3 bit-identical copies in one HBM adds
   no fidelity — it only triples memory (measured: XLA tiles [N, 3, VW]
   u32 to 2 KB/row, 4.5 GB for the bench's 2.2M rows).

Per-row metadata packs into ONE u32 word (`meta`):

    bits [31:2] = ver   (monotonic: commit/insert/delete all bump it, so
                         OCC validate is an equality compare with no
                         delete/reinsert ABA window)
    bit  1      = exists
    bit  0      = locked (the union of the 3 servers' lock tables)

``meta >> 1`` (ver:exists, lock bit dropped) is the value OCC validation
compares — reads do not observe locks, exactly the reference's verify
stage (client_ebpf_shard.cc:765-768). One gather serves wave-1 read +
lock + existence + version; one scatter per step installs commits AND
releases locks (an install writes ``(ver+1)<<2 | exists<<1 | 0``; an
abort-release rewrites the wave-1 value with bit0 clear, reconstructed
from the carried version — the row was X-held in between, so no re-read
is needed).

Conflict resolution per fused step (replacing ops/segments.sort_batch):
  * commits: X-certified one-writer-per-row -> direct scatter.
  * lock acquires: first-slot-wins via scatter-min of write-slot index
    into a per-row winner scratch, then a gather-back compare — the
    batched equivalent of the reference's CAS loop (shard_kern.c:251-297).
    Arbitration runs in [w, 2] write-slot space (2 lock slots per txn),
    measured 2x cheaper than arbitrating all [w, K] lanes.
  * reads/validates: pure gathers.

Scatter discipline (TPU, all measured on v5e): every scatter is 1-D or
row-major on axis 0 with ``unique_indices=True`` and masked lanes routed
OUT OF BOUNDS under ``mode="drop"`` — duplicate-index and multi-dim-index
scatters serialize, and uniqueness is guaranteed by certification (one
X-lock holder per row). Row N is a never-written sentinel that NOP lanes
gather from; OOB gather indices clip onto it.

The 3-stage software pipeline (wave 1 of cohort t + validate of t-1 +
commit of t-2 fused into ONE device program) is inherited from
engines/tatp_pipeline.py, which remains the semantics reference; its
gen_cohort (txn mix, NURand, lane layout) is reused verbatim.

Memory: ~22*(n_sub+1) rows; val dominates at N*VW u32 (tiled to 128
words/row). At the bench's n_sub=1e5 that's ~1.1 GB + a 0.5 GB log —
single-chip HBM. Reference scale (n_sub=7e6) needs the multi-chip shard
path, as it does for the reference (3 servers).
"""
from __future__ import annotations

import functools

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from ..tables import log as logring
from . import tatp
from .types import Op, Reply
from .tatp_pipeline import K, MAGIC, N_SHARDS, classify_wave1, gen_cohort
from .tatp_pipeline import (STAT_ATTEMPTED, STAT_COMMITTED, STAT_AB_LOCK,     # noqa: F401 (re-exported)
                            STAT_AB_MISSING, STAT_AB_VALIDATE, STAT_MAGIC_BAD,
                            N_STATS)

I32 = jnp.int32
U32 = jnp.uint32

BIG = jnp.int32(1 << 30)


def _bases(p1: int) -> np.ndarray:
    """Flat row-id base per table id (tatp.SUBSCRIBER..tatp.CALL_FORWARDING)."""
    return np.cumsum([0, p1, p1, 4 * p1, 4 * p1]).astype(np.int32)


def n_rows(n_sub: int) -> int:
    return 22 * (n_sub + 1)


@flax.struct.dataclass
class DenseDB:
    """All 5 TATP tables + locks + logs in flat dense arrays (row N is the
    sentinel every NOP/padded lane gathers from; it is never written)."""
    val: jax.Array      # u32 [N+1, VW]  word0 payload, word1 magic
    meta: jax.Array     # u32 [N+1]      ver<<2 | exists<<1 | locked
    log: logring.RepLog   # 3 replica entries packed per slot (log x3)

    @property
    def n_sub(self):
        return self.meta.shape[0] // 22 - 1

    # convenience views (tests / recovery / oracles — not the hot path)
    @property
    def ver(self):
        return self.meta >> 2

    @property
    def exists(self):
        return (self.meta & 2) != 0

    @property
    def locked(self):
        return (self.meta & 1) != 0


def create(n_sub: int, val_words: int = 10, log_lanes: int = 16,
           log_capacity: int = 1 << 16,
           log_replicas: int = N_SHARDS) -> DenseDB:
    """``log_replicas``: the single-chip engine packs the log x3 locally;
    the multi-chip path (parallel/dense_sharded.py) passes 1 because the
    3 copies live on 3 devices there."""
    n1 = n_rows(n_sub) + 1
    return DenseDB(
        val=jnp.zeros((n1, val_words), U32),
        meta=jnp.zeros((n1,), U32),
        log=logring.create_rep(log_lanes, log_capacity, val_words,
                               replicas=log_replicas),
    )


def populate(rng: np.random.Generator, n_sub: int, val_words: int = 10,
             **kw) -> DenseDB:
    """Same population as clients/tatp_client.populate_shards (reference
    populate: tatp/caladan/client_ebpf_shard.cc:96-341): all subscribers
    present, ai/sf types present w.p. 0.625 (>=1 each), CF rows on 25% of
    present sf rows per start_time; val word0 = row payload, word1 = magic
    (tatp/caladan/tatp.h:67-72)."""
    p1 = n_sub + 1
    db = create(n_sub, val_words=val_words, **kw)
    n1 = n_rows(n_sub) + 1
    base = _bases(p1)

    val = np.zeros((n1, val_words), np.uint32)
    meta = np.zeros(n1, np.uint32)

    def put(rows, payload):
        val[rows, 0] = payload.astype(np.uint32)
        val[rows, 1] = MAGIC
        meta[rows] = (1 << 2) | (1 << 1)      # ver 1, exists, unlocked

    s_ids = np.arange(1, p1)
    put(base[tatp.SUBSCRIBER] + s_ids, s_ids)
    put(base[tatp.SEC_SUBSCRIBER] + s_ids, s_ids)

    ai_present = rng.random((p1, 4)) < 0.625
    sf_present = rng.random((p1, 4)) < 0.625
    ai_present[0] = sf_present[0] = False
    ai_present[1:][ai_present[1:].sum(1) == 0, 0] = True
    sf_present[1:][sf_present[1:].sum(1) == 0, 0] = True
    ai_idx = np.nonzero(ai_present.reshape(-1))[0]
    sf_idx = np.nonzero(sf_present.reshape(-1))[0]
    put(base[tatp.ACCESS_INFO] + ai_idx, ai_idx)
    put(base[tatp.SPECIAL_FACILITY] + sf_idx, sf_idx)

    sfi, sft = np.nonzero(sf_present)
    cf_keys = []
    for st in (0, 8, 16):
        mask = rng.random(len(sfi)) < 0.25
        cf_keys.append(np.asarray(tatp.cf_key(sfi[mask], sft[mask] + 1, st)))
    cf_keys = np.unique(np.concatenate(cf_keys)).astype(np.int64)
    put(base[tatp.CALL_FORWARDING] + cf_keys, cf_keys)

    return db.replace(val=jnp.asarray(val), meta=jnp.asarray(meta))


# ---------------------------------------------------------------- pipeline


@flax.struct.dataclass
class DenseCtx:
    """An in-flight cohort between pipeline stages (cf. tatp_pipeline.PipeCtx
    — row ids and versions are captured once at wave 1). Bootstrap cohorts
    have attempted == 0 and all-False masks."""
    rows: jax.Array       # i32 [w, K] flat row ids (sentinel for NOP lanes)
    is_read: jax.Array    # bool [w, K] OCC_READ lanes
    vv1: jax.Array        # u32 [w, K] meta>>1 (ver:exists) at wave 1
    alive: jax.Array      # bool [w]
    ro_commit: jax.Array  # bool [w]
    granted: jax.Array    # bool [w, 2]
    ws_rows: jax.Array    # i32 [w, 2] write-slot row ids (sentinel if inactive)
    ws_vv: jax.Array      # u32 [w, 2] write-slot ver:exists at wave 1
    ws_tbl: jax.Array     # i32 [w, 2]
    ws_key: jax.Array     # i32 [w, 2] (logged key)
    ws_kind: jax.Array    # i32 [w, 2] 0 commit / 1 insert / 2 delete
    ws_active: jax.Array  # bool [w, 2]
    attempted: jax.Array  # i32 scalar
    ab_lock: jax.Array    # i32 scalar
    ab_missing: jax.Array # i32 scalar
    ab_validate: jax.Array  # i32 scalar
    magic_bad: jax.Array  # i32 scalar


def empty_ctx(w: int) -> DenseCtx:
    def z(shape, dt):
        return jnp.asarray(np.zeros(shape, dt))

    return DenseCtx(
        rows=z((w, K), np.int32), is_read=z((w, K), bool),
        vv1=z((w, K), np.uint32), alive=z((w,), bool),
        ro_commit=z((w,), bool), granted=z((w, 2), bool),
        ws_rows=z((w, 2), np.int32), ws_vv=z((w, 2), np.uint32),
        ws_tbl=z((w, 2), np.int32),
        ws_key=z((w, 2), np.int32), ws_kind=z((w, 2), np.int32),
        ws_active=z((w, 2), bool),
        attempted=z((), np.int32), ab_lock=z((), np.int32),
        ab_missing=z((), np.int32), ab_validate=z((), np.int32),
        magic_bad=z((), np.int32))


def _stats_of(c: DenseCtx):
    return jnp.stack([
        c.attempted, (c.ro_commit | c.alive).sum(dtype=I32),
        c.ab_lock, c.ab_missing, c.ab_validate, c.magic_bad])


@flax.struct.dataclass
class Installs:
    """Wave-3 install record of one step: what a backup replica must apply
    (parallel/dense_sharded.py ppermutes this to the +1/+2 devices — the
    reference's CommitBck x2 + CommitLog fan-out,
    client_ebpf_shard.cc:779-900). Rows are the emitting device's local
    ids; wmask marks real writes (releases are lock-only and stay local)."""
    wmask: jax.Array     # bool [2w]
    rows: jax.Array      # i32 [2w]
    meta: jax.Array      # u32 [2w]  new ver<<2|exists<<1 (lock bit clear)
    val: jax.Array       # u32 [2w, VW]
    tbl: jax.Array       # i32 [2w]  (for the log)
    key: jax.Array       # u32 [2w]
    is_del: jax.Array    # i32 [2w]
    ver: jax.Array       # u32 [2w]


def pipe_step(db: DenseDB, c1: DenseCtx, c2: DenseCtx, key, *, w: int,
              n_sub: int, val_words: int, gen_new: bool = True, mix=None,
              emit_installs: bool = False):
    """One fused device step: commit wave of c2, validate wave of c1, and
    read+lock wave of a NEW cohort — ordered commits -> reads -> locks per
    row exactly like the generic engine's phase order (engines/tatp.
    _dense_step), so cohort t-2's installs are visible to t-1's validation
    and this step's reads, and its unlocks free rows for this step's lock
    acquires. Returns (db', new_ctx, c1', stats-of-c2), plus the Installs
    record when ``emit_installs`` (static) is set."""
    p1 = n_sub + 1
    n1 = n_rows(n_sub) + 1
    sent = n1 - 1     # sentinel row: gathered by NOP lanes, never written
    oob = n1          # scatter index for masked lanes under mode="drop"
    base = jnp.asarray(_bases(p1))
    kg, kv3 = jax.random.split(key)

    # ---- wave 3 of c2: install + unlock + log -----------------------------
    # one meta scatter covers every granted slot: installs write the bumped
    # version with the lock bit clear (COMMIT/INSERT/DELETE_PRIM release the
    # row lock, shard_kern.c:338-476); aborted-but-granted slots rewrite
    # their wave-1 value with bit0 clear (the row was X-held since wave 1,
    # so ws_vv is still current — no re-read). Uniqueness: one X-holder per
    # row, and a txn's two slots target different tables.
    do_write = c2.ws_active & c2.alive[:, None]                 # [w, 2]
    wmask = do_write.reshape(-1)
    release = c2.granted.reshape(-1) & ~wmask
    touch = wmask | release
    trows = jnp.where(touch, c2.ws_rows.reshape(-1), oob)       # [2w]
    wkind = c2.ws_kind.reshape(-1)
    newex = (wkind != 2) & wmask
    vv = c2.ws_vv.reshape(-1)
    meta_new = jnp.where(
        wmask, (((vv >> 1) + 1) << 2) | (newex.astype(U32) << 1),
        vv << 1)
    meta = db.meta.at[trows].set(meta_new, mode="drop",
                                 unique_indices=True)

    wrows = jnp.where(wmask, c2.ws_rows.reshape(-1), oob)
    payload = jax.random.randint(kv3, (w, 2), 0, 1 << 16, dtype=I32)
    newval = jnp.zeros((w, 2, val_words), U32)
    newval = newval.at[:, :, 0].set(payload.astype(U32))
    newval = newval.at[:, :, 1].set(
        jnp.where(do_write & (c2.ws_kind != 2), U32(MAGIC), U32(0)))
    newval = newval.reshape(-1, val_words)
    newval = jnp.where((wkind == 2)[:, None], U32(0), newval)   # delete zeroes
    val = db.val.at[wrows].set(newval, mode="drop", unique_indices=True)

    newver = (vv >> 1) + 1
    flags_del = (wkind == 2).astype(I32)
    log_tbl = c2.ws_tbl.reshape(-1)
    log_key = c2.ws_key.reshape(-1).astype(U32)
    zero_hi = jnp.zeros_like(log_key)
    logs = logring.append_rep(db.log, wmask, log_tbl, flags_del, zero_hi,
                              log_key, newver, newval)

    # ---- wave 2 of c1: validate read-set version compare ------------------
    vvB = meta[c1.rows] >> 1                                    # [w, K]
    bad = c1.is_read & (vvB != c1.vv1)
    changed = bad.any(axis=1)
    c1 = c1.replace(alive=c1.alive & ~changed,
                    ab_validate=(c1.alive & changed).sum(dtype=I32))

    # ---- wave 1: new cohort read + lock -----------------------------------
    if gen_new:
        ttype, ops, tbl, kk, ws = gen_cohort(kg, w, n_sub, mix=mix)
        ws_active, ws_lane, ws_tbl, ws_key, ws_kind = ws
    else:
        ttype = jnp.zeros((w,), I32)
        ops = jnp.zeros((w, K), I32)
        tbl = jnp.zeros((w, K), I32)
        kk = jnp.zeros((w, K), I32)
        ws_active = jnp.zeros((w, 2), bool)
        ws_lane = jnp.zeros((w, 2), I32)
        ws_tbl = jnp.zeros((w, 2), I32)
        ws_key = jnp.zeros((w, 2), I32)
        ws_kind = jnp.zeros((w, 2), I32)

    used = ops != Op.NOP
    rows = jnp.where(used, base[tbl] + kk, sent)                # [w, K]
    is_read = ops == Op.OCC_READ

    rmeta = meta[rows]                                          # [w, K]
    vv1 = rmeta >> 1
    rex = (rmeta & 2) != 0
    rmagic = val[rows, 1]
    magic_bad = jnp.sum(is_read & rex & (rmagic != MAGIC), dtype=I32)

    # lock arbitration in [w, 2] write-slot space: first slot wins per row
    # (batched CAS, tatp/ebpf/shard_kern.c:251-297); losers and held rows
    # REJECT. ws_lane points at this txn's lock lanes, so lock state comes
    # from the wave-1 gather — no extra fetch.
    ws_rows = jnp.where(ws_active, base[ws_tbl] + ws_key, sent)  # [w, 2]
    ws_meta = jnp.take_along_axis(rmeta, ws_lane, axis=1)
    ws_vv = jnp.take_along_axis(vv1, ws_lane, axis=1)
    held = (ws_meta & 1) != 0
    flat_ws = ws_rows.reshape(-1)
    slot_idx = jnp.arange(2 * w, dtype=I32)
    arb_rows = jnp.where(ws_active.reshape(-1), flat_ws, oob)
    winner = jnp.full((n1,), BIG, I32).at[arb_rows].min(slot_idx,
                                                       mode="drop")
    grant = (ws_active.reshape(-1) & ~held.reshape(-1)
             & (winner[flat_ws] == slot_idx)).reshape(w, 2)
    meta = meta.at[jnp.where(grant.reshape(-1), flat_ws, oob)].set(
        (ws_vv.reshape(-1) << 1) | 1, mode="drop", unique_indices=True)

    # reply types: reads from the gather; write-slot GRANT/REJECT direct
    rt = jnp.where(is_read & used,
                   jnp.where(rex, Reply.VAL, Reply.NOT_EXIST), Reply.NONE)
    ws_rt = jnp.where(grant, Reply.GRANT,
                      jnp.where(ws_active, Reply.REJECT, Reply.NONE))

    # ---- wave-1 outcome: shared per-txn-type rules ------------------------
    is_ro, rw, granted, lock_rejected, missing = classify_wave1(
        ttype, rt, ops, ws_active, ws_lane, ws_rt=ws_rt)

    new_ctx = DenseCtx(
        rows=rows, is_read=is_read & used, vv1=vv1,
        alive=rw & ~lock_rejected & ~missing,
        ro_commit=is_ro & ~missing, granted=granted,
        ws_rows=ws_rows, ws_vv=ws_vv,
        ws_tbl=ws_tbl, ws_key=ws_key, ws_kind=ws_kind,
        ws_active=ws_active,
        attempted=jnp.asarray(w if gen_new else 0, I32),
        ab_lock=(rw & lock_rejected).sum(dtype=I32),
        ab_missing=((rw & ~lock_rejected & missing)
                    | (is_ro & missing)).sum(dtype=I32),
        ab_validate=jnp.asarray(0, I32),
        magic_bad=magic_bad)

    db = db.replace(val=val, meta=meta, log=logs)
    if emit_installs:
        inst = Installs(
            wmask=wmask, rows=c2.ws_rows.reshape(-1),
            meta=jnp.where(wmask, meta_new, U32(0)),
            val=newval, tbl=log_tbl, key=log_key,
            is_del=flags_del, ver=newver)
        return db, new_ctx, c1, _stats_of(c2), inst
    return db, new_ctx, c1, _stats_of(c2)


def build_pipelined_runner(n_sub: int, w: int = 8192, val_words: int = 10,
                           cohorts_per_block: int = 8, mix=None):
    """jit(scan(pipe_step)) over carry (db, c1, c2); same contract as
    tatp_pipeline.build_pipelined_runner: returns (run, init, drain)."""
    kw = dict(w=w, n_sub=n_sub, val_words=val_words)

    def scan_fn(carry, key):
        db, c1, c2 = carry
        db, new_ctx, c1, stats = pipe_step(db, c1, c2, key, mix=mix, **kw)
        return (db, new_ctx, c1), stats

    def block(carry, key):
        keys = jax.random.split(key, cohorts_per_block)
        return jax.lax.scan(scan_fn, carry, keys)

    def init(db):
        return (db, empty_ctx(w), empty_ctx(w))

    @functools.partial(jax.jit, donate_argnums=0)
    def drain(carry):
        db, c1, c2 = carry
        key = jax.random.PRNGKey(0)
        db, _, c1, s1 = pipe_step(db, c1, c2, key, gen_new=False, **kw)
        db, _, _, s2 = pipe_step(db, empty_ctx(w), c1, key, gen_new=False,
                                 **kw)
        return db, jnp.stack([s1, s2])

    return jax.jit(block, donate_argnums=0), init, drain
