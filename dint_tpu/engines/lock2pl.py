"""lock_2pl: batched no-wait S/X lock server.

TPU equivalent of the reference's 2PL lock server (XDP state machine at
lock_2pl/ebpf/ls_kern.c:33-110: CAS entry spinlock, then grant/reject by
num_sh/num_ex counters; userspace twin lock_2pl/caladan/server.cc:39-105).

Batch serialization contract (same closed form the oracle implements):
per lock slot, releases apply first, then acquires in lane order. Since
no-wait 2PL never blocks, the sequential acquire outcome has a closed form:
  * ex held after releases        -> reject every acquire
  * sh held after releases        -> grant all S, reject all X
  * free: earliest acquire is X   -> grant exactly that X, reject the rest
  * free: earliest acquire is S   -> grant all S, reject all X
RETRY (spinlock busy, lock_2pl/caladan/server.cc:51-57) is never emitted.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops import segments
from ..tables import locks
from .types import Batch, Op, Replies, Reply

I32 = jnp.int32
U32 = jnp.uint32


def step(table: locks.SXLockTable, batch: Batch):
    r = batch.width
    slot = locks.lock_slot(batch.key_hi, batch.key_lo, table.n_slots)
    sb = segments.sort_batch(jnp.zeros((r,), U32), slot.astype(U32))
    op = batch.op[sb.perm]
    s_slot = slot[sb.perm]

    sh0 = table.num_sh[s_slot]
    ex0 = table.num_ex[s_slot]

    is_acq_s = op == Op.ACQ_S
    is_acq_x = op == Op.ACQ_X
    is_acq = is_acq_s | is_acq_x
    rel_s = segments.seg_sum(sb, (op == Op.REL_S).astype(I32))
    rel_x = segments.seg_sum(sb, (op == Op.REL_X).astype(I32))
    sh1 = jnp.maximum(sh0 - rel_s, 0)
    ex1 = jnp.maximum(ex0 - rel_x, 0)

    first_acq = segments.first_rank_where(sb, is_acq)
    pos_first = jnp.clip(sb.head_pos + first_acq, 0, r - 1)
    first_is_x = is_acq_x[pos_first] & (first_acq < (1 << 30))
    x_takes = first_is_x & (sh1 == 0) & (ex1 == 0)

    grant_x = is_acq_x & x_takes & (sb.rank == first_acq)
    grant_s = is_acq_s & (ex1 == 0) & ~x_takes
    granted = grant_s | grant_x

    n_grant_s = segments.seg_sum(sb, grant_s.astype(I32))
    n_grant_x = segments.seg_sum(sb, grant_x.astype(I32))
    new_sh = sh1 + n_grant_s
    new_ex = ex1 + n_grant_x

    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where((op == Op.REL_S) | (op == Op.REL_X), Reply.ACK, rtype)
    rtype = jnp.where(is_acq, jnp.where(granted, Reply.GRANT, Reply.REJECT), rtype)

    touched = (op != Op.NOP)
    writer = sb.last & segments.seg_any(sb, touched)
    table = table.replace(
        num_sh=segments.scatter_rows(table.num_sh, s_slot, new_sh, writer),
        num_ex=segments.scatter_rows(table.num_ex, s_slot, new_ex, writer),
    )
    o_rtype = segments.unsort(sb, rtype)
    zeros = jnp.zeros((r, batch.val.shape[1]), U32)
    return table, Replies(rtype=o_rtype, val=zeros, ver=jnp.zeros((r,), U32))
