"""lock_fasst: batched FaSST-style OCC lock/version server.

TPU equivalent of the reference's OCC primitives in XDP
(lock_fasst/ebpf/ls_kern.c:58-97): READ -> return version; ACQUIRE_LOCK ->
CAS; COMMIT -> ver++, unlock; ABORT -> unlock. Userspace twin with
locks[]+ver_table[] arrays at lock_fasst/caladan/server.cc:30-92.

Batch serialization contract: per slot, commits/aborts (unlocks) first,
then reads (which therefore see post-commit versions), then lock acquires
in lane order — first acquirer wins a free lock, the rest are rejected.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops import segments
from ..tables import locks
from .types import Batch, Op, Replies, Reply

I32 = jnp.int32
U32 = jnp.uint32


def step(table: locks.OCCTable, batch: Batch):
    r = batch.width
    slot = locks.lock_slot(batch.key_hi, batch.key_lo, table.n_slots)
    sb = segments.sort_batch(jnp.zeros((r,), U32), slot.astype(U32))
    op = batch.op[sb.perm]
    s_slot = slot[sb.perm]

    locked0 = table.locked[s_slot]
    ver0 = table.ver[s_slot]

    is_commit = op == Op.COMMIT_VER
    is_abort = op == Op.ABORT
    is_read = op == Op.READ_VER
    is_lock = op == Op.LOCK

    n_commits = segments.seg_sum(sb, is_commit.astype(I32))
    unlock_any = segments.seg_any(sb, is_commit | is_abort)
    ver1 = ver0 + n_commits.astype(U32)
    locked1 = locked0 & ~unlock_any

    first_lock = segments.first_rank_where(sb, is_lock)
    grant = is_lock & ~locked1 & (sb.rank == first_lock)
    new_locked = locked1 | segments.seg_any(sb, grant)

    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where(is_commit | is_abort, Reply.ACK, rtype)
    rtype = jnp.where(is_read, Reply.VAL, rtype)
    rtype = jnp.where(is_lock, jnp.where(grant, Reply.GRANT, Reply.REJECT), rtype)
    rver = jnp.where(is_read, ver1, U32(0))
    # READ_VER also reports the lock bit (reply val word 0), as the
    # reference's validation re-read does — a locked slot fails OCC
    # validation (lock_fasst/caladan/client.cc:199-215). `locked1` is the
    # state after this batch's unlocks, before its acquires (oracle order).
    rlocked = jnp.where(is_read, locked1.astype(U32), U32(0))

    touched = op != Op.NOP
    writer = sb.last & segments.seg_any(sb, touched)
    table = table.replace(
        locked=segments.scatter_rows(table.locked, s_slot, new_locked, writer),
        ver=segments.scatter_rows(table.ver, s_slot, ver1, writer),
    )
    o_rtype, o_rver, o_rlocked = segments.unsort(sb, rtype, rver, rlocked)
    rval = jnp.zeros((r, batch.val.shape[1]), U32).at[:, 0].set(o_rlocked)
    return table, Replies(rtype=o_rtype, val=rval, ver=o_rver)


def step_attr(table, batch: Batch):
    """Lock-attribution variant (the reference's instrumented TATP server,
    tatp/ebpf/lock_kern.c): the lock word carries its holder's key, and a
    rejected LOCK reports REJECT_SAME_KEY when the holder's key equals the
    requester's (true conflict) vs plain REJECT (hash-slot sharing,
    lock_kern.c:292-298). State: tables.locks.OCCAttrTable."""
    from ..tables.locks import OCCAttrTable  # noqa: F401  (type of `table`)

    r = batch.width
    slot = locks.lock_slot(batch.key_hi, batch.key_lo, table.n_slots)
    sb = segments.sort_batch(jnp.zeros((r,), U32), slot.astype(U32))
    op = batch.op[sb.perm]
    k_hi = batch.key_hi[sb.perm]
    k_lo = batch.key_lo[sb.perm]
    s_slot = slot[sb.perm]

    locked0 = table.locked[s_slot]
    ver0 = table.ver[s_slot]
    own_hi0 = table.owner_hi[s_slot]
    own_lo0 = table.owner_lo[s_slot]

    is_commit = op == Op.COMMIT_VER
    is_abort = op == Op.ABORT
    is_read = op == Op.READ_VER
    is_lock = op == Op.LOCK

    n_commits = segments.seg_sum(sb, is_commit.astype(I32))
    unlock_any = segments.seg_any(sb, is_commit | is_abort)
    ver1 = ver0 + n_commits.astype(U32)
    locked1 = locked0 & ~unlock_any

    first_lock = segments.first_rank_where(sb, is_lock)
    grant = is_lock & ~locked1 & (sb.rank == first_lock)
    new_locked = locked1 | segments.seg_any(sb, grant)
    # owner after this batch: the granting lane's key, else the prior owner
    pos_first = jnp.clip(sb.head_pos + first_lock, 0, r - 1)
    won = segments.seg_any(sb, grant)
    new_own_hi = jnp.where(won, k_hi[pos_first], own_hi0)
    new_own_lo = jnp.where(won, k_lo[pos_first], own_lo0)
    # the key a rejected LOCK lost to: pre-held -> table owner; freshly
    # granted this batch -> the winning lane's key
    lose_hi = jnp.where(locked1, own_hi0, new_own_hi)
    lose_lo = jnp.where(locked1, own_lo0, new_own_lo)
    same = (lose_hi == k_hi) & (lose_lo == k_lo)

    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where(is_commit | is_abort, Reply.ACK, rtype)
    rtype = jnp.where(is_read, Reply.VAL, rtype)
    rtype = jnp.where(is_lock,
                      jnp.where(grant, Reply.GRANT,
                                jnp.where(same, Reply.REJECT_SAME_KEY,
                                          Reply.REJECT)), rtype)
    rver = jnp.where(is_read, ver1, U32(0))
    rlocked = jnp.where(is_read, locked1.astype(U32), U32(0))

    touched = op != Op.NOP
    writer = sb.last & segments.seg_any(sb, touched)
    table = table.replace(
        locked=segments.scatter_rows(table.locked, s_slot, new_locked, writer),
        ver=segments.scatter_rows(table.ver, s_slot, ver1, writer),
        owner_hi=segments.scatter_rows(table.owner_hi, s_slot, new_own_hi,
                                       writer),
        owner_lo=segments.scatter_rows(table.owner_lo, s_slot, new_own_lo,
                                       writer),
    )
    o_rtype, o_rver, o_rlocked = segments.unsort(sb, rtype, rver, rlocked)
    rval = jnp.zeros((r, batch.val.shape[1]), U32).at[:, 0].set(o_rlocked)
    return table, Replies(rtype=o_rtype, val=rval, ver=o_rver)
