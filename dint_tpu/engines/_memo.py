"""Process-wide builder memoisation.

Every `build_*_runner` returns a triple of stateless jitted closures;
the only inputs that shape the compiled program are the builder's own
(hashable) arguments. Callers in different modules still pay a full
XLA compile each, because each call creates fresh `jax.jit` objects —
in the test suite that means the same dense engine at the same
geometry compiles once per test FILE, and in the serving plane a
restarted engine recompiles its whole width menu. Memoising the
builder collapses those to one compile per distinct configuration per
process. Unhashable arguments (shouldn't happen, but e.g. an ad-hoc
dict) fall back to an uncached build rather than failing.
"""
from __future__ import annotations

import functools
import os

# builders resolve None-valued knobs from these at BUILD time
# (ops/pallas_gather.resolve_use_*, monitor/txnevents trace defaults),
# so the ambient values are part of the compiled program's identity —
# fold a snapshot into the key or a monkeypatched env would hit a
# stale entry
_ENV_KNOBS = ("DINT_USE_PALLAS", "DINT_USE_FUSED", "DINT_USE_HOTSET",
              "DINT_PALLAS_INTERPRET", "DINT_TRACE", "DINT_TRACE_RATE",
              "DINT_TRACE_CAP")


def memoize_builder(fn):
    cache: dict = {}

    @functools.wraps(fn)
    def wrapped(*args, **kw):
        env = tuple(os.environ.get(k) for k in _ENV_KNOBS)
        try:
            key = (args, tuple(sorted(kw.items())), env)
            hit = cache.get(key)         # hashing happens here too (ndarray
        except TypeError:                # mix= etc.): build uncached
            return fn(*args, **kw)
        if hit is None:
            hit = cache[key] = fn(*args, **kw)
        return hit

    wrapped.cache = cache        # introspection / explicit clears in tests
    return wrapped
