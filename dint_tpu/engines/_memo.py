"""Process-wide builder memoisation.

Every `build_*_runner` returns a triple of stateless jitted closures;
the only inputs that shape the compiled program are the builder's own
(hashable) arguments. Callers in different modules still pay a full
XLA compile each, because each call creates fresh `jax.jit` objects —
in the test suite that means the same dense engine at the same
geometry compiles once per test FILE, and in the serving plane a
restarted engine recompiles its whole width menu. Memoising the
builder collapses those to one compile per distinct configuration per
process. Unhashable arguments (shouldn't happen, but e.g. an ad-hoc
dict) fall back to an uncached build rather than failing.
"""
from __future__ import annotations

import functools

# builders resolve None-valued knobs from the ambient environment at
# BUILD time (ops/pallas_gather.resolve_use_*, monitor/txnevents trace
# defaults), so those values are part of the compiled program's
# identity — fold a snapshot into the key or a monkeypatched env would
# hit a stale entry. The snapshot is analysis/plan.env_knob_signature():
# the CANONICALIZED resolution of every build-identity knob, from the
# same single resolver the builders and the plan checker use — unset,
# "" and "0" (all False to a builder) share one memo entry, and the
# memo key can never disagree with the builder about what a flag means.


def _env_signature() -> tuple:
    from ..analysis import plan           # deferred: engines must import
    return plan.env_knob_signature()      # without the analysis package


def memoize_builder(fn):
    cache: dict = {}

    @functools.wraps(fn)
    def wrapped(*args, **kw):
        env = _env_signature()
        try:
            key = (args, tuple(sorted(kw.items())), env)
            hit = cache.get(key)         # hashing happens here too (ndarray
        except TypeError:                # mix= etc.): build uncached
            return fn(*args, **kw)
        if hit is None:
            hit = cache[key] = fn(*args, **kw)
        return hit

    wrapped.cache = cache        # introspection / explicit clears in tests
    return wrapped
