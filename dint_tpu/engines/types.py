"""Batch/reply containers and op/reply codes shared by all server engines.

A server engine is a pure function ``step(state, batch) -> (state, replies)``
over fixed-shape arrays — the batched equivalent of the reference's
per-packet XDP state machine `(request_packet, table_state) ->
(reply_packet, table_state')` (e.g. /root/reference/tatp/ebpf/shard_kern.c:111).

Batches are fixed width R; unused lanes carry ``op == NOP`` and
``key == PAD_KEY``. Request arrival order is the lane index — intra-batch
conflict resolution is serial-equivalent to processing lanes in index order
(per key), see dint_tpu.ops.segments.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32
U32 = jnp.uint32

# Reserved key for padding lanes (never a legal application key).
PAD_KEY = 0xFFFFFFFFFFFFFFFF


class Op:
    """Request op codes (superset across engines; each engine uses a subset)."""
    NOP = 0
    # store / KV
    GET = 1
    SET = 2
    INSERT = 3
    DELETE = 4
    # 2PL lock server (lock_2pl/ebpf/ls_kern.c:33-110)
    ACQ_S = 5
    ACQ_X = 6
    REL_S = 7
    REL_X = 8
    # FaSST OCC (lock_fasst/ebpf/ls_kern.c:58-97)
    READ_VER = 9
    LOCK = 10
    COMMIT_VER = 11
    ABORT = 12
    # log server (log_server/ebpf/ls_kern.c:40-78)
    LOG_APPEND = 13
    # txn engines (smallbank/tatp): fused lock+read / commit pipeline ops,
    # mirroring smallbank/ebpf/shard_kern.c:96-666 & tatp/ebpf/shard_kern.c:140-939
    ACQ_S_READ = 14    # acquire shared + read value in one RTT
    ACQ_X_READ = 15    # acquire exclusive + read value in one RTT
    OCC_READ = 16      # read value + version (no lock)
    OCC_LOCK = 17      # CAS row lock
    COMMIT_PRIM = 18   # install value, ver++, release row lock
    COMMIT_BCK = 19    # install value+ver on backup replica
    COMMIT_LOG = 20    # append to replication log
    INSERT_PRIM = 21
    DELETE_PRIM = 22
    INSERT_BCK = 23
    DELETE_BCK = 24
    DELETE_LOG = 25
    # dintscan range scan over the ordered run (tables/run.py): key is the
    # start key, `ver` carries the requested row count (clipped to the
    # engine's static scan_max). Replies land in ScanReplies; the lane's
    # Replies slot carries VAL + the row count in `ver` (or RETRY when the
    # run overlay is stale and the scan must be re-sent after a rebuild).
    SCAN = 26


class Reply:
    """Reply codes; names follow the reference's packet-type enums
    (smallbank/caladan/proto.h:14-37, tatp/udp/net.h:15-52)."""
    NONE = 0
    GRANT = 1          # lock granted (carries value for fused lock+read)
    REJECT = 2         # no-wait lock reject / OCC lock busy
    RETRY = 3          # reference-only (entry spinlock busy); never emitted on TPU
    ACK = 4            # release/commit/log/set ack
    NOT_EXIST = 5      # bloom-negative / missing key
    VAL = 6            # read reply carrying value+version
    SPILL = 7          # bucket overflow: host must take over this key
    REJECT_SAME_KEY = 8  # lock-attribution variant: holder has the SAME key
                         # (true conflict, not hash sharing) — the reference's
                         # REJECT_LOCK_SAME_KEY (tatp/ebpf/lock_kern.c:292-298)
    TIMEOUT = 9        # transport-level sentinel: the wire client exhausted
                       # its resend budget for this lane. Never emitted by an
                       # engine; the reference client resends forever
                       # (client_ebpf_shard.cc:643-677) so loss shows up as
                       # latency — here a capped retry loop surfaces it as an
                       # ab_timeout txn instead of voiding the whole run


@flax.struct.dataclass
class Batch:
    """A fixed-width batch of requests (struct-of-arrays).

    Mirrors `struct message` fields {ord, type, table, key, val, ver}
    (tatp/ebpf/utils.h:80-87); `ord` is implicit as the lane index.
    """
    op: jax.Array       # i32 [R]
    table: jax.Array    # i32 [R] (table id for multi-table engines)
    key_hi: jax.Array   # u32 [R]
    key_lo: jax.Array   # u32 [R]
    val: jax.Array      # u32 [R, VW]
    ver: jax.Array      # u32 [R]

    @property
    def width(self):
        return self.op.shape[0]


@flax.struct.dataclass
class Replies:
    rtype: jax.Array    # i32 [R]
    val: jax.Array      # u32 [R, VW]
    ver: jax.Array      # u32 [R]


@flax.struct.dataclass
class ScanReplies:
    """Row slabs for Op.SCAN lanes (zero rows for non-scan lanes).

    Rows are the first `count` live keys >= the lane's start key in the
    merged run∪delta view, in key order; rows past count are zeroed.
    Per-row versions ride along so an OCC coordinator can validate a
    scanned range like any other read set (FaSST OSDI'16 §4.3).
    `delta_hits` counts rows served from the write-through overlay rather
    than the sorted run — a freshness diagnostic (dintmon
    scan_delta_hits), not part of the serial-order contract."""
    key_hi: jax.Array   # u32 [R, SMAX]
    key_lo: jax.Array   # u32 [R, SMAX]
    ver: jax.Array      # u32 [R, SMAX]
    val: jax.Array      # u32 [R, SMAX, VW]
    count: jax.Array    # i32 [R]
    delta_hits: jax.Array  # i32 [R]


def make_batch(ops, keys, vals=None, vers=None, tables=None, width=None,
               val_words: int = 10) -> Batch:
    """Host-side batch builder (numpy in, pytree of jnp out), with padding."""
    from ..ops import u64

    ops = np.asarray(ops, np.int32)
    keys = np.asarray(keys, np.uint64)
    r = len(ops)
    width = width or r
    assert width >= r
    pad = width - r

    def _pad(x, fill=0):
        if pad == 0:
            return x
        shape = (pad,) + x.shape[1:]
        return np.concatenate([x, np.full(shape, fill, x.dtype)])

    ops = _pad(ops)
    keys = _pad(keys, PAD_KEY)
    hi, lo = u64.split(keys)
    if vals is None:
        vals = np.zeros((r, val_words), np.uint32)
    vals = _pad(np.asarray(vals, np.uint32))
    vers = _pad(np.asarray(vers if vers is not None else np.zeros(r), np.uint32))
    tables = _pad(np.asarray(tables if tables is not None else np.zeros(r), np.int32))
    return Batch(op=jnp.asarray(ops), table=jnp.asarray(tables),
                 key_hi=jnp.asarray(hi), key_lo=jnp.asarray(lo),
                 val=jnp.asarray(vals), ver=jnp.asarray(vers))
