"""SmallBank shard server engine: 2PL + replication over dense tables.

TPU equivalent of the reference's SmallBank txn server
(smallbank/ebpf/shard_kern.c): per shard, SAVINGS + CHECKING tables with
S/X lock units, a replication log, and fused lock+read ops —
ACQUIRE_{SHARED,EXCLUSIVE} lock *and* return value+version in one RTT
(shard_kern.c:96-328), RELEASE_* (:330-392), COMMIT_PRIM/BCK (install value,
bump version, :394-564), COMMIT_LOG (:566-583).

TPU-first deltas from the reference:
  * accounts are dense 0..N-1, so values/versions/locks are direct-indexed
    HBM arrays — no hash probe, and per-account locks are exact rather than
    hash-conflated (reference: fasthash64 % SAV_LOCK_SIZE).
  * each engine instance is one shard holding the full replicated keyspace
    (reference: every record lives on all 3 servers; primary = key % 3,
    smallbank/caladan/client_ebpf_shard.cc:287-289).

Batch serialization contract (per (table, account) group): releases first,
then commit installs (newest version wins), then lock acquires with fused
reads (which therefore see committed values) in lane order — closed-form,
like engines.lock2pl.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from ..ops import segments
from ..tables import dense, log as logring
from .types import Batch, Op, Replies, Reply

I32 = jnp.int32
U32 = jnp.uint32

SAVINGS = 0
CHECKING = 1


@flax.struct.dataclass
class Shard:
    sav: dense.DenseTable
    chk: dense.DenseTable
    sav_sh: jax.Array   # i32 [N] shared-lock counts
    sav_ex: jax.Array   # i32 [N] exclusive-lock counts
    chk_sh: jax.Array
    chk_ex: jax.Array
    log: logring.LogRing

    @property
    def n_accounts(self):
        return self.sav.size


def create(n_accounts: int, val_words: int = 2, log_lanes: int = 16,
           log_capacity: int = 1 << 20) -> Shard:
    return Shard(
        sav=dense.create(n_accounts, val_words),
        chk=dense.create(n_accounts, val_words),
        sav_sh=jnp.zeros((n_accounts,), I32),
        sav_ex=jnp.zeros((n_accounts,), I32),
        chk_sh=jnp.zeros((n_accounts,), I32),
        chk_ex=jnp.zeros((n_accounts,), I32),
        log=logring.create(log_lanes, log_capacity, val_words),
    )


def _gather(shard: Shard, is_chk, acct):
    sh0 = jnp.where(is_chk, shard.chk_sh[acct], shard.sav_sh[acct])
    ex0 = jnp.where(is_chk, shard.chk_ex[acct], shard.sav_ex[acct])
    val0 = jnp.where(is_chk[:, None], dense.gather_rows(shard.chk, acct),
                     dense.gather_rows(shard.sav, acct))
    ver0 = jnp.where(is_chk, shard.chk.ver[acct], shard.sav.ver[acct])
    return sh0, ex0, val0, ver0


def step(shard: Shard, batch: Batch):
    """Certify and apply one batch against this shard. Returns (shard', replies)."""
    r = batch.width
    # group by (table, account): table id in the sort key's high word
    sb = segments.sort_batch(batch.table.astype(U32), batch.key_lo)
    op = batch.op[sb.perm]
    val_in = batch.val[sb.perm]
    ver_in = batch.ver[sb.perm]
    is_chk = sb.key_hi == U32(CHECKING)
    acct = sb.key_lo.astype(I32)

    sh0, ex0, val0, ver0 = _gather(shard, is_chk, acct)

    # --- phase 1: releases --------------------------------------------------
    rel_s = segments.seg_sum(sb, (op == Op.REL_S).astype(I32))
    rel_x = segments.seg_sum(sb, (op == Op.REL_X).astype(I32))
    sh1 = jnp.maximum(sh0 - rel_s, 0)
    ex1 = jnp.maximum(ex0 - rel_x, 0)

    # --- phase 2: commit installs (newest version wins) ---------------------
    is_commit = (op == Op.COMMIT_PRIM) | (op == Op.COMMIT_BCK)
    max_cver = segments.seg_max_where(sb, is_commit, ver_in.astype(I32), I32(-1))
    install = max_cver > ver0.astype(I32)
    # the lane carrying the winning version supplies the value
    win_rank = segments.seg_min_where(
        sb, is_commit & (ver_in.astype(I32) == max_cver), sb.rank, I32(1 << 30))
    pos_win = jnp.clip(sb.head_pos + win_rank, 0, r - 1)
    val1 = jnp.where(install[:, None], val_in[pos_win], val0)
    ver1 = jnp.where(install, max_cver.astype(U32), ver0)

    # --- phase 3: lock acquires with fused read -----------------------------
    is_acq_s = op == Op.ACQ_S_READ
    is_acq_x = op == Op.ACQ_X_READ
    is_acq = is_acq_s | is_acq_x
    first_acq = segments.first_rank_where(sb, is_acq)
    pos_first = jnp.clip(sb.head_pos + first_acq, 0, r - 1)
    first_is_x = is_acq_x[pos_first] & (first_acq < (1 << 30))
    x_takes = first_is_x & (sh1 == 0) & (ex1 == 0)
    grant_x = is_acq_x & x_takes & (sb.rank == first_acq)
    grant_s = is_acq_s & (ex1 == 0) & ~x_takes
    granted = grant_s | grant_x
    new_sh = sh1 + segments.seg_sum(sb, grant_s.astype(I32))
    new_ex = ex1 + segments.seg_sum(sb, grant_x.astype(I32))

    # --- replies ------------------------------------------------------------
    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where((op == Op.REL_S) | (op == Op.REL_X), Reply.ACK, rtype)
    rtype = jnp.where(is_commit | (op == Op.COMMIT_LOG), Reply.ACK, rtype)
    rtype = jnp.where(is_acq, jnp.where(granted, Reply.GRANT, Reply.REJECT), rtype)
    rval = jnp.where(granted[:, None], val1, jnp.zeros_like(val1))
    rver = jnp.where(granted, ver1, U32(0))

    # --- scatters: one writer per (table, account) segment ------------------
    writer = sb.last & segments.seg_any(sb, op != Op.NOP)
    w_sav = writer & ~is_chk
    w_chk = writer & is_chk
    v_sav = w_sav & segments.seg_any(sb, is_commit & install)
    v_chk = w_chk & segments.seg_any(sb, is_commit & install)
    shard = shard.replace(
        sav_sh=segments.scatter_rows(shard.sav_sh, acct, new_sh, w_sav),
        sav_ex=segments.scatter_rows(shard.sav_ex, acct, new_ex, w_sav),
        chk_sh=segments.scatter_rows(shard.chk_sh, acct, new_sh, w_chk),
        chk_ex=segments.scatter_rows(shard.chk_ex, acct, new_ex, w_chk),
        sav=shard.sav.replace(
            val=dense.scatter_rows_val(shard.sav, acct, val1, v_sav),
            ver=segments.scatter_rows(shard.sav.ver, acct, ver1, v_sav)),
        chk=shard.chk.replace(
            val=dense.scatter_rows_val(shard.chk, acct, val1, v_chk),
            ver=segments.scatter_rows(shard.chk.ver, acct, ver1, v_chk)),
    )

    # --- replication log append (original lane order) -----------------------
    do_log = batch.op == Op.COMMIT_LOG
    new_log, _, _ = logring.append(
        shard.log, do_log, batch.table, jnp.zeros_like(batch.op),
        batch.key_hi, batch.key_lo, batch.ver, batch.val)
    shard = shard.replace(log=new_log)

    o_rtype, o_rver = segments.unsort(sb, rtype, rver)
    o_rval = segments.unsort(sb, rval)
    return shard, Replies(rtype=o_rtype, val=o_rval, ver=o_rver)
