"""Device-fused TATP transaction pipeline: whole txns in one jitted step.

The reference's client-side coordinator (tatp/caladan/client_ebpf_shard.cc)
drives each transaction through 5+ network RTTs against 3 replicated shard
servers (read+lock -> validate -> CommitLog x3 -> CommitBck x2 -> CommitPrim,
SURVEY.md §3.3). The host-side port of that coordinator
(clients/tatp_client.py) keeps the same wave structure but pays a
host<->device round trip per wave — which dominates when the TPU sits behind
a network tunnel.

This module is the TPU-first re-design: the *entire* cohort pipeline —
workload generation (NURand ids, txn mix), per-shard routing, all three
certification waves, replication fan-out, and abort accounting — runs inside
one jitted function over the 3 shard replicas (vmapped `tatp.step`), and a
`lax.scan` runs many cohorts per dispatch. Host traffic per scan block is one
RNG key in and one small stats matrix out.

The 3 "servers" are a stacked leading axis on the Shard pytree. A lane's
op differs per shard (NOP unless routed there; PRIM at the owner vs BCK at
backups), which is exactly the reference's per-shard message batches
(client_ebpf_shard.cc:636-641) — expressed as a [3, R] op array instead of
3 socket fan-outs.

Wave structure per cohort (3 vmapped steps total):
  wave 1  [R=4w lanes]  OCC_READ read-set + OCC_LOCK write-set at owners
  wave 2  [R lanes]     validate: re-read read-set of surviving RW txns
  wave 3  [4w lanes]    log block (COMMIT/DELETE_LOG on all shards) +
                        role block (PRIM at owner / BCK at backups / ABORT
                        of granted locks of dead txns at owner)

Abort semantics mirror clients/tatp_client.py lane for lane (which itself
mirrors client_ebpf_shard.cc:608-900); stats categories are disjoint:
ab_lock (write-set lock rejected), ab_missing (required row absent /
insert-exists), ab_validate (read-set version changed).
"""
from __future__ import annotations

import functools

import flax.struct
import jax
import jax.numpy as jnp

from ..clients import workloads as wl
from ._memo import memoize_builder
from ..monitor import counters as mon
from ..monitor import waves
from . import tatp
from .types import Batch, Op, PAD_KEY, Reply

I32 = jnp.int32
U32 = jnp.uint32

N_SHARDS = 3
K = 4                  # wave-1 lanes per txn
MAGIC = 0x7A79         # parity with clients/tatp_client.py

# stats vector layout
STAT_ATTEMPTED = 0
STAT_COMMITTED = 1
STAT_AB_LOCK = 2
STAT_AB_MISSING = 3
STAT_AB_VALIDATE = 4
STAT_MAGIC_BAD = 5
N_STATS = 6


def stack_shards(shards) -> tatp.Shard:
    """[Shard] * 3 -> one Shard pytree with leading [3] device axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *shards)


def _broadcast_batch(op_s, table, key_lo, val, ver):
    """Per-shard op array [S, R] + shared lane fields [R] -> stacked Batch."""
    s = op_s.shape[0]

    def bc(x):
        return jnp.broadcast_to(x[None], (s,) + x.shape)

    return Batch(op=op_s, table=bc(table),
                 key_hi=bc(jnp.zeros_like(key_lo)), key_lo=bc(key_lo),
                 val=bc(val), ver=bc(ver))


def _merge(owner, stacked):
    """Pick each lane's reply from its owner shard: [S, R...] -> [R...]."""
    r = owner.shape[0]
    return stacked[owner, jnp.arange(r)]


def gen_cohort(key, w: int, n_sub: int, mix=None):
    """On-device workload generation (tatp/caladan/tatp.h:40-63).

    One `random.bits` draw feeds every field via modular reduction — the
    same arithmetic the reference's generators use (`rand() % n`,
    tatp/caladan/tatp.h:40-43); the txn type comes from a searchsorted
    over the cumulative mix, which is exactly the reference's
    proportion-filled workgen array (store/caladan/client_caladan.cc:56-66)
    in closed form. 4 threefry splits + a weighted `choice` measured
    ~2.3 ms per 8192-txn step on v5e — 40% of the whole fused step — and
    this is ~6x cheaper.

    Returns (ttype [w], lane ops/tbl/keys [w, K], write-slot arrays [w, 2]).
    """
    bits = jax.random.bits(key, (w, 4), U32)
    thresh = jnp.asarray(wl.mix_thresholds(
        wl.TATP_MIX if mix is None else mix))
    ttype = jnp.minimum(
        jnp.searchsorted(thresh, bits[:, 0], side="right"), 6).astype(I32)
    # NURand: ((x | y) % n) + 1
    x = (bits[:, 1] % U32(wl.TATP_A + 1)).astype(I32)
    y = (bits[:, 2] % U32(n_sub)).astype(I32) + 1
    s_id = ((x | y) % n_sub) + 1
    kx = bits[:, 3]
    xtype = (kx % 4 + 1).astype(I32)          # ai_type / sf_type 1..4
    stime = ((kx >> 2) % 3).astype(I32) * 8   # 0 / 8 / 16

    sf_idx = s_id * 4 + (xtype - 1)
    ai_idx = sf_idx
    cfk = tatp.cf_key(s_id, xtype, stime)

    T = tatp
    t = ttype
    ops = jnp.zeros((w, K), I32)
    tbl = jnp.zeros((w, K), I32)
    kk = jnp.zeros((w, K), I32)

    def put(ops, tbl, kk, mask, lane, op, tb, keyv):
        ops = ops.at[:, lane].set(jnp.where(mask, op, ops[:, lane]))
        tbl = tbl.at[:, lane].set(jnp.where(mask, tb, tbl[:, lane]))
        kk = kk.at[:, lane].set(jnp.where(mask, keyv, kk[:, lane]))
        return ops, tbl, kk

    m = t == wl.TATP_GET_SUBSCRIBER
    ops, tbl, kk = put(ops, tbl, kk, m, 0, Op.OCC_READ, T.SUBSCRIBER, s_id)
    m = t == wl.TATP_GET_ACCESS
    ops, tbl, kk = put(ops, tbl, kk, m, 0, Op.OCC_READ, T.ACCESS_INFO, ai_idx)
    m = t == wl.TATP_GET_NEW_DEST
    ops, tbl, kk = put(ops, tbl, kk, m, 0, Op.OCC_READ, T.SPECIAL_FACILITY, sf_idx)
    ops, tbl, kk = put(ops, tbl, kk, m, 1, Op.OCC_READ, T.CALL_FORWARDING, cfk)
    m = t == wl.TATP_UPDATE_SUBSCRIBER
    ops, tbl, kk = put(ops, tbl, kk, m, 0, Op.OCC_READ, T.SUBSCRIBER, s_id)
    ops, tbl, kk = put(ops, tbl, kk, m, 1, Op.OCC_READ, T.SPECIAL_FACILITY, sf_idx)
    ops, tbl, kk = put(ops, tbl, kk, m, 2, Op.OCC_LOCK, T.SUBSCRIBER, s_id)
    ops, tbl, kk = put(ops, tbl, kk, m, 3, Op.OCC_LOCK, T.SPECIAL_FACILITY, sf_idx)
    m = t == wl.TATP_UPDATE_LOCATION
    ops, tbl, kk = put(ops, tbl, kk, m, 0, Op.OCC_READ, T.SEC_SUBSCRIBER, s_id)
    ops, tbl, kk = put(ops, tbl, kk, m, 1, Op.OCC_READ, T.SUBSCRIBER, s_id)
    ops, tbl, kk = put(ops, tbl, kk, m, 2, Op.OCC_LOCK, T.SUBSCRIBER, s_id)
    m = t == wl.TATP_INSERT_CF
    ops, tbl, kk = put(ops, tbl, kk, m, 0, Op.OCC_READ, T.SPECIAL_FACILITY, sf_idx)
    ops, tbl, kk = put(ops, tbl, kk, m, 1, Op.OCC_READ, T.CALL_FORWARDING, cfk)
    ops, tbl, kk = put(ops, tbl, kk, m, 2, Op.OCC_LOCK, T.CALL_FORWARDING, cfk)
    m = t == wl.TATP_DELETE_CF
    ops, tbl, kk = put(ops, tbl, kk, m, 0, Op.OCC_READ, T.CALL_FORWARDING, cfk)
    ops, tbl, kk = put(ops, tbl, kk, m, 1, Op.OCC_LOCK, T.CALL_FORWARDING, cfk)

    # write slots (== lock lanes): (active, lane_idx, table, key, kind)
    # kind: 0 = commit (dense install), 1 = insert (CF), 2 = delete (CF)
    is_us = t == wl.TATP_UPDATE_SUBSCRIBER
    is_ul = t == wl.TATP_UPDATE_LOCATION
    is_ic = t == wl.TATP_INSERT_CF
    is_dc = t == wl.TATP_DELETE_CF
    ws_active = jnp.stack([is_us | is_ul | is_ic | is_dc, is_us], axis=1)
    ws_lane = jnp.stack([jnp.where(is_dc, 1, 2), jnp.full((w,), 3, I32)], axis=1)
    ws_tbl = jnp.stack([
        jnp.where(is_us | is_ul, T.SUBSCRIBER, T.CALL_FORWARDING),
        jnp.full((w,), T.SPECIAL_FACILITY, I32)], axis=1)
    ws_key = jnp.stack([
        jnp.where(is_us | is_ul, s_id, cfk), sf_idx], axis=1)
    ws_kind = jnp.stack([
        jnp.where(is_ic, 1, jnp.where(is_dc, 2, 0)),
        jnp.zeros((w,), I32)], axis=1)
    return ttype, ops, tbl, kk, (ws_active, ws_lane, ws_tbl, ws_key, ws_kind)


def cohort_step(stacked: tatp.Shard, key, *, w: int, n_sub: int,
                val_words: int, validate: bool = True):
    """One full cohort of w txns against the 3 stacked replicas.

    ``validate`` (static) keeps the reference protocol's wave-2 read-set
    re-read (client_ebpf_shard.cc:688-768). In this fused pipeline it is
    *protocol-parity ballast*: cohorts serialize on the device, no commit can
    land between a txn's read and its validation, so ab_validate is
    structurally 0 — the wave is kept (and benchmarked) to pay the same
    per-txn work the reference client pays. ``validate=False`` is the
    TPU-first fast path: batch lock certification subsumes validation, a
    design win the reference cannot express.

    Returns (stacked', stats [N_STATS] i32)."""
    step_v = jax.vmap(tatp.step)
    kg, kv = jax.random.split(key)
    ttype, ops, tbl, kk, ws = gen_cohort(kg, w, n_sub)
    ws_active, ws_lane, ws_tbl, ws_key, ws_kind = ws
    r = w * K

    lane_op = ops.reshape(r)
    lane_tbl = tbl.reshape(r)
    lane_key = kk.reshape(r).astype(U32)
    used = lane_op != Op.NOP
    # NOP lanes get the pad key so they never join a real key's segment
    lane_key = jnp.where(used, lane_key, U32(PAD_KEY & 0xFFFFFFFF))
    owner = (kk.reshape(r) % N_SHARDS).astype(I32)
    sid = jnp.arange(N_SHARDS, dtype=I32)

    zval = jnp.zeros((r, val_words), U32)
    zver = jnp.zeros((r,), U32)

    # ---- wave 1: read + lock at owners ------------------------------------
    op_s = jnp.where((owner[None] == sid[:, None]) & used[None],
                     lane_op[None], Op.NOP)
    stacked, rep1 = step_v(stacked, _broadcast_batch(op_s, lane_tbl, lane_key,
                                                     zval, zver))
    rt1 = _merge(owner, rep1.rtype).reshape(w, K)
    rv1 = _merge(owner, rep1.val)
    rver1 = _merge(owner, rep1.ver).reshape(w, K)

    is_val_lane = rt1.reshape(r) == Reply.VAL
    magic_bad = jnp.sum(is_val_lane & (rv1[:, 1] != MAGIC), dtype=I32)

    # ---- outcome of wave 1 (generated cohorts always have a lane-0 op, so
    # classify_wave1's NOP guard is vacuous here) ---------------------------
    is_ro, rw, granted, lock_rejected, missing = classify_wave1(
        ttype, rt1, ops, ws_active, ws_lane)

    ab_lock = rw & lock_rejected
    ab_missing = rw & ~lock_rejected & missing
    alive = rw & ~lock_rejected & ~missing

    # ---- wave 2: validate read-set of surviving RW txns --------------------
    if validate:
        is_read_lane = (ops == Op.OCC_READ) & alive[:, None]
        v_op = jnp.where(is_read_lane.reshape(r), Op.OCC_READ, Op.NOP)
        v_used = v_op != Op.NOP
        v_key = jnp.where(v_used, kk.reshape(r).astype(U32),
                          U32(PAD_KEY & 0xFFFFFFFF))
        op_s2 = jnp.where((owner[None] == sid[:, None]) & v_used[None],
                          v_op[None], Op.NOP)
        stacked, rep2 = step_v(stacked, _broadcast_batch(op_s2, lane_tbl,
                                                         v_key, zval, zver))
        vrt = _merge(owner, rep2.rtype).reshape(w, K)
        vver = _merge(owner, rep2.ver).reshape(w, K)
        bad_lane = is_read_lane & (
            (vver != rver1) | ((vrt != Reply.VAL) & (rt1 == Reply.VAL)))
        changed = bad_lane.any(axis=1)
    else:
        changed = jnp.zeros((w,), bool)
    ab_validate = alive & changed
    alive = alive & ~changed

    # ---- wave 3: log block + role block (prim/bck/abort) -------------------
    # lanes: [log ws0 | log ws1 | role ws0 | role ws1], each w wide
    w_owner = (ws_key % N_SHARDS).astype(I32)              # [w, 2]
    do_write = ws_active & alive[:, None]
    newval = jnp.zeros((w, 2, val_words), U32)
    payload = jax.random.randint(kv, (w, 2), 0, 1 << 16, dtype=I32)
    newval = newval.at[:, :, 0].set(payload.astype(U32))
    newval = newval.at[:, :, 1].set(jnp.where(do_write, U32(MAGIC), U32(0)))

    log_op = jnp.where(do_write,
                       jnp.where(ws_kind == 2, Op.DELETE_LOG, Op.COMMIT_LOG),
                       Op.NOP)                              # [w, 2], all shards
    prim_op = jnp.select([ws_kind == 1, ws_kind == 2],
                         [Op.INSERT_PRIM, Op.DELETE_PRIM], Op.COMMIT_PRIM)
    bck_op = jnp.select([ws_kind == 1, ws_kind == 2],
                        [Op.INSERT_BCK, Op.DELETE_BCK], Op.COMMIT_BCK)
    # role op per shard s: owner -> prim; others -> bck; dead+granted -> ABORT
    dead_abort = granted & ~alive[:, None]
    role_s = jnp.where(
        do_write[None], jnp.where(w_owner[None] == sid[:, None, None],
                                  prim_op[None], bck_op[None]),
        jnp.where(dead_abort[None] & (w_owner[None] == sid[:, None, None]),
                  Op.ABORT, Op.NOP))                        # [S, w, 2]

    c_used = do_write | dead_abort
    c_key = jnp.where(c_used, ws_key.astype(U32), U32(PAD_KEY & 0xFFFFFFFF))
    lane3_key = jnp.concatenate([c_key[:, 0], c_key[:, 1],
                                 c_key[:, 0], c_key[:, 1]])
    lane3_tbl = jnp.concatenate([ws_tbl[:, 0], ws_tbl[:, 1],
                                 ws_tbl[:, 0], ws_tbl[:, 1]])
    lane3_val = jnp.concatenate([newval[:, 0], newval[:, 1],
                                 newval[:, 0], newval[:, 1]])
    op3_s = jnp.concatenate([
        jnp.broadcast_to(log_op[:, 0][None], (N_SHARDS, w)),
        jnp.broadcast_to(log_op[:, 1][None], (N_SHARDS, w)),
        role_s[:, :, 0], role_s[:, :, 1]], axis=1)
    zver3 = jnp.zeros((w * 4,), U32)
    stacked, _ = step_v(stacked, _broadcast_batch(
        op3_s, lane3_tbl, lane3_key, lane3_val, zver3))

    committed = (is_ro & ~missing) | alive
    stats = jnp.stack([
        jnp.asarray(w, I32),
        committed.sum(dtype=I32),
        ab_lock.sum(dtype=I32),
        (ab_missing | (is_ro & missing)).sum(dtype=I32),
        ab_validate.sum(dtype=I32),
        magic_bad,
    ])
    return stacked, stats


# --------------------------------------------------------------------------
# Cross-cohort software pipeline: REAL concurrency between transactions.
#
# The serialized cohort_step above runs read+lock -> validate -> commit to
# completion per cohort, so no commit can ever land between a txn's read and
# its validation (ab_validate is structurally 0 — the honest caveat in its
# docstring). This pipeline overlaps cohort lifetimes exactly like the
# reference's thousands of concurrently in-flight client txns
# (tatp/caladan/client_ebpf_shard.cc:1589-1613): device step t executes, in
# ONE combined batch,
#
#   wave 1 of cohort t     (read + lock at owners)
#   wave 2 of cohort t-1   (validate re-reads)
#   wave 3 of cohort t-2   (log x3 / prim / bck / abort)
#
# The engine's per-row phase order (commits install and release BEFORE
# reads, lock acquires LAST — engines/tatp._dense_step) gives the reference
# interleaving: cohort t-2's commits are visible to cohort t-1's validation
# re-reads, so a version bumped between read (step t-1) and validate
# (step t) aborts the txn — ab_validate is live and responds to contention.
# Locks held by in-flight cohorts likewise reject younger cohorts' lock
# attempts (no-wait, first-wins), raising ab_lock under skew. Validation is
# version-compare only, exactly the reference's verify stage
# (client_ebpf_shard.cc:765-768) — reads do not check row locks.
# --------------------------------------------------------------------------


@flax.struct.dataclass
class PipeCtx:
    """An in-flight cohort between pipeline stages (all [w]-shaped unless
    noted). Bootstrap cohorts have attempted == 0 and all-False masks, so
    they contribute NOP lanes and zero stats."""
    ops: jax.Array        # i32 [w, K] wave-1 lane ops
    tbl: jax.Array        # i32 [w, K]
    kk: jax.Array         # i32 [w, K] lane keys
    rver1: jax.Array      # u32 [w, K] versions read at wave 1
    rt1_val: jax.Array    # bool [w, K] lane replied VAL at wave 1
    granted: jax.Array    # bool [w, 2] write-slot locks granted
    alive: jax.Array      # bool [w] still commit-eligible
    ro_commit: jax.Array  # bool [w] read-only txn that succeeded at wave 1
    ws_active: jax.Array  # bool [w, 2]
    ws_tbl: jax.Array     # i32 [w, 2]
    ws_key: jax.Array     # i32 [w, 2]
    ws_kind: jax.Array    # i32 [w, 2] 0 commit / 1 insert / 2 delete
    attempted: jax.Array  # i32 scalar (w, or 0 for bootstrap)
    ab_lock: jax.Array    # i32 scalar
    ab_missing: jax.Array # i32 scalar
    ab_validate: jax.Array  # i32 scalar (set by the validate stage)
    magic_bad: jax.Array  # i32 scalar


def empty_ctx(w: int) -> PipeCtx:
    # every field materializes its OWN device buffer (via a fresh numpy
    # array): the runner donates the whole carry, and XLA rejects donating
    # an aliased buffer twice
    import numpy as np

    def z(shape, dt):
        return jnp.asarray(np.zeros(shape, dt))

    return PipeCtx(
        ops=z((w, K), np.int32), tbl=z((w, K), np.int32),
        kk=z((w, K), np.int32), rver1=z((w, K), np.uint32),
        rt1_val=z((w, K), bool), granted=z((w, 2), bool),
        alive=z((w,), bool), ro_commit=z((w,), bool),
        ws_active=z((w, 2), bool), ws_tbl=z((w, 2), np.int32),
        ws_key=z((w, 2), np.int32), ws_kind=z((w, 2), np.int32),
        attempted=z((), np.int32), ab_lock=z((), np.int32),
        ab_missing=z((), np.int32), ab_validate=z((), np.int32),
        magic_bad=z((), np.int32))


def classify_wave1(ttype, rt, ops, ws_active, ws_lane, ws_rt=None):
    """Per-txn-type wave-1 outcome rules, shared by every TATP engine.

    Given reply types rt [w, K] (VAL/NOT_EXIST for reads, GRANT/REJECT for
    locks), classifies each txn exactly like the reference coordinator
    (read-only commit on success, REJECT -> lock abort, required-row
    absence / insert-exists -> missing abort; client_ebpf_shard.cc:608-703).
    Returns (is_ro, rw, granted [w,2], lock_rejected, missing), all masked
    to lanes that exist (ops[:,0] != NOP for bootstrap/drain cohorts).

    ``ws_rt`` [w, 2]: write-slot reply types, for engines that arbitrate
    locks in write-slot space and never materialize lock replies in rt
    (engines/tatp_dense.py); defaults to gathering rt at ws_lane."""
    t = ttype
    is_ro = ((t == wl.TATP_GET_SUBSCRIBER) | (t == wl.TATP_GET_ACCESS)
             | (t == wl.TATP_GET_NEW_DEST)) & (ops[:, 0] != Op.NOP)
    rw = (ops[:, 0] != Op.NOP) & ~is_ro

    if ws_rt is None:
        ws_rt = jnp.take_along_axis(rt, ws_lane, axis=1)
    granted = ws_active & (ws_rt == Reply.GRANT)
    rejected = (ws_rt == Reply.REJECT) | (ws_rt == Reply.REJECT_SAME_KEY)
    lock_rejected = (ws_active & rejected).any(axis=1)

    missing = jnp.zeros(t.shape, bool)
    # GET_ACCESS fails on an absent ACCESS_INFO row — kNotExist returns
    # false, excluded from goodput (client_ebpf_shard.cc:583-587); by the
    # 0.625 population this fails ~37% of the time BY DESIGN (TATP spec)
    m = t == wl.TATP_GET_ACCESS
    missing |= m & (rt[:, 0] != Reply.VAL)
    # GET_NEW_DEST succeeds only when the SPECIAL_FACILITY row exists AND
    # the CALL_FORWARDING read hits (client_ebpf_shard.cc:492,549-563 —
    # kNotExist on either ends the txn unsuccessfully; the reference's
    # additional is_active/end_time predicates are over synthetic payload
    # fields this schema does not model)
    m = t == wl.TATP_GET_NEW_DEST
    missing |= m & ((rt[:, 0] != Reply.VAL) | (rt[:, 1] != Reply.VAL))
    m = (t == wl.TATP_UPDATE_SUBSCRIBER) | (t == wl.TATP_UPDATE_LOCATION)
    missing |= m & ((rt[:, 0] != Reply.VAL) | (rt[:, 1] != Reply.VAL))
    m = t == wl.TATP_INSERT_CF
    missing |= m & ((rt[:, 0] != Reply.VAL) | (rt[:, 1] == Reply.VAL))
    m = t == wl.TATP_DELETE_CF
    missing |= m & (rt[:, 0] != Reply.VAL)
    missing &= (ops[:, 0] != Op.NOP)
    return is_ro, rw, granted, lock_rejected, missing


def _wave1_lanes(ops, tbl, kk):
    """Flat wave-1 lane arrays + owner routing ([r] each, r = w*K)."""
    r = ops.shape[0] * K
    lane_op = ops.reshape(r)
    lane_tbl = tbl.reshape(r)
    used = lane_op != Op.NOP
    lane_key = jnp.where(used, kk.reshape(r).astype(U32),
                         U32(PAD_KEY & 0xFFFFFFFF))
    owner = (kk.reshape(r) % N_SHARDS).astype(I32)
    return lane_op, lane_tbl, lane_key, owner, used


def _validate_lanes(ctx: PipeCtx):
    """Wave-2 lane arrays for an in-flight cohort: re-read the read-set of
    surviving RW txns (and of nothing else)."""
    w = ctx.alive.shape[0]
    r = w * K
    is_read_lane = (ctx.ops == Op.OCC_READ) & ctx.alive[:, None]
    v_op = jnp.where(is_read_lane.reshape(r), Op.OCC_READ, Op.NOP)
    v_used = v_op != Op.NOP
    v_key = jnp.where(v_used, ctx.kk.reshape(r).astype(U32),
                      U32(PAD_KEY & 0xFFFFFFFF))
    owner = (ctx.kk.reshape(r) % N_SHARDS).astype(I32)
    return v_op, ctx.tbl.reshape(r), v_key, owner, v_used, is_read_lane


def _wave3_lanes(ctx: PipeCtx, kval, val_words: int):
    """Wave-3 lane arrays for a validated cohort (4w lanes: log ws0 | log
    ws1 | role ws0 | role ws1), identical to the serialized wave 3."""
    w = ctx.alive.shape[0]
    sid = jnp.arange(N_SHARDS, dtype=I32)
    w_owner = (ctx.ws_key % N_SHARDS).astype(I32)
    do_write = ctx.ws_active & ctx.alive[:, None]
    newval = jnp.zeros((w, 2, val_words), U32)
    payload = jax.random.randint(kval, (w, 2), 0, 1 << 16, dtype=I32)
    newval = newval.at[:, :, 0].set(payload.astype(U32))
    newval = newval.at[:, :, 1].set(jnp.where(do_write, U32(MAGIC), U32(0)))

    log_op = jnp.where(do_write,
                       jnp.where(ctx.ws_kind == 2, Op.DELETE_LOG,
                                 Op.COMMIT_LOG), Op.NOP)
    prim_op = jnp.select([ctx.ws_kind == 1, ctx.ws_kind == 2],
                         [Op.INSERT_PRIM, Op.DELETE_PRIM], Op.COMMIT_PRIM)
    bck_op = jnp.select([ctx.ws_kind == 1, ctx.ws_kind == 2],
                        [Op.INSERT_BCK, Op.DELETE_BCK], Op.COMMIT_BCK)
    dead_abort = ctx.granted & ~ctx.alive[:, None]
    role_s = jnp.where(
        do_write[None], jnp.where(w_owner[None] == sid[:, None, None],
                                  prim_op[None], bck_op[None]),
        jnp.where(dead_abort[None] & (w_owner[None] == sid[:, None, None]),
                  Op.ABORT, Op.NOP))                       # [S, w, 2]

    c_used = do_write | dead_abort
    c_key = jnp.where(c_used, ctx.ws_key.astype(U32),
                      U32(PAD_KEY & 0xFFFFFFFF))
    lane_key = jnp.concatenate([c_key[:, 0], c_key[:, 1],
                                c_key[:, 0], c_key[:, 1]])
    lane_tbl = jnp.concatenate([ctx.ws_tbl[:, 0], ctx.ws_tbl[:, 1],
                                ctx.ws_tbl[:, 0], ctx.ws_tbl[:, 1]])
    lane_val = jnp.concatenate([newval[:, 0], newval[:, 1],
                                newval[:, 0], newval[:, 1]])
    op_s = jnp.concatenate([
        jnp.broadcast_to(log_op[:, 0][None], (N_SHARDS, w)),
        jnp.broadcast_to(log_op[:, 1][None], (N_SHARDS, w)),
        role_s[:, :, 0], role_s[:, :, 1]], axis=1)
    return op_s, lane_tbl, lane_key, lane_val


def pipe_step(stacked: tatp.Shard, c1: PipeCtx, c2: PipeCtx, key, *, w: int,
              n_sub: int, val_words: int, gen_new: bool = True, mix=None,
              counters: mon.Counters | None = None):
    """One pipelined device step: wave 1 of a NEW cohort + wave 2 of c1 +
    wave 3 of c2, in a single vmapped engine step. Returns
    (stacked', new_ctx, c1', stats-of-c2) — c2 completes here.

    ``gen_new=False`` (static) feeds an empty cohort instead of generating
    one: used to drain the pipeline at end of run.

    ``counters`` (monitor.Counters | None): the dintmon counter plane;
    bumps the engine-independent parity counters (txn outcomes, lock
    grant/reject, validate lanes/failures, install/log counts — the same
    definitions as engines/tatp_dense.pipe_step, so on the parity
    workloads the two engines produce bit-identical values) and appends
    the updated Counters to the return tuple. The held-vs-arb reject
    split and the ring gauge are dense-engine observables and stay 0
    here."""
    step_v = jax.vmap(tatp.step)
    kg, kv3 = jax.random.split(key)
    r = w * K
    sid = jnp.arange(N_SHARDS, dtype=I32)

    # ---- assemble the combined batch [12w lanes] ---------------------------
    if gen_new:
        with waves.scope("tatp_pipeline", "gen"):
            ttype, ops, tbl, kk, ws = gen_cohort(kg, w, n_sub, mix=mix)
        ws_active, ws_lane, ws_tbl, ws_key, ws_kind = ws
    else:
        e = empty_ctx(w)
        ttype = jnp.zeros((w,), I32)
        ops, tbl, kk = e.ops, e.tbl, e.kk
        ws_active, ws_lane = e.ws_active, jnp.zeros((w, 2), I32)
        ws_tbl, ws_key, ws_kind = e.ws_tbl, e.ws_key, e.ws_kind
    with waves.scope("tatp_pipeline", "assemble"):
        a_op, a_tbl, a_key, a_owner, a_used = _wave1_lanes(ops, tbl, kk)
        opA_s = jnp.where((a_owner[None] == sid[:, None]) & a_used[None],
                          a_op[None], Op.NOP)

        b_op, b_tbl, b_key, b_owner, b_used, is_read_lane = \
            _validate_lanes(c1)
        opB_s = jnp.where((b_owner[None] == sid[:, None]) & b_used[None],
                          b_op[None], Op.NOP)

        opC_s, c_tbl, c_key, c_val = _wave3_lanes(c2, kv3, val_words)

        zvalAB = jnp.zeros((2 * r, val_words), U32)
        lane_tbl = jnp.concatenate([a_tbl, b_tbl, c_tbl])
        lane_key = jnp.concatenate([a_key, b_key, c_key])
        lane_val = jnp.concatenate([zvalAB, c_val])
        op_s = jnp.concatenate([opA_s, opB_s, opC_s], axis=1)
        zver = jnp.zeros((lane_key.shape[0],), U32)

    with waves.scope("tatp_pipeline", "engine_step"):
        stacked, rep = step_v(stacked, _broadcast_batch(
            op_s, lane_tbl, lane_key, lane_val, zver))

    # ---- wave-1 outcome for the new cohort --------------------------------
    with waves.scope("tatp_pipeline", "classify"):
        rtA = _merge(a_owner, rep.rtype[:, :r]).reshape(w, K)
        rvA = _merge(a_owner, rep.val[:, :r])
        rverA = _merge(a_owner, rep.ver[:, :r]).reshape(w, K)
        is_val_lane = rtA.reshape(r) == Reply.VAL
        magic_bad = jnp.sum(is_val_lane & (rvA[:, 1] != MAGIC), dtype=I32)

        is_ro, rw, granted, lock_rejected, missing = classify_wave1(
            ttype, rtA, ops, ws_active, ws_lane)

        new_ctx = PipeCtx(
            ops=ops, tbl=tbl, kk=kk, rver1=rverA,
            rt1_val=(rtA == Reply.VAL),
            granted=granted, alive=rw & ~lock_rejected & ~missing,
            ro_commit=is_ro & ~missing,
            ws_active=ws_active, ws_tbl=ws_tbl, ws_key=ws_key,
            ws_kind=ws_kind,
            attempted=jnp.asarray(w if gen_new else 0, I32),
            ab_lock=(rw & lock_rejected).sum(dtype=I32),
            ab_missing=((rw & ~lock_rejected & missing)
                        | (is_ro & missing)).sum(dtype=I32),
            ab_validate=jnp.asarray(0, I32),
            magic_bad=magic_bad)

        # ---- validate outcome for c1 --------------------------------------
        rtB = _merge(b_owner, rep.rtype[:, r:2 * r]).reshape(w, K)
        rverB = _merge(b_owner, rep.ver[:, r:2 * r]).reshape(w, K)
        bad_lane = is_read_lane & ((rverB != c1.rver1)
                                   | ((rtB != Reply.VAL) & c1.rt1_val))
        changed = bad_lane.any(axis=1)
        c1 = c1.replace(alive=c1.alive & ~changed,
                        ab_validate=(c1.alive & changed).sum(dtype=I32))

        # ---- c2 completed: emit its stats ---------------------------------
        stats = jnp.stack([
            c2.attempted,
            (c2.ro_commit | c2.alive).sum(dtype=I32),
            c2.ab_lock, c2.ab_missing, c2.ab_validate, c2.magic_bad])
    if counters is not None:
        dw2 = c2.ws_active & c2.alive[:, None]   # == _wave3_lanes do_write
        counters = mon.bump(counters, {
            mon.CTR_STEPS: 1,
            mon.CTR_TXN_ATTEMPTED: stats[STAT_ATTEMPTED],
            mon.CTR_TXN_COMMITTED: stats[STAT_COMMITTED],
            mon.CTR_AB_LOCK: c2.ab_lock,
            mon.CTR_AB_MISSING: c2.ab_missing,
            mon.CTR_AB_VALIDATE: c2.ab_validate,
            mon.CTR_MAGIC_BAD: c2.magic_bad,
            mon.CTR_LOCK_REQUESTS: ws_active.sum(dtype=I32),
            mon.CTR_LOCK_GRANTED: granted.sum(dtype=I32),
            mon.CTR_LOCK_REJECTED: (ws_active & ~granted).sum(dtype=I32),
            mon.CTR_VALIDATE_LANES: is_read_lane.sum(dtype=I32),
            mon.CTR_VALIDATE_FAILED: bad_lane.sum(dtype=I32),
            mon.CTR_INSTALL_WRITES: dw2.sum(dtype=I32),
            mon.CTR_LOG_APPENDS: dw2.sum(dtype=I32),
            mon.CTR_DISPATCH_XLA: 1,
        })
        return stacked, new_ctx, c1, stats, counters
    return stacked, new_ctx, c1, stats


@memoize_builder
def build_pipelined_runner(n_sub: int, w: int = 4096, val_words: int = 10,
                           cohorts_per_block: int = 8, mix=None,
                           monitor: bool = False):
    """jit(scan(pipe_step)) over carry (stacked, c1, c2): one dispatch runs
    `cohorts_per_block` pipelined cohorts; in-flight cohorts persist across
    blocks via the carry, so nothing is lost at block boundaries.

    Returns (run, init, drain):
      run(carry, key) -> (carry', stats [cohorts_per_block, N_STATS])
      init(stacked)   -> carry with two bootstrap (empty) cohorts in flight
      drain(carry)    -> (stacked, stats [2, N_STATS]) flushing the pipeline

    ``monitor``: thread the dintmon counter plane — the carry grows a
    trailing monitor.Counters leaf and drain returns (stacked, stats,
    counters); off (default) = contract and jaxpr unchanged.
    """
    kw = dict(w=w, n_sub=n_sub, val_words=val_words)
    kw_gen = dict(kw, mix=mix)

    def step_mon(stacked, c1, c2, key, cnt, **skw):
        out = pipe_step(stacked, c1, c2, key, counters=cnt, **skw)
        return out if cnt is not None else out + (None,)

    def scan_fn(carry, key):
        stacked, c1, c2 = carry[:3]
        cnt = carry[3] if monitor else None
        stacked, new_ctx, c1, stats, cnt = step_mon(stacked, c1, c2, key,
                                                    cnt, **kw_gen)
        out = (stacked, new_ctx, c1) + ((cnt,) if monitor else ())
        return out, stats

    def block(carry, key):
        keys = jax.random.split(key, cohorts_per_block)
        return jax.lax.scan(scan_fn, carry, keys)

    def init(stacked):
        base = (stacked, empty_ctx(w), empty_ctx(w))
        return base + ((mon.create(),) if monitor else ())

    @functools.partial(jax.jit, donate_argnums=0)
    def drain(carry):
        stacked, c1, c2 = carry[:3]
        cnt = carry[3] if monitor else None
        key = jax.random.PRNGKey(0)
        stacked, _, c1, s1, cnt = step_mon(stacked, c1, c2, key, cnt,
                                           gen_new=False, **kw)
        stacked, _, _, s2, cnt = step_mon(stacked, empty_ctx(w), c1, key,
                                          cnt, gen_new=False, **kw)
        stats = jnp.stack([s1, s2])
        if monitor:
            return stacked, stats, cnt
        return stacked, stats

    return jax.jit(block, donate_argnums=0), init, drain


@memoize_builder
def build_runner(n_sub: int, w: int = 4096, val_words: int = 10,
                 cohorts_per_block: int = 8, validate: bool = True):
    """jit(scan(cohort_step)): one dispatch runs `cohorts_per_block` cohorts.

    Returns run(stacked, key) -> (stacked', stats [cohorts_per_block, N_STATS]).
    State is donated — tables update in place in HBM.
    """
    step = functools.partial(cohort_step, w=w, n_sub=n_sub,
                             val_words=val_words, validate=validate)

    def block(stacked, key):
        keys = jax.random.split(key, cohorts_per_block)
        return jax.lax.scan(step, stacked, keys)

    return jax.jit(block, donate_argnums=0)
