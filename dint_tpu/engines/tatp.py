"""TATP shard server engine: OCC + replication over 5 tables.

TPU equivalent of the reference's TATP txn server
(tatp/ebpf/shard_kern.c): READ with bloom (:140-250), ACQUIRE_LOCK CAS
(:251-297), ABORT (:298-337), COMMIT_PRIM installs + releases the row lock
(:338-476), INSERT/DELETE_PRIM (:477-658), COMMIT/INSERT/DELETE_BCK
(:659-913), COMMIT_LOG/DELETE_LOG (:914-939).

Table layout (TPU-first: dense-index what the reference hashes):
  SUBSCRIBER(0)        dense by s_id, exact per-row OCC lock
  SEC_SUBSCRIBER(1)    dense by sub_nbr
  ACCESS_INFO(2)       dense by s_id*4 + (ai_type-1); ver==0 means absent
  SPECIAL_FACILITY(3)  dense by s_id*4 + (sf_type-1), per-row lock
  CALL_FORWARDING(4)   sparse composite key (s_id, sf_type, start_time)
                       -> tables.kv.KVTable with insert/delete + bloom,
                       row locks hash-conflated in a tables.locks.OCCTable
                       (exactly the reference's lock-array shape,
                       tatp/ebpf/shard_kern.c:26-59)

The CF table is processed by *reusing* engines.store.step (KV semantics:
GET/SET/INSERT/DELETE with SPILL) and engines.fasst.step (lock word CAS),
each on a derived op view of the batch; dense tables get a closed-form OCC
pass (commits/unlocks, then reads, then lock acquires — per (table, row)).

Versions auto-increment server-side on install (store.step semantics); since
every replica applies the same certified ops, replicas stay bit-identical
without client-supplied versions.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from ..ops import segments
from ..tables import dense, kv, locks, log as logring
from . import fasst, store
from .types import Batch, Op, Replies, Reply

I32 = jnp.int32
U32 = jnp.uint32

SUBSCRIBER = 0
SEC_SUBSCRIBER = 1
ACCESS_INFO = 2
SPECIAL_FACILITY = 3
CALL_FORWARDING = 4

N_DENSE = 4


def cf_key(s_id, sf_type, start_time):
    """Composite CALL_FORWARDING key -> u64 (start_time in {0, 8, 16})."""
    return s_id * 12 + (sf_type - 1) * 3 + start_time // 8


@flax.struct.dataclass
class Shard:
    sub: dense.DenseTable
    sec: dense.DenseTable
    ai: dense.DenseTable
    sf: dense.DenseTable
    sub_lock: jax.Array   # bool [P+1]
    sec_lock: jax.Array
    ai_lock: jax.Array    # bool [4(P+1)]
    sf_lock: jax.Array
    cf: kv.KVTable
    cf_lock: locks.OCCTable
    log: logring.LogRing

    @property
    def n_subscribers(self):
        return self.sub.size - 1


def create(n_subscribers: int, val_words: int = 10, cf_buckets: int | None = None,
           cf_lock_slots: int | None = None, log_lanes: int = 16,
           log_capacity: int = 1 << 20, attr_locks: bool = False) -> Shard:
    """``attr_locks=True`` builds the lock-ATTRIBUTION variant: CF lock
    words carry their holder's key so rejects distinguish true same-key
    conflicts from hash-slot sharing — the reference's instrumented TATP
    server (tatp/ebpf/lock_kern.c:12-16). Dense-table locks are exact
    per-row, so only the hash-conflated CF table has sharing to attribute."""
    p1 = n_subscribers + 1          # ids are 1-based
    if cf_buckets is None:
        cf_buckets = max(1 << (p1 * 4).bit_length(), 16)  # ~load<=0.25 at 4 slots
    if cf_lock_slots is None:
        cf_lock_slots = max(cf_buckets, 16)
    return Shard(
        sub=dense.create(p1, val_words),
        sec=dense.create(p1, val_words),
        ai=dense.create(4 * p1, val_words),
        sf=dense.create(4 * p1, val_words),
        sub_lock=jnp.zeros((p1,), bool),
        sec_lock=jnp.zeros((p1,), bool),
        ai_lock=jnp.zeros((4 * p1,), bool),
        sf_lock=jnp.zeros((4 * p1,), bool),
        cf=kv.create(cf_buckets, slots=4, val_words=val_words),
        cf_lock=(locks.create_occ_attr(cf_lock_slots) if attr_locks
                 else locks.create_occ(cf_lock_slots)),
        log=logring.create(log_lanes, log_capacity, val_words),
    )


# --------------------------------------------------------------- dense OCC


def _dense_gather(shard: Shard, tbl, idx):
    """Gather (val, ver, locked) for dense tables 0..3, OOB-safe."""
    def pick(t: dense.DenseTable, lock, n):
        i = jnp.clip(idx, 0, n - 1)
        return dense.gather_rows(t, i), t.ver[i], lock[i]

    v0, r0, l0 = pick(shard.sub, shard.sub_lock, shard.sub.size)
    v1, r1, l1 = pick(shard.sec, shard.sec_lock, shard.sec.size)
    v2, r2, l2 = pick(shard.ai, shard.ai_lock, shard.ai.size)
    v3, r3, l3 = pick(shard.sf, shard.sf_lock, shard.sf.size)
    val = jnp.where((tbl == 0)[:, None], v0,
          jnp.where((tbl == 1)[:, None], v1,
          jnp.where((tbl == 2)[:, None], v2, v3)))
    ver = jnp.where(tbl == 0, r0, jnp.where(tbl == 1, r1, jnp.where(tbl == 2, r2, r3)))
    lck = jnp.where(tbl == 0, l0, jnp.where(tbl == 1, l1, jnp.where(tbl == 2, l2, l3)))
    return val, ver, lck


def _dense_step(shard: Shard, batch: Batch):
    """Closed-form OCC pass over the four dense tables.

    Per (table, row) group: commit installs + unlocks first, then aborts'
    unlocks, then reads (seeing post-commit state), then lock acquires in
    lane order. ver==0 rows are absent (NOT_EXIST on read; commits create).
    """
    r = batch.width
    is_dense = batch.table < N_DENSE
    op = jnp.where(is_dense, batch.op, Op.NOP)
    sb = segments.sort_batch(batch.table.astype(U32), batch.key_lo)
    op = op[sb.perm]
    val_in = batch.val[sb.perm]
    tbl = sb.key_hi.astype(I32)
    idx = sb.key_lo.astype(I32)

    val0, ver0, locked0 = _dense_gather(shard, tbl, idx)

    is_cprim = op == Op.COMMIT_PRIM
    is_cbck = op == Op.COMMIT_BCK
    is_commit = is_cprim | is_cbck
    is_abort = op == Op.ABORT
    is_read = op == Op.OCC_READ
    is_lock = op == Op.OCC_LOCK

    # commits install (last by lane order wins; X-certified so one per row)
    last_c = segments.seg_max_where(sb, is_commit, sb.rank, I32(-1))
    pos_c = jnp.clip(sb.head_pos + last_c, 0, r - 1)
    any_c = last_c >= 0
    n_c = segments.seg_sum(sb, is_commit.astype(I32))
    val1 = jnp.where(any_c[:, None], val_in[pos_c], val0)
    ver1 = jnp.where(any_c, ver0 + n_c.astype(U32), ver0)
    unlock = segments.seg_any(sb, is_cprim | is_abort)
    locked1 = locked0 & ~unlock

    first_lock = segments.first_rank_where(sb, is_lock)
    grant = is_lock & ~locked1 & (sb.rank == first_lock)
    new_locked = locked1 | segments.seg_any(sb, grant)

    exists = ver1 > 0
    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where(is_commit | is_abort, Reply.ACK, rtype)
    rtype = jnp.where(is_read, jnp.where(exists, Reply.VAL, Reply.NOT_EXIST), rtype)
    rtype = jnp.where(is_lock, jnp.where(grant, Reply.GRANT, Reply.REJECT), rtype)
    rval = jnp.where((is_read & exists)[:, None], val1, jnp.zeros_like(val1))
    rver = jnp.where(is_read & exists, ver1, U32(0))

    writer = sb.last & segments.seg_any(sb, op != Op.NOP)

    def scat(t: dense.DenseTable, lock, n, which):
        m = writer & (tbl == which)
        i = jnp.clip(idx, 0, n - 1)
        return t.replace(
            val=dense.scatter_rows_val(t, i, val1, m),
            ver=segments.scatter_rows(t.ver, i, ver1, m),
        ), segments.scatter_rows(lock, i, new_locked, m)

    new_sub, new_sub_l = scat(shard.sub, shard.sub_lock, shard.sub.size, 0)
    new_sec, new_sec_l = scat(shard.sec, shard.sec_lock, shard.sec.size, 1)
    new_ai, new_ai_l = scat(shard.ai, shard.ai_lock, shard.ai.size, 2)
    new_sf, new_sf_l = scat(shard.sf, shard.sf_lock, shard.sf.size, 3)
    shard = shard.replace(sub=new_sub, sub_lock=new_sub_l, sec=new_sec,
                          sec_lock=new_sec_l, ai=new_ai, ai_lock=new_ai_l,
                          sf=new_sf, sf_lock=new_sf_l)
    o_rtype, o_rver = segments.unsort(sb, rtype, rver)
    o_rval = segments.unsort(sb, rval)
    return shard, Replies(rtype=o_rtype, val=o_rval, ver=o_rver)


# --------------------------------------------------------------- CF (sparse)

_KV_OP = {Op.OCC_READ: Op.GET, Op.COMMIT_PRIM: Op.SET, Op.COMMIT_BCK: Op.SET,
          Op.INSERT_PRIM: Op.INSERT, Op.INSERT_BCK: Op.INSERT,
          Op.DELETE_PRIM: Op.DELETE, Op.DELETE_BCK: Op.DELETE}
_UNLOCK_OPS = (Op.COMMIT_PRIM, Op.INSERT_PRIM, Op.DELETE_PRIM, Op.ABORT)


def _translate(op, table, mapping, default=Op.NOP):
    out = jnp.full_like(op, default)
    for src, dst in mapping.items():
        out = jnp.where((table == CALL_FORWARDING) & (op == src), dst, out)
    return out


def _cf_step(shard: Shard, batch: Batch):
    """CALL_FORWARDING pass: store.step handles the KV mutations, fasst.step
    handles the hash-slot row locks; prim ops appear in both views (install
    in the KV view, unlock in the lock view)."""
    kv_ops = _translate(batch.op, batch.table, _KV_OP)
    new_cf, kv_rep = store.step(shard.cf, batch.replace(op=kv_ops),
                                maintain_bloom=True)
    lock_map = {Op.OCC_LOCK: Op.LOCK}
    for o in _UNLOCK_OPS:
        lock_map[o] = Op.ABORT
    lk_ops = _translate(batch.op, batch.table, lock_map)
    # static dispatch on the shard's lock-table flavor (tatp.create
    # attr_locks): the attribution variant reports REJECT_SAME_KEY vs
    # plain REJECT on conflicts (tatp/ebpf/lock_kern.c:292-298)
    lock_step = (fasst.step_attr
                 if isinstance(shard.cf_lock, locks.OCCAttrTable)
                 else fasst.step)
    new_cf_lock, lk_rep = lock_step(shard.cf_lock, batch.replace(op=lk_ops))
    shard = shard.replace(cf=new_cf, cf_lock=new_cf_lock)
    # lock replies only for OCC_LOCK lanes; everything else from the KV view
    use_lock = (batch.table == CALL_FORWARDING) & (batch.op == Op.OCC_LOCK)
    rep = Replies(
        rtype=jnp.where(use_lock, lk_rep.rtype, kv_rep.rtype),
        val=kv_rep.val,
        ver=jnp.where(use_lock, lk_rep.ver, kv_rep.ver),
    )
    return shard, rep


def step(shard: Shard, batch: Batch):
    """Certify and apply one batch (all 5 tables + log). Returns (shard', replies)."""
    shard, dense_rep = _dense_step(shard, batch)
    shard, cf_rep = _cf_step(shard, batch)

    do_log = (batch.op == Op.COMMIT_LOG) | (batch.op == Op.DELETE_LOG)
    new_log, _, _ = logring.append(
        shard.log, do_log, batch.table,
        (batch.op == Op.DELETE_LOG).astype(I32),
        batch.key_hi, batch.key_lo, batch.ver, batch.val)
    shard = shard.replace(log=new_log)

    is_cf = batch.table == CALL_FORWARDING
    rtype = jnp.where(is_cf, cf_rep.rtype, dense_rep.rtype)
    rtype = jnp.where(do_log, I32(Reply.ACK), rtype)
    rval = jnp.where(is_cf[:, None], cf_rep.val, dense_rep.val)
    rver = jnp.where(is_cf, cf_rep.ver, dense_rep.ver)
    return shard, Replies(rtype=rtype, val=rval, ver=rver)
