"""Fused single-program TATP engine: 3 replicas as one flat device state.

The TPU-first answer to a finding from profiling the stacked pipeline
(engines/tatp_pipeline.py): vmapping a generic 5-table engine over 3 shard
replicas costs ~4.5x one shard — every wave re-sorts, re-gathers each table
separately, and runs install/alloc machinery on mostly-NOP lanes.

Here the whole cluster state is flat arrays indexed by shard offset:

  bank   u32 [3*NR, D]   all four dense tables of all three replicas;
                         row = shard*NR + table_offset + local_idx,
                         record = [val.. (VW), ver, lock]  (D = VW+2)
  cf     u32 [3*NBC*SL, 2+VW]  CALL_FORWARDING single-hash 4-way table,
                         row = (shard*NBC + h(key)) * SL + slot,
                         record = [key_lo, ver, val..]; ver==0 <=> empty
                         (the reference's per-table cache-map shape,
                         tatp/ebpf/shard_kern.c:61-94)
  cf_lock u32 [3*NLC]    OCC lock words, hash-conflated
                         (tatp/ebpf/shard_kern.c:26-59)
  log    u32 [3*L*CAP, EW] + heads [3*L]  per-replica append rings
                         (log_server/ebpf/ls_kern.c:26-38)

Replication is not a second program execution: a commit produces one lane
per destination replica (prim at owner, bck at the other two), all certified
in the same sorted pass — the reference client's CommitBck fan-out RTTs
(SURVEY.md §3.3) become index arithmetic. One cohort = 3 sorted passes:

  wave 1   [R=4w]  OCC_READ + OCC_LOCK at owner replicas
  wave 2           validation re-read: bank/cf re-gather over wave 1's
                   sort (protocol-parity; see tatp_pipeline.cohort_step)
  wave 3   [6w]    log append x3 + {COMMIT,INSERT,DELETE}_{PRIM,BCK} and
                   ABORT lanes, one lane per (write-slot, replica)

CF lanes ride the same sorts: their sort key is (key << 2 | dest) offset
past a sentinel, so they land in a fixed-width suffix window where a compact
single-hash sub-engine probes/installs them. Window overflow lanes get
REJECT (client-retry semantics) and are counted in stats; wave-3 windows are
sized so overflow is effectively impossible at the TATP mix.
"""
from __future__ import annotations

import functools

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from ..clients import workloads as wl
from ..ops import hashing
from . import tatp, tatp_pipeline as tp
from .types import Op, Reply

I32 = jnp.int32
U32 = jnp.uint32

S = 3            # replicas
K = tp.K         # wave-1 lanes per txn
SL = 4           # cf slots per bucket
MAGIC = tp.MAGIC

# sort-key spaces: dense rows < BIG_NOP < cf lanes at BIG_CF | key<<2 | dest
BIG_NOP = jnp.uint32(0x4000_0000)
BIG_CF = jnp.uint32(0x8000_0000)

# stats layout = tatp_pipeline's + window-overflow counter
N_STATS = tp.N_STATS + 1
STAT_ATTEMPTED = tp.STAT_ATTEMPTED
STAT_COMMITTED = tp.STAT_COMMITTED
STAT_AB_LOCK = tp.STAT_AB_LOCK
STAT_AB_MISSING = tp.STAT_AB_MISSING
STAT_AB_VALIDATE = tp.STAT_AB_VALIDATE
STAT_MAGIC_BAD = tp.STAT_MAGIC_BAD
STAT_OVERFLOW = tp.N_STATS


@flax.struct.dataclass
class FusedState:
    bank: jax.Array       # u32 [S*NR, D]
    cf: jax.Array         # u32 [S*NBC*SL, 2+VW]
    cf_lock: jax.Array    # u32 [S*NLC]
    log: jax.Array        # u32 [S*L*CAP, EW]
    log_head: jax.Array   # u32 [S*L]

    @property
    def val_words(self):
        return self.bank.shape[1] - 2


def _layout(n_sub: int):
    p1 = n_sub + 1
    nr = 10 * p1
    # the u32 sort key encodes dense row ids below BIG_NOP (2^30): a
    # bigger bank would alias dense rows into the NOP/CF key ranges and
    # silently corrupt the segment sort (round-1 advisor finding)
    assert S * nr < int(BIG_NOP), (
        f"n_sub={n_sub} overflows the fused sort-key encoding "
        f"({S * nr} rows >= {int(BIG_NOP)}); use engines/tatp_dense.py "
        "or the sharded path at this scale")
    # offsets inside one replica's bank: SUB, SEC, AI, SF
    return p1, nr, (0, p1, 2 * p1, 6 * p1)


def create(n_sub: int, val_words: int = 10, cf_buckets: int = 1 << 15,
           cf_lock_slots: int = 1 << 15, log_lanes: int = 16,
           log_capacity: int = 1 << 14, cf_slots: int = SL) -> FusedState:
    _, nr, _ = _layout(n_sub)
    ew = 4 + val_words
    return FusedState(
        bank=jnp.zeros((S * nr, val_words + 2), U32),
        cf=jnp.zeros((S * cf_buckets * cf_slots, 2 + val_words), U32),
        cf_lock=jnp.zeros((S * cf_lock_slots,), U32),
        log=jnp.zeros((S * log_lanes * log_capacity, ew), U32),
        log_head=jnp.zeros((S * log_lanes,), U32),
    )


def from_replicas(shards, n_sub: int, cf_buckets: int = 1 << 15,
                  cf_lock_slots: int = 1 << 15, cf_slots: int = SL,
                  **log_kw) -> FusedState:
    """Convert tatp_client.populate_shards replicas into fused flat state
    (numpy; used by tests for cross-engine equivalence and by bench setup)."""
    from ..tables import kv as kvmod

    vw = shards[0].sub.val.shape[1]
    p1, nr, off = _layout(n_sub)
    st = create(n_sub, vw, cf_buckets, cf_lock_slots, cf_slots=cf_slots,
                **log_kw)
    bank = np.zeros((S * nr, vw + 2), np.uint32)
    cf = np.zeros((S * cf_buckets * cf_slots, 2 + vw), np.uint32)
    for s, sh in enumerate(shards):
        base = s * nr
        for t_i, tbl in enumerate((sh.sub, sh.sec, sh.ai, sh.sf)):
            n = tbl.val.shape[0]
            rows = base + off[t_i] + np.arange(n)
            bank[rows, :vw] = np.asarray(tbl.val)
            bank[rows, vw] = np.asarray(tbl.ver)
        d = kvmod.to_dict(sh.cf)
        keys = np.array(sorted(d), np.uint64)
        if len(keys):
            # two-choice placement, same scheme the probe uses
            bkt, slot = kvmod.assign_two_choice(keys, cf_buckets, cf_slots)
            for key, b, sl_i in zip(keys, bkt, slot):
                val, ver = d[int(key)]
                row = (s * cf_buckets + int(b)) * cf_slots + int(sl_i)
                cf[row, 0] = int(key) & 0xFFFFFFFF
                cf[row, 1] = ver
                cf[row, 2:] = val[:vw]
    return st.replace(bank=jnp.asarray(bank), cf=jnp.asarray(cf))


# ------------------------------------------------------------------ helpers


def _segmeta(sort_key):
    """head/rank/last/seg_id over equal sorted keys."""
    r = sort_key.shape[0]
    head = jnp.concatenate([jnp.ones((1,), bool),
                            sort_key[1:] != sort_key[:-1]])
    idx = jnp.arange(r, dtype=I32)
    head_pos = jax.lax.cummax(jnp.where(head, idx, 0))
    rank = idx - head_pos
    last = jnp.concatenate([head[1:], jnp.ones((1,), bool)])
    seg_id = jnp.cumsum(head.astype(I32)) - 1

    def seg_sum(x):
        return jax.ops.segment_sum(x, seg_id, num_segments=r)[seg_id]

    def seg_max(x):
        return jax.ops.segment_max(x, seg_id, num_segments=r)[seg_id]

    def seg_min(x):
        return jax.ops.segment_min(x, seg_id, num_segments=r)[seg_id]

    return head_pos, rank, last, seg_sum, seg_max, seg_min


def _unsort_packed(perm, *arrays):
    """Return sorted-order arrays to lane order with ONE packed scatter."""
    cols = [a[:, None] if a.ndim == 1 else a for a in arrays]
    widths = [c.shape[1] for c in cols]
    m = jnp.concatenate([c.astype(U32) for c in cols], axis=1)
    out = jnp.zeros_like(m).at[perm].set(m)
    res, s0 = [], 0
    for a, wd in zip(arrays, widths):
        piece = out[:, s0:s0 + wd]
        res.append(piece[:, 0].astype(a.dtype) if a.ndim == 1
                   else piece.astype(a.dtype))
        s0 += wd
    return res


def _occ_dense(bank, sorted_rows, op, val_in, vw):
    """Closed-form OCC pass over row-sorted lanes: ONE gather, ONE scatter.

    Ops: OCC_READ / OCC_LOCK / COMMIT_PRIM / COMMIT_BCK / ABORT — the
    semantics of tatp._dense_step on the flat bank. Returns
    (bank', rtype, rver, rval) in SORTED order."""
    head_pos, rank, last, seg_sum, seg_max, seg_min = _segmeta(sorted_rows)
    r = op.shape[0]

    rec = bank[sorted_rows]                 # [r, D] — THE gather
    val0 = rec[:, :vw]
    ver0 = rec[:, vw]
    lock0 = rec[:, vw + 1] != 0

    is_cp = op == Op.COMMIT_PRIM
    is_commit = is_cp | (op == Op.COMMIT_BCK)
    is_abort = op == Op.ABORT
    is_read = op == Op.OCC_READ
    is_lock = op == Op.OCC_LOCK

    max_c = seg_max(jnp.where(is_commit, rank, I32(-1)))
    any_c = max_c >= 0
    pos_c = jnp.clip(head_pos + max_c, 0, r - 1)
    n_c = seg_sum(is_commit.astype(I32))
    val1 = jnp.where(any_c[:, None], val_in[pos_c], val0)
    ver1 = jnp.where(any_c, ver0 + n_c.astype(U32), ver0)
    unlock = seg_sum((is_cp | is_abort).astype(I32)) > 0
    lock1 = lock0 & ~unlock

    first_l = seg_min(jnp.where(is_lock, rank, I32(1 << 30)))
    grant = is_lock & ~lock1 & (rank == first_l)
    lock2 = lock1 | (seg_sum(grant.astype(I32)) > 0)

    exists = ver1 > 0
    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where(is_commit | is_abort, Reply.ACK, rtype)
    rtype = jnp.where(is_read, jnp.where(exists, Reply.VAL, Reply.NOT_EXIST),
                      rtype)
    rtype = jnp.where(is_lock, jnp.where(grant, Reply.GRANT, Reply.REJECT),
                      rtype)
    rver = jnp.where(is_read & exists, ver1, U32(0))
    rval = jnp.where((is_read & exists)[:, None], val1, jnp.zeros_like(val1))

    writer = last & (seg_sum((op != Op.NOP).astype(I32)) > 0)
    rec1 = jnp.concatenate(
        [val1, ver1[:, None], lock2.astype(U32)[:, None]], axis=1)
    safe = jnp.where(writer, sorted_rows, bank.shape[0])
    bank = bank.at[safe].set(rec1, mode="drop")
    return bank, rtype, rver, rval


def _cf_pass(cf, cf_lock, nbc, nlc, shard, keys, op, val_in, active, vw):
    """Compact CF sub-engine over window lanes sorted by (key, dest shard).

    Ops: OCC_READ / OCC_LOCK / INSERT_* / DELETE_* / COMMIT_* / ABORT.
    Lock ops hit cf_lock (hash-conflated OCC word); row ops hit the
    single-hash SL-way table with exact per-bucket slot allocation.
    Returns (cf', cf_lock', rtype, rver, rval) in window order."""
    r = op.shape[0]
    klo = keys.astype(U32)
    zero = jnp.zeros_like(klo)
    sl = cf.shape[0] // (S * nbc)
    h1, h2 = hashing.bucket_pair(zero, klo, nbc)   # two-choice (kv.py layout)
    b1 = shard * nbc + h1
    b2 = shard * nbc + h2
    lslot = shard * nlc + hashing.bucket(zero, klo, nlc)
    # one segment per (key, dest): caller sorts by exactly that
    segkey = jnp.where(active, (klo << U32(2)) | shard.astype(U32),
                       U32(0xFFFFFFFF))
    head_pos, rank, last, seg_sum, seg_max, seg_min = _segmeta(segkey)

    recs = [cf[b1 * sl + s_i] for s_i in range(sl)] + \
           [cf[b2 * sl + s_i] for s_i in range(sl)]   # 2*sl gathers [r, 2+vw]
    kcol = jnp.stack([rc[:, 0] for rc in recs], 1)        # [r, 2*SL]
    vercol = jnp.stack([rc[:, 1] for rc in recs], 1)
    match = (kcol == klo[:, None]) & (vercol > 0) & active[:, None]
    hit = match.any(1)
    slot2 = jnp.argmax(match, 1).astype(I32)       # index into the 2*sl cols
    free = vercol == 0
    rec0 = jnp.take_along_axis(jnp.stack(recs, 1), slot2[:, None, None],
                               1)[:, 0]
    ver0 = jnp.where(hit, rec0[:, 1], U32(0))
    val0 = rec0[:, 2:]

    is_read = op == Op.OCC_READ
    is_lockop = op == Op.OCC_LOCK
    is_ins = (op == Op.INSERT_PRIM) | (op == Op.INSERT_BCK)
    is_del = (op == Op.DELETE_PRIM) | (op == Op.DELETE_BCK)
    is_com = (op == Op.COMMIT_PRIM) | (op == Op.COMMIT_BCK)
    is_prim = ((op == Op.COMMIT_PRIM) | (op == Op.INSERT_PRIM)
               | (op == Op.DELETE_PRIM))
    is_abort = op == Op.ABORT
    is_write = is_ins | is_del | is_com

    # lock table: unlocks (prim/abort) first, then acquires in lane order
    lk0 = cf_lock[lslot] != 0
    unlocked = seg_sum((is_prim | is_abort).astype(I32)) > 0
    lk1 = lk0 & ~unlocked
    first_l = seg_min(jnp.where(is_lockop, rank, I32(1 << 30)))
    grant = is_lockop & ~lk1 & (rank == first_l)
    lk2 = lk1 | (seg_sum(grant.astype(I32)) > 0)
    lwriter = last & active & (
        seg_sum((is_lockop | is_prim | is_abort).astype(I32)) > 0)
    cf_lock = cf_lock.at[jnp.where(lwriter, lslot, cf_lock.shape[0])].set(
        lk2.astype(U32), mode="drop")

    # row state: writes in lane order; last write decides existence/value
    max_w = seg_max(jnp.where(is_write, rank, I32(-1)))
    any_w = max_w >= 0
    pos_w = jnp.clip(head_pos + max_w, 0, r - 1)
    last_is_del = is_del[pos_w]
    n_com = seg_sum(is_com.astype(I32))
    n_ins = seg_sum(is_ins.astype(I32))
    final_exists = jnp.where(any_w, ~last_is_del, hit)
    ver1 = jnp.where(hit, ver0 + n_com.astype(U32),
                     jnp.maximum(n_ins.astype(U32), U32(1)))
    val1 = jnp.where(any_w[:, None], val_in[pos_w], val0)

    # slot allocation for fresh installs: target = the emptier of the two
    # candidate buckets (pre-batch occupancy), then rank per TARGET bucket,
    # nth free slot; rank past the free count -> SPILL (counted; the
    # reference's overflow instead chains in the userspace KVS)
    need_alloc = last & any_w & final_exists & ~hit & active
    free1 = free[:, :sl]
    free2 = free[:, sl:]
    use2 = free2.sum(1) > free1.sum(1)
    tgt_bkt = jnp.where(use2, b2, b1)
    tgt_free = jnp.where(use2[:, None], free2, free1)
    order = jnp.arange(r, dtype=I32)
    b_key, b_perm = jax.lax.sort(
        (jnp.where(need_alloc, tgt_bkt.astype(U32), U32(0xFFFFFFFF)), order),
        num_keys=2)
    _, b_rank, _, _, _, _ = _segmeta(b_key)
    alloc_rank = jnp.zeros((r,), I32).at[b_perm].set(b_rank)
    cumfree = jnp.cumsum(tgt_free.astype(I32), axis=1)
    want = tgt_free & (cumfree == (alloc_rank[:, None] + 1))
    has_slot = want.any(1)
    new_slot = jnp.argmax(want, 1).astype(I32)
    spill_seg = seg_sum((need_alloc & ~has_slot).astype(I32)) > 0

    writer = last & any_w & active & ~spill_seg & (hit | has_slot)
    hit_row = jnp.where(slot2 < sl, b1 * sl + slot2, b2 * sl + (slot2 - sl))
    row = jnp.where(hit, hit_row, tgt_bkt * sl + new_slot)
    rec1 = jnp.concatenate(
        [jnp.where(final_exists, klo, U32(0))[:, None],
         jnp.where(final_exists, ver1, U32(0))[:, None], val1], axis=1)
    cf = cf.at[jnp.where(writer, row, cf.shape[0])].set(rec1, mode="drop")

    rtype = jnp.full((r,), Reply.NONE, I32)
    rtype = jnp.where(is_read, jnp.where(hit, Reply.VAL, Reply.NOT_EXIST),
                      rtype)
    rtype = jnp.where(is_lockop,
                      jnp.where(grant, Reply.GRANT, Reply.REJECT), rtype)
    rtype = jnp.where(is_write | is_abort, Reply.ACK, rtype)
    rtype = jnp.where(is_write & spill_seg, Reply.SPILL, rtype)
    rver = jnp.where(is_read & hit, ver0, U32(0))
    rval = jnp.where((is_read & hit)[:, None], val0, jnp.zeros_like(val0))
    rtype = jnp.where(active, rtype, Reply.NONE)
    return cf, cf_lock, rtype, rver, rval


def _log_append(log, head, n_lanes: int, do, key, ver, val, table_id, is_del):
    """Append write records to each replica's ring: S row-scatters."""
    cap = log.shape[0] // (S * n_lanes)
    r = do.shape[0]
    idx = jnp.arange(r, dtype=I32)
    lane_local = idx % n_lanes
    one = do.astype(I32)
    padr = (-r) % n_lanes
    one_p = jnp.pad(one, (0, padr)).reshape(-1, n_lanes)
    rank = (jnp.cumsum(one_p, axis=0) - one_p).reshape(-1)[:r]
    counts = one_p.sum(axis=0).astype(U32)
    flags = is_del.astype(U32) | (table_id.astype(U32) << U32(8))
    entry = jnp.concatenate(
        [flags[:, None], jnp.zeros((r, 1), U32), key.astype(U32)[:, None],
         ver[:, None], val], axis=1)
    nrow = log.shape[0]
    for s in range(S):
        lane = s * n_lanes + lane_local
        pos = head[lane] + rank.astype(U32)
        row = lane * cap + (pos % U32(cap)).astype(I32)
        log = log.at[jnp.where(do, row, nrow)].set(entry, mode="drop")
    return log, head + jnp.tile(counts, S)


# ------------------------------------------------------------------ cohort


def cohort_step(state: FusedState, key, *, w: int, n_sub: int,
                cf_buckets: int, cf_lock_slots: int, log_lanes: int = 16,
                validate: bool = True):
    """One cohort of w txns against the fused 3-replica state.

    Returns (state', stats [N_STATS] i32); stats layout is
    tatp_pipeline's + STAT_OVERFLOW (cf window overflow -> lane REJECTs)."""
    vw = state.val_words
    p1, nr, off = _layout(n_sub)
    kg, kv = jax.random.split(key)
    ttype, ops, tbl, kk, ws = tp.gen_cohort(kg, w, n_sub)
    ws_active, ws_lane, ws_tbl, ws_key, ws_kind = ws
    r = w * K
    r_cf = w  # wave-1/2 suffix window (E[cf lanes] ~ 0.18w)

    lane_op = ops.reshape(r)
    lane_tbl = tbl.reshape(r)
    lane_key = kk.reshape(r)
    used = lane_op != Op.NOP
    owner = (lane_key % S).astype(I32)
    is_cf = (lane_tbl == tatp.CALL_FORWARDING) & used

    offs = jnp.asarray(off, I32)
    dense_row = owner * nr + offs[jnp.clip(lane_tbl, 0, 3)] + lane_key
    cf_code = (lane_key.astype(U32) << U32(2)) | owner.astype(U32)
    sort_key = jnp.where(is_cf, BIG_CF + cf_code,
                         jnp.where(used, dense_row.astype(U32), BIG_NOP))

    order = jnp.arange(r, dtype=I32)
    s_key, perm = jax.lax.sort((sort_key, order), num_keys=2)
    s_op = lane_op[perm]
    s_rows = jnp.where(s_key < BIG_NOP, s_key.astype(I32), I32(S * nr))

    zval = jnp.zeros((r, vw), U32)
    d_op = jnp.where(s_key < BIG_NOP, s_op, Op.NOP)
    bank, d_rt, d_rv, d_rvl = _occ_dense(state.bank, s_rows, d_op, zval, vw)

    # cf window = last r_cf sorted lanes
    wd = slice(r - r_cf, r)
    cf_active = s_key[wd] >= BIG_CF
    cf_code_w = s_key[wd] - BIG_CF
    cf_keys = cf_code_w >> U32(2)
    cf_shard = (cf_code_w & U32(3)).astype(I32)
    cf_op = jnp.where(cf_active, s_op[wd], Op.NOP)
    cf, cf_lock, c_rt, c_rv, c_rvl = _cf_pass(
        state.cf, state.cf_lock, cf_buckets, cf_lock_slots, cf_shard,
        cf_keys, cf_op, zval[:r_cf], cf_active, vw)
    overflow = s_key[: r - r_cf] >= BIG_CF
    n_over = overflow.sum(dtype=I32)

    rt_s = d_rt.at[wd].set(jnp.where(cf_active, c_rt, d_rt[wd]))
    rt_s = jnp.where(jnp.concatenate([overflow, jnp.zeros((r_cf,), bool)]),
                     Reply.REJECT, rt_s)
    rv_s = d_rv.at[wd].set(jnp.where(cf_active, c_rv, d_rv[wd]))
    rvl_s = d_rvl.at[wd].set(jnp.where(cf_active[:, None], c_rvl, d_rvl[wd]))
    rt1f, rv1f, rvl1 = _unsort_packed(perm, rt_s, rv_s, rvl_s)
    rt1 = rt1f.reshape(w, K)
    rver1 = rv1f.reshape(w, K)

    magic_bad = jnp.sum((rt1f == Reply.VAL) & (rvl1[:, 1] != MAGIC),
                        dtype=I32)

    # ---- outcome ----------------------------------------------------------
    t = ttype
    is_ro = ((t == wl.TATP_GET_SUBSCRIBER) | (t == wl.TATP_GET_ACCESS)
             | (t == wl.TATP_GET_NEW_DEST))
    rw = ~is_ro
    ws_rt = jnp.take_along_axis(rt1, ws_lane, axis=1)
    granted = ws_active & (ws_rt == Reply.GRANT)
    lock_rejected = (ws_active & (ws_rt == Reply.REJECT)).any(axis=1)

    missing = jnp.zeros((w,), bool)
    m = t == wl.TATP_GET_NEW_DEST
    missing |= m & (rt1[:, 0] != Reply.VAL)
    m = (t == wl.TATP_UPDATE_SUBSCRIBER) | (t == wl.TATP_UPDATE_LOCATION)
    missing |= m & ((rt1[:, 0] != Reply.VAL) | (rt1[:, 1] != Reply.VAL))
    m = t == wl.TATP_INSERT_CF
    missing |= m & ((rt1[:, 0] != Reply.VAL) | (rt1[:, 1] == Reply.VAL))
    m = t == wl.TATP_DELETE_CF
    missing |= m & (rt1[:, 0] != Reply.VAL)

    ab_lock = rw & lock_rejected
    ab_missing = rw & ~lock_rejected & missing
    alive = rw & ~lock_rejected & ~missing

    # ---- wave 2: validation re-read (parity ballast; re-gathers state) ----
    if validate:
        is_read_lane = (ops == Op.OCC_READ) & alive[:, None]
        v_lane = jnp.where(is_read_lane.reshape(r), Op.OCC_READ, Op.NOP)
        v_s = v_lane[perm]
        vd_op = jnp.where(s_key < BIG_NOP, v_s, Op.NOP)
        bank, v_rt, v_rv, _ = _occ_dense(bank, s_rows, vd_op, zval, vw)
        cf, cf_lock, vc_rt, vc_rv, _ = _cf_pass(
            cf, cf_lock, cf_buckets, cf_lock_slots, cf_shard, cf_keys,
            jnp.where(cf_active, v_s[wd], Op.NOP), zval[:r_cf], cf_active,
            vw)
        v_rt = v_rt.at[wd].set(jnp.where(cf_active, vc_rt, v_rt[wd]))
        v_rv = v_rv.at[wd].set(jnp.where(cf_active, vc_rv, v_rv[wd]))
        vrtf, vrvf = _unsort_packed(perm, v_rt, v_rv)
        vrt = vrtf.reshape(w, K)
        vver = vrvf.reshape(w, K)
        bad = is_read_lane & ((vver != rver1)
                              | ((vrt != Reply.VAL) & (rt1 == Reply.VAL)))
        changed = bad.any(axis=1)
    else:
        changed = jnp.zeros((w,), bool)
    ab_validate = alive & changed
    alive = alive & ~changed

    state = state.replace(bank=bank, cf=cf, cf_lock=cf_lock)

    # ---- wave 3: log x3 + one lane per (write slot, replica) --------------
    do_w = ws_active & alive[:, None]                    # [w, 2]
    w_owner = (ws_key % S).astype(I32)
    payload = jax.random.randint(kv, (w, 2), 0, 1 << 16, dtype=I32)
    newval = jnp.zeros((w, 2, vw), U32)
    newval = newval.at[:, :, 0].set(payload.astype(U32))
    newval = newval.at[:, :, 1].set(jnp.where(do_w, U32(MAGIC), U32(0)))

    flat_do = jnp.concatenate([do_w[:, 0], do_w[:, 1]])
    new_log, new_head = _log_append(
        state.log, state.log_head, log_lanes, flat_do,
        jnp.concatenate([ws_key[:, 0], ws_key[:, 1]]),
        jnp.zeros((2 * w,), U32),
        jnp.concatenate([newval[:, 0], newval[:, 1]]),
        jnp.concatenate([ws_tbl[:, 0], ws_tbl[:, 1]]),
        jnp.concatenate([ws_kind[:, 0] == 2, ws_kind[:, 1] == 2]))
    state = state.replace(log=new_log, log_head=new_head)

    prim_op = jnp.select([ws_kind == 1, ws_kind == 2],
                         [Op.INSERT_PRIM, Op.DELETE_PRIM], Op.COMMIT_PRIM)
    bck_op = jnp.select([ws_kind == 1, ws_kind == 2],
                        [Op.INSERT_BCK, Op.DELETE_BCK], Op.COMMIT_BCK)
    dead_abort = granted & ~alive[:, None]               # [w, 2]

    parts = {"op": [], "key": [], "tbl": [], "val": [], "dest": []}
    for sl_i in range(2):
        for d_rel in range(S):
            dest = (w_owner[:, sl_i] + d_rel) % S
            if d_rel == 0:
                o = jnp.where(do_w[:, sl_i], prim_op[:, sl_i],
                              jnp.where(dead_abort[:, sl_i], Op.ABORT,
                                        Op.NOP))
            else:
                o = jnp.where(do_w[:, sl_i], bck_op[:, sl_i], Op.NOP)
            parts["op"].append(o)
            parts["key"].append(ws_key[:, sl_i])
            parts["tbl"].append(ws_tbl[:, sl_i])
            parts["val"].append(newval[:, sl_i])
            parts["dest"].append(dest)
    c_op = jnp.concatenate(parts["op"])
    c_key = jnp.concatenate(parts["key"])
    c_tbl = jnp.concatenate(parts["tbl"])
    c_val = jnp.concatenate(parts["val"])
    c_dest = jnp.concatenate(parts["dest"])
    rc = c_op.shape[0]                                   # 6w
    c_used = c_op != Op.NOP
    c_is_cf = (c_tbl == tatp.CALL_FORWARDING) & c_used
    c_row = c_dest * nr + offs[jnp.clip(c_tbl, 0, 3)] + c_key
    c_code = (c_key.astype(U32) << U32(2)) | c_dest.astype(U32)
    c_sort = jnp.where(c_is_cf, BIG_CF + c_code,
                       jnp.where(c_used, c_row.astype(U32), BIG_NOP))
    order3 = jnp.arange(rc, dtype=I32)
    s3_key, perm3 = jax.lax.sort((c_sort, order3), num_keys=2)
    s3_op = c_op[perm3]
    s3_val = c_val[perm3]
    s3_rows = jnp.where(s3_key < BIG_NOP, s3_key.astype(I32), I32(S * nr))
    d3_op = jnp.where(s3_key < BIG_NOP, s3_op, Op.NOP)
    new_bank, _, _, _ = _occ_dense(state.bank, s3_rows, d3_op, s3_val, vw)

    r3_cf = w // 2   # cf write lanes ~ 0.12w at the TATP mix
    wd3 = slice(rc - r3_cf, rc)
    cf3_active = s3_key[wd3] >= BIG_CF
    over3 = (s3_key[: rc - r3_cf] >= BIG_CF).sum(dtype=I32)
    cf3_code = s3_key[wd3] - BIG_CF
    new_cf, new_cf_lock, _, _, _ = _cf_pass(
        state.cf, state.cf_lock, cf_buckets, cf_lock_slots,
        (cf3_code & U32(3)).astype(I32), cf3_code >> U32(2),
        jnp.where(cf3_active, s3_op[wd3], Op.NOP), s3_val[wd3], cf3_active,
        vw)
    state = state.replace(bank=new_bank, cf=new_cf, cf_lock=new_cf_lock)

    committed = (is_ro & ~missing) | alive
    stats = jnp.stack([
        jnp.asarray(w, I32), committed.sum(dtype=I32),
        ab_lock.sum(dtype=I32),
        (ab_missing | (is_ro & missing)).sum(dtype=I32),
        ab_validate.sum(dtype=I32), magic_bad, n_over + over3,
    ])
    return state, stats


def build_runner(n_sub: int, w: int = 8192, cf_buckets: int = 1 << 15,
                 cf_lock_slots: int = 1 << 15, log_lanes: int = 16,
                 cohorts_per_block: int = 8, validate: bool = True):
    """jit(scan(cohort_step)); state donated, tables updated in place."""
    step = functools.partial(cohort_step, w=w, n_sub=n_sub,
                             cf_buckets=cf_buckets,
                             cf_lock_slots=cf_lock_slots,
                             log_lanes=log_lanes, validate=validate)

    def block(state, key):
        keys = jax.random.split(key, cohorts_per_block)
        return jax.lax.scan(step, state, keys)

    return jax.jit(block, donate_argnums=0)
