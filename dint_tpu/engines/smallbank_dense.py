"""Sort-free dense SmallBank engine: the TPU-first fast path.

Companion to engines/tatp_dense.py for the SmallBank workload, replacing
the vmapped sort-based smallbank.step pair the device-fused pipeline pays
per cohort (engines/smallbank_pipeline.py). Same structural moves:

* SAVINGS/CHECKING are dense 0..N-1 (smallbank/ebpf/smallbank.h:20-66), so
  both tables live in ONE flat row-id space: row = table*N + account, with
  row M = 2N as the never-written gather sentinel.
* The 3 servers' S/X lock tables partition by key%3
  (smallbank/caladan/client_ebpf_shard.cc:287-289), so their union is one
  exact pair of arrays: x_held bool [M+1] + s_count i32 [M+1].
* Replicas are bit-identical by construction (CommitLog x3 + CommitBck x2 +
  CommitPrim install everywhere), kept as axis 1 of val/ver and written by
  one row-major unique scatter; reads gather replica 0.

No-wait S/X arbitration without a sort (the closed form of processing a
row's lock requests in lane order, == the reference's per-entry CAS +
grant/reject counters, smallbank/ebpf/shard_kern.c:96-328):
  first_x, first_s = per-row scatter-min of lane index over X / S requests
  x_wins(row)      = first_x < first_s  and row free (no X held, no S held)
  X grant          = x_wins and lane == first_x
  S grant          = row has no X held and not x_wins
(if any S precedes the first X, the X rejects and ALL batch S's share the
row; if an X is first on a free row it takes it and everything else
rejects.)

The 2-stage software pipeline fuses, per device step,
  wave 1 of cohort t     (S/X lock + fused balance read + compute),
                         arbitrated against cohort t-1's STILL-HELD locks
  wave 2 of cohort t-1   (install + release + log x3), applied after
so locks are held across one step boundary and lock conflicts between
consecutive cohorts are real concurrency, exactly like the reference's
overlapping in-flight txns (acquire-before-release is what makes that
true — a release-first order would hand every acquire an empty lock
table). Per-txn balance logic is shared with the generic pipeline
(smallbank_pipeline.compute_phase).
"""
from __future__ import annotations

import functools

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from ..tables import log as logring
from . import smallbank
from .types import Op
from .smallbank_pipeline import (AMT, L, MAGIC, N_SHARDS, TS_AMT_MAX, VW,     # noqa: F401 (re-exported)
                                 STAT_ATTEMPTED, STAT_COMMITTED, STAT_AB_LOCK,
                                 STAT_AB_LOGIC, STAT_MAGIC_BAD, STAT_BAL_DELTA,
                                 N_STATS, compute_phase, gen_cohort,
                                 _lock_slots)

I32 = jnp.int32
U32 = jnp.uint32

BIG = jnp.int32(1 << 30)


@flax.struct.dataclass
class DenseBank:
    """Both tables + locks + logs in flat dense arrays (row M = 2N is the
    gather sentinel; masked scatters route out of bounds and drop)."""
    val: jax.Array       # u32 [M+1, 3, VW]  replica-identical values
    ver: jax.Array       # u32 [M+1, 3]
    x_held: jax.Array    # bool [M+1]  union of the 3 servers' X-lock maps
    s_count: jax.Array   # i32 [M+1]   union of the 3 servers' S counts
    log: logring.LogRing   # stacked [3] leading axis

    @property
    def n_accounts(self):
        return self.x_held.shape[0] // 2


def create(n_accounts: int, init_balance: int = 1000, log_lanes: int = 16,
           log_capacity: int = 1 << 20) -> DenseBank:
    """Populated on device (reference: smallbank/ebpf/shard_user.c:74-77);
    every account starts at init_balance with the magic word set."""
    m1 = 2 * n_accounts + 1
    val = jnp.zeros((m1, N_SHARDS, VW), U32)
    val = val.at[:-1, :, 0].set(U32(init_balance))
    val = val.at[:-1, :, 1].set(U32(MAGIC))
    ver = jnp.ones((m1, N_SHARDS), U32).at[-1].set(0)
    one_log = logring.create(log_lanes, log_capacity, VW)
    return DenseBank(
        val=val, ver=ver,
        x_held=jnp.zeros((m1,), bool),
        s_count=jnp.zeros((m1,), I32),
        log=jax.tree.map(lambda x: jnp.stack([x] * N_SHARDS), one_log),
    )


def total_balance(db: DenseBank, replica: int = 0):
    """Device-side balance sum over one replica (mod 2^32, i32 accumulate —
    conservation compares deltas under the same wraparound)."""
    return db.val[:-1, replica, 0].astype(I32).sum(dtype=I32)


@flax.struct.dataclass
class BankCtx:
    """A cohort between lock+compute (wave 1) and install+release (wave 2).
    Stats are emitted when the writes land. Bootstrap cohorts have
    attempted == 0 and all-False masks."""
    rows: jax.Array      # i32 [w, L] flat row ids (sentinel if inactive)
    granted: jax.Array   # bool [w, L]
    is_x: jax.Array      # bool [w, L] granted lock is exclusive
    do_write: jax.Array  # bool [w, L]
    nw: jax.Array        # i32 [w, L] new balances
    tbl: jax.Array       # i32 [w, L] (for the log)
    acc: jax.Array       # i32 [w, L] (for the log)
    attempted: jax.Array   # i32 scalar
    committed: jax.Array   # i32 scalar
    ab_lock: jax.Array     # i32 scalar
    ab_logic: jax.Array    # i32 scalar
    magic_bad: jax.Array   # i32 scalar
    bal_delta: jax.Array   # i32 scalar


def empty_ctx(w: int) -> BankCtx:
    def z(shape, dt):
        return jnp.asarray(np.zeros(shape, dt))

    return BankCtx(
        rows=z((w, L), np.int32), granted=z((w, L), bool),
        is_x=z((w, L), bool), do_write=z((w, L), bool),
        nw=z((w, L), np.int32), tbl=z((w, L), np.int32),
        acc=z((w, L), np.int32),
        attempted=z((), np.int32), committed=z((), np.int32),
        ab_lock=z((), np.int32), ab_logic=z((), np.int32),
        magic_bad=z((), np.int32), bal_delta=z((), np.int32))


def _stats_of(c: BankCtx):
    return jnp.stack([c.attempted, c.committed, c.ab_lock, c.ab_logic,
                      c.magic_bad, c.bal_delta])


def pipe_step(db: DenseBank, c1: BankCtx, key, *, w: int, n_accounts: int,
              gen_new: bool = True, hot_frac=None, hot_prob=None, mix=None):
    """One fused device step: wave 1 of a NEW cohort acquires against c1's
    STILL-HELD locks, then wave 2 installs c1's writes and releases them.
    Acquire-before-release is what makes cross-cohort lock conflicts real:
    cohort t's locks are visible to cohort t+1's no-wait acquires, exactly
    like the reference's overlapping in-flight txns. The order is safe for
    the fused reads too — any row c1 is about to install was X-held by c1,
    so the new cohort's acquire on it REJECTed and its (pre-install) value
    is never consumed; S-held rows are unmodified by definition.
    Returns (db', new_ctx, stats-of-c1)."""
    m1 = 2 * n_accounts + 1
    sent = m1 - 1
    oob = m1
    kgen, kamt = jax.random.split(key)

    # ---- wave 1: new cohort lock + fused read + compute -------------------
    if gen_new:
        skew = {"mix": mix}
        if hot_frac is not None:
            skew["hot_frac"] = hot_frac
        if hot_prob is not None:
            skew["hot_prob"] = hot_prob
        ttype, a1, a2 = gen_cohort(kgen, w, n_accounts, **skew)
        l_op, l_tb, l_ac = _lock_slots(ttype, a1, a2)      # [w, L]
    else:
        ttype = jnp.zeros((w,), I32)
        l_op = jnp.zeros((w, L), I32)
        l_tb = jnp.zeros((w, L), I32)
        l_ac = jnp.zeros((w, L), I32)
    ts_amt = jax.random.randint(kamt, (w,), -TS_AMT_MAX, TS_AMT_MAX + 1,
                                dtype=I32)

    active = l_op != 0
    rows = jnp.where(active, l_tb * n_accounts + l_ac, sent)  # [w, L]
    flat_rows = rows.reshape(-1)
    is_x_lane = (l_op == Op.ACQ_X_READ).reshape(-1)
    is_s_lane = (l_op == Op.ACQ_S_READ).reshape(-1)
    lane = jnp.arange(w * L, dtype=I32)

    first_x = jnp.full((m1,), BIG, I32).at[
        jnp.where(is_x_lane, flat_rows, oob)].min(lane, mode="drop")
    first_s = jnp.full((m1,), BIG, I32).at[
        jnp.where(is_s_lane, flat_rows, oob)].min(lane, mode="drop")
    # arbitrate against c1's STILL-HELD locks (released below, after)
    row_free = ~db.x_held & (db.s_count == 0)
    x_wins = (first_x < first_s) & row_free
    grant_x = is_x_lane & x_wins[flat_rows] & (first_x[flat_rows] == lane)
    grant_s = is_s_lane & ~db.x_held[flat_rows] & ~x_wins[flat_rows]
    x_held = db.x_held.at[jnp.where(grant_x, flat_rows, oob)].set(
        True, mode="drop", unique_indices=True)
    s_count = db.s_count.at[jnp.where(grant_s, flat_rows, oob)].add(
        1, mode="drop")

    granted = (grant_x | grant_s).reshape(w, L)
    lock_rejected = (active & ~granted).any(axis=1)
    alive = ~lock_rejected & (l_op[:, 0] != 0)

    # fused reads from the pre-install tables: rows c1 will install below
    # were X-held by c1, so this cohort never granted (or reads) them
    gbal = db.val[flat_rows, 0, 0].astype(I32)
    gmagic = db.val[flat_rows, 0, 1]
    magic_bad = jnp.sum((grant_x | grant_s) & (gmagic != MAGIC), dtype=I32)
    bal = jnp.where(granted, gbal.reshape(w, L), 0)

    nw, do, logic_abort, commit, committed = compute_phase(
        ttype, bal, alive, ts_amt)
    do_write = do & commit[:, None] & active
    bal_delta = jnp.sum(jnp.where(do_write, nw - bal, 0), dtype=I32)

    new_ctx = BankCtx(
        rows=rows, granted=granted, is_x=is_x_lane.reshape(w, L),
        do_write=do_write, nw=nw, tbl=l_tb, acc=l_ac,
        attempted=jnp.asarray(w if gen_new else 0, I32),
        committed=committed.sum(dtype=I32),
        ab_lock=(lock_rejected & (l_op[:, 0] != 0)).sum(dtype=I32),
        ab_logic=logic_abort.sum(dtype=I32),
        magic_bad=magic_bad,
        bal_delta=bal_delta)

    # ---- wave 2 of c1: install + release + log x3 -------------------------
    dwf = c1.do_write.reshape(-1)
    wrows = jnp.where(dwf, c1.rows.reshape(-1), oob)       # [wL]
    newbal = c1.nw.reshape(-1)
    newval = jnp.zeros((wrows.shape[0], VW), U32)
    newval = newval.at[:, 0].set(newbal.astype(U32))
    newval = newval.at[:, 1].set(jnp.where(dwf, U32(MAGIC), U32(0)))
    newver = db.ver[jnp.clip(wrows, 0, sent), 0] + 1

    def rep(x):
        return jnp.broadcast_to(x[:, None], x.shape[:1] + (N_SHARDS,)
                                + x.shape[1:])

    val = db.val.at[wrows].set(rep(newval), mode="drop", unique_indices=True)
    ver = db.ver.at[wrows].set(rep(newver), mode="drop", unique_indices=True)

    # release c1's locks AFTER the new cohort's acquires saw them; X rows
    # granted this step are disjoint from c1's (they were held), S counts
    # compose by +/-
    relx = (c1.granted & c1.is_x).reshape(-1)
    rels = (c1.granted & ~c1.is_x).reshape(-1)
    x_held = x_held.at[jnp.where(relx, c1.rows.reshape(-1), oob)].set(
        False, mode="drop", unique_indices=True)
    s_count = s_count.at[jnp.where(rels, c1.rows.reshape(-1), oob)].add(
        -1, mode="drop")

    zero = jnp.zeros_like(newbal, U32)
    logs = jax.vmap(
        lambda ring: logring.append(ring, dwf, c1.tbl.reshape(-1),
                                    jnp.zeros_like(newbal), zero,
                                    c1.acc.reshape(-1).astype(U32),
                                    newver, newval)[0])(db.log)

    db = db.replace(val=val, ver=ver, x_held=x_held, s_count=s_count,
                    log=logs)
    return db, new_ctx, _stats_of(c1)


def build_pipelined_runner(n_accounts: int, w: int = 8192,
                           cohorts_per_block: int = 8, hot_frac=None,
                           hot_prob=None, mix=None):
    """jit(scan(pipe_step)) over carry (db, c1). Returns (run, init, drain):
      run(carry, key) -> (carry', stats [cohorts_per_block, N_STATS])
      init(db)        -> carry with one bootstrap cohort in flight
      drain(carry)    -> (db, stats [1, N_STATS]) flushing the pipeline
    """
    kw = dict(w=w, n_accounts=n_accounts)
    kw_gen = dict(kw, hot_frac=hot_frac, hot_prob=hot_prob, mix=mix)

    def scan_fn(carry, key):
        db, c1 = carry
        db, new_ctx, stats = pipe_step(db, c1, key, **kw_gen)
        return (db, new_ctx), stats

    def block(carry, key):
        keys = jax.random.split(key, cohorts_per_block)
        return jax.lax.scan(scan_fn, carry, keys)

    def init(db):
        return (db, empty_ctx(w))

    @functools.partial(jax.jit, donate_argnums=0)
    def drain(carry):
        db, c1 = carry
        db, _, s1 = pipe_step(db, c1, jax.random.PRNGKey(0), gen_new=False,
                              **kw)
        return db, jnp.stack([s1])

    return jax.jit(block, donate_argnums=0), init, drain
