"""Sort-free dense SmallBank engine: the TPU-first fast path.

Companion to engines/tatp_dense.py for the SmallBank workload, replacing
the vmapped sort-based smallbank.step pair the device-fused pipeline pays
per cohort (engines/smallbank_pipeline.py). Structural moves, each forced
by a measured v5e fact:

* SAVINGS/CHECKING are dense 0..N-1 (smallbank/ebpf/smallbank.h:20-66), so
  both tables live in ONE flat row-id space: row = table*N + account, with
  row M = 2N as the never-written gather sentinel. Balances are a single
  1-D u32 array — any 2-D [M, k] layout is tiled to 128 words/row by XLA
  (24 GB at the reference's 24M accounts ([48M, 3, 2] u32 does not even
  compile on a 16 GB chip — observed), and 1-D scatters/gathers are the
  fast path anyway.

* Replicas are bit-identical by construction (CommitLog x3 + CommitBck x2 +
  CommitPrim install everywhere, smallbank/caladan/client_ebpf_shard.cc:
  389-560), so table content is stored once; the replication that matters
  for recovery stays physical in the log x3 (tables/log.RepLog). The
  multi-chip path (parallel/sharded.py) places real per-device replicas.

* Locks live in a HASHED slot space like the reference's lock tables
  (lock arrays indexed by a key hash, with hash-conflation conflicts,
  smallbank/ebpf/shard_kern.c:26-38) — exact (slot == row) whenever the
  table fits the slot cap, multiply-shift hashed above that. Because every
  lock is held for EXACTLY one pipeline step (acquire at wave 1 of step T,
  release at wave 2 in step T+1), lock state is a step stamp, not a
  counter: slot held at step T iff its stamp == T-1. Releases are implicit
  (stamps go stale), which deletes the X-release scatter and the
  duplicate-index S-count inc/dec scatters (duplicate-index scatters
  serialize on TPU) from the hot loop entirely.

* Per-row version words exist in the reference to order replicated
  installs (versioned kvs_set). Under deterministic batch certification
  the pipeline step index IS that order: log entries carry ver = step, so
  recovery's max-version-per-row rule works unchanged, and the table
  needs no version array (2 fewer random ops per step).

No-wait S/X arbitration without a sort (the closed form of processing a
slot's lock requests in lane order, == the reference's per-entry CAS +
grant/reject counters, smallbank/ebpf/shard_kern.c:96-328):
  first_x, first_s = per-slot scatter-min of lane index over X / S requests
  x_wins(slot)     = first_x < first_s  and slot free last step
  X grant          = x_wins and lane == first_x
  S grant          = slot has no X stamp and not x_wins
(if any S precedes the first X, the X rejects and ALL batch S's share the
slot; if an X is first on a free slot it takes it and everything else
rejects.) The S stamp is written by the first S lane only, so every
scatter in the step has provably unique indices.

The 2-stage software pipeline fuses, per device step,
  wave 1 of cohort t     (S/X lock + fused balance read + compute),
                         arbitrated against cohort t-1's STILL-HELD stamps
  wave 2 of cohort t-1   (install + log x3), applied after
so locks are held across one step boundary and lock conflicts between
consecutive cohorts are real concurrency, exactly like the reference's
overlapping in-flight txns. The wave-1 balance gather safely precedes
c1's installs: any row c1 installs was X-stamped by c1, so this cohort's
acquire on it REJECTed and its pre-install value is never consumed.
Per-txn balance logic is shared with the generic pipeline
(smallbank_pipeline.compute_phase).

The magic-word integrity check of the generic engines (STAT_MAGIC_BAD) is
structurally vacuous here — balances live alone in their array, and the
magic word would be a never-mutated constant — so it is not stored; the
window-wide balance-conservation invariant (bench_smallbank) is the
stronger integrity oracle. The stat slot is kept (always 0) for schema
compatibility.
"""
from __future__ import annotations

import functools

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from ._memo import memoize_builder
from ..monitor import counters as mon
from ..monitor import txnevents as txe
from ..monitor import waves
from ..ops import pallas_gather as pg
from ..tables import log as logring
from .types import Op
from .smallbank_pipeline import (AMT, L, MAGIC, N_SHARDS, TS_AMT_MAX, VW,     # noqa: F401 (re-exported)
                                 STAT_ATTEMPTED, STAT_COMMITTED, STAT_AB_LOCK,
                                 STAT_AB_LOGIC, STAT_MAGIC_BAD, STAT_BAL_DELTA,
                                 N_STATS, compute_phase, gen_cohort,
                                 _lock_slots)

I32 = jnp.int32
U32 = jnp.uint32

BIG = jnp.int32(1 << 30)
MAX_LOCK_SLOTS = 1 << 25


def lock_slots_for(m1: int) -> int:
    """Lock-table size: exact (>= m1) up to 2^25, hashed above — the
    reference's lock arrays are likewise a fixed hash space (~1.5x the
    keyspace, smallbank/ebpf/utils.h:16-17) with hash-conflation rejects.
    The cap trades conflation aborts against per-access cost on the stamp
    arrays (measured on v5e at the reference's 48M rows: 2^24 slots ->
    1.07M txn/s at 11.4% aborts of which ~5% are conflation; 2^26 -> 473k
    at 6.6% with conflation ~0; 2^25 is the balance point)."""
    return min(1 << (m1 - 1).bit_length(), MAX_LOCK_SLOTS)


@flax.struct.dataclass
class DenseBank:
    """Both tables + locks + logs in flat dense arrays (row M = 2N is the
    gather sentinel; masked scatters route out of bounds and drop).

    The ``hot_*`` leaves are the dintcache hot tier (round 10): a compact
    physical mirror of the hot-account prefix — mirror index
    ``tbl * hot_n + acc`` for accounts ``acc < hot_n`` — that every
    install writes through to, so mirror == table prefix is an invariant,
    not a protocol. ``hot_x``/``hot_s`` exist only while the lock table
    is EXACT (slot == row): under the hashed slot cap a cold account can
    conflate onto a hot account's slot, which would make a slot mirror
    incoherent, so hashed geometries serve stamps from the full arrays.
    None (the default) = no hot tier; the pytree and jaxpr are unchanged."""
    bal: jax.Array       # u32 [M+1]  balances (i32 bits)
    x_step: jax.Array    # u32 [H]    last step an X grant stamped the slot
    s_step: jax.Array    # u32 [H]    last step an S grant stamped the slot
    step: jax.Array      # u32 scalar, monotonic (starts at 2: stamp 0 is
                         #   "never held", so step-1 must never be 0)
    log: logring.RepLog  # 3 replica entries packed per slot (log x3)
    hot_bal: jax.Array | None = None   # u32 [2*hot_n] balance mirror
    hot_x: jax.Array | None = None     # u32 [2*hot_n] X-stamp mirror (exact)
    hot_s: jax.Array | None = None     # u32 [2*hot_n] S-stamp mirror (exact)
    hot_n: int = flax.struct.field(pytree_node=False, default=0)

    @property
    def n_accounts(self):
        return self.bal.shape[0] // 2

    @property
    def lock_slots(self):
        return self.x_step.shape[0]


def attach_hotset(db: DenseBank, hot_n: int) -> DenseBank:
    """Build the hot mirror for accounts [0, hot_n) from the current
    tables (a few MiB at the bench's 960k-account hot set). Stamps are
    mirrored only in the exact lock regime — see DenseBank."""
    n = db.n_accounts
    hot_n = int(min(max(int(hot_n), 1), n))
    m1 = 2 * n + 1
    idx = jnp.concatenate([jnp.arange(hot_n, dtype=I32),
                           n + jnp.arange(hot_n, dtype=I32)])
    exact = db.lock_slots >= m1
    return db.replace(
        hot_bal=db.bal[idx],
        hot_x=db.x_step[idx] if exact else None,
        hot_s=db.s_step[idx] if exact else None,
        hot_n=hot_n)


def create(n_accounts: int, init_balance: int = 1000, log_lanes: int = 16,
           log_capacity: int = 1 << 16) -> DenseBank:
    """Populated on device (reference: smallbank/ebpf/shard_user.c:74-77);
    every account starts at init_balance.

    ``log_capacity`` bounds the recovery window: the ring holds
    lanes*capacity entries and wraps like the reference's fixed rings
    (log_server/ebpf/ls_kern.c:72-73), and recover_* REFUSES a wrapped
    ring. The default (1M entries) wraps within ~1 s at full bench
    throughput — benchmarks trade recoverability for HBM; pass a larger
    capacity when recovery artifacts are wanted."""
    m1 = 2 * n_accounts + 1
    h = lock_slots_for(m1)
    bal = jnp.full((m1,), np.uint32(init_balance), U32).at[-1].set(0)
    return DenseBank(
        bal=bal,
        x_step=jnp.zeros((h,), U32),
        s_step=jnp.zeros((h,), U32),
        step=jnp.asarray(2, U32),
        log=logring.create_rep(log_lanes, log_capacity, VW,
                               replicas=N_SHARDS),
    )


def _slot_of(rows, m1: int, h: int):
    """Row -> lock slot: identity when exact, multiply-shift hash when the
    keyspace exceeds the lock table (the reference's fasthash-indexed lock
    arrays conflate keys the same way)."""
    if h >= m1:
        return rows
    shift = 32 - int(np.log2(h))
    return ((rows.astype(U32) * U32(0x9E3779B1)) >> U32(shift)).astype(I32)


def total_balance(db: DenseBank, replica: int = 0):
    """Device-side balance sum (mod 2^32, i32 accumulate — conservation
    compares deltas under the same wraparound). `replica` kept for
    signature compatibility: table content is stored once."""
    return db.bal[:-1].astype(I32).sum(dtype=I32)


@flax.struct.dataclass
class BankCtx:
    """A cohort between lock+compute (wave 1) and install (wave 2); lock
    release is implicit (stamps expire). Stats are emitted when the writes
    land. Bootstrap cohorts have attempted == 0 and all-False masks."""
    rows: jax.Array      # i32 [w, L] flat row ids (sentinel if inactive)
    do_write: jax.Array  # bool [w, L]
    nw: jax.Array        # i32 [w, L] new balances
    tbl: jax.Array       # i32 [w, L] (for the log)
    acc: jax.Array       # i32 [w, L] (for the log)
    attempted: jax.Array   # i32 scalar
    committed: jax.Array   # i32 scalar
    ab_lock: jax.Array     # i32 scalar
    ab_logic: jax.Array    # i32 scalar
    magic_bad: jax.Array   # i32 scalar (structurally 0, kept for schema)
    bal_delta: jax.Array   # i32 scalar


def empty_ctx(w: int) -> BankCtx:
    def z(shape, dt):
        return jnp.asarray(np.zeros(shape, dt))

    return BankCtx(
        rows=z((w, L), np.int32), do_write=z((w, L), bool),
        nw=z((w, L), np.int32), tbl=z((w, L), np.int32),
        acc=z((w, L), np.int32),
        attempted=z((), np.int32), committed=z((), np.int32),
        ab_lock=z((), np.int32), ab_logic=z((), np.int32),
        magic_bad=z((), np.int32), bal_delta=z((), np.int32))


def _stats_of(c: BankCtx):
    return jnp.stack([c.attempted, c.committed, c.ab_lock, c.ab_logic,
                      c.magic_bad, c.bal_delta])


def pipe_step(db: DenseBank, c1: BankCtx, key, *, w: int, n_accounts: int,
              gen_new: bool = True, hot_frac=None, hot_prob=None, mix=None,
              use_pallas: bool = False, use_hotset: bool = False,
              use_fused: bool = False,
              occupancy: jax.Array | None = None,
              shed: jax.Array | None = None,
              counters: mon.Counters | None = None,
              ring: txe.TxnRing | None = None,
              tcfg: txe.TraceCfg | None = None):
    """One fused device step: wave 1 of a NEW cohort acquires against c1's
    STILL-HELD stamps (stamp == step-1), then wave 2 installs c1's writes.
    Returns (db', new_ctx, stats-of-c1).

    ``use_pallas`` (static) routes the step's random single-word gathers —
    the held-stamp reads on x_step/s_step and the fused balance read —
    through the DMA-ring kernel (ops/pallas_gather.gather_rows),
    bit-identical to the XLA gathers; the scatter-min arbitration and the
    install scatters stay XLA (they are already 1-D unique-index fast
    paths).

    ``use_hotset`` (static) serves those same gathers through the
    dintcache partition instead (db must carry the hot mirror —
    attach_hotset): hot lanes (account < hot_n) read the compact mirror
    (VMEM-resident inside the pallas kernel, a small-array gather on the
    XLA route) while cold lanes walk the full tables, and the wave-2
    install writes through to the mirror (the fused
    ops/pallas_gather.scatter_rows_hot kernel on the pallas route, a
    double 1-D unique-index scatter on XLA). At the workload's 90%/4%
    skew this converts the dominant random-HBM row DMAs into VMEM
    accesses; outputs stay bit-identical to the default path (pinned in
    tests/test_hotset.py).

    ``use_fused`` (static; OFF by default) swallows the step's wave pairs
    into the round-12 megakernels: the held-stamp gathers + the fused
    balance read become gather streams of ONE lock_validate dispatch
    (the scatter-min arbitration and grant compares stay XLA — LOCK_WIN
    still seeds at the compare), and the balance install + log x3 append
    (+ hot-mirror write-through) become scatter streams of ONE
    install_log dispatch. Bit-identical to the unfused path
    (tests/test_fused_ops.py); independent of ``use_pallas``. With
    ``use_hotset`` the fused gathers read the main arrays directly
    (bit-identical by the mirror invariant) while installs keep the
    write-through, so the mirror stays coherent.

    ``occupancy``/``shed`` (device i32 scalars, or None = off): the
    dintserve variable-occupancy plane — lanes >= occupancy have their
    lock slots zeroed BEFORE arbitration (their txns never request,
    grant, compute, or install anything) and ``attempted`` counts only
    the admitted prefix; ``shed`` mirrors the host-side SLO-shed tally
    onto the device ledger. Traced scalars: one compiled step serves
    every occupancy at this width, and occupancy == w is bit-identical
    to the closed-loop path (tests/test_dintserve.py).

    ``counters`` (monitor.Counters | None): the dintmon counter plane —
    txn outcomes from c1's completing stats, S/X arbitration won-vs-lost
    (held-slot rejects split from intra-batch losses), install/log
    counts, ring high-water, backend dispatch. When threaded the updated
    Counters is appended to the return tuple; None (default) leaves the
    jaxpr untouched.

    ``ring``/``tcfg`` (monitor.txnevents): the dinttrace flight-recorder
    plane — lock verdicts, installs, and outcome classifications of the
    deterministically sampled txn-id subset land in the per-device event
    ring with one scatter-add per step. The updated TxnRing is appended
    AFTER the Counters leaf; None (default) adds nothing to the jaxpr."""
    m1 = 2 * n_accounts + 1
    sent = m1 - 1
    oob = m1
    h = db.lock_slots
    t = db.step
    kgen, kamt = jax.random.split(key)

    # ---- wave 1: new cohort lock + fused read + compute -------------------
    if gen_new:
        skew = {"mix": mix}
        if hot_frac is not None:
            skew["hot_frac"] = hot_frac
        if hot_prob is not None:
            skew["hot_prob"] = hot_prob
        with waves.scope("smallbank_dense", "gen"):
            ttype, a1, a2 = gen_cohort(kgen, w, n_accounts, **skew)
            l_op, l_tb, l_ac = _lock_slots(ttype, a1, a2)  # [w, L]
    else:
        ttype = jnp.zeros((w,), I32)
        l_op = jnp.zeros((w, L), I32)
        l_tb = jnp.zeros((w, L), I32)
        l_ac = jnp.zeros((w, L), I32)
    ts_amt = jax.random.randint(kamt, (w,), -TS_AMT_MAX, TS_AMT_MAX + 1,
                                dtype=I32)

    if occupancy is not None:
        # serving-plane occupancy mask: the cohort generates full-width
        # (RNG stream identical to the closed-loop path) and lanes past
        # the admitted occupancy have their lock slots erased before
        # arbitration — a padded lane requests nothing, computes nothing,
        # installs nothing
        with waves.scope("smallbank_dense", "serve"):
            occ = jnp.asarray(occupancy, I32)
            lane_ok = jnp.arange(w, dtype=I32) < occ
            l_op = jnp.where(lane_ok[:, None], l_op, 0)

    active = l_op != 0
    rows = jnp.where(active, l_tb * n_accounts + l_ac, sent)  # [w, L]
    flat_rows = rows.reshape(-1)
    slot = _slot_of(flat_rows, m1, h)                         # [wL]
    is_x_lane = (l_op == Op.ACQ_X_READ).reshape(-1)
    is_s_lane = (l_op == Op.ACQ_S_READ).reshape(-1)
    lane = jnp.arange(w * L, dtype=I32)

    # dintcache partition: a lane is hot iff its account sits in the
    # mirrored prefix; mirror index = tbl*hot_n + acc. Stamps share the
    # same mapping in the exact slot regime (slot == row).
    hn = db.hot_n
    stamp_hot = use_hotset and db.hot_x is not None
    if use_hotset:
        hot_lane = (active & (l_ac < hn)).reshape(-1)
        midx = jnp.where(hot_lane, (l_tb * hn + l_ac).reshape(-1), -1)

    if use_fused:
        # lock_validate megakernel: both held-stamp gathers AND the wave-1
        # balance read ride ONE gather_streams dispatch. All three read
        # pre-install state (the balance rows c1 installs below were
        # X-stamped by c1, so this cohort never granted them), and the
        # fused route reads the main arrays directly — bit-identical to
        # the hot-partitioned serving by the mirror invariant
        with waves.scope("smallbank_dense", "lock_validate"):
            hx_raw, hs_raw, fused_bal = pg.gather_streams(
                (db.x_step, db.s_step, db.bal),
                (slot, slot, flat_rows), (1, 1, 1))

    with waves.scope("smallbank_dense", "lock"):
        first_x = jnp.full((h,), BIG, I32).at[
            jnp.where(is_x_lane, slot, h)].min(lane, mode="drop")
        first_s = jnp.full((h,), BIG, I32).at[
            jnp.where(is_s_lane, slot, h)].min(lane, mode="drop")
        # held = stamped by the previous step's cohort (released implicitly
        # one step later; acquire-before-release semantics preserved)
        if use_fused:
            held_x = hx_raw == t - 1
            held_s = hs_raw == t - 1
        elif stamp_hot:
            held_x = pg.hot_gather(db.x_step, db.hot_x, slot, midx, 1,
                                   use_pallas=use_pallas) == t - 1
            held_s = pg.hot_gather(db.s_step, db.hot_s, slot, midx, 1,
                                   use_pallas=use_pallas) == t - 1
        elif use_pallas:
            held_x = pg.gather_rows(db.x_step, slot, 1) == t - 1
            held_s = pg.gather_rows(db.s_step, slot, 1) == t - 1
        else:
            held_x = db.x_step[slot] == t - 1
            held_s = db.s_step[slot] == t - 1
        slot_free = ~held_x & ~held_s
        x_wins = (first_x[slot] < first_s[slot]) & slot_free
        grant_x = is_x_lane & x_wins & (first_x[slot] == lane)
        grant_s = is_s_lane & ~held_x & ~x_wins
        x_step = db.x_step.at[jnp.where(grant_x, slot, h)].set(
            t, mode="drop", unique_indices=True)
        # one writer per slot: the first S lane stamps for all sharers
        s_writer = grant_s & (first_s[slot] == lane)
        s_step = db.s_step.at[
            jnp.where(s_writer, slot, h)].set(
            t, mode="drop", unique_indices=True)
        hot_x, hot_s = db.hot_x, db.hot_s
        if stamp_hot:
            # stamp write-through: the grant masks are one-writer-per-slot,
            # so their hot subsets are one-writer-per-mirror-index
            hot_x = hot_x.at[jnp.where(grant_x & (midx >= 0), midx,
                                       2 * hn)].set(t, mode="drop",
                                                    unique_indices=True)
            hot_s = hot_s.at[jnp.where(s_writer & (midx >= 0), midx,
                                       2 * hn)].set(t, mode="drop",
                                                    unique_indices=True)

        granted = (grant_x | grant_s).reshape(w, L)
        lock_rejected = (active & ~granted).any(axis=1)
        alive = ~lock_rejected & (l_op[:, 0] != 0)

    # fused reads from the pre-install table: rows c1 installs below were
    # X-stamped by c1, so this cohort never granted (or consumed) them
    with waves.scope("smallbank_dense", "read"):
        if use_fused:
            raw_bal = fused_bal     # already gathered in lock_validate
        elif use_hotset:
            raw_bal = pg.hot_gather(db.bal, db.hot_bal, flat_rows, midx, 1,
                                    use_pallas=use_pallas)
        else:
            raw_bal = (pg.gather_rows(db.bal, flat_rows, 1) if use_pallas
                       else db.bal[flat_rows])
        bal = jnp.where(granted, raw_bal.astype(I32).reshape(w, L), 0)

    with waves.scope("smallbank_dense", "compute"):
        nw, do, logic_abort, commit, committed = compute_phase(
            ttype, bal, alive, ts_amt)
        do_write = do & commit[:, None] & active
        bal_delta = jnp.sum(jnp.where(do_write, nw - bal, 0), dtype=I32)

    new_ctx = BankCtx(
        rows=rows, do_write=do_write, nw=nw, tbl=l_tb, acc=l_ac,
        attempted=(occ if occupancy is not None
                   else jnp.asarray(w if gen_new else 0, I32)),
        committed=committed.sum(dtype=I32),
        ab_lock=(lock_rejected & (l_op[:, 0] != 0)).sum(dtype=I32),
        ab_logic=logic_abort.sum(dtype=I32),
        magic_bad=jnp.asarray(0, I32),
        bal_delta=bal_delta)

    # ---- wave 2 of c1: install + log x3 (locks expire by stamp) -----------
    # MACHINE-CHECKED (dintlint protocol pass): c1.do_write descends from
    # the S/X grants (lock-dominates-write), and the x_step/s_step writes
    # stamp the step scalar — the expiring-lock witness that discharges
    # abort-implies-unlock for this engine's release-free design.
    with waves.scope("smallbank_dense",
                     "install_log" if use_fused else "install"):
        dwf = c1.do_write.reshape(-1)
        wrows = jnp.where(dwf, c1.rows.reshape(-1), oob)       # [wL]
        newbal = c1.nw.reshape(-1)
        if use_fused:
            # install_log megakernel: balance install, log x3 append, and
            # (hotset) the mirror write-through as masked row-scatter
            # streams of ONE dispatch. The log plan is the exact
            # append_rep plan (tables/log.plan_rep), so ring bytes match
            # the unfused path bit for bit
            newval = jnp.zeros((wrows.shape[0], VW), U32)
            newval = newval.at[:, 0].set(newbal.astype(U32))
            newval = newval.at[:, 1].set(
                jnp.where(dwf, U32(MAGIC), U32(0)))
            zero = jnp.zeros_like(newbal, U32)
            stepv = jnp.broadcast_to(t, newbal.shape)
            lflat, entry3, lane_counts = logring.plan_rep(
                db.log, dwf, c1.tbl.reshape(-1), jnp.zeros_like(newbal),
                zero, c1.acc.reshape(-1).astype(U32), stepv, newval)
            widx = jnp.where(dwf, c1.rows.reshape(-1), -1)
            tabs = [db.bal, db.log.entries.reshape(-1)]
            idxs = [widx, lflat]
            vals = [newbal.astype(U32), entry3.reshape(-1)]
            vws = [1, db.log.entries.shape[1]]
            if use_hotset:
                w_acc = c1.acc.reshape(-1)
                w_midx = jnp.where(dwf & (w_acc < hn),
                                   c1.tbl.reshape(-1) * hn + w_acc, -1)
                tabs += [db.hot_bal]
                idxs += [w_midx]
                vals += [newbal.astype(U32)]
                vws += [1]
            outs = pg.scatter_streams(tuple(tabs), tuple(idxs),
                                      tuple(vals), tuple(vws))
            bal_new = outs[0]
            logs = db.log.replace(
                entries=outs[1].reshape(db.log.entries.shape),
                head=db.log.head + lane_counts)
            hot_bal = outs[2] if use_hotset else db.hot_bal
        elif use_hotset:
            # partitioned install: the full table AND the hot mirror take
            # the write (one fused kernel on the pallas route, a double
            # 1-D unique-index scatter on XLA) — the write-through that
            # keeps mirror == table prefix an invariant, not a protocol
            w_acc = c1.acc.reshape(-1)
            w_midx = jnp.where(dwf & (w_acc < hn),
                               c1.tbl.reshape(-1) * hn + w_acc, -1)
            bal_new, hot_bal = pg.hot_scatter(
                db.bal, db.hot_bal, c1.rows.reshape(-1), w_midx, dwf,
                newbal.astype(U32), 1, use_pallas=use_pallas)
        else:
            hot_bal = db.hot_bal
            bal_new = db.bal.at[wrows].set(newbal.astype(U32), mode="drop",
                                           unique_indices=True)

    if not use_fused:
        with waves.scope("smallbank_dense", "log_append"):
            newval = jnp.zeros((wrows.shape[0], VW), U32)
            newval = newval.at[:, 0].set(newbal.astype(U32))
            newval = newval.at[:, 1].set(jnp.where(dwf, U32(MAGIC),
                                                   U32(0)))
            zero = jnp.zeros_like(newbal, U32)
            # log ver = step index: monotonic per row (one X-writer per
            # row per step), all recovery's max-ver-per-row rule needs
            stepv = jnp.broadcast_to(t, newbal.shape)
            logs = logring.append_rep(db.log, dwf, c1.tbl.reshape(-1),
                                      jnp.zeros_like(newbal), zero,
                                      c1.acc.reshape(-1).astype(U32),
                                      stepv, newval)

    db = db.replace(bal=bal_new, x_step=x_step, s_step=s_step,
                    step=t + 1, log=logs, hot_bal=hot_bal,
                    hot_x=hot_x, hot_s=hot_s)
    extra = ()
    if ring is not None:
        # dinttrace: this step's candidate events — lock verdicts of the
        # NEW cohort (txn id = gen_step*w + lane, stable across waves),
        # its outcome classification, and c1's landing installs — in ONE
        # sampled scatter-add (monitor/txnevents.emit)
        with waves.scope("smallbank_dense", "trace"):
            tu = jnp.asarray(t).astype(U32)
            lane_w = jnp.arange(w, dtype=U32)
            txn_new = tu * U32(w) + lane_w
            txn_c1 = (tu - U32(1)) * U32(w) + lane_w
            grant_l = (grant_x | grant_s)
            held_l = held_x | held_s
            lock_aux = (jnp.where(grant_l, txe.LOCK_GRANTED, 0)
                        | jnp.where(held_l, txe.LOCK_HELD, 0))
            ab_lock_m = lock_rejected & (l_op[:, 0] != 0)
            out_mask = committed | ab_lock_m | logic_abort
            cause = jnp.where(
                ab_lock_m, txe.CAUSE_LOCK,
                jnp.where(logic_abort, txe.CAUSE_LOGIC, txe.CAUSE_COMMIT))
            groups = (
                txe.ev(active.reshape(-1), jnp.repeat(txn_new, L),
                       txe.EV_LOCK,
                       waves.full_name("smallbank_dense", "lock"),
                       aux=lock_aux, step=tu),
                txe.ev(out_mask, txn_new, txe.EV_OUTCOME,
                       waves.full_name("smallbank_dense", "compute"),
                       aux=cause, step=tu),
                txe.ev(dwf, jnp.repeat(txn_c1, L), txe.EV_INSTALL,
                       waves.full_name("smallbank_dense", "install"),
                       step=tu),
            )
            ring, counters = txe.emit(ring, tcfg, groups, counters)
        extra = (ring,)
    if counters is not None:
        act_l = active.reshape(-1)
        grant_l = granted.reshape(-1)
        held_l = held_x | held_s            # [wL] slot stamped last step
        rej_l = act_l & ~grant_l
        hot_ctrs = {}
        if use_hotset:
            # partition accounting: every hot-partitioned gather serves
            # (midx >= 0) lanes from the mirror and the rest via cold row
            # DMAs; the mirror refresh is one bulk DMA per pallas gather
            # invocation (0 on the XLA partition route). The fused route
            # reads the main arrays directly (no gather is partitioned),
            # so its partition counters are structurally zero
            n_g = 0 if use_fused else 1 + (2 if stamp_hot else 0)
            hits = (midx >= 0).sum(dtype=I32)
            hot_ctrs = {
                mon.CTR_HOT_HITS: n_g * hits,
                mon.CTR_HOT_COLD_ROWS: n_g * (w * L) - n_g * hits,
                mon.CTR_HOT_REFRESH_BYTES:
                    (n_g * 2 * hn * 4) if use_pallas else 0,
            }
        serve_ctrs = {}
        if occupancy is not None:
            serve_ctrs = {
                mon.CTR_SERVE_OCC_LANES: occ,
                mon.CTR_SERVE_PAD_LANES: jnp.asarray(w, I32) - occ,
                mon.CTR_SERVE_SHED_LANES:
                    jnp.asarray(0 if shed is None else shed, I32),
            }
        counters = mon.bump(counters, {
            **hot_ctrs,
            **serve_ctrs,
            mon.CTR_STEPS: 1,
            mon.CTR_TXN_ATTEMPTED: c1.attempted,
            mon.CTR_TXN_COMMITTED: c1.committed,
            mon.CTR_AB_LOCK: c1.ab_lock,
            mon.CTR_AB_LOGIC: c1.ab_logic,
            mon.CTR_MAGIC_BAD: c1.magic_bad,
            mon.CTR_LOCK_REQUESTS: act_l.sum(dtype=I32),
            mon.CTR_LOCK_GRANTED: grant_l.sum(dtype=I32),
            mon.CTR_LOCK_REJECTED: rej_l.sum(dtype=I32),
            mon.CTR_LOCK_REJECT_HELD: (rej_l & held_l).sum(dtype=I32),
            mon.CTR_LOCK_REJECT_ARB: (rej_l & ~held_l).sum(dtype=I32),
            mon.CTR_INSTALL_WRITES: dwf.sum(dtype=I32),
            mon.CTR_LOG_APPENDS: dwf.sum(dtype=I32),
            (mon.CTR_DISPATCH_PALLAS if use_pallas
             else mon.CTR_DISPATCH_XLA): 1,
            **({mon.CTR_FUSED_DISPATCH: 1} if use_fused else {}),
        })
        counters = mon.gauge_max(
            counters, {mon.CTR_RING_HWM: logs.head.max()})
        return (db, new_ctx, _stats_of(c1), counters) + extra
    return (db, new_ctx, _stats_of(c1)) + extra


@memoize_builder
def build_pipelined_runner(n_accounts: int, w: int = 8192,
                           cohorts_per_block: int = 8, hot_frac=None,
                           hot_prob=None, mix=None, use_pallas=None,
                           use_hotset=None, use_fused=None,
                           monitor: bool = False, trace=None,
                           trace_rate=None, trace_cap=None,
                           serve: bool = False):
    """jit(scan(pipe_step)) over carry (db, c1). Returns (run, init, drain):
      run(carry, key) -> (carry', stats [cohorts_per_block, N_STATS])
      init(db)        -> carry with one bootstrap cohort in flight
      drain(carry)    -> (db, stats [1, N_STATS]) flushing the pipeline

    ``use_pallas``: None = honor DINT_USE_PALLAS env; Mosaic failure falls
    back to the XLA gathers (ops/pallas_gather.resolve_use_pallas).

    ``use_hotset``: None = honor DINT_USE_HOTSET env. Serves the step's
    random gathers through the dintcache hot/cold partition; the hot set
    defaults to the WORKLOAD's hot set (``hot_frac``, else the SmallBank
    90%/4% skew constant) so the mirror covers exactly the keys the skew
    concentrates on. init() attaches the mirror to a db that lacks one.
    A Mosaic rejection of the hot kernels degrades the serving backend to
    the XLA index-compare partition, never the split itself.

    ``use_fused``: None = honor DINT_USE_FUSED env; True/False forces.
    Routes the step through the round-12 megakernels (gather-stream
    lock_validate + scatter-stream install_log) after probing them at
    this runner's geometry; probe failure degrades to the unfused path
    with a logged warning (pg.resolve_use_fused).

    ``monitor``: thread the dintmon counter plane — the carry grows a
    trailing monitor.Counters leaf and drain returns (db, stats,
    counters); off (default) = contract and jaxpr unchanged.

    ``trace``/``trace_rate``/``trace_cap``: thread the dinttrace event
    ring (None = honor DINT_TRACE / DINT_TRACE_RATE). The carry grows a
    TxnRing leaf BEFORE the Counters leaf (counters stay carry[-1]); the
    ring is zeroed at each block/drain entry so every drained window is
    self-contained, and `init.trace_cfg` exposes the resolved TraceCfg
    (None when off) for the host-side drain. Default capacity is
    lossless for a full block: candidate lanes/step x cohorts_per_block.

    ``serve``: the dintserve variable-occupancy mode — run's signature
    becomes ``run(carry, key, occ, shed)`` with occ/shed i32
    [cohorts_per_block] arrays scanned alongside the step keys
    (pipe_step's occupancy/shed). Carry layout, init, and drain are
    unchanged.
    """
    from ..clients import workloads as wl
    use_hotset = pg.resolve_use_hotset(use_hotset)
    use_pallas = pg.resolve_use_pallas(use_pallas, n_idx=w * L, m_lock=None)
    hot_n = 0
    if use_hotset:
        frac = wl.SB_HOT_FRAC if hot_frac is None else float(hot_frac)
        hot_n = max(1, min(int(n_accounts * frac), n_accounts))
        if use_pallas and not pg.hot_kernels_available(n_idx=w * L):
            use_pallas = False      # partition stays; XLA serves it
    ew3 = N_SHARDS * (logring.HDR_WORDS + VW)
    scat_geoms = ((w * L, 1), (w * L, ew3))
    if use_hotset:
        scat_geoms = scat_geoms + ((w * L, 1),)
    use_fused = pg.resolve_use_fused(
        use_fused,
        gathers=((w * L, 1), (w * L, 1), (w * L, 1)),
        scatters=scat_geoms)
    kw = dict(w=w, n_accounts=n_accounts, use_pallas=use_pallas,
              use_hotset=use_hotset, use_fused=use_fused)
    kw_gen = dict(kw, hot_frac=hot_frac, hot_prob=hot_prob, mix=mix)
    trace_on = txe.trace_enabled(trace)
    tcfg = None
    if trace_on:
        n_step = w * (2 * L + 1)    # lock wL + outcome w + install wL
        cap = int(trace_cap) if trace_cap is not None \
            else n_step * cohorts_per_block
        tcfg = txe.TraceCfg(rate=txe.trace_rate(trace_rate), cap=cap,
                            wave=waves.full_name("smallbank_dense",
                                                 "trace"))

    def step_mon(db, c1, key, cnt, ring, **skw):
        out = pipe_step(db, c1, key, counters=cnt, ring=ring, tcfg=tcfg,
                        **skw)
        i = 3
        cnt = out[i] if cnt is not None else None
        i += 1 if monitor else 0
        ring = out[i] if ring is not None else None
        return out[0], out[1], out[2], cnt, ring

    def scan_fn(carry, x):
        key, occ, shed = x if serve else (x, None, None)
        db, c1 = carry[:2]
        ring = carry[2] if trace_on else None
        cnt = carry[-1] if monitor else None
        db, new_ctx, stats, cnt, ring = step_mon(db, c1, key, cnt, ring,
                                                 occupancy=occ, shed=shed,
                                                 **kw_gen)
        out = ((db, new_ctx) + ((ring,) if trace_on else ())
               + ((cnt,) if monitor else ()))
        return out, stats

    def _pre(carry):
        if trace_on:
            # each block is one drain window: self-contained ring
            carry = carry[:2] + (txe.reset(carry[2]),) + carry[3:]
        return carry

    if serve:
        def block(carry, key, occ, shed):
            carry = _pre(carry)
            keys = jax.random.split(key, cohorts_per_block)
            return jax.lax.scan(scan_fn, carry, (keys, occ, shed))
    else:
        def block(carry, key):
            carry = _pre(carry)
            keys = jax.random.split(key, cohorts_per_block)
            return jax.lax.scan(scan_fn, carry, keys)

    def init(db):
        if use_hotset and db.hot_n == 0:
            db = attach_hotset(db, hot_n)
        base = (db, empty_ctx(w))
        return (base + ((txe.create_ring(tcfg.cap),) if trace_on else ())
                + ((mon.create(),) if monitor else ()))

    @functools.partial(jax.jit, donate_argnums=0)
    def drain(carry):
        db, c1 = carry[:2]
        ring = txe.reset(carry[2]) if trace_on else None
        cnt = carry[-1] if monitor else None
        db, _, s1, cnt, ring = step_mon(db, c1, jax.random.PRNGKey(0),
                                        cnt, ring, gen_new=False, **kw)
        return ((db, jnp.stack([s1]))
                + ((ring,) if trace_on else ())
                + ((cnt,) if monitor else ()))

    init.trace_cfg = tcfg
    return jax.jit(block, donate_argnums=0), init, drain
