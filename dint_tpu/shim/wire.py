"""Wire-code profiles: reference packet-type enums <-> engine op/reply codes.

Each workload family in the reference has its own packet-type enum; the
engines here use one shared Op/Reply vocabulary (engines.types). A Profile
provides vectorized numpy maps both ways so the pump can translate a whole
batch at once. Wire enum sources:
  store     /root/reference/store/ebpf/utils.h:22-32
  lock_2pl  /root/reference/lock_2pl/ebpf/utils.h:9-17
  lock_fasst/root/reference/lock_fasst/ebpf/utils.h:9-17
  log_server/root/reference/log_server/ebpf/utils.h:11-12
  smallbank /root/reference/smallbank/caladan/proto.h:14-37
  tatp      /root/reference/tatp/ebpf/utils.h:38-73
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..engines.types import Op, Reply
from .native import FMT_FASST9, FMT_LOCK6, FMT_LOG53, FMT_MSG55

_N_WIRE = 64  # wire codes fit in u8; 64 covers every reference enum


@dataclasses.dataclass(frozen=True)
class Profile:
    """req_map[wire_type] -> Op;  rep_map[wire_req_type, Reply] -> wire code.

    Entries are -1 where undefined (unknown request -> NOP lane; undefined
    reply combination -> 255 on the wire, a code no reference enum uses).
    """
    name: str
    fmt: int
    req_map: np.ndarray   # i32 [_N_WIRE]
    rep_map: np.ndarray   # i32 [_N_WIRE, n_reply_codes]

    def to_ops(self, wire_type: np.ndarray, wire_table: np.ndarray):
        """(wire type, wire table) -> engine op array."""
        return self.req_map[np.minimum(wire_type, _N_WIRE - 1)]

    def to_wire(self, wire_req_type: np.ndarray, rtype: np.ndarray):
        """(original wire request type, engine Reply code) -> wire reply."""
        w = self.rep_map[np.minimum(wire_req_type, _N_WIRE - 1), rtype]
        return np.where(w < 0, 255, w).astype(np.uint8)


def _profile(name, fmt, req: dict, rep: dict) -> Profile:
    n_rep = 16  # headroom over engines.types.Reply codes (currently 0..8)
    req_map = np.full(_N_WIRE, Op.NOP, np.int32)
    for wcode, op in req.items():
        req_map[wcode] = op
    rep_map = np.full((_N_WIRE, n_rep), -1, np.int32)
    for wcode, m in rep.items():
        for rcode, wreply in m.items():
            rep_map[wcode, rcode] = wreply
    return Profile(name, fmt, req_map, rep_map)


# --- store: READ 0 / SET 1 / INSERT 2; replies GRANT_READ 3, REJECT_READ 4,
#     SET_ACK 5, REJECT_SET 6, NOT_EXIST 7, INSERT_ACK 8, REJECT_INSERT 9.
STORE = _profile("store", FMT_MSG55,
                 {0: Op.GET, 1: Op.SET, 2: Op.INSERT},
                 {0: {Reply.VAL: 3, Reply.REJECT: 4, Reply.NOT_EXIST: 7},
                  1: {Reply.ACK: 5, Reply.SPILL: 6, Reply.NOT_EXIST: 7},
                  2: {Reply.ACK: 8, Reply.SPILL: 9}})

# --- lock_2pl: ACQUIRE 0 / RELEASE 1 with lock type S/X in the table byte;
#     handled via LOCK2PL.to_ops override below.
_L2PL_REP = {0: {Reply.GRANT: 2, Reply.REJECT: 3, Reply.RETRY: 4},
             1: {Reply.ACK: 5}}
_LOCK2PL_BASE = _profile("lock_2pl", FMT_LOCK6, {}, _L2PL_REP)


class _Lock2PLProfile(Profile):
    def to_ops(self, wire_type, wire_table):
        is_x = wire_table != 0  # SHARED_LOCK 0 / EXCLUSIVE_LOCK 1
        acq = np.where(is_x, Op.ACQ_X, Op.ACQ_S)
        rel = np.where(is_x, Op.REL_X, Op.REL_S)
        return np.where(wire_type == 0, acq,
                        np.where(wire_type == 1, rel, Op.NOP)).astype(np.int32)


LOCK2PL = _Lock2PLProfile("lock_2pl", FMT_LOCK6, _LOCK2PL_BASE.req_map,
                          _LOCK2PL_BASE.rep_map)

# --- lock_fasst: READ 0 / ACQUIRE_LOCK 1 / ABORT 2 / COMMIT 3; replies
#     GRANT_READ 4, GRANT_LOCK 5, REJECT_LOCK 6, ABORT_ACK 7, COMMIT_ACK 8.
FASST = _profile("lock_fasst", FMT_FASST9,
                 {0: Op.READ_VER, 1: Op.LOCK, 2: Op.ABORT, 3: Op.COMMIT_VER},
                 {0: {Reply.VAL: 4},
                  # lock_fasst's wire enum has no same-key code; the
                  # attribution variant degrades to plain REJECT_LOCK here
                  1: {Reply.GRANT: 5, Reply.REJECT: 6,
                      Reply.REJECT_SAME_KEY: 6},
                  2: {Reply.ACK: 7},
                  3: {Reply.ACK: 8, Reply.REJECT: 6}})

# --- log_server: COMMIT 0 -> ACK 1.
LOG = _profile("log_server", FMT_LOG53,
               {0: Op.LOG_APPEND},
               {0: {Reply.ACK: 1}})

# --- smallbank: kAcquireShared..kCommitLog 0-6 (fused lock+read); replies
#     kGrantShared 7 .. kCommitLogAck 15, kRetry 16.
SMALLBANK = _profile("smallbank", FMT_MSG55,
                     {0: Op.ACQ_S_READ, 1: Op.ACQ_X_READ, 2: Op.REL_S,
                      3: Op.REL_X, 4: Op.COMMIT_PRIM, 5: Op.COMMIT_BCK,
                      6: Op.COMMIT_LOG},
                     {0: {Reply.GRANT: 7, Reply.REJECT: 8, Reply.RETRY: 16},
                      1: {Reply.GRANT: 9, Reply.REJECT: 10, Reply.RETRY: 16},
                      2: {Reply.ACK: 11},
                      3: {Reply.ACK: 12},
                      4: {Reply.ACK: 13, Reply.REJECT: 11},
                      5: {Reply.ACK: 14, Reply.REJECT: 11},
                      6: {Reply.ACK: 15}})

# --- tatp: READ 0, ACQUIRE_LOCK 1, ABORT 2, COMMIT_PRIM/BCK/LOG 12-14,
#     INSERT_PRIM/BCK 18/19, DELETE_PRIM/BCK/LOG 22-24; replies
#     GRANT_READ 4, REJECT_READ 5, NOT_EXIST 6, GRANT_LOCK 7, REJECT_LOCK 8,
#     ABORT_ACK 9, REJECT_COMMIT 11, *_ACK 15-17/20-21/25-27,
#     REJECT_LOCK_SAME_KEY 28.
TATP = _profile("tatp", FMT_MSG55,
                {0: Op.OCC_READ, 1: Op.OCC_LOCK, 2: Op.ABORT,
                 12: Op.COMMIT_PRIM, 13: Op.COMMIT_BCK, 14: Op.COMMIT_LOG,
                 18: Op.INSERT_PRIM, 19: Op.INSERT_BCK,
                 22: Op.DELETE_PRIM, 23: Op.DELETE_BCK, 24: Op.DELETE_LOG},
                {0: {Reply.VAL: 4, Reply.REJECT: 5, Reply.NOT_EXIST: 6},
                 1: {Reply.GRANT: 7, Reply.REJECT: 8,
                     Reply.REJECT_SAME_KEY: 28},
                 2: {Reply.ACK: 9},
                 12: {Reply.ACK: 15, Reply.REJECT: 11},
                 13: {Reply.ACK: 16, Reply.REJECT: 11},
                 14: {Reply.ACK: 17},
                 18: {Reply.ACK: 20, Reply.SPILL: 11},
                 19: {Reply.ACK: 21},
                 22: {Reply.ACK: 25, Reply.NOT_EXIST: 6},
                 23: {Reply.ACK: 26},
                 24: {Reply.ACK: 27}})

PROFILES = {p.name: p for p in
            (STORE, LOCK2PL, FASST, LOG, SMALLBANK, TATP)}
