"""ctypes bindings for the native host shim (native/shim.cc).

The shim is the framework's L0: a C++ UDP pump that batches the reference's
wire formats into fixed-width struct-of-arrays buffers (one per engine
step) and scatters replies with sendmmsg. Python sees numpy views over the
C++ buffers — zero copies on the poll side.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

VAL_SIZE = 40           # bytes, store/ebpf/utils.h:11
VAL_WORDS = VAL_SIZE // 4

# wire formats (native/shim.cc)
FMT_MSG55 = 0
FMT_LOCK6 = 1
FMT_FASST9 = 2
FMT_LOG53 = 3

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO = os.path.abspath(os.path.join(_NATIVE_DIR, "libdintshim.so"))
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "shim.cc"))


class _View(ctypes.Structure):
    _fields_ = [
        ("count", ctypes.c_uint32),
        ("slot", ctypes.c_uint32),
        ("ord", ctypes.POINTER(ctypes.c_uint8)),
        ("type", ctypes.POINTER(ctypes.c_uint8)),
        ("table", ctypes.POINTER(ctypes.c_uint8)),
        ("key", ctypes.POINTER(ctypes.c_uint64)),
        ("val", ctypes.POINTER(ctypes.c_uint8)),
        ("ver", ctypes.POINTER(ctypes.c_uint32)),
    ]


_lib = None


def load() -> ctypes.CDLL:
    """Load libdintshim.so, (re)building it with make if missing/stale."""
    global _lib
    if _lib is not None:
        return _lib
    if (not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
        subprocess.run(["make", "-C", os.path.dirname(_SO)], check=True,
                       capture_output=True)
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        # stale/foreign binary (e.g. built on another arch): force a rebuild
        subprocess.run(["make", "-B", "-C", os.path.dirname(_SO)], check=True,
                       capture_output=True)
        lib = ctypes.CDLL(_SO)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.shim_server_create.restype = ctypes.c_void_p
    lib.shim_server_create.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                       ctypes.c_uint32, ctypes.c_uint32,
                                       ctypes.c_uint32, ctypes.c_int]
    lib.shim_server_port.restype = ctypes.c_uint16
    lib.shim_server_port.argtypes = [ctypes.c_void_p]
    lib.shim_server_poll.restype = ctypes.c_int
    lib.shim_server_poll.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                     ctypes.POINTER(_View)]
    lib.shim_server_reply.restype = ctypes.c_int
    lib.shim_server_reply.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                      u8p, u8p, u32p]
    lib.shim_server_stats.argtypes = [ctypes.c_void_p, u64p]
    lib.shim_server_destroy.argtypes = [ctypes.c_void_p]
    lib.shim_client_create.restype = ctypes.c_void_p
    lib.shim_client_create.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                       ctypes.c_int]
    lib.shim_client_exchange.restype = ctypes.c_int
    lib.shim_client_exchange.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                         u8p, u8p, u8p, u64p, u8p, u32p,
                                         u8p, u8p, u8p, u64p, u8p, u32p,
                                         ctypes.c_uint32]
    lib.shim_client_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def _as_np(ptr, n, dtype):
    return np.ctypeslib.as_array(ptr, shape=(n,)).view(dtype)


class ShimServer:
    """The batching UDP pump. poll() -> dict of numpy views; reply() sends."""

    def __init__(self, port: int = 0, width: int = 4096, flush_us: int = 200,
                 nrings: int = 8, fmt: int = FMT_MSG55, ip: str = "127.0.0.1"):
        self._lib = load()
        self._h = self._lib.shim_server_create(ip.encode(), port, width,
                                               flush_us, nrings, fmt)
        if not self._h:
            raise OSError(f"shim: cannot bind UDP {ip}:{port}")
        self.width = width
        self.port = self._lib.shim_server_port(self._h)
        self._pending: dict[int, int] = {}   # slot -> polled batch count

    def poll(self, timeout_us: int = 100_000):
        """Returns (slot, batch dict of numpy views) or None on timeout.
        Views alias C++ memory: invalid after reply(slot)."""
        v = _View()
        if not self._lib.shim_server_poll(self._h, timeout_us,
                                          ctypes.byref(v)):
            return None
        n = v.count
        self._pending[v.slot] = n
        return v.slot, {
            "ord": _as_np(v.ord, n, np.uint8),
            "type": _as_np(v.type, n, np.uint8),
            "table": _as_np(v.table, n, np.uint8),
            "key": _as_np(v.key, n, np.uint64),
            "val": np.ctypeslib.as_array(v.val, shape=(n, VAL_SIZE)),
            "ver": _as_np(v.ver, n, np.uint32),
        }

    def reply(self, slot: int, rtype, rval=None, rver=None):
        n = len(rtype)
        expect = self._pending.pop(slot, None)
        if expect is not None and n != expect:
            raise ValueError(
                f"reply() got {n} lanes for slot {slot}, poll() returned "
                f"{expect} — C++ reads the full polled count")
        rtype = np.ascontiguousarray(rtype, np.uint8)
        if rval is None:
            rval = np.zeros((n, VAL_SIZE), np.uint8)
        rval = np.ascontiguousarray(rval, np.uint8)
        rver = np.ascontiguousarray(
            rver if rver is not None else np.zeros(n), np.uint32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        return self._lib.shim_server_reply(
            self._h, slot, rtype.ctypes.data_as(u8p),
            rval.ctypes.data_as(u8p), rver.ctypes.data_as(u32p))

    def stats(self):
        out = (ctypes.c_uint64 * 4)()
        self._lib.shim_server_stats(self._h, out)
        return {"pkts_rx": out[0], "pkts_tx": out[1], "batches": out[2],
                "dropped": out[3]}

    def close(self):
        if self._h:
            self._lib.shim_server_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class ShimClient:
    """Native synthetic client: one 1-RTT batched exchange per call."""

    def __init__(self, ip: str, port: int, fmt: int = FMT_MSG55):
        self._lib = load()
        self._h = self._lib.shim_client_create(ip.encode(), port, fmt)

    def exchange(self, types, keys, tables=None, vals=None, vers=None,
                 ords=None, timeout_ms: int = 1000):
        """Send n requests, wait for n replies. Returns dict of reply arrays
        (count may be < n on timeout; see 'n')."""
        n = len(types)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u64p = ctypes.POINTER(ctypes.c_uint64)

        def u8(x, default=None):
            if x is None:
                x = default if default is not None else np.zeros(n, np.uint8)
            return np.ascontiguousarray(x, np.uint8)

        types = u8(types)
        ords = u8(ords, np.arange(n, dtype=np.uint8))
        tables = u8(tables)
        keys = np.ascontiguousarray(keys, np.uint64)
        if vals is None:
            vals = np.zeros((n, VAL_SIZE), np.uint8)
        vals = np.ascontiguousarray(vals, np.uint8)
        vers = np.ascontiguousarray(
            vers if vers is not None else np.zeros(n), np.uint32)

        r_ord = np.zeros(n, np.uint8)
        r_type = np.zeros(n, np.uint8)
        r_table = np.zeros(n, np.uint8)
        r_key = np.zeros(n, np.uint64)
        r_val = np.zeros((n, VAL_SIZE), np.uint8)
        r_ver = np.zeros(n, np.uint32)
        got = self._lib.shim_client_exchange(
            self._h, n, ords.ctypes.data_as(u8p), types.ctypes.data_as(u8p),
            tables.ctypes.data_as(u8p), keys.ctypes.data_as(u64p),
            vals.ctypes.data_as(u8p), vers.ctypes.data_as(u32p),
            r_ord.ctypes.data_as(u8p), r_type.ctypes.data_as(u8p),
            r_table.ctypes.data_as(u8p), r_key.ctypes.data_as(u64p),
            r_val.ctypes.data_as(u8p), r_ver.ctypes.data_as(u32p),
            timeout_ms)
        return {"n": got, "ord": r_ord[:got], "type": r_type[:got],
                "table": r_table[:got], "key": r_key[:got],
                "val": r_val[:got], "ver": r_ver[:got]}

    def close(self):
        if self._h:
            self._lib.shim_client_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
