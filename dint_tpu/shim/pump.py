"""EnginePump: serve a batch-certification engine over the native UDP shim.

This is the L0/L4 glue of SURVEY.md §2.4's "TPU equivalent" row: the C++
pump (native/shim.cc) accumulates reference-wire-format datagrams into a
fixed-width batch, this class translates wire codes -> engine ops
(shim.wire profiles), pads to the jitted step's static width, runs the
step, translates Reply codes back, and hands the reply arrays to C++ for
sendmmsg scatter.

The serve loop is DOUBLE-BUFFERED over the shim's 8-slot ready ring:
batch i is dispatched (async jax step) before batch i-1's replies are
fetched and serialized, so device execution of i overlaps both the C++ RX
batching of i+1 and the host-side reply scatter of i-1 — the wire-path
analogue of the reference's run-to-completion prefetch pipeline
(tatp/dpdk/server_shard.cc:999-1016).
"""
from __future__ import annotations

import collections
import threading
import time

import jax
import numpy as np

from ..engines.types import make_batch
from ..stats import LatencyHistogram
from .native import VAL_SIZE, ShimServer
from .wire import Profile


class EnginePump:
    """Owns engine state; serves batches arriving on a ShimServer.

    Open-loop arrival accounting (dintscope SLO sensors): every batch is
    timestamped at poll return (arrival to the host), at step dispatch,
    and at reply scatter, and two exact-merge histograms record the split
    — ``queue_hist`` (arrival -> dispatch: host-side hold) and
    ``service_hist`` (dispatch -> replies on the wire: device execution +
    fetch + scatter, which under the double-buffered loop includes the
    overlap slack). One sample per batch; `latency_snapshot()` serializes
    both for artifacts, so queueing delay is recorded separately from
    service time instead of being folded into one client RTT."""

    def __init__(self, profile: Profile, step_fn, state, width: int = 4096,
                 port: int = 0, flush_us: int = 200, val_words: int = 10,
                 depth: int = 2, idle_poll_us: int = 50_000):
        assert depth >= 1
        self.profile = profile
        self.state = state
        self.width = width
        self.val_words = val_words
        self.depth = depth              # serve_forever keeps <= depth-1
        self.idle_poll_us = idle_poll_us  # .. in flight; poll bound idle
        self._step = jax.jit(step_fn, donate_argnums=0)
        self.server = ShimServer(port=port, width=width, flush_us=flush_us,
                                 fmt=profile.fmt)
        self.port = self.server.port
        self.batches_served = 0
        self.occupancy_lanes = 0        # real txns across served batches
        self.padded_lanes = 0           # width - occupancy padding waste
        self.queue_hist = LatencyHistogram()
        self.service_hist = LatencyHistogram()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _dispatch(self, got, t_arrival: float | None = None):
        """Parse a polled batch and dispatch the jitted step (async).
        The C++ ring slot's views are fully consumed here (make_batch
        copies to device buffers), so only the slot id + reply metadata
        survive. Returns a pending record for _finish."""
        slot, b = got
        n = len(b["key"])
        wire_type = b["type"].copy()  # views die at reply(); copy what we keep
        ops = self.profile.to_ops(wire_type, b["table"])
        vals = np.ascontiguousarray(b["val"]).view(np.uint32)
        vals = vals[:, :self.val_words]
        batch = make_batch(ops, b["key"], vals=vals, vers=b["ver"],
                           tables=b["table"].astype(np.int32),
                           width=self.width, val_words=self.val_words)
        t_disp = time.monotonic()
        self.state, replies = self._step(self.state, batch)
        self.occupancy_lanes += n
        self.padded_lanes += self.width - n
        if t_arrival is not None:
            self.queue_hist.add(max(t_disp - t_arrival, 0.0) * 1e6)
        return slot, n, wire_type, replies, t_disp

    def _finish(self, pending):
        """Fetch a dispatched batch's replies (value fetch = sync) and
        scatter them back over the wire."""
        slot, n, wire_type, replies, t_disp = pending
        rtype = np.asarray(replies.rtype)[:n]
        rval32 = np.asarray(replies.val)[:n]
        rver = np.asarray(replies.ver)[:n]
        wire_reply = self.profile.to_wire(wire_type, rtype)
        rval = np.zeros((n, VAL_SIZE), np.uint8)
        rval[:, :self.val_words * 4] = np.ascontiguousarray(
            rval32[:, :self.val_words]).view(np.uint8).reshape(n, -1)
        self.server.reply(slot, wire_reply, rval, rver)
        self.service_hist.add((time.monotonic() - t_disp) * 1e6)
        self.batches_served += 1

    def latency_snapshot(self) -> dict:
        """Queue/service split for artifacts: percentiles + the exact
        histograms (one sample per served batch), plus the dintserve
        occupancy accounting — width, real vs padded lanes (identity:
        occupancy + padded == width * batches), and lanes the C++ ring
        overflowed before the host ever saw them ("shed": the wire-path
        analogue of serve_shed_lanes)."""
        def side(h):
            return {**{f"{k}_us": round(v, 2)
                       for k, v in h.percentiles().items()},
                    "hist": h.to_dict()}

        return {"batches": self.batches_served,
                "width": self.width,
                "depth": self.depth,
                "occupancy_lanes": self.occupancy_lanes,
                "padded_lanes": self.padded_lanes,
                "shed": int(self.server.stats()["dropped"]),
                "queue": side(self.queue_hist),
                "service": side(self.service_hist)}

    def serve_one(self, timeout_us: int = 100_000) -> bool:
        """Poll one batch, certify, reply (synchronous single-batch path).
        Returns True if a batch ran."""
        got = self.server.poll(timeout_us)
        if got is None:
            return False
        self._finish(self._dispatch(got, time.monotonic()))
        return True

    def serve_forever(self):
        """Depth-k double-buffered loop: up to ``depth - 1`` dispatched
        batches stay in flight behind the one being accumulated, so
        device execution of batch i overlaps the C++ RX batching of
        i+1..i+k-1 AND the host-side reply scatter of i-1 (depth=2 is
        the classic double buffer this loop shipped with). The poll is
        NON-blocking while anything is in flight — if the ring has a
        follow-up batch ready it pipelines, otherwise the oldest pending
        replies go out immediately (closed-loop clients are blocked on
        them, so waiting here would just add dead reply latency); an
        idle pump parks in the kernel for ``idle_poll_us`` per poll."""
        pending = collections.deque()
        while not self._stop.is_set():
            got = self.server.poll(
                timeout_us=0 if pending else self.idle_poll_us)
            if got is not None:
                pending.append(self._dispatch(got, time.monotonic()))
                if len(pending) < self.depth:
                    continue            # room to run ahead: poll again
            while pending:
                self._finish(pending.popleft())
                if got is not None:
                    break               # keep only the freshest in flight
        while pending:
            self._finish(pending.popleft())

    def start(self):
        """Run the serve loop on a background thread (tests/benchmarks)."""
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join()
            self._thread = None

    def close(self):
        self.stop()
        self.server.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
