"""Native host shim: C++ UDP request pump + wire-format profiles + pump."""
from .native import (FMT_FASST9, FMT_LOCK6, FMT_LOG53, FMT_MSG55, VAL_SIZE,
                     ShimClient, ShimServer)
from .pump import EnginePump
from .wire import FASST, LOCK2PL, LOG, PROFILES, SMALLBANK, STORE, TATP

__all__ = ["ShimClient", "ShimServer", "EnginePump", "PROFILES", "STORE",
           "LOCK2PL", "FASST", "LOG", "SMALLBANK", "TATP", "VAL_SIZE",
           "FMT_MSG55", "FMT_LOCK6", "FMT_FASST9", "FMT_LOG53"]
