"""Host backing KVS + cached-store orchestrator (the userspace fallback).

Plays the role of the reference's userspace KVS worker threads
(store/ebpf/store_user.c:99-168: apply the evicted record piggybacked in the
ext_message, then serve GET/SET/INSERT/DELETE against the real chained KVS)
plus the bloom bookkeeping the kernel cannot do (DELETE-side bloom
recompute happens in userspace, tatp/ebpf/shard_user.c DELETE path).

`CachedStore` is the full two-tier server: device cache (engines.store_cache)
in front, this host KVS behind, refills flowing back like the TC egress hook.
"""
from __future__ import annotations

import dataclasses

import jax
import numpy as np

from ..engines import store_cache
from ..engines.types import Op, Reply, make_batch
from ..ops import hashing


class HostKVS:
    """Authoritative backing store: dict of key -> (val tuple, ver), with
    per-cache-bucket membership so bloom words stay exact."""

    def __init__(self, cache_buckets: int, val_words: int):
        self.data: dict[int, tuple[tuple, int]] = {}
        self.nb = cache_buckets
        self.vw = val_words
        self._bucket_keys: dict[int, set] = {}   # cache bucket -> keys

    def _bucket(self, key: int) -> int:
        return int(hashing.bucket_np(np.uint64(key), self.nb))

    def bloom_word(self, bucket: int) -> int:
        word = 0
        for k in self._bucket_keys.get(bucket, ()):
            word |= 1 << int(hashing.bloom_bit_np(np.uint64(k)))
        return word

    def _track(self, key: int):
        self._bucket_keys.setdefault(self._bucket(key), set()).add(key)

    def _untrack(self, key: int):
        self._bucket_keys.get(self._bucket(key), set()).discard(key)

    def populate(self, keys, vals, vers=None):
        vers = vers if vers is not None else np.ones(len(keys))
        for k, v, ver in zip(keys, np.asarray(vals), vers):
            self.data[int(k)] = (tuple(int(x) for x in v), int(ver))
            self._track(int(k))

    def writeback(self, key: int, val, ver: int):
        """Apply an evicted dirty record (ext_message ver1==1 protocol)."""
        self.data[key] = (tuple(int(x) for x in val), ver)
        self._track(key)

    def resolve_batch(self, ops, keys, vals):
        """Serve the deferred lanes of one batch with the engine's
        serialization contract (engines/store.py header): per key, GETs see
        pre-batch state, then writes apply in lane order with monotonic
        versions. Deferral is whole-segment, so every lane of a deferred key
        is here — semantics compose exactly with the cache's local segments.

        Returns (rtype [m], val [m, VW], ver [m])."""
        m = len(ops)
        rtype = np.zeros(m, np.int32)
        rver = np.zeros(m, np.uint32)
        rval = np.zeros((m, self.vw), np.uint32)
        for i in range(m):
            if ops[i] == Op.GET:
                ent = self.data.get(int(keys[i]))
                if ent is None:
                    rtype[i] = Reply.NOT_EXIST
                else:
                    rtype[i] = Reply.VAL
                    rval[i] = ent[0]
                    rver[i] = ent[1]
        base: dict[int, int] = {}
        cnt: dict[int, int] = {}
        for i in range(m):
            k = int(keys[i])
            if ops[i] in (Op.SET, Op.INSERT):
                if k not in base:
                    base[k] = self.data[k][1] if k in self.data else 0
                    cnt[k] = 0
                cnt[k] += 1
                ver = base[k] + cnt[k]
                self.data[k] = (tuple(int(x) for x in vals[i]), ver)
                self._track(k)
                rtype[i] = Reply.ACK
                rver[i] = ver
            elif ops[i] == Op.DELETE:
                if k not in base:
                    base[k] = self.data[k][1] if k in self.data else 0
                    cnt[k] = 0
                if k in self.data:
                    del self.data[k]
                    self._untrack(k)
                    rtype[i] = Reply.ACK
                else:
                    rtype[i] = Reply.NOT_EXIST
        return rtype, rval, rver


@dataclasses.dataclass
class CacheStats:
    served: int = 0
    hits: int = 0          # lanes answered by the device cache
    misses: int = 0        # lanes deferred to the host
    bloom_negatives: int = 0
    writebacks: int = 0    # evicted dirty records applied


class CachedStore:
    """Two-tier store server: device cache + host KVS + refill loop."""

    def __init__(self, cache_buckets: int, val_words: int = 10,
                 slots: int = 4, policy: str = store_cache.WB_BLOOM,
                 width: int = 4096):
        self.cache = store_cache.create(cache_buckets, slots, val_words)
        self.kvs = HostKVS(cache_buckets, val_words)
        self.policy = policy
        self.vw = val_words
        self.width = width
        self.stats = CacheStats()
        self._step = jax.jit(
            lambda c, b: store_cache.cache_step(c, b, policy=policy),
            donate_argnums=0)
        self._refill = jax.jit(store_cache.refill, donate_argnums=0)
        self._pending: dict[int, bool] = {}    # refill keys (bloom-only if False)

    def populate(self, keys, vals, vers=None):
        """Load the backing store AND prime the device bloom words — the
        reference's equivalent state arises from populate-over-network, where
        every install travels the TC path and sets its bloom bit
        (store/ebpf/store_kern.c:302-372). A zeroed bloom would wrongly
        short-circuit GETs for populated-but-uncached keys to NOT_EXIST."""
        import jax.numpy as jnp

        self.kvs.populate(keys, vals, vers)
        keys = np.asarray(keys, np.uint64)
        nb = self.cache.kv.n_buckets
        bkt = hashing.bucket_np(keys, nb)
        bits = hashing.bloom_bit_np(keys)
        bloom = np.zeros(nb, np.uint64)
        np.bitwise_or.at(bloom, bkt, np.uint64(1) << bits.astype(np.uint64))
        t = self.cache.kv
        self.cache = self.cache.replace(kv=t.replace(
            bloom_hi=jnp.asarray((bloom >> np.uint64(32)).astype(np.uint32)),
            bloom_lo=jnp.asarray(bloom.astype(np.uint32))))

    def serve(self, ops, keys, vals=None):
        """One server round: refill -> device step -> host fallback.

        Returns (rtype [n], val [n, VW], ver [n]) numpy arrays.
        """
        n = len(ops)
        ops = np.asarray(ops, np.int32)
        keys = np.asarray(keys, np.uint64)
        if vals is None:
            vals = np.zeros((n, self.vw), np.uint32)

        self._do_refills()
        batch = make_batch(ops, keys, vals, width=self.width,
                           val_words=self.vw)
        self.cache, replies, miss, flush = self._step(self.cache, batch)
        rtype = np.asarray(replies.rtype)[:n].copy()
        rval = np.asarray(replies.val)[:n].copy()
        rver = np.asarray(replies.ver)[:n].copy()
        miss = np.asarray(miss)[:n]

        # dirty cached copies of deferred segments MUST land in the backing
        # store before their lanes are resolved (see cache_step docstring)
        f_mask = np.asarray(flush["mask"])
        if f_mask.any():
            fkh = np.asarray(flush["key_hi"])[f_mask]
            fkl = np.asarray(flush["key_lo"])[f_mask]
            fv = np.asarray(flush["val"])[f_mask]
            fr = np.asarray(flush["ver"])[f_mask]
            for kh, kl, v, vr in zip(fkh, fkl, fv, fr):
                self.kvs.writeback((int(kh) << 32) | int(kl), v, int(vr))
                self.stats.writebacks += 1

        st = self.stats
        st.served += n
        st.misses += int(miss.sum())
        st.hits += int((~miss & (ops != Op.NOP)).sum())
        st.bloom_negatives += int((rtype[~miss] == Reply.NOT_EXIST).sum())

        # host fallback: resolve the deferred lanes as one sub-batch
        mi = np.nonzero(miss)[0]
        if len(mi):
            rt, rv, rr = self.kvs.resolve_batch(ops[mi], keys[mi],
                                                np.asarray(vals)[mi])
            rtype[mi], rver[mi] = rt, rr
            rval[mi] = rv
            # queue refills: full record for present keys, bloom-only after
            # DELETE / for absent keys (keeps negatives exact)
            for k in keys[mi]:
                self._pending[int(k)] = int(k) in self.kvs.data
        return rtype, rval, rver

    def _do_refills(self):
        if not self._pending:
            return
        items = list(self._pending.items())[: self.width]
        for k, _ in items:
            del self._pending[k]
        r = len(items)
        key = np.array([k for k, _ in items], np.uint64)
        val = np.zeros((r, self.vw), np.uint32)
        ver = np.zeros(r, np.uint32)
        bloom = np.zeros(r, np.uint64)
        for j, (k, present) in enumerate(items):
            if present:
                ent = self.kvs.data[k]
                val[j] = ent[0]
                ver[j] = ent[1]
            bloom[j] = self.kvs.bloom_word(self.kvs._bucket(k))
        # dedup per bucket: refill installs at most one record per bucket per
        # call; re-queue the rest
        bkt = hashing.bucket_np(key, self.cache.kv.n_buckets)
        seen, keep = set(), []
        for j in range(r):
            if int(bkt[j]) in seen:
                self._pending[int(key[j])] = items[j][1]
            else:
                seen.add(int(bkt[j]))
                keep.append(j)
        keep = np.array(keep, np.int64)
        key, val, ver, bloom = key[keep], val[keep], ver[keep], bloom[keep]
        r = len(keep)

        pad = self.width - r
        key_hi = (key >> np.uint64(32)).astype(np.uint32)
        key_lo = key.astype(np.uint32)
        b_hi = (bloom >> np.uint64(32)).astype(np.uint32)
        b_lo = bloom.astype(np.uint32)

        def p(x, fill=0):
            return np.concatenate([x, np.full((pad,) + x.shape[1:], fill,
                                              x.dtype)])

        mask = p(np.ones(r, bool), False)
        self.cache, ev = self._refill(
            self.cache, p(key_hi), p(key_lo), p(val), p(ver),
            p(b_hi), p(b_lo), mask)
        ev_mask = np.asarray(ev["mask"])
        if ev_mask.any():
            ekh = np.asarray(ev["key_hi"])[ev_mask]
            ekl = np.asarray(ev["key_lo"])[ev_mask]
            evv = np.asarray(ev["val"])[ev_mask]
            evr = np.asarray(ev["ver"])[ev_mask]
            for kh, kl, v, vr in zip(ekh, ekl, evv, evr):
                self.kvs.writeback((int(kh) << 32) | int(kl), v, int(vr))
                self.stats.writebacks += 1
