"""Host backing KVS + cached-store orchestrator (the userspace fallback).

Plays the role of the reference's userspace KVS worker threads
(store/ebpf/store_user.c:99-168: apply the evicted record piggybacked in the
ext_message, then serve GET/SET/INSERT/DELETE against the real chained KVS)
plus the bloom bookkeeping the kernel cannot do (DELETE-side bloom
recompute happens in userspace, tatp/ebpf/shard_user.c DELETE path).

The KVS is VECTORIZED numpy end to end (this was a per-lane Python dict
loop until round 3, unbenchable at the reference's 24M-key scale): a
two-choice bucketized open-addressing table (8 slots/bucket, grow-and-
rehash on pressure, tiny spill dict as the overflow escape), batch
lookup/upsert/delete, and exact per-(cache-bucket, bloom-bit) liveness
counters so DELETE keeps device bloom words exact without scanning.
resolve_batch's common case (any mix of GETs + SET/INSERT-only keys) is
fully vectorized; only key-groups containing a DELETE fall back to an
ordered scalar walk, preserving the engine's serialization contract
exactly (GETs see pre-batch state; writes apply in lane order with
monotonic versions).

`CachedStore` is the full two-tier server: device cache (engines.store_cache)
in front, this host KVS behind, refills flowing back like the TC egress hook.
"""
from __future__ import annotations

import dataclasses

import jax
import numpy as np

from ..engines import store_cache
from ..engines.types import Op, Reply, make_batch
from ..ops import hashing

S = 8              # slots per backing bucket
GROW_SPILL = 1024  # spill-dict size that triggers a grow+rehash


class HostKVS:
    """Authoritative backing store: vectorized two-choice hash table with
    per-cache-bucket bloom liveness counters."""

    def __init__(self, cache_buckets: int, val_words: int,
                 capacity: int = 1 << 15):
        self.cache_nb = cache_buckets
        self.vw = val_words
        nb = max(16, 1 << int(np.ceil(np.log2(max(capacity, 256) * 2 / S))))
        self._alloc(nb)
        # liveness count per (cache bucket, bloom bit); u16 add/sub exact
        # far past any realistic per-bit occupancy
        self._bloom_cnt = np.zeros(cache_buckets * 64, np.uint16)
        self._spill: dict[int, tuple[np.ndarray, int]] = {}
        self.n_live = 0

    def _alloc(self, nb: int):
        self.nb = nb
        self._keys = np.zeros((nb, S), np.uint64)
        self._used = np.zeros((nb, S), bool)
        self._vals = np.zeros((nb, S, self.vw), np.uint32)
        self._vers = np.zeros((nb, S), np.uint32)

    # ------------------------------------------------------------ core ops

    def _find(self, keys: np.ndarray):
        """Vectorized slot search. Returns (found [m], bkt [m], slot [m]);
        spill-dict keys report found=False here (callers check _spill)."""
        m = len(keys)
        b1, b2 = hashing.bucket_pair_np(keys, self.nb)
        found = np.zeros(m, bool)
        bkt = np.zeros(m, np.int64)
        slot = np.zeros(m, np.int64)
        for b in (np.asarray(b1, np.int64), np.asarray(b2, np.int64)):
            match = self._used[b] & (self._keys[b] == keys[:, None])
            hit = match.any(axis=1)
            take = hit & ~found
            bkt[take] = b[take]
            slot[take] = match.argmax(axis=1)[take]
            found |= hit
        return found, bkt, slot

    def contains(self, keys) -> np.ndarray:
        keys = np.asarray(keys, np.uint64)
        found, _, _ = self._find(keys)
        if not found.all() and self._spill:
            for i in np.nonzero(~found)[0]:
                found[i] = int(keys[i]) in self._spill
        return found

    def lookup(self, keys):
        """Batch read: (found [m], vals [m, VW], vers [m])."""
        keys = np.asarray(keys, np.uint64)
        found, bkt, slot = self._find(keys)
        vals = np.zeros((len(keys), self.vw), np.uint32)
        vers = np.zeros(len(keys), np.uint32)
        vals[found] = self._vals[bkt[found], slot[found]]
        vers[found] = self._vers[bkt[found], slot[found]]
        if self._spill:
            for i in np.nonzero(~found)[0]:
                ent = self._spill.get(int(keys[i]))
                if ent is not None:
                    found[i] = True
                    vals[i] = ent[0]
                    vers[i] = ent[1]
        return found, vals, vers

    def _bloom_add(self, keys: np.ndarray):
        idx = (hashing.bucket_np(keys, self.cache_nb).astype(np.int64) * 64
               + hashing.bloom_bit_np(keys).astype(np.int64))
        u, c = np.unique(idx, return_counts=True)
        self._bloom_cnt[u] += c.astype(np.uint16)

    def _bloom_sub(self, keys: np.ndarray):
        idx = (hashing.bucket_np(keys, self.cache_nb).astype(np.int64) * 64
               + hashing.bloom_bit_np(keys).astype(np.int64))
        u, c = np.unique(idx, return_counts=True)
        self._bloom_cnt[u] -= np.minimum(self._bloom_cnt[u],
                                         c.astype(np.uint16))

    def _insert_new(self, keys, vals, vers):
        """Place NEW unique keys (not present anywhere)."""
        self.n_live += len(keys)
        self._bloom_add(keys)
        self._place(keys, vals, vers)

    def _place(self, keys, vals, vers):
        """Raw placement (no bloom/liveness accounting): two-choice with
        in-batch (bucket, slot) contention retries; leftovers spill."""
        for _ in range(4):
            if len(keys) == 0:
                return
            b1, b2 = hashing.bucket_pair_np(keys, self.nb)
            b1 = np.asarray(b1, np.int64)
            b2 = np.asarray(b2, np.int64)
            use_b = np.where((~self._used[b1]).any(axis=1), b1, b2)
            free = ~self._used[use_b]
            has = free.any(axis=1)
            slot = free.argmax(axis=1)
            lin = use_b * S + slot
            _, first = np.unique(lin, return_index=True)
            win = np.zeros(len(keys), bool)
            win[first] = True
            ok = has & win
            self._used[use_b[ok], slot[ok]] = True
            self._keys[use_b[ok], slot[ok]] = keys[ok]
            self._vals[use_b[ok], slot[ok]] = vals[ok]
            self._vers[use_b[ok], slot[ok]] = vers[ok]
            keys, vals, vers = keys[~ok], vals[~ok], vers[~ok]
        for k, v, r in zip(keys, vals, vers):
            self._spill[int(k)] = (np.array(v, np.uint32), int(r))
        if len(self._spill) > GROW_SPILL:
            self._grow()

    def _grow(self):
        """Double the table and re-place every live entry (same live set,
        so bloom counters and n_live are untouched)."""
        live_b, live_s = np.nonzero(self._used)
        keys = self._keys[live_b, live_s]
        vals = self._vals[live_b, live_s]
        vers = self._vers[live_b, live_s]
        spill = self._spill
        self._spill = {}
        self._alloc(self.nb * 2)
        self._place(keys, vals, vers)
        if spill:
            sk = np.fromiter(spill.keys(), np.uint64, len(spill))
            sv = np.stack([v for v, _ in spill.values()])
            sr = np.fromiter((r for _, r in spill.values()), np.uint32,
                             len(spill))
            self._place(sk, sv, sr)

    def _reserve(self, extra: int):
        if (self.n_live + extra) > int(self.nb * S * 0.6):
            need = (self.n_live + extra) * 2 // S
            while self.nb < need:
                self._grow()

    def upsert_batch(self, keys, vals, vers):
        """Install (create-or-overwrite) keys with given versions.
        Duplicate keys collapse last-wins (a double _insert_new would
        occupy two slots and desync n_live/bloom counters)."""
        keys = np.asarray(keys, np.uint64)
        vals = np.asarray(vals, np.uint32)
        vers = np.asarray(vers, np.uint32)
        if len(keys) == 0:
            return
        _, ridx = np.unique(keys[::-1], return_index=True)
        if len(ridx) != len(keys):
            keep = len(keys) - 1 - ridx     # last occurrence of each key
            keys, vals, vers = keys[keep], vals[keep], vers[keep]
        self._reserve(len(keys))
        found, bkt, slot = self._find(keys)
        self._vals[bkt[found], slot[found]] = vals[found]
        self._vers[bkt[found], slot[found]] = vers[found]
        miss = ~found
        if miss.any() and self._spill:
            for i in np.nonzero(miss)[0]:
                k = int(keys[i])
                if k in self._spill:
                    self._spill[k] = (np.array(vals[i], np.uint32),
                                      int(vers[i]))
                    miss[i] = False
        if miss.any():
            self._insert_new(keys[miss], vals[miss], vers[miss])

    def delete_batch(self, keys):
        """Remove keys; returns found-mask (absent keys are no-ops).
        Duplicates collapse (double-clearing would over-decrement
        n_live/bloom counters)."""
        keys = np.asarray(keys, np.uint64)
        _, ridx = np.unique(keys[::-1], return_index=True)
        if len(ridx) != len(keys):
            dedup = np.zeros(len(keys), bool)
            dedup[len(keys) - 1 - ridx] = True
            out = np.zeros(len(keys), bool)
            sub = self.delete_batch(keys[dedup])
            out[np.nonzero(dedup)[0]] = sub
            # one lane per key carries the outcome; dup lanes read False
            return out
        found, bkt, slot = self._find(keys)
        self._used[bkt[found], slot[found]] = False
        gone = found.copy()
        if self._spill:
            for i in np.nonzero(~found)[0]:
                if self._spill.pop(int(keys[i]), None) is not None:
                    gone[i] = True
        self._bloom_sub(keys[gone])
        self.n_live -= int(gone.sum())
        return gone

    # ------------------------------------------------- protocol interfaces

    def populate(self, keys, vals, vers=None):
        keys = np.asarray(keys, np.uint64)
        vers = np.asarray(vers if vers is not None else np.ones(len(keys)),
                          np.uint32)
        self.upsert_batch(keys, np.asarray(vals, np.uint32), vers)

    def writeback_batch(self, keys, vals, vers):
        """Apply evicted dirty records (ext_message ver1==1 protocol)."""
        self.upsert_batch(keys, vals, vers)

    def bloom_words(self, cache_buckets) -> np.ndarray:
        """Exact bloom word per cache bucket from the liveness counters."""
        b = np.asarray(cache_buckets, np.int64)
        bits = self._bloom_cnt.reshape(-1, 64)[b] > 0       # [m, 64]
        weights = np.uint64(1) << np.arange(64, dtype=np.uint64)
        return (bits.astype(np.uint64) * weights).sum(axis=1,
                                                      dtype=np.uint64)

    def _live_keys(self) -> np.ndarray:
        """All live keys, ascending — the host-side ordered view (round-20
        dintscan). O(table) per call; scans through the cache tier are a
        deferral path, not the bandwidth-bound fast path (that is the
        authoritative store's OrderedRun)."""
        ks = self._keys[self._used].astype(np.uint64)
        if self._spill:
            ks = np.r_[ks, np.fromiter(self._spill.keys(), np.uint64,
                                       len(self._spill))]
        return np.sort(ks)

    def scan_batch(self, starts, lens, scan_max: int):
        """Range scans against current state: per lane, the first
        min(lens[i], scan_max) live keys >= starts[i] in key order.
        Returns a per-lane list of (key, val tuple, ver) rows — the
        oracle's row format (testing/oracle.StoreOracle.scan)."""
        live = self._live_keys()
        out = []
        for s, want in zip(np.asarray(starts, np.uint64),
                           np.asarray(lens, np.int64)):
            k = max(0, min(int(want), scan_max))
            i = np.searchsorted(live, s, side="left")
            ks = live[i:i + k]
            _, vals, vers = self.lookup(ks)
            out.append([(int(kk), tuple(int(x) for x in v), int(r))
                        for kk, v, r in zip(ks, vals, vers)])
        return out

    def resolve_batch(self, ops, keys, vals, scan_lens=None,
                      scan_max: int = 0):
        """Serve the deferred lanes of one batch with the engine's
        serialization contract (engines/store.py header): per key, GETs see
        pre-batch state, then writes apply in lane order with monotonic
        versions. Deferral is whole-segment, so every lane of a deferred key
        is here — semantics compose exactly with the cache's local segments.

        Op.SCAN lanes (always deferred by the cache — see
        store_cache.cache_step) resolve here too when ``scan_max`` > 0:
        they sit in phase 1 with the GETs (pre-batch state), rtype VAL
        with the row count in ver, and the return grows a 4th element —
        the per-lane row lists of scan_batch.

        Returns (rtype [m], val [m, VW], ver [m][, scans])."""
        ops = np.asarray(ops, np.int32)
        keys = np.asarray(keys, np.uint64)
        vals = np.asarray(vals, np.uint32)
        m = len(ops)
        rtype = np.zeros(m, np.int32)
        rver = np.zeros(m, np.uint32)
        rval = np.zeros((m, self.vw), np.uint32)
        scans: list[list] = [[] for _ in range(m)]

        # GET/SCAN phase: pre-batch state, fully vectorized
        gi = np.nonzero(ops == Op.GET)[0]
        if len(gi):
            found, gv, gr = self.lookup(keys[gi])
            rtype[gi] = np.where(found, Reply.VAL, Reply.NOT_EXIST)
            rval[gi[found]] = gv[found]
            rver[gi] = np.where(found, gr, 0)
        if scan_max > 0:
            si = np.nonzero(ops == Op.SCAN)[0]
            if len(si):
                lens = (np.asarray(scan_lens)[si]
                        if scan_lens is not None else np.zeros(len(si)))
                rows = self.scan_batch(keys[si], lens, scan_max)
                for i, rws in zip(si, rows):
                    scans[i] = rws
                rtype[si] = Reply.VAL
                rver[si] = np.array([len(r) for r in rows], np.uint32)

        def _done():
            return (rtype, rval, rver, scans) if scan_max > 0 \
                else (rtype, rval, rver)

        is_w = (ops == Op.SET) | (ops == Op.INSERT) | (ops == Op.DELETE)
        wi = np.nonzero(is_w)[0]
        if len(wi) == 0:
            return _done()
        order = np.argsort(keys[wi], kind="stable")
        sw = wi[order]                       # lanes in (key, arrival) order
        sk = keys[sw]
        head = np.r_[True, sk[1:] != sk[:-1]]
        seg = np.cumsum(head) - 1
        has_del = np.zeros(seg[-1] + 1, bool)
        np.logical_or.at(has_del, seg, ops[sw] == Op.DELETE)
        simple = ~has_del[seg]               # per sorted lane

        if simple.any():
            # SET/INSERT-only keys: ver = pre-ver + arrival rank + 1,
            # last lane's value installs
            pos = np.arange(len(sk))
            head_pos = np.maximum.accumulate(np.where(head, pos, 0))
            rank = pos - head_pos
            hmask = head & simple
            _, _, base = self.lookup(sk[hmask])
            base_per_seg = np.zeros(seg[-1] + 1, np.int64)
            base_per_seg[seg[hmask]] = base
            lane_ver = (base_per_seg[seg] + rank + 1)[simple]
            li = sw[simple]
            rtype[li] = Reply.ACK
            rver[li] = lane_ver.astype(np.uint32)
            last = np.r_[head[1:], True] & simple
            self.upsert_batch(sk[last], vals[sw[last]],
                              (base_per_seg[seg] + rank + 1)[last])

        if has_del.any():
            # delete-containing key groups: ordered scalar walk (rare)
            for li in np.nonzero(~simple)[0]:
                i = sw[li]
                k = keys[i:i + 1]
                if head[li]:
                    _, _, v0 = self.lookup(k)
                    base, cnt = int(v0[0]), 0
                if ops[i] in (Op.SET, Op.INSERT):
                    cnt += 1
                    self.upsert_batch(k, vals[i][None],
                                      np.array([base + cnt], np.uint32))
                    rtype[i] = Reply.ACK
                    rver[i] = base + cnt
                else:
                    gone = self.delete_batch(k)
                    rtype[i] = Reply.ACK if gone[0] else Reply.NOT_EXIST
        return _done()


@dataclasses.dataclass
class CacheStats:
    served: int = 0
    hits: int = 0          # lanes answered by the device cache
    misses: int = 0        # lanes deferred to the host
    bloom_negatives: int = 0
    writebacks: int = 0    # evicted dirty records applied


class CachedStore:
    """Two-tier store server: device cache + host KVS + refill loop."""

    def __init__(self, cache_buckets: int, val_words: int = 10,
                 slots: int = 4, policy: str = store_cache.WB_BLOOM,
                 width: int = 4096, hot_keys: int = 0,
                 use_pallas: bool = False):
        """``hot_keys`` > 0 attaches the dintcache mirror for key ids
        [0, hot_keys) inside the device cache (store_cache.CacheTable);
        ``use_pallas`` serves its partition with the VMEM hot kernels."""
        self.cache = store_cache.create(cache_buckets, slots, val_words,
                                        hot_keys=hot_keys)
        self.kvs = HostKVS(cache_buckets, val_words)
        self.policy = policy
        self.vw = val_words
        self.width = width
        self.stats = CacheStats()
        self._step = jax.jit(
            lambda c, b: store_cache.cache_step(c, b, policy=policy,
                                                use_pallas=use_pallas),
            donate_argnums=0)
        self._refill = jax.jit(store_cache.refill, donate_argnums=0)
        self._pending: dict[int, bool] = {}    # refill keys (bloom-only if False)

    def populate(self, keys, vals, vers=None):
        """Load the backing store AND prime the device bloom words — the
        reference's equivalent state arises from populate-over-network, where
        every install travels the TC path and sets its bloom bit
        (store/ebpf/store_kern.c:302-372). A zeroed bloom would wrongly
        short-circuit GETs for populated-but-uncached keys to NOT_EXIST."""
        import jax.numpy as jnp

        self.kvs.populate(keys, vals, vers)
        keys = np.asarray(keys, np.uint64)
        nb = self.cache.kv.n_buckets
        bkt = hashing.bucket_np(keys, nb)
        bits = hashing.bloom_bit_np(keys)
        bloom = np.zeros(nb, np.uint64)
        np.bitwise_or.at(bloom, bkt, np.uint64(1) << bits.astype(np.uint64))
        t = self.cache.kv
        self.cache = self.cache.replace(kv=t.replace(
            bloom_hi=jnp.asarray((bloom >> np.uint64(32)).astype(np.uint32)),
            bloom_lo=jnp.asarray(bloom.astype(np.uint32))))

    def _writeback_records(self, rec, mask):
        """Apply flushed/evicted dirty records to the backing store."""
        kh = np.asarray(rec["key_hi"])[mask].astype(np.uint64)
        kl = np.asarray(rec["key_lo"])[mask].astype(np.uint64)
        self.kvs.writeback_batch((kh << np.uint64(32)) | kl,
                                 np.asarray(rec["val"])[mask],
                                 np.asarray(rec["ver"])[mask])
        self.stats.writebacks += int(mask.sum())

    def serve(self, ops, keys, vals=None, scan_lens=None,
              scan_max: int = 0):
        """One server round: refill -> device step -> host fallback.

        Op.SCAN lanes always count as misses (the device cache holds an
        unordered working-set subset; cache_step defers them wholesale)
        and resolve host-side in resolve_batch's phase 1. With
        ``scan_max`` > 0 the return grows a 4th element: per-lane scan
        row lists (empty on non-scan lanes).

        Returns (rtype [n], val [n, VW], ver [n][, scans]) numpy arrays.
        """
        n = len(ops)
        ops = np.asarray(ops, np.int32)
        keys = np.asarray(keys, np.uint64)
        scans: list[list] = [[] for _ in range(n)]
        if vals is None:
            vals = np.zeros((n, self.vw), np.uint32)

        self._do_refills()
        if scan_max > 0 and (ops == Op.SCAN).any():
            # scan barrier: the host resolves scans against ITS view, so
            # every dirty cached record (a write the backing store has
            # not seen) must land first — else a range row is stale.
            # Point deferrals don't need this (whole-segment deferral
            # flushes the segment's own dirty copy); ranges cross keys.
            self._flush_dirty()
        batch = make_batch(ops, keys, vals, width=self.width,
                           val_words=self.vw)
        self.cache, replies, miss, flush = self._step(self.cache, batch)
        rtype = np.asarray(replies.rtype)[:n].copy()
        rval = np.asarray(replies.val)[:n].copy()
        rver = np.asarray(replies.ver)[:n].copy()
        miss = np.asarray(miss)[:n]

        # dirty cached copies of deferred segments MUST land in the backing
        # store before their lanes are resolved (see cache_step docstring)
        f_mask = np.asarray(flush["mask"])
        if f_mask.any():
            self._writeback_records(flush, f_mask)

        st = self.stats
        st.served += n
        st.misses += int(miss.sum())
        st.hits += int((~miss & (ops != Op.NOP)).sum())
        st.bloom_negatives += int((rtype[~miss] == Reply.NOT_EXIST).sum())

        # host fallback: resolve the deferred lanes as one sub-batch
        mi = np.nonzero(miss)[0]
        if len(mi):
            out = self.kvs.resolve_batch(
                ops[mi], keys[mi], np.asarray(vals)[mi],
                scan_lens=(np.asarray(scan_lens)[mi]
                           if scan_lens is not None else None),
                scan_max=scan_max)
            rt, rv, rr = out[:3]
            rtype[mi], rver[mi] = rt, rr
            rval[mi] = rv
            if scan_max > 0:
                for i, rws in zip(mi, out[3]):
                    scans[i] = rws
            # queue refills: full record for present keys, bloom-only after
            # DELETE / for absent keys (keeps negatives exact)
            # scan starts are range predicates, not cacheable point keys
            pt = mi[ops[mi] != Op.SCAN]
            for k, p in zip(keys[pt], self.kvs.contains(keys[pt])):
                self._pending[int(k)] = bool(p)
        if scan_max > 0:
            return rtype, rval, rver, scans
        return rtype, rval, rver

    def _flush_dirty(self):
        """Write back EVERY dirty cached record (scan barrier): after
        this the backing store's ordered view covers all committed
        writes; the cached copies stay resident, now clean."""
        from ..ops import u64
        c = self.cache
        t = c.kv
        live = np.asarray(c.dirty) & np.asarray(t.valid)
        e = np.nonzero(live)[0]
        if len(e) == 0:
            return
        keys = u64.join(np.asarray(t.key_hi)[e], np.asarray(t.key_lo)[e])
        vals = np.asarray(t.val).reshape(-1, t.val_words)[e]
        vers = np.asarray(t.ver)[e]
        self.kvs.writeback_batch(keys, vals, vers)
        self.stats.writebacks += len(e)
        self.cache = c.replace(dirty=jax.numpy.zeros_like(c.dirty))

    def _do_refills(self):
        if not self._pending:
            return
        items = list(self._pending.items())[: self.width]
        for k, _ in items:
            del self._pending[k]
        key = np.array([k for k, _ in items], np.uint64)
        present = np.array([p for _, p in items], bool)

        # dedup per bucket: refill installs at most one record per bucket per
        # call; re-queue the rest
        bkt = hashing.bucket_np(key, self.cache.kv.n_buckets)
        order = np.argsort(bkt, kind="stable")
        first = np.zeros(len(key), bool)
        ob = bkt[order]
        first[order] = np.r_[True, ob[1:] != ob[:-1]]
        for j in np.nonzero(~first)[0]:
            self._pending[int(key[j])] = bool(present[j])
        key, present, bkt = key[first], present[first], bkt[first]
        r = len(key)

        val = np.zeros((r, self.vw), np.uint32)
        ver = np.zeros(r, np.uint32)
        found, lv, lr = self.kvs.lookup(key)
        take = found & present
        val[take] = lv[take]
        ver[take] = lr[take]
        bloom = self.kvs.bloom_words(bkt)

        pad = self.width - r
        key_hi = (key >> np.uint64(32)).astype(np.uint32)
        key_lo = key.astype(np.uint32)
        b_hi = (bloom >> np.uint64(32)).astype(np.uint32)
        b_lo = bloom.astype(np.uint32)

        def p(x, fill=0):
            return np.concatenate([x, np.full((pad,) + x.shape[1:], fill,
                                              x.dtype)])

        mask = p(np.ones(r, bool), False)
        self.cache, ev = self._refill(
            self.cache, p(key_hi), p(key_lo), p(val), p(ver),
            p(b_hi), p(b_lo), mask)
        ev_mask = np.asarray(ev["mask"])
        if ev_mask.any():
            self._writeback_records(ev, ev_mask)
