"""Ordered run: dense key-sorted snapshot of the store + delta overlay.

The reference serves every request packet-at-a-time through per-key hash
probes (store/ebpf/store_kern.c), so a range scan costs one random probe
per key — the one access pattern where the HBM-resident table should win
by an order of magnitude, because a scan over a sorted layout is a single
sequential DMA at memory bandwidth (DINT NSDI'24 leaves scans to the
userspace KVS; YCSB-E is the canonical workload). The `OrderedRun` is the
scan-serving companion of `tables.kv.KVTable`:

  * **run** — a dense key-sorted snapshot of the table's live records,
    struct-of-arrays and FLAT like the table itself (key_hi/key_lo/ver
    u32 [cap], val u32 [cap*VW] interleaved); rows past `n` keep the
    reserved PAD key 0xFFFFFFFF:FFFFFFFF so binary search needs no
    bounds plumbing. Contiguous key-adjacent rows are what turns a scan
    into a sequential DMA (ops/pallas_gather.scan_rows).
  * **delta overlay** — a small key-sorted write-through buffer fed by
    `store.step`'s installs/deletes (upserts + tombstones, at most one
    entry per key, latest write wins). Scans merge run ∪ delta so the
    run snapshot never has to be rebuilt inside a step.
  * **rebuild** — `rebuild_run` merge-compacts run ∪ delta back into a
    dense sorted run in one batched on-device pass (two stable
    `lax.sort`s + gathers, no scatters), invoked at serve drain
    boundaries (serve/engine.py) so the run stays sorted without ever
    stalling the step. If the overlay ever overflowed (`stale`),
    `refresh` falls back to `from_table` — the overlay is best-effort
    capacity, never best-effort correctness: a stale run answers no
    scans (store.step replies RETRY) until rebuilt.

Sizing rule: a scan of `scan_max` rows gathers `scan_max + delta_cap`
contiguous run rows. Each overlay tombstone can shadow at most one run
row in the scanned range, so the overshoot window always covers the
first `scan_max` live keys of the merged view — the static price of
answering scans between rebuilds without dynamic shapes.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from ..ops.u64 import U32
from . import kv

I32 = jnp.int32

# reserved pad key (both words): matches engines/types.PAD_KEY's u64 form
PAD_W = 0xFFFFFFFF


@flax.struct.dataclass
class OrderedRun:
    # dense sorted snapshot (rows >= n hold the PAD key, zero ver/val)
    key_hi: jax.Array     # u32 [cap]
    key_lo: jax.Array     # u32 [cap]
    ver: jax.Array        # u32 [cap]
    val: jax.Array        # u32 [cap*VW] interleaved
    n: jax.Array          # i32 [] live rows
    # key-sorted delta overlay (rows >= d_n hold the PAD key)
    d_key_hi: jax.Array   # u32 [dcap]
    d_key_lo: jax.Array   # u32 [dcap]
    d_ver: jax.Array      # u32 [dcap]
    d_val: jax.Array      # u32 [dcap*VW]
    d_tomb: jax.Array     # bool [dcap] — True: key deleted since snapshot
    d_seq: jax.Array      # u32 [dcap] — arrival stamp (latest wins)
    d_n: jax.Array        # i32 [] live overlay entries
    d_seq_next: jax.Array  # u32 [] next arrival stamp
    stale: jax.Array      # bool [] — overlay overflowed since last rebuild
    delta_cap: int = flax.struct.field(pytree_node=False, default=64)
    val_words: int = flax.struct.field(pytree_node=False, default=10)

    @property
    def cap(self):
        return self.key_hi.shape[0]


def create(cap: int, delta_cap: int = 64, val_words: int = 10) -> OrderedRun:
    assert cap >= 1 and delta_cap >= 1
    return OrderedRun(
        key_hi=jnp.full((cap,), PAD_W, U32),
        key_lo=jnp.full((cap,), PAD_W, U32),
        ver=jnp.zeros((cap,), U32),
        val=jnp.zeros((cap * val_words,), U32),
        n=I32(0),
        d_key_hi=jnp.full((delta_cap,), PAD_W, U32),
        d_key_lo=jnp.full((delta_cap,), PAD_W, U32),
        d_ver=jnp.zeros((delta_cap,), U32),
        d_val=jnp.zeros((delta_cap * val_words,), U32),
        d_tomb=jnp.zeros((delta_cap,), bool),
        d_seq=jnp.zeros((delta_cap,), U32),
        d_n=I32(0),
        d_seq_next=jnp.zeros((), U32),
        stale=jnp.zeros((), bool),
        delta_cap=delta_cap, val_words=val_words,
    )


def _word_idx(idx, vw: int):
    """Flat val word indices for row indices `idx` (any shape)."""
    return idx[..., None] * vw + jnp.arange(vw, dtype=I32)


def _compact(keys_hi, keys_lo, ver, val_rows, live, cap_out: int, vw: int):
    """Stable-compact `live` rows (already key-sorted) to the front of a
    cap_out-sized run layout: dead rows become PAD/zero so binary search
    sees one sorted array. Pure gathers — no scatters."""
    m = keys_hi.shape[0]
    iota = jnp.arange(m, dtype=I32)
    dead = (~live).astype(U32)
    _, perm = jax.lax.sort((dead, iota), num_keys=1)   # stable: keeps order
    take = perm[:cap_out]
    rank = jnp.arange(cap_out, dtype=I32)
    n_live = jnp.sum(live.astype(I32))
    ok = rank < n_live
    out_hi = jnp.where(ok, keys_hi[take], U32(PAD_W))
    out_lo = jnp.where(ok, keys_lo[take], U32(PAD_W))
    out_ver = jnp.where(ok, ver[take], U32(0))
    out_val = jnp.where(ok[:, None], val_rows[take], U32(0)).reshape(-1)
    return out_hi, out_lo, out_ver, out_val, n_live


def from_table(table: kv.KVTable, delta_cap: int = 64) -> OrderedRun:
    """Fresh snapshot: sort the table's live entries into a dense run
    (cap = the table's entry count, so the run can never overflow).
    Jittable — the serve plane calls this at drain boundaries when the
    overlay went stale."""
    ne = table.key_hi.shape[0]
    vw = table.val_words
    iota = jnp.arange(ne, dtype=I32)
    hi = jnp.where(table.valid, table.key_hi, U32(PAD_W))
    lo = jnp.where(table.valid, table.key_lo, U32(PAD_W))
    _, _, perm = jax.lax.sort((hi, lo, iota), num_keys=2)
    s_valid = table.valid[perm]
    out = _compact(hi[perm], lo[perm], table.ver[perm],
                   table.val.reshape(-1, vw)[perm], s_valid, ne, vw)
    run = create(ne, delta_cap, vw)
    return run.replace(key_hi=out[0], key_lo=out[1], ver=out[2],
                       val=out[3], n=out[4])


def rebuild_run(run: OrderedRun) -> OrderedRun:
    """Batched on-device merge-compact: fold the delta overlay into the
    run (upserts replace/insert rows, tombstones remove them) and clear
    the overlay. Two stable sorts + gathers over cap + delta_cap rows —
    the drain-boundary cost of keeping the run sorted without stalling
    the step. A stale run (overflowed overlay) cannot be repaired from
    the overlay; use `refresh`."""
    cap, dcap, vw = run.cap, run.delta_cap, run.val_words
    d_live = jnp.arange(dcap, dtype=I32) < run.d_n
    hi = jnp.concatenate([jnp.where(d_live, run.d_key_hi, U32(PAD_W)),
                          run.key_hi])
    lo = jnp.concatenate([jnp.where(d_live, run.d_key_lo, U32(PAD_W)),
                          run.key_lo])
    # delta rows sort BEFORE the run row of the same key (pref 0 < 1), so
    # the head of each key group is the overlay's latest word on that key
    pref = jnp.concatenate([jnp.zeros((dcap,), U32), jnp.ones((cap,), U32)])
    iota = jnp.arange(dcap + cap, dtype=I32)
    s_hi, s_lo, _, perm = jax.lax.sort((hi, lo, pref, iota), num_keys=3)
    head = jnp.concatenate([jnp.ones((1,), bool),
                            (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1])])
    valid = (s_hi != U32(PAD_W)) | (s_lo != U32(PAD_W))
    tomb = jnp.concatenate([run.d_tomb, jnp.zeros((cap,), bool)])[perm]
    live = head & valid & ~tomb
    ver = jnp.concatenate([run.d_ver, run.ver])[perm]
    val_rows = jnp.concatenate(
        [run.d_val.reshape(-1, vw), run.val.reshape(-1, vw)])[perm]
    out = _compact(s_hi, s_lo, ver, val_rows, live, cap, vw)
    fresh = create(cap, dcap, vw)
    return fresh.replace(key_hi=out[0], key_lo=out[1], ver=out[2],
                         val=out[3], n=jnp.minimum(out[4], I32(cap)))


def refresh(table: kv.KVTable, run: OrderedRun) -> OrderedRun:
    """The drain-boundary entry point: merge-compact when the overlay is
    intact, full re-snapshot from the authoritative table when it went
    stale. Both branches produce identical runs on an intact overlay
    (pinned in tests/test_run.py) — `stale` only ever trades compute."""
    assert run.cap == table.key_hi.shape[0], \
        "refresh expects a from_table-sized run"
    return jax.lax.cond(run.stale,
                        lambda: from_table(table, run.delta_cap),
                        lambda: rebuild_run(run))


def delta_append(run: OrderedRun, key_hi, key_lo, ver, val, tomb,
                 mask) -> OrderedRun:
    """Write-through append of one batch's effective writes (store.step's
    post-spill-fixup writer lanes: at most one per key). Re-sorts the
    overlay by key with latest-wins dedupe — the overlay invariant every
    scan's merge relies on. Overflow beyond delta_cap sets `stale`
    (dropped entries would otherwise silently vanish from scans); the
    run serves no scans until `refresh`.

    val arrives flat [r*VW] (interleaved, like the table's install
    operand)."""
    dcap, vw = run.delta_cap, run.val_words
    r = key_hi.shape[0]
    d_live = jnp.arange(dcap, dtype=I32) < run.d_n
    hi = jnp.concatenate([jnp.where(d_live, run.d_key_hi, U32(PAD_W)),
                          jnp.where(mask, key_hi.astype(U32), U32(PAD_W))])
    lo = jnp.concatenate([jnp.where(d_live, run.d_key_lo, U32(PAD_W)),
                          jnp.where(mask, key_lo.astype(U32), U32(PAD_W))])
    seq = jnp.concatenate([run.d_seq,
                           jnp.full((r,), 1, U32) * run.d_seq_next])
    # latest wins: sort by (key, ~seq) so the newest stamp heads its group
    iota = jnp.arange(dcap + r, dtype=I32)
    s_hi, s_lo, _, perm = jax.lax.sort((hi, lo, ~seq, iota), num_keys=3)
    head = jnp.concatenate([jnp.ones((1,), bool),
                            (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1])])
    valid = (s_hi != U32(PAD_W)) | (s_lo != U32(PAD_W))
    live = head & valid
    ver_c = jnp.concatenate([run.d_ver, ver.astype(U32)])[perm]
    tomb_c = jnp.concatenate([run.d_tomb, tomb])[perm]
    seq_c = seq[perm]
    val_rows = jnp.concatenate([run.d_val.reshape(-1, vw),
                                val.reshape(-1, vw)])[perm]
    out = _compact(s_hi, s_lo, ver_c, val_rows, live, dcap, vw)
    n_live = out[4]
    # _compact zeroes ver on dead rows; redo tomb/seq with the same perm
    dead = (~live).astype(U32)
    _, perm2 = jax.lax.sort((dead, jnp.arange(dcap + r, dtype=I32)),
                            num_keys=1)
    take = perm2[:dcap]
    ok = jnp.arange(dcap, dtype=I32) < n_live
    return run.replace(
        d_key_hi=out[0], d_key_lo=out[1], d_ver=out[2], d_val=out[3],
        d_tomb=jnp.where(ok, tomb_c[take], False),
        d_seq=jnp.where(ok, seq_c[take], U32(0)),
        d_n=jnp.minimum(n_live, I32(dcap)),
        d_seq_next=run.d_seq_next + U32(1),
        stale=run.stale | (n_live > dcap),
    )


def locate_bits(cap: int) -> int:
    """Binary-search depth over a cap-row run (geometry var `lg` in the
    dint.store.scan_locate wave formula)."""
    return max(1, int(cap).bit_length())


def locate(run: OrderedRun, q_hi, q_lo):
    """Lower bound: per lane, the first run offset whose key is >= the
    lane's start key. Branchless meta binary search — `locate_bits(cap)`
    rounds of two u32 point gathers per lane; rows past `n` hold the PAD
    key (the largest key), so no bounds vector rides along."""
    cap = run.cap
    pos = jnp.zeros(q_hi.shape, I32)
    for b in reversed(range(locate_bits(cap))):
        cand = pos + I32(1 << b)
        safe = jnp.minimum(cand, I32(cap)) - 1
        kh = run.key_hi[safe]
        kl = run.key_lo[safe]
        less = (kh < q_hi) | ((kh == q_hi) & (kl < q_lo))
        pos = jnp.where((cand <= cap) & less, cand, pos)
    return pos


def merge_scan(run: OrderedRun, slab_hi, slab_lo, slab_ver, slab_val,
               win_base, q_hi, q_lo, slen, scan_max: int):
    """Merge a gathered run window with the delta overlay into per-lane
    scan replies: the first `slen` live keys >= the start key of the
    merged (run ∪ delta) view.

    slab_* : [r, LG(, vw)] contiguous run rows starting at win_base (the
    clamped locate offset; LG = scan_max + delta_cap). Returns
    (count [r], hi/lo/ver [r, scan_max], val [r, scan_max, vw],
    delta_hits [r]); reply rows past count are zeroed."""
    vw = run.val_words
    dcap = run.delta_cap
    r, lg = slab_hi.shape
    d_live = jnp.arange(dcap, dtype=I32) < run.d_n

    # run rows shadowed by ANY overlay entry for the same key (upsert
    # replaces, tombstone removes); the overlay is tiny, so the flat
    # [r, LG, dcap] compare beats a second search pass
    sh = (d_live[None, None, :]
          & (slab_hi[:, :, None] == run.d_key_hi[None, None, :])
          & (slab_lo[:, :, None] == run.d_key_lo[None, None, :])).any(-1)
    row_idx = win_base[:, None] + jnp.arange(lg, dtype=I32)[None, :]
    run_ok = (row_idx < run.n) & ~sh & _ge(slab_hi, slab_lo, q_hi, q_lo)

    d_hi = jnp.broadcast_to(run.d_key_hi[None, :], (r, dcap))
    d_lo = jnp.broadcast_to(run.d_key_lo[None, :], (r, dcap))
    d_ok = (d_live[None, :] & ~run.d_tomb[None, :]
            & _ge(d_hi, d_lo, q_hi, q_lo))

    c_hi = jnp.concatenate([slab_hi, d_hi], axis=1)
    c_lo = jnp.concatenate([slab_lo, d_lo], axis=1)
    c_ok = jnp.concatenate([run_ok, d_ok], axis=1)
    c_delta = jnp.concatenate([jnp.zeros((r, lg), bool),
                               jnp.ones((r, dcap), bool)], axis=1)
    iota = jnp.broadcast_to(jnp.arange(lg + dcap, dtype=I32)[None, :],
                            (r, lg + dcap))
    bad = (~c_ok).astype(U32)
    s_bad, _, _, perm = jax.lax.sort(
        (bad, c_hi, c_lo, iota), num_keys=3, dimension=1)
    take = perm[:, :scan_max]
    lane = jnp.arange(r, dtype=I32)[:, None]
    n_ok = jnp.sum(c_ok.astype(I32), axis=1)
    count = jnp.minimum(slen.astype(I32), n_ok)
    keep = jnp.arange(scan_max, dtype=I32)[None, :] < count[:, None]

    out_hi = jnp.where(keep, c_hi[lane, take], U32(0))
    out_lo = jnp.where(keep, c_lo[lane, take], U32(0))
    c_ver = jnp.concatenate([slab_ver, jnp.broadcast_to(
        run.d_ver[None, :], (r, dcap))], axis=1)
    c_val = jnp.concatenate([slab_val, jnp.broadcast_to(
        run.d_val.reshape(1, dcap, vw), (r, dcap, vw))], axis=1)
    out_ver = jnp.where(keep, c_ver[lane, take], U32(0))
    out_val = jnp.where(keep[:, :, None], c_val[lane, take], U32(0))
    delta_hits = jnp.sum((keep & c_delta[lane, take]).astype(I32), axis=1)
    return count, out_hi, out_lo, out_ver, out_val, delta_hits


def _ge(hi, lo, q_hi, q_lo):
    qh = q_hi if hi.ndim == q_hi.ndim else q_hi[:, None]
    ql = q_lo if lo.ndim == q_lo.ndim else q_lo[:, None]
    return (hi > qh) | ((hi == qh) & (lo >= ql))


# ------------------------------------------------------------- host side


def to_items(run: OrderedRun):
    """Host-side merged view {key: (val tuple, ver)} — the oracle's
    vocabulary (testing/oracle.py), for differential tests."""
    import numpy as np
    vw = run.val_words
    out = {}
    n = int(run.n)
    hi = np.asarray(run.key_hi)[:n].astype(np.uint64)
    lo = np.asarray(run.key_lo)[:n].astype(np.uint64)
    ver = np.asarray(run.ver)[:n]
    val = np.asarray(run.val).reshape(-1, vw)[:n]
    for i in range(n):
        out[int((hi[i] << 32) | lo[i])] = (
            tuple(int(x) for x in val[i]), int(ver[i]))
    dn = int(run.d_n)
    d_hi = np.asarray(run.d_key_hi)[:dn].astype(np.uint64)
    d_lo = np.asarray(run.d_key_lo)[:dn].astype(np.uint64)
    d_ver = np.asarray(run.d_ver)[:dn]
    d_val = np.asarray(run.d_val).reshape(-1, vw)[:dn]
    d_tomb = np.asarray(run.d_tomb)[:dn]
    for i in range(dn):
        k = int((d_hi[i] << 32) | d_lo[i])
        if d_tomb[i]:
            out.pop(k, None)
        else:
            out[k] = (tuple(int(x) for x in d_val[i]), int(d_ver[i]))
    return out
