"""Lock and version tables.

TPU re-expression of the reference's lock arrays:
  - 2PL no-wait S/X counters `struct lock_unit {lock, num_sh, num_ex}`
    (lock_2pl/ebpf/utils.h; smallbank/ebpf/shard_kern.c:26-38)
  - FaSST OCC single lock word + version table
    (lock_fasst/ebpf/ls_kern.c; tatp/ebpf/shard_kern.c:26-59)

Keys map to lock slots via hash, exactly like the reference
(fasthash64(key) % kLockHashSize, lock_2pl/caladan/proto.h:8) — hash
collisions conflate locks, which is accepted behavior there and here.
The reference's per-unit CAS spinlock (`lock` field) has no TPU equivalent:
batch certification makes each step's grants deterministic.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from ..ops import hashing

I32 = jnp.int32
U32 = jnp.uint32


@flax.struct.dataclass
class SXLockTable:
    """No-wait 2PL shared/exclusive counters, one unit per hash slot."""
    num_sh: jax.Array   # i32 [NL]
    num_ex: jax.Array   # i32 [NL]

    @property
    def n_slots(self):
        return self.num_sh.shape[0]


def create_sx(n_slots: int) -> SXLockTable:
    assert n_slots & (n_slots - 1) == 0
    return SXLockTable(num_sh=jnp.zeros((n_slots,), I32),
                       num_ex=jnp.zeros((n_slots,), I32))


@flax.struct.dataclass
class OCCTable:
    """FaSST-style OCC state: lock bit + record version per hash slot."""
    locked: jax.Array   # bool [NL]
    ver: jax.Array      # u32 [NL]

    @property
    def n_slots(self):
        return self.locked.shape[0]


def create_occ(n_slots: int) -> OCCTable:
    assert n_slots & (n_slots - 1) == 0
    return OCCTable(locked=jnp.zeros((n_slots,), bool),
                    ver=jnp.zeros((n_slots,), U32))


@flax.struct.dataclass
class OCCAttrTable:
    """OCC lock word + the HOLDER'S KEY, so rejects can distinguish a true
    same-key conflict from hash-slot sharing — the reference's
    `struct txn_lock {lock_bit, key}` (tatp/ebpf/lock_kern.c:12-16)."""
    locked: jax.Array    # bool [NL]
    ver: jax.Array       # u32 [NL]
    owner_hi: jax.Array  # u32 [NL]
    owner_lo: jax.Array  # u32 [NL]

    @property
    def n_slots(self):
        return self.locked.shape[0]


def create_occ_attr(n_slots: int) -> OCCAttrTable:
    assert n_slots & (n_slots - 1) == 0
    return OCCAttrTable(locked=jnp.zeros((n_slots,), bool),
                        ver=jnp.zeros((n_slots,), U32),
                        owner_hi=jnp.zeros((n_slots,), U32),
                        owner_lo=jnp.zeros((n_slots,), U32))


def lock_slot(key_hi, key_lo, n_slots: int):
    """key -> lock-table slot (hash-sharded, collisions conflate)."""
    return hashing.bucket(key_hi, key_lo, n_slots)
