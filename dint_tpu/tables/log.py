"""Replication log: fixed-capacity multi-lane append-only rings.

TPU re-expression of the reference's per-CPU log rings
(`BPF_MAP_TYPE_PERCPU_ARRAY` of `struct log_entry {is_del, table, key, val,
ver}` + per-CPU counter, log_server/ebpf/ls_kern.c:26-38, append at :63-77;
userspace equivalents smallbank/udp/server_shard.cc:175-186).

Lanes replace CPUs: a batch's appends are distributed across L lanes, each
append gets slot = head[lane] + its arrival rank within the lane, and heads
advance by per-lane counts — all as one conflict-free scatter. Rings wrap,
exactly like the reference (ls_kern.c:72-73).

Entry layout (u32 words): [flags(is_del|table<<8), key_hi, key_lo, ver, val...]
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

I32 = jnp.int32
U32 = jnp.uint32

HDR_WORDS = 4


@flax.struct.dataclass
class LogRing:
    entries: jax.Array   # u32 [L, CAP, HDR_WORDS + VW]
    head: jax.Array      # u32 [L] (monotonic; slot = head % CAP)

    @property
    def lanes(self):
        return self.entries.shape[0]

    @property
    def capacity(self):
        return self.entries.shape[1]


def create(lanes: int, capacity: int, val_words: int = 10) -> LogRing:
    assert capacity & (capacity - 1) == 0
    return LogRing(entries=jnp.zeros((lanes, capacity, HDR_WORDS + val_words), U32),
                   head=jnp.zeros((lanes,), U32))


def append(ring: LogRing, do_append, table_id, is_del, key_hi, key_lo, ver, val):
    """Batched append. do_append: bool [R]; others [R]/[R, VW].

    Lane assignment is round-robin by lane index over the batch (the
    reference's per-CPU choice is likewise load-balancing, not semantic).
    Returns (ring', lane [R], slot [R]).
    """
    r = do_append.shape[0]
    lanes = ring.lanes
    cap = ring.capacity
    idx = jnp.arange(r, dtype=I32)
    lane = idx % lanes
    # rank of this request among appends in its lane (arrival order)
    one = do_append.astype(I32)
    # per-lane exclusive running count: segment by lane via scatter-free trick —
    # lane pattern is round-robin so lane l's appends are at positions l, l+L, ...
    # rank = (# of appends at positions j < i with j % L == l). Compute with a
    # cumulative sum per residue class using reshape (r must be multiple of L).
    pad = (-r) % lanes
    one_p = jnp.pad(one, (0, pad)).reshape(-1, lanes)            # [rows, L]
    excl = jnp.cumsum(one_p, axis=0) - one_p                     # [rows, L]
    rank = excl.reshape(-1)[:r]
    lane_counts = one_p.sum(axis=0).astype(U32)                  # [L]
    pos = ring.head[lane] + rank.astype(U32)
    slot = (pos % U32(cap)).astype(I32)

    flags = (is_del.astype(U32) | (table_id.astype(U32) << U32(8)))
    entry = jnp.concatenate(
        [flags[:, None], key_hi[:, None], key_lo[:, None], ver[:, None],
         val.astype(U32)], axis=1)
    safe_lane = jnp.where(do_append, lane, lanes)
    new_entries = ring.entries.at[safe_lane, slot].set(entry, mode="drop")
    new_head = ring.head + lane_counts
    return ring.replace(entries=new_entries, head=new_head), lane, slot
