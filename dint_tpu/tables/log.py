"""Replication log: fixed-capacity multi-lane append-only rings.

TPU re-expression of the reference's per-CPU log rings
(`BPF_MAP_TYPE_PERCPU_ARRAY` of `struct log_entry {is_del, table, key, val,
ver}` + per-CPU counter, log_server/ebpf/ls_kern.c:26-38, append at :63-77;
userspace equivalents smallbank/udp/server_shard.cc:175-186).

Lanes replace CPUs: a batch's appends are distributed across L lanes, each
append gets slot = head[lane] + its arrival rank within the lane, and heads
advance by per-lane counts — all as one conflict-free scatter. Rings wrap,
exactly like the reference (ls_kern.c:72-73).

Entry layout (u32 words): [flags(is_del|table<<8), key_hi, key_lo, ver, val...]
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

I32 = jnp.int32
U32 = jnp.uint32

HDR_WORDS = 4


@flax.struct.dataclass
class LogRing:
    entries: jax.Array   # u32 [L, CAP, HDR_WORDS + VW]
    head: jax.Array      # u32 [L] (monotonic; slot = head % CAP)

    @property
    def lanes(self):
        return self.entries.shape[0]

    @property
    def capacity(self):
        return self.entries.shape[1]


def create(lanes: int, capacity: int, val_words: int = 10) -> LogRing:
    assert capacity & (capacity - 1) == 0
    return LogRing(entries=jnp.zeros((lanes, capacity, HDR_WORDS + val_words), U32),
                   head=jnp.zeros((lanes,), U32))


def append(ring: LogRing, do_append, table_id, is_del, key_hi, key_lo, ver, val):
    """Batched append. do_append: bool [R]; others [R]/[R, VW].

    Lane assignment is round-robin by lane index over the batch (the
    reference's per-CPU choice is likewise load-balancing, not semantic).
    Returns (ring', lane [R], slot [R]).
    """
    r = do_append.shape[0]
    lanes = ring.lanes
    cap = ring.capacity
    idx = jnp.arange(r, dtype=I32)
    lane = idx % lanes
    # rank of this request among appends in its lane (arrival order)
    one = do_append.astype(I32)
    # per-lane exclusive running count: segment by lane via scatter-free trick —
    # lane pattern is round-robin so lane l's appends are at positions l, l+L, ...
    # rank = (# of appends at positions j < i with j % L == l). Compute with a
    # cumulative sum per residue class using reshape (r must be multiple of L).
    pad = (-r) % lanes
    one_p = jnp.pad(one, (0, pad)).reshape(-1, lanes)            # [rows, L]
    excl = jnp.cumsum(one_p, axis=0) - one_p                     # [rows, L]
    rank = excl.reshape(-1)[:r]
    lane_counts = one_p.sum(axis=0).astype(U32)                  # [L]
    pos = ring.head[lane] + rank.astype(U32)
    slot = (pos % U32(cap)).astype(I32)

    flags = (is_del.astype(U32) | (table_id.astype(U32) << U32(8)))
    entry = jnp.concatenate(
        [flags[:, None], key_hi[:, None], key_lo[:, None], ver[:, None],
         val.astype(U32)], axis=1)
    # one writer per (lane, slot): per-lane ranks are distinct and a batch
    # appends << cap entries per lane, so slots cannot re-wrap in-batch;
    # masked lanes route to the out-of-range row `lanes` and drop
    safe_lane = jnp.where(do_append, lane, lanes)
    new_entries = ring.entries.at[safe_lane, slot].set(entry, mode="drop",
                                                       unique_indices=True)
    new_head = ring.head + lane_counts
    return ring.replace(entries=new_entries, head=new_head), lane, slot


# --------------------------------------------------------------------------
# Replicated flat ring: the dense engines' log x3.
#
# The reference replicates every log append to all 3 servers (CommitLog x3,
# tatp/caladan/client_ebpf_shard.cc:779-810) and the replicas are
# bit-identical by construction, so the dense engines keep ONE set of slots
# with the 3 replica entries packed side by side in the trailing word axis,
# written by a single row-major unique-index scatter — the same scatter
# discipline as their table installs (engines/tatp_dense.py module
# docstring). Two facts force this exact shape:
#   * the append is a FLAT 1-D row scatter (lane l's slots occupy rows
#     [l*cap, (l+1)*cap)): per-lane arrival ranks make the (lane, slot)
#     pairs provably distinct, which the flat row id turns into a plain
#     `unique_indices=True` declaration (round 7) — ~2 ms per 16 K appends
#     on v5e, where the historical [L, CAP] 2-D index form cost ~15 ms
#     before it carried the uniqueness declaration. The same flat layout
#     is what lets round 12's install_log megakernel take the append as
#     one more masked row-scatter stream (`plan_rep` below exposes the
#     planned rows; ops/pallas_gather.scatter_streams does the write);
#   * a [slots, 3, EW] u32 array is tiled T(4,128) over its minor dims, so
#     each slot physically occupies 2 KB — 34 GB at 16M slots (observed
#     OOM). Packing replicas into the word axis pays the 128-lane padding
#     once per slot, not once per replica.
# --------------------------------------------------------------------------


@flax.struct.dataclass
class RepLog:
    entries: jax.Array   # u32 [L*CAP, S * (HDR_WORDS + VW)]
    head: jax.Array      # u32 [L] (monotonic; replicas identical)
    lanes: int = flax.struct.field(pytree_node=False, default=16)
    replicas: int = flax.struct.field(pytree_node=False, default=3)

    @property
    def entry_words(self):
        return self.entries.shape[1] // self.replicas

    @property
    def capacity(self):
        return self.entries.shape[0] // self.lanes


def create_rep(lanes: int, capacity: int, val_words: int = 10,
               replicas: int = 3) -> RepLog:
    assert capacity & (capacity - 1) == 0
    return RepLog(
        entries=jnp.zeros((lanes * capacity,
                           replicas * (HDR_WORDS + val_words)), U32),
        head=jnp.zeros((lanes,), U32), lanes=lanes, replicas=replicas)


def plan_rep(ring: RepLog, do_append, table_id, is_del, key_hi, key_lo,
             ver, val):
    """Plan a replicated append without writing: returns
    (flat [R] i32 row ids with -1 for masked lanes, entry3 [R, S*(HDR+VW)]
    u32 replica-packed rows, lane_counts u32 [L]). `append_rep` is exactly
    this plan + one unique-index row scatter + the head advance; the
    fused install_log path feeds the SAME plan to
    ops/pallas_gather.scatter_streams instead, so the ring bytes are
    bit-identical on both routes."""
    r = do_append.shape[0]
    lanes = ring.lanes
    cap = ring.capacity
    idx = jnp.arange(r, dtype=I32)
    lane = idx % lanes
    one = do_append.astype(I32)
    pad = (-r) % lanes
    one_p = jnp.pad(one, (0, pad)).reshape(-1, lanes)
    excl = jnp.cumsum(one_p, axis=0) - one_p
    rank = excl.reshape(-1)[:r]
    lane_counts = one_p.sum(axis=0).astype(U32)
    pos = ring.head[lane] + rank.astype(U32)
    slot = (pos % U32(cap)).astype(I32)
    flat = jnp.where(do_append, lane * cap + slot, -1)

    flags = (is_del.astype(U32) | (table_id.astype(U32) << U32(8)))
    entry = jnp.concatenate(
        [flags[:, None], key_hi[:, None], key_lo[:, None], ver[:, None],
         val.astype(U32)], axis=1)                        # [R, HDR+VW]
    entry3 = jnp.tile(entry, (1, ring.replicas))          # [R, S*(HDR+VW)]
    return flat, entry3, lane_counts


def append_rep(ring: RepLog, do_append, table_id, is_del, key_hi, key_lo,
               ver, val) -> RepLog:
    """Batched replicated append; same slot assignment as `append` (lane =
    round-robin, slot = head[lane] + arrival rank within the lane, rings
    wrap). One unique-index row scatter installs all replicas."""
    flat, entry3, lane_counts = plan_rep(ring, do_append, table_id,
                                         is_del, key_hi, key_lo, ver, val)
    lanes = ring.lanes
    cap = ring.capacity
    widx = jnp.where(flat >= 0, flat, lanes * cap)
    new_entries = ring.entries.at[widx].set(entry3, mode="drop",
                                            unique_indices=True)
    return ring.replace(entries=new_entries, head=ring.head + lane_counts)


def advance_watermark(ring: LogRing | RepLog, watermark, consumed):
    """Advance a ring's durability watermark after `consumed` entries per
    lane have been checkpointed or replayed downstream.

    The rings themselves wrap unconditionally, exactly like the
    reference's fixed per-CPU arrays (ls_kern.c:72-73): an append never
    blocks, and `recovery._flat_entries` refuses a wrapped ring because
    the overwritten prefix is gone. A caller that snapshots/replays its
    tables periodically owns a `watermark` u32 [L] ("entries below this
    head position are durable elsewhere") and advances it here; the ring
    is then bounded as long as head - watermark <= capacity between
    advances. No engine threads a watermark yet — that is the ROADMAP
    log-truncation item, and dintdur's `no-ring-truncation` check keys on
    exactly this call (the `jnp.minimum` clamp below is the TRUNCATED
    anchor in analysis/dataflow.py) to flag every ring that appends
    without one."""
    return jnp.minimum(ring.head, watermark + consumed.astype(U32))


def replica_entries(ring: RepLog, replica: int = 0):
    """One replica's slots in LogRing layout [L, CAP, HDR+VW] (the recovery
    path's input: any single surviving ring suffices)."""
    ew = ring.entry_words
    return ring.entries[:, replica * ew:(replica + 1) * ew].reshape(
        ring.lanes, ring.capacity, ew)
