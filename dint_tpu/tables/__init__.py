from . import kv, locks, log  # noqa: F401
