"""Dense tables: direct-indexed value/version arrays for dense keyspaces.

The reference hashes *every* table because its kvs.h is generic
(store/ebpf/kvs.h), even though SmallBank accounts (0..N-1,
smallbank/ebpf/smallbank.h:20-66) and TATP subscriber ids (1..P,
tatp/caladan/tatp.h:28) are dense integers. On TPU, dense keys index HBM
arrays directly — no probe, no buckets, no collisions, and per-record locks
become exact instead of hash-conflated. Sparse/composite-key tables
(e.g. TATP CALL_FORWARDING) still use tables.kv.KVTable.

``val`` is a tight interleaved 1-D word array (row r's words at
[r*VW, (r+1)*VW)) — a [N, VW] array would be XLA-tiled to 128 lanes
(512 B/row at VW=10), which caps the generic engines ~40x below the
reference's keyspace sizes on a 16 GB chip (same measured finding as
tables/kv.py and engines/tatp_dense.py).
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32
U32 = jnp.uint32


@flax.struct.dataclass
class DenseTable:
    val: jax.Array   # u32 [N * VW] interleaved
    ver: jax.Array   # u32 [N]
    val_words: int = flax.struct.field(pytree_node=False, default=10)

    @property
    def size(self):
        return self.ver.shape[0]


def create(n: int, val_words: int) -> DenseTable:
    assert n * val_words < (1 << 31), "row*VW overflows i32 flat indices"
    return DenseTable(val=jnp.zeros((n * val_words,), U32),
                      ver=jnp.zeros((n,), U32), val_words=val_words)


def row_word_idx(idx, val_words: int):
    """Flat word indices [R, VW] of rows [R] in an interleaved value array
    (shared by tables.kv's entry gathers — one implementation of the
    row*VW+j math)."""
    return idx[:, None] * val_words + jnp.arange(val_words, dtype=I32)[None]


def gather_rows(table: DenseTable, idx):
    """Row gather: idx [R] -> values [R, VW]."""
    return table.val[row_word_idx(idx, table.val_words)]


def scatter_rows_val(table: DenseTable, idx, values, mask):
    """Masked row scatter; returns the new flat val array (masked lanes
    drop out of bounds)."""
    safe = jnp.where(mask, idx, table.size)
    flat = row_word_idx(safe, table.val_words).reshape(-1)
    return table.val.at[flat].set(values.reshape(-1), mode="drop")


def populate(table: DenseTable, vals: np.ndarray, vers=None) -> DenseTable:
    vals = np.asarray(vals, np.uint32)
    assert vals.shape == (table.size, table.val_words)
    if vers is None:
        vers = np.ones(table.size, np.uint32)
    return table.replace(val=jnp.asarray(vals.reshape(-1)),
                         ver=jnp.asarray(vers))
