"""Dense tables: direct-indexed value/version arrays for dense keyspaces.

The reference hashes *every* table because its kvs.h is generic
(store/ebpf/kvs.h), even though SmallBank accounts (0..N-1,
smallbank/ebpf/smallbank.h:20-66) and TATP subscriber ids (1..P,
tatp/caladan/tatp.h:28) are dense integers. On TPU, dense keys index HBM
arrays directly — no probe, no buckets, no collisions, and per-record locks
become exact instead of hash-conflated. Sparse/composite-key tables
(e.g. TATP CALL_FORWARDING) still use tables.kv.KVTable.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32
U32 = jnp.uint32


@flax.struct.dataclass
class DenseTable:
    val: jax.Array   # u32 [N, VW]
    ver: jax.Array   # u32 [N]

    @property
    def size(self):
        return self.ver.shape[0]

    @property
    def val_words(self):
        return self.val.shape[1]


def create(n: int, val_words: int) -> DenseTable:
    return DenseTable(val=jnp.zeros((n, val_words), U32),
                      ver=jnp.zeros((n,), U32))


def populate(table: DenseTable, vals: np.ndarray, vers=None) -> DenseTable:
    vals = np.asarray(vals, np.uint32)
    assert vals.shape == table.val.shape
    if vers is None:
        vers = np.ones(table.size, np.uint32)
    return DenseTable(val=jnp.asarray(vals), ver=jnp.asarray(vers))
