"""HBM-resident bucketed hash table.

TPU re-expression of the reference's two storage layers collapsed into one:
the in-kernel cache (`struct cache_entry` {key[4], val[4][V], ver[4],
valid[4], dirty[4], bloom, lock}, /root/reference/store/ebpf/utils.h:58-66)
and the userspace chained KVS (store/ebpf/kvs.h:10-153). Here the table is
sized to hold the whole keyspace in HBM, so the fast path always "hits"
(capacity permitting); bucket overflow surfaces as a SPILL reply for a host
overflow store instead of an eviction protocol.

Layout (struct-of-arrays, S slots per bucket, ALL FLAT): entry
e = bucket*S + slot indexes
  key_hi/key_lo  u32 [NB*S]
  val            u32 [NB*S*VW]  interleaved (entry e's words at [e*VW, (e+1)*VW))
  ver            u32 [NB*S]
  valid          bool [NB*S]
  bloom_hi/lo    u32 [NB]       64-bit per-bucket bloom (negative lookups)

Flat 1-D layouts are a measured v5e requirement, not a style choice: XLA
tiles a trailing dim of S=4..16 (or VW=10) to 128 lanes, so the previous
[NB, S] / [NB, S, VW] arrays cost 512 B per slot — the reference's
24M-key store config (store/ebpf/utils.h:11-14) would need ~30 GB of HBM
for ~1.3 GB of data, and 2-D-index scatters serialize where flat
unique-index scatters do not (PERF.md; same finding that shaped
engines/tatp_dense.py).

The per-entry CAS `lock` word of the reference has no equivalent: intra-batch
conflicts are resolved deterministically (ops.segments), so the table needs no
locks at all.
"""
from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from ..ops import hashing, segments, u64
from ..ops.u64 import U32
from . import dense

I32 = jnp.int32


@flax.struct.dataclass
class KVTable:
    key_hi: jax.Array     # u32 [NB*S]
    key_lo: jax.Array     # u32 [NB*S]
    val: jax.Array        # u32 [NB*S*VW] interleaved
    ver: jax.Array        # u32 [NB*S]
    valid: jax.Array      # bool [NB*S]
    bloom_hi: jax.Array   # u32 [NB]
    bloom_lo: jax.Array   # u32 [NB]
    slots: int = flax.struct.field(pytree_node=False, default=4)
    val_words: int = flax.struct.field(pytree_node=False, default=10)

    @property
    def n_buckets(self):
        return self.key_hi.shape[0] // self.slots

    @property
    def val2d(self):
        """[NB*S, VW] view for host-side dumps (not the hot path)."""
        return self.val.reshape(-1, self.val_words)


def create(n_buckets: int, slots: int = 4, val_words: int = 10) -> KVTable:
    assert n_buckets & (n_buckets - 1) == 0
    ne = n_buckets * slots
    assert ne * val_words < (1 << 31), "entry*VW overflows i32 flat indices"
    return KVTable(
        key_hi=jnp.zeros((ne,), U32),
        key_lo=jnp.zeros((ne,), U32),
        val=jnp.zeros((ne * val_words,), U32),
        ver=jnp.zeros((ne,), U32),
        valid=jnp.zeros((ne,), bool),
        bloom_hi=jnp.zeros((n_buckets,), U32),
        bloom_lo=jnp.zeros((n_buckets,), U32),
        slots=slots, val_words=val_words,
    )


def bucket_rows(table: KVTable, bkt):
    """Flat entry indices of each request's bucket row: [R, S]."""
    s = table.slots
    return bkt[:, None] * s + jnp.arange(s, dtype=I32)[None]


def entry_val(table: KVTable, eidx):
    """Gather entry values: eidx [R] -> [R, VW] (flat interleaved words)."""
    return table.val[dense.row_word_idx(eidx, table.val_words)]


def val_word_idx(table: KVTable, eidx):
    """Flat word indices [R*VW] for scattering whole entry values; pair
    with values.reshape(-1). OOB entry indices propagate to OOB words."""
    return dense.row_word_idx(eidx, table.val_words).reshape(-1)


def _match_bucket(table: KVTable, key_hi, key_lo, bkt):
    rows = bucket_rows(table, bkt)                    # [R, S]
    rows_hi = table.key_hi[rows]
    rows_lo = table.key_lo[rows]
    rows_valid = table.valid[rows]
    match = rows_valid & (rows_hi == key_hi[:, None]) & (rows_lo == key_lo[:, None])
    free = (~rows_valid).sum(axis=-1).astype(I32)
    return match.any(axis=-1), jnp.argmax(match, axis=-1).astype(I32), free


def probe_loc(table: KVTable, key_hi, key_lo, b1, b2):
    """Two-choice LOCATION probe: find each key in either candidate bucket
    without fetching its value (the dintcache hot tier serves hot keys'
    val/ver from its mirror, so the value gather is the caller's choice).

    Returns (hit [R] bool, bkt [R] i32, slot [R] i32, free1 [R] i32,
    free2 [R] i32)."""
    hit1, slot1, free1 = _match_bucket(table, key_hi, key_lo, b1)
    hit2, slot2, free2 = _match_bucket(table, key_hi, key_lo, b2)
    hit = hit1 | hit2
    bkt = jnp.where(hit1, b1, b2)
    slot = jnp.where(hit1, slot1, slot2)
    return hit, bkt, slot, free1, free2


def probe(table: KVTable, key_hi, key_lo, b1, b2):
    """Two-choice probe: find each key in either of its two candidate buckets.

    Returns (hit [R] bool, bkt [R] i32, slot [R] i32, val [R, VW], ver [R],
    free1 [R] i32, free2 [R] i32). ``bkt``/``slot`` are the key's actual
    location when hit, arbitrary otherwise; free1/free2 are the candidate
    buckets' free-slot counts (reusing the gathers the probe already did).
    A key lives in at most one bucket (insert picks one).
    """
    hit, bkt, slot, free1, free2 = probe_loc(table, key_hi, key_lo, b1, b2)
    eidx = bkt * table.slots + slot
    val = entry_val(table, eidx)
    ver = table.ver[eidx]
    return hit, bkt, slot, val, ver, free1, free2


def bloom_maybe(table: KVTable, key_hi, key_lo, b1, b2):
    """True if either candidate bucket's bloom admits the key (one hash)."""
    bit = hashing.bloom_bit(key_hi, key_lo)           # [R] in [0, 64)
    use_hi = bit >= 32
    shift = jnp.where(use_hi, bit - 32, bit).astype(U32)

    def hit(b):
        word = jnp.where(use_hi, table.bloom_hi[b], table.bloom_lo[b])
        return ((word >> shift) & U32(1)) == U32(1)

    return hit(b1) | hit(b2)


def nth_free_slot(valid_rows, rank):
    """For each request: index of the (rank+1)-th free slot in its bucket row.

    valid_rows: bool [R, S]; rank: i32 [R].
    Returns (has_free [R] bool, slot [R] i32).
    """
    free = ~valid_rows
    cumfree = jnp.cumsum(free.astype(I32), axis=-1)
    want = free & (cumfree == (rank[:, None] + 1))
    has = want.any(axis=-1)
    slot = jnp.argmax(want, axis=-1).astype(I32)
    return has, slot


def recompute_bloom(table: KVTable, bkt, write_mask):
    """Recompute the 64-bit bloom word for each (masked) bucket from its live
    keys, and scatter back. Exact — unlike the reference, which can only OR
    bits in-kernel and recomputes in userspace on DELETE
    (tatp/ebpf/shard_user.c DELETE path)."""
    rows = bucket_rows(table, bkt)
    rows_hi = table.key_hi[rows]
    rows_lo = table.key_lo[rows]
    rows_valid = table.valid[rows]
    bit = hashing.bloom_bit(rows_hi, rows_lo)         # [R, S]
    hi_bits = jnp.where(rows_valid & (bit >= 32),
                        U32(1) << jnp.clip(bit - 32, 0, 31).astype(U32), U32(0))
    lo_bits = jnp.where(rows_valid & (bit < 32),
                        U32(1) << jnp.clip(bit, 0, 31).astype(U32), U32(0))
    new_hi = hi_bits[:, 0]
    new_lo = lo_bits[:, 0]
    for s in range(1, hi_bits.shape[1]):  # static, small S
        new_hi = new_hi | hi_bits[:, s]
        new_lo = new_lo | lo_bits[:, s]
    return table.replace(
        bloom_hi=segments.scatter_rows(table.bloom_hi, bkt, new_hi, write_mask),
        bloom_lo=segments.scatter_rows(table.bloom_lo, bkt, new_lo, write_mask),
    )


# ---------------------------------------------------------------- host-side


def to_dict(table: KVTable) -> dict:
    """Dump live entries to {key: (val tuple, ver)} for differential tests."""
    valid = np.asarray(table.valid)
    e = np.nonzero(valid)[0]
    keys = u64.join(np.asarray(table.key_hi)[e], np.asarray(table.key_lo)[e])
    vals = np.asarray(table.val).reshape(-1, table.val_words)[e]
    vers = np.asarray(table.ver)[e]
    return {int(k): (tuple(int(x) for x in v), int(ver))
            for k, v, ver in zip(keys, vals, vers)}


def _within_bucket_rank(bkt, priority=None):
    """Rank of each key within its bucket; `priority` randomizes which keys
    count as the overflow (essential for cuckoo rebalancing: victims must be
    random, or high-priority keys ping-pong without displacing residents)."""
    if priority is not None:
        order = np.lexsort((priority, bkt))
    else:
        order = np.argsort(bkt, kind="stable")
    sorted_bkt = bkt[order]
    start = np.concatenate([[True], sorted_bkt[1:] != sorted_bkt[:-1]])
    idx = np.arange(len(bkt))
    within_sorted = idx - np.maximum.accumulate(np.where(start, idx, 0))
    within = np.empty(len(bkt), np.int64)
    within[order] = within_sorted
    return within


def assign_two_choice(keys: np.ndarray, n_buckets: int, slots: int,
                      max_iters: int = 200):
    """Offline two-choice placement: per key, pick one of its two candidate
    buckets so no bucket exceeds `slots`. Parallel random-walk cuckoo:
    each iteration, keys that overflow their bucket flip to their alternate
    (with random damping), displacing others, until no bucket overflows.
    Converges comfortably up to ~0.85 load with 4-slot buckets (the parallel
    random walk slows well short of the (2,4)-cuckoo feasibility threshold of
    ~0.98) — far beyond single-choice hashing's Poisson tail. Size production
    tables at <= 0.75 load.

    Returns (bkt [N], slot [N]); raises if it cannot converge.
    """
    keys = np.asarray(keys, np.uint64)
    b1, b2 = hashing.bucket_pair_np(keys, n_buckets)
    rng = np.random.default_rng(0xD1A7)
    choice = np.zeros(len(keys), bool)   # False -> b1
    for _ in range(max_iters):
        cur = np.where(choice, b2, b1)
        within = _within_bucket_rank(cur, priority=rng.random(len(keys)))
        over = within >= slots
        if not over.any():
            return cur, within
        flip = over & (rng.random(len(keys)) < 0.7)
        choice ^= flip
    raise ValueError(
        f"two-choice placement did not converge: {len(keys)} keys into "
        f"{n_buckets} buckets x {slots} slots = {n_buckets * slots} capacity "
        f"(load {len(keys) / (n_buckets * slots):.2f}; need <~0.9 — grow "
        "cf_buckets / n_buckets)")


def populate(table: KVTable, keys: np.ndarray, vals: np.ndarray,
             vers: np.ndarray | None = None) -> KVTable:
    """Bulk-load a table host-side (numpy), like the reference's populate
    phase (smallbank/ebpf/shard_user.c:74-77, tatp/caladan/server_shard.cc:56-70).

    Two-choice placement; raises if the table genuinely cannot hold the
    keyspace (the reference instead sizes ad hoc, e.g. SAV_HASH_SIZE =
    ACCOUNT_NUM*3/2/4, smallbank/ebpf/utils.h:16-17, and relies on chaining).
    """
    nb, s = table.n_buckets, table.slots
    ne = nb * s
    keys = np.asarray(keys, np.uint64)
    if len(np.unique(keys)) != len(keys):
        raise ValueError("duplicate keys in populate")
    vals = np.asarray(vals, np.uint32)
    if vers is None:
        vers = np.ones(len(keys), np.uint32)
    bkt, slot = assign_two_choice(keys, nb, s)
    eidx = bkt * s + slot

    k_hi, k_lo = u64.split(keys)
    key_hi = np.zeros(ne, np.uint32)
    key_lo = np.zeros(ne, np.uint32)
    val = np.zeros((ne, table.val_words), np.uint32)
    ver = np.zeros(ne, np.uint32)
    valid = np.zeros(ne, bool)
    key_hi[eidx] = k_hi
    key_lo[eidx] = k_lo
    val[eidx] = vals
    ver[eidx] = vers
    valid[eidx] = True
    bits = hashing.bloom_bit_np(keys)
    bloom = np.zeros(nb, np.uint64)
    np.bitwise_or.at(bloom, bkt, np.uint64(1) << bits.astype(np.uint64))
    b_hi, b_lo = u64.split(bloom)
    return table.replace(
        key_hi=jnp.asarray(key_hi), key_lo=jnp.asarray(key_lo),
        val=jnp.asarray(val.reshape(-1)), ver=jnp.asarray(ver),
        valid=jnp.asarray(valid),
        bloom_hi=jnp.asarray(b_hi), bloom_lo=jnp.asarray(b_lo))
