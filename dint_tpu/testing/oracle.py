"""Sequential (pure Python dict) oracles for differential testing.

Each oracle implements the *same serialization contract* that the batched
engine documents — so engine output must match the oracle exactly, batch for
batch. This supplies what the reference lacks entirely (it has no unit tests;
correctness there rests on magic-byte asserts and cross-backend equivalence,
see SURVEY.md §4); the oracle here plays the role of the reference's
"other backend" in cross-backend differential testing.
"""
from __future__ import annotations

import numpy as np

from ..engines.types import Op, Reply

VER0 = 0


class StoreOracle:
    """Sequential model of engines.store: per key, GETs see pre-batch state,
    then writes apply in lane order; SET/INSERT are upserts bumping a
    monotonic version; DELETE invalidates."""

    def __init__(self):
        self.data: dict[int, tuple[tuple, int]] = {}   # key -> (val tuple, ver)

    def scan(self, start_key: int, scan_len: int):
        """Range scan against pre-batch state: the first `scan_len` live
        keys >= start_key in key order, as [(key, val tuple, ver), ...].
        SCANs are reads — they sit in phase 1 with the GETs."""
        rows = []
        for k in sorted(self.data):
            if len(rows) >= scan_len:
                break
            if k >= int(start_key):
                rows.append((k, self.data[k][0], self.data[k][1]))
        return rows

    def step(self, ops, keys, vals, scan_lens=None, scan_max: int = 0):
        """One batch. `scan_lens` [r] carries Op.SCAN lanes' requested row
        counts (clipped to scan_max, the engine's static slab width).
        Returns (rtype, rval, rver) — plus `scans`, a per-lane list of
        scan row lists, when scan_max > 0."""
        r = len(ops)
        rtype = np.zeros(r, np.int32)
        rver = np.zeros(r, np.uint32)
        rval = np.zeros((r, np.asarray(vals).shape[1]), np.uint32)
        scans: list[list] = [[] for _ in range(r)]
        # phase 1: reads against pre-state
        for i in range(r):
            if ops[i] == Op.GET:
                ent = self.data.get(int(keys[i]))
                if ent is None:
                    rtype[i] = Reply.NOT_EXIST
                else:
                    rtype[i] = Reply.VAL
                    rval[i] = ent[0]
                    rver[i] = ent[1]
            elif ops[i] == Op.SCAN:
                want = int(scan_lens[i]) if scan_lens is not None else 0
                rows = self.scan(int(keys[i]), max(0, min(want, scan_max)))
                scans[i] = rows
                rtype[i] = Reply.VAL
                rver[i] = np.uint32(len(rows))
        # phase 2: writes in lane order
        # version base = pre-batch version, recorded at the key's first write
        # in the batch; versions stay monotonic across delete+reinsert within
        # a batch (ABA avoidance — stronger than the reference's kvs)
        base: dict[int, int] = {}
        cnt: dict[int, int] = {}

        def touch(k):
            if k not in base:
                base[k] = self.data[k][1] if k in self.data else VER0
                cnt[k] = 0

        for i in range(r):
            k = int(keys[i])
            if ops[i] in (Op.SET, Op.INSERT):
                touch(k)
                cnt[k] += 1
                ver = base[k] + cnt[k]
                self.data[k] = (tuple(int(x) for x in vals[i]), ver)
                rtype[i] = Reply.ACK
                rver[i] = ver
            elif ops[i] == Op.DELETE:
                touch(k)
                if k in self.data:
                    del self.data[k]
                    rtype[i] = Reply.ACK
                else:
                    rtype[i] = Reply.NOT_EXIST
        if scan_max > 0:
            return rtype, rval, rver, scans
        return rtype, rval, rver


class SXLockOracle:
    """Sequential model of engines.lock2pl: per slot, releases apply first,
    then acquires in lane order under no-wait 2PL."""

    def __init__(self, n_slots: int):
        self.num_sh = np.zeros(n_slots, np.int64)
        self.num_ex = np.zeros(n_slots, np.int64)

    def step(self, ops, slots):
        r = len(ops)
        rtype = np.zeros(r, np.int32)
        for i in range(r):  # releases first
            s = int(slots[i])
            if ops[i] == Op.REL_S:
                self.num_sh[s] = max(self.num_sh[s] - 1, 0)
                rtype[i] = Reply.ACK
            elif ops[i] == Op.REL_X:
                self.num_ex[s] = max(self.num_ex[s] - 1, 0)
                rtype[i] = Reply.ACK
        for i in range(r):  # acquires in lane order
            s = int(slots[i])
            if ops[i] == Op.ACQ_S:
                if self.num_ex[s] == 0:
                    self.num_sh[s] += 1
                    rtype[i] = Reply.GRANT
                else:
                    rtype[i] = Reply.REJECT
            elif ops[i] == Op.ACQ_X:
                if self.num_ex[s] == 0 and self.num_sh[s] == 0:
                    self.num_ex[s] += 1
                    rtype[i] = Reply.GRANT
                else:
                    rtype[i] = Reply.REJECT
        return rtype


class OCCOracle:
    """Sequential model of engines.fasst: per slot, unlocks (commit/abort)
    first, then reads, then lock acquires in lane order."""

    def __init__(self, n_slots: int):
        self.locked = np.zeros(n_slots, bool)
        self.ver = np.zeros(n_slots, np.uint32)

    def step(self, ops, slots):
        r = len(ops)
        rtype = np.zeros(r, np.int32)
        rver = np.zeros(r, np.uint32)
        rlocked = np.zeros(r, np.uint32)
        for i in range(r):  # commits/aborts first
            s = int(slots[i])
            if ops[i] == Op.COMMIT_VER:
                self.ver[s] += 1
                self.locked[s] = False
                rtype[i] = Reply.ACK
            elif ops[i] == Op.ABORT:
                self.locked[s] = False
                rtype[i] = Reply.ACK
        for i in range(r):  # reads see post-commit versions + lock bits
            if ops[i] == Op.READ_VER:
                s = int(slots[i])
                rtype[i] = Reply.VAL
                rver[i] = self.ver[s]
                rlocked[i] = np.uint32(self.locked[s])
        for i in range(r):  # lock acquires in lane order
            if ops[i] == Op.LOCK:
                s = int(slots[i])
                if not self.locked[s]:
                    self.locked[s] = True
                    rtype[i] = Reply.GRANT
                else:
                    rtype[i] = Reply.REJECT
        return rtype, rver, rlocked
