from . import oracle  # noqa: F401
