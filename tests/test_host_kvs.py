"""Vectorized HostKVS vs a dict oracle: randomized differential test."""
import time

import numpy as np

from dint_tpu.engines.types import Op, Reply
from dint_tpu.ops import hashing
from dint_tpu.shim.host_kvs import HostKVS

VW = 4
CACHE_NB = 256


def _oracle_resolve(data, ops, keys, vals):
    """The original per-lane dict walk (pre-round-3 host_kvs semantics)."""
    m = len(ops)
    rtype = np.zeros(m, np.int32)
    rver = np.zeros(m, np.uint32)
    rval = np.zeros((m, VW), np.uint32)
    for i in range(m):
        if ops[i] == Op.GET:
            ent = data.get(int(keys[i]))
            if ent is None:
                rtype[i] = Reply.NOT_EXIST
            else:
                rtype[i] = Reply.VAL
                rval[i] = ent[0]
                rver[i] = ent[1]
    base, cnt = {}, {}
    for i in range(m):
        k = int(keys[i])
        if ops[i] in (Op.SET, Op.INSERT):
            if k not in base:
                base[k] = data[k][1] if k in data else 0
                cnt[k] = 0
            cnt[k] += 1
            data[k] = (tuple(int(x) for x in vals[i]), base[k] + cnt[k])
            rtype[i] = Reply.ACK
            rver[i] = base[k] + cnt[k]
        elif ops[i] == Op.DELETE:
            if k not in base:
                base[k] = data[k][1] if k in data else 0
                cnt[k] = 0
            if k in data:
                del data[k]
                rtype[i] = Reply.ACK
            else:
                rtype[i] = Reply.NOT_EXIST
    return rtype, rval, rver


def test_differential_vs_dict_oracle(rng):
    kvs = HostKVS(CACHE_NB, VW, capacity=64)   # tiny: forces grows + spill
    oracle: dict[int, tuple] = {}

    n0 = 300
    keys0 = rng.choice(np.arange(1, 2000, dtype=np.uint64), n0, replace=False)
    vals0 = rng.integers(0, 1 << 16, (n0, VW)).astype(np.uint32)
    kvs.populate(keys0, vals0)
    for k, v in zip(keys0, vals0):
        oracle[int(k)] = (tuple(int(x) for x in v), 1)

    for round_ in range(20):
        m = int(rng.integers(1, 200))
        ops = rng.choice([Op.GET, Op.SET, Op.INSERT, Op.DELETE], m,
                         p=[0.4, 0.3, 0.15, 0.15]).astype(np.int32)
        # small keyspace -> plenty of same-key collisions within a batch
        keys = rng.integers(1, 400, m).astype(np.uint64)
        vals = rng.integers(0, 1 << 16, (m, VW)).astype(np.uint32)

        want = _oracle_resolve(oracle, ops, keys, vals)
        got = kvs.resolve_batch(ops, keys, vals)
        for name, g, w in zip(("rtype", "rval", "rver"), got, want):
            assert np.array_equal(g, w), (round_, name)

    # end state identical
    all_keys = np.arange(1, 2001, dtype=np.uint64)
    found, v, r = kvs.lookup(all_keys)
    for i, k in enumerate(all_keys):
        ent = oracle.get(int(k))
        assert found[i] == (ent is not None), k
        if ent is not None:
            assert tuple(int(x) for x in v[i]) == ent[0], k
            assert int(r[i]) == ent[1], k
    assert kvs.n_live == len(oracle)

    # bloom words exact vs oracle liveness
    live = np.fromiter(oracle.keys(), np.uint64, len(oracle))
    bkt = hashing.bucket_np(live, CACHE_NB)
    bits = hashing.bloom_bit_np(live)
    want_words = np.zeros(CACHE_NB, np.uint64)
    np.bitwise_or.at(want_words, bkt, np.uint64(1) << bits.astype(np.uint64))
    got_words = kvs.bloom_words(np.arange(CACHE_NB))
    assert np.array_equal(got_words, want_words)


def test_populate_scale_is_vectorized():
    """1M keys must populate in seconds (the per-lane dict loop took
    minutes) and batch-read at full width."""
    n = 1_000_000
    kvs = HostKVS(1 << 19, VW, capacity=n)
    keys = np.arange(1, n + 1, dtype=np.uint64)
    vals = np.zeros((n, VW), np.uint32)
    vals[:, 0] = keys.astype(np.uint32)
    t0 = time.time()
    kvs.populate(keys, vals)
    populate_s = time.time() - t0
    # generous bound: the per-lane dict loop took minutes; the vectorized
    # path takes seconds even on a loaded machine (a tight bound flakes
    # when the suite shares cores with a TPU bench run)
    assert populate_s < 90, populate_s

    probe = np.random.default_rng(0).integers(1, n + 1, 8192).astype(np.uint64)
    t0 = time.time()
    found, v, r = kvs.lookup(probe)
    assert found.all()
    assert (v[:, 0] == probe.astype(np.uint32)).all()
    assert time.time() - t0 < 1.0


def test_duplicate_keys_in_one_upsert_are_last_wins(rng):
    kvs = HostKVS(CACHE_NB, VW, capacity=64)
    keys = np.array([5, 5, 9, 5], np.uint64)
    vals = np.arange(4 * VW, dtype=np.uint32).reshape(4, VW)
    kvs.upsert_batch(keys, vals, np.ones(4, np.uint32))
    assert kvs.n_live == 2
    found, v, _ = kvs.lookup(np.array([5, 9], np.uint64))
    assert found.all()
    assert np.array_equal(v[0], vals[3])    # last occurrence wins
    gone = kvs.delete_batch(np.array([5, 5], np.uint64))
    assert gone.sum() == 1
    assert kvs.n_live == 1
    # bloom counter for key 5 fully released
    found, _, _ = kvs.lookup(np.array([5], np.uint64))
    assert not found[0]
