"""stats.LatencyReservoir: percentile edge behavior + interpolation.

The reservoir is the latency store behind every metric block (bench.py,
exp.py, the wire clients). Its percentile contract must be total: an
empty window, a single sample, and glitch-poisoned (non-finite) samples
all return defined numbers — a NaN in a published p99 is how a
measurement silently stops being auditable.
"""
import numpy as np
import pytest

from dint_tpu.stats import LatencyReservoir, cohort_latency_percentiles


def test_empty_reservoir_returns_zeros_not_nan():
    p = LatencyReservoir().percentiles()
    assert p == dict(avg=0.0, p50=0.0, p99=0.0, p999=0.0)
    assert all(np.isfinite(v) for v in p.values())


def test_single_sample_defines_every_percentile():
    lat = LatencyReservoir()
    lat.add(42.5)
    p = lat.percentiles()
    assert p["avg"] == p["p50"] == p["p99"] == p["p999"] == 42.5


def test_two_samples_interpolate_linearly():
    lat = LatencyReservoir()
    lat.add(np.array([0.0, 100.0]))
    p = lat.percentiles()
    assert p["p50"] == pytest.approx(50.0)
    assert p["p99"] == pytest.approx(99.0)
    assert p["p999"] == pytest.approx(99.9)


def test_percentile_interpolation_matches_numpy_linear():
    # 1..1000: the linear ("nth fractional rank") interpolation values
    # are closed-form: p at q = 1 + q/100 * 999
    lat = LatencyReservoir()
    s = np.arange(1, 1001, dtype=np.float64)
    lat.add(s)
    p = lat.percentiles()
    assert p["p50"] == pytest.approx(1 + 0.50 * 999)    # 500.5
    assert p["p99"] == pytest.approx(1 + 0.99 * 999)    # 990.01
    assert p["p999"] == pytest.approx(1 + 0.999 * 999)  # 999.001
    assert p["avg"] == pytest.approx(s.mean())
    # and p50 <= p99 <= p99.9 always
    assert p["p50"] <= p["p99"] <= p["p999"]


def test_non_finite_samples_are_excluded():
    lat = LatencyReservoir()
    lat.add(np.array([1.0, np.nan, 2.0, np.inf, 3.0]))
    p = lat.percentiles()
    assert all(np.isfinite(v) for v in p.values())
    assert p["p50"] == 2.0
    assert p["avg"] == pytest.approx(2.0)
    # all-non-finite degrades to the empty contract, not NaN
    lat2 = LatencyReservoir()
    lat2.add(np.array([np.nan, np.nan]))
    assert lat2.percentiles() == dict(avg=0.0, p50=0.0, p99=0.0, p999=0.0)


def test_reservoir_downsampling_keeps_percentiles_defined():
    lat = LatencyReservoir(cap=256, seed=0)
    lat.add(np.full(10_000, 7.0))
    assert lat.n_kept == 256 and lat.n_seen == 10_000
    p = lat.percentiles()
    assert p["p50"] == p["p999"] == 7.0


def test_empty_add_is_a_noop():
    lat = LatencyReservoir()
    lat.add(np.array([]))
    assert lat.n_seen == 0
    assert lat.percentiles()["p99"] == 0.0


def test_cohort_latency_percentiles_empty_blocks():
    out = cohort_latency_percentiles([], cohorts_per_block=4, depth=3)
    assert out["n"] == 0
    assert out["p99"] == 0.0 and np.isfinite(out["p999"])
