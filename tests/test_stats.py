"""stats.LatencyReservoir: percentile edge behavior + interpolation.

The reservoir is the latency store behind every metric block (bench.py,
exp.py, the wire clients). Its percentile contract must be total: an
empty window, a single sample, and glitch-poisoned (non-finite) samples
all return defined numbers — a NaN in a published p99 is how a
measurement silently stops being auditable.
"""
import numpy as np
import pytest

from dint_tpu.stats import (LatencyHistogram, LatencyReservoir,
                            cohort_latency_percentiles)


def test_empty_reservoir_returns_zeros_not_nan():
    p = LatencyReservoir().percentiles()
    assert p == dict(avg=0.0, p50=0.0, p99=0.0, p999=0.0)
    assert all(np.isfinite(v) for v in p.values())


def test_single_sample_defines_every_percentile():
    lat = LatencyReservoir()
    lat.add(42.5)
    p = lat.percentiles()
    assert p["avg"] == p["p50"] == p["p99"] == p["p999"] == 42.5


def test_two_samples_interpolate_linearly():
    lat = LatencyReservoir()
    lat.add(np.array([0.0, 100.0]))
    p = lat.percentiles()
    assert p["p50"] == pytest.approx(50.0)
    assert p["p99"] == pytest.approx(99.0)
    assert p["p999"] == pytest.approx(99.9)


def test_percentile_interpolation_matches_numpy_linear():
    # 1..1000: the linear ("nth fractional rank") interpolation values
    # are closed-form: p at q = 1 + q/100 * 999
    lat = LatencyReservoir()
    s = np.arange(1, 1001, dtype=np.float64)
    lat.add(s)
    p = lat.percentiles()
    assert p["p50"] == pytest.approx(1 + 0.50 * 999)    # 500.5
    assert p["p99"] == pytest.approx(1 + 0.99 * 999)    # 990.01
    assert p["p999"] == pytest.approx(1 + 0.999 * 999)  # 999.001
    assert p["avg"] == pytest.approx(s.mean())
    # and p50 <= p99 <= p99.9 always
    assert p["p50"] <= p["p99"] <= p["p999"]


def test_non_finite_samples_are_excluded():
    lat = LatencyReservoir()
    lat.add(np.array([1.0, np.nan, 2.0, np.inf, 3.0]))
    p = lat.percentiles()
    assert all(np.isfinite(v) for v in p.values())
    assert p["p50"] == 2.0
    assert p["avg"] == pytest.approx(2.0)
    # all-non-finite degrades to the empty contract, not NaN
    lat2 = LatencyReservoir()
    lat2.add(np.array([np.nan, np.nan]))
    assert lat2.percentiles() == dict(avg=0.0, p50=0.0, p99=0.0, p999=0.0)


def test_reservoir_downsampling_keeps_percentiles_defined():
    lat = LatencyReservoir(cap=256, seed=0)
    lat.add(np.full(10_000, 7.0))
    assert lat.n_kept == 256 and lat.n_seen == 10_000
    p = lat.percentiles()
    assert p["p50"] == p["p999"] == 7.0


def test_empty_add_is_a_noop():
    lat = LatencyReservoir()
    lat.add(np.array([]))
    assert lat.n_seen == 0
    assert lat.percentiles()["p99"] == 0.0


def test_cohort_latency_percentiles_empty_blocks():
    out = cohort_latency_percentiles([], cohorts_per_block=4, depth=3)
    assert out["n"] == 0
    assert out["p99"] == 0.0 and np.isfinite(out["p999"])
    # the artifact "lat_hist" block rides next to the percentile dict
    assert out["hist"]["n"] == 0 and out["hist"]["buckets"] == {}


# ------------------------- LatencyHistogram (the dintscope SLO sensor) --

# the documented bound: buckets are 2^(1/8) wide and represent by their
# geometric midpoint, so an in-range percentile is within 2^(1/16)-1 of
# the exact nth-element value
HIST_REL_ERR = 2 ** (1 / 16) - 1


def test_histogram_percentiles_bounded_relative_error_vs_exact():
    """Log-bucket quantiles vs the exact nth-element on small samples
    (the reference's store/caladan/stat.h:15-20 semantics, which the
    histogram's ceil-rank read mirrors): every quantile within the
    documented relative-error bound."""
    rng = np.random.default_rng(7)
    for sample in (np.geomspace(1.0, 1e5, 333),
                   rng.lognormal(5.0, 2.0, 500),
                   np.full(100, 42.0),
                   np.array([3.0, 3000.0])):
        res = LatencyReservoir()
        hist = LatencyHistogram()
        res.add(sample)
        hist.add(sample)
        srt = np.sort(sample)
        for q in (0.50, 0.99, 0.999):
            exact = srt[min(max(int(np.ceil(q * len(srt))), 1),
                            len(srt)) - 1]
            assert hist.quantile(q) == pytest.approx(
                exact, rel=HIST_REL_ERR), (q,)
        # the mean is exact (tracked as a sum), not bucket-quantized;
        # p50 also sits near the reservoir's interpolated read
        pr, ph = res.percentiles(), hist.percentiles()
        assert ph["avg"] == pytest.approx(pr["avg"], rel=1e-12)
        if len(sample) >= 100:   # interpolation ~ nth-element at scale
            assert ph["p50"] == pytest.approx(pr["p50"], rel=0.10)


def test_histogram_merge_is_exact_and_associative():
    """Cross-shard/window merge: bucket counts add, so any grouping of
    merges equals the single histogram of the concatenated stream —
    the property reservoir downsampling cannot give."""
    rng = np.random.default_rng(0)
    parts = [rng.lognormal(4.0, 1.5, n) for n in (400, 7, 1300)]

    def h(arrs):
        out = LatencyHistogram()
        for a in arrs:
            out.add(a)
        return out

    whole = h(parts)
    left = h(parts[:1]).merge(h(parts[1:2])).merge(h(parts[2:3]))
    right = h(parts[:1]).merge(h(parts[1:2]).merge(h(parts[2:3])))
    for m in (left, right):
        np.testing.assert_array_equal(m.counts, whole.counts)
        assert m.n == whole.n
        assert m.sum_us == pytest.approx(whole.sum_us)
        assert m.percentiles() == whole.percentiles()


def test_histogram_totality_matches_reservoir_contract():
    # empty -> zeros, never NaN
    assert LatencyHistogram().percentiles() == dict(avg=0.0, p50=0.0,
                                                    p99=0.0, p999=0.0)
    # n == 1 -> every percentile is the same defined value, within the
    # bucket bound of the sample
    h1 = LatencyHistogram()
    h1.add(42.5)
    p = h1.percentiles()
    assert p["p50"] == p["p99"] == p["p999"]
    assert p["p50"] == pytest.approx(42.5, rel=HIST_REL_ERR)
    assert p["avg"] == 42.5
    # non-finite samples are excluded and counted, not poisoning
    h2 = LatencyHistogram()
    h2.add(np.array([1.0, np.nan, 2.0, np.inf, 3.0]))
    assert h2.n == 3 and h2.dropped_nonfinite == 2
    assert all(np.isfinite(v) for v in h2.percentiles().values())
    h3 = LatencyHistogram()
    h3.add(np.array([np.nan, np.nan]))
    assert h3.percentiles() == dict(avg=0.0, p50=0.0, p99=0.0, p999=0.0)
    # zero/negative and out-of-range samples clamp to edge buckets
    h4 = LatencyHistogram()
    h4.add(np.array([0.0, -5.0, 1e30]))
    assert h4.n == 3
    assert h4.counts[0] == 2 and h4.counts[-1] == 1


def test_histogram_serialization_roundtrip():
    h = LatencyHistogram()
    h.add(np.geomspace(0.5, 2e4, 257))
    h.add(np.array([np.inf]))
    d = h.to_dict()
    assert d["schema"] == LatencyHistogram.SCHEMA
    assert d["n"] == 257 and d["dropped_nonfinite"] == 1
    assert d["p50_us"] == round(h.quantile(0.5), 2)
    # sparse: only non-zero buckets serialized
    assert all(int(c) > 0 for c in d["buckets"].values())
    h2 = LatencyHistogram.from_dict(d)
    np.testing.assert_array_equal(h2.counts, h.counts)
    assert h2.n == h.n and h2.dropped_nonfinite == h.dropped_nonfinite
    p, p2 = h.percentiles(), h2.percentiles()
    assert p2["p50"] == p["p50"] and p2["p999"] == p["p999"]
    # sum_us serializes rounded to 1e-3 µs — avg roundtrips to that
    assert p2["avg"] == pytest.approx(p["avg"], abs=1e-3)


def test_reservoir_carries_exact_histogram_past_cap():
    lat = LatencyReservoir(cap=64, seed=0)
    lat.add(np.full(1000, 5.0))
    # the reservoir downsampled; the histogram counted everything
    assert lat.n_kept == 64 and lat.hist.n == 1000
    assert lat.hist.percentiles()["p50"] == pytest.approx(
        5.0, rel=HIST_REL_ERR)
