"""Test harness: force CPU backend with 8 virtual devices so the full
multi-chip sharding matrix runs without TPU hardware (the driver separately
dry-run-compiles the multi-chip path; real-chip perf is bench.py's job)."""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# sitecustomize (the TPU plugin loader) imports jax before this file runs, so
# the env var alone is too late — override via config before backends init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
