"""Test harness: force CPU backend with 8 virtual devices so the full
multi-chip sharding matrix runs without TPU hardware (the driver separately
dry-run-compiles the multi-chip path; real-chip perf is bench.py's job)."""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# sitecustomize (the TPU plugin loader) imports jax before this file runs, so
# the env var alone is too late — override via config before backends init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The tier-1 suite is XLA-compile-bound (hundreds of distinct engine
# geometries on one core); backend optimization buys runtime we don't
# measure here — correctness is integer-exact at any opt level, and perf
# is bench.py/exp.py's job on hardware (neither loads this file). Halves
# the compile bill. DINT_TEST_FULL_OPT=1 restores full optimization.
if os.environ.get("DINT_TEST_FULL_OPT", "0") in ("", "0"):
    jax.config.update("jax_disable_most_optimizations", True)

# NOTE: do NOT enable jax_compilation_cache_dir here — XLA:CPU executable
# deserialization segfaults this suite (donated buffers + 8 virtual
# devices, jax 0.4.37): a second jit object loading an executable the
# same process just serialized corrupts memory. Compile sharing is done
# in-process instead (dint_tpu.serve.engine.cached_runner).

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
