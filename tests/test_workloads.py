"""Workload-generator determinism and shape pins (clients/workloads.py).
The dintscan helpers feed exp.py artifacts and the StoreClient ladder —
given the same seed they must reproduce bit-for-bit, or a hardware A/B
is not replayable."""
import numpy as np
import pytest

from dint_tpu.clients import workloads as wl


def test_scan_lengths_bounds_and_determinism():
    a = wl.scan_lengths(np.random.default_rng(7), 10_000, 16)
    b = wl.scan_lengths(np.random.default_rng(7), 10_000, 16)
    assert np.array_equal(a, b)
    assert a.dtype == np.uint32
    assert a.min() >= 1 and a.max() <= 16
    # uniform over [1, max]: every length shows up at this sample size
    assert set(np.unique(a)) == set(range(1, 17))
    c = wl.scan_lengths(np.random.default_rng(7), 1000, 8, min_len=4)
    assert c.min() >= 4 and c.max() <= 8
    with pytest.raises(AssertionError):
        wl.scan_lengths(np.random.default_rng(0), 10, 4, min_len=5)


def test_zipf_scan_starts_matches_zipf_keys():
    # rank == key-id alignment with the point workloads: the scan skew
    # touches the same hot head the caches serve
    a = wl.zipf_scan_starts(np.random.default_rng(3), 5_000, 1_000)
    b = wl.zipf_keys(np.random.default_rng(3), 5_000, 1_000)
    assert np.array_equal(a, b)
    assert a.min() >= 1 and a.max() <= 1_000
    # hot head: key 1 strictly more popular than the median key
    assert (a == 1).sum() > (a == 500).sum()


def test_ycsb_e_ops_deterministic_shape():
    r1, r2 = np.random.default_rng(11), np.random.default_rng(11)
    s1, k1, l1 = wl.ycsb_e_ops(r1, 8_000, 10_000)
    s2, k2, l2 = wl.ycsb_e_ops(r2, 8_000, 10_000)
    assert np.array_equal(s1, s2)
    assert np.array_equal(k1, k2)
    assert np.array_equal(l1, l2)
    assert s1.dtype == bool and l1.dtype == np.uint32
    # YCSB-E mix: 95% scans, lengths uniform in [1, 100], zero on writes
    frac = s1.mean()
    assert 0.93 < frac < 0.97
    assert (l1[~s1] == 0).all()
    assert l1[s1].min() >= 1 and l1[s1].max() <= wl.YCSB_E_MAX_SCAN
    assert k1.min() >= 1 and k1.max() <= 10_000


def test_ycsb_e_ops_scan_frac_knob():
    s, _, lens = wl.ycsb_e_ops(np.random.default_rng(5), 4_000, 1_000,
                               scan_frac=0.05, max_len=8)
    assert 0.03 < s.mean() < 0.08
    assert lens[s].max() <= 8
    s0, _, l0 = wl.ycsb_e_ops(np.random.default_rng(5), 1_000, 1_000,
                              scan_frac=0.0)
    assert not s0.any() and (l0 == 0).all()
