"""dintmesh (round 18): the whole (hosts x chips) mesh as ONE open-loop
transactional service (serve/mesh.py + the serve=True cohort form of
parallel/multihost_sb.py).

The contract under test, per acceptance criteria:
  * the mesh serving loop — per-host ingestion and NEWEST-FIRST
    shedding, ONE global SLO controller in per-device units, mesh-wide
    width switches at drain boundaries — is deterministic end-to-end
    under a VirtualClock on the 8-device virtual mesh;
  * the lane ledger closes across the mesh: occupancy + padded ==
    width x steps x devices, the per-host shed tallies mirror the
    device counter exactly, and per-host admission sums to the global
    report;
  * the double-buffered (overlap=True) serving plane produces the SAME
    service — admitted/committed/width trajectory — as the unoverlapped
    plane, with every prefetched lane accounted
    (route_prefetch_lanes == lock_requests);
  * the steady state allocates nothing: donated carry ping-pong only,
    overlap included;
  * tools/dintserve.py drives the mesh engine (--mesh HxC) under
    --virtual with unchanged exit-gate semantics.
"""
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from dint_tpu.parallel import multihost_sb as mh
from dint_tpu.serve import (ControllerCfg, MeshServeEngine, ServiceModel,
                            VirtualClock, constant_schedule,
                            poisson_schedule)

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KEY = jax.random.PRNGKey

H, C = 4, 2
D = H * C
N = 256
W, CPB = 16, 2


def _engine(overlap=False, widths=(8, W), mesh_shape=(H, C), seed=0):
    return MeshServeEngine(N, mesh_shape=mesh_shape,
                           cfg=ControllerCfg(widths=widths),
                           model=ServiceModel(),
                           cohorts_per_block=CPB, clock=VirtualClock(),
                           monitor=True, seed=seed, overlap=overlap)


def _identities(rep):
    assert rep["offered"] == rep["admitted"] + rep["shed"]
    c = rep["counters"]
    assert c["serve_occupancy_lanes"] == rep["admitted"] == rep["attempted"]
    assert c["serve_shed_lanes"] == rep["shed"]
    served = sum(int(w) * n for w, n in rep["steps_by_width"].items())
    # the mesh identity: D cohorts of width w serve on EVERY step
    assert c["serve_occupancy_lanes"] + c["serve_padded_lanes"] \
        == served * D
    # per-host admission sums to the global report
    assert sum(h["admitted"] for h in rep["per_host"]) == rep["admitted"]
    assert sum(h["shed"] for h in rep["per_host"]) == rep["shed"]
    assert c["route_ici_lanes"] + c["route_dcn_lanes"] == \
        c["lock_requests"] + c["install_writes"]


def test_mesh_engine_deterministic_and_ledger_closes():
    """The whole mesh serving loop is a pure function of (schedule,
    seed) under the VirtualClock — two runs give the SAME snapshot,
    field for field — and the mesh-wide lane ledger closes exactly."""
    reps = []
    for _ in range(2):
        eng = _engine()
        eng.run(poisson_schedule(300_000.0, 0.005, seed=3))
        eng.close()
        reps.append(eng.snapshot())
    assert reps[0] == reps[1]
    rep = reps[0]
    assert rep["mesh"] == {"n_hosts": H, "n_ici": C, "hierarchical": True,
                           "overlap": False}
    assert rep["offered"] > 0 and rep["committed"] > 0
    _identities(rep)
    # round-robin ingest: every host served arrivals
    assert all(h["admitted"] > 0 for h in rep["per_host"])


def test_mesh_engine_width_switch_is_mesh_coordinated():
    """A saturating burst drives the ONE global controller to the knee
    and back; each switch passes through _detach's drain — the
    recompile point that is the mesh-wide barrier — and the ledger
    still closes over the whole trajectory, sheds included."""
    eng = _engine()
    eng.run(constant_schedule(6_000_000.0, 0.004))
    eng.close()
    rep = eng.snapshot()
    ctl = rep["controller"]
    assert ctl["lanes_scale"] == D              # per-device units
    assert [w for _, w in ctl["switches"]].count(W) >= 1   # hit the knee
    assert rep["steps_by_width"][str(W)] > 0
    assert rep["shed"] > 0                      # admission did its job
    _identities(rep)
    # newest-first shedding is per host: every host's bound was enforced
    assert all(h["shed"] > 0 for h in rep["per_host"])


def test_mesh_engine_overlap_serves_identically():
    """The overlap A/B the PERF.md round-18 decision rule rests on: the
    double-buffered plane must change the SCHEDULE, never the service.
    Same arrivals => same admitted/shed/committed/width trajectory and
    same lock/install ledger; the only deltas are the overlap flag, the
    prefetch counter (== lock_requests), and the one extra drain step
    the in-flight cohort costs."""
    reps = {}
    for overlap in (False, True):
        eng = _engine(overlap=overlap)
        eng.run(poisson_schedule(400_000.0, 0.004, seed=7))
        eng.close()
        reps[overlap] = eng.snapshot()
    a, b = reps[False], reps[True]
    for k in ("offered", "admitted", "shed", "attempted", "committed",
              "blocks", "steps_by_width", "controller", "per_host"):
        assert a[k] == b[k], k
    assert a["mesh"]["overlap"] is False and b["mesh"]["overlap"] is True
    ca, cb = a["counters"], b["counters"]
    assert ca["route_prefetch_lanes"] == 0
    assert cb["route_prefetch_lanes"] == cb["lock_requests"] > 0
    for k in ("lock_requests", "install_writes", "txn_committed",
              "serve_occupancy_lanes", "serve_shed_lanes"):
        assert ca[k] == cb[k], k
    _identities(b)


def test_mesh_serve_zero_alloc_steady_state():
    """The round-17 zero-allocation pin survives the mesh AND the
    double buffer: after warmup every overlapped serve block runs
    through donated buffers — constant live-array census, the big
    sharded table leaf ping-pongs between at most two buffers."""
    mesh = mh.make_mesh_2d(H, C)
    # monitor=True matches the engine tests' config exactly, so the
    # builder memo shares the compile (and the census covers the
    # counter plane's carry leaves too)
    run, init, drain = mh.build_multihost_sb_runner(
        mesh, N, w=W, cohorts_per_block=CPB, monitor=True, serve=True,
        overlap=True)
    carry = init(mh.create_multihost_sb(mesh, N))
    occ = np.full((H, C, CPB), W, np.int32)
    shed = np.zeros((H, C, CPB), np.int32)

    def big_ptrs(c):
        leaf = max(jax.tree_util.tree_leaves(c), key=lambda x: x.nbytes)
        return tuple(s.data.unsafe_buffer_pointer()
                     for s in leaf.addressable_shards)

    for i in range(3):                          # warmup: compile + settle
        carry, s = run(carry, jax.random.fold_in(KEY(1), i), occ, shed)
    np.asarray(s)                               # sync
    base = len(jax.live_arrays())

    counts, ptrs = [], set()
    for i in range(3, 9):
        carry, s = run(carry, jax.random.fold_in(KEY(1), i), occ, shed)
        np.asarray(s)
        counts.append(len(jax.live_arrays()))
        ptrs.add(big_ptrs(carry))
    assert counts == [base] * 6, counts         # zero net allocations
    assert len(ptrs) <= 2, ptrs                 # donated ping-pong only
    drain(carry)


@pytest.mark.slow
def test_mesh_engine_3_host_reference_topology():
    """The reference's 3-machine shape serves too (every host holds a
    copy of every shard at H == replication factor); slow-marked per
    the tier-1 budget rule — the 3x2 geometry stays statically covered
    by the @h3 cost targets."""
    eng = _engine(mesh_shape=(3, 2))
    eng.run(poisson_schedule(200_000.0, 0.01, seed=1))
    eng.close()
    rep = eng.snapshot()
    assert rep["mesh"]["n_hosts"] == 3 and rep["committed"] > 0
    c = rep["counters"]
    served = sum(int(w) * n for w, n in rep["steps_by_width"].items())
    assert c["serve_occupancy_lanes"] + c["serve_padded_lanes"] \
        == served * 6
    assert sum(h["admitted"] for h in rep["per_host"]) == rep["admitted"]


@pytest.mark.slow
def test_mesh_engine_soak_reentrant_identities():
    """Soak: three back-to-back schedules (ramp, overload, trickle) on
    one long-lived OVERLAPPED mesh engine; the mesh-wide lane ledger
    must still close exactly across re-attaches."""
    eng = _engine(overlap=True, seed=2)
    start = 0.0
    for r, (rate, win) in enumerate([(150_000.0, 0.01),
                                     (6_000_000.0, 0.003),
                                     (30_000.0, 0.01)]):
        rep = eng.run(poisson_schedule(rate, win, seed=r, start_s=start))
        start = rep["elapsed_s"]
    eng.close()
    rep = eng.snapshot()
    _identities(rep)
    assert rep["shed"] > 0 and rep["committed"] > 0
    assert len(rep["controller"]["switches"]) >= 2
    assert rep["counters"]["route_prefetch_lanes"] == \
        rep["counters"]["lock_requests"] > 0


# -------------------------------------------------------------------- CLI


def _cli(*args, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintserve.py"),
         *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def test_dintserve_cli_mesh_simulate_and_describe():
    """simulate --mesh rehearses the mesh plane (lanes_scale = H*C:
    8 devices absorb 8x the rate before the controller moves) and
    describe names the mesh waves and targets."""
    a = _cli("simulate", "--rate", "20000000", "--window", "0.004",
             "--mesh", "4x2", "--json")
    assert a.returncode == 0, a.stderr
    out = json.loads(a.stdout)
    assert out["mesh"] == [4, 2]
    b = _cli("simulate", "--rate", "20000000", "--window", "0.004",
             "--json")
    ref = json.loads(b.stdout)
    # same offered load looks 8x lighter per device: the mesh run's
    # width trajectory must stay at or below the single-device one
    assert out["final_width"] <= ref["final_width"]
    c = _cli("describe")
    assert c.returncode == 0, c.stderr
    for want in ("route_prefetch_lanes", "multihost_sb/serve@overlap",
                 "dint.multihost_sb.route_prefetch"):
        assert want in c.stdout, want


@pytest.mark.slow
def test_dintserve_cli_mesh_virtual_run():
    c = _cli("run", "--mesh", "4x2", "--size", str(N), "--rate", "200000",
             "--window", "0.01", "--widths", f"8,{W}", "--cpb",
             str(CPB), "--virtual", "--json")
    assert c.returncode == 0, c.stderr          # SLO gate: met -> exit 0
    rep = json.loads(c.stdout.strip().splitlines()[-1])
    assert rep["mesh"]["n_hosts"] == 4 and rep["mesh"]["n_ici"] == 2
    assert rep["offered"] == rep["admitted"] + rep["shed"] > 0
    assert rep["slo_met"] is True
    served = sum(int(w) * n for w, n in rep["steps_by_width"].items())
    assert rep["counters"]["serve_occupancy_lanes"] + \
        rep["counters"]["serve_padded_lanes"] == served * 8


def test_mesh_engine_resolves_geometry_knobs_from_plan():
    """ISSUE 17: hierarchical/overlap left unset resolve from the
    pinned plan's multihost_serve workload (hierarchical ON / overlap
    OFF pending the pre-registered hardware A/B) and the snapshot
    carries the plan provenance alongside the mesh geometry."""
    eng = MeshServeEngine(N, mesh_shape=(H, C),
                          cfg=ControllerCfg(widths=(8, W)),
                          model=ServiceModel(),
                          cohorts_per_block=CPB, clock=VirtualClock(),
                          monitor=True, seed=0)
    try:
        eng.run(constant_schedule(100_000.0, 0.004))
    finally:
        eng.close()
    rep = eng.snapshot()
    assert rep["mesh"] == {"n_hosts": H, "n_ici": C,
                           "hierarchical": True, "overlap": False}
    assert rep["plan"]["source"].endswith("PLAN.json")
    assert rep["plan"]["overridden"] == []
