"""Microbenchmark clients: store/2PL/FaSST/log replay + stats contract."""
import numpy as np

from dint_tpu.clients import micro, workloads as wl
from dint_tpu.stats import LatencyReservoir, MetricBlock, Recorder


def test_store_client_mixes(rng):
    for frac in (1.0, 0.5):   # parallel / contention
        c = micro.StoreClient.populated(1000, width=512, read_frac=frac)
        for _ in range(3):
            ok = c.run_wave(rng, 512)
            assert ok == 512              # GET/SET on populated keys all succeed
        blk = c.rec.block(elapsed_s=1.0)
        assert blk.throughput == 3 * 512
        assert blk.goodput == 3 * 512
        assert blk.p99_us >= blk.p50_us > 0


def test_store_client_scan_mix(rng):
    """YCSB-E-shaped wave through the scan-threaded step: every scan
    lane answers VAL (run fresh each rebuild_every waves), counts obey
    len/scan_max clipping (asserted inside run_wave), goodput counts
    scan rows' lanes like any other reply."""
    c = micro.StoreClient.populated(500, width=256, read_frac=0.5,
                                    key_dist="zipfian", use_scan=True,
                                    scan_frac=0.3, scan_max=8,
                                    rebuild_every=2)
    assert c.use_scan and c.scan_frac == 0.3
    for _ in range(4):
        ok = c.run_wave(rng, 256)
        assert ok == 256
    blk = c.rec.block(elapsed_s=1.0)
    assert blk.goodput == 4 * 256


def test_store_client_scan_stale_rebuilds_and_retries(rng):
    """The in-doubt discipline: a stale overlay (tiny delta_cap, write-
    heavy mix) makes scans RETRY; the client rebuilds the run mid-wave
    and re-sends exactly those lanes, which must then answer VAL —
    run_wave asserts the contract, we pin that the path actually ran."""
    c = micro.StoreClient.populated(300, width=128, read_frac=0.0,
                                    use_scan=True, scan_frac=0.5,
                                    scan_max=4, delta_cap=4,
                                    rebuild_every=10_000)
    rebuilds = []
    orig = c._rebuild
    c._rebuild = lambda s: (rebuilds.append(1), orig(s))[1]
    for _ in range(3):
        c.run_wave(rng, 128)
    # rebuild_every is effectively off: every rebuild here was the
    # RETRY-recovery action
    assert rebuilds, "stale overlay never exercised the retry path"


def test_log_client(rng):
    c = micro.LogClient(width=256, lanes=4, capacity=1 << 10)
    for _ in range(4):
        c.run_wave(rng, 256)
    heads = np.asarray(c.state.head)
    assert heads.sum() == 4 * 256
    assert (heads == 256).all()          # round-robin balance


def test_lock2pl_client_conflicts(rng):
    trace = wl.lock_trace(rng, n_txns=200, key_range=64)   # heavy conflicts
    c = micro.Lock2PLClient(trace, n_slots=1 << 10, cohort=64, width=1024)
    total_committed = 0
    for _ in range(5):
        total_committed += c.run_round()
        # every granted lock was released in the same round
        assert np.asarray(c.state.num_sh).sum() == 0
        assert np.asarray(c.state.num_ex).sum() == 0
    assert 0 < total_committed <= c.rec.attempted
    assert c.rec.committed == total_committed
    blk = c.rec.block(1.0)
    assert 0.0 < blk.abort_rate < 1.0     # contention must cause some aborts


def test_lock2pl_no_conflict_commits_all(rng):
    # one txn per round, huge keyspace: no conflicts -> everything commits
    trace = wl.lock_trace(rng, n_txns=50, key_range=1 << 20)
    c = micro.Lock2PLClient(trace, n_slots=1 << 20, cohort=1, width=64)
    for _ in range(5):
        c.run_round()
    assert c.rec.committed == c.rec.attempted


def test_fasst_client(rng):
    # reference trace envelope: key range 4800 (lock_2pl/caladan/trace_init.sh)
    trace = wl.lock_trace(rng, n_txns=200, key_range=4800, read_prop=0.5)
    c = micro.FasstClient(trace, n_slots=1 << 16, cohort=64, width=1024)
    total = 0
    for _ in range(5):
        total += c.run_round()
        assert not np.asarray(c.state.locked).any()   # all locks resolved
    assert 0 < total < c.rec.attempted  # conflicts abort some, not all
    # committed writes bumped versions
    assert np.asarray(c.state.ver).sum() > 0


def test_fasst_client_validation_abort():
    # two txns, same single key: one reads it, one writes it. The writer's
    # wave-1 lock makes the reader's validation re-read see the lock bit ->
    # reader aborts (reference lock_fasst/caladan/client.cc:199-215).
    key = np.array([7], np.int64)
    trace = [(key, np.array([True])), (key, np.array([False]))]
    c = micro.FasstClient(trace, n_slots=1 << 10, cohort=2, width=64)
    committed = c.run_round()
    assert committed == 1        # writer commits, reader fails validation


def test_latency_reservoir_downsampling():
    r = LatencyReservoir(cap=100, seed=0)
    r.add(np.full(50, 10.0))
    assert r.n_kept == 50
    r.add(np.full(500, 20.0))
    assert r.n_kept == 100
    assert r.n_seen == 550
    p = r.percentiles()
    assert 10.0 <= p["p50"] <= 20.0


def test_metric_block_format():
    rec = Recorder()
    rec.record(100, 90, np.linspace(10, 1000, 100), device_s=0.5)
    blk = rec.block(elapsed_s=2.0)
    assert blk.throughput == 50.0
    assert blk.goodput == 45.0
    assert abs(blk.abort_rate - 0.1) < 1e-9
    assert blk.device_duty == 0.25
    assert "median" in blk.format()
    d = blk.to_dict()
    for k in ("throughput", "goodput", "abort_rate", "avg_us", "p50_us",
              "p99_us", "p999_us", "device_duty"):
        assert k in d


def test_stat_clock_phases():
    from dint_tpu.stats import StatClock, Window
    c = StatClock(Window(warmup_s=0.0, measure_s=0.05))
    assert c.tick() == "measure"
    import time
    time.sleep(0.06)
    assert c.tick() == "done"
    assert c.measured_s > 0
