"""Sort-free dense SmallBank pipeline: invariants + contention response."""
import jax
import numpy as np

from dint_tpu.engines import smallbank_dense as sd
from dint_tpu.tables import log as logring


def _run_blocks(n_accounts, w, blocks, cohorts_per_block=2, seed=0, **kw):
    db = sd.create(n_accounts)
    base = int(np.asarray(sd.total_balance(db)))
    run, init, drain = sd.build_pipelined_runner(
        n_accounts, w=w, cohorts_per_block=cohorts_per_block, **kw)
    carry = init(db)
    key = jax.random.PRNGKey(seed)
    total = np.zeros(sd.N_STATS, np.int64)
    for i in range(blocks):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    db, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    return db, total, base


def test_invariants_small():
    db, total, base = _run_blocks(n_accounts=512, w=256, blocks=3)

    attempted = int(total[sd.STAT_ATTEMPTED])
    committed = int(total[sd.STAT_COMMITTED])
    assert attempted == 3 * 2 * 256
    assert 0 < committed <= attempted
    assert committed + total[sd.STAT_AB_LOCK] + total[sd.STAT_AB_LOGIC] \
        == attempted
    assert int(total[sd.STAT_MAGIC_BAD]) == 0

    # balance conservation: table delta == sum of committed deltas (mod 2^32)
    final = int(np.asarray(sd.total_balance(db)))
    want = int(total[sd.STAT_BAL_DELTA])
    assert (final - base) % (1 << 32) == want % (1 << 32)

    # all locks expired after drain: no slot stamped at the final step
    last = int(np.asarray(db.step)) - 1
    assert not (np.asarray(db.x_step) == last).any()
    assert not (np.asarray(db.s_step) == last).any()

    # log x3: identical replica slots, nonzero depth
    r0 = np.asarray(logring.replica_entries(db.log, 0))
    assert np.array_equal(r0, np.asarray(logring.replica_entries(db.log, 1)))
    assert np.array_equal(r0, np.asarray(logring.replica_entries(db.log, 2)))
    assert np.asarray(db.log.head).sum() > 0

    # sentinel row untouched
    assert int(np.asarray(db.bal)[-1]) == 0


def test_abort_rate_responds_to_contention():
    _, hot, _ = _run_blocks(n_accounts=64, w=512, blocks=2, seed=1)
    _, cold, _ = _run_blocks(n_accounts=1 << 16, w=64, blocks=2, seed=1)
    hot_rate = hot[sd.STAT_AB_LOCK] / hot[sd.STAT_ATTEMPTED]
    cold_rate = cold[sd.STAT_AB_LOCK] / cold[sd.STAT_ATTEMPTED]
    assert hot_rate > 0.2, hot_rate
    assert cold_rate < 0.05, cold_rate


def test_cross_cohort_lock_conflicts_exist():
    """Locks held across the step boundary: at w=1 there is NO intra-cohort
    arbitration, so every lock abort here is a cross-cohort conflict with
    the previous cohort's still-held locks (the generic per-cohort engine
    cannot express this; a release-before-acquire bug would make this 0)."""
    _, total, _ = _run_blocks(n_accounts=2, w=1, blocks=4,
                              cohorts_per_block=16, seed=2,
                              hot_frac=1.0, hot_prob=1.0)
    assert int(total[sd.STAT_AB_LOCK]) > 0


def test_shared_locks_do_not_conflict():
    """A Balance-only world (all S locks) must never lock-abort, even with
    every txn on the same tiny hot set."""
    mix = np.array([0, 100, 0, 0, 0, 0], np.float64) / 100.0
    _, total, _ = _run_blocks(n_accounts=8, w=128, blocks=3, seed=3,
                              hot_frac=1.0, hot_prob=1.0, mix=mix)
    assert int(total[sd.STAT_AB_LOCK]) == 0
    assert int(total[sd.STAT_COMMITTED]) == int(total[sd.STAT_ATTEMPTED])

def test_hashed_lock_slots_conserve_balance(monkeypatch):
    """The multiply-shift hashed lock table (engaged at reference scale,
    where 48M rows exceed the slot cap) may conflate rows into shared
    slots — that adds false no-wait rejects but must NEVER corrupt
    balances. Force hashing at test scale by shrinking the cap."""
    monkeypatch.setattr(sd, "MAX_LOCK_SLOTS", 256)
    n_acc = 4096                      # m1 = 8193 rows >> 256 slots
    db = sd.create(n_acc)
    assert db.lock_slots == 256       # hashing engaged
    base = int(np.asarray(sd.total_balance(db)))
    run, init, drain = sd.build_pipelined_runner(n_acc, w=256,
                                                 cohorts_per_block=2)
    carry = init(db)
    key = jax.random.PRNGKey(7)
    total = np.zeros(sd.N_STATS, np.int64)
    for i in range(3):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    db, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)

    attempted = int(total[sd.STAT_ATTEMPTED])
    committed = int(total[sd.STAT_COMMITTED])
    assert committed + int(total[sd.STAT_AB_LOCK]) \
        + int(total[sd.STAT_AB_LOGIC]) == attempted
    # heavy conflation (16 rows/slot avg on the hot set) must still commit
    # some txns and conserve every cent
    assert committed > 0
    final = int(np.asarray(sd.total_balance(db)))
    assert (final - base) % (1 << 32) == \
        int(total[sd.STAT_BAL_DELTA]) % (1 << 32)
