"""L6 sweep driver: structural smoke over the quick TATP sweep."""
import json
import os

import exp


def test_quick_tatp_sweep(tmp_path):
    out = str(tmp_path / "res")
    results = exp.run_all(out, window_s=0.4, quick=True, only="tatp")

    names = sorted(results)
    assert any(n.startswith("tatp_closed_w") for n in names)
    assert any(n.startswith("tatp_open_") for n in names)
    # the wire + colocate points are gated in by `only in name` too
    assert "tatp_wire" in names
    assert any(n.startswith("tatp_colocate_c") for n in names)

    measured = 0
    for name, block in results.items():
        # a point may legitimately be an error artifact (run_point's
        # record-and-continue fault tolerance — e.g. a loaded CI box
        # starving a core-pinned colocate point); measured points must
        # carry the full reference metric contract
        if "error" in block:
            continue
        measured += 1
        for field in ("throughput", "goodput", "abort_rate", "avg_us",
                      "p50_us", "p99_us", "p999_us"):
            assert field in block, (name, field)
        assert block["goodput"] > 0
        assert block["p99_us"] >= block["p50_us"] >= 0
        if name.startswith(("tatp_closed", "tatp_open")):
            # abort breakdown travels with every pipeline TATP point
            for field in ("ab_lock", "ab_missing", "ab_validate"):
                assert field in block, (name, field)
        # one JSON file per config, written the moment the point landed
        with open(os.path.join(out, f"{name}.json")) as f:
            assert json.load(f) == block
    # the closed/open pipeline points must actually measure (they carry
    # the sweep's anchor); only the wire/colocate extras may error out
    pipeline_pts = [n for n in names
                    if n.startswith(("tatp_closed", "tatp_open"))]
    assert all("error" not in results[n] for n in pipeline_pts), pipeline_pts
    assert measured >= len(pipeline_pts)

    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    assert sorted(summary["configs"]) == names

    # open-loop points record offered vs target load
    op = next(v for k, v in results.items() if k.startswith("tatp_open_"))
    assert op["mode"] == "open"
    assert op["target_rate"] > 0 and op["offered_rate"] > 0
