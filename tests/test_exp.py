"""L6 sweep driver: structural smoke over the quick TATP sweep."""
import json
import os

import pytest

import exp


@pytest.mark.slow  # ~48s of compiles on the 1-core tier-1 box
def test_quick_tatp_sweep(tmp_path):
    out = str(tmp_path / "res")
    results = exp.run_all(out, window_s=0.4, quick=True, only="tatp")

    names = sorted(results)
    assert any(n.startswith("tatp_closed_w") for n in names)
    assert any(n.startswith("tatp_open_") for n in names)
    # the wire + colocate points are gated in by `only in name` too
    assert "tatp_wire" in names
    assert any(n.startswith("tatp_colocate_c") for n in names)

    measured = 0
    for name, block in results.items():
        # a point may legitimately be an error artifact (run_point's
        # record-and-continue fault tolerance — e.g. a loaded CI box
        # starving a core-pinned colocate point); measured points must
        # carry the full reference metric contract
        if "error" in block:
            continue
        measured += 1
        for field in ("throughput", "goodput", "abort_rate", "avg_us",
                      "p50_us", "p99_us", "p999_us"):
            assert field in block, (name, field)
        assert block["goodput"] > 0
        assert block["p99_us"] >= block["p50_us"] >= 0
        if name.startswith(("tatp_closed", "tatp_open")):
            # abort breakdown travels with every pipeline TATP point
            for field in ("ab_lock", "ab_missing", "ab_validate"):
                assert field in block, (name, field)
        # one JSON file per config, written the moment the point landed
        with open(os.path.join(out, f"{name}.json")) as f:
            assert json.load(f) == block
    # the closed/open pipeline points must actually measure (they carry
    # the sweep's anchor); only the wire/colocate extras may error out
    pipeline_pts = [n for n in names
                    if n.startswith(("tatp_closed", "tatp_open"))]
    assert all("error" not in results[n] for n in pipeline_pts), pipeline_pts
    assert measured >= len(pipeline_pts)

    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    assert sorted(summary["configs"]) == names

    # open-loop points record offered vs target load
    op = next(v for k, v in results.items() if k.startswith("tatp_open_"))
    assert op["mode"] == "open"
    assert op["target_rate"] > 0 and op["offered_rate"] > 0


@pytest.mark.slow
def test_quick_serve_mesh_sweep(tmp_path):
    """--only serve_mesh is a preset: it drives the mesh serving plane
    ladder (saturation probe + rate points with mesh/per-host extras)
    and SUPPRESSES the single-device serve legs the bidirectional
    substring filter would otherwise fire."""
    out = str(tmp_path / "res")
    results = exp.run_all(out, window_s=0.3, quick=True, only="serve_mesh")

    names = sorted(results)
    assert "serve_mesh_sat" in names
    assert not any(n.startswith(("serve_tatp", "serve_smallbank"))
                   for n in names), names
    blk = results["serve_mesh_sat"]
    assert "error" not in blk, blk
    assert blk["mesh"]["n_hosts"] >= 3 and blk["mesh"]["n_ici"] >= 1
    assert blk["offered"] == blk["admitted"] + blk["shed"]
    assert sum(h["admitted"] for h in blk["per_host"]) == blk["admitted"]
    sc = blk["serve_counters"]
    assert sc["serve_occupancy_lanes"] == blk["admitted"]
    assert "route_prefetch_lanes" in sc
    assert blk["controller"]["lanes_scale"] == \
        blk["mesh"]["n_hosts"] * blk["mesh"]["n_ici"]
    # the ladder ran past the anchor
    assert any(n.startswith("serve_mesh_r") for n in names)
