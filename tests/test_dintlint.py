"""dintlint: each pass proven live on a deliberately-broken mini step,
silent on the matching safe idiom, suppressible by an allowlist entry —
plus the standing tier-1 gate: the full pass suite over every registered
engine/sharded target must report zero unsuppressed errors.

The broken fixtures are the bug classes the passes exist for:
  * a colliding scatter (no unique_indices, no segment mask),
  * an aliased Pallas kernel whose donated input is read afterwards (and a
    double-aliased one),
  * a jitted call whose donated operand stays live,
  * host callbacks / Python branching on traced data in a "step",
  * a packed stamp cast to int32 and compared signed,
  * ppermutes whose permutation disagrees with the mesh.
"""
import functools
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

import dint_tpu.parallel  # noqa: F401 — installs the jax.shard_map shim
from dint_tpu import analysis
from dint_tpu.analysis import allowlist as al
from dint_tpu.analysis import core
from dint_tpu.ops import segments

S = jax.ShapeDtypeStruct
U32 = jnp.uint32
I32 = jnp.int32

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_pass(name, fn, args, mesh_axes=(), protocol=("certified",)):
    tr = core.trace_target(f"fixture/{name}", fn, args, mesh_axes=mesh_axes,
                           protocol=protocol)
    return analysis.PASSES[name](tr)


def codes(findings, severity=None):
    return {f.code for f in findings
            if severity is None or f.severity == severity}


# ------------------------------------------------------------ scatter_race


def test_scatter_race_fires_on_colliding_scatter():
    def bad(tab, idx, v):
        return tab.at[idx].set(v)       # arbitrary idx: duplicate = race

    fs = run_pass("scatter_race", bad,
                  (S((64,), U32), S((8,), I32), S((8,), U32)))
    assert "nonunique-scatter" in codes(fs, "error")


def test_scatter_race_accepts_declared_unique_and_segment_masked():
    def ok_unique(tab, idx, v):
        return tab.at[idx].set(v, mode="drop", unique_indices=True)

    def ok_segmented(tab, kh, kl, v):
        sb = segments.sort_batch(kh, kl)
        return segments.scatter_rows(tab, sb.key_lo.astype(I32), v[sb.perm],
                                     sb.last)   # one writer per key

    fs1 = run_pass("scatter_race", ok_unique,
                   (S((64,), U32), S((8,), I32), S((8,), U32)))
    fs2 = run_pass("scatter_race", ok_segmented,
                   (S((64,), U32), S((8,), U32), S((8,), U32), S((8,), U32)))
    assert not codes(fs1, "error") and not codes(fs2, "error")


# ---------------------------------------------------------------- aliasing


def _inc_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] + 1


def test_aliasing_pallas_use_after_donate():
    def bad(x):
        y = pl.pallas_call(_inc_kernel, out_shape=S(x.shape, x.dtype),
                           input_output_aliases={0: 0}, interpret=True)(x)
        return y + x        # x was updated in place: torn read

    def ok(x):
        y = pl.pallas_call(_inc_kernel, out_shape=S(x.shape, x.dtype),
                           input_output_aliases={0: 0}, interpret=True)(x)
        return y + 1        # only the kernel's output is used

    assert "use-after-donate" in codes(run_pass("aliasing", bad,
                                                (S((8,), U32),)), "error")
    assert not codes(run_pass("aliasing", ok, (S((8,), U32),)), "error")


def test_aliasing_double_aliased_kernel():
    def _add_kernel(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] + y_ref[...]

    def bad(x, y):
        return pl.pallas_call(_add_kernel, out_shape=S(x.shape, x.dtype),
                              input_output_aliases={0: 0, 1: 0},
                              interpret=True)(x, y)

    fs = run_pass("aliasing", bad, (S((8,), U32), S((8,), U32)))
    assert "double-alias-output" in codes(fs, "error")


def test_aliasing_pjit_donated_operand_still_live():
    @functools.partial(jax.jit, donate_argnums=0)
    def g(x):
        return x + 1

    def bad(x):
        y = g(x)
        return y + x

    fs = run_pass("aliasing", bad, (S((8,), jnp.float32),))
    assert "use-after-donate" in codes(fs, "error")


# ------------------------------------------------------------------ purity


def test_purity_flags_callbacks_and_debug_print():
    def bad_cb(x):
        return jax.pure_callback(lambda a: np.asarray(a),
                                 S((), jnp.float32), x.sum())

    def warn_dbg(x):
        jax.debug.print("x={x}", x=x.sum())
        return x * 2

    assert "pure_callback" in codes(run_pass("purity", bad_cb,
                                             (S((8,), jnp.float32),)),
                                    "error")
    fs = run_pass("purity", warn_dbg, (S((8,), jnp.float32),))
    assert "debug_callback" in codes(fs, "warning") and not codes(fs, "error")


def test_purity_flags_python_branch_on_traced_data():
    def bad(x):
        if x.sum() > 0:     # concretizes a tracer: host sync + retrace
            return x
        return -x

    fs = run_pass("purity", bad, (S((8,), jnp.float32),))
    assert "untraceable" in codes(fs, "error")


# ------------------------------------------------------------ u64_overflow


def test_u64_flags_stamp_sign_drift_and_signed_compare():
    def bad(step, lane):
        packed = ((step << U32(18)) | lane).astype(I32)
        return packed < 0

    fs = run_pass("u64_overflow", bad, (S((8,), U32), S((8,), U32)))
    assert {"stamp-sign-drift", "signed-stamp-compare"} <= codes(fs, "error")


def test_u64_accepts_masked_convert():
    def ok(step, lane):
        # masked below 2^31 before the convert: the repo's bucket-index idiom
        packed = (((step << U32(18)) | lane) & U32(0x3FFFF)).astype(I32)
        return packed < 0

    assert not run_pass("u64_overflow", ok, (S((8,), U32), S((8,), U32)))


# ------------------------------------------------------ shard_consistency


def _mesh4():
    from dint_tpu.parallel.sharded import make_mesh
    assert len(jax.devices()) >= 4
    return make_mesh(4)


def test_shard_consistency_flags_bad_perms():
    mesh = _mesh4()

    def dup_dest(x):
        return jax.lax.ppermute(x, "shard", [(0, 1), (2, 1)])

    def out_of_range(x):
        return jax.lax.ppermute(x, "shard", [(0, 7)])

    def ok(x):
        return jax.lax.ppermute(x, "shard",
                                [(i, (i + 1) % 4) for i in range(4)])

    def sm(body):
        return jax.shard_map(body, mesh=mesh, in_specs=P("shard"),
                             out_specs=P("shard"))

    arg = (S((8, 4), jnp.float32),)
    assert "perm-duplicate-dest" in codes(
        run_pass("shard_consistency", sm(dup_dest), arg), "error")
    assert "perm-out-of-range" in codes(
        run_pass("shard_consistency", sm(out_of_range), arg), "error")
    assert not codes(run_pass("shard_consistency", sm(ok), arg), "error")


# ---------------------------------------------------------------- protocol
#
# Mutated-engine fixtures for the dataflow pass: a miniature step-stamped
# OCC engine under lax.scan (so facts must flow around the carry exactly
# like the real pipelines' cohort contexts) with one protocol edge
# deliberately severed per variant, and a mini explicit-release 2PL
# engine plus a mini replicated shard step for the other two invariants.


def _mini_occ_args():
    W, N = 8, 32
    return (S((N + 1,), U32), S((N + 1,), U32), S((N + 1,), U32), S((), U32),
            S((W,), I32), S((W,), U32), S((W,), jnp.bool_), S((3, W), I32))


def _mini_occ(variant: str):
    """Step-stamped OCC mini engine: acquire (scatter-max of step<<K),
    validate (meta re-read vs snapshot), install (mask descends from the
    surviving-txn chain alive & ~changed, like the real pipelines).
    `variant` severs one edge: "drop_lock" installs on validation alone,
    "drop_validate" installs on the grant alone."""
    W, N, KB = 8, 32, 8

    def fn(tab, meta, arb, step, c_rows, c_snap, c_alive, xs_rows):
        def body(carry, rows):
            tab, meta, arb, step, c_rows, c_snap, c_alive = carry
            # wave 3 of the in-flight cohort: validate then install
            cur = meta[c_rows]
            valid = cur == c_snap                      # VALIDATED seed
            changed = (~valid)[:, None].any(axis=1)    # ABORT_MASK seed
            if variant == "drop_lock":
                mask = ~changed
            elif variant == "drop_validate":
                mask = c_alive
            else:
                mask = c_alive & ~changed
            widx = jnp.where(mask, c_rows, N + 1)
            meta2 = meta.at[widx].set(cur + U32(1), mode="drop",
                                      unique_indices=True)
            tab2 = tab.at[widx].set(c_rows.astype(U32), mode="drop",
                                    unique_indices=True)
            # wave 1 of a new cohort: expiring-stamp lock arbitration
            lane = jnp.arange(W, dtype=U32)
            packed = (step << U32(KB)) | (U32(W) - lane)
            held = (arb[rows] >> U32(KB)) == step - U32(1)
            cand = ~held
            arb2 = arb.at[jnp.where(cand, rows, N + 1)].max(
                packed, mode="drop")
            grant = cand & (arb2[rows] == packed)      # LOCK_WIN seed
            rejected = (~grant)[:, None].any(axis=1)   # ABORT_MASK seed
            alive = grant & ~rejected
            snap = meta2[rows]
            carry = (tab2, meta2, arb2, step + U32(1), rows, snap, alive)
            return carry, (changed | rejected).sum(dtype=jnp.int32)

        carry = (tab, meta, arb, step, c_rows, c_snap, c_alive)
        return jax.lax.scan(body, carry, xs_rows)

    return fn


def _mini_2pl(release: bool):
    """Explicit-release mini 2PL engine: first-lane-wins arbitration over
    a bool lock array (no step stamp — locks are sticky), validation,
    install. ``release=True`` adds the release wave clearing EVERY
    granted lock (committed or aborted); False models "return early past
    the unlock wave": the only lock write left is the grant."""
    W, N = 8, 32
    BIG = jnp.int32(1 << 30)

    def fn(tab, lock, c_rows, c_snap, c_grant, xs_rows):
        def body(carry, rows):
            tab, lock, c_rows, c_snap, c_grant = carry
            cur = tab[c_rows]
            valid = cur == c_snap                      # VALIDATED seed
            changed = (~valid)[:, None].any(axis=1)    # ABORT_MASK seed
            commit = c_grant & ~changed
            widx = jnp.where(commit, c_rows, N + 1)
            tab2 = tab.at[widx].set(cur + U32(1), mode="drop",
                                    unique_indices=True)
            lock2 = lock
            if release:
                # the release mask is `granted` — commits AND aborts —
                # so it legitimately does NOT depend on the abort bit
                ridx = jnp.where(c_grant, c_rows, N + 1)
                lock2 = lock.at[ridx].set(False, mode="drop",
                                          unique_indices=True)
            # new cohort: first-lane-wins acquire on the lock array
            lane = jnp.arange(W, dtype=I32)
            first = jnp.full((N + 1,), BIG, I32).at[rows].min(
                lane, mode="drop")
            free = ~lock2[rows]
            grant = free & (first[rows] == lane)       # LOCK_WIN seed
            gidx = jnp.where(grant, rows, N + 1)
            lock3 = lock2.at[gidx].set(True, mode="drop",
                                       unique_indices=True)
            snap = tab2[rows]
            carry = (tab2, lock3, rows, snap, grant)
            return carry, changed.sum(dtype=jnp.int32)

        return jax.lax.scan(body, (tab, lock, c_rows, c_snap, c_grant),
                            xs_rows)

    return fn


def _mini_repl(variant: str):
    """Mini replicated shard step under shard_map: install locally, then
    ("ok") ppermute the record to the +1 neighbor and apply it to the
    backup slice; "no_push" installs without any collective; "drop_push"
    ppermutes but applies the LOCAL record to the backup instead."""
    mesh = _mesh4()
    perm = [(i, (i + 1) % 4) for i in range(4)]

    def body(bal, bck, rows, vals, mask):
        bal, bck, rows, vals, mask = (x[0] for x in
                                      (bal, bck, rows, vals, mask))
        N = bal.shape[0] - 1
        widx = jnp.where(mask, rows, N)
        bal2 = bal.at[widx].set(vals, mode="drop", unique_indices=True)
        if variant == "no_push":
            f_rows, f_vals, f_mask = rows, vals, mask
        else:
            pp = functools.partial(jax.lax.ppermute, axis_name="shard",
                                   perm=perm)
            f_rows, f_vals, f_mask = pp(rows), pp(vals), pp(mask)
            if variant == "drop_push":
                f_rows, f_vals, f_mask = rows, vals, mask
        bidx = jnp.where(f_mask, f_rows, N)
        bck2 = bck.at[bidx].set(f_vals, mode="drop", unique_indices=True)
        return bal2[None], bck2[None]

    def fn(bal, bck, rows, vals, mask):
        sm = jax.shard_map(body, mesh=mesh,
                           in_specs=(P("shard"),) * 5,
                           out_specs=(P("shard"),) * 2)
        return sm(bal, bck, rows, vals, mask)

    return fn


def _repl_args():
    return (S((4, 33), U32), S((4, 33), U32), S((4, 8), I32),
            S((4, 8), U32), S((4, 8), jnp.bool_))


@pytest.mark.parametrize("variant,code", [
    ("drop_lock", "unlocked-install"),
    ("drop_validate", "unvalidated-install"),
])
@pytest.mark.lint
def test_protocol_occ_fixtures_fire(variant, code):
    fs = run_pass("protocol", _mini_occ(variant), _mini_occ_args(),
                  protocol=("certified", "occ"))
    assert code in codes(fs, "error"), [str(f) for f in fs]
    # each severed edge trips exactly its own invariant, not its sibling
    other = ({"unlocked-install", "unvalidated-install"} - {code}).pop()
    assert other not in codes(fs, "error")


@pytest.mark.lint
def test_protocol_safe_occ_engine_clean():
    fs = run_pass("protocol", _mini_occ("safe"), _mini_occ_args(),
                  protocol=("certified", "occ"))
    assert not codes(fs, "error"), [str(f) for f in fs]


@pytest.mark.lint
def test_protocol_abort_unlock_fixture():
    args = (S((33,), U32), S((33,), jnp.bool_), S((8,), I32), S((8,), U32),
            S((8,), jnp.bool_), S((3, 8), I32))
    broken = run_pass("protocol", _mini_2pl(release=False), args)
    assert "abort-leaks-lock" in codes(broken, "error"), \
        [str(f) for f in broken]
    safe = run_pass("protocol", _mini_2pl(release=True), args)
    assert "abort-leaks-lock" not in codes(safe, "error"), \
        [str(f) for f in safe]


@pytest.mark.parametrize("variant,code", [
    ("no_push", "no-replication-push"),
    ("drop_push", "push-not-applied"),
])
@pytest.mark.lint
def test_protocol_replication_fixtures_fire(variant, code):
    fs = run_pass("protocol", _mini_repl(variant), _repl_args(),
                  protocol=("replicated",))
    assert code in codes(fs, "error"), [str(f) for f in fs]


@pytest.mark.lint
def test_protocol_safe_replication_clean():
    fs = run_pass("protocol", _mini_repl("ok"), _repl_args(),
                  protocol=("replicated",))
    assert not codes(fs, "error"), [str(f) for f in fs]


@pytest.mark.lint
@pytest.mark.parametrize("target", [
    "tatp_dense/block",            # dense OCC, XLA route
    "tatp_dense/block@pallas",     # grant comes from the fused kernel
    "tatp_pipeline/block",         # generic sort-based OCC
    "smallbank_dense/block",       # 2PL expiring stamps
    "dense_sharded/block",         # OCC + ICI replication
])
def test_protocol_clean_on_real_engines(target):
    """Safe-idiom controls: the dense, pipeline, and pallas variants of
    the real engines satisfy every protocol check through genuine
    dataflow (no allowlist involved)."""
    fs = analysis.run(targets=[target], passes=["protocol"])
    assert not [str(f) for f in fs if f.severity == "error"]


@pytest.mark.lint
def test_protocol_dense_installs_prove_lock_and_validate():
    """The interprocedural claim itself: the flagship engine's install
    scatters carry LOCK_WIN *and* VALIDATED — facts seeded at the grant
    compare / validate compare and flowed around two scan-carry hops —
    without leaning on the segment-sort evidence ladder."""
    from dint_tpu.analysis import dataflow as df
    trace = analysis.get_trace("tatp_dense/block")
    flow = df.analyze(trace)
    installs = [r for r in flow.scatters
                if r.prim == "scatter" and r.is_state and not r.in_pallas]
    assert installs
    for r in installs:
        assert df.LOCK_WIN in r.write_facts, r.site
        assert df.VALIDATED in r.write_facts, r.site
        assert df.SORTED not in r.write_facts, r.site


# --------------------------------------------------------------- allowlist


def _broken_scatter_findings():
    def bad(tab, idx, v):
        return tab.at[idx].set(v)

    return run_pass("scatter_race", bad,
                    (S((64,), U32), S((8,), I32), S((8,), U32)))


def test_allowlist_suppresses_matched_finding(tmp_path):
    path = tmp_path / "allow.json"
    path.write_text(json.dumps([
        {"pass": "scatter_race", "code": "nonunique-scatter",
         "target": "fixture/scatter_race",
         "reason": "fixture: uniqueness proven by the test harness"}]))
    fs = al.apply(_broken_scatter_findings(), al.load(str(path)))
    assert not analysis.has_errors(fs)
    assert any(f.suppressed for f in fs)     # visible, flagged, not hidden


def test_allowlist_requires_reason_and_reports_stale_entries(tmp_path):
    bad = tmp_path / "noreason.json"
    bad.write_text(json.dumps([{"pass": "x", "code": "y"}]))
    with pytest.raises(al.AllowlistError):
        al.load(str(bad))

    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps([
        {"pass": "scatter_race", "code": "no-such-code",
         "reason": "matches nothing"}]))
    fs = al.apply(_broken_scatter_findings(), al.load(str(stale)))
    assert "unused-entry" in codes(fs, "warning")
    assert analysis.has_errors(fs)           # the real finding stays fatal


def test_allowlist_mismatch_does_not_suppress(tmp_path):
    path = tmp_path / "other.json"
    path.write_text(json.dumps([
        {"pass": "scatter_race", "code": "nonunique-scatter",
         "target": "some/other-target", "reason": "scoped elsewhere"}]))
    fs = al.apply(_broken_scatter_findings(), al.load(str(path)),
                  check_unused=False)
    assert analysis.has_errors(fs)


def _broken_findings(pname):
    """Fresh findings from the canonical broken fixture of each pass."""
    if pname == "scatter_race":
        return _broken_scatter_findings()
    if pname == "aliasing":
        def bad(x):
            y = pl.pallas_call(_inc_kernel, out_shape=S(x.shape, x.dtype),
                               input_output_aliases={0: 0},
                               interpret=True)(x)
            return y + x
        return run_pass("aliasing", bad, (S((8,), U32),))
    if pname == "purity":
        def bad(x):
            return jax.pure_callback(lambda a: np.asarray(a),
                                     S((), jnp.float32), x.sum())
        return run_pass("purity", bad, (S((8,), jnp.float32),))
    if pname == "u64_overflow":
        def bad(step, lane):
            return ((step << U32(18)) | lane).astype(I32) < 0
        return run_pass("u64_overflow", bad, (S((8,), U32), S((8,), U32)))
    if pname == "shard_consistency":
        def body(x):
            return jax.lax.ppermute(x, "shard", [(0, 1), (2, 1)])
        sm = jax.shard_map(body, mesh=_mesh4(), in_specs=P("shard"),
                           out_specs=P("shard"))
        return run_pass("shard_consistency", sm, (S((8, 4), jnp.float32),))
    if pname == "protocol":
        return run_pass("protocol", _mini_occ("drop_lock"),
                        _mini_occ_args(), protocol=("certified", "occ"))
    if pname == "cost_budget":
        # a registered dispatch budget of 0 turns any memory op into a
        # regression; the full gate lives in tests/test_dintcost.py
        from dint_tpu.analysis import targets as T

        def bad(tab, idx, v):
            return tab.at[idx].set(v, mode="drop", unique_indices=True)
        T.TARGET_COST["fixture/cost_budget"] = {
            "steps": 1.0, "geom": {}, "wave_expect": {},
            "budget": {"dispatches": 0, "bytes": None, "footprint": None}}
        try:
            return run_pass("cost_budget", bad,
                            (S((64,), U32), S((8,), I32), S((8,), U32)))
        finally:
            T.TARGET_COST.pop("fixture/cost_budget", None)
    if pname == "durability":
        # the canonical broken durability fixture (an engine that
        # installs certified writes with no log append) lives with the
        # rest of the dintdur fixtures
        import test_dintdur
        return test_dintdur.broken_wal_order_findings()
    if pname == "plan_check":
        # the canonical broken plan fixture (swapped frontier ranks =>
        # flipped-ordering) lives with the rest of the dintplan fixtures
        import test_dintplan
        return test_dintplan.broken_plan_findings()
    if pname == "calib_check":
        # the canonical broken calibration fixture (hand-edited
        # coefficient => unfit-model + stale-provenance) lives with the
        # rest of the dintcal fixtures
        import test_dintcal
        return test_dintcal.broken_calib_findings()
    if pname == "mut_check":
        # the canonical broken mutation fixture (a killed cell flipped
        # to survived => stale-provenance + survivor) lives with the
        # rest of the dintmut fixtures
        import test_dintmut
        return test_dintmut.broken_mutcov_findings()
    raise AssertionError(pname)


@pytest.mark.parametrize("pname", sorted(analysis.PASSES))
def test_every_pass_fires_and_is_allowlist_silenceable(pname, tmp_path):
    """Acceptance contract: each registered pass is proven live by a
    deliberately-broken fixture that FAILS the lint, and a scoped
    allowlist entry silences exactly that failure."""
    findings = _broken_findings(pname)
    assert analysis.has_errors(findings), f"{pname} fixture did not fire"

    path = tmp_path / "allow.json"
    path.write_text(json.dumps([
        {"pass": pname, "code": "*", "target": f"fixture/{pname}",
         "reason": "test fixture: violation is constructed on purpose"}]))
    fs = al.apply(_broken_findings(pname), al.load(str(path)),
                  check_unused=False)
    assert not analysis.has_errors(fs)
    assert any(f.suppressed for f in fs)


# ------------------------------------------------------------ tier-1 gate


@pytest.mark.lint
def test_dintlint_gate_all_targets():
    """The standing CI gate: every registered engine/sharded target, every
    pass, repo allowlist applied — zero unsuppressed errors."""
    allow = os.path.join(REPO, "tools", "dintlint_allow.json")
    findings = analysis.run(
        allowlist_path=allow if os.path.exists(allow) else None)
    errors = [str(f) for f in findings
              if f.severity == "error" and not f.suppressed]
    assert not errors, "dintlint gate failed:\n" + "\n".join(errors)


@pytest.mark.lint
def test_cli_json_single_target():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintlint.py"),
         "--target", "tatp_dense/block", "--json", "--time"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "dintlint" and payload["ok"] is True
    # schema-stable keys downstream parsing (bench artifacts) relies on
    for k in ("schema", "targets", "passes", "n_findings", "n_errors",
              "n_suppressed", "findings"):
        assert k in payload
    assert isinstance(payload["schema"], int) and payload["schema"] >= 2
    # --time: per-target trace/pass wall time rides the payload
    t = payload["timing"]["targets"]["tatp_dense/block"]
    assert "trace_s" in t and "protocol" in t["passes"]


@pytest.mark.lint
def test_cli_unknown_names_exit_2_with_registry():
    """Typos exit 2 with the registered names, never a traceback."""
    for args in (["--target", "nope/bad"], ["--all", "--pass", "nope"]):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "dintlint.py"),
             *args],
            capture_output=True, text=True, cwd=REPO, timeout=300)
        assert out.returncode == 2, (out.returncode, out.stderr[-500:])
        assert "Traceback" not in out.stderr
        assert "unknown" in out.stderr and "registered" in out.stderr
        assert "tatp_dense/block" in out.stderr or "protocol" \
            in out.stderr


# ---------------------------------------------------------- prune helpers


def test_allowlist_prune_drops_only_stale_entries(tmp_path):
    """--prune-allowlist semantics at the library level: after apply()
    over findings, prune_entries splits used from stale and save()
    rewrites the file without private bookkeeping keys."""
    path = tmp_path / "allow.json"
    path.write_text(json.dumps([
        {"pass": "scatter_race", "code": "nonunique-scatter",
         "target": "fixture/scatter_race", "reason": "live entry"},
        {"pass": "scatter_race", "code": "no-such-code",
         "reason": "stale entry"}]))
    entries = al.load(str(path))
    al.apply(_broken_scatter_findings(), entries)
    kept, dropped = al.prune_entries(entries)
    assert [e["code"] for e in kept] == ["nonunique-scatter"]
    assert [e["code"] for e in dropped] == ["no-such-code"]
    al.save(str(path), kept)
    rewritten = json.loads(path.read_text())
    assert rewritten == [{"pass": "scatter_race",
                          "code": "nonunique-scatter",
                          "target": "fixture/scatter_race",
                          "reason": "live entry"}]   # `_used` stripped


def _dintlint_main():
    """Load tools/dintlint.py as a module so main() runs in-process and
    the full-matrix prune reuses this process's TraceCache instead of
    re-tracing 36 targets in a subprocess."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_dintlint_cli", os.path.join(REPO, "tools", "dintlint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


@pytest.mark.lint
def test_prune_check_is_a_dry_run_that_fails_on_stale(tmp_path, capsys):
    """--prune-allowlist --check: exit 1 on stale entries WITHOUT
    rewriting the file; without --check the same run prunes and passes.
    This is the CI form — allowlist rot fails the gate instead of
    waiting for someone to remember the manual prune."""
    main = _dintlint_main()
    repo_allow = os.path.join(REPO, "tools", "dintlint_allow.json")
    entries = json.loads(open(repo_allow).read())
    entries.append({"pass": "scatter_race", "code": "no-such-code",
                    "reason": "stale on purpose"})
    path = tmp_path / "allow.json"
    path.write_text(json.dumps(entries))
    before = path.read_text()

    assert main(["--prune-allowlist", "--check",
                 "--allowlist", str(path)]) == 1
    assert path.read_text() == before          # dry-run: NOT rewritten
    out = capsys.readouterr().out
    assert "NOT rewritten" in out and "no-such-code" in out

    assert main(["--prune-allowlist", "--allowlist", str(path)]) == 0
    pruned = json.loads(path.read_text())
    assert [e["code"] for e in entries
            if e["code"] != "no-such-code"] == [e["code"] for e in pruned]

    # and pruning to a clean file means a following --check passes
    assert main(["--prune-allowlist", "--check",
                 "--allowlist", str(path)]) == 0

    with pytest.raises(SystemExit) as exc:     # --check needs the prune
        main(["--check", "--all"])
    assert exc.value.code == 2
