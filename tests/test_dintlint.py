"""dintlint: each pass proven live on a deliberately-broken mini step,
silent on the matching safe idiom, suppressible by an allowlist entry —
plus the standing tier-1 gate: the full pass suite over every registered
engine/sharded target must report zero unsuppressed errors.

The broken fixtures are the bug classes the passes exist for:
  * a colliding scatter (no unique_indices, no segment mask),
  * an aliased Pallas kernel whose donated input is read afterwards (and a
    double-aliased one),
  * a jitted call whose donated operand stays live,
  * host callbacks / Python branching on traced data in a "step",
  * a packed stamp cast to int32 and compared signed,
  * ppermutes whose permutation disagrees with the mesh.
"""
import functools
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

import dint_tpu.parallel  # noqa: F401 — installs the jax.shard_map shim
from dint_tpu import analysis
from dint_tpu.analysis import allowlist as al
from dint_tpu.analysis import core
from dint_tpu.ops import segments

S = jax.ShapeDtypeStruct
U32 = jnp.uint32
I32 = jnp.int32

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_pass(name, fn, args, mesh_axes=()):
    tr = core.trace_target(f"fixture/{name}", fn, args, mesh_axes=mesh_axes)
    return analysis.PASSES[name](tr)


def codes(findings, severity=None):
    return {f.code for f in findings
            if severity is None or f.severity == severity}


# ------------------------------------------------------------ scatter_race


def test_scatter_race_fires_on_colliding_scatter():
    def bad(tab, idx, v):
        return tab.at[idx].set(v)       # arbitrary idx: duplicate = race

    fs = run_pass("scatter_race", bad,
                  (S((64,), U32), S((8,), I32), S((8,), U32)))
    assert "nonunique-scatter" in codes(fs, "error")


def test_scatter_race_accepts_declared_unique_and_segment_masked():
    def ok_unique(tab, idx, v):
        return tab.at[idx].set(v, mode="drop", unique_indices=True)

    def ok_segmented(tab, kh, kl, v):
        sb = segments.sort_batch(kh, kl)
        return segments.scatter_rows(tab, sb.key_lo.astype(I32), v[sb.perm],
                                     sb.last)   # one writer per key

    fs1 = run_pass("scatter_race", ok_unique,
                   (S((64,), U32), S((8,), I32), S((8,), U32)))
    fs2 = run_pass("scatter_race", ok_segmented,
                   (S((64,), U32), S((8,), U32), S((8,), U32), S((8,), U32)))
    assert not codes(fs1, "error") and not codes(fs2, "error")


# ---------------------------------------------------------------- aliasing


def _inc_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] + 1


def test_aliasing_pallas_use_after_donate():
    def bad(x):
        y = pl.pallas_call(_inc_kernel, out_shape=S(x.shape, x.dtype),
                           input_output_aliases={0: 0}, interpret=True)(x)
        return y + x        # x was updated in place: torn read

    def ok(x):
        y = pl.pallas_call(_inc_kernel, out_shape=S(x.shape, x.dtype),
                           input_output_aliases={0: 0}, interpret=True)(x)
        return y + 1        # only the kernel's output is used

    assert "use-after-donate" in codes(run_pass("aliasing", bad,
                                                (S((8,), U32),)), "error")
    assert not codes(run_pass("aliasing", ok, (S((8,), U32),)), "error")


def test_aliasing_double_aliased_kernel():
    def _add_kernel(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] + y_ref[...]

    def bad(x, y):
        return pl.pallas_call(_add_kernel, out_shape=S(x.shape, x.dtype),
                              input_output_aliases={0: 0, 1: 0},
                              interpret=True)(x, y)

    fs = run_pass("aliasing", bad, (S((8,), U32), S((8,), U32)))
    assert "double-alias-output" in codes(fs, "error")


def test_aliasing_pjit_donated_operand_still_live():
    @functools.partial(jax.jit, donate_argnums=0)
    def g(x):
        return x + 1

    def bad(x):
        y = g(x)
        return y + x

    fs = run_pass("aliasing", bad, (S((8,), jnp.float32),))
    assert "use-after-donate" in codes(fs, "error")


# ------------------------------------------------------------------ purity


def test_purity_flags_callbacks_and_debug_print():
    def bad_cb(x):
        return jax.pure_callback(lambda a: np.asarray(a),
                                 S((), jnp.float32), x.sum())

    def warn_dbg(x):
        jax.debug.print("x={x}", x=x.sum())
        return x * 2

    assert "pure_callback" in codes(run_pass("purity", bad_cb,
                                             (S((8,), jnp.float32),)),
                                    "error")
    fs = run_pass("purity", warn_dbg, (S((8,), jnp.float32),))
    assert "debug_callback" in codes(fs, "warning") and not codes(fs, "error")


def test_purity_flags_python_branch_on_traced_data():
    def bad(x):
        if x.sum() > 0:     # concretizes a tracer: host sync + retrace
            return x
        return -x

    fs = run_pass("purity", bad, (S((8,), jnp.float32),))
    assert "untraceable" in codes(fs, "error")


# ------------------------------------------------------------ u64_overflow


def test_u64_flags_stamp_sign_drift_and_signed_compare():
    def bad(step, lane):
        packed = ((step << U32(18)) | lane).astype(I32)
        return packed < 0

    fs = run_pass("u64_overflow", bad, (S((8,), U32), S((8,), U32)))
    assert {"stamp-sign-drift", "signed-stamp-compare"} <= codes(fs, "error")


def test_u64_accepts_masked_convert():
    def ok(step, lane):
        # masked below 2^31 before the convert: the repo's bucket-index idiom
        packed = (((step << U32(18)) | lane) & U32(0x3FFFF)).astype(I32)
        return packed < 0

    assert not run_pass("u64_overflow", ok, (S((8,), U32), S((8,), U32)))


# ------------------------------------------------------ shard_consistency


def _mesh4():
    from dint_tpu.parallel.sharded import make_mesh
    assert len(jax.devices()) >= 4
    return make_mesh(4)


def test_shard_consistency_flags_bad_perms():
    mesh = _mesh4()

    def dup_dest(x):
        return jax.lax.ppermute(x, "shard", [(0, 1), (2, 1)])

    def out_of_range(x):
        return jax.lax.ppermute(x, "shard", [(0, 7)])

    def ok(x):
        return jax.lax.ppermute(x, "shard",
                                [(i, (i + 1) % 4) for i in range(4)])

    def sm(body):
        return jax.shard_map(body, mesh=mesh, in_specs=P("shard"),
                             out_specs=P("shard"))

    arg = (S((8, 4), jnp.float32),)
    assert "perm-duplicate-dest" in codes(
        run_pass("shard_consistency", sm(dup_dest), arg), "error")
    assert "perm-out-of-range" in codes(
        run_pass("shard_consistency", sm(out_of_range), arg), "error")
    assert not codes(run_pass("shard_consistency", sm(ok), arg), "error")


# --------------------------------------------------------------- allowlist


def _broken_scatter_findings():
    def bad(tab, idx, v):
        return tab.at[idx].set(v)

    return run_pass("scatter_race", bad,
                    (S((64,), U32), S((8,), I32), S((8,), U32)))


def test_allowlist_suppresses_matched_finding(tmp_path):
    path = tmp_path / "allow.json"
    path.write_text(json.dumps([
        {"pass": "scatter_race", "code": "nonunique-scatter",
         "target": "fixture/scatter_race",
         "reason": "fixture: uniqueness proven by the test harness"}]))
    fs = al.apply(_broken_scatter_findings(), al.load(str(path)))
    assert not analysis.has_errors(fs)
    assert any(f.suppressed for f in fs)     # visible, flagged, not hidden


def test_allowlist_requires_reason_and_reports_stale_entries(tmp_path):
    bad = tmp_path / "noreason.json"
    bad.write_text(json.dumps([{"pass": "x", "code": "y"}]))
    with pytest.raises(al.AllowlistError):
        al.load(str(bad))

    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps([
        {"pass": "scatter_race", "code": "no-such-code",
         "reason": "matches nothing"}]))
    fs = al.apply(_broken_scatter_findings(), al.load(str(stale)))
    assert "unused-entry" in codes(fs, "warning")
    assert analysis.has_errors(fs)           # the real finding stays fatal


def test_allowlist_mismatch_does_not_suppress(tmp_path):
    path = tmp_path / "other.json"
    path.write_text(json.dumps([
        {"pass": "scatter_race", "code": "nonunique-scatter",
         "target": "some/other-target", "reason": "scoped elsewhere"}]))
    fs = al.apply(_broken_scatter_findings(), al.load(str(path)),
                  check_unused=False)
    assert analysis.has_errors(fs)


def _broken_findings(pname):
    """Fresh findings from the canonical broken fixture of each pass."""
    if pname == "scatter_race":
        return _broken_scatter_findings()
    if pname == "aliasing":
        def bad(x):
            y = pl.pallas_call(_inc_kernel, out_shape=S(x.shape, x.dtype),
                               input_output_aliases={0: 0},
                               interpret=True)(x)
            return y + x
        return run_pass("aliasing", bad, (S((8,), U32),))
    if pname == "purity":
        def bad(x):
            return jax.pure_callback(lambda a: np.asarray(a),
                                     S((), jnp.float32), x.sum())
        return run_pass("purity", bad, (S((8,), jnp.float32),))
    if pname == "u64_overflow":
        def bad(step, lane):
            return ((step << U32(18)) | lane).astype(I32) < 0
        return run_pass("u64_overflow", bad, (S((8,), U32), S((8,), U32)))
    if pname == "shard_consistency":
        def body(x):
            return jax.lax.ppermute(x, "shard", [(0, 1), (2, 1)])
        sm = jax.shard_map(body, mesh=_mesh4(), in_specs=P("shard"),
                           out_specs=P("shard"))
        return run_pass("shard_consistency", sm, (S((8, 4), jnp.float32),))
    raise AssertionError(pname)


@pytest.mark.parametrize("pname", sorted(analysis.PASSES))
def test_every_pass_fires_and_is_allowlist_silenceable(pname, tmp_path):
    """Acceptance contract: each registered pass is proven live by a
    deliberately-broken fixture that FAILS the lint, and a scoped
    allowlist entry silences exactly that failure."""
    findings = _broken_findings(pname)
    assert analysis.has_errors(findings), f"{pname} fixture did not fire"

    path = tmp_path / "allow.json"
    path.write_text(json.dumps([
        {"pass": pname, "code": "*", "target": f"fixture/{pname}",
         "reason": "test fixture: violation is constructed on purpose"}]))
    fs = al.apply(_broken_findings(pname), al.load(str(path)),
                  check_unused=False)
    assert not analysis.has_errors(fs)
    assert any(f.suppressed for f in fs)


# ------------------------------------------------------------ tier-1 gate


@pytest.mark.lint
def test_dintlint_gate_all_targets():
    """The standing CI gate: every registered engine/sharded target, every
    pass, repo allowlist applied — zero unsuppressed errors."""
    allow = os.path.join(REPO, "tools", "dintlint_allow.json")
    findings = analysis.run(
        allowlist_path=allow if os.path.exists(allow) else None)
    errors = [str(f) for f in findings
              if f.severity == "error" and not f.suppressed]
    assert not errors, "dintlint gate failed:\n" + "\n".join(errors)


@pytest.mark.lint
def test_cli_json_single_target():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintlint.py"),
         "--target", "tatp_dense/block", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "dintlint" and payload["ok"] is True
    # schema-stable keys downstream parsing relies on
    for k in ("targets", "passes", "n_findings", "n_errors",
              "n_suppressed", "findings"):
        assert k in payload
