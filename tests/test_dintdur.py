"""dintdur: every durability check proven live on a deliberately-broken
mini engine, silent on the matching safe idiom AND on every real target,
suppressible by a scoped allowlist entry — plus the standing tier-1 gate
(`dintdur check --all` semantics in-process) and the replay-twin
equivalence proofs against the numpy recovery paths.

The broken fixtures are the durability bug classes the pass exists for:
  * an engine that installs certified writes without any log append
    (wal-order),
  * a replication fan-out collapsed to one destination, and a 2-D-mesh
    replication hop riding the ICI axis (quorum-fanout),
  * a ring whose static appends/trace exceed its slot count
    (unbounded-ring), and appends with no watermark advance
    (no-ring-truncation),
  * a replay that skips a header column or reads past the populated
    entry prefix (replay-coverage),
  * a coordinator whose TIMEOUT handling is surgically removed
    (in-doubt-totality, source-mutation fixtures over the real client).
"""
import functools
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import dint_tpu.parallel  # noqa: F401 — installs the jax.shard_map shim
from dint_tpu import analysis, recovery
from dint_tpu.analysis import allowlist as al
from dint_tpu.analysis import core
from dint_tpu.analysis import targets as T
from dint_tpu.analysis.passes import durability as dur
from dint_tpu.engines import smallbank_dense as sd
from dint_tpu.engines import tatp_dense as td
from dint_tpu.tables import log as tlog

S = jax.ShapeDtypeStruct
U32 = jnp.uint32
I32 = jnp.int32

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

W, N = 4, 32            # mini-engine geometry: 4 lanes, 32 rows


def run_pass(fn, args, mesh_axes=(), protocol=("certified", "durable")):
    tr = core.trace_target("fixture/durability", fn, args,
                           mesh_axes=mesh_axes, protocol=protocol)
    return analysis.PASSES["durability"](tr)


def codes(findings, severity=None):
    return {f.code for f in findings
            if severity is None or f.severity == severity}


# ------------------------------------------------- mini durable engine
#
# A miniature validate-then-install engine under lax.scan whose appends
# go through the REAL tables/log.py (the LOG_SLOT/LOGGED facts seed at
# its slot math, exactly like the production engines). Variants sever
# one durability edge each.


def _mini_durable(variant, lanes=2, capacity=8):
    vw = 1

    def fn(tab, meta, entries, head, rows, snap, vals, xs):
        def body(carry, _):
            tab, meta, ring, rows, snap, vals = carry
            cur = meta[rows]
            valid = cur == snap                        # VALIDATED seed
            mask = valid
            if variant != "nolog":
                ring = tlog.append_rep(
                    ring, mask, jnp.zeros((W,), U32), jnp.zeros((W,), U32),
                    jnp.zeros((W,), U32), rows.astype(U32), cur, vals)
            widx = jnp.where(mask, rows, N)
            tab2 = tab.at[widx].set(vals[:, 0], mode="drop",
                                    unique_indices=True)
            meta2 = meta.at[widx].set(cur + U32(1), mode="drop",
                                      unique_indices=True)
            carry = (tab2, meta2, ring, rows, meta2[rows], vals)
            return carry, mask.sum(dtype=U32)

        ring = tlog.RepLog(entries=entries, head=head,
                           lanes=lanes, replicas=3)
        carry, counted = jax.lax.scan(
            body, (tab, meta, ring, rows, snap, vals), xs)
        tab2, meta2, ring2 = carry[0], carry[1], carry[2]
        out = (tab2, meta2, ring2.entries, ring2.head, counted)
        if variant == "ok":
            # the checkpoint wave the real engines still lack (the
            # allowlisted ROADMAP gap): advancing a watermark is what
            # the no-ring-truncation check wants to see reachable
            consumed = jnp.broadcast_to(counted.sum(), (lanes,))
            out += (tlog.advance_watermark(ring2, jnp.zeros((lanes,), U32),
                                           consumed),)
        return out

    args = (S((N + 1,), U32), S((N + 1,), U32),
            S((lanes * capacity, 3 * (tlog.HDR_WORDS + vw)), U32),
            S((lanes,), U32), S((W,), I32), S((W,), U32), S((W, vw), U32),
            S((2 if capacity >= 8 else 4, 1), I32))
    return fn, args


def broken_wal_order_findings():
    """Certified installs, zero log appends — the canonical broken
    durability fixture (also imported by test_dintlint's every-pass
    liveness parametrization)."""
    return run_pass(*_mini_durable("nolog"))


@pytest.mark.lint
def test_wal_order_fires_on_dropped_append():
    fs = broken_wal_order_findings()
    assert "wal-order" in codes(fs, "error"), [str(f) for f in fs]
    # no appends at all: the ring checks have nothing to bound
    assert "no-ring-truncation" not in codes(fs)
    assert "unbounded-ring" not in codes(fs)


@pytest.mark.lint
def test_ring_truncation_fires_without_watermark():
    fs = run_pass(*_mini_durable("notrunc"))
    assert "no-ring-truncation" in codes(fs, "error"), [str(f) for f in fs]
    # the append rides the same certified mask: wal-order is satisfied
    assert "wal-order" not in codes(fs)


@pytest.mark.lint
def test_unbounded_ring_fires_on_tiny_capacity():
    # 2 lanes x 2 slots = 4, appends = W(4) x 4 scan trips = 16 > 4
    fs = run_pass(*_mini_durable("notrunc", capacity=2))
    assert "unbounded-ring" in codes(fs, "error"), [str(f) for f in fs]


@pytest.mark.lint
def test_safe_durable_engine_clean():
    """Append under the certified mask + watermark advance: every
    durability check passes through genuine dataflow."""
    fs = run_pass(*_mini_durable("ok"))
    assert not codes(fs, "error"), [str(f) for f in fs]


# --------------------------------------------------------- quorum-fanout


def _mesh(shape, axes):
    assert len(jax.devices()) >= int(np.prod(shape))
    devs = np.array(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return jax.sharding.Mesh(devs, axes)


def _mini_quorum(offsets, shape=(4,), axes=("shard",), perm_axis="shard"):
    """Install locally, then push the record over ppermute hop(s) with
    the given offsets and apply to the backup slice."""
    mesh = _mesh(shape, axes)
    n = shape[axes.index(perm_axis)]
    spec = P(axes if len(axes) > 1 else axes[0])

    def body(bal, bck, rows, vals, mask):
        bal, bck, rows, vals, mask = (x.reshape(x.shape[-2:])[0]
                                      for x in (bal, bck, rows, vals, mask))
        m = bal.shape[0] - 1
        bal2 = bal.at[jnp.where(mask, rows, m)].set(
            vals, mode="drop", unique_indices=True)
        bck2 = bck
        for off in offsets:
            pp = functools.partial(
                jax.lax.ppermute, axis_name=perm_axis,
                perm=[(i, (i + off) % n) for i in range(n)])
            f_rows, f_vals, f_mask = pp(rows), pp(vals), pp(mask)
            bck2 = bck2.at[jnp.where(f_mask, f_rows, m)].set(
                f_vals, mode="drop", unique_indices=True)
        return bal2[None, None], bck2[None, None]

    def fn(bal, bck, rows, vals, mask):
        sm = jax.shard_map(body, mesh=mesh, in_specs=(spec,) * 5,
                           out_specs=(spec,) * 2)
        return sm(bal, bck, rows, vals, mask)

    d = int(np.prod(shape))
    args = (S((d, 33), U32), S((d, 33), U32), S((d, 8), I32),
            S((d, 8), U32), S((d, 8), jnp.bool_))
    return fn, args


@pytest.mark.lint
def test_quorum_fires_on_collapsed_fanout():
    # both hops +1: every source reaches ONE distinct destination
    fs = run_pass(*_mini_quorum((1, 1)),
                  protocol=("durable", "replicated"), mesh_axes=("shard",))
    assert "quorum-fanout" in codes(fs, "error"), [str(f) for f in fs]


@pytest.mark.lint
def test_quorum_accepts_two_distinct_hops():
    fs = run_pass(*_mini_quorum((1, 2)),
                  protocol=("durable", "replicated"), mesh_axes=("shard",))
    assert not codes(fs, "error"), [str(f) for f in fs]


@pytest.mark.lint
def test_quorum_2d_mesh_rejects_ici_replication():
    """On a (dcn, ici) mesh the replication hops must ride dcn: replicas
    one ICI hop apart share the host fault domain."""
    fs = run_pass(*_mini_quorum((1, 2), shape=(2, 4), axes=("dcn", "ici"),
                                perm_axis="ici"),
                  protocol=("durable", "replicated"),
                  mesh_axes=("dcn", "ici"))
    assert "quorum-fanout" in codes(fs, "error"), [str(f) for f in fs]
    fs = run_pass(*_mini_quorum((1, 2), shape=(4, 2), axes=("dcn", "ici"),
                                perm_axis="dcn"),
                  protocol=("durable", "replicated"),
                  mesh_axes=("dcn", "ici"))
    assert not codes(fs, "error"), [str(f) for f in fs]


# ------------------------------------------------------- replay-coverage


def _mini_replay(variant):
    """A replay-shaped function over a [L, CAP, words] ring; variants
    drop a required header read or read past the populated prefix."""
    L, CAP, WORDS = 2, 4, 8

    def fn(db, entries, heads):
        key_lo = entries[:, :, 2].reshape(-1)
        ver = entries[:, :, 3].reshape(-1)
        acc = key_lo + ver
        if variant != "nohdr":
            acc = acc + entries[:, :, 0].reshape(-1)     # flags
        vcol = 7 if variant == "overread" else 4
        acc = acc + entries[:, :, vcol].reshape(-1)
        rows = jnp.minimum(key_lo.astype(I32), db.shape[0] - 1)
        return db.at[rows].max(acc, mode="drop")

    return fn, (S((16,), U32), S((L, CAP, WORDS), U32), S((L,), U32))


@pytest.mark.lint
def test_replay_missing_header_read_fires():
    fs = run_pass(*_mini_replay("nohdr"), protocol=("replay",))
    assert "replay-coverage" in codes(fs, "error"), [str(f) for f in fs]
    assert any("flags" in f.message for f in fs)


@pytest.mark.lint
def test_replay_overread_fires_with_spec(monkeypatch):
    monkeypatch.setitem(T.REPLAY_SPECS, "fixture/durability",
                        dict(val_words=2))
    fs = run_pass(*_mini_replay("overread"), protocol=("replay",))
    msgs = [f.message for f in fs if f.code == "replay-coverage"]
    assert any("past the populated prefix" in m for m in msgs), msgs


@pytest.mark.lint
def test_replay_in_prefix_reads_clean(monkeypatch):
    monkeypatch.setitem(T.REPLAY_SPECS, "fixture/durability",
                        dict(val_words=2))
    fs = run_pass(*_mini_replay("ok"), protocol=("replay",))
    assert not codes(fs, "error"), [str(f) for f in fs]


@pytest.mark.lint
def test_replay_twin_arm_fires_on_uncovered_table(monkeypatch):
    """Engine side: point the mini durable engine at a twin that does
    NOT rebuild its (33,) tables — the coverage diff must name them."""
    monkeypatch.setitem(T.REPLAY_TWINS, "fixture/durability",
                        "recovery/smallbank_dense")
    fs = run_pass(*_mini_durable("ok"))
    msgs = [f.message for f in fs if f.code == "replay-coverage"]
    assert any("(33,)" in m and "never reconstructs" in m for m in msgs), \
        [str(f) for f in fs]


# ------------------------------------------------- replay-twin equality
#
# The traceable replay_* twins must compute EXACTLY what the numpy
# recovery paths compute — including the version tie-break (latest flat
# slot wins) — otherwise the coverage proof is about the wrong function.


def _hand_ring(lanes, cap, words, recs):
    """Handcrafted ring: recs[lane] = [(flags, kh, kl, ver, val...), ...]"""
    entries = np.zeros((lanes, cap, words), np.uint32)
    heads = np.zeros((lanes,), np.uint32)
    for lane, rows in enumerate(recs):
        for slot, rec in enumerate(rows):
            entries[lane, slot, :len(rec)] = rec
        heads[lane] = len(rows)
    return entries, heads


def test_replay_tatp_twin_matches_numpy():
    rng = np.random.default_rng(0)
    db0 = td.populate(rng, 4, val_words=4)
    # rows across tables, duplicate rows with rising vers, and an exact
    # (row, ver) tie — the lexsort-last rule must pick the later slot
    entries, heads = _hand_ring(2, 8, 8, [
        [(0 | (0 << 8), 0, 1, 3, 11, 12, 13, 14),
         (0 | (2 << 8), 0, 7, 5, 21, 22, 23, 24),
         (0 | (0 << 8), 0, 1, 4, 31, 32, 33, 34)],     # same row, ver 4 > 3
        [(1 | (1 << 8), 0, 2, 2, 41, 42, 43, 44),      # a delete
         (0 | (2 << 8), 0, 7, 5, 51, 52, 53, 54)],     # ver TIE with lane 0
    ])
    want = recovery.recover_tatp_dense(db0, entries, heads)
    got = recovery.replay_tatp_dense(db0, jnp.asarray(entries),
                                     jnp.asarray(heads))
    assert np.array_equal(np.asarray(got.val), np.asarray(want.val))
    assert np.array_equal(np.asarray(got.meta), np.asarray(want.meta))
    # the tie really exercised the rule: lane 1's entry is the winner
    row = int(np.asarray(td._bases(5))[2]) + 7
    assert int(np.asarray(got.val).reshape(-1, 4)[row, 0]) == 51


def test_replay_smallbank_twin_matches_numpy():
    db0 = sd.create(16)
    entries, heads = _hand_ring(2, 8, 6, [
        [(0 | (0 << 8), 0, 3, 1, 500, 0),
         (0 | (1 << 8), 0, 3, 2, 600, 0),
         (0 | (0 << 8), 0, 3, 4, 700, 0)],
        [(0 | (0 << 8), 0, 9, 4, 800, 0)],
    ])
    want = recovery.recover_smallbank_dense(db0, entries, heads)
    got = recovery.replay_smallbank_dense(db0, jnp.asarray(entries),
                                          jnp.asarray(heads))
    assert np.array_equal(np.asarray(got.bal), np.asarray(want.bal))
    assert int(np.asarray(got.step)) == int(np.asarray(want.step))
    assert not np.asarray(got.x_step).any()


def test_replay_sb_shard_twin_matches_numpy():
    n_acc, n_shards, dead = 32, 4, 1
    from dint_tpu.parallel.dense_sharded_sb import m1_local
    # global account ids; only acct % 4 == 1 belongs to the dead device
    entries, heads = _hand_ring(2, 8, 6, [
        [(0 | (0 << 8), 0, 5, 1, 111, 0),     # 5 % 4 == 1: dead's stream
         (0 | (0 << 8), 0, 6, 1, 222, 0),     # 6 % 4 == 2: not ours
         (0 | (1 << 8), 0, 9, 3, 333, 0)],    # savings row
        [(0 | (0 << 8), 0, 5, 2, 444, 0)],    # newer version of acct 5
    ])
    want = recovery.recover_sb_shard(n_acc, dead, n_shards, entries, heads)
    bal0 = np.full((m1_local(n_acc, n_shards),), 1000, np.uint32)
    bal0[-1] = 0
    got = recovery.replay_sb_shard(jnp.asarray(bal0), jnp.asarray(entries),
                                   jnp.asarray(heads),
                                   dead=dead, n_shards=n_shards)
    assert np.array_equal(np.asarray(got), want)


def test_replay_smallbank_twin_matches_numpy_after_real_run():
    """End-to-end: run the real engine, then replay its actual ring with
    both paths — bit-identical balances and step."""
    n_acc = 64
    db0 = sd.create(n_acc)
    run, init, drain = sd.build_pipelined_runner(n_acc, w=32,
                                                 cohorts_per_block=2)
    carry = init(db0)
    key = jax.random.PRNGKey(3)
    for i in range(2):
        carry, _ = run(carry, jax.random.fold_in(key, i))
    db, _ = drain(carry)
    entries = np.asarray(tlog.replica_entries(db.log, 0))
    heads = np.asarray(db.log.head)
    want = recovery.recover_smallbank_dense(sd.create(n_acc), entries, heads)
    got = recovery.replay_smallbank_dense(
        sd.create(n_acc), jnp.asarray(entries), jnp.asarray(heads))
    assert np.array_equal(np.asarray(got.bal), np.asarray(want.bal))
    assert int(np.asarray(got.step)) == int(np.asarray(want.step))
    assert np.array_equal(np.asarray(got.bal), np.asarray(db.bal))


# ---------------------------------------------------- in-doubt totality


def _client_src():
    with open(os.path.join(REPO, "dint_tpu", "clients",
                           "tatp_client.py")) as f:
        return f.read()


@pytest.mark.lint
def test_in_doubt_real_client_satisfies_all_obligations():
    assert dur.in_doubt_violations(_client_src()) == []


@pytest.mark.lint
@pytest.mark.parametrize("mutate,frag", [
    # never compares against Reply.TIMEOUT at all
    (lambda s: s.replace("Reply.TIMEOUT", "Reply.VAL"), "never tested"),
    # detects timeouts but never folds them out of the survivor mask
    (lambda s: s.replace(" & ~timed", "").replace(" & ~tmo2", "")
               .replace(" & ~in_doubt", ""), "alive"),
    # no lock-release wave for dead/doubted txns
    (lambda s: s.replace("Op.ABORT", "Op.OCC_READ"), "ABORT"),
])
def test_in_doubt_mutations_fire(mutate, frag):
    vs = dur.in_doubt_violations(mutate(_client_src()))
    assert vs and any(frag in m for m, _ in vs), vs


@pytest.mark.lint
def test_in_doubt_runs_through_the_pass(tmp_path, monkeypatch):
    """Pass-level wiring: a registered client source with a severed
    TIMEOUT path produces an in-doubt-totality ERROR on its target."""
    bad = tmp_path / "client.py"
    bad.write_text(_client_src().replace("Op.ABORT", "Op.OCC_READ"))
    monkeypatch.setitem(dur._CLIENT_SOURCES, "fixture/durability",
                        str(bad))

    def fn(x):
        return x + 1

    fs = run_pass(fn, (S((8,), U32),), protocol=())
    assert "in-doubt-totality" in codes(fs, "error"), [str(f) for f in fs]


# --------------------------------------------------- allowlist coverage


def _findings_for(code, tmp_path, monkeypatch):
    if code == "wal-order":
        return broken_wal_order_findings()
    if code == "no-ring-truncation":
        return run_pass(*_mini_durable("notrunc"))
    if code == "unbounded-ring":
        return run_pass(*_mini_durable("notrunc", capacity=2))
    if code == "quorum-fanout":
        return run_pass(*_mini_quorum((1, 1)),
                        protocol=("durable", "replicated"),
                        mesh_axes=("shard",))
    if code == "replay-coverage":
        return run_pass(*_mini_replay("nohdr"), protocol=("replay",))
    if code == "in-doubt-totality":
        bad = tmp_path / "client.py"
        bad.write_text(_client_src().replace("Op.ABORT", "Op.OCC_READ"))
        monkeypatch.setitem(dur._CLIENT_SOURCES, "fixture/durability",
                            str(bad))

        def fn(x):
            return x + 1

        return run_pass(fn, (S((8,), U32),), protocol=())
    raise AssertionError(code)


@pytest.mark.lint
@pytest.mark.parametrize("code", ["wal-order", "quorum-fanout",
                                  "unbounded-ring", "no-ring-truncation",
                                  "replay-coverage", "in-doubt-totality"])
def test_each_check_fires_and_is_allowlist_silenceable(code, tmp_path,
                                                       monkeypatch):
    """Acceptance contract: each of the durability checks is proven live
    by a broken fixture AND silenceable by a scoped entry with a written
    reason — never by anything broader."""
    findings = _findings_for(code, tmp_path, monkeypatch)
    assert code in codes(findings, "error"), \
        f"{code} fixture did not fire: " + str([str(f) for f in findings])

    path = tmp_path / "allow.json"
    path.write_text(json.dumps([
        {"pass": "durability", "code": code,
         "target": "fixture/durability",
         "reason": "test fixture: violation is constructed on purpose"}]))
    fs = al.apply(_findings_for(code, tmp_path, monkeypatch),
                  al.load(str(path)), check_unused=False)
    assert not any(f.severity == "error" and not f.suppressed
                   and f.code == code for f in fs)
    assert any(f.suppressed for f in fs)


# ------------------------------------------------------------ tier-1 gate


@pytest.mark.lint
def test_dintdur_gate_all_targets():
    """The standing CI gate (`python tools/dintdur.py check --all`
    in-process): every registered target, the shared repo allowlist —
    zero unsuppressed errors, and the ONLY suppressed class is the
    documented no-ring-truncation one (the ROADMAP log-truncation gap).
    Everything else — wal-order, quorum-fanout, unbounded-ring,
    replay-coverage, in-doubt-totality — holds with no allowlist help."""
    allow = os.path.join(REPO, "tools", "dintlint_allow.json")
    findings = analysis.run(
        passes=["durability"],
        allowlist_path=allow if os.path.exists(allow) else None)
    errors = [str(f) for f in findings
              if f.severity == "error" and not f.suppressed]
    assert not errors, "dintdur gate failed:\n" + "\n".join(errors)
    assert codes([f for f in findings if f.suppressed]) \
        <= {"no-ring-truncation"}
    # the gate is not vacuous: the documented finding class IS present
    assert any(f.code == "no-ring-truncation" for f in findings)


@pytest.mark.lint
def test_recovery_targets_are_registered_and_traced():
    """The replay twins are first-class analysis targets with cost rows:
    dintcost and dintdur both see them."""
    for name in ("recovery/tatp_dense", "recovery/smallbank_dense",
                 "recovery/sb_shard"):
        assert name in analysis.TARGETS
        assert "replay" in analysis.TARGET_PROTOCOL[name]
        assert name in T.TARGET_COST
        assert analysis.get_trace(name).jaxpr is not None
    for eng, twin in T.REPLAY_TWINS.items():
        assert eng in analysis.TARGETS and twin in analysis.TARGETS


@pytest.mark.lint
def test_dintdur_cli_json_and_sarif(tmp_path):
    sarif_path = tmp_path / "out.sarif"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintdur.py"),
         "check", "--target", "tatp_dense/block",
         "--target", "recovery/tatp_dense",
         "--json", "--sarif", str(sarif_path)],
        capture_output=True, text=True, cwd=REPO, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "dintdur" and payload["ok"] is True
    for k in ("schema", "mode", "targets", "n_findings", "n_errors",
              "n_suppressed", "findings"):
        assert k in payload
    assert payload["n_errors"] == 0 and payload["n_suppressed"] >= 1

    sarif = json.loads(sarif_path.read_text())
    assert sarif["version"] == "2.1.0"
    run0 = sarif["runs"][0]
    assert run0["tool"]["driver"]["name"] == "dintdur"
    assert any(r["ruleId"] == "durability/no-ring-truncation"
               and r.get("suppressions") for r in run0["results"])
    loc = run0["results"][0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith(".py")
    assert loc["region"]["startLine"] > 0


@pytest.mark.lint
def test_dintdur_cli_unknown_target_exits_2():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintdur.py"),
         "check", "--target", "nope/bad"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert out.returncode == 2, (out.returncode, out.stderr[-500:])
    assert "Traceback" not in out.stderr
    assert "unknown target" in out.stderr and "tatp_dense/block" \
        in out.stderr


@pytest.mark.lint
def test_dintlint_sarif_export(tmp_path):
    """--sarif on dintlint shares the same serializer (analysis.core)."""
    sarif_path = tmp_path / "lint.sarif"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintlint.py"),
         "--target", "tatp_dense/block", "--sarif", str(sarif_path)],
        capture_output=True, text=True, cwd=REPO, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    sarif = json.loads(sarif_path.read_text())
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["tool"]["driver"]["name"] == "dintlint"


def _dintdur_main():
    # main() runs in-process (same importlib pattern as the dintcost
    # prune test) so the CLI reuses this process's TraceCache
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_dintdur_cli", os.path.join(REPO, "tools", "dintdur.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def test_prune_check_is_a_gate_scoped_dry_run(tmp_path, capsys):
    """Same stale-entry contract as dintlint/dintcost, scoped to the
    durability pass: dry run fails without rewriting; the real prune
    drops ONLY the stale durability entry — the repo's still-matching
    durability suppression, wildcard-pass entries and other passes'
    entries all survive."""
    main = _dintdur_main()
    entries = json.loads(
        open(os.path.join(REPO, "tools", "dintlint_allow.json")).read())
    n_repo = len(entries)
    # the repo allowlist carries a REAL durability suppression — the
    # prune must keep it (its finding still fires)
    assert any(e["pass"] == "durability" for e in entries)
    entries.append({"pass": "durability", "code": "no-such-code",
                    "reason": "stale on purpose"})
    path = tmp_path / "allow.json"
    path.write_text(json.dumps(entries))
    before = path.read_text()

    assert main(["check", "--prune-allowlist", "--check",
                 "--allowlist", str(path)]) == 1
    assert path.read_text() == before
    out = capsys.readouterr().out
    assert "NOT rewritten" in out
    assert "durability/no-such-code" in out

    assert main(["check", "--prune-allowlist",
                 "--allowlist", str(path)]) == 0
    capsys.readouterr()
    pruned = json.loads(path.read_text())
    assert len(pruned) == n_repo
    assert not any(e["code"] == "no-such-code" for e in pruned)
    assert any(e["pass"] == "durability"
               and e["code"] == "no-ring-truncation" for e in pruned)
    assert any(e["pass"] == "scatter_race" for e in pruned)

    with pytest.raises(SystemExit):      # --check without the prune
        main(["check", "--all", "--check"])
    with pytest.raises(SystemExit):      # prune is check-mode only
        main(["report", "--all", "--prune-allowlist"])
