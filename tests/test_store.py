import jax
import numpy as np
import pytest

from dint_tpu.engines import store
from dint_tpu.engines.types import Op, Reply, make_batch
from dint_tpu.tables import kv, run as run_mod
from dint_tpu.testing.oracle import StoreOracle

VW = 4


def run_step(table, ops, keys, vals, width=None, bloom=False):
    batch = make_batch(ops, keys, vals, width=width or len(ops), val_words=VW)
    step = jax.jit(store.step, static_argnames=("maintain_bloom",))
    table, replies = step(table, batch, maintain_bloom=bloom)
    return table, (np.asarray(replies.rtype), np.asarray(replies.val),
                   np.asarray(replies.ver))


def rand_vals(rng, n):
    return rng.integers(0, 1 << 32, size=(n, VW), dtype=np.uint32)


def test_get_set_basic(rng):
    table = kv.create(1 << 10, slots=4, val_words=VW)
    keys = np.array([7, 9, 7], dtype=np.uint64)
    vals = rand_vals(rng, 3)
    table, (rt, rv, rver) = run_step(table, [Op.SET, Op.SET, Op.GET], keys, vals)
    assert rt[0] == Reply.ACK and rver[0] == 1
    assert rt[1] == Reply.ACK and rver[1] == 1
    # GET sees pre-batch state: key 7 absent before this batch
    assert rt[2] == Reply.NOT_EXIST

    table, (rt, rv, rver) = run_step(
        table, [Op.GET, Op.GET, Op.GET], np.array([7, 9, 1234], np.uint64),
        rand_vals(rng, 3))
    assert rt[0] == Reply.VAL and np.array_equal(rv[0], vals[0]) and rver[0] == 1
    assert rt[1] == Reply.VAL and np.array_equal(rv[1], vals[1])
    assert rt[2] == Reply.NOT_EXIST


def test_delete_and_bloom(rng):
    table = kv.create(1 << 8, slots=4, val_words=VW)
    keys = np.arange(100, dtype=np.uint64)
    table = kv.populate(table, keys, rand_vals(rng, 100))
    table, (rt, _, _) = run_step(table, [Op.DELETE] * 50,
                                 np.arange(50, dtype=np.uint64), rand_vals(rng, 50),
                                 bloom=True)
    assert (rt == Reply.ACK).all()
    d = kv.to_dict(table)
    assert set(d) == set(range(50, 100))
    # double delete -> second acks NOT_EXIST (sequential within batch)
    table, (rt, _, _) = run_step(table, [Op.DELETE, Op.DELETE],
                                 np.array([60, 60], np.uint64), rand_vals(rng, 2))
    assert rt[0] == Reply.ACK and rt[1] == Reply.NOT_EXIST


def test_conflicting_writes_same_key(rng):
    table = kv.create(1 << 8, slots=4, val_words=VW)
    vals = rand_vals(rng, 4)
    # four SETs to the same key in one batch: last lane wins, ver counts all
    table, (rt, _, rver) = run_step(table, [Op.SET] * 4,
                                    np.full(4, 42, np.uint64), vals)
    assert (rt == Reply.ACK).all()
    assert list(rver) == [1, 2, 3, 4]
    d = kv.to_dict(table)
    assert d[42] == (tuple(int(x) for x in vals[3]), 4)


def test_insert_after_delete_same_batch(rng):
    table = kv.create(1 << 8, slots=4, val_words=VW)
    v0 = rand_vals(rng, 1)
    table = kv.populate(table, np.array([5], np.uint64), v0)
    v = rand_vals(rng, 2)
    table, (rt, _, _) = run_step(table, [Op.DELETE, Op.INSERT],
                                 np.array([5, 5], np.uint64), v)
    assert rt[0] == Reply.ACK and rt[1] == Reply.ACK
    d = kv.to_dict(table)
    assert d[5][0] == tuple(int(x) for x in v[1])


def test_bucket_overflow_spills(rng):
    # 1 bucket x 2 slots: third distinct key must SPILL
    table = kv.create(1, slots=2, val_words=VW)
    keys = np.array([1, 2, 3], dtype=np.uint64)
    table, (rt, _, _) = run_step(table, [Op.INSERT] * 3, keys, rand_vals(rng, 3))
    assert sorted(rt) == sorted([Reply.ACK, Reply.ACK, Reply.SPILL])
    assert len(kv.to_dict(table)) == 2


def test_spill_reply_routing(rng):
    # full bucket: SPILL must land on the failed installs, not bystander lanes
    table = kv.create(1, slots=2, val_words=VW)
    table = kv.populate(table, np.array([1, 2], np.uint64), rand_vals(rng, 2))
    # INSERT k then GET k: insert fails -> SPILL; GET sees pre-state -> NOT_EXIST
    table, (rt, _, _) = run_step(table, [Op.INSERT, Op.GET],
                                 np.array([9, 9], np.uint64), rand_vals(rng, 2))
    assert list(rt) == [Reply.SPILL, Reply.NOT_EXIST]
    # both SETs of an un-installable key must SPILL (no phantom ACK)
    table, (rt, _, rver) = run_step(table, [Op.SET, Op.SET],
                                    np.array([9, 9], np.uint64), rand_vals(rng, 2))
    assert list(rt) == [Reply.SPILL, Reply.SPILL]
    assert list(rver) == [0, 0]
    # INSERT then DELETE of un-installable key: net effect is a no-op, so no
    # slot is ever needed — both ops ack (serial-equivalent: the transient
    # insert is observable by nobody)
    table, (rt, _, _) = run_step(table, [Op.INSERT, Op.DELETE],
                                 np.array([9, 9], np.uint64), rand_vals(rng, 2))
    assert list(rt) == [Reply.ACK, Reply.ACK]
    assert len(kv.to_dict(table)) == 2  # table untouched


def test_populate_rejects_duplicates(rng):
    table = kv.create(1 << 4, slots=4, val_words=VW)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="duplicate"):
        kv.populate(table, np.array([5, 5], np.uint64), rand_vals(rng, 2))


@pytest.mark.parametrize("width", [64, 256])
def test_differential_vs_oracle(rng, width):
    table = kv.create(1 << 8, slots=8, val_words=VW)
    oracle = StoreOracle()
    keyspace = 40  # small => heavy intra-batch conflicts
    step = jax.jit(store.step)
    for _ in range(12):
        n = int(rng.integers(width // 2, width + 1))
        ops = rng.choice([Op.GET, Op.SET, Op.INSERT, Op.DELETE, Op.NOP],
                         size=n, p=[0.4, 0.25, 0.1, 0.15, 0.1]).astype(np.int32)
        keys = rng.integers(0, keyspace, size=n).astype(np.uint64)
        vals = rand_vals(rng, n)
        batch = make_batch(ops, keys, vals, width=width, val_words=VW)
        table, replies = step(table, batch)
        rt = np.asarray(replies.rtype)[:n]
        rv = np.asarray(replies.val)[:n]
        rver = np.asarray(replies.ver)[:n]
        ot, ov, over = oracle.step(ops, keys, vals)
        assert np.array_equal(rt, ot), (rt, ot)
        assert np.array_equal(rver, over)
        getmask = (ops == Op.GET) & (ot == Reply.VAL)
        assert np.array_equal(rv[getmask], ov[getmask])
        # full state equivalence every step
        d = kv.to_dict(table)
        assert d == oracle.data


def test_bloom_exact_after_churn(rng):
    table = kv.create(1 << 6, slots=8, val_words=VW)
    keys = np.arange(200, dtype=np.uint64)
    table = kv.populate(table, keys, rand_vals(rng, 200))
    table, _ = run_step(table, [Op.DELETE] * 100, keys[:100], rand_vals(rng, 100),
                        bloom=True)
    # bloom must still admit all live keys (no false negatives)
    import jax.numpy as jnp
    from dint_tpu.ops import hashing, u64
    hi, lo = map(jnp.asarray, u64.split(keys[100:]))
    b1, b2 = hashing.bucket_pair(hi, lo, table.n_buckets)
    ok = np.asarray(kv.bloom_maybe(table, hi, lo, b1, b2))
    assert ok.all()


def test_two_choice_capacity(rng):
    # load factor 0.76 with 4-slot buckets: impossible for single-choice
    # hashing (Poisson tail), fine for two-choice placement
    table = kv.create(1 << 16, slots=4, val_words=VW)
    keys = rng.choice(1 << 40, size=200_000, replace=False).astype(np.uint64)
    table = kv.populate(table, keys, np.zeros((len(keys), VW), np.uint32))
    d = kv.to_dict(table)
    assert len(d) == len(keys)


def test_insert_falls_back_to_alternate_bucket(rng):
    # craft keys sharing the same preferred bucket in a 2-bucket, 1-slot
    # table: the loser of the preferred bucket must land in its alternate,
    # not SPILL (two-choice insert fallback)
    from dint_tpu.ops import hashing
    ks = np.arange(1, 4000, dtype=np.uint64)
    b1, b2 = hashing.bucket_pair_np(ks, 2)
    cands = ks[(b1 == 0) & (b2 == 1)]
    assert len(cands) >= 2
    k1, k2 = cands[:2]
    table = kv.create(2, slots=1, val_words=VW)
    table, (rt, _, _) = run_step(table, [Op.INSERT, Op.INSERT],
                                 np.array([k1, k2], np.uint64), rand_vals(rng, 2))
    assert list(rt) == [Reply.ACK, Reply.ACK]
    assert set(kv.to_dict(table)) == {int(k1), int(k2)}
    # a third key with the same candidates now genuinely has nowhere to go
    k3 = cands[2]
    table, (rt, _, _) = run_step(table, [Op.INSERT],
                                 np.array([k3], np.uint64), rand_vals(rng, 1))
    assert list(rt) == [Reply.SPILL]


# ------------------------------------------------------------- dintscan
# Op.SCAN through step's run∪delta path: pre-batch serial order,
# route bit-identity, the stale/RETRY contract, and the oracle
# differential on adversarial mixed batches.

SMAX = 8
DCAP = 8


def scan_step(table, run, ops, keys, vals, scan_lens, scan_max=SMAX,
              width=None, use_pallas=False):
    batch = make_batch(ops, keys, vals,
                       vers=np.asarray(scan_lens, np.uint32),
                       width=width or len(ops), val_words=VW)
    step = jax.jit(store.step, static_argnames=(
        "maintain_bloom", "use_pallas", "scan_max"))
    table, rep, run, srep = step(table, batch, use_pallas=use_pallas,
                                 run=run, scan_max=scan_max)
    return table, run, rep, srep


def srep_rows(srep, lane):
    """Device scan reply for one lane as the oracle's row list."""
    c = int(np.asarray(srep.count)[lane])
    lo = np.asarray(srep.key_lo)[lane]
    hi = np.asarray(srep.key_hi)[lane].astype(np.uint64)
    ver = np.asarray(srep.ver)[lane]
    val = np.asarray(srep.val)[lane]
    return [(int((hi[j] << 32) | lo[j]),
             tuple(int(x) for x in val[j]), int(ver[j]))
            for j in range(c)]


def test_scan_sees_pre_batch_state(rng):
    table = kv.create(1 << 6, slots=8, val_words=VW)
    vals0 = rand_vals(rng, 3)
    table = kv.populate(table, np.array([10, 20, 30], np.uint64), vals0)
    run = run_mod.from_table(table, delta_cap=DCAP)
    v = rand_vals(rng, 2)
    # SET 15 rides in the SAME batch: the scan must NOT see it (scans
    # are phase-1 reads — a valid serial order puts them with the GETs)
    table, run, rep, srep = scan_step(
        table, run, [Op.SET, Op.SCAN], np.array([15, 10], np.uint64),
        v, [0, 3])
    rt = np.asarray(rep.rtype)
    assert rt[1] == Reply.VAL
    assert int(np.asarray(rep.ver)[1]) == 3
    assert [r[0] for r in srep_rows(srep, 1)] == [10, 20, 30]
    # ...and the NEXT batch's scan sees the install, via the overlay
    table, run, rep, srep = scan_step(
        table, run, [Op.SCAN], np.array([10], np.uint64),
        rand_vals(rng, 1), [4])
    rows = srep_rows(srep, 0)
    assert [r[0] for r in rows] == [10, 15, 20, 30]
    assert rows[1][1] == tuple(int(x) for x in v[0])
    # scan lanes carry rows in the slab, never in the point-reply val
    assert (np.asarray(rep.val)[0] == 0).all()


def test_scan_differential_vs_oracle(rng):
    """Adversarial mixed batches: SCAN lanes straddling same-batch
    SET/INSERT/DELETE writes to the scanned range, reply-for-reply
    against the sequential oracle, run rebuilt at every drain boundary
    (keyspace <= 40: the oracle does not model SPILL)."""
    table = kv.create(1 << 6, slots=8, val_words=VW)
    oracle = StoreOracle()
    run = run_mod.from_table(table, delta_cap=DCAP)
    keyspace, n = 40, 24
    for it in range(12):
        ops = rng.choice(
            [Op.GET, Op.SET, Op.INSERT, Op.DELETE, Op.SCAN, Op.NOP],
            size=n, p=[0.2, 0.2, 0.05, 0.15, 0.3, 0.1]).astype(np.int32)
        keys = rng.integers(0, keyspace, size=n).astype(np.uint64)
        vals = rand_vals(rng, n)
        lens = np.where(ops == Op.SCAN,
                        rng.integers(0, SMAX + 1, size=n), 0)
        table, run, rep, srep = scan_step(table, run, ops, keys, vals,
                                          lens, use_pallas=bool(it % 2))
        rt = np.asarray(rep.rtype)[:n]
        rver = np.asarray(rep.ver)[:n]
        ot, ov, over, oscans = oracle.step(ops, keys, vals,
                                           scan_lens=lens, scan_max=SMAX)
        assert np.array_equal(rt, ot), (it, rt, ot)
        assert np.array_equal(rver, over), it
        for i in np.nonzero(ops == Op.SCAN)[0]:
            assert srep_rows(srep, i) == oscans[i], (it, i, keys[i])
        # drain boundary: fold the overlay before the overlay overflows
        run = store.rebuild_run(table, run)
        assert run_mod.to_items(run) == oracle.data
        assert kv.to_dict(table) == oracle.data


def test_scan_never_sees_spilled_insert(rng):
    """A SPILLed insert lands NOWHERE — not the table, not the overlay:
    a later scan over its range must skip it (the same fixup that keeps
    replies honest keeps the run honest)."""
    from dint_tpu.ops import hashing
    ks = np.arange(1, 4000, dtype=np.uint64)
    b1, b2 = hashing.bucket_pair_np(ks, 4)
    cands = ks[(b1 == 0) & (b2 == 1)]
    assert len(cands) >= 3
    k1, k2, k3 = (int(x) for x in cands[:3])
    table = kv.create(4, slots=1, val_words=VW)       # ne=4 >= 2+2
    run = run_mod.from_table(table, delta_cap=2)
    v = rand_vals(rng, 4)
    # k3's both buckets are full after k1/k2 land -> SPILL, same batch
    table, run, rep, srep = scan_step(
        table, run, [Op.INSERT, Op.INSERT, Op.INSERT, Op.SCAN],
        np.array([k1, k2, k3, 0], np.uint64), v, [0, 0, 0, 2],
        scan_max=2)
    rt = np.asarray(rep.rtype)
    assert list(rt[:3]) == [Reply.ACK, Reply.ACK, Reply.SPILL]
    assert srep_rows(srep, 3) == []                   # pre-batch: empty
    table, run, rep, srep = scan_step(
        table, run, [Op.SCAN], np.array([0], np.uint64),
        rand_vals(rng, 1), [2], scan_max=2)
    got = [r[0] for r in srep_rows(srep, 0)]
    assert got == sorted((k1, k2))[:2] and k3 not in got
    assert k3 not in run_mod.to_items(run)


def test_scan_three_routes_bit_identical(rng):
    """Acceptance: identical ScanReplies from (a) the XLA slab-gather
    fallback, (b) the pallas scan_rows kernel, and (c) the XLA route
    after a drain-boundary rebuild_run folded the overlay."""
    table = kv.create(1 << 6, slots=8, val_words=VW)
    keys = rng.choice(40, size=25, replace=False).astype(np.uint64)
    table = kv.populate(table, keys, rand_vals(rng, 25))
    run = run_mod.from_table(table, delta_cap=DCAP)
    # populate the overlay: writes + a delete through the scan-threaded
    # step (effective-writer lanes are what delta_append receives)
    wops = [Op.SET, Op.SET, Op.INSERT, Op.DELETE]
    wkeys = np.array([keys[0], keys[1], 41, keys[2]], np.uint64)
    table, run, _, _ = scan_step(table, run, wops, wkeys,
                                 rand_vals(rng, 4), [0, 0, 0, 0])
    assert int(run.d_n) > 0
    sops = [Op.SCAN] * 6
    starts = np.array([0, 5, 17, 38, 41, 100], np.uint64)
    lens = np.array([SMAX, 3, 5, SMAX, 1, 4])
    svals = rand_vals(rng, 6)

    def answer(t, rn, use_pallas):
        _, _, rep, srep = scan_step(t, rn, sops, starts, svals, lens,
                                    use_pallas=use_pallas)
        return rep, srep

    rep_a, srep_a = answer(table, run, False)
    rep_b, srep_b = answer(table, run, True)
    rebuilt = store.rebuild_run(table, run)
    assert int(rebuilt.d_n) == 0
    rep_c, srep_c = answer(table, rebuilt, False)
    for rep, srep in ((rep_b, srep_b), (rep_c, srep_c)):
        assert np.array_equal(np.asarray(rep.rtype),
                              np.asarray(rep_a.rtype))
        assert np.array_equal(np.asarray(rep.ver), np.asarray(rep_a.ver))
        for f in ("key_hi", "key_lo", "ver", "val", "count"):
            assert np.array_equal(np.asarray(getattr(srep, f)),
                                  np.asarray(getattr(srep_a, f))), f
    # the overlay-pending routes served rows from the delta...
    assert int(np.asarray(srep_a.delta_hits).sum()) > 0
    # ...and the rebuilt run serves the same rows from the dense run
    assert int(np.asarray(srep_c.delta_hits).sum()) == 0


def test_scan_stale_overlay_replies_retry_until_rebuild(rng):
    table = kv.create(1 << 6, slots=8, val_words=VW)
    table = kv.populate(table, np.arange(1, 9, dtype=np.uint64),
                        rand_vals(rng, 8))
    run = run_mod.from_table(table, delta_cap=2)
    # 4 distinct-key writes overflow the 2-entry overlay -> stale
    table, run, _, _ = scan_step(
        table, run, [Op.SET] * 4, np.array([1, 2, 3, 4], np.uint64),
        rand_vals(rng, 4), [0] * 4, scan_max=2)
    assert bool(np.asarray(run.stale))
    table, run, rep, srep = scan_step(
        table, run, [Op.SCAN], np.array([1], np.uint64),
        rand_vals(rng, 1), [2], scan_max=2)
    assert int(np.asarray(rep.rtype)[0]) == Reply.RETRY
    assert int(np.asarray(srep.count)[0]) == 0        # stale: no rows
    # drain-boundary refresh re-snapshots; the retry answers VAL
    run = store.rebuild_run(table, run)
    assert not bool(np.asarray(run.stale))
    table, run, rep, srep = scan_step(
        table, run, [Op.SCAN], np.array([1], np.uint64),
        rand_vals(rng, 1), [2], scan_max=2)
    assert int(np.asarray(rep.rtype)[0]) == Reply.VAL
    assert [r[0] for r in srep_rows(srep, 0)] == [1, 2]
