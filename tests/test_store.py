import jax
import numpy as np
import pytest

from dint_tpu.engines import store
from dint_tpu.engines.types import Op, Reply, make_batch
from dint_tpu.tables import kv
from dint_tpu.testing.oracle import StoreOracle

VW = 4


def run_step(table, ops, keys, vals, width=None, bloom=False):
    batch = make_batch(ops, keys, vals, width=width or len(ops), val_words=VW)
    step = jax.jit(store.step, static_argnames=("maintain_bloom",))
    table, replies = step(table, batch, maintain_bloom=bloom)
    return table, (np.asarray(replies.rtype), np.asarray(replies.val),
                   np.asarray(replies.ver))


def rand_vals(rng, n):
    return rng.integers(0, 1 << 32, size=(n, VW), dtype=np.uint32)


def test_get_set_basic(rng):
    table = kv.create(1 << 10, slots=4, val_words=VW)
    keys = np.array([7, 9, 7], dtype=np.uint64)
    vals = rand_vals(rng, 3)
    table, (rt, rv, rver) = run_step(table, [Op.SET, Op.SET, Op.GET], keys, vals)
    assert rt[0] == Reply.ACK and rver[0] == 1
    assert rt[1] == Reply.ACK and rver[1] == 1
    # GET sees pre-batch state: key 7 absent before this batch
    assert rt[2] == Reply.NOT_EXIST

    table, (rt, rv, rver) = run_step(
        table, [Op.GET, Op.GET, Op.GET], np.array([7, 9, 1234], np.uint64),
        rand_vals(rng, 3))
    assert rt[0] == Reply.VAL and np.array_equal(rv[0], vals[0]) and rver[0] == 1
    assert rt[1] == Reply.VAL and np.array_equal(rv[1], vals[1])
    assert rt[2] == Reply.NOT_EXIST


def test_delete_and_bloom(rng):
    table = kv.create(1 << 8, slots=4, val_words=VW)
    keys = np.arange(100, dtype=np.uint64)
    table = kv.populate(table, keys, rand_vals(rng, 100))
    table, (rt, _, _) = run_step(table, [Op.DELETE] * 50,
                                 np.arange(50, dtype=np.uint64), rand_vals(rng, 50),
                                 bloom=True)
    assert (rt == Reply.ACK).all()
    d = kv.to_dict(table)
    assert set(d) == set(range(50, 100))
    # double delete -> second acks NOT_EXIST (sequential within batch)
    table, (rt, _, _) = run_step(table, [Op.DELETE, Op.DELETE],
                                 np.array([60, 60], np.uint64), rand_vals(rng, 2))
    assert rt[0] == Reply.ACK and rt[1] == Reply.NOT_EXIST


def test_conflicting_writes_same_key(rng):
    table = kv.create(1 << 8, slots=4, val_words=VW)
    vals = rand_vals(rng, 4)
    # four SETs to the same key in one batch: last lane wins, ver counts all
    table, (rt, _, rver) = run_step(table, [Op.SET] * 4,
                                    np.full(4, 42, np.uint64), vals)
    assert (rt == Reply.ACK).all()
    assert list(rver) == [1, 2, 3, 4]
    d = kv.to_dict(table)
    assert d[42] == (tuple(int(x) for x in vals[3]), 4)


def test_insert_after_delete_same_batch(rng):
    table = kv.create(1 << 8, slots=4, val_words=VW)
    v0 = rand_vals(rng, 1)
    table = kv.populate(table, np.array([5], np.uint64), v0)
    v = rand_vals(rng, 2)
    table, (rt, _, _) = run_step(table, [Op.DELETE, Op.INSERT],
                                 np.array([5, 5], np.uint64), v)
    assert rt[0] == Reply.ACK and rt[1] == Reply.ACK
    d = kv.to_dict(table)
    assert d[5][0] == tuple(int(x) for x in v[1])


def test_bucket_overflow_spills(rng):
    # 1 bucket x 2 slots: third distinct key must SPILL
    table = kv.create(1, slots=2, val_words=VW)
    keys = np.array([1, 2, 3], dtype=np.uint64)
    table, (rt, _, _) = run_step(table, [Op.INSERT] * 3, keys, rand_vals(rng, 3))
    assert sorted(rt) == sorted([Reply.ACK, Reply.ACK, Reply.SPILL])
    assert len(kv.to_dict(table)) == 2


def test_spill_reply_routing(rng):
    # full bucket: SPILL must land on the failed installs, not bystander lanes
    table = kv.create(1, slots=2, val_words=VW)
    table = kv.populate(table, np.array([1, 2], np.uint64), rand_vals(rng, 2))
    # INSERT k then GET k: insert fails -> SPILL; GET sees pre-state -> NOT_EXIST
    table, (rt, _, _) = run_step(table, [Op.INSERT, Op.GET],
                                 np.array([9, 9], np.uint64), rand_vals(rng, 2))
    assert list(rt) == [Reply.SPILL, Reply.NOT_EXIST]
    # both SETs of an un-installable key must SPILL (no phantom ACK)
    table, (rt, _, rver) = run_step(table, [Op.SET, Op.SET],
                                    np.array([9, 9], np.uint64), rand_vals(rng, 2))
    assert list(rt) == [Reply.SPILL, Reply.SPILL]
    assert list(rver) == [0, 0]
    # INSERT then DELETE of un-installable key: net effect is a no-op, so no
    # slot is ever needed — both ops ack (serial-equivalent: the transient
    # insert is observable by nobody)
    table, (rt, _, _) = run_step(table, [Op.INSERT, Op.DELETE],
                                 np.array([9, 9], np.uint64), rand_vals(rng, 2))
    assert list(rt) == [Reply.ACK, Reply.ACK]
    assert len(kv.to_dict(table)) == 2  # table untouched


def test_populate_rejects_duplicates(rng):
    table = kv.create(1 << 4, slots=4, val_words=VW)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="duplicate"):
        kv.populate(table, np.array([5, 5], np.uint64), rand_vals(rng, 2))


@pytest.mark.parametrize("width", [64, 256])
def test_differential_vs_oracle(rng, width):
    table = kv.create(1 << 8, slots=8, val_words=VW)
    oracle = StoreOracle()
    keyspace = 40  # small => heavy intra-batch conflicts
    step = jax.jit(store.step)
    for _ in range(12):
        n = int(rng.integers(width // 2, width + 1))
        ops = rng.choice([Op.GET, Op.SET, Op.INSERT, Op.DELETE, Op.NOP],
                         size=n, p=[0.4, 0.25, 0.1, 0.15, 0.1]).astype(np.int32)
        keys = rng.integers(0, keyspace, size=n).astype(np.uint64)
        vals = rand_vals(rng, n)
        batch = make_batch(ops, keys, vals, width=width, val_words=VW)
        table, replies = step(table, batch)
        rt = np.asarray(replies.rtype)[:n]
        rv = np.asarray(replies.val)[:n]
        rver = np.asarray(replies.ver)[:n]
        ot, ov, over = oracle.step(ops, keys, vals)
        assert np.array_equal(rt, ot), (rt, ot)
        assert np.array_equal(rver, over)
        getmask = (ops == Op.GET) & (ot == Reply.VAL)
        assert np.array_equal(rv[getmask], ov[getmask])
        # full state equivalence every step
        d = kv.to_dict(table)
        assert d == oracle.data


def test_bloom_exact_after_churn(rng):
    table = kv.create(1 << 6, slots=8, val_words=VW)
    keys = np.arange(200, dtype=np.uint64)
    table = kv.populate(table, keys, rand_vals(rng, 200))
    table, _ = run_step(table, [Op.DELETE] * 100, keys[:100], rand_vals(rng, 100),
                        bloom=True)
    # bloom must still admit all live keys (no false negatives)
    import jax.numpy as jnp
    from dint_tpu.ops import hashing, u64
    hi, lo = map(jnp.asarray, u64.split(keys[100:]))
    b1, b2 = hashing.bucket_pair(hi, lo, table.n_buckets)
    ok = np.asarray(kv.bloom_maybe(table, hi, lo, b1, b2))
    assert ok.all()


def test_two_choice_capacity(rng):
    # load factor 0.76 with 4-slot buckets: impossible for single-choice
    # hashing (Poisson tail), fine for two-choice placement
    table = kv.create(1 << 16, slots=4, val_words=VW)
    keys = rng.choice(1 << 40, size=200_000, replace=False).astype(np.uint64)
    table = kv.populate(table, keys, np.zeros((len(keys), VW), np.uint32))
    d = kv.to_dict(table)
    assert len(d) == len(keys)


def test_insert_falls_back_to_alternate_bucket(rng):
    # craft keys sharing the same preferred bucket in a 2-bucket, 1-slot
    # table: the loser of the preferred bucket must land in its alternate,
    # not SPILL (two-choice insert fallback)
    from dint_tpu.ops import hashing
    ks = np.arange(1, 4000, dtype=np.uint64)
    b1, b2 = hashing.bucket_pair_np(ks, 2)
    cands = ks[(b1 == 0) & (b2 == 1)]
    assert len(cands) >= 2
    k1, k2 = cands[:2]
    table = kv.create(2, slots=1, val_words=VW)
    table, (rt, _, _) = run_step(table, [Op.INSERT, Op.INSERT],
                                 np.array([k1, k2], np.uint64), rand_vals(rng, 2))
    assert list(rt) == [Reply.ACK, Reply.ACK]
    assert set(kv.to_dict(table)) == {int(k1), int(k2)}
    # a third key with the same candidates now genuinely has nowhere to go
    k3 = cands[2]
    table, (rt, _, _) = run_step(table, [Op.INSERT],
                                 np.array([k3], np.uint64), rand_vals(rng, 1))
    assert list(rt) == [Reply.SPILL]
