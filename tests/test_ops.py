import jax
import jax.numpy as jnp
import numpy as np

from dint_tpu.ops import hashing, segments, u64


def test_u64_split_join_roundtrip(rng):
    x = rng.integers(0, 1 << 64, size=1000, dtype=np.uint64)
    hi, lo = u64.split(x)
    assert np.array_equal(u64.join(hi, lo), x)


def test_u64_mul_matches_numpy(rng):
    a = rng.integers(0, 1 << 64, size=512, dtype=np.uint64)
    b = rng.integers(0, 1 << 64, size=512, dtype=np.uint64)
    a_hi, a_lo = u64.split(a)
    b_hi, b_lo = u64.split(b)
    hi, lo = jax.jit(u64.mul)(jnp.asarray(a_hi), jnp.asarray(a_lo),
                              jnp.asarray(b_hi), jnp.asarray(b_lo))
    with np.errstate(over="ignore"):
        want = a * b
    assert np.array_equal(u64.join(np.asarray(hi), np.asarray(lo)), want)


def test_u64_shr_shl(rng):
    x = rng.integers(0, 1 << 64, size=256, dtype=np.uint64)
    hi, lo = map(jnp.asarray, u64.split(x))
    for n in (1, 16, 23, 31, 32, 33, 47, 63):
        s_hi, s_lo = u64.shr(hi, lo, n)
        assert np.array_equal(u64.join(np.asarray(s_hi), np.asarray(s_lo)),
                              x >> np.uint64(n)), f"shr {n}"
        s_hi, s_lo = u64.shl(hi, lo, n)
        with np.errstate(over="ignore"):
            want = (x << np.uint64(n)) & np.uint64(0xFFFFFFFFFFFFFFFF)
        assert np.array_equal(u64.join(np.asarray(s_hi), np.asarray(s_lo)), want), f"shl {n}"


def test_u64_add_lt(rng):
    a = rng.integers(0, 1 << 64, size=256, dtype=np.uint64)
    b = rng.integers(0, 1 << 64, size=256, dtype=np.uint64)
    a_hi, a_lo = map(jnp.asarray, u64.split(a))
    b_hi, b_lo = map(jnp.asarray, u64.split(b))
    s_hi, s_lo = u64.add(a_hi, a_lo, b_hi, b_lo)
    with np.errstate(over="ignore"):
        want = a + b
    assert np.array_equal(u64.join(np.asarray(s_hi), np.asarray(s_lo)), want)
    assert np.array_equal(np.asarray(u64.lt(a_hi, a_lo, b_hi, b_lo)), a < b)


def test_u64_carry_boundaries():
    """Stamp arithmetic at the hi-word carry boundary: ground truth for the
    dintlint u64_overflow pass (ANALYSIS.md). 0xFFFFFFFF -> 0x1_00000000 is
    exactly where a lo-word-only (or sign-drifted int32) implementation
    silently wraps while the (hi, lo) pair must carry."""
    edges = np.array([0xFFFFFFFF,              # lo all-ones: +1 must carry
                      0x1_00000000,            # the carry landing point
                      0x1_FFFFFFFF,
                      0x7FFFFFFF,              # int32 sign boundary
                      0x80000000,              # int32 wraparound point
                      0xFFFFFFFF_FFFFFFFF],    # max stamp
                     dtype=np.uint64)
    one = (jnp.zeros(len(edges), jnp.uint32), jnp.ones(len(edges), jnp.uint32))
    hi, lo = map(jnp.asarray, u64.split(edges))
    s_hi, s_lo = jax.jit(u64.add)(hi, lo, *one)
    with np.errstate(over="ignore"):
        want = edges + np.uint64(1)            # max-stamp wraps to 0
    assert np.array_equal(u64.join(np.asarray(s_hi), np.asarray(s_lo)), want)
    # the max stamp + 1 wrapped all the way to zero through BOTH words
    assert int(np.asarray(s_hi)[-1]) == 0 and int(np.asarray(s_lo)[-1]) == 0


def test_u64_lt_at_hi_word_boundary():
    """Unsigned compare must order by the hi word first: 0xFFFFFFFF (hi=0)
    < 0x1_00000000 (hi=1) even though the lo words compare the other way —
    the compare a signed/lo-only stamp implementation gets wrong."""
    a = np.array([0xFFFFFFFF, 0x1_00000000, 0x80000000,
                  0xFFFFFFFF_FFFFFFFF, 0x7FFFFFFF_FFFFFFFF],
                 dtype=np.uint64)
    b = np.array([0x1_00000000, 0xFFFFFFFF, 0x7FFFFFFF,
                  0x0, 0x80000000_00000000], dtype=np.uint64)
    a_hi, a_lo = map(jnp.asarray, u64.split(a))
    b_hi, b_lo = map(jnp.asarray, u64.split(b))
    assert np.array_equal(np.asarray(jax.jit(u64.lt)(a_hi, a_lo,
                                                     b_hi, b_lo)), a < b)
    assert np.array_equal(np.asarray(jax.jit(u64.eq)(a_hi, a_lo,
                                                     b_hi, b_lo)), a == b)


def test_u64_mul32x32_carry_saturation():
    """mul32x32's 16-bit-limb mid-sum carries (c1+c2) at the all-ones
    inputs: 0xFFFFFFFF^2 = 0xFFFFFFFE_00000001 exercises both carry
    outs; a dropped carry loses bit 32/33 of the product."""
    vals = np.array([0xFFFFFFFF, 0xFFFF0001, 0x80000000, 0x10001],
                    np.uint64).astype(np.uint32)
    a = jnp.asarray(vals)
    hi, lo = jax.jit(u64.mul32x32)(a, a)
    want = vals.astype(np.uint64) * vals.astype(np.uint64)
    assert np.array_equal(u64.join(np.asarray(hi), np.asarray(lo)), want)


def test_hash_device_matches_host(rng):
    keys = rng.integers(0, 1 << 64, size=2048, dtype=np.uint64)
    hi, lo = map(jnp.asarray, u64.split(keys))
    d_hi, d_lo = jax.jit(hashing.hash64)(hi, lo)
    got = u64.join(np.asarray(d_hi), np.asarray(d_lo))
    assert np.array_equal(got, hashing.hash64_np(keys))


def test_bucket_and_bloom(rng):
    keys = rng.integers(0, 1 << 64, size=4096, dtype=np.uint64)
    hi, lo = map(jnp.asarray, u64.split(keys))
    nb = 1 << 14
    b = np.asarray(jax.jit(lambda h, l: hashing.bucket(h, l, nb))(hi, lo))
    assert np.array_equal(b, hashing.bucket_np(keys, nb))
    assert b.min() >= 0 and b.max() < nb
    # buckets should be reasonably uniform
    counts = np.bincount(b, minlength=nb)
    assert counts.max() <= 12
    bb = np.asarray(jax.jit(hashing.bloom_bit)(hi, lo))
    assert np.array_equal(bb, hashing.bloom_bit_np(keys))
    assert bb.min() >= 0 and bb.max() < 64
    assert len(np.unique(bb)) == 64


def _ref_segments(keys):
    """Sequential reference for segment structure."""
    order = np.argsort(keys, kind="stable")
    return order


def test_sort_batch_structure(rng):
    keys = rng.integers(0, 8, size=64, dtype=np.uint64)  # lots of duplicates
    hi, lo = map(jnp.asarray, u64.split(keys))
    sb = jax.jit(segments.sort_batch)(hi, lo)
    perm = np.asarray(sb.perm)
    skeys = keys[perm]
    assert np.all(np.diff(skeys.astype(np.int64)) >= 0)
    # stable: equal keys keep arrival order
    for k in np.unique(skeys):
        idxs = perm[skeys == k]
        assert np.all(np.diff(idxs) > 0)
    head = np.asarray(sb.head)
    want_head = np.concatenate([[True], skeys[1:] != skeys[:-1]])
    assert np.array_equal(head, want_head)
    rank = np.asarray(sb.rank)
    # rank counts arrival position within the key group
    for k in np.unique(skeys):
        r = rank[skeys == k]
        assert np.array_equal(r, np.arange(len(r)))


def test_segment_reductions(rng):
    keys = rng.integers(0, 10, size=128, dtype=np.uint64)
    vals = rng.integers(0, 100, size=128).astype(np.int32)
    hi, lo = map(jnp.asarray, u64.split(keys))
    sb = segments.sort_batch(hi, lo)
    perm = np.asarray(sb.perm)
    skeys, svals = keys[perm], jnp.asarray(vals[perm])

    tot = np.asarray(segments.seg_sum(sb, svals))
    excl = np.asarray(segments.seg_cumsum_excl(sb, svals))
    for i, k in enumerate(skeys):
        mask = skeys == k
        assert tot[i] == vals[perm][mask].sum()
        assert excl[i] == np.asarray(svals)[mask & (np.arange(128) < i)].sum()

    # unsort roundtrip
    out = np.asarray(segments.unsort(sb, svals))
    assert np.array_equal(out, vals)


def test_scatter_rows_masked():
    table = jnp.zeros((8, 2), jnp.int32)
    idx = jnp.array([1, 3, 3, 7], jnp.int32)
    vals = jnp.array([[1, 1], [2, 2], [9, 9], [4, 4]], jnp.int32)
    mask = jnp.array([True, False, True, True])
    out = np.asarray(segments.scatter_rows(table, idx, vals, mask))
    assert np.array_equal(out[1], [1, 1])
    assert np.array_equal(out[3], [9, 9])  # only the masked-in writer landed
    assert np.array_equal(out[7], [4, 4])
    assert out.sum() == 28


def test_cpu_monitor_measures_busy_host():
    import time

    from dint_tpu.stats import CpuMonitor

    mon = CpuMonitor()
    t0 = time.time()
    x = 0
    while time.time() - t0 < 0.4:    # burn user cpu
        x += sum(range(1000))
    cores = mon.cores()
    assert set(cores) == {"host_ucores", "host_kcores", "proc_ucores",
                          "proc_kcores"}
    # ~1 user core nominally; generous floor for loaded/quota'd runners
    assert cores["proc_ucores"] > 0.1
    assert cores["host_ucores"] >= cores["proc_ucores"] - 0.2
    for v in cores.values():
        assert v >= 0


def test_mix_thresholds_normalizes_raw_weights():
    """Raw (unnormalized) weights must sample the same distribution as
    fractions — jax.random.choice normalized internally, and the
    closed-form sampler must too (sweep ablations pass raw mixes)."""
    from dint_tpu.clients import workloads as wl

    frac = wl.mix_thresholds(np.asarray(wl.TATP_MIX))
    raw = wl.mix_thresholds(np.asarray(wl.TATP_MIX) * 100.0)
    assert np.array_equal(frac, raw)
    assert frac[-1] == 0xFFFFFFFF
    # empirical check: 1M words land within 0.5% of each target fraction
    words = np.random.default_rng(0).integers(0, 1 << 32, 1_000_000,
                                              dtype=np.uint64)
    t = np.minimum(np.searchsorted(frac, words, side="right"),
                   len(frac) - 1)
    got = np.bincount(t, minlength=len(frac)) / len(words)
    assert np.abs(got - np.asarray(wl.TATP_MIX)).max() < 0.005


def test_oob_dup_scatter_unique_indices():
    """Pin the lowering behavior every engine's masked scatter relies on:
    all masked lanes share ONE out-of-bounds sentinel index under
    unique_indices=True + mode="drop" (see engines/store.py scatter note).
    Duplicated OOB indices are technically outside JAX's uniqueness
    contract; if a jaxlib upgrade changes how drop interacts with dedup,
    this must fail before the differential tests see corrupted tables."""
    import jax
    import jax.numpy as jnp

    n = 16

    @jax.jit
    def scatter(arr, idx, val):
        return arr.at[idx].set(val, mode="drop", unique_indices=True)

    arr = jnp.zeros(n, jnp.uint32)
    # 2 real writers, 6 masked lanes all routed to the OOB sentinel n
    idx = jnp.asarray([3, n, n, 7, n, n, n, n], jnp.int32)
    val = jnp.arange(1, 9, dtype=jnp.uint32)
    out = np.asarray(scatter(arr, idx, val))
    expect = np.zeros(n, np.uint32)
    expect[3], expect[7] = 1, 4
    assert np.array_equal(out, expect)
