import jax
import numpy as np

from dint_tpu.engines import tatp
from dint_tpu.engines.types import Op, Reply
from dint_tpu.parallel import sharded

VW = 4


def test_replicated_step_8dev(rng):
    n = 8
    assert len(jax.devices()) >= n
    mesh = sharded.make_mesh(n)
    p = 64  # global subscribers
    state = sharded.create_sharded_state(mesh, n, p, val_words=VW,
                                         cf_buckets=256, cf_lock_slots=256)
    step = sharded.build_sharded_step(mesh, n)

    # lock a set of subscriber rows (primary-routed), then commit them
    keys = rng.choice(np.arange(1, p + 1), size=32, replace=False).astype(np.int64)
    m = len(keys)
    ops = np.full(m, Op.OCC_LOCK, np.int32)
    tbls = np.full(m, tatp.SUBSCRIBER, np.int32)
    width = 16
    batch, owner = sharded.route_batches(ops, tbls, keys, None, None, n, width, VW)
    state, replies, committed = step(state, batch)
    rt = np.asarray(replies.rtype)
    # every routed lock lane granted (fresh locks, distinct rows)
    for d in range(n):
        cnt = int((owner == d).sum())
        assert (rt[d, :cnt] == Reply.GRANT).all()
    assert int(committed[0]) == 0

    # commit new values at primaries; replication must land on both backups
    vals = np.zeros((m, VW), np.uint32)
    vals[:, 0] = 1234
    ops = np.full(m, Op.COMMIT_PRIM, np.int32)
    batch, owner = sharded.route_batches(ops, tbls, keys, vals, None, n, width, VW)
    state, replies, committed = step(state, batch)
    assert int(committed[0]) == m  # psum'd vote count, same on every device

    # pull state host-side and check primary + both replicas of each key
    sub_val = np.asarray(jax.device_get(state.sub.val))  # [n, rows, VW]
    sub_ver = np.asarray(jax.device_get(state.sub.ver))
    for k in keys:
        own = int(k % n)
        for role in range(3):
            dev = (own + role) % n
            local = int(sharded.local_dense_key(k, n, role))
            assert sub_val[dev, local, 0] == 1234, (k, role)
            # state starts empty: the commit creates the row at ver 1
            assert sub_ver[dev, local] == 1, (k, role)

    # locks released by COMMIT_PRIM at the primary
    sub_lock = np.asarray(jax.device_get(state.sub_lock))
    assert not sub_lock.any()


def test_route_batches_padding(rng):
    keys = np.array([0, 1, 2, 9, 10], np.int64)
    ops = np.full(5, Op.OCC_READ, np.int32)
    tbls = np.zeros(5, np.int32)
    batch, owner = sharded.route_batches(ops, tbls, keys, None, None, 3, 8, VW)
    assert batch.op.shape == (3, 8)
    # owner 0: keys 0, 9; owner 1: 1, 10; owner 2: 2
    assert list(np.asarray(batch.op).sum(axis=1)) == [2 * Op.OCC_READ,
                                                      2 * Op.OCC_READ,
                                                      Op.OCC_READ]
