import jax
import numpy as np

from dint_tpu.engines import tatp
from dint_tpu.engines.types import Op, Reply
from dint_tpu.parallel import sharded

VW = 4


def test_replicated_step_8dev(rng):
    n = 8
    assert len(jax.devices()) >= n
    mesh = sharded.make_mesh(n)
    p = 64  # global subscribers
    state = sharded.create_sharded_state(mesh, n, p, val_words=VW,
                                         cf_buckets=256, cf_lock_slots=256,
                                         log_capacity=1 << 12)
    step = sharded.build_sharded_step(mesh, n)

    # lock a set of subscriber rows (primary-routed), then commit them
    keys = rng.choice(np.arange(1, p + 1), size=32, replace=False).astype(np.int64)
    m = len(keys)
    ops = np.full(m, Op.OCC_LOCK, np.int32)
    tbls = np.full(m, tatp.SUBSCRIBER, np.int32)
    width = 16
    (batch,), owner = sharded.route_batches(ops, tbls, keys, None, None, n, width, VW)
    state, replies, committed = step(state, batch)
    rt = np.asarray(replies.rtype)
    # every routed lock lane granted (fresh locks, distinct rows)
    for d in range(n):
        cnt = int((owner == d).sum())
        assert (rt[d, :cnt] == Reply.GRANT).all()
    assert int(committed[0]) == 0

    # commit new values at primaries; replication must land on both backups
    vals = np.zeros((m, VW), np.uint32)
    vals[:, 0] = 1234
    ops = np.full(m, Op.COMMIT_PRIM, np.int32)
    (batch,), owner = sharded.route_batches(ops, tbls, keys, vals, None, n, width, VW)
    state, replies, committed = step(state, batch)
    assert int(committed[0]) == m  # psum'd vote count, same on every device

    # pull state host-side and check primary + both replicas of each key
    sub_val = np.asarray(jax.device_get(state.sub.val))
    sub_val = sub_val.reshape(sub_val.shape[0], -1, VW)  # [n, rows, VW]
    sub_ver = np.asarray(jax.device_get(state.sub.ver))
    for k in keys:
        own = int(k % n)
        for role in range(3):
            dev = (own + role) % n
            local = int(sharded.local_dense_key(k, n, role))
            assert sub_val[dev, local, 0] == 1234, (k, role)
            # state starts empty: the commit creates the row at ver 1
            assert sub_ver[dev, local] == 1, (k, role)

    # locks released by COMMIT_PRIM at the primary
    sub_lock = np.asarray(jax.device_get(state.sub_lock))
    assert not sub_lock.any()


def test_route_batches_padding(rng):
    keys = np.array([0, 1, 2, 9, 10], np.int64)
    ops = np.full(5, Op.OCC_READ, np.int32)
    tbls = np.zeros(5, np.int32)
    (batch,), owner = sharded.route_batches(ops, tbls, keys, None, None, 3, 8, VW)
    assert batch.op.shape == (3, 8)
    # owner 0: keys 0, 9; owner 1: 1, 10; owner 2: 2
    assert list(np.asarray(batch.op).sum(axis=1)) == [2 * Op.OCC_READ,
                                                      2 * Op.OCC_READ,
                                                      Op.OCC_READ]


def test_route_batches_spills_on_skew():
    # adversarial skew: every key owned by device 0, 3x the batch width --
    # must spill across waves, not crash
    keys = np.arange(0, 72, 3, dtype=np.int64)   # 24 keys, all % 3 == 0
    ops = np.full(24, Op.OCC_READ, np.int32)
    tbls = np.zeros(24, np.int32)
    waves, owner = sharded.route_batches(ops, tbls, keys, None, None, 3, 8, VW)
    assert len(waves) == 3
    total = sum(int((np.asarray(b.op) == Op.OCC_READ).sum()) for b in waves)
    assert total == 24
    for b in waves:
        assert (np.asarray(b.op)[1:] == Op.NOP).all()   # other devices idle


def test_sharded_smallbank_8dev(rng):
    from dint_tpu.engines import smallbank

    n = 8
    mesh = sharded.make_mesh(n)
    n_accounts = 64
    state = sharded.create_sharded_smallbank(mesh, n, n_accounts, val_words=2,
                                             log_capacity=1 << 12)
    step = sharded.build_sharded_step(mesh, n, engine="smallbank")

    accts = rng.choice(np.arange(n_accounts), size=32, replace=False).astype(np.int64)
    m = len(accts)
    width = 16

    # X-lock + fused read at primaries
    ops = np.full(m, Op.ACQ_X_READ, np.int32)
    tbls = np.full(m, smallbank.SAVINGS, np.int32)
    waves, owner = sharded.route_batches(ops, tbls, accts, None, None, n,
                                         width, 2)
    assert len(waves) == 1
    state, replies, _ = step(state, waves[0])
    rt = np.asarray(replies.rtype)
    for d in range(n):
        cnt = int((owner == d).sum())
        assert (rt[d, :cnt] == Reply.GRANT).all()

    # commit balances at primaries (client supplies the bumped version,
    # clients/smallbank_client.py c_ver = rver1 + 1); replication lands on
    # both backup roles via ppermute
    vals = np.zeros((m, 2), np.uint32)
    vals[:, 0] = 777
    vers = np.ones(m, np.uint32)
    ops = np.full(m, Op.COMMIT_PRIM, np.int32)
    waves, owner = sharded.route_batches(ops, tbls, accts, vals, vers, n,
                                         width, 2)
    state, replies, committed = step(state, waves[0])
    assert int(committed[0]) == m

    sav_val = np.asarray(jax.device_get(state.sav.val))
    sav_val = sav_val.reshape(sav_val.shape[0], -1, 2)  # [n, rows, 2]
    for a in accts:
        own = int(a % n)
        for role in range(3):
            dev = (own + role) % n
            local = int(sharded.local_dense_key(a, n, role))
            assert sav_val[dev, local, 0] == 777, (a, role)

    # explicit release wave (lock -> log -> bck -> prim -> RELEASE protocol,
    # smallbank/caladan/client_ebpf_shard.cc:389-560)
    ops = np.full(m, Op.REL_X, np.int32)
    waves, _ = sharded.route_batches(ops, tbls, accts, None, None, n,
                                     width, 2)
    state, replies, _ = step(state, waves[0])
    assert int(np.asarray(jax.device_get(state.sav_ex)).sum()) == 0
