"""Round-12 megakernels: lock_validate + install_log (ISSUE 8).

The contract under test, per acceptance criteria:
  * kernel-vs-unfused parity at the op level, including adversarial
    duplicate-index batches and lock batches straddling the hot_n VMEM
    prefix — the fused dispatch must be bit-identical to the two
    dispatches it swallows;
  * the probe-and-degrade contract: DINT_USE_FUSED defaults off,
    explicit kwarg beats the env, and a Mosaic rejection (simulated)
    degrades to the unfused path without raising — and is cached;
  * both dense engines and both sharded paths produce bit-identical
    final state + stats with the fused waves on vs off (the tatp pin
    drives the env plumbing: DINT_USE_FUSED=1 with use_fused=None);
  * the fused waves compose with the round-10 hot-set tier and the
    round-6 Pallas backends (DINT_USE_FUSED x DINT_USE_HOTSET x
    DINT_USE_PALLAS all-on == all-off);
  * the dintscope diff gate folds the swallowed waves onto their fused
    successor (attrib.WAVE_ALIASES) and still exits 1 naming the fused
    wave on an injected regression — which --no-alias provably hides.

Everything runs in Pallas interpret mode on CPU (conftest pins
JAX_PLATFORMS=cpu), so fused-vs-unfused parity is a tier-1 CI fact;
tools/hw_round12.sh carries the same comparisons to hardware.
"""
import copy
import functools
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dint_tpu.monitor import attrib, waves
from dint_tpu.ops import pallas_gather as pg

pytestmark = pytest.mark.fused

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "dintscope_trace.json")
GEOM = {"w": 8192, "k": 4, "l": 3, "vw": 10, "d": 8}
CLI = [sys.executable, os.path.join(REPO, "tools", "dintscope.py")]
KEY = jax.random.PRNGKey
U32 = jnp.uint32
I32 = jnp.int32

# one shared tiny geometry per path -> one compile per configuration
# (BLOCKS=1 still overlaps cohorts: CPB=2 steps + the drain finish the
# pipeline, and the fused kernels run interpret-mode per step, so block
# count is execution cost, not coverage — tier-1 budget, round-10 rule)
N_SUB = 256
N_ACC = 128
W = 64
VW = 4
CPB = 2
BLOCKS = 1


def _cli(args, **kw):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(CLI + args, capture_output=True, text=True,
                          timeout=120, env=env, cwd=REPO, **kw)


def _trees_equal(ta, tb):
    la, lb = jax.tree.leaves(ta), jax.tree.leaves(tb)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# ------------------------------------------------------- kernel parity


def test_lock_validate_matches_unfused_composition():
    """One lock_validate dispatch == lock_arbitrate + the XLA validate
    compare + the XLA read-meta gather, bit for bit — with duplicate
    rows in the lock batch (arbitration must pick the same winner),
    duplicate validate indices, inactive lanes, and (hot_n=24) a batch
    straddling the VMEM arb prefix: duplicates on both sides of the
    boundary and pairs that cross it."""
    n, m, v, r, k_arb = 96, 64, 48, 40, 18
    rng = np.random.default_rng(7)
    meta = jnp.asarray(rng.integers(0, 1 << 31, n), U32)
    step = jnp.asarray(5, U32)
    rows = jnp.asarray(np.concatenate([
        rng.integers(0, n, m - 10),
        # adversarial tail: duplicates below, at, and above hot_n=24
        [3, 3, 23, 23, 24, 24, 50, 50, 23, 24]]), I32)
    act = jnp.asarray(rng.integers(0, 2, m), bool)
    vidx = jnp.asarray(np.concatenate([
        rng.integers(0, n, v - 4), [5, 5, 9, 9]]), I32)
    vv1 = jnp.where(jnp.arange(v) % 2 == 0, meta[vidx],
                    meta[vidx] ^ U32(1))
    ridx = jnp.asarray(rng.integers(0, n, r), I32)
    for hot_n in (0, 24):
        arb0 = jnp.asarray(
            (np.uint32(4) << k_arb) * rng.integers(0, 2, n + 1)
            + rng.integers(0, 1 << 10, n + 1), U32)
        arb_u, grant_u = pg.lock_arbitrate(jnp.array(arb0), rows, act,
                                           step, k_arb, hot_n=hot_n)
        vbad_u = (meta[vidx] != vv1).astype(U32)
        rmeta_u = meta[ridx]
        arb_f, grant_f, vbad_f, rmeta_f = pg.lock_validate(
            jnp.array(arb0), meta, vidx, vv1, ridx, rows, act, step,
            k_arb, hot_n=hot_n)
        assert np.array_equal(arb_f, arb_u), hot_n
        assert np.array_equal(grant_f, grant_u), hot_n
        assert np.array_equal(vbad_f, vbad_u), hot_n
        assert np.array_equal(rmeta_f, rmeta_u), hot_n


def test_gather_streams_matches_xla_gathers():
    """One dispatch, three streams of different row widths — including a
    stream whose every lane hits the SAME row (maximal duplicate-index
    pressure on the DMA ring)."""
    n = 64
    rng = np.random.default_rng(11)
    vws = (1, 4, 3)
    tabs = tuple(jnp.asarray(rng.integers(0, 1 << 31, n * vw), U32)
                 for vw in vws)
    idxs = (jnp.asarray(rng.integers(0, n, 40), I32),
            jnp.full((24,), 17, I32),             # all-duplicate stream
            jnp.asarray(rng.integers(0, n, 8), I32))
    got = pg.gather_streams(tabs, idxs, vws)
    want = pg._xla_gather_streams(tabs, idxs, vws)
    for g, w_ in zip(got, want):
        assert np.array_equal(g, w_)


def test_scatter_streams_matches_xla_scatters():
    """One dispatch, three masked scatter streams == the per-stream XLA
    drop-scatters. Adversarial: the same row numbers masked-in across
    different streams (disjoint tables), duplicate row values on
    masked-OUT lanes (idx stays -1, so the one-writer contract holds),
    and one stream entirely masked (zero traffic)."""
    n, k = 64, 40
    rng = np.random.default_rng(13)
    vws = (4, 1, 3)
    tabs = [jnp.asarray(rng.integers(0, 1 << 31, n * vw), U32)
            for vw in vws]
    perm = rng.permutation(n)[:k].astype(np.int32)
    lane = np.arange(k)
    idx0 = np.where(lane % 3 == 0, perm, -1).astype(np.int32)
    # stream 1 masks IN the rows stream 0 masked OUT (cross-stream
    # duplicates of the same row ids against a disjoint table)
    idx1 = np.where(lane % 3 != 0, perm, -1).astype(np.int32)
    idx2 = np.full((k,), -1, np.int32)            # all-masked stream
    idxs = tuple(jnp.asarray(i) for i in (idx0, idx1, idx2))
    vals = tuple(jnp.asarray(rng.integers(0, 1 << 31, k * vw), U32)
                 for vw in vws)
    got = pg.scatter_streams(tuple(jnp.array(t) for t in tabs), idxs,
                             vals, vws)
    want = pg._xla_scatter_streams(tabs, idxs, vals, vws)
    for s, (g, w_) in enumerate(zip(got, want)):
        assert np.array_equal(g, w_), s
    # the all-masked stream wrote nothing
    assert np.array_equal(got[2], tabs[2])


# --------------------------------------------------- probe-and-degrade


def test_resolve_use_fused_env_and_explicit(monkeypatch):
    monkeypatch.delenv("DINT_USE_FUSED", raising=False)
    assert pg.resolve_use_fused(None) is False        # default OFF
    monkeypatch.setenv("DINT_USE_FUSED", "0")
    assert pg.resolve_use_fused(None) is False
    monkeypatch.setenv("DINT_USE_FUSED", "1")
    assert pg.resolve_use_fused(False) is False       # explicit beats env
    # env on + a real probe at a tiny geometry: interpret mode passes,
    # so the resolver says fused (the stream-kernel probes are exercised
    # by every fused engine build below)
    assert pg.resolve_use_fused(None, lockv=(16, 16, 16, 18, 8)) is True


def test_probe_failure_degrades_and_caches(monkeypatch):
    """A kernel that raises at probe time (the Mosaic-rejection shape)
    degrades resolve_use_fused to False — no exception escapes — and the
    verdict is cached per geometry: restoring the kernel does not flip
    an already-probed key."""
    real_lockv = pg.lock_validate
    monkeypatch.setattr(pg, "_probe_cache", {})       # isolate the cache

    def boom(*a, **k):
        raise RuntimeError("simulated Mosaic rejection")

    monkeypatch.setattr(pg, "lock_validate", boom)
    geom = (24, 24, 16, 18, 0)
    assert pg.resolve_use_fused(True, lockv=geom) is False
    monkeypatch.setattr(pg, "lock_validate", real_lockv)
    assert pg.resolve_use_fused(True, lockv=geom) is False    # cached
    # a DIFFERENT geometry re-probes and succeeds with the real kernel
    assert pg.resolve_use_fused(True, lockv=(16, 16, 16, 18, 0)) is True
    # the stream kernels degrade the same way
    monkeypatch.setattr(pg, "scatter_streams", boom)
    assert pg.resolve_use_fused(True, scatters=((24, 4),)) is False
    monkeypatch.setattr(pg, "gather_streams", boom)
    assert pg.resolve_use_fused(True, gathers=((24, 1),)) is False


# ------------------------------------------------ engine parity (dense)


@functools.lru_cache(maxsize=None)
def _td_build(use_fused, use_pallas=False):
    # use_fused=None is only ever requested under DINT_USE_FUSED=1
    # (test_tatp_dense_fused_parity) — the env-plumbing leg of the pin
    from dint_tpu.engines import tatp_dense as td

    return td.build_pipelined_runner(
        N_SUB, w=W, val_words=VW, cohorts_per_block=CPB,
        use_pallas=use_pallas, use_fused=use_fused)


@functools.lru_cache(maxsize=None)
def _sb_build(use_fused, use_hotset=False, use_pallas=False):
    from dint_tpu.engines import smallbank_dense as sd

    return sd.build_pipelined_runner(
        N_ACC, w=W, cohorts_per_block=CPB, use_pallas=use_pallas,
        use_hotset=use_hotset, use_fused=use_fused)


def _run_td(use_fused, use_pallas=False, seed=0):
    from dint_tpu.engines import tatp_dense as td

    db = td.populate(np.random.default_rng(seed), N_SUB, val_words=VW)
    run, init, drain = _td_build(use_fused, use_pallas)
    carry = init(db)
    blocks = []
    for i in range(BLOCKS):
        carry, s = run(carry, jax.random.fold_in(KEY(seed), i))
        blocks.append(np.asarray(s))
    db2, tail = drain(carry)
    blocks.append(np.asarray(tail))
    return db2, np.concatenate(blocks, axis=0)


def _run_sb(use_fused, use_hotset=False, use_pallas=False, seed=0):
    from dint_tpu.engines import smallbank_dense as sd

    db = sd.create(N_ACC)
    run, init, drain = _sb_build(use_fused, use_hotset, use_pallas)
    carry = init(db)
    blocks = []
    for i in range(BLOCKS):
        carry, s = run(carry, jax.random.fold_in(KEY(seed), i))
        blocks.append(np.asarray(s))
    db2, tail = drain(carry)
    blocks.append(np.asarray(tail))
    return db2, np.concatenate(blocks, axis=0)


def test_tatp_dense_fused_parity(monkeypatch):
    """DINT_USE_FUSED=1 (env -> builder -> probe -> megakernels) is
    bit-identical to the unfused chain: final DenseDB and every stats
    block, drain included."""
    monkeypatch.setenv("DINT_USE_FUSED", "1")
    db_f, st_f = _run_td(None)          # env-resolved fused
    monkeypatch.delenv("DINT_USE_FUSED")
    db_u, st_u = _run_td(False)
    assert _trees_equal(db_f, db_u)
    assert np.array_equal(st_f, st_u)
    assert st_u.sum() > 0               # the pin exercised real traffic


def test_smallbank_dense_fused_parity():
    db_f, st_f = _run_sb(True)
    db_u, st_u = _run_sb(False)
    assert _trees_equal(db_f, db_u)
    assert np.array_equal(st_f, st_u)
    assert st_u.sum() > 0


# ----------------------------------------------- engine parity (sharded)


# the two sharded parities compile the full shard_map pipeline twice
# each; slow-marked to hold the 1-CPU tier-1 budget (round-10 rule) —
# the fused kernel mechanics and both dense-engine pins stay tier-1,
# and `pytest -m fused` / tools/hw_round12.sh still run these.
@pytest.mark.slow
def test_dense_sharded_fused_parity():
    from dint_tpu.parallel import dense_sharded as ds

    mesh = ds.make_mesh(4)
    n_glob = 4 * 200
    outs = []
    for fused in (True, False):
        run, init, drain = ds.build_sharded_pipelined_runner(
            mesh, 4, n_glob, w=32, val_words=4, cohorts_per_block=CPB,
            use_fused=fused)
        carry = init(ds.create_sharded(mesh, 4, n_glob, val_words=4,
                                       log_capacity=128))
        blocks = []
        for i in range(BLOCKS):
            carry, s = run(carry, jax.random.fold_in(KEY(2), i))
            blocks.append(np.asarray(s))
        state, tail = drain(carry)
        blocks.append(np.asarray(tail))
        outs.append((state, np.concatenate(blocks, axis=0)))
    (sf, stf), (su, stu) = outs
    assert _trees_equal(sf, su)
    assert np.array_equal(stf, stu)
    assert stu.sum() > 0


@pytest.mark.slow
def test_dense_sharded_sb_fused_parity():
    from dint_tpu.parallel import dense_sharded_sb as dsb

    mesh = dsb.make_mesh(4)
    n_glob = 4 * 128
    outs = []
    for fused in (True, False):
        run, init, drain = dsb.build_sharded_sb_runner(
            mesh, 4, n_glob, w=32, cohorts_per_block=CPB,
            use_fused=fused)
        carry = init(dsb.create_sharded_sb(mesh, 4, n_glob))
        blocks = []
        for i in range(BLOCKS):
            carry, s = run(carry, jax.random.fold_in(KEY(3), i))
            blocks.append(np.asarray(s))
        state, tail = drain(carry)
        blocks.append(np.asarray(tail))
        outs.append((state, np.concatenate(blocks, axis=0)))
    (sf, stf), (su, stu) = outs
    assert _trees_equal(sf, su)
    assert np.array_equal(stf, stu)
    assert stu.sum() > 0


# ------------------------------------------------- feature interactions


def test_smallbank_fused_hotset_pallas_stack_parity():
    """The whole stack at once — DINT_USE_FUSED x DINT_USE_HOTSET x
    DINT_USE_PALLAS all on — equals the all-off run bit for bit on
    every main-table field and every stats block (every layer is
    semantics-neutral by its own pins). The hot tier attaches VMEM
    mirror leaves the all-off bank never carries, so the comparison is
    by field name, skipping exactly the round-10 mirrors."""
    import dataclasses

    db_s, st_s = _run_sb(True, use_hotset=True, use_pallas=True)
    db_u, st_u = _run_sb(False)
    mirrors = {"hot_bal", "hot_x", "hot_s", "hot_n"}
    names = {f.name for f in dataclasses.fields(db_u)}
    assert mirrors < names                  # the skip-list stays honest
    for name in sorted(names - mirrors):   # `log` is a nested RepLog
        assert _trees_equal(getattr(db_s, name), getattr(db_u, name)), \
            name
    assert np.array_equal(st_s, st_u)


# ------------------------------------- the dintscope aliased diff gate


def _zero_row():
    return {"ms": 0.0, "slices": 0, "ms_per_step": None, "pct": 0.0,
            "bytes_per_step": None, "gbps": None}


def _fused_ab_artifacts():
    """A fused-vs-unfused A/B pair built from the checked-in fixture:
    A ran the unfused chain (fused waves unobserved), B ran the
    megakernels (constituents unobserved, each fused wave carrying
    exactly its constituents' time) — the equal-work case the aliased
    gate must pass."""
    base = attrib.report(FIXTURE, geometry=GEOM)
    a, b = copy.deepcopy(base), copy.deepcopy(base)
    dsts = sorted(set(attrib.WAVE_ALIASES.values()))
    for dst in dsts:
        a["waves"][dst] = _zero_row()
    for src in attrib.WAVE_ALIASES:
        b["waves"][src] = _zero_row()
    for dst in dsts:
        srcs = [s for s, d in attrib.WAVE_ALIASES.items() if d == dst]
        b["waves"][dst] = dict(
            _zero_row(),
            ms=round(sum(base["waves"][s]["ms"] for s in srcs), 6),
            slices=sum(base["waves"][s]["slices"] for s in srcs),
            ms_per_step=round(sum(base["waves"][s]["ms_per_step"]
                                  for s in srcs), 6),
            pct=round(sum(base["waves"][s]["pct"] for s in srcs), 3))
    return a, b


def test_aliased_fold_merges_constituents():
    a, b = _fused_ab_artifacts()
    d = attrib.diff_breakdowns(a, b)
    assert d["ok"], d["regressions"]
    # every fused wave folded, each listing its sorted constituents
    assert set(d["aliased"]) == set(attrib.WAVE_ALIASES.values())
    rows = {r["wave"]: r for r in d["rows"]}
    for src, dst in attrib.WAVE_ALIASES.items():
        assert src not in rows                  # merged away
        assert src in rows[dst]["includes"]
        assert rows[dst]["includes"] == sorted(
            s for s, t in attrib.WAVE_ALIASES.items() if t == dst)
    # folding conserves time: folded A's fused row == constituent sum
    lv = "dint.smallbank_dense.lock_validate"
    want = round(sum(
        attrib.report(FIXTURE, geometry=GEOM)["waves"][s]["ms_per_step"]
        for s, t in attrib.WAVE_ALIASES.items() if t == lv), 6)
    assert abs(rows[lv]["a_ms_per_step"] - want) < 1e-6
    # symmetric sides (unfused-vs-unfused, fused-vs-fused) never fold
    assert attrib.diff_breakdowns(a, a)["aliased"] == {}
    assert attrib.diff_breakdowns(b, b)["aliased"] == {}
    assert attrib.diff_breakdowns(a, b, alias=False)["aliased"] == {}


def test_fused_diff_cli_gate_names_regressed_wave(tmp_path):
    """Acceptance: the CLI gate folds the alias map, passes the
    equal-work fused A/B, and exits 1 NAMING the fused wave when its
    megakernel regresses past threshold — a regression --no-alias
    provably cannot see (the raw rows have no common observed wave)."""
    a, b = _fused_ab_artifacts()
    lv = "dint.smallbank_dense.lock_validate"
    b2 = copy.deepcopy(b)
    b2["waves"][lv]["ms"] = round(b2["waves"][lv]["ms"] * 1.6, 6)
    b2["waves"][lv]["ms_per_step"] = round(
        b2["waves"][lv]["ms_per_step"] * 1.6, 6)
    pa, pb, pb2 = (str(tmp_path / f"{n}.json") for n in ("a", "b", "b2"))
    for p, obj in ((pa, a), (pb, b), (pb2, b2)):
        with open(p, "w") as f:
            json.dump(obj, f)
    c = _cli(["diff", pa, pb])
    assert c.returncode == 0, (c.stdout, c.stderr)
    assert "aliased:" in c.stdout                # the fold is announced
    c = _cli(["diff", pa, pb2, "--json"])
    assert c.returncode == 1, (c.stdout, c.stderr)
    d = json.loads(c.stdout.strip().splitlines()[-1])
    assert any(r.get("wave") == lv for r in d["regressions"])
    assert d["aliased"][lv] == sorted(
        s for s, t in attrib.WAVE_ALIASES.items() if t == lv)
    c = _cli(["diff", pa, pb2])                  # human mode names it too
    assert c.returncode == 1
    assert lv in c.stdout
    # the raw-scope comparison hides it: A never observed the fused
    # wave, B2 never observed the constituents, so no row is comparable
    assert _cli(["diff", pa, pb2, "--no-alias"]).returncode == 0


# -------------------------------------------------- registry satellites


def test_fused_waves_registered():
    """The fused waves are first-class registry citizens and the alias
    map's endpoints all resolve (attrib asserts this at import; pin it
    explicitly so a registry edit fails here, not at import time)."""
    for eng in ("tatp_dense", "smallbank_dense"):
        for wv in ("lock_validate", "install_log"):
            assert waves.full_name(eng, wv) in waves.ALL_WAVES
            waves.scope(eng, wv)                 # no KeyError
    for src, dst in attrib.WAVE_ALIASES.items():
        assert src in waves.ALL_WAVES
        assert dst in waves.ALL_WAVES
        assert src.split(".")[1] == dst.split(".")[1]   # same engine
