"""dinttrace: the per-transaction flight recorder (OBSERVABILITY.md).

The contract under test, per acceptance criteria:
  * at rate 1.0 the event stream RECONCILES with the dintmon counter
    plane exactly on every instrumented path (both dense engines, the
    sharded smallbank path, and the 2-D multihost mesh): lock events ==
    lock_requests, install events == install_writes, outcome splits ==
    txn_committed / ab_* — every sampled journey is complete;
  * the sampling mask is deterministic and monotone: the rate-0.25 event
    set is a strict subset of the rate-1.0 set (same txns on every
    shard, retry, and rate — what makes cross-shard joins exact);
  * tracing OFF (the default) changes no engine output bit;
  * ring overflow is keep-first and LOSS-COUNTED: head keeps counting,
    the excess drops, and the `trace_dropped` counter agrees;
  * the checked-in synthetic fixture does not drift from its generator,
    and the dintmon/dinttrace CLIs work end to end — including a joined
    cross-shard span tree (route -> lock -> vote -> install -> both
    replication hops) assembled from a real multihost_sb run.

Builders are cached at module scope (one compile per configuration),
same budget discipline as tests/test_dintmon.py.
"""
import functools
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from dint_tpu import monitor as M
from dint_tpu.monitor import txnevents as txe
from dint_tpu.monitor import txntrace as tt

pytestmark = pytest.mark.trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "dinttrace_events.jsonl")
KEY = jax.random.PRNGKey

# one shared tiny geometry -> one compile per configuration
N_SUB = 300
N_ACC = 400
W = 64
VW = 4
CPB = 2


def _cli(*argv):
    return subprocess.run(
        [sys.executable] + list(argv), capture_output=True, text=True,
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"))


# ------------------------------------------------------- cached builders


@functools.lru_cache(maxsize=None)
def _td_build(trace=True, rate=1.0, cap=None, monitor=True):
    from dint_tpu.engines import tatp_dense as td

    return td.build_pipelined_runner(
        N_SUB, w=W, val_words=VW, cohorts_per_block=CPB, monitor=monitor,
        trace=trace, trace_rate=rate, trace_cap=cap)


@functools.lru_cache(maxsize=None)
def _sb_build(trace=True, rate=1.0, cap=None, monitor=True):
    from dint_tpu.engines import smallbank_dense as sd

    return sd.build_pipelined_runner(
        N_ACC, w=W, cohorts_per_block=CPB, monitor=monitor,
        trace=trace, trace_rate=rate, trace_cap=cap)


@functools.lru_cache(maxsize=None)
def _dsb_build():
    from dint_tpu.parallel import dense_sharded_sb as dsb

    mesh = dsb.make_mesh(4)
    runner = dsb.build_sharded_sb_runner(
        mesh, 4, 4 * 128, w=32, cohorts_per_block=2, monitor=True,
        trace=True)
    return runner, mesh


@functools.lru_cache(maxsize=None)
def _mhsb_build():
    from dint_tpu.parallel import multihost_sb as mh

    mesh = mh.make_mesh_2d(4, 2)
    runner = mh.build_multihost_sb_runner(
        mesh, 8 * 128, w=32, cohorts_per_block=2, monitor=True,
        trace=True)
    return runner, mesh


def _drive(runner, state, n_stats, *, trace=True, monitor=True, blocks=3,
           seed=0, path=None):
    """Run `blocks` dispatches + the drain, observing the ring after each
    (the ring zeroes at block entry, so each observe is self-contained).
    Returns (state_out, stats_total, counter_snapshot, TxnMonitor)."""
    run, init, drain = runner
    carry = init(state)
    tmon = txe.TxnMonitor(init.trace_cfg, path=path) if trace else None
    tot = np.zeros(n_stats, np.int64)
    for i in range(blocks):
        carry, s = run(carry, jax.random.fold_in(KEY(seed), i))
        tot += np.asarray(s, np.int64).sum(axis=0)
        if tmon is not None:
            tmon.observe(carry[-2] if monitor else carry[-1])
    out = drain(carry)
    tot += np.asarray(out[1], np.int64).sum(axis=0)
    rest = list(out[2:])
    if tmon is not None:
        tmon.observe(rest.pop(0))
        tmon.close()
    snap = M.snapshot(rest.pop(0)) if monitor else None
    return out[0], tot, snap, tmon


@functools.lru_cache(maxsize=None)
def _sb_full_drive():
    """The rate-1.0 smallbank drive, shared (read-only) by the
    reconciliation, subset, and bit-identity tests: one run, one compile."""
    from dint_tpu.engines import smallbank_dense as sd

    return _drive(_sb_build(), sd.create(N_ACC), sd.N_STATS, seed=1)


def _kind_counts(tmon):
    """(kind-name counts, outcome-cause counts) over every window."""
    kinds, outcomes = {}, {}
    for win in tmon.windows:
        for rec in win:
            for _w0, w1, _w2, _w3 in rec["events"]:
                kind, _wave, _shard, aux = txe.unpack_w1(w1)
                name = txe.KIND_NAMES[kind]
                kinds[name] = kinds.get(name, 0) + 1
                if kind == txe.EV_OUTCOME:
                    cause = txe.CAUSE_NAMES[aux]
                    outcomes[cause] = outcomes.get(cause, 0) + 1
    return kinds, outcomes


def _event_set(tmon):
    return {tuple(e) for win in tmon.windows for rec in win
            for e in rec["events"]}


# ------------------------------------------- full-rate reconciliation


def test_tatp_dense_full_rate_reconciles():
    from dint_tpu.engines import tatp_dense as td

    db = td.populate(np.random.default_rng(0), N_SUB, val_words=VW)
    _, tot, snap, tmon = _drive(_td_build(), db, td.N_STATS)
    kinds, outcomes = _kind_counts(tmon)
    assert kinds["lock"] == snap["lock_requests"] > 0
    assert kinds["validate"] == snap["validate_lanes"] > 0
    assert kinds["install"] == snap["install_writes"] > 0
    assert kinds["outcome"] == snap["txn_attempted"] \
        == tot[td.STAT_ATTEMPTED]
    assert outcomes.get("commit", 0) == snap["txn_committed"]
    assert outcomes.get("ab_lock", 0) == snap["ab_lock"]
    assert outcomes.get("ab_missing", 0) == snap["ab_missing"]
    assert outcomes.get("ab_validate", 0) == snap["ab_validate"]
    assert snap["trace_dropped"] == tmon.summary()["dropped"] == 0


def test_sb_dense_full_rate_reconciles():
    from dint_tpu.engines import smallbank_dense as sd

    _, tot, snap, tmon = _sb_full_drive()
    kinds, outcomes = _kind_counts(tmon)
    assert kinds["lock"] == snap["lock_requests"] > 0
    assert kinds["install"] == snap["install_writes"] > 0
    assert kinds["outcome"] == snap["txn_attempted"] \
        == tot[sd.STAT_ATTEMPTED]
    assert outcomes.get("commit", 0) == snap["txn_committed"]
    assert outcomes.get("ab_lock", 0) == snap["ab_lock"]
    assert outcomes.get("ab_logic", 0) == snap["ab_logic"]
    assert snap["trace_dropped"] == tmon.summary()["dropped"] == 0


def test_dense_sharded_sb_full_rate_reconciles():
    from dint_tpu.parallel import dense_sharded_sb as dsb

    runner, mesh = _dsb_build()
    _, tot, snap, tmon = _drive(
        runner, dsb.create_sharded_sb(mesh, 4, 4 * 128), dsb.N_STATS,
        seed=3)
    kinds, outcomes = _kind_counts(tmon)
    # single-host mesh: the route counters stay zero (ICI-only transport
    # predates the 2-D split), so ROUTE events tie to the lock requests
    # they carried — one lock-route hop per requested slot
    assert kinds["route"] == snap["lock_requests"] == kinds["lock"] > 0
    assert kinds["vote"] == snap["txn_attempted"] \
        == tot[dsb.STAT_ATTEMPTED]
    assert kinds["install"] == snap["install_writes"] > 0
    assert kinds["repl"] == snap["repl_push_hop1"] + snap["repl_push_hop2"]
    assert kinds["outcome"] == snap["txn_attempted"]
    assert outcomes.get("commit", 0) == snap["txn_committed"]
    assert outcomes.get("ab_lock", 0) == snap["ab_lock"]
    assert outcomes.get("ab_logic", 0) == snap["ab_logic"]
    assert snap["trace_dropped"] == tmon.summary()["dropped"] == 0


def test_multihost_sb_full_rate_reconciles(tmp_path):
    from dint_tpu.parallel import dense_sharded_sb as dsb
    from dint_tpu.parallel import multihost_sb as mh

    runner, mesh = _mhsb_build()
    path = str(tmp_path / "mhsb.jsonl")
    _, tot, snap, tmon = _drive(
        runner, mh.create_multihost_sb(mesh, 8 * 128), dsb.N_STATS,
        seed=5, path=path)
    kinds, outcomes = _kind_counts(tmon)
    # the route counters tally lock-route AND install-route lanes; ROUTE
    # events mark lock routes only, so the install writes subtract out
    assert kinds["route"] == snap["route_ici_lanes"] \
        + snap["route_dcn_lanes"] - snap["install_writes"]
    assert snap["route_dcn_lanes"] > 0          # 2-D mesh: DCN hops real
    assert kinds["lock"] == snap["lock_requests"] > 0
    assert kinds["vote"] == snap["txn_attempted"] \
        == tot[dsb.STAT_ATTEMPTED]
    assert kinds["install"] == snap["install_writes"] > 0
    assert kinds["repl"] == snap["repl_push_hop1"] + snap["repl_push_hop2"]
    assert outcomes.get("commit", 0) == snap["txn_committed"]
    assert outcomes.get("ab_lock", 0) == snap["ab_lock"]
    assert snap["trace_dropped"] == tmon.summary()["dropped"] == 0

    # acceptance demo: one committed cross-shard txn assembles into a
    # single joined span tree — route -> lock -> vote -> install -> both
    # replication hops -> outcome — via the CLI, from the JSONL stream
    meta, records = tt.read_trace(path)
    groups = tt.by_txn(tt.decode_records(meta, records))
    full = {txe.EV_ROUTE, txe.EV_LOCK, txe.EV_VOTE, txe.EV_INSTALL,
            txe.EV_REPL, txe.EV_OUTCOME}
    cands = [t for t, g in groups.items()
             if {e["kind"] for e in g} >= full
             and len({e["aux"] for e in g
                      if e["kind"] == txe.EV_REPL}) >= 2
             and len({e["shard"] for e in g}) >= 2
             and tt.span_tree(t, g)["outcome"] == "commit"]
    assert cands, "no committed cross-shard txn with full journey"
    r = _cli("tools/dinttrace.py", "show", path, str(cands[0]))
    assert r.returncode == 0, r.stderr
    for token in ("route", "granted", "vote", "install", "repl hop=",
                  "[commit]"):
        assert token in r.stdout, (token, r.stdout)


# ----------------------------------------- sampling mask + off-path


def test_quarter_rate_events_are_subset_of_full_rate():
    from dint_tpu.engines import smallbank_dense as sd

    _, tot_full, _, tm_full = _sb_full_drive()
    _, tot_q, _, tm_q = _drive(_sb_build(rate=0.25), sd.create(N_ACC),
                               sd.N_STATS, seed=1)
    assert tot_full.tolist() == tot_q.tolist()   # sampling never steers
    full, quarter = _event_set(tm_full), _event_set(tm_q)
    assert 0 < len(quarter) < len(full)
    assert quarter <= full
    # the mask is a pure function of the txn id: a txn is in or out WHOLE
    sampled = {e[0] for e in quarter}
    assert {e for e in full if e[0] in sampled} == quarter


def test_trace_off_is_bit_identical():
    """A/B on the trace flag alone (monitor on in both arms): the on-arm
    is the cached full-rate drive, the off-arm compiles once here."""
    from dint_tpu.engines import smallbank_dense as sd

    db_off, tot_off, _, _ = _drive(_sb_build(trace=False),
                                   sd.create(N_ACC), sd.N_STATS,
                                   trace=False, seed=1)
    db_on, tot_on, _, _ = _sb_full_drive()
    assert tot_off.tolist() == tot_on.tolist()
    for a, b in zip(jax.tree_util.tree_leaves(db_off),
                    jax.tree_util.tree_leaves(db_on)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# -------------------------------------------------- overflow accounting


def test_ring_overflow_keeps_first_and_counts_losses():
    from dint_tpu.engines import smallbank_dense as sd

    _, _, snap, tmon = _drive(_sb_build(cap=16), sd.create(N_ACC),
                              sd.N_STATS, seed=1)
    s = tmon.summary()
    assert s["dropped"] > 0 and s["dropped_windows"]
    # device-side loss counter agrees with the host derivation exactly
    assert snap["trace_dropped"] == s["dropped"]
    for win in tmon.windows:
        for rec in win:
            assert len(rec["events"]) == min(rec["head"], 16)
            assert rec["dropped"] == max(0, rec["head"] - 16)


# ------------------------------------------------ fixture + CLI surface


def test_synth_fixture_has_not_drifted(tmp_path):
    fresh = str(tmp_path / "synth.jsonl")
    tt.synthesize_events(fresh)
    with open(fresh) as f, open(FIXTURE) as g:
        assert f.read() == g.read(), \
            "regenerate with `python tools/dinttrace.py synth`"


def test_dinttrace_cli_on_fixture():
    r = _cli("tools/dinttrace.py", "summarize", FIXTURE)
    assert r.returncode == 0 and "OVERFLOW" in r.stdout
    r = _cli("tools/dinttrace.py", "summarize", FIXTURE, "--json")
    s = json.loads(r.stdout)
    assert s["events"] == 14 and s["txns"] == 3 and s["dropped"] == 3

    r = _cli("tools/dinttrace.py", "show", FIXTURE, "101")
    assert r.returncode == 0
    for token in ("route", "granted", "install", "repl hop=1",
                  "repl hop=2", "[commit]"):
        assert token in r.stdout, (token, r.stdout)
    assert _cli("tools/dinttrace.py", "show", FIXTURE,
                "999").returncode == 1

    r = _cli("tools/dinttrace.py", "aborts", FIXTURE, "--by-cause",
             "--json")
    out = json.loads(r.stdout)
    assert out["aborted"] == 2
    assert set(out["by_cause"]) == {"ab_lock", "ab_validate"}

    r = _cli("tools/dinttrace.py", "slowest", FIXTURE, "--json")
    assert json.loads(r.stdout)["slowest"][0]["txn"] in (101, 103, 205)


def test_dinttrace_export_merges_on_own_pid(tmp_path):
    out = str(tmp_path / "spans.json")
    r = _cli("tools/dinttrace.py", "export", FIXTURE, "-o", out, "--json")
    assert r.returncode == 0
    trace = json.load(open(out))
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 14
    assert {e["pid"] for e in xs} == {tt.EXPORT_PID}


def test_dintmon_check_cli(tmp_path):
    good = {"counters": {
        "lock_requests": 10, "lock_granted": 7, "lock_rejected": 3,
        "lock_reject_held": 2, "lock_reject_arb": 1,
        "steps": 4, "dispatch_xla": 4, "dispatch_pallas": 0}}
    p = str(tmp_path / "good.json")
    json.dump(good, open(p, "w"))
    r = _cli("tools/dintmon.py", "check", p)
    assert r.returncode == 0 and "dintmon check: ok" in r.stdout
    # the route identity must be SKIPPED when both route counters are 0
    r = _cli("tools/dintmon.py", "check", p, "--json")
    rows = {x["identity"]: x["status"]
            for x in json.loads(r.stdout)["identities"]}
    assert rows["route_ici_lanes + route_dcn_lanes == "
                "lock_requests + install_writes"] == "skipped"

    bad = {"counters": dict(good["counters"], lock_granted=9)}
    q = str(tmp_path / "bad.json")
    json.dump(bad, open(q, "w"))
    r = _cli("tools/dintmon.py", "check", q)
    assert r.returncode == 1
    assert "lock_requests == lock_granted + lock_rejected" in r.stdout

    null = str(tmp_path / "null.json")
    json.dump({"counters": None}, open(null, "w"))
    assert _cli("tools/dintmon.py", "check", null).returncode == 1
