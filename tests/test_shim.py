"""End-to-end tests of the native host shim (C++ UDP pump) over loopback:
reference-wire-format requests in, engine-certified replies out. This is
the cross-layer test the reference runs only on a real cluster (SURVEY.md
§4.3); here the whole L0->L2 path runs in-process over 127.0.0.1."""
import numpy as np
import pytest

from dint_tpu.engines import lock2pl, logsrv, store
from dint_tpu.shim import (FMT_LOCK6, FMT_LOG53, LOCK2PL, LOG, STORE,
                           EnginePump, ShimClient)
from dint_tpu.tables import kv, locks
from dint_tpu.tables import log as logring


def _warm(pump, fmt=None):
    """Absorb the pump's first XLA compile before the test's short-timeout
    exchanges: under full-suite CPU load the first step can take >5s, which
    otherwise shows up as a flaky 0-reply timeout."""
    kw = {} if fmt is None else {"fmt": fmt}
    with ShimClient("127.0.0.1", pump.port, **kw) as c:
        for _ in range(12):
            r = c.exchange(np.zeros(1, np.uint8),
                           np.array([1], np.uint64), timeout_ms=10_000)
            if r["n"] == 1:
                return
    raise RuntimeError("pump did not answer warmup exchanges")


@pytest.fixture
def store_pump():
    table = kv.create(1 << 8, val_words=10)
    with EnginePump(STORE, store.step, table, width=256,
                    flush_us=2000).start() as p:
        _warm(p)
        yield p


def test_store_wire_roundtrip(store_pump):
    with ShimClient("127.0.0.1", store_pump.port) as c:
        n = 32
        keys = np.arange(1, n + 1, dtype=np.uint64)
        vals = np.zeros((n, 40), np.uint8)
        vals[:, 0] = np.arange(n)
        vals[:, 1] = 0xAB  # magic-byte convention, store/caladan/client_caladan.cc:160
        # INSERT (wire type 2) everything in one exchange
        r = c.exchange(np.full(n, 2, np.uint8), keys, vals=vals,
                       timeout_ms=5000)
        assert r["n"] == n
        assert (r["type"] == 8).all()  # INSERT_ACK
        # READ (wire type 0) them back
        r = c.exchange(np.zeros(n, np.uint8), keys, timeout_ms=5000)
        assert r["n"] == n
        assert (r["type"] == 3).all()  # GRANT_READ
        got = {int(k): (v[0], v[1]) for k, v in zip(r["key"], r["val"])}
        for i, k in enumerate(keys):
            assert got[int(k)] == (i, 0xAB)
        # READ a missing key -> NOT_EXIST (7)
        r = c.exchange(np.zeros(1, np.uint8), np.array([999], np.uint64),
                       timeout_ms=5000)
        assert r["n"] == 1 and r["type"][0] == 7


def test_store_set_bumps_version(store_pump):
    with ShimClient("127.0.0.1", store_pump.port) as c:
        key = np.array([7], np.uint64)
        c.exchange(np.array([2], np.uint8), key, timeout_ms=5000)  # INSERT
        r1 = c.exchange(np.array([1], np.uint8), key, timeout_ms=5000)  # SET
        assert r1["type"][0] == 5  # SET_ACK
        r2 = c.exchange(np.array([0], np.uint8), key, timeout_ms=5000)  # READ
        assert r2["ver"][0] == r1["ver"][0]
        assert r2["ver"][0] >= 1


def test_lock2pl_wire(rng):
    table = locks.create_sx(1 << 10)
    with EnginePump(LOCK2PL, lock2pl.step, table, width=64,
                    flush_us=2000).start() as p:
        with ShimClient("127.0.0.1", p.port, fmt=FMT_LOCK6) as c:
            lid = np.array([42], np.uint64)
            # ACQUIRE (0) shared (table byte 0) -> GRANT_LOCK (2)
            r = c.exchange(np.zeros(1, np.uint8), lid, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 2
            # ACQUIRE exclusive (table byte 1) on same lid -> REJECT_LOCK (3)
            r = c.exchange(np.zeros(1, np.uint8), lid,
                           tables=np.ones(1, np.uint8), timeout_ms=5000)
            assert r["type"][0] == 3
            # RELEASE (1) shared -> RELEASE_ACK (5); then X grant succeeds
            r = c.exchange(np.ones(1, np.uint8), lid, timeout_ms=5000)
            assert r["type"][0] == 5
            r = c.exchange(np.zeros(1, np.uint8), lid,
                           tables=np.ones(1, np.uint8), timeout_ms=5000)
            assert r["type"][0] == 2


def test_log_wire(rng):
    ring = logring.create(4, 1 << 8, val_words=10)
    with EnginePump(LOG, logsrv.step, ring, width=64,
                    flush_us=2000).start() as p:
        with ShimClient("127.0.0.1", p.port, fmt=FMT_LOG53) as c:
            n = 16
            keys = rng.integers(0, 1000, n).astype(np.uint64)
            vals = rng.integers(0, 256, (n, 40)).astype(np.uint8)
            r = c.exchange(np.zeros(n, np.uint8), keys, vals=vals,
                           vers=np.arange(n, dtype=np.uint32),
                           timeout_ms=5000)
            assert r["n"] == n
            assert (r["type"] == 1).all()  # ACK


def test_pump_batches_full_width():
    """A single exchange wider than flush granularity still round-trips."""
    table = kv.create(1 << 10, val_words=10)
    with EnginePump(STORE, store.step, table, width=512,
                    flush_us=1000).start() as p:
        with ShimClient("127.0.0.1", p.port) as c:
            n = 512
            keys = np.arange(1, n + 1, dtype=np.uint64)
            r = c.exchange(np.full(n, 2, np.uint8), keys, timeout_ms=10000)
            assert r["n"] == n
            assert (r["type"] == 8).all()
        assert p.server.stats()["pkts_rx"] >= n


def test_smallbank_wire_lock_commit_roundtrip(rng):
    """SmallBank over the reference 55-byte wire format: fused X-lock+read
    grants with the balance, COMMIT_PRIM installs + releases, re-lock sees
    the new balance (smallbank/caladan/proto.h:14-37 type codes)."""
    from dint_tpu.clients.smallbank_client import init_shards
    from dint_tpu.clients import workloads as wl
    from dint_tpu.engines import smallbank
    from dint_tpu.shim import SMALLBANK

    shard = init_shards(64, init_balance=100)[0]
    with EnginePump(SMALLBANK, smallbank.step, shard, width=128,
                    flush_us=2000, val_words=2).start() as p:
        _warm(p)
        with ShimClient("127.0.0.1", p.port) as c:
            # kAcquireExclusive (1) on SAVINGS acct 7: grant carries balance
            r = c.exchange(np.array([1], np.uint8),
                           np.array([7], np.uint64),
                           tables=np.array([smallbank.SAVINGS], np.uint8),
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 9      # kGrantExclusive
            bal = int(np.frombuffer(r["val"][0][:4].tobytes(),
                                    np.uint32)[0])
            assert bal == 100
            # kCommitPrim (4) installs bal 250; release is the
            # coordinator's SEPARATE final kReleaseExclusive phase
            # (smallbank/caladan/proto.h:19-20) — the row stays X-held,
            # asserted by the REJECT below
            nv = np.zeros((1, 40), np.uint8)
            nv[0, :4] = np.frombuffer(np.uint32(250).tobytes(), np.uint8)
            nv[0, 4:8] = np.frombuffer(np.uint32(wl.SB_MAGIC).tobytes(),
                                       np.uint8)
            r = c.exchange(np.array([4], np.uint8),
                           np.array([7], np.uint64), vals=nv,
                           vers=np.array([2], np.uint32),
                           tables=np.array([smallbank.SAVINGS], np.uint8),
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 13     # kCommitPrimAck
            # while still X-held, a second acquire REJECTS (type 10)
            r = c.exchange(np.array([1], np.uint8),
                           np.array([7], np.uint64),
                           tables=np.array([smallbank.SAVINGS], np.uint8),
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 10     # kRejectExclusive
            # kReleaseExclusive (3): the coordinator's final phase
            # (lock -> log x3 -> bck x2 -> prim -> RELEASE,
            #  client_ebpf_shard.cc:389-560)
            r = c.exchange(np.array([3], np.uint8),
                           np.array([7], np.uint64),
                           tables=np.array([smallbank.SAVINGS], np.uint8),
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 12     # kReleaseExclusiveAck
            # re-acquire: grant carries the NEW balance
            r = c.exchange(np.array([1], np.uint8),
                           np.array([7], np.uint64),
                           tables=np.array([smallbank.SAVINGS], np.uint8),
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 9
            bal = int(np.frombuffer(r["val"][0][:4].tobytes(),
                                    np.uint32)[0])
            assert bal == 250


def test_tatp_wire_occ_roundtrip(rng):
    """TATP over the reference 55-byte wire format through the pump — the
    path the reference serves with tatp/udp/server_shard.cc: kRead with
    bloom-negative NOT_EXIST, kAcquireLock CAS, kCommitPrim install +
    row-lock release, kAbort release (tatp/ebpf/utils.h:38-73 codes;
    handler tatp/caladan/server_shard.cc:131-230)."""
    from dint_tpu.clients import tatp_client as tc
    from dint_tpu.engines import tatp
    from dint_tpu.shim import TATP

    shard = tc.populate_shards(np.random.default_rng(0), 64,
                               val_words=10, log_capacity=1 << 14)[0][0]
    sub = np.array([tatp.SUBSCRIBER], np.uint8)
    k5 = np.array([5], np.uint64)
    with EnginePump(TATP, tatp.step, shard, width=128,
                    flush_us=2000).start() as p:
        _warm(p)
        with ShimClient("127.0.0.1", p.port) as c:
            # kRead (0) SUBSCRIBER 5 -> kGrantRead (4) with payload + ver
            r = c.exchange(np.zeros(1, np.uint8), k5, tables=sub,
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 4
            assert int(np.frombuffer(r["val"][0][:4].tobytes(),
                                     np.uint32)[0]) == 5
            ver1 = int(r["ver"][0])
            assert ver1 >= 1
            # kRead on an absent CALL_FORWARDING row -> kNotExist (6)
            r = c.exchange(np.zeros(1, np.uint8),
                           np.array([tatp.cf_key(9, 1, 0)], np.uint64),
                           tables=np.array([tatp.CALL_FORWARDING],
                                           np.uint8), timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 6
            # kAcquireLock (1) -> kGrantLock (7); a second -> kRejectLock (8)
            r = c.exchange(np.ones(1, np.uint8), k5, tables=sub,
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 7
            r = c.exchange(np.ones(1, np.uint8), k5, tables=sub,
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 8
            # kCommitPrim (12) installs AND releases the row lock
            # (shard_kern.c:338-476)
            nv = np.zeros((1, 40), np.uint8)
            nv[0, :4] = np.frombuffer(np.uint32(777).tobytes(), np.uint8)
            r = c.exchange(np.array([12], np.uint8), k5, vals=nv,
                           vers=np.array([ver1 + 1], np.uint32),
                           tables=sub, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 15   # kCommitPrimAck
            # re-read: new payload, bumped version
            r = c.exchange(np.zeros(1, np.uint8), k5, tables=sub,
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 4
            assert int(np.frombuffer(r["val"][0][:4].tobytes(),
                                     np.uint32)[0]) == 777
            assert int(r["ver"][0]) == ver1 + 1
            # lock free again: grant then kAbort (2) -> kAbortAck (9)
            r = c.exchange(np.ones(1, np.uint8), k5, tables=sub,
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 7
            r = c.exchange(np.array([2], np.uint8), k5, tables=sub,
                           timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 9


def test_fasst_wire_occ_roundtrip(rng):
    """FaSST OCC over the 9-byte wire format {type, lid u32, ver u32}
    (lock_fasst/caladan/proto.h:32-36): READ returns the version,
    ACQUIRE_LOCK CAS grants then rejects, COMMIT bumps ver + unlocks,
    ABORT unlocks (lock_fasst/ebpf/ls_kern.c:58-97)."""
    from dint_tpu.engines import fasst
    from dint_tpu.shim import FASST, FMT_FASST9
    from dint_tpu.tables import locks

    table = locks.create_occ(1 << 10)
    lid = np.array([17], np.uint64)
    with EnginePump(FASST, fasst.step, table, width=64,
                    flush_us=2000).start() as p:
        _warm(p, fmt=FMT_FASST9)
        with ShimClient("127.0.0.1", p.port, fmt=FMT_FASST9) as c:
            # READ (0) -> GRANT_READ (4), ver 0
            r = c.exchange(np.zeros(1, np.uint8), lid, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 4
            assert int(r["ver"][0]) == 0
            # ACQUIRE_LOCK (1) -> GRANT_LOCK (5); second -> REJECT_LOCK (6)
            r = c.exchange(np.ones(1, np.uint8), lid, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 5
            r = c.exchange(np.ones(1, np.uint8), lid, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 6
            # COMMIT (3) -> COMMIT_ACK (8): ver++ and unlock
            r = c.exchange(np.array([3], np.uint8), lid, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 8
            r = c.exchange(np.zeros(1, np.uint8), lid, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 4
            assert int(r["ver"][0]) == 1
            # lock again (freed by COMMIT), then ABORT (2) -> ABORT_ACK (7)
            r = c.exchange(np.ones(1, np.uint8), lid, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 5
            r = c.exchange(np.array([2], np.uint8), lid, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 7
            # and the slot is lockable again after the abort release
            r = c.exchange(np.ones(1, np.uint8), lid, timeout_ms=5000)
            assert r["n"] == 1 and r["type"][0] == 5


@pytest.mark.slow  # ~58s: heaviest wire e2e; the per-op wire roundtrips
def test_tatp_full_transactions_over_wire():  # above stay tier-1
    """FULL TATP transactions over the wire against 3 UDP shard servers —
    the reference's client/server topology (3 server processes + a
    coordinator fanning per-shard batches, client_ebpf_shard.cc:636-677)
    in-process: every phase (read+lock, validate, log x3, bck x2, prim,
    abort) crosses loopback datagrams in the 55-byte format."""
    from dint_tpu.clients import tatp_wire as tw

    with tw.serve_shards(200, width=256, flush_us=1000) as ports:
        with tw.WireCoordinator(ports, 200, width=256) as coord:
            rng = np.random.default_rng(0)
            for _ in range(3):
                coord.run_cohort(rng, 64)
            st = coord.stats
            assert st.attempted == 3 * 64
            assert st.committed > 0
            # outcome taxonomy closes
            assert (st.committed + st.aborted_lock + st.aborted_validate
                    + st.aborted_missing + st.aborted_timeout) \
                == st.attempted
            assert st.timeout_lanes == 0    # loopback: no loss
            # population-driven miss floor is ~25% of the mix; leave slack
            # for the tiny keyspace's contention
            assert st.committed > st.attempted * 0.45


def test_tatp_wire_timeout_counts_not_raises():
    """A lossy/dead server must yield a NUMBER plus a timeout count, not a
    voided run (round-4 verdict: the reference client retries forever so
    loss shows up as latency; our capped retry budget surfaces it as
    ab_timeout txns instead of raising away the whole bench point)."""
    import socket

    from dint_tpu.clients import tatp_wire as tw

    # 3 bound-but-never-served ports: every datagram vanishes
    socks = [socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
             for _ in range(3)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    try:
        with tw.WireCoordinator(ports, 200, width=256, timeout_ms=50,
                                max_tries=2) as coord:
            st = coord.run_cohort(np.random.default_rng(0), 32)
            assert st.attempted == 32
            assert st.committed == 0
            assert st.aborted_timeout == 32       # every txn classified
            assert st.timeout_lanes > 0           # raw datagram count too
            assert (st.aborted_lock + st.aborted_validate
                    + st.aborted_missing) == 0    # no misclassification
    finally:
        for s in socks:
            s.close()
