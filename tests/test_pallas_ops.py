"""Interpret-mode parity for the Pallas DMA-ring kernels (ops/pallas_gather).

Every kernel must be BIT-IDENTICAL to the XLA op chain it replaces — the
acceptance bar of ISSUE 1: `DINT_USE_PALLAS=1 JAX_PLATFORMS=cpu` runs the
dense engines through the kernels (interpret mode, no Mosaic) and must
reproduce the XLA path's stats, table state, and log rings exactly. These
tests pin (a) each kernel against its XLA formula, (b) the fused lock pass
against tatp_dense's actual arb chain on adversarial duplicate/held
batches, (c) both dense engines end-to-end pallas-vs-XLA, with the env-var
plumbing exercised for real, and (d) the fallback contract: a broken
kernel degrades resolve_use_pallas to False instead of raising."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dint_tpu.engines import smallbank_dense as sd, tatp_dense as td
from dint_tpu.ops import pallas_gather as pg

U32 = jnp.uint32
I32 = jnp.int32


# ------------------------------------------------------------ gather_rows


@pytest.mark.parametrize("n,vw,k", [
    (1000, 10, 333),      # val-style wide rows
    (512, 1, 700),        # meta/arb/bal-style single words, K > N
    (37, 4, 5),           # K smaller than the DMA ring depth
    (64, 2, 64),
])
def test_gather_rows_matches_xla_take(rng, n, vw, k):
    tab = jnp.asarray(rng.integers(0, 1 << 32, n * vw, np.int64)
                      .astype(np.uint32))
    idx = jnp.asarray(rng.integers(0, n, k).astype(np.int32))
    got = pg.gather_rows(tab, idx, vw)
    want = jnp.take(tab.reshape(n, vw), idx, axis=0).reshape(-1)
    assert got.dtype == jnp.uint32 and got.shape == (k * vw,)
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_gather_rows_duplicate_and_sentinel_indices(rng):
    """The engines clamp every masked lane onto one sentinel row: heavy
    duplication of a single index must read clean."""
    n, vw = 100, 4
    tab = jnp.asarray(rng.integers(0, 1 << 32, n * vw, np.int64)
                      .astype(np.uint32))
    idx = jnp.asarray(np.full(64, n - 1, np.int32))   # all-sentinel batch
    got = pg.gather_rows(tab, idx, vw)
    assert np.array_equal(np.asarray(got).reshape(64, vw),
                          np.tile(np.asarray(tab[-vw:]), (64, 1)))


def test_gather_rows_word_offset_pattern(rng):
    """The magic-word check gathers ONE word at rows*VW + 1 — expressed as
    pre-scaled flat indices with vw=1."""
    n, vw = 200, 10
    tab = jnp.asarray(rng.integers(0, 1 << 32, n * vw, np.int64)
                      .astype(np.uint32))
    rows = jnp.asarray(rng.integers(0, n, 77).astype(np.int32))
    got = pg.gather_rows(tab, rows * vw + 1, 1)
    assert np.array_equal(np.asarray(got), np.asarray(tab[rows * vw + 1]))


# --------------------------------------------------------- lock_arbitrate


def _xla_chain(arb, rows, active, t, k_arb=td.K_ARB):
    """The exact 3-op chain of tatp_dense.pipe_step's XLA lock path."""
    m = rows.shape[0]
    oob = arb.shape[0]
    old = arb[rows]
    held = (old >> k_arb) == (t - 1)
    packed = (t << k_arb) | (jnp.uint32(m - 1)
                             - jnp.arange(m, dtype=jnp.uint32))
    cand = active & ~held
    arb2 = arb.at[jnp.where(cand, rows, oob)].max(packed, mode="drop")
    grant = cand & (arb2[rows] == packed)
    return arb2, grant


@pytest.mark.parametrize("m,row_space,seed", [
    (64, 8, 0),      # heavy in-batch duplication (8 rows, 64 lanes)
    (64, 1000, 1),   # mostly conflict-free
    (10, 3, 2),      # m > ring depth barely, brutal duplication
    (2, 1, 3),       # m below the ring depth, single row
    (130, 16, 4),    # several ring wraps
])
def test_lock_arbitrate_matches_xla(rng, m, row_space, seed):
    r = np.random.default_rng(seed)
    n1 = max(row_space + 1, 32)
    arb0 = np.zeros(n1, np.uint32)
    # pre-stamp a third of rows: half held (step-1), half stale/expired
    for row in r.choice(row_space, max(1, row_space // 3), replace=False):
        step = r.choice([3, 4])       # t=5: 4 == held, 3 == expired
        arb0[row] = np.uint32((step << td.K_ARB) | r.integers(0, 100))
    t = jnp.asarray(5, U32)
    rows = jnp.asarray(r.integers(0, row_space, m).astype(np.int32))
    act = jnp.asarray(r.random(m) < 0.75)

    a_x, g_x = _xla_chain(jnp.asarray(arb0), rows, act, t)
    a_p, g_p = pg.lock_arbitrate(jnp.asarray(arb0), rows, act, t, td.K_ARB)
    assert np.array_equal(np.asarray(a_x), np.asarray(a_p))
    assert np.array_equal(np.asarray(g_x), np.asarray(g_p) != 0)


def test_lock_arbitrate_held_rows_not_restamped(rng):
    """Candidates on held rows are masked OUT of the XLA scatter so hot
    rows cannot be livelocked by rejected attempts — the kernel must
    preserve exactly that: a held row's stamp survives untouched."""
    n1, m = 16, 8
    t = jnp.asarray(9, U32)
    arb0 = np.zeros(n1, np.uint32)
    arb0[2] = np.uint32((8 << td.K_ARB) | 5)          # held (t-1)
    rows = jnp.asarray(np.full(m, 2, np.int32))       # everyone wants row 2
    act = jnp.ones(m, bool)
    a_p, g_p = pg.lock_arbitrate(jnp.asarray(arb0), rows,
                                 jnp.asarray(act), t, td.K_ARB)
    assert int(np.asarray(g_p).sum()) == 0
    assert np.asarray(a_p)[2] == arb0[2]              # stamp untouched


# ------------------------------------------------- fallback + env plumbing


def test_resolve_use_pallas_env(monkeypatch):
    pg._probe_cache.clear()
    monkeypatch.delenv("DINT_USE_PALLAS", raising=False)
    assert pg.resolve_use_pallas(None) is False       # default off
    monkeypatch.setenv("DINT_USE_PALLAS", "0")
    assert pg.resolve_use_pallas(None) is False
    monkeypatch.setenv("DINT_USE_PALLAS", "1")
    assert pg.resolve_use_pallas(None) is True        # CPU interpret: works
    assert pg.resolve_use_pallas(False) is False      # explicit kwarg wins


def test_broken_kernel_degrades_not_raises(monkeypatch, caplog):
    """The Mosaic-rejection contract: if a kernel fails to compile/run,
    resolve_use_pallas returns False with a logged warning — builders then
    run the XLA path; nothing raises (bench.py/exp.py acceptance)."""
    pg._probe_cache.clear()

    def boom(*a, **k):
        raise RuntimeError("Mosaic lowering failed (simulated)")

    monkeypatch.setattr(pg, "gather_rows", boom)
    with caplog.at_level("WARNING", logger="dint_tpu.pallas"):
        assert pg.resolve_use_pallas(True, n_idx=64, m_lock=None) is False
    assert any("falling back" in r.message for r in caplog.records)
    pg._probe_cache.clear()
    # and a builder given the env still comes up on the XLA path
    # (bypass the builder memo both ways: a healthy cached build would
    # dodge the broken kernel, and the degraded build must not leak)
    monkeypatch.setenv("DINT_USE_PALLAS", "1")
    td.build_pipelined_runner.cache.clear()
    run, init, drain = td.build_pipelined_runner(20, w=16, val_words=4,
                                                 cohorts_per_block=2)
    carry = init(td.populate(np.random.default_rng(0), 20, val_words=4))
    tot = np.zeros(td.N_STATS, np.int64)
    for i in range(2):
        carry, s = run(carry, jax.random.fold_in(jax.random.PRNGKey(0), i))
        tot += np.asarray(s, np.int64).sum(axis=0)
    _, tail = drain(carry)
    tot += np.asarray(tail, np.int64).sum(axis=0)
    assert int(tot[td.STAT_ATTEMPTED]) == 2 * 2 * 16  # XLA path ran fine
    pg._probe_cache.clear()
    td.build_pipelined_runner.cache.clear()


# --------------------------------------------- end-to-end engine parity


def _run_tatp(use_pallas, blocks=3, seed=0):
    db = td.populate(np.random.default_rng(seed), 200, val_words=4)
    run, init, drain = td.build_pipelined_runner(
        200, w=64, val_words=4, cohorts_per_block=2, use_pallas=use_pallas)
    carry = init(db)
    key = jax.random.PRNGKey(seed)
    tot = np.zeros(td.N_STATS, np.int64)
    for i in range(blocks):
        carry, s = run(carry, jax.random.fold_in(key, i))
        tot += np.asarray(s, np.int64).sum(axis=0)
    db, tail = drain(carry)
    tot += np.asarray(tail, np.int64).sum(axis=0)
    return db, tot


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def test_tatp_dense_pallas_bit_identical(monkeypatch):
    """The full dense TATP pipeline — fused meta gather, magic gather,
    fused lock pass — under DINT_USE_PALLAS=1 (env route, the exact
    production spelling) produces the XLA path's stats, tables, arb
    stamps, AND log rings bit for bit."""
    db_x, tot_x = _run_tatp(False)
    monkeypatch.setenv("DINT_USE_PALLAS", "1")
    db_p, tot_p = _run_tatp(None)     # None -> env, end-to-end plumbing
    assert tot_x.tolist() == tot_p.tolist()
    assert int(tot_x[td.STAT_COMMITTED]) > 0          # not trivially empty
    assert int(tot_x[td.STAT_AB_LOCK]) >= 0
    assert _trees_equal(db_x, db_p)                   # incl. log x3 rings


def test_tatp_dense_pallas_contention_bit_identical():
    """US/IC-heavy mix over a tiny keyspace: lock conflicts and validate
    aborts fire (the adversarial case for the fused lock pass — in-batch
    duplicates and held rows every step), still bit-identical."""
    mix = np.array([0, 0, 0, 50, 0, 50, 0], np.float64) / 100.0

    def run(up):
        db = td.populate(np.random.default_rng(1), 16, val_words=4)
        run_f, init, drain = td.build_pipelined_runner(
            16, w=128, val_words=4, cohorts_per_block=2, mix=mix,
            use_pallas=up)
        carry = init(db)
        tot = np.zeros(td.N_STATS, np.int64)
        for i in range(3):
            carry, s = run_f(carry, jax.random.fold_in(jax.random.PRNGKey(9), i))
            tot += np.asarray(s, np.int64).sum(axis=0)
        db, tail = drain(carry)
        return db, tot + np.asarray(tail, np.int64).sum(axis=0)

    db_x, tot_x = run(False)
    db_p, tot_p = run(True)
    assert int(tot_x[td.STAT_AB_LOCK]) > 0            # conflicts really fired
    assert int(tot_x[td.STAT_AB_VALIDATE]) > 0
    assert tot_x.tolist() == tot_p.tolist()
    assert _trees_equal(db_x, db_p)


@pytest.mark.slow  # ~46s; the round-10 budget rule — kernel mechanics and
def test_dense_sharded_pallas_bit_identical():  # both dense parities stay tier-1
    """The tentpole's multi-chip integration: the 8-virtual-device sharded
    TATP runner (shard_map bodies run the kernels on their LOCAL shard
    arrays) is bit-identical XLA-vs-pallas — stats, tables, backups, logs."""
    from dint_tpu.parallel import dense_sharded as ds

    def run(up):
        mesh = ds.make_mesh(8)
        state = ds.create_sharded(mesh, 8, 800, val_words=4, seed=0)
        run_f, init, drain = ds.build_sharded_pipelined_runner(
            mesh, 8, 800, w=32, val_words=4, cohorts_per_block=2,
            use_pallas=up)
        carry = init(state)
        tot = np.zeros(td.N_STATS, np.int64)
        for i in range(2):
            carry, s = run_f(carry, jax.random.fold_in(jax.random.PRNGKey(0), i))
            tot += np.asarray(s, np.int64).sum(axis=0)
        state, tail = drain(carry)
        return state, tot + np.asarray(tail, np.int64).sum(axis=0)

    s_x, t_x = run(False)
    s_p, t_p = run(True)
    assert t_x.tolist() == t_p.tolist()
    assert int(t_x[td.STAT_COMMITTED]) > 0
    assert _trees_equal(s_x, s_p)


def test_dense_sharded_sb_pallas_bit_identical():
    """Sharded SmallBank with TRUE cross-device txns: the owner-side
    held-stamp + balance gathers run through the kernel per device,
    bit-identical stats and global state XLA-vs-pallas."""
    from dint_tpu.parallel import dense_sharded_sb as dsb

    def run(up):
        mesh = dsb.make_mesh(8)
        state = dsb.create_sharded_sb(mesh, 8, 400)
        run_f, init, drain = dsb.build_sharded_sb_runner(
            mesh, 8, 400, w=32, cohorts_per_block=2, use_pallas=up)
        carry = init(state)
        tot = np.zeros(dsb.N_STATS, np.int64)
        for i in range(2):
            carry, s = run_f(carry, jax.random.fold_in(jax.random.PRNGKey(2), i))
            tot += np.asarray(s, np.int64).sum(axis=0)
        state, tail = drain(carry)
        return state, tot + np.asarray(tail, np.int64).sum(axis=0)

    s_x, t_x = run(False)
    s_p, t_p = run(True)
    assert t_x.tolist() == t_p.tolist()
    assert _trees_equal(s_x, s_p)


@pytest.mark.slow  # ~19s; both dense pallas bit-identity pins stay tier-1
def test_tatp_dense_pallas_matches_generic_engine_oracle(monkeypatch):
    """ISSUE 1 acceptance: the EXISTING TATP dense parity test — dense
    engine vs the generic sort-based pipelined engine, the differential
    oracle of tests/test_tatp_dense.py (dint_tpu/testing/oracle.py's
    cross-backend role) — re-run end-to-end with DINT_USE_PALLAS=1. Only
    the dense side routes through the kernels; the generic engine is the
    untouched reference, so this catches any divergence the pallas-vs-XLA
    self-comparison above could share."""
    monkeypatch.setenv("DINT_USE_PALLAS", "1")
    from test_tatp_dense import (
        test_matches_generic_pipelined_engine_at_low_contention as parity)
    parity()


def test_smallbank_dense_pallas_bit_identical(monkeypatch):
    """SmallBank dense: held-stamp + balance gathers through the kernel,
    bit-identical stats/balances/logs, and balance conservation holds."""
    def run(up):
        db = sd.create(300)
        run_f, init, drain = sd.build_pipelined_runner(
            300, w=64, cohorts_per_block=2, use_pallas=up)
        carry = init(db)
        tot = np.zeros(sd.N_STATS, np.int64)
        for i in range(3):
            carry, s = run_f(carry, jax.random.fold_in(jax.random.PRNGKey(3), i))
            tot += np.asarray(s, np.int64).sum(axis=0)
        db, tail = drain(carry)
        return db, tot + np.asarray(tail, np.int64).sum(axis=0)

    db_x, tot_x = run(False)
    monkeypatch.setenv("DINT_USE_PALLAS", "1")
    db_p, tot_p = run(None)                           # env route
    assert tot_x.tolist() == tot_p.tolist()
    assert int(tot_x[sd.STAT_COMMITTED]) > 0
    assert _trees_equal(db_x, db_p)
    # the window-wide conservation oracle on the pallas path
    start = 2 * 300 * 1000
    assert int(np.asarray(sd.total_balance(db_p))) \
        == start + int(tot_p[sd.STAT_BAL_DELTA])
