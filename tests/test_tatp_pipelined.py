"""Cross-cohort pipelined TATP: real concurrency, live ab_validate."""
import jax
import numpy as np
import pytest

from dint_tpu.clients import tatp_client as tc
from dint_tpu.engines import tatp_pipeline as tp

VW = 4


def _run(n_sub, w, blocks, cohorts_per_block=2, seed=0, mix=None):
    rng = np.random.default_rng(seed)
    # cf_buckets left to tatp.create's default sizing (~load<=0.25 at 4
    # slots), which scales with n_sub — a hardcoded 1<<12 cannot hold the
    # ~37.5k CF rows populated at n_sub=20_000
    # log_capacity: the default 1<<20 ring is a GB-scale zero+copy per
    # block on the CI host; these runs commit a few thousand rows at most
    shards, _ = tc.populate_shards(rng, n_sub, val_words=VW,
                                   log_capacity=1 << 14)
    stacked = tp.stack_shards(shards)
    run, init, drain = tp.build_pipelined_runner(
        n_sub, w=w, val_words=VW, cohorts_per_block=cohorts_per_block,
        mix=mix)
    carry = init(stacked)
    key = jax.random.PRNGKey(seed)
    total = np.zeros(tp.N_STATS, np.int64)
    for i in range(blocks):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    stacked, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    return stacked, total


def test_contention_fires_validate_aborts():
    # In the TATP mix nearly every read-set row is also lock-protected by
    # its own txn; the unprotected overlap is InsertCallForwarding's
    # SPECIAL_FACILITY read vs UpdateSubscriberData's sf write
    # (tatp/caladan/client_ebpf_shard.cc:598-608 vs :1379-1390). Force a
    # US/IC-heavy mix over a tiny keyspace so in-flight cohorts commit sf
    # rows between a younger cohort's read and its validate.
    mix = np.array([0, 0, 0, 50, 0, 50, 0], np.float64) / 100.0
    stacked, total = _run(n_sub=32, w=256, blocks=4, mix=mix)
    attempted = int(total[tp.STAT_ATTEMPTED])
    committed = int(total[tp.STAT_COMMITTED])
    assert attempted == 4 * 2 * 256
    assert committed > 0
    assert int(total[tp.STAT_MAGIC_BAD]) == 0
    # the whole point of the pipeline: validation aborts are REAL now
    assert int(total[tp.STAT_AB_VALIDATE]) > 0
    # and lock conflicts across in-flight cohorts exist too
    assert int(total[tp.STAT_AB_LOCK]) > 0
    # accounting closes: every attempted txn has exactly one outcome
    outcomes = (committed + int(total[tp.STAT_AB_LOCK])
                + int(total[tp.STAT_AB_MISSING])
                + int(total[tp.STAT_AB_VALIDATE]))
    assert outcomes == attempted


@pytest.mark.slow  # ~16s; contention + drain invariants stay tier-1
def test_low_contention_mostly_commits():
    stacked, total = _run(n_sub=20_000, w=64, blocks=3)
    attempted = int(total[tp.STAT_ATTEMPTED])
    committed = int(total[tp.STAT_COMMITTED])
    rate = 1 - committed / attempted
    # ab_missing is population-driven, not contention: GET_ACCESS /
    # GET_NEW_DEST / DELETE_CF hit absent AI/SF/CF rows by TATP spec
    # (~25% of the mix fails row lookups regardless of load — the
    # reference counts these as unsuccessful txns too,
    # tatp/caladan/client_ebpf_shard.cc:567-596; analytic expectation
    # pinned in test_tatp_dense.test_ab_missing_matches_population_analytics)
    assert rate < 0.30, rate
    # the CONTENTION aborts are what low load must keep near zero
    contention = int(total[tp.STAT_AB_LOCK]) + int(total[tp.STAT_AB_VALIDATE])
    assert contention / attempted < 0.01, total
    assert int(total[tp.STAT_MAGIC_BAD]) == 0


def test_drain_releases_locks_and_replicas_converge():
    stacked, _ = _run(n_sub=64, w=128, blocks=3, seed=3)
    # all OCC row locks free after drain
    for lk in (stacked.sub_lock, stacked.sec_lock, stacked.ai_lock,
               stacked.sf_lock):
        assert not np.asarray(lk).any()
    assert not np.asarray(stacked.cf_lock.locked).any()
    # dense replicas identical (commit reached prim + both backups)
    for t in (stacked.sub, stacked.sec, stacked.ai, stacked.sf):
        v = np.asarray(t.val)
        r = np.asarray(t.ver)
        assert np.array_equal(v[0], v[1]) and np.array_equal(v[0], v[2])
        assert np.array_equal(r[0], r[1]) and np.array_equal(r[0], r[2])


def test_run_latency_window_measures_real_timestamps():
    """Latency-mode window (stats.run_latency_window): cpb=1 runner, one
    sync fetch per step, percentiles from measured wall-clock spans —
    sample count must be steps - depth + 1 and totals must account every
    dispatched cohort (plus the drain's in-flight tail)."""
    import jax

    from dint_tpu import stats as st
    from dint_tpu.engines import tatp_dense as td

    n_sub, w = 512, 64
    db = td.populate(np.random.default_rng(0), n_sub, val_words=4)
    run, init, drain = td.build_pipelined_runner(n_sub, w=w, val_words=4,
                                                 cohorts_per_block=1)
    carry = init(db)
    carry, total, dt, steps, p = st.run_latency_window(
        run, carry, jax.random.PRNGKey(0), 1.0, td.N_STATS, depth=3)
    _, tail = drain(carry)
    total = total + np.asarray(tail, np.int64).sum(axis=0)
    assert steps > 3
    assert p["n"] == steps - 2                  # steps - depth + 1
    assert p["p50"] > 0 and p["p999"] >= p["p99"] >= p["p50"]
    # a cohort's outcome stats surface depth-1 steps after dispatch, so
    # the timed window + drain capture the 2 warmup cohorts' outcomes
    # too: attempted covers warmup + every timed dispatch (the ~0.5%
    # overcount vs steps*w is documented run_latency_window semantics)
    assert int(total[td.STAT_ATTEMPTED]) == (steps + 2) * w
