"""Multi-host (DCN x ICI) dense TATP: replication crosses host fault
domains (parallel/multihost.py)."""
import jax
import numpy as np
import pytest

from dint_tpu.engines import tatp_dense as td
from dint_tpu.parallel import dense_sharded as ds, multihost as mh

VW = 4
H, C = 4, 2          # 4 hosts x 2 chips on the 8-virtual-device mesh
D = H * C


def _run(n_sub_global, w, blocks, seed=0, h=H, c=C):
    mesh = mh.make_mesh_2d(h, c)
    state = mh.create_multihost(mesh, n_sub_global, val_words=VW,
                                seed=seed)
    run, init, drain = mh.build_multihost_runner(
        mesh, n_sub_global, w=w, val_words=VW, cohorts_per_block=2)
    carry = init(state)
    key = jax.random.PRNGKey(seed)
    total = np.zeros(td.N_STATS, np.int64)
    for i in range(blocks):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    state, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    return state, total


@pytest.mark.slow  # ~18s; the 1-D-totals equivalence pin stays tier-1
def test_accounting_closes_over_2d_mesh():
    state, total = _run(n_sub_global=D * 256, w=64, blocks=3)
    attempted = int(total[td.STAT_ATTEMPTED])
    committed = int(total[td.STAT_COMMITTED])
    assert attempted == 3 * 2 * 64 * D      # psummed over BOTH axes
    assert committed > 0
    assert int(total[td.STAT_MAGIC_BAD]) == 0
    outcomes = (committed + int(total[td.STAT_AB_LOCK])
                + int(total[td.STAT_AB_MISSING])
                + int(total[td.STAT_AB_VALIDATE]))
    assert outcomes == attempted


def test_replicas_live_on_distinct_hosts():
    """The fault-domain property the 2-D mesh exists for: device (h, c)'s
    written rows are mirrored at hosts h+1 and h+2, SAME chip coordinate
    — so all 3 copies of any row sit on 3 different hosts."""
    state, _ = _run(n_sub_global=D * 256, w=64, blocks=4)
    n_loc = mh.n_sub_local(D * 256, D)
    n1 = td.n_rows(n_loc) + 1

    meta = np.asarray(state.db.meta)                    # [H, C, n1]
    val = np.asarray(state.db.val).reshape(H, C, -1, VW)
    bck_meta = np.asarray(state.bck_meta)               # [H, C, 2*n1]
    bck_val = np.asarray(state.bck_val)                 # [H, C, 2*n1*VW]

    wrote = (meta >> 1) > 1
    assert wrote.any()
    for h in range(H):
        for c in range(C):
            for off, slot in ((1, 0), (2, 1)):
                hh = (h + off) % H          # backup HOST, same chip c
                bm = bck_meta[hh, c, slot * n1:(slot + 1) * n1]
                bv = bck_val[hh, c, slot * n1 * VW:(slot + 1) * n1 * VW]
                rows = np.nonzero(wrote[h, c])[0]
                assert np.array_equal(bm[rows], meta[h, c, rows]), \
                    (h, c, off)
                assert np.array_equal(bv.reshape(n1, VW)[rows],
                                      val[h, c, rows]), (h, c, off)


def test_host_failure_recovers_from_surviving_host():
    """Kill host h: every (h, c) range rebuilds from a SURVIVING host's
    log — (h+1, c) or (h+2, c) — via the source-tag filter, proving the
    DCN replication stream is sufficient for cross-host failover."""
    from dint_tpu import recovery

    n_sub_global = D * 256
    n_loc = mh.n_sub_local(n_sub_global, D)
    state, _ = _run(n_sub_global=n_sub_global, w=64, blocks=3)

    meta = np.asarray(state.db.meta)
    val = np.asarray(state.db.val)
    entries = np.asarray(state.db.log.entries)   # [H, C, L*CAP, EW]
    heads = np.asarray(state.db.log.head)        # [H, C, L]
    lanes = state.db.log.lanes
    cap = entries.shape[2] // lanes

    dead_h = 1
    for c in range(C):
        dead = dead_h * C + c                    # linear partition id
        snap = td.populate(np.random.default_rng(dead), n_loc,
                           val_words=VW, log_replicas=1)
        for off in (1, 2):
            hh = (dead_h + off) % H
            e = entries[hh, c].reshape(lanes, cap, -1)
            rec = recovery.recover_tatp_dense(snap, e, heads[hh, c],
                                              key_hi_filter=dead + 1)
            assert np.array_equal(np.asarray(rec.val), val[dead_h, c]), \
                (c, off)
            assert np.array_equal(np.asarray(rec.meta),
                                  meta[dead_h, c]), (c, off)


def test_matches_1d_sharded_totals():
    """Program equivalence: the 2-D mesh partitions the same global
    keyspace into H*C ranges with the same per-partition workload streams
    as the 1-D runner over D devices — total attempted/committed match
    exactly (the transport axis changed, the math did not)."""
    n_sub_global = D * 128
    _, total_2d = _run(n_sub_global, w=32, blocks=2)

    mesh = ds.make_mesh(D)
    state = ds.create_sharded(mesh, D, n_sub_global, val_words=VW, seed=0)
    run, init, drain = ds.build_sharded_pipelined_runner(
        mesh, D, n_sub_global, w=32, val_words=VW, cohorts_per_block=2)
    carry = init(state)
    key = jax.random.PRNGKey(0)
    total_1d = np.zeros(td.N_STATS, np.int64)
    for i in range(2):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total_1d += np.asarray(stats, np.int64).sum(axis=0)
    _, tail = drain(carry)
    total_1d += np.asarray(tail, np.int64).sum(axis=0)

    assert np.array_equal(total_2d, total_1d)


def test_two_hosts_refused():
    with pytest.raises(ValueError, match="3 hosts"):
        mh.create_multihost(mh.make_mesh_2d(2, 2), 64, val_words=VW)


def test_reference_topology_3_hosts():
    """The reference's exact machine count: 3 hosts (x2 chips). With
    H == replication factor, each host backs up BOTH other hosts and
    every row has a copy on every host — accounting still closes."""
    _, total = _run(6 * 128, w=32, blocks=2, seed=3, h=3, c=2)
    attempted = int(total[td.STAT_ATTEMPTED])
    committed = int(total[td.STAT_COMMITTED])
    assert attempted == 2 * 2 * 32 * 6
    assert committed > 0
    assert int(total[td.STAT_MAGIC_BAD]) == 0
    outcomes = (committed + int(total[td.STAT_AB_LOCK])
                + int(total[td.STAT_AB_MISSING])
                + int(total[td.STAT_AB_VALIDATE]))
    assert outcomes == attempted
