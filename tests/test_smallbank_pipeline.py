"""Device-fused SmallBank pipeline: invariants + contention response."""
import jax
import numpy as np
import pytest

from dint_tpu.engines import smallbank_pipeline as sp


def _run_blocks(n_accounts, w, blocks, cohorts_per_block=2, seed=0):
    stacked = sp.create_stacked(n_accounts)
    base = int(np.asarray(sp.total_balance(stacked)))
    run = sp.build_runner(n_accounts, w=w, cohorts_per_block=cohorts_per_block)
    key = jax.random.PRNGKey(seed)
    total = np.zeros(sp.N_STATS, np.int64)
    for i in range(blocks):
        stacked, stats = run(stacked, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    return stacked, total, base


def test_invariants_small():
    stacked, total, base = _run_blocks(n_accounts=512, w=256, blocks=3)

    attempted = int(total[sp.STAT_ATTEMPTED])
    committed = int(total[sp.STAT_COMMITTED])
    assert attempted == 3 * 2 * 256
    assert 0 < committed <= attempted
    assert committed + total[sp.STAT_AB_LOCK] + total[sp.STAT_AB_LOGIC] == attempted
    assert int(total[sp.STAT_MAGIC_BAD]) == 0

    # balance conservation: table delta == sum of committed deltas (mod 2^32)
    final = int(np.asarray(sp.total_balance(stacked)))
    want = int(total[sp.STAT_BAL_DELTA])
    assert (final - base) % (1 << 32) == want % (1 << 32)

    # all locks released (committed AND aborted txns release)
    for lk in (stacked.sav_sh, stacked.sav_ex, stacked.chk_sh, stacked.chk_ex):
        assert int(np.asarray(lk).sum()) == 0

    # replicas converged: every commit reached prim + both backups
    for tbl in (stacked.sav, stacked.chk):
        v = np.asarray(tbl.val)
        r = np.asarray(tbl.ver)
        assert np.array_equal(v[0], v[1]) and np.array_equal(v[0], v[2])
        assert np.array_equal(r[0], r[1]) and np.array_equal(r[0], r[2])

    # log: one entry per written record per shard, identical depth
    heads = np.asarray(stacked.log.head).sum(axis=1)
    assert heads[0] == heads[1] == heads[2] > 0


@pytest.mark.slow  # ~32s; invariants + host-coordinator oracle stay tier-1
def test_abort_rate_responds_to_contention():
    # tiny hot set + wide cohort -> heavy lock contention; large keyspace ->
    # almost none. The no-wait 2PL reject semantics must show the difference.
    _, hot, _ = _run_blocks(n_accounts=64, w=512, blocks=2, seed=1)
    _, cold, _ = _run_blocks(n_accounts=1 << 16, w=64, blocks=2, seed=1)
    hot_rate = hot[sp.STAT_AB_LOCK] / hot[sp.STAT_ATTEMPTED]
    cold_rate = cold[sp.STAT_AB_LOCK] / cold[sp.STAT_ATTEMPTED]
    assert hot_rate > 0.2, hot_rate
    assert cold_rate < 0.05, cold_rate


def test_matches_host_coordinator_balance_model():
    # non-conserving ops change totals by +AMT (deposit/transact) and
    # -(AMT [+1 overdraw]) (write_check); conserving mix keeps delta 0.
    # With the full mix, delta must equal the stats' own accounting — checked
    # in test_invariants_small — and be plausible in magnitude here.
    _, total, _ = _run_blocks(n_accounts=4096, w=256, blocks=2, seed=2)
    committed = int(total[sp.STAT_COMMITTED])
    delta = int(total[sp.STAT_BAL_DELTA])
    assert abs(delta) <= max(sp.AMT + 1, sp.TS_AMT_MAX) * committed
