"""dintcost: the static cost model and its CI gate.

Liveness: mutated mini-engine fixtures — an extra unfused scatter
dispatch, a doubled gather width, a dropped donation — prove each
cost_budget check fires (naming the offending wave/target) and is
silenceable by a scoped allowlist entry; fused-pair fixtures prove the
dominance checks in both directions. Soundness: the full 36-target
matrix reconciles against every declared waves.py formula, stays inside
its registered budgets with ZERO cost_budget allowlist entries, and
every @fused target strictly dominates its unfused twin on dispatches —
the round-12 claim as a standing CPU-only assertion. The geometry pins
at the bottom keep the budget ledger's formula variables honest against
the engine modules' real constants.
"""
import contextlib
import json
import os

import jax
import jax.numpy as jnp
import pytest

import dint_tpu.parallel  # noqa: F401 — installs the jax.shard_map shim
from dint_tpu import analysis
from dint_tpu.analysis import allowlist as al
from dint_tpu.analysis import core, cost
from dint_tpu.analysis import targets as T
from dint_tpu.monitor import waves

pytestmark = pytest.mark.cost

S = jax.ShapeDtypeStruct
U32 = jnp.uint32
I32 = jnp.int32

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ALLOW = os.path.join(REPO, "tools", "dintlint_allow.json")

# ------------------------------------------------- mini-engine fixtures
#
# One table, one wave-scoped gather whose traffic equals the registered
# magic_gather formula EXACTLY at this geometry (so the clean fixture
# reconciles at ratio 1.0), one unattributed install scatter, donated
# table. Budgets are calibrated from the clean fixture's own derived
# model, then each mutation regresses exactly one number.

WAVE = "dint.tatp_dense.magic_gather"
GEOM = dict(w=8, k=4, vw=2)
DECL = waves.wave_bytes(WAVE, **GEOM)          # = w*k*4 = 128 B
NE = DECL // 4                                  # gather elements
N = 512


def _mini_step(wide=False, extra=False, donate=True):
    ne = NE * (2 if wide else 1)

    def raw(tab, idx, vals):
        with jax.named_scope(WAVE):
            got = tab[idx]                      # ne rows * 4 B
        s = got.sum(dtype=U32)
        tab2 = tab.at[idx[:NE]].set(vals + s, mode="drop",
                                    unique_indices=True)
        if extra:                               # the unfused regression
            tab2 = tab2.at[idx[:NE]].set(vals ^ s, mode="drop",
                                         unique_indices=True)
        return tab2

    fn = jax.jit(raw, donate_argnums=(0,)) if donate else jax.jit(raw)
    return fn, (S((N,), U32), S((ne,), I32), S((NE,), U32))


@contextlib.contextmanager
def _registered(name, fn, args, meta):
    """Temporarily add a fixture target (+ cost meta) to the registry so
    the real analysis.run plumbing — pass, dedup, allowlist — applies."""
    T.TARGETS[name] = lambda: core.trace_target(name, fn, args)
    T.TARGET_DOCS[name] = "dintcost test fixture"
    T.TARGET_PROTOCOL[name] = ()
    if meta is not None:
        T.TARGET_COST[name] = meta
    try:
        yield
    finally:
        for d in (T.TARGETS, T.TARGET_DOCS, T.TARGET_PROTOCOL,
                  T.TARGET_COST):
            d.pop(name, None)


def _meta(budget):
    return {"steps": 1.0, "geom": dict(GEOM), "wave_expect": {},
            "budget": budget}


def _clean_numbers():
    """Derive the clean fixture once: its numbers calibrate every
    mutated fixture's budget."""
    fn, args = _mini_step()
    model = cost.derive(core.trace_target("fixture_cost/_probe", fn, args),
                        steps=1.0, geom=GEOM)
    return model.dispatches_per_step, model.bytes_per_step, \
        model.footprint_bytes


def _run(name, allowlist_entries=None):
    return analysis.run(targets=[name], passes=["cost_budget"],
                        allowlist_entries=allowlist_entries)


def _err_codes(findings):
    return {f.code for f in findings
            if f.severity == "error" and not f.suppressed}


def test_clean_mini_engine_passes_gate():
    disp, nbytes, fp = _clean_numbers()
    fn, args = _mini_step()
    name = "fixture_cost/clean"
    with _registered(name, fn, args, _meta(
            {"dispatches": disp, "bytes": nbytes, "footprint": fp})):
        fs = _run(name)
        assert not _err_codes(fs), [str(f) for f in fs]
        # and the wave reconciles at exactly the declared formula
        model = cost.model_for(name)
        checks = cost.reconcile_for(name, model)
        assert [c.wave for c in checks] == [WAVE]
        assert checks[0].ratio == pytest.approx(1.0)


def test_extra_scatter_fires_dispatch_budget_and_is_silenceable():
    disp, _, fp = _clean_numbers()
    fn, args = _mini_step(extra=True)
    name = "fixture_cost/extra-dispatch"
    meta = _meta({"dispatches": disp, "bytes": None, "footprint": fp})
    with _registered(name, fn, args, meta):
        fs = _run(name)
        assert _err_codes(fs) == {"over-dispatch-budget"}, \
            [str(f) for f in fs]
        hit = [f for f in fs if f.code == "over-dispatch-budget"]
        assert hit[0].target == name       # the offender is named
        fs2 = _run(name, allowlist_entries=[
            {"pass": "cost_budget", "code": "over-dispatch-budget",
             "target": name, "reason": "fixture: regression on purpose"}])
        assert not analysis.has_errors(fs2)
        assert any(f.suppressed for f in fs2)


def test_doubled_gather_fires_formula_and_bytes_budget():
    disp, nbytes, fp = _clean_numbers()
    fn, args = _mini_step(wide=True)
    name = "fixture_cost/wide-gather"
    # footprint unbudgeted: the wider idx input grows live state too, and
    # this test isolates the byte checks
    meta = _meta({"dispatches": disp, "bytes": nbytes, "footprint": None})
    with _registered(name, fn, args, meta):
        fs = _run(name)
        assert _err_codes(fs) == {"formula-mismatch", "over-bytes-budget"}
        mism = [f for f in fs if f.code == "formula-mismatch"]
        assert mism[0].site == WAVE        # the offending WAVE is named
        assert "2.00" in mism[0].message   # derived = 2x declared
        fs2 = _run(name, allowlist_entries=[
            {"pass": "cost_budget", "code": "formula-mismatch",
             "target": name, "reason": "fixture: doubled on purpose"},
            {"pass": "cost_budget", "code": "over-bytes-budget",
             "target": name, "reason": "fixture: doubled on purpose"}])
        assert not analysis.has_errors(fs2)


def test_dropped_donation_fires_footprint_budget():
    disp, nbytes, fp = _clean_numbers()
    fn, args = _mini_step(donate=False)
    name = "fixture_cost/no-donate"
    meta = _meta({"dispatches": disp, "bytes": nbytes, "footprint": fp})
    with _registered(name, fn, args, meta):
        fs = _run(name)
        assert _err_codes(fs) == {"over-footprint-budget"}, \
            [str(f) for f in fs]
        # dropping donate_argnums re-allocates the table: ~doubled state
        model = cost.model_for(name)
        assert model.footprint_bytes >= fp + N * 4
        fs2 = _run(name, allowlist_entries=[
            {"pass": "cost_budget", "code": "over-footprint-budget",
             "target": name, "reason": "fixture: donation dropped"}])
        assert not analysis.has_errors(fs2)


def test_fused_dominance_fires_when_fused_loses():
    disp, nbytes, fp = _clean_numbers()
    twin_fn, twin_args = _mini_step()               # 2 dispatches
    fused_fn, fused_args = _mini_step(extra=True)   # 3 dispatches: WORSE
    twin, fused = "fixture_cost/mini", "fixture_cost/mini@fused"
    fused_model = cost.derive(
        core.trace_target("fixture_cost/_probe_fused", fused_fn,
                          fused_args), steps=1.0, geom=GEOM)
    meta = _meta({"dispatches": fused_model.dispatches_per_step,
                  "bytes": None, "footprint": fp})
    with _registered(twin, twin_fn, twin_args, None), \
            _registered(fused, fused_fn, fused_args, meta):
        fs = _run(fused)
        assert {"fused-dispatch-dominance",
                "fused-bytes-dominance"} <= _err_codes(fs), \
            [str(f) for f in fs]
        dom = [f for f in fs if f.code == "fused-dispatch-dominance"]
        assert dom[0].site == twin         # the twin is named
        fs2 = _run(fused, allowlist_entries=[
            {"pass": "cost_budget", "code": "fused-dispatch-dominance",
             "target": fused, "reason": "fixture: regression on purpose"},
            {"pass": "cost_budget", "code": "fused-bytes-dominance",
             "target": fused, "reason": "fixture: regression on purpose"}])
        assert not analysis.has_errors(fs2)


def test_fused_dominance_clean_when_fused_wins():
    _, nbytes, fp = _clean_numbers()
    fused_fn, fused_args = _mini_step()             # 2 dispatches: wins
    twin_fn, twin_args = _mini_step(extra=True)     # 3 dispatches
    twin, fused = "fixture_cost/mini2", "fixture_cost/mini2@fused"
    meta = _meta({"dispatches": 2, "bytes": nbytes, "footprint": fp})
    with _registered(twin, twin_fn, twin_args, None), \
            _registered(fused, fused_fn, fused_args, meta):
        assert not _err_codes(_run(fused))


# ------------------------------------------------------ full-matrix gate


def test_cost_gate_full_matrix_clean_with_zero_allowlist_entries():
    """Acceptance: `dintcost check --all` semantics — the cost_budget
    pass over every registered target, repo allowlist applied, zero
    unsuppressed errors AND zero cost_budget suppressions in the file."""
    findings = analysis.run(
        passes=["cost_budget"],
        allowlist_path=ALLOW if os.path.exists(ALLOW) else None)
    errors = [str(f) for f in findings
              if f.severity == "error" and not f.suppressed]
    assert not errors, "dintcost gate failed:\n" + "\n".join(errors)
    entries = al.load(ALLOW) if os.path.exists(ALLOW) else []
    assert not [e for e in entries if e["pass"] == "cost_budget"], \
        "the dintcost gate must hold without allowlist exceptions"


def test_every_fused_target_dominates_its_twin():
    """The round-12 fusion claim, statically: strictly fewer dispatches
    per step than the unfused twin, never >5% more bytes."""
    from dint_tpu.analysis.passes.cost_budget import DOM_BYTES_EPS
    pairs = 0
    for name in sorted(T.TARGETS):
        twin = cost.fused_twin(name)
        if not twin or twin not in T.TARGETS:
            continue
        try:
            mf, mt = cost.model_for(name), cost.model_for(twin)
        except T.SkipTarget:
            continue
        assert not mf.error and not mt.error, (name, mf.error, mt.error)
        assert mf.dispatches_per_step < mt.dispatches_per_step, \
            (name, mf.dispatches_per_step, twin, mt.dispatches_per_step)
        assert mf.bytes_per_step <= mt.bytes_per_step \
            * (1 + DOM_BYTES_EPS), (name, mf.bytes_per_step, twin)
        pairs += 1
    assert pairs >= 10        # tatp x3, sb x3, ds x2, dsb x3


def test_reconciliation_full_matrix():
    """Every declared waves.py formula a target exercises agrees with
    the derived bytes within tolerance — the hand ledger cannot rot."""
    covered = 0
    for name in sorted(T.TARGET_COST):
        try:
            model = cost.model_for(name)
        except T.SkipTarget:
            continue
        assert not model.error, (name, model.error)
        for c in cost.reconcile_for(name, model):
            assert c.ok, (name, c.wave, c.derived, c.declared,
                          round(c.ratio, 3))
            covered += 1
    assert covered >= 60      # the matrix exercises the formula ledger


def test_wave_registry_complete():
    """Satellite contract: every registered wave has a bytes formula or
    an explicit compute-only / unmodeled doc marker — no silently
    unaccounted wave can enter the registry."""
    for n in waves.ALL_WAVES:
        doc = waves.WAVE_DOCS[n].lower()
        assert (waves.WAVE_BYTES[n] is not None
                or "compute-only" in doc or "unmodeled" in doc), \
            (n, "needs a bytes formula or a compute-only/unmodeled marker")


def test_budget_geometry_pins_engine_constants():
    """The ledger's formula variables against the engine modules' real
    constants — a drifted K/L/VW would silently skew every budget."""
    from dint_tpu.engines import smallbank_pipeline, tatp_pipeline
    assert T._TD_GEOM["k"] == tatp_pipeline.K
    assert T._TD_GEOM["w"] == T._W and T._TD_GEOM["vw"] == T._VW
    assert T._SB_GEOM["l"] == smallbank_pipeline.L
    assert T._SB_GEOM["vw"] == smallbank_pipeline.VW
    assert T._DS_GEOM["d"] == T._MESH_SHARDS
    assert T._DSB_GEOM["d"] == T._MESH_SHARDS
    # every registered target has a complete cost declaration
    assert sorted(T.TARGET_COST) == sorted(T.TARGETS)
    for name, meta in T.TARGET_COST.items():
        assert meta["budget"]["dispatches"] is not None, name
        assert meta["budget"]["footprint"] is not None, name


# --------------------------------------------------------------- the CLI
#
# main() runs in-process (same importlib pattern as the dintlint prune
# test) so the CLI tests reuse this process's TraceCache instead of
# paying a fresh jax import + trace per subprocess — the exit-code and
# JSON-line contract is identical either way.


def _dintcost_main():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_dintcost_cli", os.path.join(REPO, "tools", "dintcost.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def test_cli_report_check_and_diff(tmp_path, capsys):
    """One CLI round-trip: report -o artifact + --json schema, check
    exit 0, and diff catching an injected regression by name."""
    main = _dintcost_main()
    art = tmp_path / "cost.json"
    assert main(["report", "tatp_dense/block", "tatp_dense/block@fused",
                 "--json", "-o", str(art)]) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["metric"] == "dintcost"
    assert isinstance(payload["schema"], int)
    e = payload["targets"]["tatp_dense/block@fused"]
    for k in ("bytes_per_step", "dispatches_per_step", "footprint_bytes",
              "waves", "reconcile", "budget", "ledger_bytes"):
        assert k in e
    assert e["fused_twin"] == "tatp_dense/block"
    assert all(c["ok"] for c in e["reconcile"])

    assert main(["check", "--target", "tatp_dense/block@fused",
                 "--json"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out.strip().splitlines()[-1])["ok"] is True

    mutated = json.loads(art.read_text())
    t = mutated["targets"]["tatp_dense/block"]
    t["dispatches_per_step"] += 1
    wave = "dint.tatp_dense.install"
    t["waves"][wave]["bytes_per_step"] *= 2
    mut = tmp_path / "mutated.json"
    mut.write_text(json.dumps(mutated))
    assert main(["diff", str(art), str(mut), "--json"]) == 1
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    kinds = {(r["kind"], r.get("wave")) for r in d["regressions"]}
    assert ("dispatches", None) in kinds
    assert ("wave-bytes", wave) in kinds
    # and A vs A is clean
    assert main(["diff", str(art), str(art)]) == 0
    capsys.readouterr()


def test_cli_unknown_target_exits_2(capsys):
    main = _dintcost_main()
    with pytest.raises(SystemExit) as exc:
        main(["report", "nope/bad"])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "unknown" in err and "tatp_dense/block" in err


# ------------------------------------------- hierarchical route (2-D mesh)


def test_hier_route_strictly_fewer_dcn_bytes_everywhere():
    """Round-14 tentpole, statically: at EVERY calibrated 2-D geometry
    the hierarchical (ici-then-dcn) route moves strictly fewer bytes
    over the dcn axis than its flat tuple-axis twin — the whole reason
    the transport restructure exists. 1-D targets carry no dcn bytes at
    all (the axis split only prices the 2-D mesh)."""
    pairs = 0
    for name, twin in sorted(T.TARGET_FLAT_TWIN.items()):
        mh_, mf_ = cost.model_for(name), cost.model_for(twin)
        assert not mh_.error and not mf_.error, (name, mh_.error)
        assert mh_.dcn_bytes_per_step < mf_.dcn_bytes_per_step, \
            (name, mh_.dcn_bytes_per_step, twin, mf_.dcn_bytes_per_step)
        assert mh_.dcn_bytes_per_step > 0
        assert mh_.axis_bytes_per_step()["ici"] > 0
        pairs += 1
    assert pairs >= 3         # block, block@mon, block@h3
    assert cost.model_for("dense_sharded_sb/block").dcn_bytes_per_step == 0


def test_hier_dominance_finding_fires_when_hier_regresses(monkeypatch):
    """Liveness for the hier-dcn-dominance gate: point a target at
    itself as its own flat twin — equal dcn bytes is NOT strict
    dominance, so the error must fire and name the twin."""
    from types import SimpleNamespace

    from dint_tpu.analysis.passes import cost_budget as cb

    name = "multihost_sb/block@flat"
    model = cost.model_for(name)
    monkeypatch.setitem(T.TARGET_FLAT_TWIN, name, name)
    fs = cb._hier_dominance_findings(SimpleNamespace(name=name), model)
    assert [f.code for f in fs] == ["hier-dcn-dominance"]
    assert fs[0].severity == "error" and fs[0].site == name


# --------------------------------------- double-buffered route (round 18)


def test_overlap_twins_parity_and_priced_footprint_everywhere():
    """Round-18 tentpole, statically: at every calibrated overlap pair
    the double-buffered serve route moves NO MORE dcn-axis link bytes
    per step than the unoverlapped twin it is supposed to hide under,
    and its footprint exceeds the twin's by exactly the priced prefetch
    double buffer (targets.OVERLAP_FOOTPRINT) — the in-flight cohort is
    the ONLY extra state the overlap may hold."""
    pairs = 0
    for name, twin in sorted(T.TARGET_OVERLAP_TWIN.items()):
        mo, mt = cost.model_for(name), cost.model_for(twin)
        assert not mo.error and not mt.error, (name, mo.error, mt.error)
        assert mo.dcn_bytes_per_step <= mt.dcn_bytes_per_step, \
            (name, mo.dcn_bytes_per_step, twin, mt.dcn_bytes_per_step)
        allowance = cost.eval_budget_bytes(T.OVERLAP_FOOTPRINT,
                                           mo.geom, 0.0)
        assert allowance and allowance > 0, (name, mo.geom)
        extra = mo.footprint_bytes - mt.footprint_bytes
        assert 0 < extra <= allowance, (name, extra, allowance)
        pairs += 1
    assert pairs >= 2         # serve@overlap, serve@overlap+mon


def test_overlap_findings_fire_on_regression(monkeypatch):
    """Liveness for the round-18 overlap gates: (a) pointing a target
    whose route moves MORE dcn bytes at a cheaper twin must fire
    overlap-dcn-parity; (b) a target carrying state past the priced
    double buffer must fire overlap-footprint. Both name the twin."""
    from types import SimpleNamespace

    from dint_tpu.analysis.passes import cost_budget as cb

    # (a) the flat serve lowering moves MORE dcn bytes than the
    # hierarchical serve target — parity must fire
    name = "multihost_sb/serve@flat"
    model = cost.model_for(name)
    monkeypatch.setitem(T.TARGET_OVERLAP_TWIN, name, "multihost_sb/serve")
    fs = cb._overlap_findings(SimpleNamespace(name=name), model)
    assert "overlap-dcn-parity" in [f.code for f in fs]
    assert all(f.severity == "error" and f.site == "multihost_sb/serve"
               for f in fs)

    # (b) the trace variant carries event-ring state far past the
    # priced prefetch buffer — footprint must fire
    name2 = "multihost_sb/block@trace"
    model2 = cost.model_for(name2)
    monkeypatch.setitem(T.TARGET_OVERLAP_TWIN, name2, "multihost_sb/block")
    fs2 = cb._overlap_findings(SimpleNamespace(name=name2), model2)
    assert "overlap-footprint" in [f.code for f in fs2]


def test_prune_check_is_a_gate_scoped_dry_run(tmp_path, capsys):
    """The stale-entry contract, shared verbatim with dintlint and
    dintdur: `check --prune-allowlist --check` is a DRY RUN that fails
    (exit 1) on a stale cost_budget entry without touching the file;
    without --check the stale entry is dropped — but ONLY entries
    scoped to this gate's pass. Wildcard-pass entries and entries for
    other passes belong to dintlint's full-suite prune and survive."""
    main = _dintcost_main()
    entries = json.loads(
        open(os.path.join(REPO, "tools", "dintlint_allow.json")).read())
    n_repo = len(entries)
    entries += [
        {"pass": "cost_budget", "code": "no-such-code",
         "reason": "stale on purpose"},
        {"pass": "*", "code": "no-such-code",
         "reason": "wildcard: only dintlint may judge this"},
    ]
    path = tmp_path / "allow.json"
    path.write_text(json.dumps(entries))
    before = path.read_text()

    # dry run: exit 1, file NOT rewritten, offender named
    assert main(["check", "--prune-allowlist", "--check",
                 "--allowlist", str(path)]) == 1
    assert path.read_text() == before
    out = capsys.readouterr().out
    assert "NOT rewritten" in out
    assert "cost_budget/no-such-code" in out

    # real prune: exit 0, ONLY the gate-scoped stale entry dropped
    assert main(["check", "--prune-allowlist",
                 "--allowlist", str(path)]) == 0
    capsys.readouterr()
    pruned = json.loads(path.read_text())
    assert len(pruned) == n_repo + 1
    assert not any(e["pass"] == "cost_budget" for e in pruned)
    assert any(e["pass"] == "*" and e["code"] == "no-such-code"
               for e in pruned)          # dintlint's problem, kept

    # usage discipline: --check only modifies --prune-allowlist, and
    # the prune needs the gate's full matrix (no --target)
    with pytest.raises(SystemExit):
        main(["check", "--all", "--check"])
    with pytest.raises(SystemExit):
        main(["check", "--prune-allowlist", "--target",
              "tatp_dense/block", "--allowlist", str(path)])


def test_every_scan_target_beats_point_probes_per_row():
    """The round-20 dintscan bandwidth claim, statically: every @scan
    target's dint.store.scan wave must deliver reply rows STRICTLY
    cheaper (HBM bytes/row) than its point twin's dint.store.probe
    wave prices a probed reply (bytes/probe) — the same inequality the
    standing scan-bytes-dominance gate enforces, pinned here with the
    actual numbers so a silent geometry drift is loud."""
    pairs = 0
    for name, twin in sorted(T.TARGET_SCAN_TWIN.items()):
        try:
            ms, mt = cost.model_for(name), cost.model_for(twin)
        except T.SkipTarget:
            continue
        assert not ms.error and not mt.error, (name, ms.error, mt.error)
        geom = ms.geom or {}
        w, sl = float(geom["w"]), float(geom["sl"])
        scan_b = ms.wave_bytes_per_step().get("dint.store.scan", 0.0)
        probe_b = mt.wave_bytes_per_step().get("dint.store.probe", 0.0)
        assert scan_b > 0 and probe_b > 0, (name, scan_b, twin, probe_b)
        per_row, per_probe = scan_b / (w * sl), probe_b / w
        assert per_row < per_probe, (name, per_row, twin, per_probe)
        pairs += 1
    assert pairs >= 3     # block@scan, block@scan+pallas, serve@scan
