"""Multi-chip dense TATP: device-local txns + ppermute'd replication."""
import jax
import numpy as np

from dint_tpu.engines import tatp_dense as td
from dint_tpu.parallel import dense_sharded as ds

VW = 4
D = 8


def _run(n_sub_global, w, blocks, seed=0, mix=None):
    mesh = ds.make_mesh(D)
    state = ds.create_sharded(mesh, D, n_sub_global, val_words=VW,
                              seed=seed)
    run, init, drain = ds.build_sharded_pipelined_runner(
        mesh, D, n_sub_global, w=w, val_words=VW, cohorts_per_block=2,
        mix=mix)
    carry = init(state)
    key = jax.random.PRNGKey(seed)
    total = np.zeros(td.N_STATS, np.int64)
    for i in range(blocks):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    state, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    return state, total


def test_accounting_closes_and_scales_by_devices():
    state, total = _run(n_sub_global=8 * 512, w=128, blocks=3)
    attempted = int(total[td.STAT_ATTEMPTED])
    committed = int(total[td.STAT_COMMITTED])
    # every device contributes w txns per step (psummed stats)
    assert attempted == 3 * 2 * 128 * D
    assert committed > 0
    assert int(total[td.STAT_MAGIC_BAD]) == 0
    outcomes = (committed + int(total[td.STAT_AB_LOCK])
                + int(total[td.STAT_AB_MISSING])
                + int(total[td.STAT_AB_VALIDATE]))
    assert outcomes == attempted


def test_backups_mirror_primaries_and_logs_replicate():
    state, total = _run(n_sub_global=8 * 256, w=64, blocks=4)
    n_loc = ds.n_sub_local(8 * 256, D)
    n1 = td.n_rows(n_loc) + 1

    meta = np.asarray(state.db.meta)          # [D, n1]
    val = np.asarray(state.db.val).reshape(D, -1, VW)   # [D, n1, VW]
    bck_meta = np.asarray(state.bck_meta)     # [D, 2*n1]
    bck_val = np.asarray(state.bck_val)       # [D, 2*n1*VW]

    assert not np.asarray(state.db.locked).any()   # all stamps expired
    wrote = (meta >> 1) > 1                   # rows written past populate
    assert wrote.any()
    for d in range(D):
        for off, slot in ((1, 0), (2, 1)):
            holder = (d + off) % D            # device that backs up d
            bm = bck_meta[holder, slot * n1:(slot + 1) * n1]
            bv = bck_val[holder, slot * n1 * VW:(slot + 1) * n1 * VW]
            bv = bv.reshape(n1, VW)
            rows = np.nonzero(wrote[d])[0]
            assert np.array_equal(bm[rows], meta[d, rows]), (d, off)
            assert np.array_equal(bv[rows], val[d, rows]), (d, off)

    # replicated logging: every write appended on 3 devices
    heads = np.asarray(state.db.log.head).sum()
    # deleted rows bumped ver but exists=0; every bump logged once per
    # device x3 replicas-over-devices. ver counts bumps exactly.
    vers0 = []
    for d in range(D):
        db0 = td.populate(np.random.default_rng(d), n_loc, val_words=VW)
        vers0.append(np.asarray(db0.meta) >> 1)
    bumps = int(sum((meta[d].astype(np.int64) >> 1).sum()
                    - vers0[d].astype(np.int64).sum() for d in range(D)))
    assert heads == 3 * bumps, (heads, bumps)


def test_lost_device_recovers_from_any_log_stream():
    """Device d's primary range rebuilds from its local snapshot + ANY of
    the 3 logs carrying its stream: its own ring (source tag 0) or a
    backup holder's ring (tag d+1) — the failover the reference's
    write-ahead logs exist for but never implement (SURVEY.md 5.3)."""
    from dint_tpu import recovery

    n_sub_global = 8 * 256
    n_loc = ds.n_sub_local(n_sub_global, D)
    state, _ = _run(n_sub_global=n_sub_global, w=64, blocks=3)

    meta = np.asarray(state.db.meta)
    val = np.asarray(state.db.val)
    entries = np.asarray(state.db.log.entries)   # [D, L*CAP, EW]
    heads = np.asarray(state.db.log.head)        # [D, L]
    lanes = state.db.log.lanes
    cap = entries.shape[1] // lanes    # .capacity sees the stacked axis

    def ring_of(dev):
        return entries[dev].reshape(lanes, cap, -1), heads[dev]

    for dead in (0, 3):
        snap = td.populate(np.random.default_rng(dead), n_loc, val_words=4,
                           log_replicas=1)
        # own log stream (tag 0) and both backup holders' streams (tag d+1)
        sources = [(dead, 0), ((dead + 1) % D, dead + 1),
                   ((dead + 2) % D, dead + 1)]
        for holder, tag in sources:
            e, h = ring_of(holder)
            rec = recovery.recover_tatp_dense(snap, e, h,
                                              key_hi_filter=tag)
            assert np.array_equal(np.asarray(rec.val), val[dead]), \
                (dead, holder, tag)
            assert np.array_equal(np.asarray(rec.meta), meta[dead]), \
                (dead, holder, tag)


def test_uneven_partition_rounds_up():
    """n_sub_global not divisible by D: every device sizes for the ceil
    and the accounting still closes (psummed across the mesh)."""
    state, total = _run(n_sub_global=8 * 100 + 3, w=32, blocks=2)
    assert int(total[td.STAT_ATTEMPTED]) == 2 * 2 * 32 * D
    outcomes = (int(total[td.STAT_COMMITTED])
                + int(total[td.STAT_AB_LOCK])
                + int(total[td.STAT_AB_MISSING])
                + int(total[td.STAT_AB_VALIDATE]))
    assert outcomes == int(total[td.STAT_ATTEMPTED])
