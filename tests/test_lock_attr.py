"""Lock-attribution OCC variant (tatp/ebpf/lock_kern.c semantics)."""
import numpy as np

from dint_tpu.clients import micro, workloads as wl
from dint_tpu.engines import fasst
from dint_tpu.engines.types import Op, Reply, make_batch
from dint_tpu.ops import hashing
from dint_tpu.tables import locks

NL = 16


def _colliding_pair():
    """Two distinct keys sharing a lock slot, plus a lone key."""
    base = np.arange(1, 4000, dtype=np.uint64)
    slots = hashing.bucket_np(base, NL)
    for i in range(len(base)):
        for j in range(i + 1, min(i + 200, len(base))):
            if slots[i] == slots[j]:
                return int(base[i]), int(base[j])
    raise AssertionError("no collision found")


def test_reject_attribution():
    a, b = _colliding_pair()
    t = locks.create_occ_attr(NL)

    # batch 1: a takes the lock; b (sharing the slot) and a-again rejected
    ops = np.array([Op.LOCK, Op.LOCK, Op.LOCK], np.int32)
    keys = np.array([a, b, a], np.uint64)
    t, rep = fasst.step_attr(t, make_batch(ops, keys, val_words=1))
    rt = np.asarray(rep.rtype)
    assert rt[0] == Reply.GRANT
    assert rt[1] == Reply.REJECT            # hash sharing: holder key != b
    assert rt[2] == Reply.REJECT_SAME_KEY   # true conflict on a

    # batch 2: lock still held by a across batches -> same attribution
    t, rep = fasst.step_attr(
        t, make_batch(np.array([Op.LOCK, Op.LOCK], np.int32),
                      np.array([b, a], np.uint64), val_words=1))
    rt = np.asarray(rep.rtype)
    assert rt[0] == Reply.REJECT and rt[1] == Reply.REJECT_SAME_KEY

    # commit by a frees the slot; b can now take it
    t, rep = fasst.step_attr(
        t, make_batch(np.array([Op.COMMIT_VER, Op.LOCK], np.int32),
                      np.array([a, b], np.uint64), val_words=1))
    rt = np.asarray(rep.rtype)
    assert rt[0] == Reply.ACK and rt[1] == Reply.GRANT


def test_attr_matches_plain_occ_outcomes(rng):
    """Attribution changes only reject LABELS: grant/reject outcomes equal
    the plain OCC engine's on identical batches."""
    t_plain = locks.create_occ(1 << 8)
    t_attr = locks.create_occ_attr(1 << 8)
    for _ in range(5):
        n = 64
        ops = rng.choice([Op.LOCK, Op.READ_VER, Op.COMMIT_VER, Op.ABORT],
                         size=n).astype(np.int32)
        keys = rng.integers(1, 500, size=n).astype(np.uint64)
        b = make_batch(ops, keys, val_words=1)
        t_plain, rp = fasst.step(t_plain, b)
        t_attr, ra = fasst.step_attr(t_attr, b)
        rp_t = np.asarray(rp.rtype)
        ra_t = np.asarray(ra.rtype)
        ra_t = np.where(ra_t == Reply.REJECT_SAME_KEY, Reply.REJECT, ra_t)
        np.testing.assert_array_equal(rp_t, ra_t)
        np.testing.assert_array_equal(np.asarray(rp.ver), np.asarray(ra.ver))


def test_client_lock_counters(rng):
    trace = wl.lock_trace(rng, n_txns=200, key_range=300)
    c = micro.FasstClient(trace, n_slots=1 << 8, cohort=64, width=1024,
                          attribute=True)
    for _ in range(4):
        c.run_round()
    ex = c.rec.extra
    assert ex["lock_cnt"] > 0
    assert ex["reject_sharing_cnt"] + ex["reject_same_key_cnt"] <= ex["lock_cnt"]
    # contention on 300 keys across 64 txns x ~2 write locks: both kinds occur
    assert ex["reject_sharing_cnt"] + ex["reject_same_key_cnt"] > 0


def test_tatp_integrated_attribution(rng):
    """Attribution on the TATP engine itself (VERDICT r2 #19): attr shards
    + client counters at the reference mix. Tiny keyspace + tiny CF lock
    table force both true conflicts and hash-sharing rejects."""
    from dint_tpu.clients import tatp_client as tc
    from dint_tpu.engines import tatp

    n_sub = 24
    shards, _ = tc.populate_shards(rng, n_sub, val_words=4,
                                   cf_lock_slots=16, attr_locks=True,
                                   log_capacity=1 << 14)
    assert isinstance(shards[0].cf_lock, locks.OCCAttrTable)
    coord = tc.Coordinator(shards, n_sub, width=2048, val_words=4)
    for _ in range(6):
        coord.run_cohort(rng, 256)
    st = coord.stats

    # outcome accounting still closes with the attr server
    accounted = (st.committed + st.aborted_lock + st.aborted_validate
                 + st.aborted_missing)
    assert accounted == st.attempted
    assert st.lock_cnt > 0
    # contention on 24 subscribers: true same-key conflicts must appear
    assert st.reject_same_key_cnt > 0
    # 16 CF lock slots for ~100+ CF keys: hash-sharing rejects must appear
    assert st.reject_sharing_cnt > 0
    # every reject is attributed exactly once
    assert st.reject_same_key_cnt + st.reject_sharing_cnt <= st.lock_cnt


def test_tatp_attr_off_by_default(rng):
    from dint_tpu.clients import tatp_client as tc

    shards, _ = tc.populate_shards(rng, 8, val_words=4,
                                   log_capacity=1 << 14)
    assert not isinstance(shards[0].cf_lock, locks.OCCAttrTable)


def test_tatp_attr_counters_stay_zero_without_attr_shards(rng):
    """Default shards can't attribute: counters must stay zero, not count
    every CF reject as 'sharing'."""
    from dint_tpu.clients import tatp_client as tc

    shards, _ = tc.populate_shards(rng, 24, val_words=4,
                                   log_capacity=1 << 14)
    coord = tc.Coordinator(shards, 24, width=2048, val_words=4)
    for _ in range(3):
        coord.run_cohort(rng, 256)
    st = coord.stats
    assert st.aborted_lock > 0          # contention definitely happened
    assert st.lock_cnt == 0
    assert st.reject_sharing_cnt == 0
    assert st.reject_same_key_cnt == 0
