"""Lock-attribution OCC variant (tatp/ebpf/lock_kern.c semantics)."""
import numpy as np

from dint_tpu.clients import micro, workloads as wl
from dint_tpu.engines import fasst
from dint_tpu.engines.types import Op, Reply, make_batch
from dint_tpu.ops import hashing
from dint_tpu.tables import locks

NL = 16


def _colliding_pair():
    """Two distinct keys sharing a lock slot, plus a lone key."""
    base = np.arange(1, 4000, dtype=np.uint64)
    slots = hashing.bucket_np(base, NL)
    for i in range(len(base)):
        for j in range(i + 1, min(i + 200, len(base))):
            if slots[i] == slots[j]:
                return int(base[i]), int(base[j])
    raise AssertionError("no collision found")


def test_reject_attribution():
    a, b = _colliding_pair()
    t = locks.create_occ_attr(NL)

    # batch 1: a takes the lock; b (sharing the slot) and a-again rejected
    ops = np.array([Op.LOCK, Op.LOCK, Op.LOCK], np.int32)
    keys = np.array([a, b, a], np.uint64)
    t, rep = fasst.step_attr(t, make_batch(ops, keys, val_words=1))
    rt = np.asarray(rep.rtype)
    assert rt[0] == Reply.GRANT
    assert rt[1] == Reply.REJECT            # hash sharing: holder key != b
    assert rt[2] == Reply.REJECT_SAME_KEY   # true conflict on a

    # batch 2: lock still held by a across batches -> same attribution
    t, rep = fasst.step_attr(
        t, make_batch(np.array([Op.LOCK, Op.LOCK], np.int32),
                      np.array([b, a], np.uint64), val_words=1))
    rt = np.asarray(rep.rtype)
    assert rt[0] == Reply.REJECT and rt[1] == Reply.REJECT_SAME_KEY

    # commit by a frees the slot; b can now take it
    t, rep = fasst.step_attr(
        t, make_batch(np.array([Op.COMMIT_VER, Op.LOCK], np.int32),
                      np.array([a, b], np.uint64), val_words=1))
    rt = np.asarray(rep.rtype)
    assert rt[0] == Reply.ACK and rt[1] == Reply.GRANT


def test_attr_matches_plain_occ_outcomes(rng):
    """Attribution changes only reject LABELS: grant/reject outcomes equal
    the plain OCC engine's on identical batches."""
    t_plain = locks.create_occ(1 << 8)
    t_attr = locks.create_occ_attr(1 << 8)
    for _ in range(5):
        n = 64
        ops = rng.choice([Op.LOCK, Op.READ_VER, Op.COMMIT_VER, Op.ABORT],
                         size=n).astype(np.int32)
        keys = rng.integers(1, 500, size=n).astype(np.uint64)
        b = make_batch(ops, keys, val_words=1)
        t_plain, rp = fasst.step(t_plain, b)
        t_attr, ra = fasst.step_attr(t_attr, b)
        rp_t = np.asarray(rp.rtype)
        ra_t = np.asarray(ra.rtype)
        ra_t = np.where(ra_t == Reply.REJECT_SAME_KEY, Reply.REJECT, ra_t)
        np.testing.assert_array_equal(rp_t, ra_t)
        np.testing.assert_array_equal(np.asarray(rp.ver), np.asarray(ra.ver))


def test_client_lock_counters(rng):
    trace = wl.lock_trace(rng, n_txns=200, key_range=300)
    c = micro.FasstClient(trace, n_slots=1 << 8, cohort=64, width=1024,
                          attribute=True)
    for _ in range(4):
        c.run_round()
    ex = c.rec.extra
    assert ex["lock_cnt"] > 0
    assert ex["reject_sharing_cnt"] + ex["reject_same_key_cnt"] <= ex["lock_cnt"]
    # contention on 300 keys across 64 txns x ~2 write locks: both kinds occur
    assert ex["reject_sharing_cnt"] + ex["reject_same_key_cnt"] > 0
