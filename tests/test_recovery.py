"""Crash recovery: rebuild table state from base snapshot + one log ring.

The subsystem the reference's write-ahead logs exist for but never
implement (SURVEY.md §5.3/5.4)."""
import jax
import numpy as np
import pytest

from dint_tpu import recovery
from dint_tpu.tables import log as logring
from dint_tpu.engines import smallbank_dense as sd, tatp_dense as td

VW = 4


def _run_tatp(n_sub, w, blocks, seed=0):
    db0 = td.populate(np.random.default_rng(seed), n_sub, val_words=VW)
    snapshot = jax.tree.map(np.array, db0)
    run, init, drain = td.build_pipelined_runner(n_sub, w=w, val_words=VW,
                                                 cohorts_per_block=2)
    carry = init(db0)
    key = jax.random.PRNGKey(seed)
    for i in range(blocks):
        carry, _ = run(carry, jax.random.fold_in(key, i))
    db, _ = drain(carry)
    return snapshot, db


def test_tatp_recovers_from_any_single_log_replica():
    n_sub = 64
    snapshot, db = _run_tatp(n_sub, w=128, blocks=4)
    heads = np.asarray(db.log.head)          # [L] (replicas identical)
    for replica in range(3):
        rec = recovery.recover_tatp_dense(
            jax.tree.map(jax.numpy.asarray, snapshot),
            np.asarray(logring.replica_entries(db.log, replica)), heads)
        assert np.array_equal(np.asarray(rec.val), np.asarray(db.val)), replica
        assert np.array_equal(np.asarray(rec.ver), np.asarray(db.ver))
        assert np.array_equal(np.asarray(rec.exists), np.asarray(db.exists))
        assert not np.asarray(rec.locked).any()
    # sanity: the run actually mutated state (recovery wasn't vacuous)
    assert not np.array_equal(snapshot.ver, np.asarray(db.ver))


def test_smallbank_recovers_and_conserves_balance():
    n_acc = 256
    db0 = sd.create(n_acc)
    snapshot = jax.tree.map(np.array, db0)
    run, init, drain = sd.build_pipelined_runner(n_acc, w=128,
                                                 cohorts_per_block=2)
    carry = init(db0)
    key = jax.random.PRNGKey(1)
    for i in range(4):
        carry, _ = run(carry, jax.random.fold_in(key, i))
    db, _ = drain(carry)

    rec = recovery.recover_smallbank_dense(
        jax.tree.map(jax.numpy.asarray, snapshot),
        np.asarray(logring.replica_entries(db.log, 1)),
        np.asarray(db.log.head))
    assert np.array_equal(np.asarray(rec.bal), np.asarray(db.bal))
    assert int(np.asarray(sd.total_balance(rec))) == \
        int(np.asarray(sd.total_balance(db)))
    # lock stamps reset and the step counter resumes past every logged step
    assert int(np.asarray(rec.step)) >= int(np.asarray(db.step)) - 1
    assert not np.asarray(rec.x_step).any()


def test_wrapped_ring_refuses_recovery():
    n_acc = 512
    db0 = sd.create(n_acc, log_capacity=16)   # tiny ring: wraps fast
    # uniform sampling: commits (and so log appends) dominate
    run, init, drain = sd.build_pipelined_runner(n_acc, w=128,
                                                 cohorts_per_block=2,
                                                 hot_frac=1.0)
    carry = init(db0)
    key = jax.random.PRNGKey(2)
    for i in range(6):
        carry, _ = run(carry, jax.random.fold_in(key, i))
    db, _ = drain(carry)
    assert (np.asarray(db.log.head) > 16).any()
    with pytest.raises(ValueError, match="wrapped"):
        recovery.recover_smallbank_dense(
            sd.create(n_acc), np.asarray(logring.replica_entries(db.log, 0)),
            np.asarray(db.log.head))


def test_geometry_mismatch_refuses_recovery():
    # log from n_sub=64 against a smaller db0: must raise, not corrupt
    _, db = _run_tatp(64, w=128, blocks=2)
    small = td.populate(np.random.default_rng(0), 4, val_words=VW)
    with pytest.raises(ValueError, match="geometry"):
        recovery.recover_tatp_dense(
            small, np.asarray(logring.replica_entries(db.log, 0)),
            np.asarray(db.log.head))
