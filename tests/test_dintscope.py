"""dintscope: wave registry, attribution, the regression gate, exports.

Tier-1 drives the whole timing plane on a CHECKED-IN synthetic profiler
trace (tests/fixtures/dintscope_trace.json — regenerate with
`python tools/dintscope.py synth` after appending to the registry), so
schema stability, every-registered-wave coverage, and the diff gate's
nonzero exit on an injected regression are CI facts, not TPU-day facts.
The named-scope annotations themselves are pinned semantics-neutral:
engine outputs bit-identical with scopes present vs DINT_SCOPE=0.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dint_tpu.monitor import attrib, waves

pytestmark = pytest.mark.scope

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "dintscope_trace.json")
GEOM = {"w": 8192, "k": 4, "l": 3, "vw": 10, "d": 8,
        "lg": 13, "sl": 8, "dc": 64}
CLI = [sys.executable, os.path.join(REPO, "tools", "dintscope.py")]


def _cli(args, **kw):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(CLI + args, capture_output=True, text=True,
                          timeout=120, env=env, cwd=REPO, **kw)


# ---------------------------------------------------------------- registry


def test_registry_schema():
    # unique full names, non-empty docs, engines cover all six hot paths
    assert len(set(waves.ALL_WAVES)) == waves.N_WAVES
    assert all(waves.WAVE_DOCS[n] for n in waves.ALL_WAVES)
    for eng in ("tatp_dense", "smallbank_dense", "tatp_pipeline",
                "smallbank_pipeline", "dense_sharded", "dense_sharded_sb"):
        assert waves.WAVES_BY_ENGINE[eng], eng
    # every declared bytes formula evaluates to a positive int at full
    # geometry, and returns None (not garbage) when variables are missing
    for name in waves.ALL_WAVES:
        if waves.WAVE_BYTES[name] is None:
            assert waves.wave_bytes(name, **GEOM) is None
        else:
            b = waves.wave_bytes(name, **GEOM)
            assert isinstance(b, int) and b > 0, name
            assert waves.wave_bytes(name) is None, name   # no vars -> None


def test_scope_rejects_unregistered_wave():
    with pytest.raises(KeyError):
        waves.scope("tatp_dense", "no_such_wave")


def test_scope_annotation_is_semantics_neutral(monkeypatch):
    """Acceptance: engine outputs bit-identical with scopes present
    (default) vs disabled (DINT_SCOPE=0) — named_scope adds no jaxpr
    equations, and this pins the off-switch that makes that claim A/B
    testable."""
    import jax

    from dint_tpu.engines import smallbank_dense as sd

    def run_once():
        run, init, drain = sd.build_pipelined_runner(
            512, w=64, cohorts_per_block=2, use_pallas=False)
        carry = init(sd.create(512))
        carry, stats = run(carry, jax.random.PRNGKey(3))
        db, tail = drain(carry)
        return (np.asarray(stats), np.asarray(tail),
                np.asarray(db.bal), np.asarray(db.x_step))

    assert waves.scopes_enabled()
    a = run_once()
    monkeypatch.setenv("DINT_SCOPE", "0")
    assert not waves.scopes_enabled()
    b = run_once()
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


# ------------------------------------------------------------- attribution


def test_fixture_covers_every_registered_wave():
    """Acceptance: report on the trace fixture attributes time to EVERY
    registered wave of (at least) the two dense engines and one sharded
    path — the fixture actually covers all engines, so registry growth
    without regenerating it fails here with a actionable message."""
    bd = attrib.report(FIXTURE, geometry=GEOM)
    assert bd["schema"] == attrib.BREAKDOWN_SCHEMA
    assert bd["kind"] == "dintscope_breakdown"
    assert bd["missing"] == [], (
        "fixture does not cover the registry — regenerate it: "
        "python tools/dintscope.py synth")
    for eng in ("tatp_dense", "smallbank_dense", "dense_sharded_sb"):
        for name in waves.WAVES_BY_ENGINE[eng]:
            rec = bd["waves"][name]
            assert rec["ms"] > 0 and rec["slices"] > 0, name
            assert rec["ms_per_step"] > 0, name
    # schema-stable per-wave record
    for rec in bd["waves"].values():
        assert set(rec) == {"ms", "slices", "ms_per_step", "pct",
                            "bytes_per_step", "gbps"}
    # bandwidth appears exactly for formula-carrying waves
    assert bd["waves"]["dint.tatp_dense.install"]["gbps"] is not None
    assert bd["waves"]["dint.tatp_dense.gen"]["gbps"] is None
    # steps inferred from slice counts (no JSONL given): 4 per the fixture
    assert bd["steps"] == 4
    assert bd["unattributed_ms"] > 0          # the filler slices
    assert bd["attributed_ms"] == pytest.approx(
        sum(r["ms"] for r in bd["waves"].values()))


def test_fixture_matches_fresh_synth(tmp_path):
    """Drift guard: the checked-in fixture IS synthesize_trace's output
    (the synthesizer is deterministic — durations derive from registry
    position, no clocks). A registry change that alters synth output
    without regenerating the fixture fails here, not three tests later
    with a confusing coverage message."""
    fresh = str(tmp_path / "synth.json")
    attrib.synthesize_trace(fresh, steps=4)
    with open(FIXTURE) as fa, open(fresh) as fb:
        a, b = json.load(fa), json.load(fb)
    assert a == b, (
        "tests/fixtures/dintscope_trace.json drifted from the "
        "synthesizer — regenerate it: python tools/dintscope.py synth")


def test_attribution_uses_jsonl_steps_and_rates(tmp_path):
    from dint_tpu.monitor import trace as tr

    from dint_tpu.monitor import counters as ctr

    jsonl = str(tmp_path / "run.jsonl")
    with tr.TraceWriter(jsonl, meta={"name": "t"}) as wr:
        for i in range(3):
            c = dict(ctr.zeros_dict(), steps=2, txn_attempted=100,
                     txn_committed=90)
            wr.wave(step=i, t=0.1 * i, dur_s=0.1, batch=100, counters=c)
    bd = attrib.report(FIXTURE, jsonl=jsonl, geometry=GEOM)
    assert bd["steps"] == 6                    # 3 waves x 2 steps each
    assert bd["rates"]["txn_committed_per_s"] == pytest.approx(
        270 / 0.3, rel=1e-6)
    assert 0 < bd["rates"]["abort_rate"] < 1


def test_diff_detects_injected_wave_regression(tmp_path):
    pert = str(tmp_path / "pert.json")
    attrib.synthesize_trace(pert, steps=4,
                            scale={"dint.smallbank_dense.read": 1.8})
    a = attrib.report(FIXTURE, geometry=GEOM)
    b = attrib.report(pert, geometry=GEOM)
    d = attrib.diff_breakdowns(a, b)
    assert not d["ok"]
    kinds = {(r["kind"], r.get("wave")) for r in d["regressions"]}
    assert ("wave", "dint.smallbank_dense.read") in kinds
    # identical breakdowns pass the gate
    assert attrib.diff_breakdowns(a, a)["ok"]
    # thresholds are honored: an 80% bump passes a 100% gate
    assert attrib.diff_breakdowns(a, b, wave_pct=100.0, step_pct=50.0)["ok"]


def test_diff_names_overlap_route_prefetch_regression(tmp_path):
    """Round 18: a regression in the double-buffered mesh exchange — the
    wave that must stay HIDDEN under cohort i's owner waves — is named
    by diff. tools/hw_mesh_serve.sh's overlap A/B stage gates on exactly
    this: overlap that stops overlapping fails loudly, by name."""
    pert = str(tmp_path / "pert.json")
    attrib.synthesize_trace(
        pert, steps=4, scale={"dint.multihost_sb.route_prefetch": 2.0})
    a = attrib.report(FIXTURE, geometry=GEOM)
    b = attrib.report(pert, geometry=GEOM)
    d = attrib.diff_breakdowns(a, b)
    assert not d["ok"]
    assert any(r.get("wave") == "dint.multihost_sb.route_prefetch"
               for r in d["regressions"])


def test_diff_ignores_sub_noise_waves():
    a = attrib.report(FIXTURE, geometry=GEOM)
    b = json.loads(json.dumps(a))
    name = "dint.tatp_dense.gen"
    # a 10x regression on a wave below min_ms is dispatch noise
    b["waves"][name]["ms_per_step"] = 0.004
    a2 = json.loads(json.dumps(a))
    a2["waves"][name]["ms_per_step"] = 0.0004
    d = attrib.diff_breakdowns(a2, b, min_ms=0.05)
    assert all(r.get("wave") != name for r in d["regressions"])


# ---------------------------------------------------------------- the CLI


def test_report_cli_json_and_artifact(tmp_path):
    out = str(tmp_path / "bd.json")
    c = _cli(["report", FIXTURE, "--geom", "w=8192", "k=4", "l=3",
              "vw=10", "d=8", "--json", "-o", out])
    assert c.returncode == 0, c.stderr
    bd = json.loads(c.stdout.strip().splitlines()[-1])
    assert bd["kind"] == "dintscope_breakdown"
    assert bd["missing"] == []
    with open(out) as f:
        assert json.load(f) == bd


def test_diff_cli_exits_nonzero_naming_regressed_wave(tmp_path):
    """Acceptance: diff against a perturbed fixture fails with a nonzero
    exit naming the regressed wave."""
    pert = str(tmp_path / "pert.json")
    attrib.synthesize_trace(pert, steps=4,
                            scale={"dint.tatp_dense.meta_gather": 2.5})
    c = _cli(["diff", FIXTURE, pert, "--json"])
    assert c.returncode == 1, (c.stdout, c.stderr)
    d = json.loads(c.stdout.strip().splitlines()[-1])
    assert any(r.get("wave") == "dint.tatp_dense.meta_gather"
               for r in d["regressions"])
    # human mode also names it, and self-diff exits 0
    c2 = _cli(["diff", FIXTURE, pert])
    assert c2.returncode == 1
    assert "dint.tatp_dense.meta_gather" in c2.stdout
    assert _cli(["diff", FIXTURE, FIXTURE]).returncode == 0


def test_describe_cli_matches_registry():
    c = _cli(["describe", "--json"])
    assert c.returncode == 0, c.stderr
    d = json.loads(c.stdout.strip().splitlines()[-1])
    assert [wv["name"] for wv in d["waves"]] == list(waves.ALL_WAVES)
    assert sorted(d["engines"]) == sorted(waves.ENGINES)


# -------------------------------------------------- merged timeline export


def test_export_trace_merge_aligns_clocks(tmp_path):
    from dint_tpu.monitor import counters as ctr
    from dint_tpu.monitor import trace as tr

    jsonl = str(tmp_path / "run.jsonl")
    with tr.TraceWriter(jsonl, meta={"name": "merge_test"}) as wr:
        for i in range(2):
            wr.wave(step=i, t=1.0 + 0.5 * i, dur_s=0.5, batch=64,
                    counters=dict(ctr.zeros_dict(), steps=1))
    out = str(tmp_path / "merged.json")
    n = tr.export_chrome_trace(jsonl, out, merge_trace=FIXTURE)
    assert n > 0
    with open(out) as f:
        events = json.load(f)["traceEvents"]
    wave_ev = [e for e in events if str(e.get("name", "")).startswith("wave ")]
    dev_ev = [e for e in events if e.get("ph") == "X"
              and attrib._wave_of(e) is not None]
    assert wave_ev and dev_ev
    # shared clock offset: the first wave lands at the device trace start
    dev_t0 = min(float(e["ts"]) for e in dev_ev)
    assert min(float(e["ts"]) for e in wave_ev) == pytest.approx(dev_t0,
                                                                 abs=1.0)
    # wave slices keep their own pid row (never interleaved with ops)
    assert {e["pid"] for e in wave_ev} == {1000}


def test_export_trace_merge_cli(tmp_path):
    from dint_tpu.monitor import counters as ctr
    from dint_tpu.monitor import trace as tr

    jsonl = str(tmp_path / "run.jsonl")
    with tr.TraceWriter(jsonl) as wr:
        wr.wave(step=0, t=0.0, dur_s=0.1, batch=1,
                counters=dict(ctr.zeros_dict(), steps=1))
    out = str(tmp_path / "merged.json")
    c = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintmon.py"),
         "export-trace", jsonl, "-o", out, "--merge", FIXTURE, "--json"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO)
    assert c.returncode == 0, c.stderr
    rec = json.loads(c.stdout.strip().splitlines()[-1])
    assert rec["merged"] == FIXTURE and rec["events"] > 0


# ------------------------------------------------- artifact schema hygiene


def test_exp_artifacts_carry_schema_breakdown_and_histogram(tmp_path):
    """Acceptance: sweep artifacts carry "schema" + "breakdown" (explicit
    null when attribution is off) and the latency histogram block next to
    the percentile block — including the open-loop queue/service split."""
    import exp

    out = str(tmp_path / "res")
    results = exp.run_all(out, window_s=0.3, quick=True,
                          only="tatp_closed")
    blocks = [b for b in results.values() if "error" not in b]
    assert blocks
    for b in blocks:
        assert b["schema"] == attrib.ARTIFACT_SCHEMA
        assert "breakdown" in b and b["breakdown"] is None   # no trace dir
        h = b["lat_hist"]
        assert h["n"] > 0 and h["buckets"]
        # Two views of one sample stream must agree where agreement is
        # guaranteed: the mean exactly (both track a sum), and the
        # reservoir's interpolated p50 inside the bucket span bracketing
        # the middle order statistics. A rel=0.10 p50 compare only holds
        # at scale on unimodal samples (test_stats.py) — a short measured
        # window's median can straddle a bimodal steady-state/contended
        # gap, where interpolation and the ceil-rank read legitimately
        # diverge.
        from dint_tpu import stats as dstats
        hist = dstats.LatencyHistogram.from_dict(h)
        assert h["avg_us"] == pytest.approx(b["avg_us"], rel=1e-4,
                                            abs=0.02)
        assert h["p50_us"] == round(hist.quantile(0.5), 2)
        cum = np.cumsum(hist.counts)
        n = hist.n
        lo_rank, hi_rank = (n + 1) // 2, n // 2 + 1
        i_lo = int(np.searchsorted(cum, lo_rank))
        i_hi = int(np.searchsorted(cum, hi_rank))
        lo_edge = 2.0 ** (h["lo_exp"] + i_lo / h["per_octave"])
        hi_edge = 2.0 ** (h["lo_exp"] + (i_hi + 1) / h["per_octave"])
        assert lo_edge * 0.999 <= b["p50_us"] <= hi_edge * 1.001
