"""Cache-mode store (device cache + host KVS) vs the sequential oracle.

The two-tier server (engines/store_cache + shim/host_kvs) must be reply-
equivalent to the flat sequential oracle for every policy — the cache, the
miss/refill protocol, evictions, and dirty write-backs are pure
implementation detail (exactly the reference's claim for its kernel cache,
SURVEY.md §4.2 cross-backend equivalence)."""
import numpy as np
import pytest

from dint_tpu.engines import store_cache
from dint_tpu.engines.types import Op, Reply
from dint_tpu.shim.host_kvs import CachedStore
from dint_tpu.testing.oracle import StoreOracle

VW = 4


def _run_diff(policy, rng, rounds=12, n=96, keyspace=60, cache_buckets=8):
    """Tiny cache (8 buckets x 4 slots = 32 slots) over a 60-key space:
    plenty of misses, evictions, and bucket pressure."""
    srv = CachedStore(cache_buckets, val_words=VW, policy=policy, width=128)
    oracle = StoreOracle()

    keys0 = np.arange(1, keyspace // 2, dtype=np.uint64)
    vals0 = rng.integers(1, 99, size=(len(keys0), VW)).astype(np.uint32)
    srv.populate(keys0, vals0)
    oracle.step(np.full(len(keys0), Op.INSERT, np.int32), keys0, vals0)

    for _ in range(rounds):
        ops = rng.choice([Op.GET, Op.GET, Op.GET, Op.SET, Op.SET, Op.INSERT,
                          Op.DELETE], size=n).astype(np.int32)
        keys = rng.integers(1, keyspace, size=n).astype(np.uint64)
        vals = rng.integers(1, 99, size=(n, VW)).astype(np.uint32)
        rt, rv, rr = srv.serve(ops, keys, vals)
        ort, orv, orr = oracle.step(ops, keys, vals)
        # oracle INSERT replies ACK with ver, ours too; compare everything
        np.testing.assert_array_equal(rt, ort, err_msg=f"rtype {policy}")
        np.testing.assert_array_equal(rr, orr, err_msg=f"ver {policy}")
        isval = ort == Reply.VAL
        np.testing.assert_array_equal(rv[isval], orv[isval],
                                      err_msg=f"val {policy}")
    return srv


@pytest.mark.parametrize("policy", store_cache.POLICIES)
def test_policy_matches_oracle(policy, rng):
    srv = _run_diff(policy, rng)
    st = srv.stats
    assert st.misses > 0, "workload never exercised the miss path"
    assert st.hits > 0, "workload never hit the cache"


def test_writeback_evictions_flush_dirty(rng):
    """Write-back under heavy pressure must produce evictions whose dirty
    records land in the backing store (ext_message ver1==1 protocol)."""
    srv = _run_diff(store_cache.WB_BLOOM, rng, rounds=20, keyspace=120,
                    cache_buckets=4)
    assert srv.stats.writebacks > 0


def test_bloom_negative_short_circuit(rng):
    """WB_BLOOM answers GETs for absent keys on-device (NOT_EXIST without a
    host trip); WB_NOBLOOM pays a miss for the same workload."""
    def count_miss(policy):
        srv = CachedStore(8, val_words=VW, policy=policy, width=64)
        srv.populate(np.array([1, 2], np.uint64),
                     np.ones((2, VW), np.uint32))
        ops = np.full(32, Op.GET, np.int32)
        keys = np.arange(100, 132, dtype=np.uint64)   # all absent
        rt, _, _ = srv.serve(ops, keys)
        assert (rt == Reply.NOT_EXIST).all()
        return srv.stats.misses

    assert count_miss(store_cache.WB_BLOOM) == 0
    assert count_miss(store_cache.WB_NOBLOOM) == 32


def test_write_through_set_invalidates(rng):
    """WT: SET defers to host and drops the cached copy; the next GET
    re-misses and refills (store_wt_kern.c:115-151 semantics)."""
    srv = CachedStore(8, val_words=VW, policy=store_cache.WT, width=64)
    srv.populate(np.array([5], np.uint64), np.full((1, VW), 7, np.uint32))
    # GET warms the cache
    rt, _, _ = srv.serve(np.array([Op.GET], np.int32), np.array([5], np.uint64))
    m0 = srv.stats.misses
    # second GET: refilled -> cache hit
    srv.serve(np.array([Op.GET], np.int32), np.array([5], np.uint64))
    assert srv.stats.misses == m0
    # SET invalidates + defers
    srv.serve(np.array([Op.SET], np.int32), np.array([5], np.uint64),
              np.full((1, VW), 9, np.uint32))
    assert srv.stats.misses == m0 + 1
    # GET after SET: the refill queued by the SET lands at the start of the
    # next round (the TC hook installing the fetched record), so this HITS
    # with the new value — no second miss
    rt, rv, rr = srv.serve(np.array([Op.GET], np.int32),
                           np.array([5], np.uint64))
    assert rt[0] == Reply.VAL and rv[0, 0] == 9 and rr[0] == 2
    assert srv.stats.misses == m0 + 1


@pytest.mark.parametrize("policy", store_cache.POLICIES)
def test_scan_mix_matches_oracle(policy, rng):
    """dintscan through the two-tier server: Op.SCAN lanes resolve
    host-side against the authoritative KVS (ranges aren't cacheable
    point keys), so every dirty cached record must be written back
    BEFORE the scan answers — reply-for-reply against the oracle over
    mixed GET/SET/INSERT/DELETE/SCAN batches, per policy."""
    scan_max = 6
    srv = CachedStore(8, val_words=VW, policy=policy, width=128)
    oracle = StoreOracle()
    keys0 = np.arange(1, 30, dtype=np.uint64)
    vals0 = rng.integers(1, 99, size=(len(keys0), VW)).astype(np.uint32)
    srv.populate(keys0, vals0)
    oracle.step(np.full(len(keys0), Op.INSERT, np.int32), keys0, vals0)

    n, keyspace = 96, 60
    saw_scan_after_dirty = False
    for _ in range(12):
        ops = rng.choice([Op.GET, Op.GET, Op.SET, Op.SET, Op.INSERT,
                          Op.DELETE, Op.SCAN, Op.SCAN],
                         size=n).astype(np.int32)
        keys = rng.integers(1, keyspace, size=n).astype(np.uint64)
        vals = rng.integers(1, 99, size=(n, VW)).astype(np.uint32)
        lens = np.where(ops == Op.SCAN,
                        rng.integers(0, scan_max + 1, size=n),
                        0).astype(np.uint32)
        saw_scan_after_dirty |= bool(
            np.asarray(srv.cache.dirty).any() and (ops == Op.SCAN).any())
        rt, rv, rr, scans = srv.serve(ops, keys, vals, scan_lens=lens,
                                      scan_max=scan_max)
        ort, orv, orr, oscans = oracle.step(ops, keys, vals,
                                            scan_lens=lens,
                                            scan_max=scan_max)
        np.testing.assert_array_equal(rt, ort, err_msg=f"rtype {policy}")
        np.testing.assert_array_equal(rr, orr, err_msg=f"ver {policy}")
        isval = (ort == Reply.VAL) & (ops != Op.SCAN)
        np.testing.assert_array_equal(rv[isval], orv[isval],
                                      err_msg=f"val {policy}")
        for i in np.nonzero(ops == Op.SCAN)[0]:
            assert scans[i] == oscans[i], (policy, i, keys[i])
    if policy != store_cache.WT:
        # WT never holds dirty records; the WB policies must have hit
        # the scan barrier (dirty cache + scan in one batch) for this
        # test to mean anything
        assert saw_scan_after_dirty
