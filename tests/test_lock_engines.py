import jax
import numpy as np

from dint_tpu.engines import fasst, lock2pl, logsrv
from dint_tpu.engines.types import Op, Reply, make_batch
from dint_tpu.ops import hashing
from dint_tpu.tables import locks, log as logring
from dint_tpu.testing.oracle import OCCOracle, SXLockOracle

NL = 1 << 6  # tiny slot space => heavy conflicts + hash collisions


def test_lock2pl_basic():
    table = locks.create_sx(NL)
    step = jax.jit(lock2pl.step)
    keys = np.array([1, 1, 1, 2], np.uint64)
    b = make_batch([Op.ACQ_S, Op.ACQ_S, Op.ACQ_X, Op.ACQ_X], keys, val_words=2)
    table, rep = step(table, b)
    rt = np.asarray(rep.rtype)
    # two S grants, X on same key rejected; X on free key granted
    assert list(rt) == [Reply.GRANT, Reply.GRANT, Reply.REJECT, Reply.GRANT]
    # X-first wins the slot
    b = make_batch([Op.ACQ_X, Op.ACQ_S], np.array([3, 3], np.uint64), val_words=2)
    table, rep = step(table, b)
    assert list(np.asarray(rep.rtype)) == [Reply.GRANT, Reply.REJECT]
    # release then acquire in one batch: release applies first
    b = make_batch([Op.ACQ_X, Op.REL_X], np.array([3, 3], np.uint64), val_words=2)
    table, rep = step(table, b)
    assert list(np.asarray(rep.rtype)) == [Reply.GRANT, Reply.ACK]


def test_lock2pl_differential(rng):
    table = locks.create_sx(NL)
    oracle = SXLockOracle(NL)
    step = jax.jit(lock2pl.step)
    held_s: list[int] = []  # slots we hold (to issue valid releases)
    held_x: list[int] = []
    for _ in range(20):
        n = 128
        ops = np.zeros(n, np.int32)
        keys = rng.integers(0, 500, size=n).astype(np.uint64)
        slots = hashing.bucket_np(keys, NL)
        for i in range(n):
            choice = rng.random()
            if choice < 0.35:
                ops[i] = Op.ACQ_S
            elif choice < 0.6:
                ops[i] = Op.ACQ_X
            elif choice < 0.75 and held_s:
                j = int(rng.integers(len(held_s)))
                ops[i] = Op.REL_S
                slots[i] = held_s.pop(j)
                keys[i] = 0  # slot fed directly below via trick key
            elif choice < 0.9 and held_x:
                j = int(rng.integers(len(held_x)))
                ops[i] = Op.REL_X
                slots[i] = held_x.pop(j)
            else:
                ops[i] = Op.NOP
        # regenerate keys so that key->slot matches the chosen slots: pick a
        # key hashing into each desired slot by brute force table
        keys = slot_to_key[slots]
        b = make_batch(ops, keys, val_words=2)
        table, rep = step(table, b)
        rt = np.asarray(rep.rtype)
        ot = oracle.step(ops, slots)
        assert np.array_equal(rt, ot), (rt[rt != ot], ot[rt != ot])
        for i in range(n):
            if rt[i] == Reply.GRANT:
                (held_s if ops[i] == Op.ACQ_S else held_x).append(int(slots[i]))
        assert np.array_equal(np.asarray(table.num_sh), oracle.num_sh)
        assert np.array_equal(np.asarray(table.num_ex), oracle.num_ex)


# brute-force inverse of the slot hash: one representative key per slot
slot_to_key = np.zeros(NL, np.uint64)
_k = np.arange(100000, dtype=np.uint64)
_s = hashing.bucket_np(_k, NL)
for _slot in range(NL):
    _hits = _k[_s == _slot]
    assert len(_hits) > 0
    slot_to_key[_slot] = _hits[0]


def test_fasst_differential(rng):
    table = locks.create_occ(NL)
    oracle = OCCOracle(NL)
    step = jax.jit(fasst.step)
    held: list[int] = []
    for _ in range(20):
        n = 128
        ops = np.zeros(n, np.int32)
        slots = rng.integers(0, NL, size=n)
        for i in range(n):
            c = rng.random()
            if c < 0.4:
                ops[i] = Op.READ_VER
            elif c < 0.7:
                ops[i] = Op.LOCK
            elif c < 0.85 and held:
                ops[i] = Op.COMMIT_VER
                slots[i] = held.pop(int(rng.integers(len(held))))
            elif held:
                ops[i] = Op.ABORT
                slots[i] = held.pop(int(rng.integers(len(held))))
            else:
                ops[i] = Op.NOP
        keys = slot_to_key[slots]
        b = make_batch(ops, keys, val_words=2)
        table, rep = step(table, b)
        rt = np.asarray(rep.rtype)
        rv = np.asarray(rep.ver)
        ot, over, olocked = oracle.step(ops, slots)
        assert np.array_equal(rt, ot)
        assert np.array_equal(rv, over)
        assert np.array_equal(np.asarray(rep.val)[:, 0], olocked)
        for i in range(n):
            if rt[i] == Reply.GRANT:
                held.append(int(slots[i]))
        assert np.array_equal(np.asarray(table.locked), oracle.locked)
        assert np.array_equal(np.asarray(table.ver), oracle.ver)


def test_log_append_and_wrap(rng):
    ring = logring.create(lanes=4, capacity=8, val_words=2)
    step = jax.jit(logsrv.step)
    total = 0
    for it in range(3):
        n = 16
        keys = rng.integers(0, 1000, size=n).astype(np.uint64)
        vals = rng.integers(0, 1 << 32, size=(n, 2), dtype=np.uint32)
        vers = rng.integers(0, 100, size=n).astype(np.uint32)
        b = make_batch([Op.LOG_APPEND] * n, keys, vals, vers=vers, val_words=2)
        ring, rep = step(ring, b)
        assert (np.asarray(rep.rtype) == Reply.ACK).all()
        total += n
    heads = np.asarray(ring.head)
    assert heads.sum() == total
    assert (heads == total // 4).all()  # round-robin lanes
    # last batch's entries present: check one
    entries = np.asarray(ring.entries)
    # lane of lane-index 0 request in last batch; head advanced 4 per batch
    assert entries[0, (heads[0] - 1) % 8, 3] == vers[12]  # ver word of lane0's last append
