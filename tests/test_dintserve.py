"""dintserve: the always-on serving plane (ISSUE 14 tentpole).

The acceptance pins, per ISSUE.md:
  * serving is a MASKING of batch certification, not a fork of it: at
    occupancy == width the serve path is bit-identical to the closed
    loop on the same fold_in key sequence, and a bursty schedule whose
    bursts straddle block boundaries still replays the closed-loop
    table state exactly;
  * zero steady-state allocation: after warmup the donated carry
    ping-pongs through the same buffers and jax.live_arrays() stays
    constant block over block;
  * the SLO controller moves BOTH directions deterministically on CPU:
    small width under a tight SLO at low rate (ms-scale queue p99),
    the knee width + shedding under saturation — and the whole serve
    loop under a VirtualClock is a pure function of (schedule, seed);
  * the lane ledger reconciles: occupancy + padded == width x serving
    steps, shed counted host-side AND mirrored device-side, and
    offered == admitted + shed (no arrival silently dropped).

Geometry matches tests/test_dintmon.py (tiny tables, W=64, CPB=2) so
every jit here compiles in seconds inside the tier-1 budget.
"""
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from dint_tpu.serve import (ArrivalStream, ControllerCfg, ServeEngine,
                            ServiceModel, VirtualClock, WidthController,
                            burst_schedule, cached_runner, choose_width,
                            constant_schedule, make_schedule, max_backlog,
                            poisson_schedule, recommend_hot_frac,
                            simulate_widths)

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KEY = jax.random.PRNGKey

N_SUB = 300
N_ACC = 400
W = 64
VW = 4
CPB = 2


# ------------------------------------------------------ arrival schedules


def test_constant_schedule_spacing():
    s = constant_schedule(1000.0, 0.01)
    assert len(s) == 10
    assert np.allclose(np.diff(s), 1e-3)
    assert s[0] > 0 and s[-1] <= 0.01 + 1e-12
    assert len(constant_schedule(1000.0, 0.0)) == 0


def test_poisson_schedule_deterministic_and_windowed():
    a = poisson_schedule(50_000.0, 0.01, seed=7)
    b = poisson_schedule(50_000.0, 0.01, seed=7)
    c = poisson_schedule(50_000.0, 0.01, seed=8)
    assert np.array_equal(a, b)
    assert not np.array_equal(a, c)
    assert (np.diff(a) >= 0).all() and (a < 0.01).all() and (a >= 0).all()
    # rate is approximately honoured (Poisson count, generous bound)
    assert 0.5 * 500 < len(a) < 1.5 * 500


def test_burst_schedule_shape():
    s = burst_schedule(100_000.0, 0.01, burst_lanes=128,
                       burst_every_s=0.002, seed=0)
    assert (np.diff(s) >= 0).all()
    # 5 bursts at (i + 0.5) * 2ms, each exactly burst_lanes strong
    for i in range(5):
        t = (i + 0.5) * 0.002
        assert int((s == t).sum()) == 128
    # baseline takes the residual rate; total is in the right ballpark
    assert 5 * 128 <= len(s) < 2 * 100_000 * 0.01


def test_make_schedule_factory():
    assert len(make_schedule("constant", 1000.0, 0.01)) == 10
    assert np.array_equal(make_schedule("poisson", 1000.0, 0.01, seed=3),
                          poisson_schedule(1000.0, 0.01, seed=3))
    s = make_schedule("burst", 10_000.0, 0.01, seed=1, burst_lanes=16,
                      burst_every_s=0.005)
    assert len(s) > 0
    with pytest.raises(ValueError):
        make_schedule("uniform", 1.0, 1.0)


def test_arrival_stream_cursor():
    st = ArrivalStream(np.array([0.1, 0.2, 0.2, 0.5]))
    assert len(st) == 4 and st.peek() == 0.1 and not st.exhausted
    got = st.take_until(0.2)
    assert got.tolist() == [0.1, 0.2, 0.2]
    assert len(st) == 1 and st.peek() == 0.5
    assert st.take_until(0.3).tolist() == []
    assert st.take_until(1.0).tolist() == [0.5]
    assert st.exhausted and st.peek() is None
    with pytest.raises(AssertionError):
        ArrivalStream(np.array([0.2, 0.1]))


# ----------------------------------------------------- controller policy


def _svc(cfg, m):
    return {w: m.service_us(w) for w in cfg.widths}


def test_choose_width_moves_both_directions():
    cfg, m = ControllerCfg(), ServiceModel()
    s = _svc(cfg, m)
    # low rate: smallest feasible width wins (lowest latency)
    assert choose_width(1_000.0, s, cfg) == (256, False)
    # mid rate infeasible for 256 climbs exactly one notch
    assert choose_width(2.0e6, s, cfg) == (1024, False)
    # nothing feasible: the knee (max-capacity) width + saturated flag
    assert choose_width(100e6, s, cfg) == (8192, True)


def test_choose_width_tight_slo_blocks_big_cohorts():
    m = ServiceModel()
    tight = ControllerCfg(slo_us=500.0)   # block time may eat 250us
    s = _svc(tight, m)
    # service(256)=160us fits, service(4096)=314us does not
    assert choose_width(1_000.0, s, tight) == (256, False)
    # rate beyond 256's capacity with the SLO blocking everything bigger
    w, sat = choose_width(5e6, s, tight)
    assert sat and w == 8192              # knee: shed rather than stall


def test_max_backlog_floor_and_growth():
    cfg, m = ControllerCfg(), ServiceModel()
    assert max_backlog(64, 1e9, cfg) == 64          # floor: one cohort
    small = max_backlog(256, m.service_us(256), cfg)
    big = max_backlog(8192, m.service_us(8192), cfg)
    assert big > small > 256


def test_recommend_hot_frac():
    assert recommend_hot_frac(0.1, 0, 0) == 0.1            # no evidence
    assert recommend_hot_frac(0.1, 50, 50) == 0.2          # miss -> double
    assert recommend_hot_frac(0.4, 0, 100) == 0.5          # clamped at hi
    assert recommend_hot_frac(0.25, 1000, 1) == 0.125      # saturated -> halve
    assert recommend_hot_frac(1 / 64, 1000, 0) == 1 / 64   # clamped at lo
    assert recommend_hot_frac(0.2, 95, 5) == 0.2           # in band: hold


def test_width_controller_hysteresis_and_both_directions():
    cfg, m = ControllerCfg(), ServiceModel()
    ctl = WidthController(cfg, m)
    assert ctl.width() == 256             # cold start: smallest width
    ctl.observe_service(256, m.service_us(256))
    ctl.observe_rate(50e6)
    assert ctl.width() == 256             # hysteresis holds the switch
    for _ in range(cfg.hysteresis_blocks - 1):
        ctl.observe_service(256, m.service_us(256))
    assert ctl.width() == 8192            # window elapsed: knee width
    assert ctl.saturated and ctl.switches[-1][1] == 8192
    # load vanishes: the controller comes back DOWN
    for _ in range(cfg.hysteresis_blocks):
        ctl.observe_service(8192, m.service_us(8192))
    for _ in range(40):
        ctl.observe_rate(0.0)             # EWMA decays toward zero
    assert ctl.width() == 256 and not ctl.saturated
    assert ctl.switches[-1][1] == 256 and len(ctl.switches) == 2


def test_simulate_widths_deterministic_and_moves():
    cfg, m = ControllerCfg(), ServiceModel()
    lo = simulate_widths(constant_schedule(1_000.0, 0.05), cfg, m)
    assert lo and set(lo) == {256}        # low rate never leaves small
    hi = simulate_widths(constant_schedule(20e6, 0.004), cfg, m)
    assert hi[-1] == 8192                 # saturation climbs to the knee
    assert hi[0] == 256                   # ... starting from the bottom
    again = simulate_widths(constant_schedule(20e6, 0.004), cfg, m)
    assert hi == again                    # pure function of the schedule


# --------------------------------------------------- serve-mode builders


def _td_build(serve, monitor=False):
    # cached_runner so every test (and the ServeEngine tests below)
    # shares one compile per distinct config within the process
    return cached_runner("tatp_dense", N_SUB, val_words=VW, w=W,
                         cohorts_per_block=CPB, monitor=monitor,
                         trace=False, serve=serve)


def _closed_loop_tatp(blocks, seed=0):
    from dint_tpu.engines import tatp_dense as td

    db = td.populate(np.random.default_rng(seed), N_SUB, val_words=VW)
    run, init, drain = _td_build(False)
    carry = init(db)
    tot = np.zeros(td.N_STATS, np.int64)
    for i in range(blocks):
        carry, s = run(carry, jax.random.fold_in(KEY(seed), i))
        tot += np.asarray(s, np.int64).sum(axis=0)
    out = drain(carry)
    tot += np.asarray(out[1], np.int64).sum(axis=0)
    return out[0], tot


def _assert_trees_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_serve_full_occupancy_bit_identical_to_closed_loop():
    """occ == width on the same fold_in keys replays the closed loop
    exactly: same table state, same stats. Serving = masking."""
    from dint_tpu.engines import tatp_dense as td

    blocks = 3
    db = td.populate(np.random.default_rng(0), N_SUB, val_words=VW)
    run, init, drain = _td_build(True)
    carry = init(db)
    occ = np.full(CPB, W, np.int32)
    shed = np.zeros(CPB, np.int32)
    tot = np.zeros(td.N_STATS, np.int64)
    for i in range(blocks):
        carry, s = run(carry, jax.random.fold_in(KEY(0), i), occ, shed)
        tot += np.asarray(s, np.int64).sum(axis=0)
    out = drain(carry)
    tot += np.asarray(out[1], np.int64).sum(axis=0)

    db_ref, tot_ref = _closed_loop_tatp(blocks)
    assert tot.tolist() == tot_ref.tolist()
    _assert_trees_equal(out[0], db_ref)


def test_serve_zero_alloc_steady_state():
    """The zero-allocation pin: after warmup, every serve block runs
    through donated buffers — the live-array census is constant block
    over block and the big table leaf ping-pongs through at most two
    device buffers (double buffer), never a fresh allocation."""
    from dint_tpu.engines import tatp_dense as td

    run, init, drain = _td_build(True)
    db = td.populate(np.random.default_rng(1), N_SUB, val_words=VW)
    carry = init(db)
    occ = np.full(CPB, W, np.int32)
    shed = np.zeros(CPB, np.int32)

    def big_ptr(c):
        leaf = max(jax.tree_util.tree_leaves(c), key=lambda x: x.nbytes)
        return leaf.unsafe_buffer_pointer()

    for i in range(3):                          # warmup: compile + settle
        carry, s = run(carry, jax.random.fold_in(KEY(1), i), occ, shed)
    np.asarray(s)                               # sync
    base = len(jax.live_arrays())

    counts, ptrs = [], set()
    for i in range(3, 9):
        carry, s = run(carry, jax.random.fold_in(KEY(1), i), occ, shed)
        np.asarray(s)
        counts.append(len(jax.live_arrays()))
        ptrs.add(big_ptr(carry))
    assert counts == [base] * 6, counts         # zero net allocations
    assert len(ptrs) <= 2, ptrs                 # donated ping-pong only
    drain(carry)


# ----------------------------------------------------------- ServeEngine


def test_serve_engine_bursty_straddle_bit_identical():
    """Bursts that straddle block boundaries (200 arrivals into 128-lane
    blocks) still fill every cohort exactly — the backlog carries the
    tail across the boundary — so the served table state is
    bit-identical to the closed loop on the same keys."""
    eng = ServeEngine("tatp_dense", N_SUB, cfg=ControllerCfg(widths=(W,)),
                      cohorts_per_block=CPB, val_words=VW,
                      clock=VirtualClock(), monitor=True, seed=0)
    # 3 blocks x 2 cohorts x 64 lanes = 384, delivered as misaligned
    # bursts; under the service model the backlog never empties, so
    # every cohort serves at full occupancy
    sched = np.sort(np.concatenate([np.zeros(200),
                                    np.full(100, 2e-4),
                                    np.full(84, 4e-4)]))
    rep = eng.run(sched)
    eng.close()
    rep = eng.snapshot()

    assert rep["blocks"] == 3
    assert rep["offered"] == rep["admitted"] == rep["attempted"] == 384
    assert rep["shed"] == 0
    c = rep["counters"]
    assert c["serve_occupancy_lanes"] == 384
    assert c["serve_padded_lanes"] == 0         # every cohort was full
    assert c["serve_shed_lanes"] == 0

    db_ref, tot_ref = _closed_loop_tatp(3)
    assert rep["committed"] == int(tot_ref[1])
    _assert_trees_equal(eng._db, db_ref)


def test_serve_engine_idle_gap_never_dispatches_empty():
    """Two bursts separated by a long idle gap: the loop parks until the
    next arrival instead of dispatching empty blocks — exactly 2 blocks,
    zero padding, and the gap shows up in elapsed time only."""
    eng = ServeEngine("tatp_dense", N_SUB, cfg=ControllerCfg(widths=(W,)),
                      cohorts_per_block=CPB, val_words=VW,
                      clock=VirtualClock(), monitor=True, seed=0)
    sched = np.sort(np.concatenate([np.zeros(CPB * W),
                                    np.full(CPB * W, 0.1)]))
    rep = eng.run(sched)
    eng.close()
    rep = eng.snapshot()
    assert rep["blocks"] == 2                   # no empty dispatches
    assert rep["counters"]["serve_padded_lanes"] == 0
    assert rep["admitted"] == rep["attempted"] == 2 * CPB * W
    assert rep["elapsed_s"] >= 0.1              # the gap was slept, not spun


def test_serve_engine_low_rate_tight_slo_stays_small():
    """Down-direction pin: at low rate the controller serves at the
    SMALLEST width — queue p99 stays ms-scale and the SLO verdict is
    MET — with partial-occupancy cohorts billed as padding."""
    eng = ServeEngine("smallbank_dense", N_ACC,
                      cfg=ControllerCfg(widths=(16, W)),
                      cohorts_per_block=CPB, clock=VirtualClock(),
                      monitor=True, seed=0)
    rep = eng.run(constant_schedule(10_000.0, 0.02))
    eng.close()
    rep = eng.snapshot()

    ctl = rep["controller"]
    assert ctl["width"] == 16 and not ctl["saturated"]
    assert ctl["switches"] == []                # never left the small width
    assert rep["shed"] == 0
    assert rep["offered"] == rep["admitted"] == 200
    assert rep["slo_met"] and 0 < rep["queue"]["p99"] <= rep["slo_us"]
    c = rep["counters"]
    assert c["serve_padded_lanes"] > 0          # open loop: partial cohorts
    served = sum(int(w) * n for w, n in rep["steps_by_width"].items())
    assert c["serve_occupancy_lanes"] + c["serve_padded_lanes"] == served
    assert c["serve_occupancy_lanes"] == rep["admitted"]
    assert c["serve_shed_lanes"] == 0


def _overload_run(seed=0):
    eng = ServeEngine("smallbank_dense", N_ACC,
                      cfg=ControllerCfg(widths=(16, W)),
                      cohorts_per_block=CPB, clock=VirtualClock(),
                      monitor=True, seed=seed)
    eng.run(constant_schedule(800_000.0, 0.01))
    eng.close()
    return eng.snapshot()


def test_serve_engine_saturation_sheds_then_recovers():
    """Up-direction pin: a saturating burst drives the controller to the
    knee width with admission shedding (host tally mirrored exactly into
    the device ledger); while the tail drains and the offered-rate EWMA
    decays, it switches back down — BOTH directions in one trajectory."""
    rep = _overload_run()
    ctl = rep["controller"]
    switch_widths = [w for _, w in ctl["switches"]]
    assert W in switch_widths                   # climbed to the knee
    assert switch_widths[-1] == 16              # ... and came back down
    assert rep["steps_by_width"][str(W)] > 0    # really SERVED at the knee
    assert rep["steps_by_width"]["16"] > 0
    assert ctl["width"] == 16 and not ctl["saturated"]  # recovered
    # no arrival unaccounted; shed mirrored host == device
    assert rep["offered"] == rep["admitted"] + rep["shed"]
    c = rep["counters"]
    assert c["serve_shed_lanes"] == rep["shed"] > 0
    assert c["serve_occupancy_lanes"] == rep["admitted"] == rep["attempted"]
    served = sum(int(w) * n for w, n in rep["steps_by_width"].items())
    assert c["serve_occupancy_lanes"] + c["serve_padded_lanes"] == served


def test_serve_engine_deterministic_under_virtual_clock():
    """The whole serving loop — ingestion, width switches, shedding,
    counters, histograms — is a pure function of (schedule, seed) under
    the VirtualClock: two runs produce the SAME snapshot, field for
    field."""
    assert _overload_run() == _overload_run()


@pytest.mark.slow
def test_serve_engine_soak_reentrant_identities():
    """Soak: three back-to-back schedules (ramp, overload, trickle) on
    one long-lived engine; the lane ledger must still close exactly."""
    eng = ServeEngine("smallbank_dense", N_ACC,
                      cfg=ControllerCfg(widths=(16, W)),
                      cohorts_per_block=CPB, clock=VirtualClock(),
                      monitor=True, seed=2)
    start = 0.0
    for r, (rate, win) in enumerate([(50_000.0, 0.05), (900_000.0, 0.02),
                                     (8_000.0, 0.05)]):
        rep = eng.run(poisson_schedule(rate, win, seed=r, start_s=start))
        start = rep["elapsed_s"]
    eng.close()
    rep = eng.snapshot()
    assert rep["offered"] == rep["admitted"] + rep["shed"]
    c = rep["counters"]
    assert c["serve_occupancy_lanes"] == rep["admitted"] == rep["attempted"]
    assert c["serve_shed_lanes"] == rep["shed"] > 0
    served = sum(int(w) * n for w, n in rep["steps_by_width"].items())
    assert c["serve_occupancy_lanes"] + c["serve_padded_lanes"] == served
    assert rep["committed"] <= rep["attempted"]
    assert len(rep["controller"]["switches"]) >= 2


def test_serve_engine_store_scan_counters_reconcile():
    """dintscan on the serve plane: the store engine family serves an
    open-loop GET/SET/SCAN mix, the ordered run rebuilds at drain
    boundaries, and the scan counter plane reconciles — every admitted
    lane lands in the ledger and scan rows stay within the static slab
    bound (scan_max x requests)."""
    eng = ServeEngine("store", N_ACC,
                      cfg=ControllerCfg(widths=(W,)),
                      cohorts_per_block=CPB, clock=VirtualClock(),
                      monitor=True, seed=3,
                      runner_kw=dict(use_scan=True, scan_frac=0.5,
                                     max_scan_len=6, scan_max=8,
                                     read_frac=0.5))
    rep = eng.run(poisson_schedule(80_000.0, 0.05, seed=5))
    eng.close()
    assert rep["offered"] == rep["admitted"] + rep["shed"]
    assert rep["admitted"] > 0
    c = rep["counters"]
    assert c["serve_occupancy_lanes"] == rep["admitted"]
    served = sum(int(w) * n for w, n in rep["steps_by_width"].items())
    assert c["serve_occupancy_lanes"] + c["serve_padded_lanes"] == served
    # the scan plane: ~half the admitted lanes issue Op.SCAN; replies
    # carry at most scan_max rows each; overlay hits only on scanned rows
    assert 0 < c["scan_requests"] < rep["admitted"]
    assert 0 < c["scan_rows"] <= 8 * c["scan_requests"]
    assert 0 <= c["scan_delta_hits"] <= c["scan_rows"]
    # stale-scan RETRYs are the only non-committed admitted lanes
    assert rep["committed"] <= rep["admitted"]


def test_serve_engine_store_scan_off_has_silent_counters():
    """use_scan=False: no run threaded, no scan counters bumped — the
    default-off decision rule leaves the serve plane bit-identical to
    the pre-dintscan store family."""
    eng = ServeEngine("store", N_ACC, cfg=ControllerCfg(widths=(W,)),
                      cohorts_per_block=CPB, clock=VirtualClock(),
                      monitor=True, seed=3,
                      runner_kw=dict(use_scan=False))
    rep = eng.run(poisson_schedule(50_000.0, 0.04, seed=6))
    eng.close()
    assert rep["admitted"] > 0 and rep["committed"] == rep["admitted"]
    c = rep["counters"]
    assert c["scan_requests"] == c["scan_rows"] == c["scan_delta_hits"] == 0


# ------------------------------------------------------------- shim pump


def test_pump_depth_and_occupancy_knobs():
    """Satellite (a): the host pump's ring depth and idle poll interval
    are constructor knobs, and latency_snapshot() carries the dintserve
    occupancy accounting (identity: occupancy + padded == width x
    batches) plus the C++-side shed count."""
    from dint_tpu.engines import store
    from dint_tpu.shim import STORE, EnginePump, ShimClient
    from dint_tpu.tables import kv

    table = kv.create(1 << 8, val_words=10)
    with pytest.raises(AssertionError):
        EnginePump(STORE, store.step, table, width=64, depth=0)
    with EnginePump(STORE, store.step, table, width=64, flush_us=2000,
                    depth=3, idle_poll_us=1000).start() as p:
        with ShimClient("127.0.0.1", p.port) as c:
            for _ in range(12):                 # absorb the first compile
                r = c.exchange(np.zeros(1, np.uint8),
                               np.array([1], np.uint64), timeout_ms=10_000)
                if r["n"] == 1:
                    break
            else:
                pytest.fail("pump did not answer warmup exchanges")
        # the reply goes out before the pump thread's tally lands; give
        # the bookkeeping a beat before snapshotting
        for _ in range(500):
            if p.batches_served >= 1:
                break
            time.sleep(0.01)
        snap = p.latency_snapshot()
    assert snap["width"] == 64 and snap["depth"] == 3
    assert snap["batches"] >= 1
    assert snap["occupancy_lanes"] >= 1
    assert snap["occupancy_lanes"] + snap["padded_lanes"] == \
        64 * snap["batches"]
    assert snap["shed"] == 0
    assert {"p50_us", "p99_us", "hist"} <= set(snap["queue"])
    assert {"p50_us", "p99_us", "hist"} <= set(snap["service"])


# -------------------------------------------------------------------- CLI


def _cli(*args, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dintserve.py"),
         *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def test_dintserve_cli_describe_and_simulate():
    c = _cli("describe")
    assert c.returncode == 0, c.stderr
    for want in ("serve_occupancy_lanes", "serve_padded_lanes",
                 "serve_shed_lanes", "tatp_dense/serve",
                 "controller defaults"):
        assert want in c.stdout
    a = _cli("simulate", "--rate", "20000000", "--window", "0.004",
             "--json")
    assert a.returncode == 0, a.stderr
    out = json.loads(a.stdout)
    assert out["final_width"] == 8192 and out["blocks"] > 0
    b = _cli("simulate", "--rate", "20000000", "--window", "0.004",
             "--json")
    assert a.stdout == b.stdout                 # deterministic


@pytest.mark.slow
def test_dintserve_cli_virtual_run():
    c = _cli("run", "--engine", "tatp_dense", "--size", str(N_SUB),
             "--rate", "30000", "--window", "0.02", "--widths", str(W),
             "--cpb", str(CPB), "--virtual", "--json")
    assert c.returncode == 0, c.stderr          # SLO gate: met -> exit 0
    rep = json.loads(c.stdout.strip().splitlines()[-1])
    assert rep["offered"] > 0
    assert rep["offered"] == rep["admitted"] + rep["shed"]
    assert rep["slo_met"] is True
    assert rep["counters"]["serve_occupancy_lanes"] == rep["admitted"]
    served = sum(int(w) * n for w, n in rep["steps_by_width"].items())
    assert rep["counters"]["serve_occupancy_lanes"] + \
        rep["counters"]["serve_padded_lanes"] == served


# ------------------------------------- controller edges (ISSUE 17 pins)


def test_choose_width_exactly_at_knee_capacity():
    """Boundary pin: the rate check is INCLUSIVE (cap >= offered x
    headroom) — at EXACTLY the knee's capacity the knee is still
    feasible (no shedding), one epsilon past it the controller
    saturates. headroom=1.0 makes the boundary float-exact because the
    test computes capacity with the controller's own arithmetic."""
    cfg, m = ControllerCfg(headroom=1.0), ServiceModel()
    s = _svc(cfg, m)
    knee = cfg.widths[-1]                    # max-capacity width
    cap = knee / (m.service_us(knee) * 1e-6)
    assert choose_width(cap, s, cfg) == (knee, False)
    assert choose_width(cap * (1 + 1e-9), s, cfg) == (knee, True)


def test_recommend_hot_frac_boundary_holds():
    """Edge pins: a hit rate EXACTLY at the grow target (0.90) or at
    the shrink threshold (0.995) HOLDS — both comparisons are strict —
    while an all-hot tally (rate 1.0) shrinks, and a recommendation
    already sitting on a clamp stays put."""
    assert recommend_hot_frac(0.1, 90, 10) == 0.1        # == target
    assert recommend_hot_frac(0.1, 995, 5) == 0.1        # == shrink
    assert recommend_hot_frac(0.2, 100, 0) == 0.1        # all-hot: halve
    assert recommend_hot_frac(0.5, 1, 99) == 0.5         # grow at hi
    assert recommend_hot_frac(1 / 64, 100, 0) == 1 / 64  # shrink at lo


# --------------------------------- plan-resolved serving (ISSUE 17)


def test_serve_engine_resolves_plan_by_default():
    """Tentpole consumer pin: with no plan argument the engine reads
    the pinned PLAN.json — the snapshot records provenance (source +
    cost-model hash, zero overrides) and the hot_frac rebuild loop
    seeds from the plan's serve prior with the counter plane on."""
    from dint_tpu.clients import workloads as wl
    eng = ServeEngine("smallbank_dense", N_ACC,
                      cfg=ControllerCfg(widths=(16, W)),
                      cohorts_per_block=CPB, clock=VirtualClock(),
                      monitor=True, seed=0)
    try:
        eng.run(constant_schedule(5_000.0, 0.004))
    finally:
        eng.close()
    rep = eng.snapshot()
    assert rep["plan"] is not None
    assert rep["plan"]["source"].endswith("PLAN.json")
    assert rep["plan"]["hash"] and rep["plan"]["overridden"] == []
    assert rep["hot_frac"] == {"current": wl.SB_HOT_FRAC,
                               "adaptive": True, "rebuilds": 0}


def test_serve_engine_cfg_and_model_from_plan_priors():
    """cfg=None pulls the width menu + SLO from the plan's serve
    priors, model=None the ServiceModel coefficients. A doctored plan
    dict proves the values actually flow (widths trimmed to the two
    already-compiled test widths so no fresh jit rides the assert)."""
    import copy

    from dint_tpu.analysis import plan as P
    doc = copy.deepcopy(P.load_plan())
    serve = doc["workloads"]["smallbank_serve"]["serve"]
    serve["widths"] = {"16": serve["widths"]["256"],
                       str(W): serve["widths"]["256"]}
    serve["slo_us"] = 4321.0
    serve["model"] = {"base_us": 149.0, "per_lane_ns": 41.0}
    eng = ServeEngine("smallbank_dense", N_ACC, cohorts_per_block=CPB,
                      clock=VirtualClock(), monitor=True, seed=0,
                      plan=doc)
    try:
        assert eng.cfg.widths == (16, W)
        assert eng.cfg.slo_us == 4321.0
        assert (eng.model.base_us, eng.model.per_lane_ns) == (149.0, 41.0)
    finally:
        eng.close()


def test_serve_engine_plan_none_records_null():
    """plan=None disables plan consumption: the snapshot records
    ``"plan": None`` explicitly — never a silent default — and with no
    prior and no caller pin the hot_frac loop stays off."""
    eng = ServeEngine("smallbank_dense", N_ACC,
                      cfg=ControllerCfg(widths=(16, W)),
                      cohorts_per_block=CPB, clock=VirtualClock(),
                      monitor=True, seed=0, plan=None)
    try:
        eng.run(constant_schedule(5_000.0, 0.004))
    finally:
        eng.close()
    rep = eng.snapshot()
    assert rep["plan"] is None
    assert rep["hot_frac"] == {"current": None, "adaptive": False,
                               "rebuilds": 0}


def test_plan_resolved_run_bit_identical_to_hand_config():
    """THE acceptance pin: a plan-resolved serve run is bit-identical
    to the same configuration passed entirely by hand (plan=None +
    hot_frac pinned to the plan's prior). Only the provenance stamp may
    differ — every counter, histogram bucket, width decision and
    committed lane must match field for field."""
    from dint_tpu.clients import workloads as wl
    sched = constant_schedule(30_000.0, 0.01)

    def snap(**kw):
        eng = ServeEngine("smallbank_dense", N_ACC,
                          cfg=ControllerCfg(widths=(16, W)),
                          cohorts_per_block=CPB, clock=VirtualClock(),
                          monitor=True, seed=0, **kw)
        try:
            eng.run(sched)
        finally:
            eng.close()
        return eng.snapshot()

    # the plan's serve priors carry the resolver's ServiceModel (pinned
    # CALIB.json coefficients when present) — the hand config must pass
    # the SAME model or the two runs legitimately diverge
    from dint_tpu.monitor.calib import resolve_service_model
    model, _ = resolve_service_model()

    a = snap()                                       # plan-resolved
    b = snap(plan=None, model=model,                 # ... by hand
             runner_kw={"hot_frac": wl.SB_HOT_FRAC})
    assert a["plan"] is not None and b["plan"] is None
    a.pop("plan"), b.pop("plan")
    assert a == b


def test_hot_frac_rebuild_at_width_switch_drain():
    """The engine rebuilds its width menu at the recommended hot_frac
    ONLY at width-switch drain boundaries: a pinned recommendation
    (0.25) applies at the FIRST switch of an overload trajectory —
    one rebuild, not one per switch — and later switches no-op once
    cur == rec."""
    from dint_tpu.clients import workloads as wl
    # start from the prior the bit-identity test already compiled, so
    # the only fresh jits here are the two post-rebuild runners
    eng = ServeEngine("smallbank_dense", N_ACC,
                      cfg=ControllerCfg(widths=(16, W)),
                      cohorts_per_block=CPB, clock=VirtualClock(),
                      monitor=True, seed=0, plan=None,
                      runner_kw={"hot_frac": wl.SB_HOT_FRAC},
                      adapt_hot_frac=True)
    eng.hot_frac_recommendation = lambda cur: 0.25
    try:
        eng.run(constant_schedule(800_000.0, 0.01))
    finally:
        eng.close()
    rep = eng.snapshot()
    assert len(rep["controller"]["switches"]) >= 2       # up AND down
    assert rep["hot_frac"] == {"current": 0.25, "adaptive": True,
                               "rebuilds": 1}
    assert eng.runner_kw["hot_frac"] == 0.25
