"""dintplan: the static configuration planner + the fifth standing gate
(ISSUE 17 tentpole).

The acceptance pins, per ISSUE.md:
  * the knob registry is first-class: values, env semantics, and the
    target-variant mapping (use_fused=True => the @fused twin) are
    declared once in analysis/plan.KNOBS and the lattice enumeration /
    pricing / domination pruning all read from it;
  * `dintplan check` exits 0 on the pinned PLAN.json with ZERO
    allowlist entries (the in-process gate below runs the FULL mode:
    fresh dintcost derivation per frontier row);
  * every plan_check ERROR is proven live by a mutated-fixture test —
    flipped priced ordering, dominated pin, unregistered knob/target,
    stale provenance, unjustified pin, env flag contradicting the plan
    without DINT_PLAN_OVERRIDE=1 — and each is silenceable by a scoped
    allowlist entry with a written reason, never by anything broader;
  * consumers resolve knobs through plan.resolve_for: the plan's pinned
    config wins, env flags are consulted ONLY under
    DINT_PLAN_OVERRIDE=1, and a missing plan degrades to plain env
    resolution with meta["source"] None (artifacts record "plan": null,
    never a silent default).

The serve-plane integration (ServeEngine plan priors, the hot_frac
rebuild at drain boundaries, plan-resolved == hand-config bit identity)
is pinned in tests/test_dintserve.py next to the engines it exercises.
"""
import copy
import json
import os
import subprocess

import pytest

from dint_tpu import analysis
from dint_tpu.analysis import allowlist as al
from dint_tpu.analysis import plan as P
from dint_tpu.analysis import targets as T
from dint_tpu.analysis.passes import plan_check as pc

pytestmark = pytest.mark.plan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLAN_PATH = os.path.join(REPO, "PLAN.json")

# the fixture target every mutated-document finding anchors to; the
# dintlint every-pass parametrization silences `fixture/plan_check`
ANCHOR = "fixture/plan_check"

_DOC = None


def _doc() -> dict:
    """A fresh deep copy of the pinned PLAN.json (loaded once)."""
    global _DOC
    if _DOC is None:
        _DOC = P.load_plan(PLAN_PATH)
    return copy.deepcopy(_DOC)


def _check(doc, environ=None, static=True):
    return pc.check_plan(doc, ANCHOR, static=static,
                         environ={} if environ is None else environ)


def codes(findings):
    return {f.code for f in findings}


# ------------------------------------------------------- knob registry


def test_knob_registry_declares_target_variants():
    """Satellite (1): the registry is the single source of knob ->
    target-variant truth: use_fused=True maps to the @fused twin,
    hierarchical=False to @flat, and the planned knobs span the lattice."""
    assert P.KNOBS["use_fused"].token == "fused"
    assert P.KNOBS["use_fused"].token_when is True
    assert P.KNOBS["hierarchical"].token == "flat"
    assert P.KNOBS["hierarchical"].token_when is False
    wl = P._WORKLOADS_BY_NAME["tatp_uniform"]
    assert P.target_name(wl, {"use_fused": True}) == "tatp_dense/block@fused"
    assert P.target_name(wl, {"use_fused": False}) == "tatp_dense/block"
    assert P.target_name(wl, {"use_hotset": True, "use_pallas": True}) \
        == "tatp_dense/block@hot+pallas"      # canonical token order
    mh = P._WORKLOADS_BY_NAME["multihost_4x2"]
    assert P.target_name(mh, {"hierarchical": False}) \
        == "multihost_sb/block@flat"
    assert P.target_name(mh, {"hierarchical": True}) == "multihost_sb/block"


def test_enumerate_candidates_flags_infeasible_combos():
    """The lattice is exhaustive over each workload's planned knobs and
    an unregistered combination (fused+pallas: the megakernels subsume
    the standalone kernels) is marked infeasible, never silently priced."""
    wl = P._WORKLOADS_BY_NAME["tatp_uniform"]
    cands = P.enumerate_candidates(wl)
    assert len(cands) == 2 ** len(wl.knobs)
    by_target = {c["target"]: c for c in cands}
    assert by_target["tatp_dense/block"]["feasible"]
    assert by_target["tatp_dense/block@fused"]["feasible"]
    fused_pallas = [c for c in cands
                    if c["knobs"].get("use_fused")
                    and c["knobs"].get("use_pallas")]
    assert fused_pallas and not any(c["feasible"] for c in fused_pallas)
    # every feasible candidate names a registered target
    for c in cands:
        assert c["feasible"] == (c["target"] in T.TARGETS)


def test_resolve_knobs_env_semantics():
    """The registry replicates each consumer's exact env semantics:
    flag01 (set-and-not-0) vs flag1 (exactly "1") vs tri-state."""
    r = P.resolve_knobs({})
    assert r["use_pallas"] is False and r["monitor"] is False
    assert r["pallas_interpret"] is None
    assert P.resolve_knobs({"DINT_USE_PALLAS": "0"})["use_pallas"] is False
    assert P.resolve_knobs({"DINT_USE_PALLAS": ""})["use_pallas"] is False
    assert P.resolve_knobs({"DINT_USE_PALLAS": "2"})["use_pallas"] is True
    assert P.resolve_knobs({"DINT_MONITOR": "1"})["monitor"] is True
    assert P.resolve_knobs({"DINT_MONITOR": "2"})["monitor"] is False
    assert P.resolve_knobs({"DINT_PALLAS_INTERPRET": "0"})[
        "pallas_interpret"] is False


def test_env_knob_signature_canonicalizes():
    """Satellite (2): the memo-key signature engines/_memo.py folds into
    builder identity canonicalizes unset == "" == "0" for the flag
    knobs, while the tri-state interpret knob keeps unset distinct."""
    base = P.env_knob_signature({})
    assert base == P.env_knob_signature({"DINT_USE_FUSED": "0"})
    assert base == P.env_knob_signature({"DINT_USE_FUSED": ""})
    assert base != P.env_knob_signature({"DINT_USE_FUSED": "1"})
    assert base != P.env_knob_signature({"DINT_PALLAS_INTERPRET": "0"})
    names = [n for n, _ in base]
    assert "use_fused" in names and "trace" in names
    assert "monitor" not in names        # not part of compiled identity


def test_memo_routes_through_shared_signature(monkeypatch):
    """engines/_memo.py derives its env fingerprint from the SAME
    registry resolution — flipping a build-identity flag changes the
    memo key, flipping an equivalent spelling does not."""
    from dint_tpu.engines import _memo
    monkeypatch.delenv("DINT_USE_FUSED", raising=False)
    k0 = _memo._env_signature()
    monkeypatch.setenv("DINT_USE_FUSED", "0")
    assert _memo._env_signature() == k0
    monkeypatch.setenv("DINT_USE_FUSED", "1")
    assert _memo._env_signature() != k0


# --------------------------------------------------- the pinned artifact


def test_pinned_plan_is_schema_versioned_and_clean():
    """The checked-in PLAN.json parses at the current schema, carries
    full provenance, and the static gate finds NOTHING wrong with it."""
    doc = _doc()
    assert doc["schema"] == P.SCHEMA
    prov = doc["provenance"]
    assert prov["knobs_hash"] == P.knobs_hash()
    assert prov["calibration_hash"] == P.calibration_hash()
    assert prov["cost_model_hash"] == P.frontier_hash(doc["frontier"])
    assert _check(doc) == []


def test_pinned_plan_covers_every_declared_workload():
    doc = _doc()
    assert set(doc["workloads"]) == {w.name for w in P.WORKLOADS}
    for wname, entry in doc["workloads"].items():
        assert entry["target"] in T.TARGETS
        assert entry["predicted_target"] in T.TARGETS
        # every pinned != predicted divergence carries a written reason
        for o in entry["overrides"]:
            assert o["reason"].strip()


def test_consumer_maps_resolve_to_declared_workloads():
    """bench/exp/serve look their workload up via these maps — every
    value must be a declared, pinned workload."""
    doc = _doc()
    for m in (P.BLOCK_WORKLOADS, P.SERVE_WORKLOADS):
        for engine, wname in m.items():
            assert wname in doc["workloads"], (engine, wname)
            assert doc["workloads"][wname]["engine"] == engine


def test_serve_priors_pinned_in_plan():
    """Serve workloads carry ServiceModel capacity priors: the width
    menu with per-width capacity, the knee, and the hot_frac prior the
    engine rebuilds toward (None for TATP — no hot tier)."""
    from dint_tpu.clients import workloads as wl
    from dint_tpu.serve.controller import ControllerCfg
    doc = _doc()
    sb = doc["workloads"]["smallbank_serve"]["serve"]
    tatp = doc["workloads"]["tatp_serve"]["serve"]
    assert sb["hot_frac"] == wl.SB_HOT_FRAC
    assert tatp["hot_frac"] is None
    cfg = ControllerCfg()
    for priors in (sb, tatp):
        assert sorted(int(w) for w in priors["widths"]) == list(cfg.widths)
        caps = {int(w): v["capacity_lanes_per_s"]
                for w, v in priors["widths"].items()}
        assert priors["knee_width"] == max(caps, key=caps.get)
    mesh = doc["workloads"]["multihost_serve"]["serve"]
    assert mesh["lanes_scale"] == 8


# ------------------------------------------------- mutated-fixture gate
#
# Each plan_check ERROR code proven live on a surgically mutated copy of
# the real pinned document (provenance hashes are EXPECTED to co-fire on
# frontier edits — the assertion is that the named code fires).


def broken_plan_findings():
    """The canonical broken plan fixture (swapped frontier ranks =>
    flipped-ordering), also imported by test_dintlint's every-pass
    liveness parametrization. Findings anchor to fixture/plan_check."""
    doc = _doc()
    rows = [r for r in doc["frontier"]
            if r["workload"] == "tatp_uniform" and not r["dominated"]]
    assert len(rows) >= 2
    rows[0]["rank"], rows[1]["rank"] = rows[1]["rank"], rows[0]["rank"]
    return _check(doc)


def _mutate(code):
    doc = _doc()
    if code == "flipped-ordering":
        rows = [r for r in doc["frontier"]
                if r["workload"] == "tatp_uniform" and not r["dominated"]]
        rows[0]["rank"], rows[1]["rank"] = rows[1]["rank"], rows[0]["rank"]
        return _check(doc)
    if code == "dominated-pin":
        entry = doc["workloads"]["tatp_uniform"]
        rows = [r for r in doc["frontier"]
                if r["workload"] == "tatp_uniform"]
        pin = next(r for r in rows if r["target"] == entry["target"])
        other = next(r for r in rows if r is not pin)
        for k in ("bytes_per_step", "dispatches_per_step",
                  "footprint_bytes"):
            pin[k] = other[k] + 1       # strictly worse on all three
        return _check(doc)
    if code == "unregistered-target":
        doc["workloads"]["tatp_uniform"]["target"] = "tatp_dense/nope"
        return _check(doc)
    if code == "unregistered-knob":
        doc["workloads"]["tatp_uniform"]["pinned"]["warp_speed"] = True
        return _check(doc)
    if code == "unknown-workload":
        doc["workloads"]["mystery"] = copy.deepcopy(
            doc["workloads"]["tatp_uniform"])
        return _check(doc)
    if code == "stale-provenance":
        doc["provenance"]["calibration_hash"] = "0" * 16
        return _check(doc)
    if code == "unjustified-pin":
        doc["workloads"]["tatp_uniform"]["overrides"] = []
        return _check(doc)
    if code == "env-override":
        return _check(doc, environ={"DINT_USE_FUSED": "1"})
    if code == "malformed-plan":
        del doc["frontier"]
        return _check(doc)
    raise AssertionError(code)


@pytest.mark.parametrize("code", [
    "flipped-ordering", "dominated-pin", "unregistered-target",
    "unregistered-knob", "unknown-workload", "stale-provenance",
    "unjustified-pin", "env-override", "malformed-plan"])
def test_each_check_fires_and_is_allowlist_silenceable(code, tmp_path):
    """Acceptance contract: each plan_check ERROR is proven live by a
    mutated fixture AND silenceable by a scoped entry with a written
    reason — never by anything broader."""
    findings = _mutate(code)
    errs = {f.code for f in findings if f.severity == "error"}
    assert code in errs, f"{code} fixture did not fire: " \
        + str([str(f) for f in findings])

    path = tmp_path / "allow.json"
    path.write_text(json.dumps([
        {"pass": "plan_check", "code": code, "target": ANCHOR,
         "reason": "test fixture: mutation is constructed on purpose"}]))
    fs = al.apply(_mutate(code), al.load(str(path)), check_unused=False)
    assert not any(f.severity == "error" and not f.suppressed
                   and f.code == code for f in fs)
    assert any(f.suppressed for f in fs)


def test_mutated_price_flips_ordering_and_provenance():
    """Editing a recorded price re-ranks the workload under the decision
    rule AND breaks the frontier digest — a doctored row cannot survive
    either check."""
    doc = _doc()
    rows = [r for r in doc["frontier"]
            if r["workload"] == "tatp_uniform" and not r["dominated"]]
    best = next(r for r in rows if r["rank"] == 0)
    best["dcn_bytes_per_step"] = 1e12      # push the pick off rank 0
    fs = _check(doc)
    assert "flipped-ordering" in codes(fs)
    assert "stale-provenance" in codes(fs)


def test_missing_and_unreadable_plan(tmp_path):
    plan, fs = pc.load_plan_findings(ANCHOR, path=tmp_path / "none.json")
    assert plan is None and codes(fs) == {"missing-plan"}

    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    plan, fs = pc.load_plan_findings(ANCHOR, path=bad)
    assert plan is None and codes(fs) == {"malformed-plan"}

    wrong = tmp_path / "wrong.json"
    wrong.write_text(json.dumps({"schema": P.SCHEMA + 1}))
    with pytest.raises(ValueError):
        P.load_plan(wrong)


def test_env_override_flag_acknowledges_contradiction():
    """DINT_PLAN_OVERRIDE=1 is the ONLY way an ambient flag may
    contradict the plan — with it the gate is silent, without it every
    contradicting workload is named."""
    doc = _doc()
    fs = _check(doc, environ={"DINT_USE_FUSED": "1"})
    hit = [f for f in fs if f.code == "env-override"]
    assert hit and all("DINT_USE_FUSED" in f.message for f in hit)
    assert _check(doc, environ={"DINT_USE_FUSED": "1",
                                "DINT_PLAN_OVERRIDE": "1"}) == []
    # contradictions() names (workload, knob, pinned, env value)
    cons = P.contradictions(doc, {"DINT_USE_FUSED": "1"})
    assert ("tatp_uniform", "use_fused", False, True) in cons
    assert P.contradictions(doc, {}) == []


def test_priced_drift_fires_in_full_mode():
    """Full mode re-derives each frontier row with dintcost: a doctored
    price that kept its rank is still caught. Frontier reduced to the
    one rank-0 row so the fresh derivation traces a single target."""
    doc = _doc()
    row = next(r for r in doc["frontier"]
               if r["workload"] == "tatp_uniform" and r["rank"] == 0)
    doc["frontier"] = [row]
    row["bytes_per_step"] += 64.0
    fs = _check(doc, static=False)
    assert "priced-drift" in codes(fs)
    drift = next(f for f in fs if f.code == "priced-drift")
    assert "bytes_per_step" in drift.message


# ------------------------------------------------------- consumer resolve


def test_resolve_for_plan_pins_beat_env():
    """Without DINT_PLAN_OVERRIDE the plan's pinned knobs win outright;
    with it, only explicitly-SET contradicting flags flip, and meta
    records exactly which."""
    doc = _doc()
    knobs, meta = P.resolve_for("tatp_uniform",
                                environ={"DINT_USE_FUSED": "1"}, plan=doc)
    assert knobs["use_fused"] is False and meta["overridden"] == []
    assert meta["source"] and meta["hash"] == \
        doc["provenance"]["cost_model_hash"]

    knobs, meta = P.resolve_for(
        "tatp_uniform", plan=doc,
        environ={"DINT_USE_FUSED": "1", "DINT_PLAN_OVERRIDE": "1"})
    assert knobs["use_fused"] is True
    assert meta["overridden"] == ["use_fused"]
    # an UNSET flag never flips a pin, even under the override
    assert knobs["use_pallas"] is False


def test_resolve_for_without_plan_falls_back_to_env(monkeypatch,
                                                    tmp_path):
    monkeypatch.setenv(P.ENV_PLAN_PATH, str(tmp_path / "none.json"))
    knobs, meta = P.resolve_for("tatp_uniform",
                                environ={"DINT_USE_FUSED": "1"})
    assert meta == {"source": None, "hash": None, "overridden": []}
    assert knobs["use_fused"] is True          # plain env resolution
    assert set(knobs) == set(
        P._WORKLOADS_BY_NAME["tatp_uniform"].knobs)


# ------------------------------------------------------------ tier-1 gate


def _dintplan_main():
    """tools/dintplan.py main() in-process: the full-mode gate reuses
    this process's TraceCache instead of re-tracing ~28 targets."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_dintplan_cli", os.path.join(REPO, "tools", "dintplan.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def test_dintplan_check_full_gate_in_process(monkeypatch, capsys):
    """THE acceptance gate: `dintplan check` (FULL mode — fresh dintcost
    derivation per frontier row) exits 0 on the pinned PLAN.json with
    zero plan_check allowlist entries."""
    # setenv (not delenv): cmd_check writes these vars, and monkeypatch
    # only restores what it touched — register the restore up front
    monkeypatch.setenv(P.ENV_PLAN_STATIC, "0")
    monkeypatch.delenv(P.ENV_PLAN_PATH, raising=False)
    for k in P.KNOBS.values():               # a clean ambient env
        if k.env:
            monkeypatch.delenv(k.env, raising=False)
    main = _dintplan_main()
    assert main(["check", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["metric"] == "dintplan" and payload["ok"] is True
    assert payload["static"] is False and payload["n_errors"] == 0
    assert payload["n_suppressed"] == 0      # ZERO allowlist entries
    allow = json.load(open(os.path.join(REPO, "tools",
                                        "dintlint_allow.json")))
    assert not [e for e in allow if e["pass"] == "plan_check"]


def test_plan_check_anchors_to_one_target(monkeypatch):
    """The whole-plan findings land exactly once: on the anchor target,
    [] everywhere else — `dintlint --all` cannot double-report."""
    monkeypatch.delenv(P.ENV_PLAN_ANCHOR, raising=False)
    monkeypatch.delenv(P.ENV_PLAN_STATIC, raising=False)
    fs = analysis.run(targets=[P.DEFAULT_ANCHOR], passes=["plan_check"])
    assert not analysis.has_errors(fs)
    other = next(n for n in sorted(T.TARGETS) if n != P.DEFAULT_ANCHOR)
    assert analysis.run(targets=[other], passes=["plan_check"]) == []


def test_dintplan_check_mutated_plan_fails(tmp_path, monkeypatch,
                                           capsys):
    """CLI exit discipline on a broken artifact: a plan whose recorded
    ordering was flipped fails `check --static` with exit 1 and names
    flipped-ordering."""
    doc = _doc()
    rows = [r for r in doc["frontier"]
            if r["workload"] == "tatp_uniform" and not r["dominated"]]
    rows[0]["rank"], rows[1]["rank"] = rows[1]["rank"], rows[0]["rank"]
    path = tmp_path / "broken_plan.json"
    path.write_text(json.dumps(doc))
    monkeypatch.setenv(P.ENV_PLAN_STATIC, "1")   # cmd_check writes this
    monkeypatch.setenv(P.ENV_PLAN_PATH, str(path))
    main = _dintplan_main()
    rc = main(["check", "--static", "--plan", str(path)])
    out = capsys.readouterr().out
    assert rc == 1 and "flipped-ordering" in out


def test_dintplan_cli_describe_and_sarif(tmp_path, capsys, monkeypatch):
    """Satellite (1): `describe` lists the registry with target
    mappings; `check --sarif` writes SARIF 2.1.0 through the shared
    exporter. In-process main() (warm TraceCache) — the subprocess
    surface is covered by the mutated-plan CLI test's sibling tools."""
    monkeypatch.setenv(P.ENV_PLAN_STATIC, "1")   # cmd_check writes it
    main = _dintplan_main()
    assert main(["describe", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["metric"] == "dintplan"
    assert payload["knobs"]["use_fused"]["token"] == "fused"
    assert payload["knobs"]["use_fused"]["env"] == "DINT_USE_FUSED"
    assert "tatp_uniform" in payload["workloads"]
    assert payload["decision_rule"]

    sarif_path = tmp_path / "plan.sarif"
    assert main(["check", "--static", "--sarif", str(sarif_path),
                 "--json"]) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["ok"] is True and payload["static"] is True
    sarif = json.loads(sarif_path.read_text())
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["tool"]["driver"]["name"] == "dintplan"


def test_bench_and_exp_route_through_resolve_for():
    """bench.py / exp.py resolve their builder knobs from the plan via
    the shared helpers — the wiring exists and names real workloads."""
    import bench
    import exp
    knobs, meta = bench._plan_resolve("tatp_uniform")
    assert meta is not None and meta["overridden"] == []
    assert set(knobs) >= {"use_pallas", "use_hotset", "use_fused"}
    assert exp._plan_knobs("smallbank_skewed").keys() == \
        set(P._WORKLOADS_BY_NAME["smallbank_skewed"].knobs)
    m = exp._plan_meta()
    assert m and m["hash"] == _doc()["provenance"]["cost_model_hash"]


def test_bench_plan_escape_hatch(monkeypatch):
    """DINT_BENCH_PLAN=0: bench falls back to env knobs and records
    "plan": null — disabled is explicit, never silent."""
    import bench
    monkeypatch.setenv("DINT_BENCH_PLAN", "0")
    knobs, meta = bench._plan_resolve("tatp_uniform")
    assert knobs == {} and meta is None


# ----------------------------------------------------- tools/dintgate.sh


def test_dintgate_orchestration_smoke(tmp_path):
    """Satellite: tools/dintgate.sh is ONE entry point for the seven
    standing gates. The smoke pins the orchestration — eight
    invocations (dintcal contributes check AND the journal audit) in
    order through $PYTHON, the allowlist-rot dry-runs riding the three
    matrix gates, dintplan full by default / static under --quick, the
    six finding gates' SARIF logs merged into one multi-run document,
    the per-stage wall-clock timings JSON line, a failing gate named
    WITHOUT stopping the others — against a millisecond stub; each real
    gate has its own in-depth tests (and the full script runs in CI
    proper)."""
    import stat
    import subprocess
    import textwrap

    calls = tmp_path / "calls.log"
    stub = tmp_path / "fakepy"
    stub.write_text(textwrap.dedent("""\
        #!/bin/sh
        # dintgate's SARIF merge runs "$PY - out in..." — that one is
        # real work, hand it to the actual interpreter
        if [ "$1" = "-" ]; then exec python "$@"; fi
        echo "$*" >> "$CALLS"
        tool=$(basename "$1" .py)
        out=""; prev=""
        for a in "$@"; do
            [ "$prev" = "--sarif" ] && out="$a"
            prev="$a"
        done
        [ -n "$out" ] && printf \\
          '{"version":"2.1.0","runs":[{"tool":{"driver":{"name":"%s"}},"results":[]}]}' \\
          "$tool" > "$out"
        [ "$tool" = dintdur ] && [ "${FAIL_DUR:-0}" = 1 ] && exit 1
        exit 0
        """))
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    script = os.path.join(REPO, "tools", "dintgate.sh")
    env = dict(os.environ, PYTHON=str(stub), CALLS=str(calls))

    merged = tmp_path / "gate.sarif"
    timings = tmp_path / "timings.json"
    r = subprocess.run(["bash", script, "--sarif", str(merged),
                        "--timings", str(timings)],
                       capture_output=True, text=True, env=env,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "all 7 gates ok" in r.stdout

    lines = calls.read_text().splitlines()
    assert [ln.split()[0].rsplit("/", 1)[-1] for ln in lines] == \
        ["dintlint.py", "dintcost.py", "dintdur.py", "dintplan.py",
         "dintmon.py", "dintcal.py", "dintcal.py", "dintmut.py"]
    # the three matrix gates carry the allowlist-rot dry-run
    assert "--prune-allowlist --check" in lines[0]
    assert "check --prune-allowlist --check" in lines[1]
    assert "check --prune-allowlist --check" in lines[2]
    assert "--static" not in lines[3]        # default: the FULL gate
    assert lines[4].endswith("tests/fixtures/dintmon_counters.json")
    assert os.path.exists(os.path.join(
        REPO, "tests", "fixtures", "dintmon_counters.json"))
    assert "check" in lines[5] and "--sarif" in lines[5]
    assert lines[6].endswith("tests/fixtures/dintcal_journal.jsonl")
    assert os.path.exists(os.path.join(
        REPO, "tests", "fixtures", "dintcal_journal.jsonl"))
    assert "check --quick" in lines[7]       # the dintmut sampled tier

    doc = json.loads(merged.read_text())
    assert doc["version"] == "2.1.0"
    assert sorted(r_["tool"]["driver"]["name"] for r_ in doc["runs"]) \
        == ["dintcal", "dintcost", "dintdur", "dintlint", "dintmut",
            "dintplan"]

    # the per-stage wall-clock block: one JSON line, mirrored to --timings
    tline = next(ln for ln in r.stdout.splitlines()
                 if ln.startswith('{"metric": "dintgate"'))
    tdoc = json.loads(tline)
    assert tdoc == json.loads(timings.read_text())
    assert [s["gate"] for s in tdoc["stages"]] == \
        ["dintlint", "dintcost", "dintdur", "dintplan", "dintmon",
         "dintcal", "dintcal-audit", "dintmut"]
    assert all(s["ok"] is True and s["wall_s"] >= 0
               for s in tdoc["stages"])
    assert tdoc["quick"] is False and tdoc["total_s"] > 0

    # --quick keeps the planner gate static
    calls.write_text("")
    r = subprocess.run(["bash", script, "--quick"], capture_output=True,
                       text=True, env=env, timeout=120)
    assert r.returncode == 0
    assert "--static" in calls.read_text().splitlines()[3]

    # one failing gate fails the run BY NAME, the rest still execute
    calls.write_text("")
    r = subprocess.run(["bash", script], capture_output=True, text=True,
                       env=dict(env, FAIL_DUR="1"), timeout=120)
    assert r.returncode == 1
    assert "dintgate: FAIL" in r.stdout and "dintdur" in r.stdout
    assert len(calls.read_text().splitlines()) == 8   # no fail-fast
    tdoc = json.loads(next(ln for ln in r.stdout.splitlines()
                           if ln.startswith('{"metric": "dintgate"')))
    assert [s["gate"] for s in tdoc["stages"]
            if s["ok"] is False] == ["dintdur"]

    # unknown flags are a usage error; --help documents the contract
    assert subprocess.run(["bash", script, "--frobnicate"],
                          capture_output=True, timeout=120).returncode == 2
    h = subprocess.run(["bash", script, "--help"], capture_output=True,
                       text=True, timeout=120)
    assert h.returncode == 0 and "dintplan check" in h.stdout
