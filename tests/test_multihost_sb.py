"""Cross-shard SmallBank 2PC over the 2-D (dcn x ici) mesh
(parallel/multihost_sb.py): the transport restructure must be invisible
to the program — hierarchical and flat routes are bit-identical to the
1-D sharded runner — while replication crosses host fault domains."""
import jax
import numpy as np
import pytest

from dint_tpu.monitor import counters as mon
from dint_tpu.parallel import dense_sharded_sb as dsb
from dint_tpu.parallel import multihost as mhost
from dint_tpu.parallel import multihost_sb as mh
from dint_tpu.parallel.sharded import make_mesh

H, C = 4, 2          # 4 hosts x 2 chips on the 8-virtual-device mesh
D = H * C
N = 256
W, BLK = 32, 3


def test_2d_routes_bit_identical_to_1d():
    """The tentpole contract: same global geometry (H*C == D), same key
    stream => the hierarchical (ici-then-dcn) route, the flat tuple-axis
    route, and the 1-D runner produce the SAME stats every block (and
    through the drain) and the SAME primary state — only the collective
    decomposition differs. One compile each; accounting, conservation,
    and backup placement assert on the same runs."""
    mesh1 = make_mesh(D)
    run1, init1, drain1 = dsb.build_sharded_sb_runner(mesh1, D, N, w=W,
                                                      cohorts_per_block=BLK)
    mesh2 = mh.make_mesh_2d(H, C)
    runh, inith, drainh = mh.build_multihost_sb_runner(
        mesh2, N, w=W, cohorts_per_block=BLK, hierarchical=True)
    runf, initf, drainf = mh.build_multihost_sb_runner(
        mesh2, N, w=W, cohorts_per_block=BLK, hierarchical=False)

    base = dsb.total_balance_global(dsb.create_sharded_sb(mesh1, D, N))
    c1 = init1(dsb.create_sharded_sb(mesh1, D, N))
    ch = inith(mh.create_multihost_sb(mesh2, N))
    cf = initf(mh.create_multihost_sb(mesh2, N))

    key = jax.random.PRNGKey(7)
    total = np.zeros(dsb.N_STATS, np.int64)
    for i in range(BLK):
        k = jax.random.fold_in(key, i)
        c1, s1 = run1(c1, k)
        ch, sh = runh(ch, k)
        cf, sf = runf(cf, k)
        assert np.array_equal(np.asarray(s1), np.asarray(sh)), ("hier", i)
        assert np.array_equal(np.asarray(s1), np.asarray(sf)), ("flat", i)
        total += np.asarray(s1, np.int64).sum(axis=0)

    # pre-drain primary state is identical across all three transports
    st1, sth, stf = c1[0], ch[0], cf[0]
    for name in ("bal", "x_step", "s_step", "step"):
        a = np.asarray(getattr(st1, name))
        assert np.array_equal(
            a, np.asarray(getattr(sth, name)).reshape(a.shape)), name
        assert np.array_equal(
            a, np.asarray(getattr(stf, name)).reshape(a.shape)), name
    # backup placement deliberately differs (host fault domains, not
    # ring neighbours); global conservation must still agree
    assert mh.total_balance_global(sth) == dsb.total_balance_global(st1)
    assert mh.total_balance_global(stf) == dsb.total_balance_global(st1)

    # the drains agree too, and the accounting closes over them
    st1, t1 = drain1(c1)
    stf, tf = drainf(cf)
    assert np.array_equal(np.asarray(t1), np.asarray(tf))
    total += np.asarray(t1, np.int64).sum(axis=0)
    attempted = int(total[dsb.STAT_ATTEMPTED])
    committed = int(total[dsb.STAT_COMMITTED])
    assert attempted == BLK * BLK * W * D
    assert committed > 0
    assert committed + int(total[dsb.STAT_AB_LOCK]) \
        + int(total[dsb.STAT_AB_LOGIC]) == attempted
    assert int(total[dsb.STAT_OVERFLOW]) == 0
    assert (mh.total_balance_global(stf) - base) % (1 << 32) == \
        int(total[dsb.STAT_BAL_DELTA]) % (1 << 32)

    # fault-domain property the 2-D mesh exists for: device (h, c)'s
    # balances are mirrored at hosts h+1 and h+2, SAME chip coordinate —
    # all 3 copies of any account sit on 3 different hosts (the 1-D
    # runner's ring neighbours do NOT give this)
    bal = np.asarray(stf.bal)            # [H, C, m1]
    bck = np.asarray(stf.bck_bal)        # [H, C, 2*m1]
    m1 = bal.shape[-1]
    for h in range(H):
        for c in range(C):
            for off, slot in ((1, 0), (2, 1)):
                hh = (h + off) % H       # backup HOST, same chip c
                got = bck[hh, c, slot * m1:(slot + 1) * m1]
                assert np.array_equal(got[:-1], bal[h, c, :-1]), (h, c, off)


def test_monitor_reconciles_per_axis_route_split():
    """route_ici_lanes + route_dcn_lanes counts every routed lane once
    (== lock_requests + install_writes over the whole run), and with
    uniform routing over 4 hosts ~3/4 of the lanes pay the DCN hop."""
    mesh = mh.make_mesh_2d(H, C)
    run, init, drain = mh.build_multihost_sb_runner(
        mesh, N, w=W, cohorts_per_block=BLK, hierarchical=True,
        monitor=True)
    carry = init(mh.create_multihost_sb(mesh, N))
    key = jax.random.PRNGKey(7)
    total = np.zeros(dsb.N_STATS, np.int64)
    for i in range(BLK):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    _, tail, cnt = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    snap = mon.snapshot(cnt)
    assert snap["txn_attempted"] == int(total[dsb.STAT_ATTEMPTED])
    assert snap["txn_committed"] == int(total[dsb.STAT_COMMITTED])
    assert snap["route_ici_lanes"] + snap["route_dcn_lanes"] == \
        snap["lock_requests"] + snap["install_writes"]
    assert snap["route_dcn_lanes"] > snap["route_ici_lanes"]


@pytest.mark.slow
def test_reference_topology_3_hosts():
    """3 hosts x 2 chips (the reference's machine count): with H equal to
    the replication factor every host holds a copy of every shard.
    Slow-marked per the round-10 tier-1-budget rule — the 3x2 geometry
    is still statically covered tier-1 by the @h3 cost targets."""
    mesh = mh.make_mesh_2d(3, 2)
    run, init, drain = mh.build_multihost_sb_runner(
        mesh, N, w=W, cohorts_per_block=BLK, hierarchical=True)
    carry = init(mh.create_multihost_sb(mesh, N))
    key = jax.random.PRNGKey(7)
    total = np.zeros(dsb.N_STATS, np.int64)
    for i in range(BLK):
        carry, stats = run(carry, jax.random.fold_in(key, i))
        total += np.asarray(stats, np.int64).sum(axis=0)
    _, tail = drain(carry)
    total += np.asarray(tail, np.int64).sum(axis=0)
    assert int(total[dsb.STAT_ATTEMPTED]) == BLK * BLK * W * 6
    assert int(total[dsb.STAT_COMMITTED]) > 0
    assert int(total[dsb.STAT_OVERFLOW]) == 0


def test_two_hosts_refused_everywhere():
    """n_hosts == 2 makes the +2 dcn hop alias the source host — one
    host failure would take a primary and its second backup together.
    All three entry points must refuse it, not silently degrade."""
    mesh = mh.make_mesh_2d(2, 4)
    with pytest.raises(ValueError, match="3 hosts"):
        mh.create_multihost_sb(mesh, N)
    with pytest.raises(ValueError, match="3 hosts"):
        mh.build_multihost_sb_runner(mesh, N, w=W)
    with pytest.raises(ValueError, match="n_hosts=2"):
        mhost.build_multihost_runner(mesh, D * 128, w=W, val_words=4)


def test_mesh_shape_from_env(monkeypatch):
    monkeypatch.delenv("DINT_BENCH_MESH", raising=False)
    assert mhost.mesh_shape_from_env() == (4, 2)
    monkeypatch.setenv("DINT_BENCH_MESH", "3x2")
    assert mhost.mesh_shape_from_env() == (3, 2)
    monkeypatch.setenv("DINT_BENCH_MESH", "4*2")
    assert mhost.mesh_shape_from_env() == (4, 2)
    monkeypatch.setenv("DINT_BENCH_MESH", "8X1")
    assert mhost.mesh_shape_from_env() == (8, 1)
    monkeypatch.setenv("DINT_BENCH_MESH", "banana")
    with pytest.raises(ValueError, match="DINT_BENCH_MESH"):
        mhost.mesh_shape_from_env()


# ------------------------------------------- mesh serving plane (round 18)


def test_serve_full_occupancy_replays_closed_loop():
    """serve=True at occ == w is the closed loop: same stats every block
    AND through the drain, same final state tree — the occupancy mask
    and the serve counter plumbing cost nothing when every lane is
    live."""
    mesh = mh.make_mesh_2d(H, C)
    run_c, init_c, drain_c = mh.build_multihost_sb_runner(
        mesh, N, w=W, cohorts_per_block=BLK)
    run_s, init_s, drain_s = mh.build_multihost_sb_runner(
        mesh, N, w=W, cohorts_per_block=BLK, serve=True)
    cc = init_c(mh.create_multihost_sb(mesh, N))
    cs = init_s(mh.create_multihost_sb(mesh, N))
    key = jax.random.PRNGKey(7)
    full = np.full((H, C, BLK), W, np.int32)
    zero = np.zeros((H, C, BLK), np.int32)
    for i in range(BLK):
        k = jax.random.fold_in(key, i)
        cc, s1 = run_c(cc, k)
        cs, s2 = run_s(cs, k, full, zero)
        assert np.array_equal(np.asarray(s1), np.asarray(s2)), i
    st1, t1 = drain_c(cc)
    st2, t2 = drain_s(cs)
    assert np.array_equal(np.asarray(t1), np.asarray(t2))
    for a, b in zip(jax.tree_util.tree_leaves(st1),
                    jax.tree_util.tree_leaves(st2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_overlap_route_bit_identical_to_unoverlapped():
    """The round-18 pin: the double-buffered route (cohort i+1's
    exchange issued under cohort i's owner waves) is a SCHEDULING
    change, not a semantic one. Same key stream, same (random, partial)
    occupancies => the ENTIRE final state tree — balances, backups,
    stamps, log rings — is bit-identical to the unoverlapped serve
    route, and the run+drain stat totals agree. Per-block stats shift by
    one step (cohort j arbitrates at t+1 under overlap), so only the
    totals are comparable."""
    mesh = mh.make_mesh_2d(H, C)
    run_s, init_s, drain_s = mh.build_multihost_sb_runner(
        mesh, N, w=W, cohorts_per_block=BLK, serve=True)
    run_o, init_o, drain_o = mh.build_multihost_sb_runner(
        mesh, N, w=W, cohorts_per_block=BLK, serve=True, overlap=True)
    cs = init_s(mh.create_multihost_sb(mesh, N))
    co = init_o(mh.create_multihost_sb(mesh, N))
    key = jax.random.PRNGKey(11)
    rng = np.random.default_rng(42)
    zero = np.zeros((H, C, BLK), np.int32)
    tot_s = np.zeros(dsb.N_STATS, np.int64)
    tot_o = np.zeros(dsb.N_STATS, np.int64)
    for i in range(BLK):
        k = jax.random.fold_in(key, i)
        occ = rng.integers(0, W + 1, size=(H, C, BLK)).astype(np.int32)
        cs, s1 = run_s(cs, k, occ, zero)
        co, s2 = run_o(co, k, occ, zero)
        tot_s += np.asarray(s1, np.int64).sum(axis=0)
        tot_o += np.asarray(s2, np.int64).sum(axis=0)
    st_s, t_s = drain_s(cs)
    st_o, t_o = drain_o(co)
    tot_s += np.asarray(t_s, np.int64).sum(axis=0)
    tot_o += np.asarray(t_o, np.int64).sum(axis=0)
    assert np.array_equal(tot_s, tot_o), (tot_s, tot_o)
    leaves_s = jax.tree_util.tree_leaves(st_s)
    leaves_o = jax.tree_util.tree_leaves(st_o)
    assert len(leaves_s) == len(leaves_o)
    for a, b in zip(leaves_s, leaves_o):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # the masked lanes really were masked: fewer attempts than the
    # closed loop would have made, and the accounting still closes
    attempted = int(tot_s[dsb.STAT_ATTEMPTED])
    assert attempted < BLK * BLK * W * D
    assert attempted == int(tot_s[dsb.STAT_COMMITTED]) \
        + int(tot_s[dsb.STAT_AB_LOCK]) + int(tot_s[dsb.STAT_AB_LOGIC])


def test_overlap_and_serve_guards():
    """overlap is a property of the SERVING route; trace widens the
    route slots the prefetch replays. Both misuses must refuse loudly at
    build time, not degrade."""
    mesh = mh.make_mesh_2d(H, C)
    with pytest.raises(ValueError, match="serve=True"):
        mh.build_multihost_sb_runner(mesh, N, w=W, overlap=True)
    with pytest.raises(ValueError, match="trace"):
        mh.build_multihost_sb_runner(mesh, N, w=W, serve=True,
                                     overlap=True, trace=True)
